"""Thread graph + lockset substrate and the LDA014–LDA018 rules.

The call graph (analysis/callgraph.py) deliberately stops at the thread
boundary: ``Thread(target=f)`` is not a call edge because ``f`` runs in
a separate failure domain. This module builds the *other* edge domain on
top of the same per-module facts:

  - **thread graph** — spawn edges from every ``Thread(target=...)``
    site into the target's call-graph-reachable set (the "thread side"),
    against the set reachable from call-graph roots that no thread can
    reach (the "main side");
  - **shared-state access sets** — each definition's reads/writes of
    ``self.*`` attributes and module globals, keyed per class/module so
    the two sides can be compared field by field;
  - **lockset inference** — every access and call site carries the
    ``with``-contexts lexically held around it; an interprocedural
    fixed point adds the locks *all* callers hold at the call site
    (the ``_trim_locked``-style callee pattern), and lock names are
    canonicalized per class/module so ``self._lock`` in two methods is
    one lock and in two classes is two.

Everything iterates over sorted structures, like the call graph: two
runs over the same tree produce byte-identical findings.

Known under-approximations (shared with ``resolve_call``): closures
handed to opaque iterators/executors never become thread roots, and a
lock object passed as a function argument changes name across the call.
A missing edge can hide a race; it never invents one.
"""

import os

from .engine import UNBOUNDED_WAIT_ATTRS
from .project import ProjectRule

# Rule ids this module contributes (bench.py stamps their finding
# counts; lddl-perf gates on them).
CONCURRENCY_RULE_IDS = frozenset(
    {'LDA014', 'LDA015', 'LDA016', 'LDA017', 'LDA018'})

# A `with` context (or attribute) is lock-like when its name says so or
# its recorded constructor is a lock type.
LOCK_NAME_TOKENS = ('lock', 'mutex', 'cond', 'sem')
LOCK_CTORS = frozenset({
    'threading.Lock', 'threading.RLock', 'threading.Condition',
    'threading.Semaphore', 'threading.BoundedSemaphore',
    'multiprocessing.Lock', 'multiprocessing.RLock',
})

# Attribute constructors that are internally synchronized: cross-thread
# use without an extra lock is their design, not a race.
THREAD_SAFE_CTORS = frozenset(LOCK_CTORS | {
    'threading.Event', 'threading.Barrier', 'threading.local',
    'queue.Queue', 'queue.SimpleQueue', 'queue.LifoQueue',
    'queue.PriorityQueue', 'multiprocessing.Queue',
    'multiprocessing.Event', 'multiprocessing.SimpleQueue',
})

# Method names that mark a definition as teardown: an unbounded
# `thread.join()` reachable from one of these is the PR 9 deadlock class.
SHUTDOWN_NAMES = frozenset({
    'close', 'stop', 'shutdown', 'teardown', 'finalize',
    '__exit__', '__del__',
})


def _testish(path):
  """Test fixtures exercise hazards on purpose; concurrency rules skip
  definitions living in test files (same convention as LDA013 etc.)."""
  p = os.path.abspath(path).replace(os.sep, '/')
  base = p.rsplit('/', 1)[-1]
  return ('/tests/' in p or base.startswith('test_')
          or base in ('conftest.py', 'testing.py'))


def _is_ctor(gq):
  return gq.rsplit('.', 1)[-1] in ('__init__', '__new__')


def _lockish(name, ctor=''):
  last = name.rsplit('.', 1)[-1].lower()
  if any(tok in last for tok in LOCK_NAME_TOKENS):
    return True
  return ctor in LOCK_CTORS


def _short_lock(canon):
  """Readable lock name for messages: last two dotted segments."""
  return '.'.join(canon.split('.')[-2:])


class ThreadGraph:
  """Spawn edges, thread/main reachable sets, shared-state access
  table, and canonical locksets over a built index + call graph."""

  def __init__(self, index, graph):
    self.index = index
    self.graph = graph
    self._parents_memo = {}
    self._canon_memo = {}
    self._trans_acq = None

    # Every spawn site, with its target resolved to a project def.
    self.spawns = []
    for gq in sorted(index.defs):
      for sp in index.defs[gq].spawns:
        self.spawns.append((gq, sp, self._resolve_target(gq, sp)))
    self.spawns.sort(
        key=lambda t: (index.def_path(t[0]), t[1].line, t[1].col))

    # Thread side: defs reachable from any Thread target. Process
    # targets live in another address space — no shared state.
    self.thread_roots = sorted({tgt for _, sp, tgt in self.spawns
                                if tgt and sp.ctor == 'Thread'})
    self.spawn_for_root = {}
    for owner, sp, tgt in self.spawns:
      if tgt and sp.ctor == 'Thread':
        self.spawn_for_root.setdefault(tgt, (owner, sp))
    self.thread_owner = {}
    for root in self.thread_roots:
      for gq in sorted(self._parents(root)):
        self.thread_owner.setdefault(gq, root)
    self.thread_defs = frozenset(self.thread_owner)

    # Main side: call-graph roots (no resolved incoming edge) that no
    # thread reaches, plus everything they reach. A def reachable only
    # through unresolvable calls lands on neither side — consistent
    # with resolve_call's under-approximation contract.
    incoming = set()
    for gq in sorted(graph.edges):
      for tgt, _ in graph.edges[gq]:
        incoming.add(tgt)
    self.main_roots = sorted(gq for gq in index.defs
                             if gq not in incoming
                             and gq not in self.thread_defs)
    self.main_owner = {}
    for root in self.main_roots:
      for gq in sorted(self._parents(root)):
        self.main_owner.setdefault(gq, root)
    self.main_defs = frozenset(self.main_owner)

    self.entry_locks = self._entry_locks()

  # -- resolution --------------------------------------------------------

  def _resolve_target(self, owner_gq, sp):
    if not sp.target:
      return ''
    index = self.index
    module = index.def_module.get(owner_gq, '')
    if sp.target.startswith('self.') and sp.target.count('.') == 1:
      facts = index.defs[owner_gq]
      if facts.cls:
        cls_gq = f'{module}.{facts.cls}' if module else facts.cls
        return index.mro_method(cls_gq, sp.target.split('.', 1)[1])
      return ''
    # x.run / self._worker.run: type the receiver like resolve_call does.
    if '.' in sp.target and not sp.target.startswith('.'):
      receiver, _, meth = sp.target.rpartition('.')
      cls_gq = index._receiver_class(module, owner_gq, receiver)
      if cls_gq:
        found = index.mro_method(cls_gq, meth)
        if found:
          return found
    return index._resolve_value(module, index.display(owner_gq),
                                sp.target)

  def _parents(self, root):
    if root not in self._parents_memo:
      self._parents_memo[root] = self.graph.bfs_parents(root)
    return self._parents_memo[root]

  # -- lock identity -----------------------------------------------------

  def canon_lock(self, gq, name):
    """Canonical (class- or module-scoped) identity of a lock-like
    ``with`` context named from inside ``gq``, or '' when the name is
    not lock-like. ``self._lock`` in two methods of one class is one
    lock; the same spelling in another class is a different lock."""
    key = (gq, name)
    if key in self._canon_memo:
      return self._canon_memo[key]
    index = self.index
    module = index.def_module.get(gq, '')
    facts = index.defs[gq]
    ctor = ''
    if name.startswith('self.'):
      rest = name.split('.', 1)[1]
      if facts.cls:
        cls_gq = f'{module}.{facts.cls}' if module else facts.cls
        cls = index.classes.get(cls_gq)
        if cls is not None and '.' not in rest:
          ctor = cls.attr_ctors.get(rest, '')
        canon = f'{cls_gq}.{rest}'
      else:
        canon = f'{module}.<self>.{rest}'
    else:
      if '.' not in name:
        ctor = facts.var_ctors.get(name, '')
      canon = f'{module}.{name}' if module else name
    out = canon if _lockish(name, ctor) else ''
    self._canon_memo[key] = out
    return out

  def canon_locks(self, gq, names):
    return frozenset(c for c in (self.canon_lock(gq, n) for n in names)
                     if c)

  def held_at(self, gq, locks):
    """Effective lockset at a site in ``gq``: the lexical `with`
    contexts plus the locks every caller provably holds on entry."""
    return self.entry_locks.get(gq, frozenset()) | \
        self.canon_locks(gq, locks)

  def _entry_locks(self):
    """gq -> locks held at *every* resolved call into gq (intersection
    over call sites, propagated to a fixed point). Thread roots are
    pinned to the empty set: a thread body always starts lock-free."""
    index, graph = self.index, self.graph
    incoming = {}
    for gq in sorted(graph.call_targets):
      facts = index.defs[gq]
      for call, tgt in zip(facts.calls, graph.call_targets.get(gq, ())):
        if tgt and tgt in index.defs:
          incoming.setdefault(tgt, []).append((gq, call.locks))
    pinned = set(self.thread_roots)
    entry = {}
    for gq in index.defs:
      entry[gq] = (frozenset() if gq in pinned or gq not in incoming
                   else None)  # None: no caller's entry known yet
    changed = True
    while changed:
      changed = False
      for gq in sorted(incoming):
        if gq in pinned:
          continue
        acc = None
        for caller, locks in incoming[gq]:
          base = entry.get(caller)
          if base is None:
            continue
          held = base | self.canon_locks(caller, locks)
          acc = held if acc is None else (acc & held)
        if acc is not None and acc != entry[gq]:
          entry[gq] = acc
          changed = True
    return {gq: (v if v is not None else frozenset())
            for gq, v in entry.items()}

  def trans_acquires(self, gq):
    """Canonical lock names ``gq`` (transitively) acquires."""
    if self._trans_acq is None:
      acq = {}
      for g in sorted(self.index.defs):
        acq[g] = frozenset(
            c for c in (self.canon_lock(g, a.name)
                        for a in self.index.defs[g].acquires) if c)
      changed = True
      while changed:
        changed = False
        for g in sorted(acq):
          merged = acq[g]
          for tgt, _ in self.graph.edges.get(g, ()):
            merged = merged | acq.get(tgt, frozenset())
          if merged != acq[g]:
            acq[g] = merged
            changed = True
      self._trans_acq = acq
    return self._trans_acq.get(gq, frozenset())

  # -- shared state ------------------------------------------------------

  def shared_access_table(self):
    """(kind, scope gq, attr) -> [(def gq, AccessSite)] for every
    ``self.*`` attribute (keyed by class) and tracked module global."""
    table = {}
    for gq in sorted(self.index.defs):
      facts = self.index.defs[gq]
      module = self.index.def_module.get(gq, '')
      for acc in facts.accesses:
        if acc.scope == 'global':
          key = ('global', module, acc.attr)
        else:
          if not facts.cls:
            continue
          cls_gq = f'{module}.{facts.cls}' if module else facts.cls
          key = ('attr', cls_gq, acc.attr)
        table.setdefault(key, []).append((gq, acc))
    return table

  def attr_ctor(self, key):
    kind, scope_gq, attr = key
    if kind != 'attr':
      return ''
    cls = self.index.classes.get(scope_gq)
    return cls.attr_ctors.get(attr, '') if cls is not None else ''

  # -- chains ------------------------------------------------------------

  def _hops_from(self, parents, gq, site_name, site_line):
    index = self.index
    hops = [{'name': f'{index.display(hop_gq)}()',
             'path': index.def_path(hop_gq), 'line': line}
            for hop_gq, line in self.graph.chain_hops(parents, gq)]
    hops.append({'name': f'{index.display(gq)}()',
                 'path': index.def_path(gq),
                 'line': index.defs[gq].line})
    hops.append({'name': site_name, 'path': index.def_path(gq),
                 'line': site_line})
    return hops

  def thread_chain(self, gq, site_name, site_line):
    """Spawn site → ... → site: how a spawned thread reaches ``gq``."""
    root = self.thread_owner[gq]
    index = self.index
    hops = []
    sp_entry = self.spawn_for_root.get(root)
    if sp_entry is not None:
      owner, sp = sp_entry
      hops.append({'name': f'{index.display(owner)}() spawns '
                           f'{index.display(root)}()',
                   'path': index.def_path(owner), 'line': sp.line})
    return hops + self._hops_from(self._parents(root), gq,
                                  site_name, site_line)

  def main_chain(self, gq, site_name, site_line):
    root = self.main_owner[gq]
    return self._hops_from(self._parents(root), gq, site_name, site_line)

  def root_chain(self, root, gq, site_name, site_line):
    return self._hops_from(self._parents(root), gq, site_name, site_line)


def thread_graph_for(index, graph):
  """The per-run ThreadGraph, built once and shared by all five rules
  (memoized on the CallGraph instance the run already owns)."""
  tg = getattr(graph, '_lddl_thread_graph', None)
  if tg is None or tg.index is not index:
    tg = ThreadGraph(index, graph)
    graph._lddl_thread_graph = tg
  return tg


# ---------------------------------------------------------------------------
# rules


class CrossThreadUnlockedState(ProjectRule):
  rule_id = 'LDA014'
  name = 'cross-thread-unlocked-state'
  invariant = ('state shared across the thread boundary is accessed '
               'under one common lock: a field written on a '
               'spawned-thread path and read or written on a main path '
               'with disjoint locksets is a data race — torn reads, '
               'lost updates, and order-dependent behavior that defeats '
               'determinism by construction')
  hint = ('guard both sides with the same lock, or hand the value '
          'across the boundary through a Queue/Event instead of a '
          'bare attribute')

  def _describe(self, key):
    kind, scope_gq, attr = key
    if kind == 'global':
      return f'module global {attr!r}'
    return f'self.{attr} (class {scope_gq.rsplit(".", 1)[-1]})'

  def _fmt_locks(self, locks):
    if not locks:
      return 'no lock'
    return ', '.join(sorted(_short_lock(c) for c in locks))

  def check(self, index, graph):
    tg = thread_graph_for(index, graph)
    if not tg.thread_roots:
      return
    for key in sorted(tg.shared_access_table().items()):
      key, sites = key
      ctor = tg.attr_ctor(key)
      if ctor in THREAD_SAFE_CTORS or _lockish(key[2], ctor):
        continue
      usable = [(gq, a) for gq, a in sites
                if not _is_ctor(gq) and not _testish(index.def_path(gq))]
      thread_side = [(gq, a) for gq, a in usable if gq in tg.thread_defs]
      main_side = [(gq, a) for gq, a in usable if gq in tg.main_defs]
      if not thread_side or not main_side:
        continue
      pair = self._first_racy_pair(tg, index, thread_side, main_side)
      if pair is None:
        continue
      (wgq, w), (ogq, o), write_on_thread = pair
      w_locks = tg.held_at(wgq, w.locks)
      o_locks = tg.held_at(ogq, o.locks)
      what = self._describe(key)
      side_w = 'thread' if write_on_thread else 'main'
      side_o = 'main' if write_on_thread else 'thread'
      w_chain = (tg.thread_chain if write_on_thread else tg.main_chain)(
          wgq, f'{what} written', w.line)
      o_chain = (tg.main_chain if write_on_thread else tg.thread_chain)(
          ogq, f'{what} {o.kind}', o.line)
      yield self.finding(
          index.def_path(wgq), w.line, w.col,
          f'{what} is written on a {side_w} path and {o.kind} on a '
          f'{side_o} path with no common lock '
          f'({side_w} holds {self._fmt_locks(w_locks)}, '
          f'{side_o} holds {self._fmt_locks(o_locks)})',
          chains=[
              {'label': f'written via {side_w} chain', 'hops': w_chain},
              {'label': f'{o.kind} via {side_o} chain', 'hops': o_chain},
          ])

  def _first_racy_pair(self, tg, index, thread_side, main_side):
    """First (write, opposite-side access) pair with disjoint effective
    locksets, in deterministic location order; thread-side writes are
    preferred as the anchor."""
    def loc(entry):
      gq, a = entry
      return (index.def_path(gq), a.line, a.col)

    for writes, others, on_thread in (
        ([e for e in thread_side if e[1].kind == 'write'], main_side,
         True),
        ([e for e in main_side if e[1].kind == 'write'], thread_side,
         False)):
      for w_entry in sorted(writes, key=loc):
        wgq, w = w_entry
        w_locks = tg.held_at(wgq, w.locks)
        for o_entry in sorted(others, key=loc):
          ogq, o = o_entry
          if (wgq, w.line, w.col) == (ogq, o.line, o.col):
            continue
          if w_locks & tg.held_at(ogq, o.locks):
            continue
          return w_entry, o_entry, on_thread
    return None


class ThreadLifecycle(ProjectRule):
  rule_id = 'LDA015'
  name = 'thread-lifecycle'
  invariant = ('every spawned thread has an exit discipline: either '
               'daemon=True (the process may exit without it) or a '
               'reachable join — and no shutdown path joins a thread '
               'without a timeout, which is exactly the infinite-join '
               'deadlock a wedged worker turns into a wedged trainer')
  hint = ('spawn with daemon=True or join the thread where it is torn '
          'down; give every shutdown-path join a timeout and handle '
          'the still-alive case')

  def check(self, index, graph):
    tg = thread_graph_for(index, graph)
    for owner_gq, sp, _tgt in tg.spawns:
      if sp.ctor != 'Thread' or _testish(index.def_path(owner_gq)):
        continue
      if sp.daemon is True or self._has_join(index, owner_gq, sp):
        continue
      bind = sp.binding or '<unbound>'
      yield self.finding(
          index.def_path(owner_gq), sp.line, sp.col,
          f'thread spawned in {index.display(owner_gq)}() (bound to '
          f'{bind}) has neither daemon=True nor a reachable join: it '
          'can outlive the process teardown and strand interpreter '
          'exit')
    yield from self._shutdown_joins(tg, index, graph)

  def _has_join(self, index, owner_gq, sp):
    if sp.binding.startswith('self.'):
      facts = index.defs[owner_gq]
      module = index.def_module.get(owner_gq, '')
      if not facts.cls:
        return False
      cls_gq = f'{module}.{facts.cls}' if module else facts.cls
      methods = index.class_methods.get(cls_gq, {})
      for mname in sorted(methods):
        for call in index.defs[methods[mname]].calls:
          if call.terminal == 'join' and call.receiver == sp.binding:
            return True
      return False
    if sp.binding:
      for call in index.defs[owner_gq].calls:
        if call.terminal == 'join' and call.receiver == sp.binding:
          return True
    return False

  def _thread_receiver(self, index, tg, gq, receiver):
    """Whether ``receiver`` names a thread object: a spawn binding or a
    Thread-constructed attribute/local visible from ``gq``."""
    facts = index.defs[gq]
    module = index.def_module.get(gq, '')
    if receiver.startswith('self.') and facts.cls:
      cls_gq = f'{module}.{facts.cls}' if module else facts.cls
      ctor = ''
      cls = index.classes.get(cls_gq)
      if cls is not None:
        ctor = cls.attr_ctors.get(receiver.split('.', 1)[1], '')
      if ctor.rsplit('.', 1)[-1] == 'Thread':
        return True
      methods = set(index.class_methods.get(cls_gq, {}).values())
      return any(owner in methods and sp.binding == receiver
                 and sp.ctor == 'Thread'
                 for owner, sp, _ in tg.spawns)
    if '.' not in receiver:
      if facts.var_ctors.get(receiver, '').rsplit('.', 1)[-1] == 'Thread':
        return True
      return any(owner == gq and sp.binding == receiver
                 and sp.ctor == 'Thread'
                 for owner, sp, _ in tg.spawns)
    return False

  def _shutdown_joins(self, tg, index, graph):
    roots = [gq for gq in sorted(index.defs)
             if gq.rsplit('.', 1)[-1] in SHUTDOWN_NAMES
             and not _testish(index.def_path(gq))]
    owner = {}
    for root in roots:
      for gq in sorted(tg._parents(root)):
        owner.setdefault(gq, root)
    seen = set()
    for gq in sorted(owner):
      facts = index.defs.get(gq)
      if facts is None or _testish(index.def_path(gq)):
        continue
      for call in facts.calls:
        if (call.terminal != 'join' or call.nargs or call.nkw
            or not call.receiver):
          continue
        if not self._thread_receiver(index, tg, gq, call.receiver):
          continue
        key = (index.def_path(gq), call.line, call.col)
        if key in seen:
          continue
        seen.add(key)
        root = owner[gq]
        chain = tg.root_chain(root, gq,
                              f'{call.receiver}.join() — no timeout',
                              call.line)
        yield self.finding(
            index.def_path(gq), call.line, call.col,
            f'{call.receiver}.join() without a timeout is reachable '
            f'from shutdown path {index.display(root)}(): if the '
            'thread is wedged, teardown never returns (the PR 9 '
            'worker-pool deadlock class)',
            chains=[{'label': 'shutdown path', 'hops': chain}])


class LockOrderInversion(ProjectRule):
  rule_id = 'LDA016'
  name = 'lock-order-inversion'
  invariant = ('any two locks are always acquired in one global order: '
               'one path taking A then B while another takes B then A '
               'deadlocks the moment both run concurrently')
  hint = ('pick one acquisition order for the pair and restructure the '
          'second path to match (or collapse the two locks into one)')

  def check(self, index, graph):
    tg = thread_graph_for(index, graph)
    pairs = {}  # (lock A canon, lock B canon) -> (path, line, gq)
    for gq in sorted(index.defs):
      if _testish(index.def_path(gq)):
        continue
      facts = index.defs[gq]
      entry = tg.entry_locks.get(gq, frozenset())
      for acq in facts.acquires:
        b = tg.canon_lock(gq, acq.name)
        if not b:
          continue
        held = entry | tg.canon_locks(gq, acq.held)
        for a in sorted(held):
          if a != b:
            pairs.setdefault((a, b),
                             (index.def_path(gq), acq.line, gq))
      for call, tgt in zip(facts.calls, graph.call_targets.get(gq, ())):
        if not tgt:
          continue
        held = entry | tg.canon_locks(gq, call.locks)
        if not held:
          continue
        for b in sorted(tg.trans_acquires(tgt)):
          for a in sorted(held):
            if a != b:
              pairs.setdefault((a, b),
                               (index.def_path(gq), call.line, gq))
    for a, b in sorted(pairs):
      if a >= b or (b, a) not in pairs:
        continue
      path1, line1, gq1 = pairs[(a, b)]
      path2, line2, gq2 = pairs[(b, a)]
      sa, sb = _short_lock(a), _short_lock(b)
      yield self.finding(
          path1, line1, 1,
          f'lock order inversion: {index.display(gq1)}() acquires '
          f'{sa} then {sb} while {index.display(gq2)}() '
          f'({path2}:{line2}) acquires {sb} then {sa} — concurrent '
          'execution of the two paths deadlocks',
          chains=[
              {'label': f'{sa} → {sb}',
               'hops': [{'name': f'{index.display(gq1)}(): '
                                 f'{sa} then {sb}',
                         'path': path1, 'line': line1}]},
              {'label': f'{sb} → {sa}',
               'hops': [{'name': f'{index.display(gq2)}(): '
                                 f'{sb} then {sa}',
                         'path': path2, 'line': line2}]},
          ])


class SignalHandlerSafety(ProjectRule):
  rule_id = 'LDA017'
  name = 'signal-handler-safety'
  invariant = ('signal handlers only set flags: a handler runs on the '
               'main thread at an arbitrary bytecode boundary, so lock '
               'acquisition self-deadlocks against the frame it '
               'interrupted, blocking I/O stalls delivery, and '
               'allocation-heavy work (logging, print) re-enters '
               'non-reentrant machinery — the PreemptionGuard bug class')
  hint = ('have the handler set a threading.Event (or write a '
          'self-pipe) and do the real work on the next loop iteration')

  def check(self, index, graph):
    tg = thread_graph_for(index, graph)
    seen = set()
    for module in sorted(index.modules):
      mfacts = index.modules[module]
      if _testish(mfacts.path):
        continue
      for handler, scope, reg_line in mfacts.signal_handlers:
        hgq = self._resolve_handler(index, module, scope, handler)
        if not hgq:
          continue
        reg_hop = {'name': f'signal.signal(..., {handler})',
                   'path': mfacts.path, 'line': reg_line}
        yield from self._scan_handler(tg, index, hgq, reg_hop, seen)

  def _resolve_handler(self, index, module, scope, handler):
    if handler.startswith('self.') and handler.count('.') == 1:
      owner_gq = f'{module}.{scope}' if module else scope
      facts = index.defs.get(owner_gq)
      if facts is None or not facts.cls:
        return ''
      cls_gq = f'{module}.{facts.cls}' if module else facts.cls
      return index.mro_method(cls_gq, handler.split('.', 1)[1])
    return index._resolve_value(module, scope, handler)

  def _scan_handler(self, tg, index, hgq, reg_hop, seen):
    parents = tg._parents(hgq)
    for gq in sorted(parents):
      facts = index.defs.get(gq)
      if facts is None:
        continue
      sites = []
      for eff in facts.effects:
        if eff.kind in ('blocking_io', 'unbounded_wait'):
          sites.append((eff.line, eff.col,
                        f'{eff.kind.replace("_", " ")} {eff.detail}'))
      for acq in facts.acquires:
        if tg.canon_lock(gq, acq.name):
          sites.append((acq.line, acq.col,
                        f'lock acquisition (with {acq.name}:)'))
      for call in facts.calls:
        d = call.dotted or ''
        if d == 'print' or d.startswith('logging.'):
          sites.append((call.line, call.col,
                        f'{call.terminal}() (allocates and takes '
                        'interpreter-internal locks)'))
      for line, col, what in sorted(sites):
        key = (index.def_path(gq), line, col)
        if key in seen:
          continue
        seen.add(key)
        hops = [reg_hop] + tg.root_chain(hgq, gq, what, line)
        yield self.finding(
            index.def_path(gq), line, col,
            f'{what} reachable from signal handler '
            f'{index.display(hgq)}(): handlers interrupt arbitrary '
            'frames — only async-signal-safe flag setting is safe '
            'here',
            chains=[{'label': 'handler path', 'hops': hops}])


class BlockingCallUnderLock(ProjectRule):
  rule_id = 'LDA018'
  name = 'blocking-under-lock'
  invariant = ('no lock is held across a blocking call: an unbounded '
               'queue/socket/join/sleep inside a with-lock region '
               'serializes every other thread on the slow operation '
               'and, if the unblocker needs the same lock, deadlocks')
  hint = ('move the blocking call outside the with block (snapshot '
          'state under the lock, block after releasing it), or bound '
          'it with a timeout; Condition.wait on the held lock is the '
          'sanctioned exception')

  # Zero-arg forms of these are unbounded waits (mirrors the engine's
  # UNBOUNDED_WAIT_ATTRS); these block regardless of arguments.
  ALWAYS_BLOCKING = frozenset({'recv', 'recv_into', 'accept', 'select'})

  def check(self, index, graph):
    tg = thread_graph_for(index, graph)
    for gq in sorted(index.defs):
      if _testish(index.def_path(gq)):
        continue
      facts = index.defs[gq]
      entry = tg.entry_locks.get(gq, frozenset())
      for call in facts.calls:
        held = entry | tg.canon_locks(gq, call.locks)
        if not held:
          continue
        hazard = self._hazard(tg, gq, call, held)
        if hazard is None:
          continue
        locks = ', '.join(sorted(_short_lock(c) for c in held))
        yield self.finding(
            index.def_path(gq), call.line, call.col,
            f'blocking {hazard} in {index.display(gq)}() while '
            f'holding {locks}: every thread contending for the lock '
            'stalls behind this call, and a deadlock if the unblocker '
            'needs the same lock')

  def _hazard(self, tg, gq, call, held):
    if call.dotted == 'time.sleep':
      return 'time.sleep(...)'
    if not call.receiver:
      return None
    recv_canon = tg.canon_lock(gq, call.receiver)
    if call.terminal in ('wait', 'wait_for') and recv_canon in held:
      return None  # Condition.wait releases the lock it waits on
    if (call.terminal in UNBOUNDED_WAIT_ATTRS
        and call.nargs == 0 and call.nkw == 0):
      return f'{call.receiver}.{call.terminal}()'
    if call.terminal == 'wait_for' and call.nkw == 0:
      return f'{call.receiver}.wait_for(...) (no timeout)'
    if call.terminal in self.ALWAYS_BLOCKING:
      return f'{call.receiver}.{call.terminal}(...)'
    return None


def concurrency_rules():
  """Fresh instances of the concurrency ruleset, in rule-id order."""
  return [
      CrossThreadUnlockedState(),
      ThreadLifecycle(),
      LockOrderInversion(),
      SignalHandlerSafety(),
      BlockingCallUnderLock(),
  ]

"""Static analysis for the pipeline's SPMD determinism and
resource-safety invariants (the ``lddl-analyze`` linter).

The correctness story of this codebase rests on properties no runtime
test can fully cover: every rank derives the identical sample plan
without communication, all randomness flows through seeded helpers,
collectives are issued uniformly, and a killed worker leaks nothing.
This package turns those conventions into an AST-based check that runs
in tier-1 (``tests/test_analysis_self.py``), so refactors cannot
silently erode them.

Layout:
  - :mod:`.engine`: parse + single ancestor-tracking walk, import-alias
    resolution, pragma suppression;
  - :mod:`.rules`: the LDA001-LDA005 ruleset;
  - :mod:`.findings`: the finding model (file:line, rule id, fix hint);
  - :mod:`.pragmas`: inline ``# lddl: noqa[LDAxxx]`` suppressions;
  - :mod:`.cli`: the ``lddl-analyze`` console entry point.
"""

import os

from .engine import (
    Rule,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from .findings import Finding
from .rules import default_rules, rules_by_id


def analyze_package(rules=None):
  """Run the linter over the installed ``lddl_tpu`` tree itself.

  Returns ``(unsuppressed, suppressed)`` finding lists — the self-check
  test and ``bench.py``'s lint-status stamp both go through here.
  """
  root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  findings, _ = analyze_paths([root], rules=rules)
  return ([f for f in findings if not f.suppressed],
          [f for f in findings if f.suppressed])


__all__ = [
    'Finding',
    'Rule',
    'analyze_file',
    'analyze_package',
    'analyze_paths',
    'analyze_source',
    'default_rules',
    'rules_by_id',
]

"""Static analysis for the pipeline's SPMD determinism and
resource-safety invariants (the ``lddl-analyze`` linter).

The correctness story of this codebase rests on properties no runtime
test can fully cover: every rank derives the identical sample plan
without communication, all randomness flows through seeded helpers,
collectives are issued uniformly — even through call chains — the
elastic path never blocks on a peer, and jit-compiled code never syncs
the host. This package turns those conventions into an AST-based check
that runs in tier-1 (``tests/test_analysis_self.py``), so refactors
cannot silently erode them.

Layout:
  - :mod:`.engine`: parse + single ancestor-tracking walk, import/local
    alias resolution, per-module facts export, pragma suppression, the
    (parallel) per-file driver;
  - :mod:`.project`: whole-program index — import/method resolution
    across modules, ``ProjectRule`` base, ``analyze_project``;
  - :mod:`.callgraph`: deterministic call graph, transitive effect
    sets, call-chain traces;
  - :mod:`.concurrency`: thread graph (spawn edges), shared-state
    access sets, lockset inference, and the LDA014–LDA018 concurrency
    rules;
  - :mod:`.cache`: content-hash incremental cache for findings and
    per-module facts (``LDDL_ANALYZE_CACHE``);
  - :mod:`.rules`: the per-file LDA001–LDA007 and interprocedural
    LDA008–LDA011 rulesets;
  - :mod:`.findings`: the finding model (file:line, rule id, fix hint,
    call chain);
  - :mod:`.pragmas`: inline ``# lddl: noqa[LDAxxx]`` suppressions;
  - :mod:`.sarif`: SARIF 2.1.0 rendering for CI annotation;
  - :mod:`.cli`: the ``lddl-analyze`` console entry point.
"""

import os

from .cache import AnalysisCache, cache_from_env
from .concurrency import CONCURRENCY_RULE_IDS
from .engine import (
    Rule,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from .findings import Finding
from .project import ProjectRule, analyze_project
from .rules import all_rules, default_rules, project_rules, rules_by_id

# Schema of the lint status dict / --format json document. v3 adds the
# labeled multi-chain traces (``chains``) the concurrency rules emit.
LINT_SCHEMA_VERSION = 3


def analyze_package(rules=None, jobs=None, cache=None):
  """Run the analyzer — project mode, full call graph — over the
  installed ``lddl_tpu`` tree itself.

  Returns ``(unsuppressed, suppressed)`` finding lists — the self-check
  test, ``bench.py``'s lint-status stamp, and the ``lddl-perf --gate``
  concurrency leg all go through here.
  """
  root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  findings, _ = analyze_project([root], rules=rules, jobs=jobs,
                                cache=cache)
  return ([f for f in findings if not f.suppressed],
          [f for f in findings if f.suppressed])


__all__ = [
    'AnalysisCache',
    'CONCURRENCY_RULE_IDS',
    'Finding',
    'LINT_SCHEMA_VERSION',
    'ProjectRule',
    'Rule',
    'all_rules',
    'cache_from_env',
    'analyze_file',
    'analyze_package',
    'analyze_paths',
    'analyze_project',
    'analyze_source',
    'default_rules',
    'project_rules',
    'rules_by_id',
]

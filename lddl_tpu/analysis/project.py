"""Whole-program index: every module parsed once, names resolved to
definitions, ready for call-graph construction.

The per-file engine sees one module at a time; this layer links the
per-module facts (:func:`~lddl_tpu.analysis.engine.extract_module_facts`)
across the project so rules can follow a call through any number of
files. Resolution is deliberately best-effort and *deterministic* —
when a name can't be pinned to exactly one project definition it
resolves to nothing rather than to a guess:

  - module-level names resolve through import aliases, including
    relative imports (anchored at the importing module's package) and
    one level of re-export chasing through package ``__init__`` files;
  - ``self.method()`` / ``cls.method()`` resolve through the enclosing
    class and its project-local bases (a bounded MRO walk);
  - ``x.method()`` resolves when ``x`` was built by a visible
    constructor — a local ``x = ClassName(...)`` (also through a
    ``... if ... else None`` conditional) or a ``self.x = ClassName(...)``
    recorded on the class;
  - as a last resort, a method name defined by exactly **one** project
    class (and not on the common-vocabulary blacklist below) resolves to
    that class's method.

``ProjectRule`` is the base for interprocedural rules; they run once
over the built index + call graph, not per AST node.
"""

import ast
import os

from .callgraph import CallGraph
from .engine import (Rule, analyze_paths, discover_py_files,
                     extract_module_facts)
from .findings import Finding, sort_findings
from .pragmas import is_suppressed, pragma_lines

# Method names too generic to trust the unique-attribute fallback with:
# if exactly one project class defines `frobnicate` the match is
# meaningful; if exactly one happens to define `read` today, resolving
# every `x.read()` there would be wrong tomorrow.
COMMON_ATTRS = frozenset({
    'get', 'put', 'read', 'write', 'open', 'close', 'run', 'start',
    'stop', 'join', 'wait', 'acquire', 'release', 'send', 'recv',
    'update', 'append', 'add', 'extend', 'insert', 'pop', 'clear',
    'copy', 'items', 'keys', 'values', 'submit', 'map', 'apply',
    'result', 'encode', 'decode', 'load', 'save', 'reset', 'flush',
    'next', 'info', 'debug', 'warning', 'error', 'exception', 'name',
})

_MAX_MRO_DEPTH = 5
_MAX_REEXPORT_DEPTH = 4


def module_name_for(path):
  """Dotted module name for a file, derived by walking up while
  ``__init__.py`` exists (matches what an import of the file would
  bind). A free-standing script is just its stem."""
  path = os.path.abspath(path)
  d, base = os.path.split(path)
  parts = [] if base == '__init__.py' else [os.path.splitext(base)[0]]
  while os.path.isfile(os.path.join(d, '__init__.py')):
    d, pkg = os.path.split(d)
    parts.append(pkg)
  return '.'.join(reversed(parts))


class ProjectIndex:
  """Cross-module definition tables + name resolution."""

  def __init__(self):
    self.modules = {}        # module name -> ModuleFacts
    self.module_is_pkg = {}  # module name -> bool (__init__.py)
    self.defs = {}           # global qualname -> DefFacts
    self.def_module = {}     # global qualname -> module name
    self.classes = {}        # global class qualname -> ClassFacts
    self.class_module = {}
    self.class_methods = {}  # class gq -> {method local name -> def gq}
    self.attr_index = {}     # method name -> sorted tuple of class gqs

  @classmethod
  def build(cls, files, cache=None):
    """Parse + index every file (sorted); unparsable files are skipped
    here — the per-file pass reports them as LDA000. With a ``cache``,
    unchanged files load their pickled ModuleFacts by content hash and
    skip the parse (the dominant cost of a warm project run)."""
    index = cls()
    for path in sorted(files):
      try:
        with open(path, encoding='utf-8') as fh:
          source = fh.read()
      except OSError:
        continue
      module = module_name_for(path)
      if module in index.modules:
        continue  # duplicate module name across roots: first (sorted) wins
      facts = cache.load('facts', path, source) if cache else None
      if facts is None:
        try:
          tree = ast.parse(source, filename=path)
        except (SyntaxError, ValueError):
          continue
        facts = extract_module_facts(tree, path)
        if cache is not None:
          cache.store('facts', path, source, facts)
      index.modules[module] = facts
      index.module_is_pkg[module] = (
          os.path.basename(path) == '__init__.py')
      for dq in facts.defs:
        gq = f'{module}.{dq}' if module else dq
        index.defs[gq] = facts.defs[dq]
        index.def_module[gq] = module
      for cq in facts.classes:
        gq = f'{module}.{cq}' if module else cq
        index.classes[gq] = facts.classes[cq]
        index.class_module[gq] = module
    for gq, d in index.defs.items():
      if not d.cls:
        continue
      module = index.def_module[gq]
      cls_gq = f'{module}.{d.cls}' if module else d.cls
      index.class_methods.setdefault(cls_gq, {})[
          d.qualname.rsplit('.', 1)[-1]] = gq
    attr = {}
    for cls_gq in sorted(index.class_methods):
      for mname in index.class_methods[cls_gq]:
        attr.setdefault(mname, []).append(cls_gq)
    index.attr_index = {m: tuple(v) for m, v in attr.items()}
    return index

  # -- display / location helpers ----------------------------------------

  def def_path(self, gq):
    return self.modules[self.def_module[gq]].path

  def display(self, gq):
    """Module-stripped def qualname ('Executor._map_elastic')."""
    module = self.def_module.get(gq, '')
    return gq[len(module) + 1:] if module and gq.startswith(module) else gq

  # -- name resolution ---------------------------------------------------

  def _absolutize(self, module, dotted):
    if not dotted.startswith('.'):
      return dotted
    level = len(dotted) - len(dotted.lstrip('.'))
    rest = dotted[level:]
    parts = module.split('.') if module else []
    anchor = parts if self.module_is_pkg.get(module) else parts[:-1]
    drop = level - 1
    if drop:
      anchor = anchor[:len(anchor) - drop] if drop <= len(anchor) else []
    return '.'.join(anchor + ([rest] if rest else []))

  def _resolve_global(self, dotted, depth=_MAX_REEXPORT_DEPTH):
    """('def'|'class'|'', gq) for an absolute dotted name, chasing
    re-exports through package __init__ aliases."""
    if dotted in self.defs:
      return 'def', dotted
    if dotted in self.classes:
      return 'class', dotted
    if depth <= 0:
      return '', ''
    # Longest known module prefix, then follow that module's alias for
    # the next segment (the `from .executor import Executor` re-export).
    parts = dotted.split('.')
    for i in range(len(parts) - 1, 0, -1):
      prefix = '.'.join(parts[:i])
      if prefix not in self.modules:
        continue
      first, rest = parts[i], parts[i + 1:]
      al = self.modules[prefix].aliases.get(first)
      if not al:
        return '', ''
      target = self._absolutize(prefix, al)
      return self._resolve_global('.'.join([target] + rest),
                                  depth=depth - 1)
    return '', ''

  def _resolve_in_scope(self, module, scope_path, dotted):
    """('def'|'class'|'', gq) for a dotted name as seen from inside
    ``scope_path`` (a def qualname within ``module``, or '')."""
    if not dotted:
      return '', ''
    if dotted.startswith('.') or '.' in dotted:
      return self._resolve_global(self._absolutize(module, dotted))
    # Plain name: walk enclosing function scopes out to module level.
    # Class frames are skipped — Python name lookup never sees them.
    segs = scope_path.split('.') if scope_path else []
    for i in range(len(segs), -1, -1):
      parent = segs[:i]
      if i:
        parent_gq = '.'.join(([module] if module else []) + parent)
        if parent_gq in self.classes:
          continue
      cand = '.'.join(([module] if module else []) + parent + [dotted])
      if cand in self.defs:
        return 'def', cand
      if cand in self.classes:
        return 'class', cand
    return '', ''

  def mro_method(self, cls_gq, mname, depth=_MAX_MRO_DEPTH):
    """Def gq of ``mname`` on ``cls_gq`` or its project-local bases."""
    methods = self.class_methods.get(cls_gq, {})
    if mname in methods:
      return methods[mname]
    if depth <= 0:
      return ''
    cls = self.classes.get(cls_gq)
    if cls is None:
      return ''
    module = self.class_module.get(cls_gq, '')
    for base in cls.bases:
      kind, bgq = self._resolve_in_scope(module, '', base)
      if kind == 'class' and bgq != cls_gq:
        found = self.mro_method(bgq, mname, depth=depth - 1)
        if found:
          return found
    return ''

  def _resolve_value(self, module, scope_path, dotted):
    """Def gq a dotted *callable* name resolves to (classes resolve to
    their __init__), or ''."""
    kind, gq = self._resolve_in_scope(module, scope_path, dotted)
    if kind == 'def':
      return gq
    if kind == 'class':
      return self.mro_method(gq, '__init__')
    return ''

  def _receiver_class(self, module, caller_gq, receiver):
    """Class gq of a call receiver, via the three typing heuristics
    (self/cls, local ctor, self-attribute ctor)."""
    facts = self.defs.get(caller_gq)
    if facts is None:
      return ''
    scope_path = self.display(caller_gq)
    if receiver in ('self', 'cls'):
      if facts.cls:
        cls_gq = f'{module}.{facts.cls}' if module else facts.cls
        if cls_gq in self.classes:
          return cls_gq
      return ''
    ctor = ''
    if receiver.startswith('self.') and receiver.count('.') == 1:
      if facts.cls:
        cls_gq = f'{module}.{facts.cls}' if module else facts.cls
        cls = self.classes.get(cls_gq)
        if cls is not None:
          ctor = cls.attr_ctors.get(receiver.split('.', 1)[1], '')
    elif '.' not in receiver:
      ctor = facts.var_ctors.get(receiver, '')
    if not ctor:
      return ''
    kind, gq = self._resolve_in_scope(module, scope_path, ctor)
    return gq if kind == 'class' else ''

  def resolve_call(self, caller_gq, call):
    """Def gq one CallSite resolves to, or '' (unresolvable names make
    no edge — missing edges under-approximate, they never invent
    reachability)."""
    module = self.def_module.get(caller_gq, '')
    scope_path = self.display(caller_gq)
    if call.terminal == 'partial' and call.arg0:
      return self._resolve_value(module, scope_path, call.arg0)
    if call.dotted:
      gq = self._resolve_value(module, scope_path, call.dotted)
      if gq:
        return gq
    if call.receiver:
      cls_gq = self._receiver_class(module, caller_gq, call.receiver)
      if cls_gq:
        found = self.mro_method(cls_gq, call.terminal)
        if found:
          return found
      if call.terminal not in COMMON_ATTRS:
        owners = self.attr_index.get(call.terminal, ())
        if len(owners) == 1:
          return self.class_methods[owners[0]][call.terminal]
    return ''

  def jit_root_defs(self):
    """Def gqs whose bodies become traced/compiled code: defs decorated
    with jit/shard_map/pallas_call (directly or through
    functools.partial), plus functions passed to ``jax.jit(f)`` /
    ``shard_map(f)`` / ``pallas_call(f)`` / ``CompiledStepCache(f)``
    call sites (including ``step_fn = jax.jit(step)`` wrapping)."""
    roots = []
    for module in sorted(self.modules):
      facts = self.modules[module]
      for dq in sorted(facts.defs):
        d = facts.defs[dq]
        for dec in d.decorators:
          if dec.rsplit('.', 1)[-1] in ('jit', 'shard_map', 'pallas_call'):
            roots.append((f'{module}.{dq}' if module else dq, dec))
            break
      for arg0, scope, _line in facts.jit_roots:
        gq = self._resolve_value(module, scope, arg0)
        if gq:
          roots.append((gq, 'wrapped'))
    out = {}
    for gq, how in roots:
      out.setdefault(gq, how)
    return out


class ProjectRule:
  """Base for interprocedural rules: runs once over the whole project
  (index + call graph), not per AST node. Same metadata contract as the
  per-file :class:`~lddl_tpu.analysis.engine.Rule`."""

  rule_id = ''
  name = ''
  invariant = ''
  hint = ''

  def check(self, index, graph):
    """Yield findings over the built project."""
    return ()

  def finding(self, path, line, col, message, chain=None, chains=None,
              hint=None):
    return Finding(
        rule_id=self.rule_id, path=path, line=line, col=col,
        message=message, hint=self.hint if hint is None else hint,
        chain=chain, chains=chains)


def build_chain(index, hops, target_gq, effect):
  """Findings' ``chain`` field: the call path root → ... → effect.

  ``hops`` come from :meth:`CallGraph.chain_hops` (each with the line of
  the call it makes toward the target); the target definition and the
  effect site close the chain.
  """
  chain = [{'name': f'{index.display(gq)}()', 'path': index.def_path(gq),
            'line': line} for gq, line in hops]
  chain.append({'name': f'{index.display(target_gq)}()',
                'path': index.def_path(target_gq),
                'line': index.defs[target_gq].line})
  chain.append({'name': effect.detail, 'path': index.def_path(target_gq),
                'line': effect.line})
  return chain


def analyze_project(paths, rules=None, jobs=None, file_filter=None,
                    cache=None):
  """Whole-program analysis: the per-file rules over every ``.py`` under
  ``paths`` (parallel when ``jobs`` allows) plus the interprocedural
  project rules over the linked index.

  Returns ``(findings, files_scanned)`` like :func:`analyze_paths`;
  project findings honor the same ``# lddl: noqa[...]`` pragmas, applied
  at the effect/call site they are anchored to.

  ``file_filter`` (a set of absolute paths, from ``--changed``)
  restricts the *per-file* pass to those files while the index and the
  project rules still cover the whole tree — interprocedural claims
  need every module, and the caller filters project findings down to
  the ones whose chains touch the filter. ``files_scanned`` stays the
  full tree count for the same reason. ``cache`` accelerates both
  passes (cached findings + cached per-module facts).
  """
  if rules is None:
    file_rules = None
    from .rules import project_rules
    proj_rules = project_rules()
  else:
    file_rules = [r for r in rules if isinstance(r, Rule)]
    proj_rules = [r for r in rules if isinstance(r, ProjectRule)]
  files = discover_py_files(paths)
  if file_filter is None:
    findings, files_scanned = analyze_paths(paths, rules=file_rules,
                                            jobs=jobs, cache=cache)
  else:
    targets = [p for p in files if os.path.abspath(p) in file_filter]
    findings, _ = analyze_paths(targets, rules=file_rules, jobs=jobs,
                                cache=cache)
    files_scanned = len(files)
  index = ProjectIndex.build(files, cache=cache)
  graph = CallGraph(index)
  project_findings = []
  for rule in proj_rules:
    project_findings.extend(rule.check(index, graph))
  pragma_cache = {}
  for f in project_findings:
    if f.path not in pragma_cache:
      try:
        with open(f.path, encoding='utf-8') as fh:
          pragma_cache[f.path] = pragma_lines(fh.read())
      except OSError:
        pragma_cache[f.path] = {}
    if pragma_cache[f.path]:
      f.suppressed = is_suppressed(f, pragma_cache[f.path])
  return sort_findings(findings + project_findings), files_scanned

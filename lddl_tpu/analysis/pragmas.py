"""Inline suppression pragmas.

Syntax (anywhere in a comment)::

  do_thing()  # lddl: noqa[LDA001] reason the hazard does not apply here
  other()     # lddl: noqa  -- suppresses every rule on this line

A pragma on a *standalone* comment line covers the whole next logical
line (the full multi-line statement), so a suppression and its
(mandatory, by convention) reason can live on their own line when the
code line has no room. When the next statement is a decorator, coverage
extends through the decorator stack to the ``def``/``class`` signature
line, so a pragma placed above a decorated definition suppresses
findings anchored at the definition itself::

  # lddl: noqa[LDA003] timeout detection: aborting a stuck collective
  # never diverges ranks, it raises.
  if now > deadline:
      ...

A finding is suppressed when a pragma naming its rule (or a bare
``noqa``) covers any source line the flagged node spans
(``lineno..end_lineno``). Comments are found with ``tokenize`` so
pragma-like text inside string literals never suppresses anything.
"""

import io
import re
import tokenize

_PRAGMA_RE = re.compile(r'#\s*lddl:\s*noqa(?:\[([A-Za-z0-9_,\s]+)\])?')

# None as a value means "suppress all rules" (bare ``# lddl: noqa``).
ALL_RULES = None

_TRIVIA = frozenset({
    tokenize.COMMENT, tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
    tokenize.DEDENT, tokenize.ENCODING, tokenize.ENDMARKER,
})


def _merge(out, line, rules):
  prev = out.get(line, frozenset())
  if rules is ALL_RULES or (line in out and prev is ALL_RULES):
    out[line] = ALL_RULES
  else:
    out[line] = prev | rules


def pragma_lines(source):
  """Map source line number -> frozenset of suppressed rule ids (or
  :data:`ALL_RULES`). Files that fail to tokenize (the engine reports
  those as LDA000) yield no pragmas."""
  try:
    tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
  except (tokenize.TokenError, SyntaxError, IndentationError):
    return {}
  code_lines = set()
  for tok in tokens:
    if tok.type not in _TRIVIA:
      code_lines.update(range(tok.start[0], tok.end[0] + 1))
  out = {}
  for i, tok in enumerate(tokens):
    if tok.type != tokenize.COMMENT:
      continue
    m = _PRAGMA_RE.search(tok.string)
    if not m:
      continue
    ids = m.group(1)
    rules = (ALL_RULES if ids is None else frozenset(
        r.strip().upper() for r in ids.split(',') if r.strip()))
    line = tok.start[0]
    _merge(out, line, rules)
    if line in code_lines:
      continue
    # Standalone comment: cover the next logical line in full (the
    # statement may span many physical lines; the flagged node can sit
    # on any of them). Comment-only lines in between — e.g. the
    # pragma's reason text — don't count as the statement. When that
    # logical line is a decorator, keep extending through any further
    # decorators and the ``def``/``class`` signature line they adorn:
    # a pragma above a decorated definition must suppress findings on
    # the definition itself, which ``ast`` anchors at the ``def`` line.
    j = i + 1
    while j < len(tokens):
      start = end = None
      first = None
      for k in range(j, len(tokens)):
        nxt = tokens[k]
        if start is None:
          if nxt.type in _TRIVIA:
            continue
          start = nxt.start[0]
          first = nxt
        end = nxt.end[0]
        if nxt.type == tokenize.NEWLINE:
          j = k + 1
          break
      else:
        j = len(tokens)
      if start is None:
        break
      for l in range(start, end + 1):
        _merge(out, l, rules)
      if not (first.type == tokenize.OP and first.string == '@'):
        break
  return out


def is_suppressed(finding, pragmas):
  """Whether ``finding`` is covered by a pragma on any line it spans."""
  for line in range(finding.line, max(finding.line, finding.end_line) + 1):
    if line not in pragmas:
      continue
    rules = pragmas[line]
    if rules is ALL_RULES or finding.rule_id in rules:
      return True
  return False

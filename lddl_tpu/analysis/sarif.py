"""SARIF 2.1.0 rendering of findings (``lddl-analyze --format sarif``).

SARIF is the interchange format CI systems (GitHub code scanning,
Azure DevOps, ...) ingest to render findings as inline annotations.
This writer emits the minimal conforming document: one run, the rule
table as ``tool.driver.rules``, one ``result`` per finding. Pragma-
suppressed findings are still emitted but carry an ``inSource``
suppression, so dashboards show them as reviewed rather than open.
Interprocedural findings render their call chain as a ``codeFlow``,
which viewers display as a step-through path to the effect site.
"""

SARIF_VERSION = '2.1.0'
_SCHEMA_URI = ('https://raw.githubusercontent.com/oasis-tcs/sarif-spec/'
               'master/Schemata/sarif-schema-2.1.0.json')


def _location(path, line, col=None, message=None):
  loc = {
      'physicalLocation': {
          'artifactLocation': {'uri': path},
          'region': {'startLine': max(1, line)},
      },
  }
  if col:
    loc['physicalLocation']['region']['startColumn'] = col
  if message:
    loc['message'] = {'text': message}
  return loc


def _code_flow(chain, label=None):
  flow = {
      'threadFlows': [{
          'locations': [
              {'location': _location(hop['path'], hop['line'],
                                     message=hop['name'])}
              for hop in chain
          ],
      }],
  }
  if label:
    flow['message'] = {'text': label}
  return flow


def to_sarif(findings, rules):
  """One SARIF 2.1.0 document (a JSON-ready dict) for ``findings``,
  with ``rules`` (per-file + project rule instances) as the driver's
  rule table."""
  rule_list = sorted(rules, key=lambda r: r.rule_id)
  rule_index = {r.rule_id: i for i, r in enumerate(rule_list)}
  results = []
  for f in findings:
    result = {
        'ruleId': f.rule_id,
        'level': 'error',
        'message': {'text': f.message},
        'locations': [_location(f.path, f.line, col=f.col)],
    }
    if f.rule_id in rule_index:
      result['ruleIndex'] = rule_index[f.rule_id]
    if f.suppressed:
      result['suppressions'] = [{'kind': 'inSource'}]
    if f.chains:
      # One codeFlow per labeled chain: a cross-thread finding shows the
      # writer's thread path and the reader's main path side by side.
      result['codeFlows'] = [_code_flow(c['hops'], label=c.get('label'))
                             for c in f.chains]
    elif f.chain:
      result['codeFlows'] = [_code_flow(f.chain)]
    results.append(result)
  return {
      '$schema': _SCHEMA_URI,
      'version': SARIF_VERSION,
      'runs': [{
          'tool': {
              'driver': {
                  'name': 'lddl-analyze',
                  'informationUri':
                      'https://github.com/NVIDIA/LDDL',
                  'rules': [{
                      'id': r.rule_id,
                      'name': r.name,
                      'shortDescription': {'text': r.invariant},
                      'help': {'text': r.hint},
                  } for r in rule_list],
              },
          },
          'results': results,
      }],
  }

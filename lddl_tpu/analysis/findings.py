"""The finding model shared by the engine, rules, and CLI.

A :class:`Finding` is one rule violation at one source location. Findings
carry a stable ``rule_id`` (``LDAxxx``), a human message describing the
hazard, and a ``hint`` describing the idiomatic fix, so both the text and
``--json`` renderings are self-explanatory. ``suppressed`` marks findings
covered by an inline ``# lddl: noqa[LDAxxx]`` pragma — they are reported
(in ``--json`` and with ``--show-suppressed``) but never fail the run.
"""

import dataclasses


@dataclasses.dataclass
class Finding:
  """One rule violation at ``path:line:col``.

  Interprocedural (project-mode) findings carry a ``chain``: the call
  path from the analysis root to the effect site, as a list of
  ``{'name', 'path', 'line'}`` hops ending at the hazardous call itself.
  Per-file findings leave it ``None``.

  Concurrency findings relate *two* execution paths (the writer's thread
  chain and the reader's main chain); those carry ``chains`` — a list of
  ``{'label', 'hops'}`` entries, each ``hops`` shaped like ``chain``.
  When ``chains`` is set, ``chain`` mirrors its first entry's hops so
  single-chain consumers keep working.
  """

  rule_id: str
  path: str
  line: int
  col: int
  message: str
  hint: str = ''
  end_line: int = 0  # last source line of the flagged node (pragma window)
  suppressed: bool = False
  chain: list = None  # call-chain trace (project mode), else None
  chains: list = None  # labeled multi-chain traces (concurrency rules)

  def __post_init__(self):
    if not self.end_line:
      self.end_line = self.line
    if self.chains and self.chain is None:
      self.chain = self.chains[0]['hops']

  def location(self):
    return f'{self.path}:{self.line}:{self.col}'

  def as_dict(self):
    """JSON-stable rendering (the ``--json`` schema v3, one entry per
    finding): rule, path, line, col, message, hint, suppressed, chain,
    chains."""
    return {
        'rule': self.rule_id,
        'path': self.path,
        'line': self.line,
        'col': self.col,
        'message': self.message,
        'hint': self.hint,
        'suppressed': self.suppressed,
        'chain': self.chain,
        'chains': self.chains,
    }

  @staticmethod
  def _render_hops(hops):
    head = ' → '.join(hop['name'] for hop in hops[:-1])
    last = hops[-1]
    sep = ' → ' if head else ''
    return (f"{head}{sep}{last['name']}"
            f" at {last['path']}:{last['line']}")

  def render(self):
    tag = ' (suppressed)' if self.suppressed else ''
    out = f'{self.location()}: {self.rule_id}{tag}: {self.message}'
    if self.chains:
      for entry in self.chains:
        out += f"\n    {entry['label']}: {self._render_hops(entry['hops'])}"
    elif self.chain:
      out += f'\n    via: {self._render_hops(self.chain)}'
    if self.hint:
      out += f'\n    hint: {self.hint}'
    return out


def sort_findings(findings):
  """Deterministic report order: path, then line/col, then rule id."""
  return sorted(findings,
                key=lambda f: (f.path, f.line, f.col, f.rule_id))

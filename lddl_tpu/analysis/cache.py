"""Incremental analysis cache (``LDDL_ANALYZE_CACHE``).

Analysis is a pure function of file content: the same source bytes
produce the same findings and the same per-module facts every time (the
whole suite is built around byte-identical output). That makes both
layers cacheable by content hash alone:

  - **findings** — per file, keyed by source + ruleset fingerprint, so a
    warm ``lddl-analyze`` run skips parsing and rule execution for every
    unchanged file;
  - **facts** — per module (:class:`~lddl_tpu.analysis.engine.ModuleFacts`
    pickles), so project mode's ``ProjectIndex.build`` skips re-parsing
    unchanged files even though the cross-file rules still run (linking
    is cheap; parsing is the 90%).

The cache is enabled by pointing ``LDDL_ANALYZE_CACHE`` at a directory
(created on demand) and bypassed by ``--no-cache``. Keys bake in the
schema constant below, the absolute *and* as-given path (findings embed
paths verbatim), and the ruleset fingerprint; custom (non-registry) rule
instances have no stable fingerprint and always bypass the cache.
``CACHE_SCHEMA`` must be bumped whenever rule logic or the facts layer
changes shape — content hashes cannot see code changes.

Entries are written atomically (tempfile + ``os.replace``) so concurrent
analyzer runs sharing one cache directory never read torn pickles; any
unreadable entry is treated as a miss.
"""

import hashlib
import os
import pickle
import tempfile

# Bump when rule logic, the facts dataclasses, or the finding model
# change: cached entries from older code are silently wrong otherwise.
CACHE_SCHEMA = 2


def cache_root(no_cache=False):
  """The cache directory from the environment, or '' when disabled."""
  if no_cache:
    return ''
  return os.environ.get('LDDL_ANALYZE_CACHE', '')


def cache_from_env(no_cache=False):
  """An :class:`AnalysisCache` per ``LDDL_ANALYZE_CACHE``, else None."""
  root = cache_root(no_cache=no_cache)
  if not root:
    return None
  try:
    return AnalysisCache(root)
  except OSError:
    return None  # unusable cache dir: run uncached rather than fail


class AnalysisCache:
  """Content-addressed pickle store for per-file findings and facts."""

  def __init__(self, root):
    self.root = root
    os.makedirs(root, exist_ok=True)

  def _key(self, kind, path, source, extra=''):
    h = hashlib.blake2b(digest_size=20)
    for part in (str(CACHE_SCHEMA), kind, os.path.abspath(path), path,
                 extra, source):
      h.update(part.encode('utf-8', 'replace'))
      h.update(b'\x00')
    return h.hexdigest()

  def _path_for(self, kind, path, source, extra=''):
    return os.path.join(self.root,
                        f'{self._key(kind, path, source, extra)}.pkl')

  def load(self, kind, path, source, extra=''):
    """The cached value, or None on any miss/corruption (a bad entry is
    a miss, never an error — the analyzer just recomputes)."""
    try:
      with open(self._path_for(kind, path, source, extra), 'rb') as fh:
        return pickle.load(fh)
    except (OSError, EOFError, pickle.UnpicklingError, AttributeError,
            ImportError, IndexError, ValueError):
      return None

  def store(self, kind, path, source, value, extra=''):
    """Atomically persist ``value``; storage failures are silent (the
    cache is an accelerator, not a dependency)."""
    target = self._path_for(kind, path, source, extra)
    try:
      fd, tmp = tempfile.mkstemp(dir=self.root, suffix='.tmp')
      try:
        with os.fdopen(fd, 'wb') as fh:
          pickle.dump(value, fh, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, target)
      except BaseException:
        try:
          os.unlink(tmp)
        except OSError:
          pass
        raise
    except (OSError, pickle.PicklingError):
      pass

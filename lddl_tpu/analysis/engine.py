"""AST visitor engine: one parse + one ancestor-tracking walk per file,
with every registered rule riding the same traversal.

The engine owns the cross-cutting machinery rules should not reimplement:

  - import-alias resolution, so ``import numpy as np; np.random.rand()``
    and ``from random import shuffle; shuffle(x)`` both resolve to their
    canonical dotted names (``numpy.random.rand`` / ``random.shuffle``) —
    including relative imports, which keep their leading dots so
    ``from ..core import random as lrandom`` can never be mistaken for
    the stdlib ``random`` module;
  - ancestor chains (``ctx.ancestors``), so rules can ask "is this call
    wrapped in ``sorted(...)``?" or "is this node under a ``with`` item?"
    without bookkeeping of their own;
  - pragma-based suppression and deterministic finding order.

Rules subclass :class:`Rule`: ``begin_module`` runs once per file (for
scope/taint pre-passes), ``on_node`` runs for every AST node.
"""

import ast
import os

from .findings import Finding, sort_findings
from .pragmas import is_suppressed, pragma_lines


class Rule:
  """Base class for one ``LDAxxx`` check."""

  rule_id = ''
  name = ''
  # One line: the pipeline invariant this rule protects (docs + --list-rules).
  invariant = ''
  hint = ''

  def exempt(self, ctx):
    """Whether this rule is off for ``ctx.path`` (e.g. LDA002 inside the
    seeded-RNG module itself). Default: applies everywhere."""
    return False

  def begin_module(self, ctx):
    """Per-file pre-pass; may yield findings."""
    return ()

  def on_node(self, node, ctx):
    """Per-node check; may yield findings."""
    return ()

  def finding(self, node, message, ctx, hint=None):
    return Finding(
        rule_id=self.rule_id,
        path=ctx.path,
        line=getattr(node, 'lineno', 1),
        col=getattr(node, 'col_offset', 0) + 1,
        message=message,
        hint=self.hint if hint is None else hint,
        end_line=getattr(node, 'end_lineno', 0) or 0,
    )


class ModuleContext:
  """Everything rules may want to know about the file being analyzed."""

  def __init__(self, tree, path, source):
    self.tree = tree
    self.path = path
    self.source = source
    # Normalized forward-slash path for rule exemption matching.
    self.norm_path = os.path.abspath(path).replace(os.sep, '/')
    self.aliases = _import_aliases(tree)
    self.ancestors = ()  # set by the walker before each on_node dispatch

  def path_is(self, *fragments):
    """Whether the file lives under any of the given path fragments
    (``'telemetry/'``, ``'core/random.py'``, ...)."""
    return any(f'/{frag}' in self.norm_path or
               self.norm_path.endswith(f'/{frag.rstrip("/")}')
               for frag in fragments)

  def basename(self):
    return os.path.basename(self.norm_path)

  def qualname(self, node):
    """Canonical dotted name of an attribute/name chain, resolved through
    this module's import aliases; None when the chain does not bottom out
    in a plain name (e.g. a call result: ``Path(p).glob(...)``)."""
    parts = []
    while isinstance(node, ast.Attribute):
      parts.append(node.attr)
      node = node.value
    if not isinstance(node, ast.Name):
      return None
    parts.append(self.aliases.get(node.id, node.id))
    return '.'.join(reversed(parts))

  def call_name(self, call):
    """(dotted, terminal) for a Call: the resolved dotted name (or None)
    and the last attribute/name segment (always available)."""
    dotted = self.qualname(call.func)
    if isinstance(call.func, ast.Attribute):
      return dotted, call.func.attr
    if isinstance(call.func, ast.Name):
      return dotted, call.func.id
    return dotted, ''

  def enclosing(self, *types):
    """Nearest ancestor of the given AST types (innermost first)."""
    for node in reversed(self.ancestors):
      if isinstance(node, types):
        return node
    return None


def _import_aliases(tree):
  """local name -> canonical dotted origin, from every import statement.

  ``import numpy as np`` -> ``np: numpy``; ``import a.b`` -> ``a: a``;
  ``from x.y import z as w`` -> ``w: x.y.z``; relative imports keep
  their dots (``from ..core import random`` -> ``random: ..core.random``)
  so they can never collide with an absolute stdlib name.
  """
  aliases = {}
  for node in ast.walk(tree):
    if isinstance(node, ast.Import):
      for a in node.names:
        if a.asname:
          aliases[a.asname] = a.name
        else:
          root = a.name.split('.')[0]
          aliases[root] = root
    elif isinstance(node, ast.ImportFrom):
      base = '.' * node.level + (node.module or '')
      for a in node.names:
        if a.name == '*':
          continue
        sep = '' if base.endswith('.') or not base else '.'
        aliases[a.asname or a.name] = f'{base}{sep}{a.name}'
  return aliases


def walk_with_ancestors(tree):
  """Yield ``(node, ancestors)`` for every node; ancestors are outermost
  first and exclude the node itself."""
  stack = [(tree, ())]
  while stack:
    node, anc = stack.pop()
    yield node, anc
    child_anc = anc + (node,)
    for child in ast.iter_child_nodes(node):
      stack.append((child, child_anc))


def analyze_source(source, path='<string>', rules=None):
  """Run ``rules`` over one module's source. Returns all findings (the
  pragma-suppressed ones flagged, not dropped), sorted by location.

  A file that does not parse yields a single ``LDA000`` finding — a
  syntactically broken module can't have its invariants checked, which
  is itself a finding, not a crash.
  """
  if rules is None:
    from .rules import default_rules
    rules = default_rules()
  try:
    tree = ast.parse(source, filename=path)
  except (SyntaxError, ValueError) as e:
    line = getattr(e, 'lineno', 1) or 1
    return [
        Finding(rule_id='LDA000', path=path, line=line, col=1,
                message=f'file does not parse: {e.msg or e}',
                hint='fix the syntax error so the file can be analyzed')
    ]
  ctx = ModuleContext(tree, path, source)
  findings = []
  applicable = [r for r in rules if not r.exempt(ctx)]
  for rule in applicable:
    findings.extend(rule.begin_module(ctx))
  node_rules = [r for r in applicable
                if type(r).on_node is not Rule.on_node]
  if node_rules:
    for node, ancestors in walk_with_ancestors(tree):
      ctx.ancestors = ancestors
      for rule in node_rules:
        findings.extend(rule.on_node(node, ctx))
  pragmas = pragma_lines(source)
  if pragmas:
    for f in findings:
      f.suppressed = is_suppressed(f, pragmas)
  return sort_findings(findings)


def analyze_file(path, rules=None):
  with open(path, encoding='utf-8') as f:
    source = f.read()
  return analyze_source(source, path=path, rules=rules)


def discover_py_files(paths):
  """Expand files/directories into a sorted, deduplicated ``.py`` list
  (sorted: the analyzer's own output order must be rank-stable too)."""
  out = []
  for p in paths:
    if os.path.isdir(p):
      # lddl: noqa[LDA001] the aggregate list is sorted(set(...)) below
      # before anything consumes it, so walk order cannot leak out.
      out.extend(
          os.path.join(r, f)
          for r, _, files in os.walk(p)
          for f in files
          if f.endswith('.py'))
    elif p.endswith('.py'):
      out.append(p)
  return sorted(set(out))


def analyze_paths(paths, rules=None):
  """Analyze every ``.py`` file under ``paths`` (files or directories).

  Returns ``(findings, files_scanned)``; findings include suppressed
  ones (callers filter on ``f.suppressed``).
  """
  files = discover_py_files(paths)
  findings = []
  for path in files:
    findings.extend(analyze_file(path, rules=rules))
  return findings, len(files)

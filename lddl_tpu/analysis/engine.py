"""AST visitor engine: one parse + one ancestor-tracking walk per file,
with every registered rule riding the same traversal.

The engine owns the cross-cutting machinery rules should not reimplement:

  - import-alias resolution, so ``import numpy as np; np.random.rand()``
    and ``from random import shuffle; shuffle(x)`` both resolve to their
    canonical dotted names (``numpy.random.rand`` / ``random.shuffle``) —
    including relative imports, which keep their leading dots so
    ``from ..core import random as lrandom`` can never be mistaken for
    the stdlib ``random`` module;
  - ancestor chains (``ctx.ancestors``), so rules can ask "is this call
    wrapped in ``sorted(...)``?" or "is this node under a ``with`` item?"
    without bookkeeping of their own;
  - pragma-based suppression and deterministic finding order.

Rules subclass :class:`Rule`: ``begin_module`` runs once per file (for
scope/taint pre-passes), ``on_node`` runs for every AST node.
"""

import ast
import concurrent.futures
import dataclasses
import multiprocessing
import os

from .findings import Finding, sort_findings
from .pragmas import is_suppressed, pragma_lines

# Identifiers that gate code on rank identity (shared with rules.LDA005
# and the interprocedural LDA008: both must agree on what "rank-
# conditional" means or findings would shift between modes).
RANK_IDENTS = frozenset({
    'process_index', 'process_id', 'is_primary', 'is_coordinator',
    'is_main_process',
})


def rank_mention(test):
  """First identifier in ``test`` that smells like a rank check, or
  None. Matches bare/attribute names containing ``rank`` and the
  conventional jax/launcher spellings in :data:`RANK_IDENTS`."""
  for node in ast.walk(test):
    ident = None
    if isinstance(node, ast.Name):
      ident = node.id
    elif isinstance(node, ast.Attribute):
      ident = node.attr
    if ident and ('rank' in ident.lower() or ident in RANK_IDENTS):
      return ident
  return None


class Rule:
  """Base class for one ``LDAxxx`` check."""

  rule_id = ''
  name = ''
  # One line: the pipeline invariant this rule protects (docs + --list-rules).
  invariant = ''
  hint = ''

  def exempt(self, ctx):
    """Whether this rule is off for ``ctx.path`` (e.g. LDA002 inside the
    seeded-RNG module itself). Default: applies everywhere."""
    return False

  def begin_module(self, ctx):
    """Per-file pre-pass; may yield findings."""
    return ()

  def on_node(self, node, ctx):
    """Per-node check; may yield findings."""
    return ()

  def finding(self, node, message, ctx, hint=None):
    return Finding(
        rule_id=self.rule_id,
        path=ctx.path,
        line=getattr(node, 'lineno', 1),
        col=getattr(node, 'col_offset', 0) + 1,
        message=message,
        hint=self.hint if hint is None else hint,
        end_line=getattr(node, 'end_lineno', 0) or 0,
    )


class ModuleContext:
  """Everything rules may want to know about the file being analyzed."""

  def __init__(self, tree, path, source):
    self.tree = tree
    self.path = path
    self.source = source
    # Normalized forward-slash path for rule exemption matching.
    self.norm_path = os.path.abspath(path).replace(os.sep, '/')
    self.aliases = _import_aliases(tree)
    self.aliases.update(_local_aliases(tree, self.aliases))
    self.ancestors = ()  # set by the walker before each on_node dispatch

  def path_is(self, *fragments):
    """Whether the file lives under any of the given path fragments
    (``'telemetry/'``, ``'core/random.py'``, ...)."""
    return any(f'/{frag}' in self.norm_path or
               self.norm_path.endswith(f'/{frag.rstrip("/")}')
               for frag in fragments)

  def basename(self):
    return os.path.basename(self.norm_path)

  def qualname(self, node):
    """Canonical dotted name of an attribute/name chain, resolved through
    this module's import aliases; None when the chain does not bottom out
    in a plain name (e.g. a call result: ``Path(p).glob(...)``)."""
    parts = []
    while isinstance(node, ast.Attribute):
      parts.append(node.attr)
      node = node.value
    if not isinstance(node, ast.Name):
      return None
    parts.append(self.aliases.get(node.id, node.id))
    return '.'.join(reversed(parts))

  def call_name(self, call):
    """(dotted, terminal) for a Call: the resolved dotted name (or None)
    and the last attribute/name segment (always available)."""
    dotted = self.qualname(call.func)
    if isinstance(call.func, ast.Attribute):
      return dotted, call.func.attr
    if isinstance(call.func, ast.Name):
      return dotted, call.func.id
    return dotted, ''

  def enclosing(self, *types):
    """Nearest ancestor of the given AST types (innermost first)."""
    for node in reversed(self.ancestors):
      if isinstance(node, types):
        return node
    return None


def _import_aliases(tree):
  """local name -> canonical dotted origin, from every import statement.

  ``import numpy as np`` -> ``np: numpy``; ``import a.b`` -> ``a: a``;
  ``from x.y import z as w`` -> ``w: x.y.z``; relative imports keep
  their dots (``from ..core import random`` -> ``random: ..core.random``)
  so they can never collide with an absolute stdlib name.
  """
  aliases = {}
  for node in ast.walk(tree):
    if isinstance(node, ast.Import):
      for a in node.names:
        if a.asname:
          aliases[a.asname] = a.name
        else:
          root = a.name.split('.')[0]
          aliases[root] = root
    elif isinstance(node, ast.ImportFrom):
      base = '.' * node.level + (node.module or '')
      for a in node.names:
        if a.name == '*':
          continue
        sep = '' if base.endswith('.') or not base else '.'
        aliases[a.asname or a.name] = f'{base}{sep}{a.name}'
  return aliases


def _qual_of(node, aliases):
  """Dotted name of a Name/Attribute chain resolved through ``aliases``,
  or None (standalone twin of :meth:`ModuleContext.qualname`)."""
  parts = []
  while isinstance(node, ast.Attribute):
    parts.append(node.attr)
    node = node.value
  if not isinstance(node, ast.Name):
    return None
  parts.append(aliases.get(node.id, node.id))
  return '.'.join(reversed(parts))


def _local_aliases(tree, import_aliases):
  """local name -> canonical dotted origin for simple rebindings.

  ``rng = random`` or ``jit = jax.jit`` makes every later use of the
  new name opaque to pure import-alias resolution — the known
  false-negative hole in LDA002/LDA005. A name qualifies only when it
  is bound exactly once in the whole module (any rebinding, loop
  target, or parameter shadow disqualifies it) and that one binding is
  a plain ``x = name.chain`` assignment, so the alias can never be
  stale. Alias-of-alias chains resolve via a short fixed point.
  """
  bind_counts = {}

  def bump(name):
    bind_counts[name] = bind_counts.get(name, 0) + 1

  for node in ast.walk(tree):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
      bump(node.name)
    elif isinstance(node, (ast.Import, ast.ImportFrom)):
      for a in node.names:
        bump((a.asname or a.name).split('.')[0])
    elif isinstance(node, ast.arg):
      bump(node.arg)
    elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                   (ast.Store, ast.Del)):
      bump(node.id)

  candidates = {}
  for node in ast.walk(tree):
    if (isinstance(node, ast.Assign) and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Name)
        and isinstance(node.value, (ast.Name, ast.Attribute))
        and bind_counts.get(node.targets[0].id, 0) == 1):
      candidates[node.targets[0].id] = node.value

  out = {}
  for _ in range(3):  # bounded fixed point for alias-of-alias chains
    changed = False
    merged = dict(import_aliases)
    merged.update(out)
    for name, value in sorted(candidates.items()):
      if name in out:
        continue
      dotted = _qual_of(value, merged)
      if dotted and dotted.split('.')[0] != name:
        out[name] = dotted
        changed = True
    if not changed:
      break
  return out


def walk_with_ancestors(tree):
  """Yield ``(node, ancestors)`` for every node; ancestors are outermost
  first and exclude the node itself."""
  stack = [(tree, ())]
  while stack:
    node, anc = stack.pop()
    yield node, anc
    child_anc = anc + (node,)
    for child in ast.iter_child_nodes(node):
      stack.append((child, child_anc))


# ---------------------------------------------------------------------------
# Per-module facts export (project mode).
#
# ``extract_module_facts`` distills one parsed module into the flat,
# picklable facts the whole-program layer needs: every definition with
# its resolved calls, lexical effects, decorators, and branch structure.
# The project index (analysis/project.py) links these across modules
# into a call graph; nothing here looks outside the file.
# ---------------------------------------------------------------------------

# Cross-rank collective operations (the repo's comm vocabulary plus the
# jax multihost spellings). Shared with rules.LDA005/LDA008/LDA009.
COLLECTIVES = frozenset({
    'allgather_object', 'allreduce_sum', 'broadcast_object', 'barrier',
    'allreduce', 'allgather', 'broadcast', 'reduce_scatter', 'all_to_all',
    'sync_global_devices', 'process_allgather',
})

# Dotted prefixes whose ``allgather``/``all_to_all``-style terminals are
# *device* collectives (legal inside jit/shard_map), not host-blocking
# cross-rank ones.
DEVICE_COLLECTIVE_PREFIXES = ('numpy.', 'jax.lax.', 'jax.numpy.')

# Wrappers whose function argument becomes traced/compiled code.
JIT_WRAPPERS = frozenset({'jit', 'shard_map', 'pallas_call',
                          'CompiledStepCache'})

# ``x.join()`` / ``x.wait()`` / ``x.get()`` / ``x.acquire()`` with *no*
# arguments: a wait with no timeout, unbounded by construction. The
# zero-arg requirement keeps ``os.path.join(a, b)``, ``sep.join(parts)``
# and ``q.get(timeout=...)`` out.
UNBOUNDED_WAIT_ATTRS = frozenset({'join', 'wait', 'acquire', 'get'})

# Method calls that mutate their receiver in place: ``self.buf.append(x)``
# is a *write* to the shared object behind ``self.buf``, not a read.
MUTATOR_METHODS = frozenset({
    'append', 'appendleft', 'extend', 'extendleft', 'insert', 'add',
    'update', 'pop', 'popleft', 'popitem', 'remove', 'discard', 'clear',
    'setdefault', 'sort', 'reverse', 'rotate',
})


@dataclasses.dataclass
class CallSite:
  """One call expression inside a definition."""
  dotted: str        # alias-resolved dotted name ('' when unresolvable)
  terminal: str      # last name segment (always available)
  receiver: str      # dotted chain of an attribute call's receiver, or ''
  line: int
  col: int
  nargs: int
  nkw: int
  arg0: str          # dotted name of first positional arg, or ''
  rank_cond: str     # gating rank identifier when under a rank branch
  locks: tuple = ()  # dotted `with` contexts lexically held at the call


@dataclasses.dataclass
class EffectSite:
  """One lexical effect (collective, host_sync, ...) at a location."""
  kind: str
  detail: str
  line: int
  col: int


@dataclasses.dataclass
class AccessSite:
  """One read or write of shared state inside a definition: a ``self.X``
  attribute (``scope='self'``) or a module global (``scope='global'``,
  recorded only in modules with a ``global`` statement naming it)."""
  attr: str          # attribute / global name
  kind: str          # 'read' | 'write'
  scope: str         # 'self' | 'global'
  line: int
  col: int
  locks: tuple = ()  # dotted `with` contexts lexically held at the access


@dataclasses.dataclass
class SpawnSite:
  """One ``Thread(target=...)`` / ``Process(target=...)`` construction."""
  ctor: str          # 'Thread' | 'Process'
  target: str        # dotted target name ('' for lambdas/opaque values)
  binding: str       # name the object binds to: 'self.X', a local, or ''
  daemon: object     # True/False when a literal daemon= kwarg, else None
  line: int
  col: int


@dataclasses.dataclass
class AcquireSite:
  """One ``with <lock-like name>:`` entry (no-call context expressions
  only — ``with open(...)`` is a resource, never a lock candidate)."""
  name: str          # dotted context name ('self._lock', 'window_lock')
  line: int
  col: int
  held: tuple = ()   # dotted contexts already held when this one enters


@dataclasses.dataclass
class BranchFacts:
  """One ``if`` statement and the call indices in each arm, in source
  order (indices into the owning DefFacts.calls)."""
  line: int
  body: list
  orelse: list


@dataclasses.dataclass
class DefFacts:
  """One function/method definition."""
  qualname: str      # dotted within the module ('Executor._map_elastic')
  line: int
  cls: str           # immediately enclosing class qualname, or ''
  decorators: tuple  # resolved dotted decorator names
  calls: list        # [CallSite]
  effects: list      # [EffectSite]
  var_ctors: dict    # local var -> dotted ctor name it was built from
  branches: list     # [BranchFacts]
  accesses: list = dataclasses.field(default_factory=list)  # [AccessSite]
  spawns: list = dataclasses.field(default_factory=list)    # [SpawnSite]
  acquires: list = dataclasses.field(default_factory=list)  # [AcquireSite]


@dataclasses.dataclass
class ClassFacts:
  qualname: str
  line: int
  bases: tuple       # resolved dotted base names
  attr_ctors: dict   # 'self.X = Ctor(...)' in any method -> {X: ctor}


@dataclasses.dataclass
class ModuleFacts:
  path: str
  defs: dict         # def qualname -> DefFacts
  classes: dict      # class qualname -> ClassFacts
  jit_roots: list    # [(arg0_dotted, scope_qualname, line)] from
                     # jit(f)/shard_map(f)/pallas_call(f)/CompiledStepCache(f)
  aliases: dict      # local name -> dotted origin (for re-export chasing)
  signal_handlers: list = dataclasses.field(default_factory=list)
                     # [(handler_dotted, scope_qualname, line)] from
                     # signal.signal(sig, handler) registrations


def _scope_chain(ancestors, node):
  """Enclosing def/class AST nodes of ``node``, outermost first,
  counting only scopes entered through their *body*: a node hanging off
  a def's decorator list or signature belongs to the outer scope —
  decorators evaluate at definition time, not inside the function."""
  chain = list(ancestors) + [node]
  scopes = []
  for i, anc in enumerate(chain[:-1]):
    if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.ClassDef)):
      if any(chain[i + 1] is stmt for stmt in anc.body):
        scopes.append(anc)
  return scopes


def _owner_def_qualname(scopes):
  """Qualname of the innermost enclosing *function* in ``scopes`` (its
  path may pass through classes), or '' for module/class-level code."""
  idx = None
  for i in range(len(scopes) - 1, -1, -1):
    if isinstance(scopes[i], (ast.FunctionDef, ast.AsyncFunctionDef)):
      idx = i
      break
  if idx is None:
    return ''
  return '.'.join(s.name for s in scopes[:idx + 1])


def _with_locks(chain, aliases):
  """Dotted ``with``-context names lexically held at the innermost node
  of ``chain`` (ancestors + node, outermost first): every ``with`` whose
  *body* the path passes through, inside the innermost enclosing
  function. Only plain Name/Attribute contexts count — ``with
  open(...)`` is a resource, not a lock candidate — and contexts from an
  enclosing def don't leak into nested defs (which run later, lock-free).
  """
  last_def = -1
  for i, anc in enumerate(chain[:-1]):
    if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) \
        and any(chain[i + 1] is stmt for stmt in anc.body):
      last_def = i
  held = []
  for i, anc in enumerate(chain[:-1]):
    if i <= last_def or not isinstance(anc, (ast.With, ast.AsyncWith)):
      continue
    if not any(chain[i + 1] is stmt for stmt in anc.body):
      continue
    for item in anc.items:
      dotted = _qual_of(item.context_expr, aliases)
      if dotted:
        held.append(dotted)
  return tuple(held)


def _access_kind(node, ancestors):
  """'read'/'write' for an attribute/name access node. A Store/Del
  context, a store through a subscript or sub-attribute
  (``self.X[k] = v``, ``self.X.y = v``), and an in-place mutator call
  (``self.X.append(v)``) are all writes to the shared object."""
  if isinstance(node.ctx, (ast.Store, ast.Del)):
    return 'write'
  parent = ancestors[-1] if ancestors else None
  if isinstance(parent, ast.Attribute) and parent.value is node:
    if isinstance(parent.ctx, (ast.Store, ast.Del)):
      return 'write'
    gp = ancestors[-2] if len(ancestors) >= 2 else None
    if (isinstance(gp, ast.Call) and gp.func is parent
        and parent.attr in MUTATOR_METHODS):
      return 'write'
  elif (isinstance(parent, ast.Subscript) and parent.value is node
        and isinstance(parent.ctx, (ast.Store, ast.Del))):
    return 'write'
  return 'read'


def _arm_of(if_node, child):
  """'body'/'orelse' when ``child`` (an immediate AST child of
  ``if_node``) sits in that arm, else None (e.g. inside the test)."""
  if any(child is stmt for stmt in if_node.body):
    return 'body'
  if any(child is stmt for stmt in if_node.orelse):
    return 'orelse'
  return None


def _decorator_names(node, aliases):
  """Resolved dotted names of a def's decorators. ``functools.partial(
  jax.jit, ...)`` resolves to its first argument — the wrapper that
  actually applies."""
  out = []
  for dec in node.decorator_list:
    if isinstance(dec, ast.Call):
      fn = _qual_of(dec.func, aliases) or ''
      if fn.rsplit('.', 1)[-1] == 'partial' and dec.args:
        inner = _qual_of(dec.args[0], aliases)
        if inner:
          out.append(inner)
          continue
      if fn:
        out.append(fn)
    else:
      fn = _qual_of(dec, aliases)
      if fn:
        out.append(fn)
  return tuple(out)


def _first_fn_arg(call, aliases):
  """Dotted name of the function a wrapper call wraps: the first
  positional arg, unwrapping one level of ``functools.partial``."""
  if not call.args:
    return ''
  a = call.args[0]
  if isinstance(a, ast.Call):
    fn = _qual_of(a.func, aliases) or ''
    if fn.rsplit('.', 1)[-1] == 'partial' and a.args:
      a = a.args[0]
    else:
      return ''
  return _qual_of(a, aliases) or ''


def _call_effects(call, dotted, terminal, receiver, aliases):
  """Lexical ``(kind, detail)`` effects of one call expression."""
  del aliases  # resolution already folded into ``dotted``
  d = dotted or ''
  nargs, nkw = len(call.args), len(call.keywords)
  effects = []
  # Attribute calls are collectives by method name; bare names only when
  # alias resolution proves the origin (mirrors rules.LDA005 — a local
  # function that happens to be named `barrier` is not one).
  if isinstance(call.func, ast.Attribute):
    coll = terminal if terminal in COLLECTIVES else ''
  else:
    coll = (d.rsplit('.', 1)[-1]
            if '.' in d and d.rsplit('.', 1)[-1] in COLLECTIVES else '')
  if coll and not d.startswith(DEVICE_COLLECTIVE_PREFIXES):
    effects.append(('collective', coll))
  if d.startswith('time.'):
    effects.append(('wall_clock', f'{d}()'))
  if terminal == 'item' and receiver and nargs == 0:
    effects.append(('host_sync', f'{receiver}.item()'))
  elif (d in ('float', 'bool') and nargs == 1
        and not isinstance(call.args[0], ast.Constant)):
    effects.append(('host_sync', f'{d}()'))
  elif d in ('numpy.asarray', 'jax.device_get'):
    effects.append(('host_sync', f'{d}()'))
  elif terminal == 'block_until_ready':
    effects.append(('host_sync', '.block_until_ready()'))
  if d == 'open' or d.startswith('subprocess.'):
    effects.append(('blocking_io', f'{d}()'))
  if (terminal in ('Thread', 'Process')
      and any(kw.arg == 'target' for kw in call.keywords)):
    effects.append(('thread_spawn', terminal))
  if (receiver and terminal in UNBOUNDED_WAIT_ATTRS
      and nargs == 0 and nkw == 0):
    effects.append(('unbounded_wait', f'{receiver}.{terminal}()'))
  return effects


def extract_module_facts(tree, path, aliases=None):
  """Distill one parsed module into :class:`ModuleFacts`.

  Calls/effects inside each definition are recorded in source order;
  module-level and class-level statements (which run at import time,
  uniformly on every rank) are not attributed to any definition.
  """
  if aliases is None:
    aliases = _import_aliases(tree)
    aliases.update(_local_aliases(tree, aliases))
  defs = {}
  classes = {}
  jit_roots = []
  signal_handlers = []
  # def qualname -> [(CallSite, [(if line, arm)])]; sorted per def at the end
  raw_calls = {}
  # def qualname -> {if line: If node}
  def_ifs = {}
  # Names declared ``global`` anywhere in the module: accesses to these
  # are shared state worth tracking. Collected up front because the main
  # walk's traversal order gives no ordering guarantee between a
  # ``global`` statement and the uses it governs.
  global_names = set()
  for n in ast.walk(tree):
    if isinstance(n, ast.Global):
      global_names.update(n.names)

  for node, ancestors in walk_with_ancestors(tree):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      scopes = _scope_chain(ancestors, node)
      qual = '.'.join([s.name for s in scopes] + [node.name])
      cls = ''
      if scopes and isinstance(scopes[-1], ast.ClassDef):
        cls = '.'.join(s.name for s in scopes)
      if qual not in defs:
        defs[qual] = DefFacts(
            qualname=qual, line=node.lineno, cls=cls,
            decorators=_decorator_names(node, aliases),
            calls=[], effects=[], var_ctors={}, branches=[])
      continue
    if isinstance(node, ast.ClassDef):
      scopes = _scope_chain(ancestors, node)
      qual = '.'.join([s.name for s in scopes] + [node.name])
      bases = tuple(b for b in (_qual_of(b, aliases) for b in node.bases)
                    if b)
      if qual not in classes:
        classes[qual] = ClassFacts(qualname=qual, line=node.lineno,
                                   bases=bases, attr_ctors={})
      continue

    scopes = _scope_chain(ancestors, node)
    owner = _owner_def_qualname(scopes)

    if isinstance(node, ast.Assign) and owner and owner in defs:
      value = node.value
      if isinstance(value, ast.IfExp):
        # `writer = Ctor() if flag else None`: either branch may type
        # the receiver; prefer the one that is a constructor call.
        value = (value.body if isinstance(value.body, ast.Call)
                 else value.orelse)
      if isinstance(value, ast.Call):
        ctor = _qual_of(value.func, aliases)
        if ctor and len(node.targets) == 1:
          tgt = node.targets[0]
          if isinstance(tgt, ast.Name):
            defs[owner].var_ctors.setdefault(tgt.id, ctor)
          elif (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == 'self' and defs[owner].cls in classes):
            classes[defs[owner].cls].attr_ctors.setdefault(tgt.attr, ctor)
      continue

    if isinstance(node, ast.If) and owner and owner in defs:
      def_ifs.setdefault(owner, {})[node.lineno] = node
      continue

    if isinstance(node, (ast.With, ast.AsyncWith)) and owner in defs:
      held = list(_with_locks(list(ancestors) + [node], aliases))
      for item in node.items:
        dotted = _qual_of(item.context_expr, aliases)
        if dotted:
          defs[owner].acquires.append(AcquireSite(
              name=dotted, line=node.lineno, col=node.col_offset + 1,
              held=tuple(held)))
          held.append(dotted)  # `with a, b:` — b enters with a held
      # fall through: nothing else to record on the With node itself

    if (isinstance(node, ast.Attribute) and owner in defs
        and isinstance(node.value, ast.Name) and node.value.id == 'self'):
      parent = ancestors[-1] if ancestors else None
      # `self.method()` is a call (a CallSite), not a state access.
      if not (isinstance(parent, ast.Call) and parent.func is node):
        defs[owner].accesses.append(AccessSite(
            attr=node.attr, kind=_access_kind(node, ancestors),
            scope='self', line=node.lineno, col=node.col_offset + 1,
            locks=_with_locks(list(ancestors) + [node], aliases)))
      continue

    if (global_names and isinstance(node, ast.Name)
        and node.id in global_names and owner in defs):
      parent = ancestors[-1] if ancestors else None
      if not (isinstance(parent, ast.Call) and parent.func is node):
        defs[owner].accesses.append(AccessSite(
            attr=node.id, kind=_access_kind(node, ancestors),
            scope='global', line=node.lineno, col=node.col_offset + 1,
            locks=_with_locks(list(ancestors) + [node], aliases)))
      continue

    if not isinstance(node, ast.Call):
      continue

    dotted = _qual_of(node.func, aliases) or ''
    if isinstance(node.func, ast.Attribute):
      terminal = node.func.attr
      receiver = _qual_of(node.func.value, aliases) or ''
    elif isinstance(node.func, ast.Name):
      terminal = node.func.id
      receiver = ''
    else:
      terminal, receiver = '', ''

    if terminal in JIT_WRAPPERS:
      arg0_fn = _first_fn_arg(node, aliases)
      if arg0_fn:
        jit_roots.append((arg0_fn, owner, node.lineno))

    if dotted == 'signal.signal' and len(node.args) >= 2:
      handler = _qual_of(node.args[1], aliases) or ''
      if handler:  # lambdas/opaque handlers can't be followed
        signal_handlers.append((handler, owner, node.lineno))

    if not owner or owner not in defs:
      continue

    if (terminal in ('Thread', 'Process')
        and any(kw.arg == 'target' for kw in node.keywords)):
      target, daemon = '', None
      for kw in node.keywords:
        if kw.arg == 'target':
          target = _qual_of(kw.value, aliases) or ''
        elif kw.arg == 'daemon' and isinstance(kw.value, ast.Constant):
          daemon = bool(kw.value.value)
      binding = ''
      parent = ancestors[-1] if ancestors else None
      if (isinstance(parent, ast.Assign) and parent.value is node
          and len(parent.targets) == 1):
        tgt_node = parent.targets[0]
        if isinstance(tgt_node, ast.Name):
          binding = tgt_node.id
        elif (isinstance(tgt_node, ast.Attribute)
              and isinstance(tgt_node.value, ast.Name)
              and tgt_node.value.id == 'self'):
          binding = f'self.{tgt_node.attr}'
      defs[owner].spawns.append(SpawnSite(
          ctor=terminal, target=target, binding=binding, daemon=daemon,
          line=node.lineno, col=node.col_offset + 1))

    # Innermost owning def node: If-ancestors beyond it gate this call.
    owner_node = None
    for s in reversed(scopes):
      if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
        owner_node = s
        break
    arms = []
    rank_cond = ''
    past_owner = False
    chain = list(ancestors) + [node]
    for i, anc in enumerate(chain[:-1]):
      if anc is owner_node:
        past_owner = True
        continue
      if not past_owner or not isinstance(anc, ast.If):
        continue
      arm = _arm_of(anc, chain[i + 1])
      if arm is None:
        continue
      arms.append((anc.lineno, arm))
      if not rank_cond:
        ident = rank_mention(anc.test)
        if ident:
          rank_cond = ident

    arg0 = ''
    if node.args and isinstance(node.args[0], (ast.Name, ast.Attribute)):
      arg0 = _qual_of(node.args[0], aliases) or ''
    site = CallSite(
        dotted=dotted, terminal=terminal, receiver=receiver,
        line=node.lineno, col=node.col_offset + 1,
        nargs=len(node.args), nkw=len(node.keywords), arg0=arg0,
        rank_cond=rank_cond, locks=_with_locks(chain, aliases))
    raw_calls.setdefault(owner, []).append((site, arms))
    for kind, detail in _call_effects(node, dotted, terminal, receiver,
                                      aliases):
      defs[owner].effects.append(
          EffectSite(kind=kind, detail=detail, line=node.lineno,
                     col=node.col_offset + 1))

  for owner, entries in raw_calls.items():
    entries.sort(key=lambda e: (e[0].line, e[0].col))
    facts = defs[owner]
    facts.calls = [site for site, _ in entries]
    arm_map = {}  # if line -> {'body': [...], 'orelse': [...]}
    for idx, (_, arms) in enumerate(entries):
      for if_line, arm in arms:
        arm_map.setdefault(if_line, {'body': [], 'orelse': []})
        arm_map[if_line][arm].append(idx)
    for if_line in sorted(def_ifs.get(owner, {})):
      arms = arm_map.get(if_line, {'body': [], 'orelse': []})
      facts.branches.append(
          BranchFacts(line=if_line, body=arms['body'],
                      orelse=arms['orelse']))
  for facts in defs.values():
    facts.effects.sort(key=lambda e: (e.line, e.col, e.kind))
    facts.accesses.sort(key=lambda a: (a.line, a.col, a.attr, a.kind))
    facts.spawns.sort(key=lambda s: (s.line, s.col))
    facts.acquires.sort(key=lambda a: (a.line, a.col, a.name))
  jit_roots.sort(key=lambda r: (r[2], r[0]))
  signal_handlers.sort(key=lambda r: (r[2], r[0]))
  return ModuleFacts(path=path, defs=defs, classes=classes,
                     jit_roots=jit_roots, aliases=dict(aliases),
                     signal_handlers=signal_handlers)


def analyze_source(source, path='<string>', rules=None):
  """Run ``rules`` over one module's source. Returns all findings (the
  pragma-suppressed ones flagged, not dropped), sorted by location.

  A file that does not parse yields a single ``LDA000`` finding — a
  syntactically broken module can't have its invariants checked, which
  is itself a finding, not a crash.
  """
  if rules is None:
    from .rules import default_rules
    rules = default_rules()
  try:
    tree = ast.parse(source, filename=path)
  except (SyntaxError, ValueError) as e:
    line = getattr(e, 'lineno', 1) or 1
    return [
        Finding(rule_id='LDA000', path=path, line=line, col=1,
                message=f'file does not parse: {e.msg or e}',
                hint='fix the syntax error so the file can be analyzed')
    ]
  ctx = ModuleContext(tree, path, source)
  findings = []
  applicable = [r for r in rules if not r.exempt(ctx)]
  for rule in applicable:
    findings.extend(rule.begin_module(ctx))
  node_rules = [r for r in applicable
                if type(r).on_node is not Rule.on_node]
  if node_rules:
    for node, ancestors in walk_with_ancestors(tree):
      ctx.ancestors = ancestors
      for rule in node_rules:
        findings.extend(rule.on_node(node, ctx))
  pragmas = pragma_lines(source)
  if pragmas:
    for f in findings:
      f.suppressed = is_suppressed(f, pragmas)
  return sort_findings(findings)


def analyze_file(path, rules=None):
  with open(path, encoding='utf-8') as f:
    source = f.read()
  return analyze_source(source, path=path, rules=rules)


def discover_py_files(paths):
  """Expand files/directories into a sorted, deduplicated ``.py`` list
  (sorted: the analyzer's own output order must be rank-stable too)."""
  out = []
  for p in paths:
    if os.path.isdir(p):
      # lddl: noqa[LDA001] the aggregate list is sorted(set(...)) below
      # before anything consumes it, so walk order cannot leak out.
      out.extend(
          os.path.join(r, f)
          for r, _, files in os.walk(p)
          for f in files
          if f.endswith('.py'))
    elif p.endswith('.py'):
      out.append(p)
  return sorted(set(out))


# Below this many files the pool's spawn cost beats the win.
_PARALLEL_MIN_FILES = 8


def _analyze_file_worker(path, rule_ids=None):
  """Top-level (picklable) per-file worker: rules travel as ids and are
  re-instantiated from the registry inside the worker process."""
  rules = None
  if rule_ids is not None:
    from .rules import rules_by_id
    by_id = rules_by_id()
    rules = [by_id[rid] for rid in rule_ids]
  return analyze_file(path, rules=rules)


def _serializable_rule_ids(rules):
  """Rule ids when ``rules`` are stock registry instances (safe to
  rebuild in a worker), else None — custom rule objects force the
  serial path rather than silently analyzing with a lookalike."""
  if rules is None:
    return None
  from .rules import rules_by_id
  by_id = rules_by_id()
  ids = []
  for r in rules:
    stock = by_id.get(r.rule_id)
    if stock is None or type(stock) is not type(r):
      return ()
    ids.append(r.rule_id)
  return ids


def resolve_jobs(jobs=None):
  """Worker count: explicit arg, else ``LDDL_ANALYZE_JOBS``, else CPU
  count."""
  if jobs is None:
    try:
      jobs = int(os.environ.get('LDDL_ANALYZE_JOBS', '0'))
    except ValueError:
      jobs = 0
  return jobs if jobs and jobs > 0 else (os.cpu_count() or 1)


def _cache_fingerprint(rule_ids):
  """Stable ruleset fingerprint for findings-cache keys, or None when
  the rules are custom instances (no stable identity → no caching)."""
  if rule_ids == ():
    return None
  if rule_ids is None:
    from .rules import default_rules
    rule_ids = [r.rule_id for r in default_rules()]
  return ','.join(sorted(rule_ids))


def analyze_paths(paths, rules=None, jobs=None, cache=None):
  """Analyze every ``.py`` file under ``paths`` (files or directories).

  Returns ``(findings, files_scanned)``; findings include suppressed
  ones (callers filter on ``f.suppressed``).

  Files fan out across a process pool when ``jobs`` (or
  ``LDDL_ANALYZE_JOBS``, or the CPU count) exceeds 1. Results are
  collected in the same sorted file order the serial loop uses and each
  file's findings are internally sorted, so the output is byte-identical
  to the serial run at any worker count. Custom (non-registry) rule
  instances can't travel to workers and fall back to the serial loop.

  With a ``cache`` (:class:`~lddl_tpu.analysis.cache.AnalysisCache`),
  unchanged files load their findings by content hash and only the
  misses are analyzed; suppression state travels with the cached
  findings (pragmas live in the hashed source), so warm output is
  byte-identical to cold.
  """
  files = discover_py_files(paths)
  jobs = resolve_jobs(jobs)
  rule_ids = _serializable_rule_ids(rules)
  fingerprint = _cache_fingerprint(rule_ids) if cache is not None else None
  per_file = {}
  pending = list(files)
  sources = {}
  if fingerprint is not None:
    pending = []
    for path in files:
      try:
        with open(path, encoding='utf-8') as fh:
          sources[path] = fh.read()
      except OSError:
        pending.append(path)  # unreadable now: let analyze_file report
        continue
      hit = cache.load('findings', path, sources[path],
                       extra=fingerprint)
      if hit is None:
        pending.append(path)
      else:
        per_file[path] = hit
  analyzed = None
  parallel_ok = (jobs > 1 and len(pending) >= _PARALLEL_MIN_FILES
                 and rule_ids != ())
  if parallel_ok:
    try:
      ctx = multiprocessing.get_context('fork')
    except ValueError:
      ctx = multiprocessing.get_context()
    try:
      with concurrent.futures.ProcessPoolExecutor(
          max_workers=min(jobs, len(pending)), mp_context=ctx) as pool:
        analyzed = list(
            pool.map(_analyze_file_worker, pending,
                     [rule_ids] * len(pending),
                     chunksize=max(1, len(pending) // (jobs * 4))))
    except (OSError, ValueError, concurrent.futures.process
            .BrokenProcessPool):
      analyzed = None  # restricted environments: serial fallback below
  if analyzed is None:
    analyzed = [analyze_file(path, rules=rules) for path in pending]
  for path, batch in zip(pending, analyzed):
    per_file[path] = batch
    if fingerprint is not None and path in sources:
      cache.store('findings', path, sources[path], batch,
                  extra=fingerprint)
  findings = [f for path in files for f in per_file.get(path, ())]
  return findings, len(files)

"""``lddl-analyze``: the SPMD determinism & resource-safety linter.

Usage::

  lddl-analyze [paths...]              # default: lddl_tpu/ if it exists
  lddl-analyze --json lddl_tpu/        # machine-readable findings
  lddl-analyze --rule LDA001,LDA004 .  # subset of rules
  lddl-analyze --changed               # only files changed vs HEAD
  lddl-analyze --changed --diff-base main~3
  lddl-analyze --list-rules

Exit status: 0 when every finding is pragma-suppressed (or none exist),
1 when unsuppressed findings remain, 2 on usage errors. The tier-1
self-check (``tests/test_analysis_self.py``) asserts exit-0 over
``lddl_tpu/`` itself, making the linter a standing gate for every PR.
"""

import argparse
import json
import os
import subprocess
import sys

from .engine import analyze_file, discover_py_files
from .rules import default_rules, rules_by_id

JSON_SCHEMA_VERSION = 1


def _git_changed_files(diff_base):
  """Absolute paths of files changed vs ``diff_base`` plus untracked
  files, per git; raises on any git failure (a broken filter silently
  scanning nothing would report a falsely clean tree)."""
  top = subprocess.run(
      ['git', 'rev-parse', '--show-toplevel'],
      capture_output=True, text=True, check=True).stdout.strip()
  changed = subprocess.run(
      ['git', 'diff', '--name-only', '-z', diff_base, '--'],
      capture_output=True, text=True, check=True, cwd=top).stdout
  untracked = subprocess.run(
      ['git', 'ls-files', '--others', '--exclude-standard', '-z'],
      capture_output=True, text=True, check=True, cwd=top).stdout
  names = [n for n in (changed + untracked).split('\0') if n]
  return {os.path.abspath(os.path.join(top, n)) for n in names}


def build_parser():
  parser = argparse.ArgumentParser(
      prog='lddl-analyze',
      description='SPMD determinism & resource-safety linter for the '
      'lddl_tpu pipeline')
  parser.add_argument('paths', nargs='*',
                      help='files or directories to analyze '
                      '(default: ./lddl_tpu when present, else .)')
  parser.add_argument('--json', action='store_true', dest='as_json',
                      help='emit one JSON object instead of text')
  parser.add_argument('--rule', default=None,
                      help='comma-separated rule ids to run '
                      '(e.g. LDA001,LDA004); default: all')
  parser.add_argument('--changed', action='store_true',
                      help='only analyze files git reports as changed '
                      'or untracked (fast local runs)')
  parser.add_argument('--diff-base', default='HEAD',
                      help='git ref --changed diffs against '
                      '(default: HEAD)')
  parser.add_argument('--show-suppressed', action='store_true',
                      help='also print pragma-suppressed findings in '
                      'text mode')
  parser.add_argument('--list-rules', action='store_true',
                      help='print the rule table and exit')
  return parser


def _select_rules(spec):
  if not spec:
    return default_rules(), None
  by_id = rules_by_id()
  wanted = [r.strip().upper() for r in spec.split(',') if r.strip()]
  unknown = [r for r in wanted if r not in by_id]
  if unknown:
    return None, f'unknown rule id(s): {", ".join(unknown)} ' \
                 f'(known: {", ".join(sorted(by_id))})'
  return [by_id[r] for r in wanted], None


def main(args=None):
  opts = build_parser().parse_args(args)
  if opts.list_rules:
    for rule in default_rules():
      print(f'{rule.rule_id}  {rule.name}')
      print(f'    protects: {rule.invariant}')
      print(f'    fix: {rule.hint}')
    return 0

  rules, err = _select_rules(opts.rule)
  if err:
    print(f'lddl-analyze: {err}', file=sys.stderr)
    return 2

  paths = opts.paths
  if not paths:
    paths = ['lddl_tpu'] if os.path.isdir('lddl_tpu') else ['.']
  missing = [p for p in paths if not os.path.exists(p)]
  if missing:
    print(f'lddl-analyze: no such path: {", ".join(missing)}',
          file=sys.stderr)
    return 2

  file_filter = None
  if opts.changed:
    try:
      file_filter = _git_changed_files(opts.diff_base)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
      print(f'lddl-analyze: --changed requires a git checkout ({e})',
            file=sys.stderr)
      return 2

  files = discover_py_files(paths)
  if file_filter is not None:
    files = [f for f in files if os.path.abspath(f) in file_filter]
  findings = []
  for f in files:
    findings.extend(analyze_file(f, rules=rules))

  unsuppressed = [f for f in findings if not f.suppressed]
  suppressed = [f for f in findings if f.suppressed]

  if opts.as_json:
    print(json.dumps({
        'version': JSON_SCHEMA_VERSION,
        'files_scanned': len(files),
        'findings': [f.as_dict() for f in findings],
        'num_findings': len(unsuppressed),
        'num_suppressed': len(suppressed),
        'clean': not unsuppressed,
    }))
    return 0 if not unsuppressed else 1

  shown = findings if opts.show_suppressed else unsuppressed
  for f in shown:
    print(f.render())
  state = 'clean' if not unsuppressed else 'DIRTY'
  print(f'lddl-analyze: {len(files)} files, '
        f'{len(unsuppressed)} finding(s), '
        f'{len(suppressed)} suppressed — {state}')
  return 0 if not unsuppressed else 1


if __name__ == '__main__':
  sys.exit(main())

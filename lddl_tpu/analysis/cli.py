"""``lddl-analyze``: the SPMD determinism & resource-safety linter.

Usage::

  lddl-analyze [paths...]              # default: lddl_tpu/ if it exists
  lddl-analyze --format json lddl_tpu/ # machine-readable findings
  lddl-analyze --format sarif .        # SARIF 2.1.0 for CI annotations
  lddl-analyze --rule LDA001,LDA009 .  # subset of rules
  lddl-analyze --no-project pkg/       # per-file rules only
  lddl-analyze --jobs 8 .              # worker count for the file pass
  lddl-analyze --changed               # only files changed vs HEAD
  lddl-analyze --changed --diff-base main~3
  lddl-analyze --list-rules

Directory targets analyze in **project mode** by default: on top of the
per-file rules, the whole-program pass builds a cross-module call graph
and runs the interprocedural rules (LDA008–LDA011), attaching a
``via: a() → b() → allgather at path:L`` call-chain trace to each
finding. ``--no-project`` restricts to the per-file rules;
``--project`` forces the whole-program pass even for file targets.
``--changed`` implies ``--no-project`` unless ``--project`` is given
(a partial file list can't support whole-program claims); with both,
the per-file pass runs only over the changed files while the call
graph is still built over the full tree, and a project finding is
reported when the changed set touches *any* hop of its call chains —
a small diff re-runs exactly the interprocedural claims it can affect.

Setting ``LDDL_ANALYZE_CACHE`` to a directory enables the incremental
cache: per-file findings and per-module facts are keyed by content
hash, so a warm run over an unchanged tree skips parsing entirely and
produces byte-identical output. ``--no-cache`` bypasses it.

Exit status: 0 when every finding is pragma-suppressed (or none exist),
1 when unsuppressed findings remain, 2 on usage errors. The tier-1
self-check (``tests/test_analysis_self.py``) asserts exit-0 over
``lddl_tpu/`` itself — in project mode — making the analyzer a standing
gate for every PR.
"""

import argparse
import json
import os
import subprocess
import sys

from .cache import cache_from_env
from .engine import Rule, analyze_paths, discover_py_files
from .project import ProjectRule, analyze_project
from .rules import all_rules, rules_by_id
from .sarif import to_sarif

JSON_SCHEMA_VERSION = 3


def _git_changed_files(diff_base):
  """Absolute paths of files changed vs ``diff_base`` plus untracked
  files, per git; raises on any git failure (a broken filter silently
  scanning nothing would report a falsely clean tree)."""
  top = subprocess.run(
      ['git', 'rev-parse', '--show-toplevel'],
      capture_output=True, text=True, check=True).stdout.strip()
  changed = subprocess.run(
      ['git', 'diff', '--name-only', '-z', diff_base, '--'],
      capture_output=True, text=True, check=True, cwd=top).stdout
  untracked = subprocess.run(
      ['git', 'ls-files', '--others', '--exclude-standard', '-z'],
      capture_output=True, text=True, check=True, cwd=top).stdout
  names = [n for n in (changed + untracked).split('\0') if n]
  return {os.path.abspath(os.path.join(top, n)) for n in names}


def build_parser():
  parser = argparse.ArgumentParser(
      prog='lddl-analyze',
      description='SPMD determinism & resource-safety linter for the '
      'lddl_tpu pipeline')
  parser.add_argument('paths', nargs='*',
                      help='files or directories to analyze '
                      '(default: ./lddl_tpu when present, else .)')
  parser.add_argument('--format', default=None, dest='fmt',
                      choices=('text', 'json', 'sarif'),
                      help='output format (default: text)')
  parser.add_argument('--json', action='store_true', dest='as_json',
                      help='shorthand for --format json')
  parser.add_argument('--project', action='store_true', default=None,
                      help='force the whole-program (call-graph) pass; '
                      'default: on for directory targets')
  parser.add_argument('--no-project', action='store_false',
                      dest='project',
                      help='per-file rules only')
  parser.add_argument('--jobs', type=int, default=None,
                      help='worker processes for the per-file pass '
                      '(default: $LDDL_ANALYZE_JOBS or CPU count)')
  parser.add_argument('--rule', default=None,
                      help='comma-separated rule ids to run '
                      '(e.g. LDA001,LDA009); default: all')
  parser.add_argument('--changed', action='store_true',
                      help='only analyze files git reports as changed '
                      'or untracked (fast local runs)')
  parser.add_argument('--diff-base', default='HEAD',
                      help='git ref --changed diffs against '
                      '(default: HEAD)')
  parser.add_argument('--no-cache', action='store_true',
                      help='ignore LDDL_ANALYZE_CACHE and recompute '
                      'everything')
  parser.add_argument('--show-suppressed', action='store_true',
                      help='also print pragma-suppressed findings in '
                      'text mode')
  parser.add_argument('--list-rules', action='store_true',
                      help='print the rule table and exit')
  return parser


def _touches(finding, file_filter):
  """Whether a finding concerns any file in the ``--changed`` set: its
  anchor file, or any hop of any of its call chains (a changed callee
  re-surfaces the project findings that flow through it)."""
  if os.path.abspath(finding.path) in file_filter:
    return True
  chains = finding.chains or (
      [{'hops': finding.chain}] if finding.chain else [])
  return any(os.path.abspath(hop['path']) in file_filter
             for entry in chains for hop in entry['hops'])


def _select_rules(spec):
  """Rule instances for a ``--rule`` spec (None = all), or an error."""
  if not spec:
    return None, None
  by_id = rules_by_id()
  wanted = [r.strip().upper() for r in spec.split(',') if r.strip()]
  unknown = [r for r in wanted if r not in by_id]
  if unknown:
    return None, f'unknown rule id(s): {", ".join(unknown)} ' \
                 f'(known: {", ".join(sorted(by_id))})'
  return [by_id[r] for r in wanted], None


def main(args=None):
  opts = build_parser().parse_args(args)
  if opts.list_rules:
    for rule in all_rules():
      scope = ('project' if isinstance(rule, ProjectRule) else 'file')
      print(f'{rule.rule_id}  {rule.name}  [{scope}]')
      print(f'    protects: {rule.invariant}')
      print(f'    fix: {rule.hint}')
    return 0

  fmt = opts.fmt or ('json' if opts.as_json else 'text')
  rules, err = _select_rules(opts.rule)
  if err:
    print(f'lddl-analyze: {err}', file=sys.stderr)
    return 2

  paths = opts.paths
  if not paths:
    paths = ['lddl_tpu'] if os.path.isdir('lddl_tpu') else ['.']
  missing = [p for p in paths if not os.path.exists(p)]
  if missing:
    print(f'lddl-analyze: no such path: {", ".join(missing)}',
          file=sys.stderr)
    return 2

  file_filter = None
  if opts.changed:
    try:
      file_filter = _git_changed_files(opts.diff_base)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
      print(f'lddl-analyze: --changed requires a git checkout ({e})',
            file=sys.stderr)
      return 2

  project_mode = opts.project
  if project_mode is None:
    selected_project_rule = bool(rules) and any(
        isinstance(r, ProjectRule) for r in rules)
    project_mode = (not opts.changed and
                    (any(os.path.isdir(p) for p in paths)
                     or selected_project_rule))

  cache = cache_from_env(no_cache=opts.no_cache)
  if project_mode:
    findings, files_scanned = analyze_project(
        paths, rules=rules, jobs=opts.jobs, file_filter=file_filter,
        cache=cache)
    if file_filter is not None:
      findings = [f for f in findings if _touches(f, file_filter)]
  else:
    file_rules = (None if rules is None
                  else [r for r in rules if isinstance(r, Rule)])
    if file_filter is not None:
      files = [f for f in discover_py_files(paths)
               if os.path.abspath(f) in file_filter]
      findings, files_scanned = analyze_paths(files, rules=file_rules,
                                              jobs=opts.jobs,
                                              cache=cache)
    else:
      findings, files_scanned = analyze_paths(paths, rules=file_rules,
                                              jobs=opts.jobs,
                                              cache=cache)

  unsuppressed = [f for f in findings if not f.suppressed]
  suppressed = [f for f in findings if f.suppressed]
  exit_code = 0 if not unsuppressed else 1

  if fmt == 'json':
    print(json.dumps({
        'version': JSON_SCHEMA_VERSION,
        'mode': 'project' if project_mode else 'files',
        'files_scanned': files_scanned,
        'findings': [f.as_dict() for f in findings],
        'num_findings': len(unsuppressed),
        'num_suppressed': len(suppressed),
        'clean': not unsuppressed,
    }))
    return exit_code
  if fmt == 'sarif':
    print(json.dumps(to_sarif(findings, all_rules())))
    return exit_code

  shown = findings if opts.show_suppressed else unsuppressed
  for f in shown:
    print(f.render())
  state = 'clean' if not unsuppressed else 'DIRTY'
  mode = 'project' if project_mode else 'files'
  print(f'lddl-analyze: {files_scanned} files ({mode} mode), '
        f'{len(unsuppressed)} finding(s), '
        f'{len(suppressed)} suppressed — {state}')
  return exit_code


if __name__ == '__main__':
  sys.exit(main())

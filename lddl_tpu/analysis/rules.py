"""The initial ruleset: the SPMD-determinism and resource-safety
invariants this pipeline actually depends on.

Every rank must derive the identical sample plan without communication
(LDA001/LDA002/LDA003), collectives must be issued uniformly by all ranks
(LDA005), and a killed worker must never leak file handles or shared
memory (LDA004). Each rule documents the invariant it protects in
``invariant`` — that text is what ``--list-rules`` and the README table
show.
"""

import ast

from .engine import (COLLECTIVES, DEVICE_COLLECTIVE_PREFIXES, Rule,
                     rank_mention)
from .callgraph import is_lexical_collective
from .project import ProjectRule, build_chain

# ---------------------------------------------------------------------------
# LDA001: unsorted filesystem iteration


_FS_OS = frozenset({'os.listdir', 'os.scandir', 'os.walk'})
_FS_GLOB = frozenset({'glob.glob', 'glob.iglob'})
_FS_PATH_METHODS = frozenset({'iterdir', 'rglob'})


class UnsortedFsIteration(Rule):
  rule_id = 'LDA001'
  name = 'unsorted-fs-iteration'
  invariant = ('every rank derives the identical file plan: directory '
               'listing order is filesystem-dependent, so unsorted '
               'iteration can diverge across hosts')
  hint = 'wrap the call in sorted(...) before consuming its order'

  def on_node(self, node, ctx):
    if not isinstance(node, ast.Call):
      return
    dotted, term = ctx.call_name(node)
    hazard = None
    if dotted in _FS_OS or dotted in _FS_GLOB:
      hazard = dotted
    elif term in _FS_PATH_METHODS and isinstance(node.func, ast.Attribute):
      hazard = f'.{term}'
    elif (term == 'glob' and isinstance(node.func, ast.Attribute) and
          dotted not in _FS_GLOB and
          (dotted is None or not dotted.startswith('glob.'))):
      # Path(...).glob(...) / some_path.glob(...): same order hazard.
      hazard = '.glob'
    if hazard is None:
      return
    for anc in ctx.ancestors:
      if (isinstance(anc, ast.Call) and
          isinstance(anc.func, ast.Name) and anc.func.id == 'sorted'):
        return
    yield self.finding(
        node,
        f'{hazard}() consumed without sorted(): filesystem iteration '
        'order is not deterministic across hosts, so ranks can derive '
        'divergent plans', ctx)


# ---------------------------------------------------------------------------
# LDA002: process-global / unseeded RNG


_NP_BIT_GENERATORS = frozenset({
    'Generator', 'Philox', 'PCG64', 'PCG64DXSM', 'MT19937', 'SFC64',
    'SeedSequence', 'BitGenerator',
})
_NP_SEED_REQUIRED = frozenset({'default_rng', 'RandomState'})


class GlobalStateRng(Rule):
  rule_id = 'LDA002'
  name = 'global-state-rng'
  invariant = ('all randomness flows through seeded Philox / '
               'core.random helpers: global-state RNG draws depend on '
               'call order and imports, not on the run seed')
  hint = ('use lddl_tpu.core.random helpers or a seeded '
          'np.random.Generator(Philox(...)) / random.Random(seed)')

  def exempt(self, ctx):
    # The seeded-RNG module itself wraps the global state (under a state
    # swap), and test/benchmark scaffolding may use ad-hoc randomness.
    if ctx.path_is('core/random.py', 'tests/'):
      return True
    base = ctx.basename()
    return (base.startswith('test_') or
            base in ('conftest.py', 'testing.py'))

  def on_node(self, node, ctx):
    if not isinstance(node, ast.Call):
      return
    dotted, _ = ctx.call_name(node)
    if not dotted:
      return
    seeded = bool(node.args or node.keywords)
    if dotted.split('.')[0] == 'random' and dotted.count('.') == 1:
      fn = dotted.split('.')[1]
      if fn == 'Random':
        if not seeded:
          yield self.finding(
              node, 'random.Random() without a seed falls back to OS '
              'entropy: draws differ per rank and per run', ctx)
        return
      if fn == 'SystemRandom':
        yield self.finding(
            node, 'random.SystemRandom draws OS entropy: '
            'non-reproducible by design', ctx)
        return
      yield self.finding(
          node, f'random.{fn}() uses the process-global RNG: draws '
          'depend on import/call order, not on the run seed', ctx)
      return
    if dotted.startswith('numpy.random.'):
      fn = dotted[len('numpy.random.'):].split('.')[0]
      if fn in _NP_BIT_GENERATORS:
        return
      if fn in _NP_SEED_REQUIRED:
        if not seeded:
          yield self.finding(
              node, f'np.random.{fn}() without a seed draws OS entropy: '
              'every rank gets a different stream', ctx)
        return
      yield self.finding(
          node, f'np.random.{fn}() uses numpy\'s process-global RNG: '
          'draws depend on call order, not on the run seed', ctx)


# ---------------------------------------------------------------------------
# LDA003: wall-clock in control flow


_CLOCKS = frozenset({
    'time.time', 'time.time_ns', 'time.monotonic', 'time.monotonic_ns',
})


def _clock_call(node, ctx):
  """The first wall-clock call anywhere under ``node``, or None."""
  for n in ast.walk(node):
    if isinstance(n, ast.Call) and ctx.call_name(n)[0] in _CLOCKS:
      return ctx.call_name(n)[0]
  return None


def _scope_nodes(root):
  """All nodes of one scope, without descending into nested functions
  (those are their own taint scopes)."""
  stack = list(ast.iter_child_nodes(root))
  while stack:
    n = stack.pop()
    yield n
    if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
      stack.extend(ast.iter_child_nodes(n))


def _assigned_names(target):
  """Plain names bound by an assignment target. Attribute/subscript
  targets (``self.t0 = time.monotonic()``) are object state, outside
  this rule's one-level name taint — tainting ``self`` for them would
  flag every later ``if self...`` branch."""
  if isinstance(target, ast.Name):
    yield target.id
  elif isinstance(target, (ast.Tuple, ast.List)):
    for elt in target.elts:
      yield from _assigned_names(elt)
  elif isinstance(target, ast.Starred):
    yield from _assigned_names(target.value)


class WallClockControlFlow(Rule):
  rule_id = 'LDA003'
  name = 'wall-clock-control-flow'
  invariant = ('control flow is a function of logical progress, not '
               'wall-clock: ranks observing different times take '
               'different branches and diverge or deadlock')
  hint = ('branch on step/sample counts instead; timing that only feeds '
          'metrics belongs in telemetry/')

  def exempt(self, ctx):
    # Telemetry is *about* time; its comparisons never steer the
    # pipeline. This covers the whole package, explicitly including the
    # live-observability modules (telemetry/live.py windowed rates,
    # telemetry/server.py LDDL_MONITOR endpoint, telemetry/monitor.py
    # dashboard repaint loop): their time arithmetic produces
    # rates/verdicts for operators, never a branch a rank acts on.
    return ctx.path_is('telemetry/')

  def begin_module(self, ctx):
    scopes = [ctx.tree]
    scopes.extend(
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
    for scope in scopes:
      nodes = list(_scope_nodes(scope))
      tainted = set()
      for n in nodes:
        value = getattr(n, 'value', None)
        if value is None:
          continue
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                          ast.NamedExpr)) and _clock_call(value, ctx):
          targets = n.targets if isinstance(n, ast.Assign) else [n.target]
          for t in targets:
            tainted.update(_assigned_names(t))
      for n in nodes:
        if not isinstance(n, (ast.If, ast.While, ast.IfExp)):
          continue
        clock = _clock_call(n.test, ctx)
        if clock:
          yield self.finding(
              n.test, f'{clock}() feeds this branch condition: ranks '
              'observing different clocks diverge', ctx)
          continue
        used = sorted(
            x.id for x in ast.walk(n.test)
            if isinstance(x, ast.Name) and x.id in tainted)
        if used:
          yield self.finding(
              n.test, f'{used[0]!r} (derived from a wall-clock read) '
              'feeds this branch condition: ranks observing different '
              'clocks diverge', ctx)


# ---------------------------------------------------------------------------
# LDA004: resource acquisition without scoped release


_OPENERS = frozenset({'open', 'io.open', 'os.fdopen'})
_RELEASE_ATTRS = frozenset({
    'close', 'destroy', 'unlink', 'terminate', 'release', 'shutdown',
    'cleanup', '__exit__',
})


def _finally_releases(try_node):
  for stmt in try_node.finalbody:
    for n in ast.walk(stmt):
      if not isinstance(n, ast.Call):
        continue
      if (isinstance(n.func, ast.Attribute) and
          n.func.attr in _RELEASE_ATTRS):
        return True
      if isinstance(n.func, ast.Name) and 'close' in n.func.id:
        return True
  return False


class UnscopedResource(Rule):
  rule_id = 'LDA004'
  name = 'unscoped-resource'
  invariant = ('a crashed or killed worker never leaks handles or '
               '/dev/shm segments: every acquisition is released by a '
               'with block or try/finally')
  hint = ('acquire under "with", or inside a try whose finally '
          'closes/unlinks the resource')

  def on_node(self, node, ctx):
    if not isinstance(node, ast.Call):
      return
    dotted, term = ctx.call_name(node)
    what = None
    if dotted in _OPENERS:
      what = f'{dotted}()'
    elif term == 'ParquetFile' and dotted != 'ParquetFile':
      what = 'pq.ParquetFile()'
    elif term == 'SharedMemory':
      what = 'shared_memory.SharedMemory()'
    if what is None:
      return
    for anc in reversed(ctx.ancestors):
      if isinstance(anc, ast.withitem):
        return  # the context expression of a with block
      if isinstance(anc, ast.Call):
        _, anc_term = ctx.call_name(anc)
        if anc_term in ('closing', 'enter_context'):
          return  # ExitStack / contextlib ownership
      if isinstance(anc, ast.Try) and _finally_releases(anc):
        return
    yield self.finding(
        node, f'{what} acquired without a scoped release: a crash '
        'before the close leaks the handle (the ParquetFile/shm leak '
        'class)', ctx)


# ---------------------------------------------------------------------------
# LDA005: collective inside a rank-conditional branch


# The collective vocabulary and rank-identifier heuristics live in the
# engine: the facts extractor (project mode) and these lexical rules
# must agree on them or findings would shift between modes.
_COLLECTIVES = COLLECTIVES
_rank_mention = rank_mention


class RankConditionalCollective(Rule):
  rule_id = 'LDA005'
  name = 'rank-conditional-collective'
  invariant = ('collectives are issued uniformly by every rank: a '
               'collective some ranks skip deadlocks the ones that '
               'entered it (the classic SPMD hang)')
  hint = ('hoist the collective out of the rank conditional; keep only '
          'the rank-local work (logging, file writes) inside it')

  def on_node(self, node, ctx):
    if not isinstance(node, ast.Call):
      return
    dotted, term = ctx.call_name(node)
    if isinstance(node.func, ast.Attribute):
      if term not in _COLLECTIVES:
        return
    elif isinstance(node.func, ast.Name):
      # A bare name is a collective only when alias resolution proves
      # it (``from ..comm import barrier`` / ``sync = comm.barrier``):
      # an unrelated local function that happens to be called
      # ``barrier`` resolves to itself, dotless, and is not flagged.
      if (not dotted or '.' not in dotted
          or dotted.rsplit('.', 1)[-1] not in _COLLECTIVES):
        return
      term = dotted.rsplit('.', 1)[-1]
    else:
      return
    if dotted and dotted.startswith(DEVICE_COLLECTIVE_PREFIXES):
      return  # array shape ops (e.g. lax.broadcast), not collectives
    for anc in ctx.ancestors:
      if isinstance(anc, (ast.If, ast.While, ast.IfExp)):
        ident = _rank_mention(anc.test)
        if ident:
          yield self.finding(
              node, f'collective {term}() inside a branch '
              f'conditioned on {ident!r}: ranks disagreeing on the '
              'branch deadlock the collective', ctx)
          return


# ---------------------------------------------------------------------------
# LDA006: worker-pool churn


_POOL_EXECUTORS = frozenset({'ProcessPoolExecutor', 'ThreadPoolExecutor'})
_POOL_LIFECYCLE_METHODS = frozenset({'__init__', '__new__', '__enter__'})


class PoolChurn(Rule):
  rule_id = 'LDA006'
  name = 'pool-churn'
  invariant = ('worker pools have a lifetime, not a call site: a pool '
               'constructed per loop iteration or per method call re-pays '
               'worker spawn + per-worker warmup (tokenizer, native '
               'encoder) on every phase')
  hint = ('hoist the pool to an owner with a lifetime (create lazily '
          'once, reuse across phases, close() at teardown) — e.g. '
          'pipeline.pool.WorkerPool owned by Executor')

  def exempt(self, ctx):
    # Tests/benchmark scaffolding may build throwaway pools on purpose.
    if ctx.path_is('tests/'):
      return True
    base = ctx.basename()
    return (base.startswith('test_') or
            base in ('conftest.py', 'testing.py'))

  def _pool_name(self, node, ctx):
    dotted, term = ctx.call_name(node)
    if term in _POOL_EXECUTORS:
      return term
    if term == 'Pool' and (isinstance(node.func, ast.Attribute) or
                           (dotted and 'multiprocessing' in dotted)):
      # mp.Pool / ctx.Pool / multiprocessing.Pool; a bare local Pool()
      # class of unrelated meaning is not flagged.
      return 'Pool'
    return None

  def on_node(self, node, ctx):
    if not isinstance(node, ast.Call):
      return
    what = self._pool_name(node, ctx)
    if what is None:
      return
    for anc in ctx.ancestors:
      if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
        yield self.finding(
            node, f'{what}() constructed inside a loop: every iteration '
            're-pays worker spawn and per-worker warmup (pool churn)',
            ctx)
        return
    func = ctx.enclosing(ast.FunctionDef, ast.AsyncFunctionDef)
    if func is None or func.name in _POOL_LIFECYCLE_METHODS:
      return
    params = func.args.posonlyargs + func.args.args
    if not params or params[0].arg not in ('self', 'cls'):
      return
    ancestors = list(ctx.ancestors)
    fi = ancestors.index(func)
    if not any(isinstance(a, ast.ClassDef) for a in ancestors[:fi]):
      return  # self-named first arg on a plain function, not a method
    for anc in reversed(ancestors):
      if isinstance(anc, ast.Assign):
        for t in anc.targets:
          if (isinstance(t, ast.Attribute) and
              isinstance(t.value, ast.Name) and
              t.value.id in ('self', 'cls')):
            return  # cached on the instance: a lifetime, not churn
    yield self.finding(
        node, f'{what}() constructed per call of method {func.name!r}: '
        'every invocation re-pays worker spawn and per-worker warmup '
        'instead of reusing a pool with a lifetime (pool churn)', ctx)


# ---------------------------------------------------------------------------
# LDA007: swallowed exceptions


_BROAD_EXC = frozenset({'Exception', 'BaseException'})


class SwallowedException(Rule):
  rule_id = 'LDA007'
  name = 'swallowed-exception'
  invariant = ('fault-tolerance code must never eat errors blindly: a '
               'bare/broad except whose body does nothing turns rank '
               'death, lease races, and IO corruption into silent wrong '
               'answers the recovery machinery can no longer see')
  hint = ('catch the narrow exception the site actually expects '
          '(OSError, FileExistsError, ...), or handle it: count it in '
          'telemetry, log it, or re-raise — if swallowing broadly is '
          'truly intended, annotate why with  # lddl: noqa[LDA007]')

  def exempt(self, ctx):
    # Tests exercise failure paths on purpose (and often probe with
    # deliberately broad catches).
    if ctx.path_is('tests/'):
      return True
    base = ctx.basename()
    return (base.startswith('test_') or
            base in ('conftest.py', 'testing.py'))

  def _is_broad(self, node, ctx):
    if node.type is None:
      return True  # bare `except:`
    types = (node.type.elts if isinstance(node.type, ast.Tuple)
             else [node.type])
    for t in types:
      name = None
      if isinstance(t, ast.Name):
        name = t.id
      elif isinstance(t, ast.Attribute):
        name = t.attr
      if name in _BROAD_EXC:
        return True
    return False

  def _is_inert(self, body):
    # pass / continue / `...` / a lone docstring: nothing observed the
    # error. A `return`/assignment/call/raise counts as handling.
    for stmt in body:
      if isinstance(stmt, (ast.Pass, ast.Continue)):
        continue
      if (isinstance(stmt, ast.Expr) and
          isinstance(stmt.value, ast.Constant) and
          (stmt.value.value is Ellipsis or
           isinstance(stmt.value.value, str))):
        continue
      return False
    return True

  def on_node(self, node, ctx):
    if not isinstance(node, ast.ExceptHandler):
      return
    if not self._is_broad(node, ctx) or not self._is_inert(node.body):
      return
    what = ('bare except:' if node.type is None else
            'except ' + ast.unparse(node.type) + ':')
    yield self.finding(
        node, f'{what} with a do-nothing body swallows every error '
        '(including rank death, lease races, and IO corruption) '
        'invisibly — catch the narrow exception the site expects, or '
        'observe the failure (telemetry/log/re-raise)', ctx)


# ---------------------------------------------------------------------------
# LDA012: socket without a deadline


_SOCKET_CTORS = frozenset({'socket.socket'})
_SOCKET_CONNECTORS = frozenset({'socket.create_connection'})


class SocketWithoutDeadline(Rule):
  rule_id = 'LDA012'
  name = 'socket-without-deadline'
  invariant = ('every socket carries a deadline before blocking use: an '
               'unbounded accept/recv/connect turns one dead peer into '
               'a hung rank the lease machinery cannot distinguish from '
               'a slow one')
  hint = ('call .settimeout(...) on the socket in the same scope, or '
          'pass timeout= to socket.create_connection(...)')

  def exempt(self, ctx):
    # Tests open throwaway sockets (port probes, fake peers) whose
    # lifetime the test harness itself bounds.
    if ctx.path_is('tests/'):
      return True
    base = ctx.basename()
    return (base.startswith('test_') or
            base in ('conftest.py', 'testing.py'))

  def begin_module(self, ctx):
    scopes = [ctx.tree]
    scopes.extend(
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
    for scope in scopes:
      nodes = list(_scope_nodes(scope))
      # One-level scope discipline (same granularity as LDA003): a
      # .settimeout(...) anywhere in the creating scope bounds every
      # socket it creates; a socket handed to another function for its
      # deadline would be flagged here, keeping the bound visible at
      # the creation site.
      has_deadline = any(
          isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
          and n.func.attr == 'settimeout' for n in nodes)
      if has_deadline:
        continue
      for n in nodes:
        if not isinstance(n, ast.Call):
          continue
        dotted, _ = ctx.call_name(n)
        if dotted in _SOCKET_CTORS:
          yield self.finding(
              n, 'socket.socket() created with no .settimeout(...) in '
              'scope: its blocking calls can hang forever on a dead '
              'peer', ctx)
        elif dotted in _SOCKET_CONNECTORS and len(n.args) < 2 and not \
            any(kw.arg == 'timeout' for kw in n.keywords):
          yield self.finding(
              n, 'socket.create_connection() without timeout= (and no '
              '.settimeout(...) in scope): the connect can block '
              'forever on an unreachable server', ctx)


# ---------------------------------------------------------------------------
# LDA013: salted builtin hash() escaping the process


# Attribute-call terminals through which a value leaves the process (or
# the run): file/socket writes, queue handoffs, serialization, wire
# packing, and the determinism ledger itself.
_HASH_SINKS = frozenset({
    'write', 'writelines', 'send', 'sendall', 'sendto', 'put',
    'put_nowait', 'dump', 'dumps', 'pack', 'pack_into', 'publish',
    'record',
})


def _builtin_hash_call(node, ctx):
  """The first builtin ``hash(...)`` call whose *value* escapes through
  ``node``, or None. Comparison/boolean subtrees are pruned: the result
  of ``hash(a) == hash(b)`` computed in one interpreter is the same for
  every salt, so only the raw hash value carries the hazard. Alias
  resolution keeps a local/imported ``hash`` name out."""
  stack = [node]
  while stack:
    n = stack.pop()
    if isinstance(n, (ast.Compare, ast.BoolOp)):
      continue  # boolean results are salt-invariant
    if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and
        ctx.call_name(n)[0] == 'hash'):
      return n
    stack.extend(ast.iter_child_nodes(n))
  return None


class SaltedHashEscape(Rule):
  rule_id = 'LDA013'
  name = 'salted-hash'
  invariant = ('fingerprints that cross a process or run boundary come '
               'from a stable hash: builtin hash() on str/bytes is '
               'salted per interpreter (PYTHONHASHSEED), so a persisted '
               'or sent value never matches the next run or another rank')
  hint = ('use hashlib (blake2b/sha256) or the telemetry.ledger '
          'fingerprint helpers for anything written, sent, or used for '
          'placement; builtin hash() is only meaningful inside one '
          'process')

  def exempt(self, ctx):
    # Tests may assert on salted hashes within their own interpreter.
    if ctx.path_is('tests/'):
      return True
    base = ctx.basename()
    return (base.startswith('test_') or
            base in ('conftest.py', 'testing.py'))

  def _sink_of(self, node, ctx, in_hash_protocol):
    """Human description of the escape ``node`` represents, or None.
    Only the *payload* position of a call counts (its arguments):
    ``hash_index.write(...)`` must not read as a hash sink."""
    if isinstance(node, ast.Call):
      _, term = ctx.call_name(node)
      if term in _HASH_SINKS:
        return f'{term}()', list(node.args) + [kw.value
                                               for kw in node.keywords]
      return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
      # hash(key) % n: placement/sharding — the classic cross-worker
      # divergence — and "%s" % hash(x) stringification both land here.
      return "a '%' placement/format expression", [node.left, node.right]
    if isinstance(node, ast.Return) and node.value is not None \
        and not in_hash_protocol:
      # A returned hash escapes the one scope this analysis can see;
      # __hash__ is the process-local protocol use and stays legal.
      return 'a return (escapes this scope)', [node.value]
    return None

  def begin_module(self, ctx):
    scopes = [ctx.tree]
    scopes.extend(
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
    for scope in scopes:
      nodes = list(_scope_nodes(scope))
      in_hash_protocol = (
          isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)) and
          scope.name == '__hash__')
      tainted = set()
      for n in nodes:
        value = getattr(n, 'value', None)
        if value is None:
          continue
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                          ast.NamedExpr)) and _builtin_hash_call(value,
                                                                 ctx):
          targets = n.targets if isinstance(n, ast.Assign) else [n.target]
          for t in targets:
            tainted.update(_assigned_names(t))
      seen = set()
      for n in nodes:
        sink = self._sink_of(n, ctx, in_hash_protocol)
        if sink is None:
          continue
        what, payload = sink
        for arg in payload:
          call = _builtin_hash_call(arg, ctx)
          if call is not None:
            key = (call.lineno, call.col_offset)
            if key not in seen:
              seen.add(key)
              yield self.finding(
                  call, f'builtin hash() feeds {what}: hash() of '
                  'str/bytes is salted per interpreter '
                  '(PYTHONHASHSEED), so the value differs across runs '
                  'and ranks', ctx)
            continue
          used = sorted(
              x.id for x in ast.walk(arg)
              if isinstance(x, ast.Name) and x.id in tainted)
          if used:
            key = (n.lineno, n.col_offset, used[0])
            if key not in seen:
              seen.add(key)
              yield self.finding(
                  n, f'{used[0]!r} (derived from builtin hash()) feeds '
                  f'{what}: hash() of str/bytes is salted per '
                  'interpreter (PYTHONHASHSEED), so the value differs '
                  'across runs and ranks', ctx)


# ---------------------------------------------------------------------------
# Project-mode (interprocedural) rules: LDA008–LDA011 run over the
# whole-program call graph, not per file. Each finding carries the call
# chain from the analysis root to the effect site.


class TransitiveRankCollective(ProjectRule):
  rule_id = 'LDA008'
  name = 'transitive-rank-collective'
  invariant = ('collectives are issued uniformly by every rank even '
               'through call chains: a rank-conditional call whose '
               'callee (transitively) performs a collective deadlocks '
               'exactly like a lexical one — LDA005 one indirection out')
  hint = ('hoist the call (or just its collective) out of the rank '
          'conditional; keep only rank-local work inside it')

  def check(self, index, graph):
    for gq in sorted(index.defs):
      facts = index.defs[gq]
      targets = graph.call_targets.get(gq, ())
      for call, tgt in zip(facts.calls, targets):
        if not call.rank_cond or not tgt:
          continue
        if is_lexical_collective(call):
          continue  # lexical case: LDA005's finding, not ours
        if 'collective' not in graph.transitive_effects(tgt):
          continue
        sites = graph.reachable_effects(tgt, ('collective',))
        if not sites:
          continue
        eff_gq, eff, hops = sites[0]
        chain = ([{'name': f'{index.display(gq)}()',
                   'path': index.def_path(gq), 'line': call.line}]
                 + build_chain(index, hops, eff_gq, eff))
        yield self.finding(
            index.def_path(gq), call.line, call.col,
            f'{call.terminal}() called under a branch conditioned on '
            f'{call.rank_cond!r} transitively issues collective '
            f'{eff.detail}(): ranks skipping the branch deadlock the '
            'ones that entered it', chain=chain)


class ElasticPathPurity(ProjectRule):
  rule_id = 'LDA009'
  name = 'elastic-path-purity'
  invariant = ('the elastic scheduling path issues zero collectives and '
               'never waits unboundedly: survivors must make progress '
               'when a rank dies mid-phase, so nothing reachable from '
               'the claim/heartbeat/re-execution machinery may block on '
               'a peer')
  hint = ('make phase completion an observable fact (manifests, lease '
          'expiry) instead of a rendezvous; give every wait a timeout')

  # Roots are matched by definition/class name so the rule holds for
  # the real executor and for fixtures shaped like it.
  ROOT_DEFS = ('Executor._map_elastic',)
  ROOT_CLASSES = ('_LeaseClaimer', '_HeartbeatPump', 'HeartbeatPump',
                  'RankMembership')

  def _roots(self, index):
    roots = []
    for gq in sorted(index.defs):
      if index.display(gq) in self.ROOT_DEFS:
        roots.append(gq)
        continue
      cls = index.defs[gq].cls
      if cls and cls.rsplit('.', 1)[-1] in self.ROOT_CLASSES:
        roots.append(gq)
    return roots

  def check(self, index, graph):
    seen = set()
    for root in self._roots(index):
      for eff_gq, eff, hops in graph.reachable_effects(
          root, ('collective', 'unbounded_wait')):
        key = (index.def_path(eff_gq), eff.line, eff.col, eff.detail)
        if key in seen:
          continue
        seen.add(key)
        what = ('collective ' + eff.detail + '()'
                if eff.kind == 'collective'
                else f'unbounded wait {eff.detail}')
        yield self.finding(
            index.def_path(eff_gq), eff.line, eff.col,
            f'{what} reachable from elastic root '
            f'{index.display(root)}(): a dead rank would hang the '
            'survivors that are supposed to outlive it',
            chain=build_chain(index, hops, eff_gq, eff))


class JitHostSync(ProjectRule):
  rule_id = 'LDA010'
  name = 'jit-host-sync'
  invariant = ('jit-compiled code stays on device: a host sync '
               '(.item()/float()/np.asarray/device_get/'
               'block_until_ready) or wall-clock read reachable from a '
               'traced function forces a device flush at best and a '
               'retrace or tracer error at worst, stalling every step')
  hint = ('keep host-side reads outside the jitted function; pass '
          'values in as arguments, return metrics as arrays and read '
          'them after the step')

  def check(self, index, graph):
    roots = index.jit_root_defs()
    seen = set()
    for root in sorted(roots):
      for eff_gq, eff, hops in graph.reachable_effects(
          root, ('host_sync', 'wall_clock')):
        key = (index.def_path(eff_gq), eff.line, eff.col, eff.detail)
        if key in seen:
          continue
        seen.add(key)
        yield self.finding(
            index.def_path(eff_gq), eff.line, eff.col,
            f'{eff.detail} ({eff.kind}) reachable from jit-compiled '
            f'{index.display(root)}(): host synchronization inside '
            'traced code stalls or retraces the step',
            chain=build_chain(index, hops, eff_gq, eff))


class CollectiveOrderDivergence(ProjectRule):
  rule_id = 'LDA011'
  name = 'collective-order-divergence'
  invariant = ('every rank issues the same collectives in the same '
               'order: two branch arms reaching different collective '
               'sequences deadlock the fleet as soon as ranks disagree '
               'on the (data-dependent) condition')
  hint = ('restructure so both arms issue the identical collective '
          'sequence (hoist the collectives out of the branch), or make '
          'the condition provably rank-uniform')

  def _arm_trace(self, graph, facts, targets, idxs):
    out = []
    for i in idxs:
      call = facts.calls[i]
      if is_lexical_collective(call):
        out.append(call.terminal)
      elif targets[i]:
        out.extend(graph.collective_trace(targets[i]))
      if len(out) >= 8:
        return tuple(out[:8])
    return tuple(out)

  def check(self, index, graph):
    for gq in sorted(index.defs):
      facts = index.defs[gq]
      targets = graph.call_targets.get(gq, ())
      for branch in facts.branches:
        if not branch.body or not branch.orelse:
          continue
        body = self._arm_trace(graph, facts, targets, branch.body)
        orelse = self._arm_trace(graph, facts, targets, branch.orelse)
        if not body or not orelse or body == orelse:
          continue
        yield self.finding(
            index.def_path(gq), branch.line, 1,
            f'branch arms in {index.display(gq)}() reach different '
            f'collective sequences ({" → ".join(body)} vs '
            f'{" → ".join(orelse)}): ranks disagreeing on the '
            'condition issue mismatched collectives and deadlock')


def default_rules():
  """Fresh instances of every shipped per-file rule, in rule-id order."""
  return [
      UnsortedFsIteration(),
      GlobalStateRng(),
      WallClockControlFlow(),
      UnscopedResource(),
      RankConditionalCollective(),
      PoolChurn(),
      SwallowedException(),
      SocketWithoutDeadline(),
      SaltedHashEscape(),
  ]


def project_rules():
  """Fresh instances of every interprocedural (project-mode) rule:
  the call-graph rules here plus the thread-graph concurrency rules
  (LDA014–LDA018) from :mod:`.concurrency`."""
  from .concurrency import concurrency_rules
  return [
      TransitiveRankCollective(),
      ElasticPathPurity(),
      JitHostSync(),
      CollectiveOrderDivergence(),
  ] + concurrency_rules()


def all_rules():
  """Per-file + project rules, in rule-id order."""
  return default_rules() + project_rules()


def rules_by_id():
  return {r.rule_id: r for r in all_rules()}

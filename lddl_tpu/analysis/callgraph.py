"""Deterministic call graph + transitive-effect engine (project mode).

Built on top of a :class:`~lddl_tpu.analysis.project.ProjectIndex`: every
definition is a node; every call site the index can resolve to a project
definition is an edge. On the graph this module computes

  - **transitive effect sets** — a function's effects are its own lexical
    effects (``collective``, ``host_sync``, ``wall_clock``,
    ``blocking_io``, ``thread_spawn``, ``unbounded_wait``) unioned with
    everything its callees can do, to a fixed point, so cycles are safe;
  - **shortest call chains** from a root to any reachable effect site
    (the ``via: a() → b() → ...`` traces findings carry);
  - **ordered collective traces** — the sequence of collectives a call
    into a function will issue, in source order, for the
    collective-order-divergence rule.

Everything iterates over sorted structures: two runs over the same tree
produce the same graph, the same chains, and byte-identical findings.

Deliberate non-edges: ``Thread(target=f)`` / ``Process(target=f)`` do
*not* link caller to ``f`` — the target runs in a separate failure
domain and its waits/collectives are not issued on the caller's path
(the spawn itself is recorded as a ``thread_spawn`` effect). Ditto
callables handed to worker pools. ``functools.partial(f, ...)`` *is* an
edge: the partial runs in the caller's dynamic extent.
"""

import collections

from .engine import COLLECTIVES, DEVICE_COLLECTIVE_PREFIXES


def is_lexical_collective(call):
  """Whether one CallSite is itself a cross-rank collective.

  Attribute calls match on the method name (``comm.barrier()``); bare
  names only when alias resolution proves the origin (``from ..comm
  import barrier``) — a local function that happens to be called
  ``barrier`` resolves dotless and is not a collective. Mirrors
  rules.LDA005 exactly: the two must agree or findings would shift
  between file and project mode.
  """
  dotted = call.dotted or ''
  if call.receiver:
    name = call.terminal
  else:
    if '.' not in dotted:
      return False
    name = dotted.rsplit('.', 1)[-1]
  return (name in COLLECTIVES
          and not dotted.startswith(DEVICE_COLLECTIVE_PREFIXES))


class CallGraph:
  """Edges + transitive effects over a built ProjectIndex."""

  # Recursion guard for collective traces (deep chains carry no extra
  # ordering information past this).
  _TRACE_DEPTH = 12
  _TRACE_LIMIT = 8

  def __init__(self, index):
    self.index = index
    # gq -> list aligned with defs[gq].calls: resolved callee gq or ''.
    self.call_targets = {}
    for gq in sorted(index.defs):
      facts = index.defs[gq]
      self.call_targets[gq] = [index.resolve_call(gq, c)
                               for c in facts.calls]
    # gq -> ((callee gq, first call-site line), ...) in source order.
    self.edges = {}
    for gq in sorted(self.call_targets):
      first_line = {}
      for call, tgt in zip(index.defs[gq].calls, self.call_targets[gq]):
        if tgt and tgt in index.defs and tgt not in first_line:
          first_line[tgt] = call.line
      self.edges[gq] = tuple(
          sorted(first_line.items(), key=lambda kv: (kv[1], kv[0])))
    self._transitive = self._fixed_point_effects()
    self._trace_memo = {}

  def _fixed_point_effects(self):
    eff = {gq: frozenset(e.kind for e in self.index.defs[gq].effects)
           for gq in self.index.defs}
    changed = True
    while changed:
      changed = False
      for gq in sorted(eff):
        merged = eff[gq]
        for tgt, _ in self.edges.get(gq, ()):
          merged = merged | eff.get(tgt, frozenset())
        if merged != eff[gq]:
          eff[gq] = merged
          changed = True
    return eff

  def transitive_effects(self, gq):
    """Effect kinds ``gq`` can perform, directly or through any callee."""
    return self._transitive.get(gq, frozenset())

  def bfs_parents(self, root):
    """First-visit parent map ``gq -> (parent gq, call-site line)`` from
    ``root`` (root maps to None). First visit along sorted adjacency =
    a deterministic shortest chain to every reachable definition."""
    parents = {root: None}
    queue = collections.deque([root])
    while queue:
      cur = queue.popleft()
      for tgt, line in self.edges.get(cur, ()):
        if tgt not in parents:
          parents[tgt] = (cur, line)
          queue.append(tgt)
    return parents

  def chain_hops(self, parents, target):
    """``[(hop gq, line of the call it makes toward target), ...]`` from
    the BFS root down to (excluding) ``target``."""
    rev = []
    cur = target
    while parents[cur] is not None:
      parent, line = parents[cur]
      rev.append((parent, line))
      cur = parent
    return list(reversed(rev))

  def reachable_effects(self, root, kinds):
    """Every effect site of ``kinds`` reachable from ``root``:
    ``(def gq, EffectSite, hops)`` sorted by effect location."""
    parents = self.bfs_parents(root)
    out = []
    for gq in sorted(parents):
      facts = self.index.defs.get(gq)
      if facts is None:
        continue
      for eff in facts.effects:
        if eff.kind in kinds:
          out.append((gq, eff, self.chain_hops(parents, gq)))
    out.sort(key=lambda t: (self.index.def_path(t[0]), t[1].line,
                            t[1].col, t[1].kind))
    return out

  def collective_trace(self, gq):
    """Ordered tuple of collective names a call to ``gq`` issues, in
    source order, following resolved callees (capped, cycle-guarded;
    best-effort on recursion)."""
    return self._trace(gq, frozenset())

  def _trace(self, gq, stack):
    if gq in self._trace_memo:
      return self._trace_memo[gq]
    facts = self.index.defs.get(gq)
    if facts is None or gq in stack or len(stack) > self._TRACE_DEPTH:
      return ()
    stack = stack | {gq}
    out = []
    for call, tgt in zip(facts.calls, self.call_targets.get(gq, ())):
      if is_lexical_collective(call):
        out.append(call.terminal)
      elif tgt:
        out.extend(self._trace(tgt, stack))
      if len(out) >= self._TRACE_LIMIT:
        out = out[:self._TRACE_LIMIT]
        break
    trace = tuple(out)
    self._trace_memo[gq] = trace
    return trace

"""Training loop layer: the consumer the reference always assumed.

LDDL is a data library; its README points users at external NVIDIA BERT
trainers. Here the trainer is in-repo so the full contract — preprocess
-> balance -> load -> sharded train step -> checkpoint/resume — is owned,
tested, and deterministic end to end.
"""

from .pretrain import TrainLoop, main  # noqa: F401

"""Black-box flight recorder: bounded batch ring → hermetic incidents.

An aircraft flight recorder does not log everything — it keeps a short
ring of the signals that matter and freezes it when something goes
wrong. This module does the same for a training run. While the loop
runs, the recorder keeps:

- the last **K host batches**, packed through the shm/wire
  ``_pack_into`` spec (loader/service.py ``pack_batch``) — the *same
  bytes* the determinism ledger fingerprints, so a frozen batch carries
  its own ground truth;
- each batch's **ledger coordinate** ``(epoch, index)`` via the
  loaders' public ``coordinate_of_batch`` contract (ring ordinals are
  global step numbers: one batch per rank per step);
- a short window of **step metrics** (loss, grad norm, data wait) and
  the latest **checkpoint ref**.

The ring tees the *host* iterator before ``prefetch_to_device``
(device arrays cannot be packed, and re-fetching them would perturb the
run), which means :meth:`FlightRecorder.wrap_host_stream` executes on
the prefetcher's producer thread — the ring is lock-protected.

When a sentinel (telemetry/sentinel.py) fires, :meth:`capture` freezes
everything into an incident directory::

  incident-step42-loss_spike/
    incident.json            # trigger, suspect coordinate, ring index,
                             # recent metrics, live_status snapshot,
                             # ledger excerpt, checkpoint ref
    bundles/
      ord000042-e0-i42/      # lddl-replay bundle per ring entry
        bundle.json
        batch.bin

Each bundle is written with the payload's **pre-capture** fingerprint,
so ``lddl-replay`` (or ``read_bundle``) later proves bit-for-bit
identity — "training diverged at 3am" becomes one command to a
hermetic repro. Capture can also arm PR 11's ``StepProfiler`` for the
next N steps (``LDDL_FLIGHT_PROFILE_STEPS``) so the trace of the steps
*after* the anomaly lands next to its cause.

Capture must never take down the run it is documenting: every failure
inside :meth:`capture` is caught and reported, not raised. The
``flight.dump`` fault site drills the two failure classes — a
raise-spec kills a dump at entry (training continues), a corrupt-spec
flips a byte of one bundle payload mid-dump so the replay reader later
*rejects* the damaged bundle instead of silently replaying it.

The recorder rides the sentinel's gate: ``get_flight_recorder()``
returns the live recorder only when ``LDDL_SENTINEL`` is on, else the
shared no-op (whose ``wrap_host_stream`` returns the iterator
untouched — zero overhead, zero threads, zero files).

``main`` is the ``lddl-incident`` CLI: ``list``/``show`` incidents,
``replay``/``bisect`` shell straight into ``lddl-replay``.
"""

import argparse
import json
import os
import sys
import threading
import time

_ENV_DIR = 'LDDL_FLIGHT_DIR'
_ENV_RING = 'LDDL_FLIGHT_RING'
_ENV_PROFILE = 'LDDL_FLIGHT_PROFILE_STEPS'

#: Incident manifest filename — what ``scan_incidents`` looks for.
MANIFEST = 'incident.json'

#: Ring capacity when ``LDDL_FLIGHT_RING`` is unset.
DEFAULT_RING = 8

#: Captures per process — a pathological run (every step fires) must
#: not fill the disk with identical incidents.
DEFAULT_MAX_INCIDENTS = 8


class NoopFlightRecorder:
  """Shared inert recorder: the stream passes through untouched."""

  __slots__ = ()
  enabled = False

  def wrap_host_stream(self, it, loader=None, ordinal0=0):
    return it

  def record_step(self, step, **metrics):
    return None

  def note_checkpoint(self, ckpt_dir, step):
    return None

  def capture(self, trigger, extra=None):
    return None


NOOP_FLIGHT = NoopFlightRecorder()


class FlightRecorder:
  """Bounded ring of packed batches + step metrics, frozen on trigger."""

  enabled = True

  def __init__(self, out_dir=None, capacity=None, metrics_window=None,
               profile_steps=None, max_incidents=DEFAULT_MAX_INCIDENTS):
    if out_dir is None:
      out_dir = os.environ.get(_ENV_DIR, '').strip()
    if not out_dir:
      base = os.environ.get('LDDL_TELEMETRY_DIR', '').strip() or '.'
      out_dir = os.path.join(base, 'incidents')
    self.out_dir = out_dir
    raw = os.environ.get(_ENV_RING, '').strip()
    self.capacity = int(capacity if capacity is not None
                        else (raw or DEFAULT_RING))
    self.metrics_window = int(metrics_window or 4 * self.capacity)
    raw = os.environ.get(_ENV_PROFILE, '').strip()
    self.profile_steps = int(profile_steps if profile_steps is not None
                             else (raw or 0))
    self.max_incidents = int(max_incidents)
    self._ring = []      # [{'ordinal','epoch','index','spec','payload'}]
    self._metrics = []   # [{'step', **scalars}]
    self._checkpoint = None
    self._lock = threading.Lock()
    self._warned = False
    self.incident_dirs = []

  # -- recording (hot path)

  def wrap_host_stream(self, it, loader=None, ordinal0=0):
    """Tee the host batch iterator into the ring.

    Runs on whatever thread drives ``it`` (the prefetcher's producer).
    ``ordinal0`` is the global step the first yielded batch will feed;
    coordinates come from ``loader.coordinate_of_batch`` when the
    loader publishes that contract, else ``(None, ordinal)``. A packing
    failure is reported once and skipped — the recorder must never
    starve the input pipeline.
    """
    def tee():
      ordinal = int(ordinal0)
      for batch in it:
        try:
          self._record_batch(batch, loader, ordinal)
        except Exception as exc:
          # _warned is shared with capture(), which can run on another
          # thread (sentinel triggers): claim the warning under the lock.
          with self._lock:
            first, self._warned = not self._warned, True
          if first:
            print(f'flight: batch recording disabled after error: '
                  f'{type(exc).__name__}: {exc}', file=sys.stderr)
        ordinal += 1
        yield batch
    return tee()

  def _record_batch(self, batch, loader, ordinal):
    from ..loader.service import pack_batch
    spec, payload = pack_batch(batch)  # copies: detached from the batch
    epoch = index = None
    co = getattr(loader, 'coordinate_of_batch', None)
    if co is not None:
      try:
        epoch, index = co(ordinal)
      except Exception:
        epoch, index = None, None
    if index is None:
      index = ordinal
    with self._lock:
      self._ring.append({'ordinal': ordinal, 'epoch': epoch,
                         'index': index, 'spec': spec, 'payload': payload})
      del self._ring[:-self.capacity]

  def record_step(self, step, **scalars):
    """Append one step's scalars (loss, grad_norm, data_wait) to the
    bounded metrics window that ships inside the manifest."""
    entry = {'step': int(step)}
    entry.update({k: v for k, v in scalars.items() if v is not None})
    with self._lock:
      self._metrics.append(entry)
      del self._metrics[:-self.metrics_window]

  def note_checkpoint(self, ckpt_dir, step):
    """Remember the newest checkpoint a replay can restore from."""
    with self._lock:
      self._checkpoint = {'dir': os.path.abspath(ckpt_dir),
                          'step': int(step)}

  # -- capture (cold path)

  def capture(self, trigger, extra=None):
    """Freeze the ring into an incident directory; returns its path or
    None. Never raises: an incident dump failing must not crash the
    training run it is documenting (the failure is reported instead)."""
    try:
      with self._lock:  # triggers can fire from producer threads
        capped = len(self.incident_dirs) >= self.max_incidents
        first = capped and not self._warned
        self._warned = self._warned or capped
      if capped:
        if first:
          print(f'flight: incident cap ({self.max_incidents}) reached; '
                'further triggers are counted but not captured',
                file=sys.stderr)
        return None
      from ..core import faults
      faults.inject('flight.dump', step=trigger.get('step'))
      return self._dump(trigger, extra)
    except Exception as exc:
      print(f'flight: incident capture failed: '
            f'{type(exc).__name__}: {exc}', file=sys.stderr)
      return None

  def _incident_dir(self, trigger):
    step = trigger.get('step')
    tag = (f'step{step}' if step is not None else 'async')
    base = os.path.join(self.out_dir,
                        f'incident-{tag}-{trigger.get("detector", "x")}')
    path, n = base, 1
    while os.path.exists(path):
      n += 1
      path = f'{base}-{n}'
    return path

  def _dump(self, trigger, extra):
    from ..core import faults
    from ..loader.service import unpack_batch
    from ..replay.bundle import write_bundle
    from ..telemetry.ledger import get_ledger
    from ..telemetry.ledger import fingerprint_packed
    with self._lock:
      entries = list(self._ring)
      metrics = list(self._metrics)
      checkpoint = dict(self._checkpoint) if self._checkpoint else None
    out = self._incident_dir(trigger)
    os.makedirs(out, exist_ok=True)
    step = trigger.get('step')
    ring, suspect = [], None
    for e in entries:
      coord = ({'epoch': e['epoch'], 'index': e['index']}
               if e['epoch'] is not None else {'index': e['index']})
      # The recorded fingerprint is taken from the *pristine* ring
      # bytes — the same bytes the ledger hashed at collate time. The
      # corrupt-spec drill below damages only the dumped copy, so the
      # replay reader proves it rejects a corrupted incident bundle.
      digest = fingerprint_packed(e['spec'], e['payload'])
      payload = bytearray(e['payload'])
      faults.corrupt_bytes('flight.dump', payload, **coord)
      name = f'ord{e["ordinal"]:06d}-e{e["epoch"]}-i{e["index"]}'
      bdir = os.path.join(out, 'bundles', name)
      write_bundle(bdir, unpack_batch(e['spec'], payload), coord,
                   digest=digest, checkpoint=checkpoint)
      entry = {'ordinal': e['ordinal'], 'coordinate': coord,
               'digest': digest, 'payload_bytes': len(e['payload']),
               'bundle': os.path.join('bundles', name),
               'suspect': step is not None and e['ordinal'] == step}
      if entry['suspect']:
        suspect = entry
      ring.append(entry)
    if suspect is None and ring:
      suspect = ring[-1]  # async triggers: newest batch is the best lead
    led = get_ledger()
    manifest = {
        'version': 1,
        'trigger': dict(trigger),
        'step': step,
        'replay_step': (step + 1) if step is not None else None,
        'unix_time': time.time(),
        'pid': os.getpid(),
        'checkpoint': checkpoint,
        'ring': ring,
        'suspect': suspect,
        'metrics': metrics,
        'ledger': led.signals() if led.enabled else None,
        'live_status': self._live_status(),
        'extra': extra,
    }
    if self.profile_steps > 0:
      manifest['profile'] = self._arm_profiler()
    with open(os.path.join(out, MANIFEST), 'w') as f:
      json.dump(manifest, f, indent=2, default=str)
      f.write('\n')
    with self._lock:  # read concurrently by capture()'s cap check
      self.incident_dirs.append(out)
    from ..telemetry.sentinel import get_sentinel
    get_sentinel().note_incident(out, trigger)
    return out

  def _live_status(self):
    """The monitor's window snapshot when one is serving, else a fresh
    one-sample window — best effort, None on any failure."""
    try:
      from ..telemetry.live import SnapshotWindow, live_status
      from ..telemetry.server import get_monitor
      mon = get_monitor()
      if mon.enabled and getattr(mon, 'window', None) is not None:
        with mon.window_lock:
          return live_status(mon.window, rank=mon.rank,
                             include_metrics=False)
      return live_status(SnapshotWindow(), include_metrics=False)
    except Exception:
      return None

  def _arm_profiler(self):
    try:
      from ..telemetry.profiling import get_step_profiler
      get_step_profiler().arm(self.profile_steps)
      return {'armed_steps': self.profile_steps}
    except Exception as exc:
      return {'armed_steps': 0,
              'error': f'{type(exc).__name__}: {exc}'}


# -- module gate: the recorder rides the sentinel's LDDL_SENTINEL gate

_active = None
# Sentinel triggers fire from producer threads while the train loop
# resolves lazily on the main thread; the lock makes install atomic.
_active_lock = threading.Lock()


def get_flight_recorder():
  """The process flight recorder — live iff the sentinel is live."""
  global _active
  with _active_lock:
    if _active is None:
      from ..telemetry.sentinel import get_sentinel
      _active = FlightRecorder() if get_sentinel().enabled else NOOP_FLIGHT
    return _active


def enable_flight(**kwargs):
  """Force-enable (tests): installs and returns a fresh recorder."""
  global _active
  with _active_lock:
    _active = FlightRecorder(**kwargs)
    return _active


def disable_flight():
  """Force-disable and drop the active instance (tests)."""
  global _active
  with _active_lock:
    _active = NOOP_FLIGHT


# -- incident inventory (shared by lddl-incident and lddl-perf)

def scan_incidents(root):
  """Every incident under ``root`` (itself an incident dir, or a tree
  of them), sorted by path: ``[{'dir', 'manifest'}, ...]``. Unreadable
  manifests surface as ``{'dir', 'error'}`` — a half-written incident
  still fails a gate."""
  out = []
  root = str(root)
  if not os.path.isdir(root):
    return out
  # lddl: noqa[LDA001] aggregate is sorted before return below
  for dirpath, dirnames, filenames in os.walk(root):
    if MANIFEST not in filenames:
      continue
    dirnames[:] = []  # bundles inside an incident are not incidents
    path = os.path.join(dirpath, MANIFEST)
    try:
      with open(path) as f:
        out.append({'dir': dirpath, 'manifest': json.load(f)})
    except (OSError, ValueError) as exc:
      out.append({'dir': dirpath, 'manifest': None,
                  'error': f'{type(exc).__name__}: {exc}'})
  return sorted(out, key=lambda i: i['dir'])


def replay_command(incident_dir, manifest):
  """The one-command repro for an incident, as a shell string (what
  ``lddl-perf --gate --incidents`` prints under a failing gate)."""
  if not manifest:
    return None
  suspect = manifest.get('suspect') or {}
  bundle = suspect.get('bundle')
  if not bundle:
    return None
  bundle = os.path.join(os.path.abspath(incident_dir), bundle)
  ckpt = manifest.get('checkpoint') or {}
  replay_step = manifest.get('replay_step')
  if ckpt.get('dir') and replay_step is not None:
    return (f'lddl-replay step --bundle {bundle} '
            f'--checkpoint-dir {ckpt["dir"]} --step {replay_step}')
  return f'lddl-incident replay {os.path.abspath(incident_dir)}'


# -- lddl-incident CLI

def _load_manifest(incident_dir):
  path = os.path.join(incident_dir, MANIFEST)
  if not os.path.isfile(path):
    raise FileNotFoundError(f'not an incident dir (no {MANIFEST}): '
                            f'{incident_dir}')
  with open(path) as f:
    return json.load(f)


def _default_root():
  root = os.environ.get(_ENV_DIR, '').strip()
  if root:
    return root
  base = os.environ.get('LDDL_TELEMETRY_DIR', '').strip() or '.'
  return os.path.join(base, 'incidents')


def _cmd_list(args):
  incidents = scan_incidents(args.root)
  if not incidents:
    print(f'no incidents under {args.root}')
    return 0
  for inc in incidents:
    man = inc['manifest']
    if man is None:
      print(f'{inc["dir"]}  [unreadable: {inc.get("error")}]')
      continue
    trig = man.get('trigger') or {}
    print(f'{inc["dir"]}  detector={trig.get("detector", "?")} '
          f'step={man.get("step")} '
          f'bundles={len(man.get("ring") or [])}')
  return 0


def _cmd_show(args):
  try:
    man = _load_manifest(args.incident)
  except (OSError, ValueError) as exc:
    print(f'lddl-incident: {exc}', file=sys.stderr)
    return 2
  trig = man.get('trigger') or {}
  print(f'incident:   {args.incident}')
  print(f'detector:   {trig.get("detector")}')
  print(f'step:       {man.get("step")}')
  print(f'reason:     {trig.get("reason")}')
  if trig.get('stats'):
    print(f'stats:      {json.dumps(trig["stats"], default=str)}')
  ckpt = man.get('checkpoint') or {}
  if ckpt:
    print(f'checkpoint: {ckpt.get("dir")} @ step {ckpt.get("step")}')
  print('ring:')
  for entry in man.get('ring') or []:
    mark = ' <- suspect' if entry.get('suspect') else ''
    print(f'  ord {entry["ordinal"]:>6}  '
          f'({_fmt_coord(entry.get("coordinate"))})  '
          f'{entry.get("digest")}  {entry.get("bundle")}{mark}')
  if man.get('metrics'):
    last = man['metrics'][-1]
    print(f'last step metrics: {json.dumps(last, default=str)}')
  cmd = replay_command(args.incident, man)
  if cmd:
    print(f'replay:     {cmd}')
  return 0


def _fmt_coord(coord):
  from ..replay.rematerialize import format_coordinate
  return format_coordinate(coord or {})


def _suspect_bundle(incident_dir, man):
  suspect = (man.get('suspect') or {}).get('bundle')
  if not suspect:
    raise LookupError('incident has no suspect bundle (empty ring?)')
  return os.path.join(os.path.abspath(incident_dir), suspect)


def _cmd_replay(args):
  try:
    man = _load_manifest(args.incident)
    bundle = _suspect_bundle(args.incident, man)
  except (OSError, ValueError, LookupError) as exc:
    print(f'lddl-incident: {exc}', file=sys.stderr)
    return 2
  rest = [a for a in (args.rest or []) if a != '--']
  if rest:
    # Shell straight into lddl-replay: the bundle names the batch, the
    # caller supplies the model/checkpoint args (e.g. --checkpoint-dir
    # ... --step N --vocab-size V for a full step replay).
    from ..replay.cli import main as replay_main
    return replay_main(['step', '--bundle', bundle] + rest)
  # No passthrough: verify the bundle's payload against its recorded
  # fingerprint — the fast "is the repro intact" check.
  from ..replay.bundle import read_bundle
  from ..replay.rematerialize import ReplayMismatch
  try:
    manifest, _ = read_bundle(bundle)
  except ReplayMismatch as exc:
    print(f'lddl-incident: {exc}', file=sys.stderr)
    return 1
  print(f'bundle ok: {_fmt_coord(manifest.get("coordinate"))} '
        f'digest={manifest.get("digest")}')
  cmd = replay_command(args.incident, man)
  if cmd:
    print(f'full step replay: {cmd}')
  return 0


def _cmd_bisect(args):
  try:
    man = _load_manifest(args.incident)
  except (OSError, ValueError) as exc:
    print(f'lddl-incident: {exc}', file=sys.stderr)
    return 2
  ckpt = man.get('checkpoint') or {}
  replay_step = man.get('replay_step') or man.get('step')
  if not ckpt.get('dir') or replay_step is None:
    print('lddl-incident: incident carries no checkpoint ref; bisect '
          'needs one (run with ckpt_every > 0)', file=sys.stderr)
    return 2
  lo = max(0, int(replay_step) - max(len(man.get('ring') or []), 1))
  rest = [a for a in (args.rest or []) if a != '--']
  from ..replay.cli import main as replay_main
  return replay_main(['bisect', '--checkpoint-dir', ckpt['dir'],
                      '--lo', str(lo), '--hi', str(replay_step)] + rest)


def attach_args(parser):
  sub = parser.add_subparsers(dest='cmd', required=True)
  p = sub.add_parser('list', help='inventory incidents under a root')
  p.add_argument('--root', default=_default_root(),
                 help='incident tree (default: LDDL_FLIGHT_DIR or '
                      '$LDDL_TELEMETRY_DIR/incidents)')
  p.set_defaults(fn=_cmd_list)
  p = sub.add_parser('show', help='render one incident manifest')
  p.add_argument('incident', help='incident directory')
  p.set_defaults(fn=_cmd_show)
  p = sub.add_parser(
      'replay', help='verify the suspect bundle, or pass extra args '
                     'through to `lddl-replay step --bundle ...`')
  p.add_argument('incident', help='incident directory')
  p.add_argument('rest', nargs=argparse.REMAINDER,
                 help='forwarded to lddl-replay step')
  p.set_defaults(fn=_cmd_replay)
  p = sub.add_parser(
      'bisect', help='shell into `lddl-replay bisect` over the steps '
                     'the ring covers (loader/model args pass through)')
  p.add_argument('incident', help='incident directory')
  p.add_argument('rest', nargs=argparse.REMAINDER,
                 help='forwarded to lddl-replay bisect')
  p.set_defaults(fn=_cmd_bisect)
  return parser


def main(argv=None):
  parser = argparse.ArgumentParser(
      prog='lddl-incident',
      description='List, inspect, and replay flight-recorder incidents.')
  attach_args(parser)
  args = parser.parse_args(argv)
  return args.fn(args)


if __name__ == '__main__':
  sys.exit(main())

"""BERT pretraining loop: mesh-sharded steps + checkpoint/resume.

The reference delegates training to external consumers and supports their
checkpoints only through ``start_epoch``/``samples_seen`` loader replay
(``lddl/torch_mp/bert.py:426-456``). Here the trainer is part of the
framework and the two halves are tied together: a checkpoint stores the
sharded model/optimizer state *and* the global ``samples_seen`` counter,
so a restart resumes both the parameter trajectory and the data stream
position. Resume determinism matches the reference's contract exactly:
every restart from the same checkpoint continues identically (bin draws
replay, dynamic-mask Philox keys are (seed, epoch, rank, step)-keyed, the
epoch's sample set is preserved); the shuffle buffer restarts fresh after
the skip (reference ``torch_mp/datasets.py:87-98``), so within-bin sample
*order* may differ from the never-interrupted trajectory.

Checkpointing uses orbax with sharding-aware restore: each host writes
its shards, restore places leaves directly onto the mesh.

CLI: ``python -m lddl_tpu.cli pretrain_bert --path <balanced> ...``.
"""

import argparse
import dataclasses
import functools
import json
import logging
import math
import os
import time


def _place_opt_state(opt_state, params, mesh):
  """Give every optimizer-state leaf an explicit mesh placement.

  Adam's ``mu``/``nu`` mirror the params tree, so each leaf inherits the
  sharding of the params leaf whose tree path it ends with (longest
  suffix wins); everything else (step counters, schedule scalars) is
  replicated. Without this the layout is whatever jit happened to pick —
  fine for one run, but a checkpoint restore reproduces it faithfully
  and then conflicts with the mesh-sharded params inside the jitted
  step.
  """
  import jax
  from jax.sharding import NamedSharding, PartitionSpec
  from jax.tree_util import (keystr, tree_flatten_with_path,
                             tree_unflatten)
  param_paths = sorted(
      ((keystr(p), leaf.sharding)
       for p, leaf in tree_flatten_with_path(params)[0]),
      key=lambda kv: -len(kv[0]))
  rep = NamedSharding(mesh, PartitionSpec())
  flat, treedef = tree_flatten_with_path(opt_state)
  placed = []
  for path, leaf in flat:
    ks = keystr(path)
    sharding = next((sh for pp, sh in param_paths if ks.endswith(pp)), rep)
    placed.append(jax.device_put(leaf, sharding))
  return tree_unflatten(treedef, placed)


class CompiledStepCache:
  """Per-bin compiled train-step cache.

  ``jax.jit`` already memoizes traces by abstract signature, but its
  misses are silent and its hits still pay signature dispatch. This
  wrapper makes the (seq-bucket, batch shape) -> executable mapping
  explicit: the first batch of a given shape signature AOT-lowers and
  compiles the jitted step (timed and counted as a miss / retrace), and
  every later batch of that signature invokes the stored executable
  directly — so a binned loader cycling through its seq buckets hits a
  warm cache after one pass over the bins, and the telemetry counters
  (``train.step_cache_hits``/``misses``, ``train.retrace_seconds``)
  prove bin switches after warmup cause zero retraces.

  Compile time is also where XLA's exact cost model is free: each new
  executable's ``cost_analysis()`` FLOPs/bytes are captured once per
  (bin, shape) entry and re-billed per step as the
  ``train.xla_flops`` / ``train.xla_bytes`` counters — the measured
  numerators the roofline verdict and MFU gauge run on, at zero
  steady-state cost (two counter adds per step).

  Disable with ``LDDL_STEP_CACHE=0`` (falls back to calling the jitted
  step directly).
  """

  def __init__(self, step_fn):
    from ..telemetry import get_telemetry
    self.inner = step_fn
    self._compiled = {}
    self._costs = {}   # key -> (process flops, process bytes) per step
    self.hits = 0
    self.misses = 0
    self.retrace_seconds = 0.0
    # Process-total costs of the most recently executed entry (the MFU
    # numerator); None until a compiled entry reported a cost model.
    self.last_costs = None
    tele = get_telemetry()
    self._tele = tele
    self._hits_c = tele.counter('train.step_cache_hits')
    self._misses_c = tele.counter('train.step_cache_misses')
    self._retrace_h = tele.histogram('train.retrace_seconds')
    self._flops_c = tele.counter('train.xla_flops')
    self._bytes_c = tele.counter('train.xla_bytes')

  @staticmethod
  def key_of(batch):
    return tuple(
        sorted((k, tuple(v.shape), str(v.dtype)) for k, v in batch.items()))

  def __call__(self, params, opt_state, rng, batch):
    key = self.key_of(batch)
    fn = self._compiled.get(key)
    if fn is None:
      t0 = time.perf_counter()
      lower = getattr(self.inner, 'lower', None)
      if lower is not None:
        fn = lower(params, opt_state, rng, batch).compile()
        # cost_analysis() reports the per-device partitioned module;
        # scale to the process total once here so the per-step billing
        # below is two plain adds.
        from ..telemetry.roofline import compiled_step_costs
        costs = compiled_step_costs(fn)
        if costs is not None:
          import jax
          n = jax.local_device_count()
          self._costs[key] = (costs[0] * n, costs[1] * n)
      else:
        fn = self.inner  # plain-callable step fns still work, uncached
      dt = time.perf_counter() - t0
      self._compiled[key] = fn
      self.misses += 1
      self.retrace_seconds += dt
      self._misses_c.add(1)
      self._retrace_h.observe(dt)
    else:
      self.hits += 1
      self._hits_c.add(1)
    costs = self._costs.get(key)
    if costs is not None:
      self.last_costs = costs
      if self._tele.enabled:
        self._flops_c.add(costs[0])
        self._bytes_c.add(costs[1])
    return fn(params, opt_state, rng, batch)


def _step_cache_enabled():
  return os.environ.get('LDDL_STEP_CACHE', '').strip().lower() not in (
      '0', 'false', 'off', 'no')


def state_fingerprint(snap):
  """Content fingerprint of a train-state pytree (params + opt_state +
  rng key data), the exact digest the ledger's ``step`` boundary
  records. ``snap`` should be a donation-safe snapshot
  (:func:`~lddl_tpu.parallel.train.snapshot_for_checkpoint`); multi-host
  sharded leaves are reduced to their local addressable bytes, identical
  across runs of the same topology. Module-level so
  :mod:`lddl_tpu.replay` can diff a re-executed step against the
  recorded line without a live ledger."""
  import jax
  import numpy as np

  from ..telemetry.ledger import fingerprint_batch

  def _host(x):
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
      return np.asarray(x.addressable_data(0))
    return x
  return fingerprint_batch(jax.tree_util.tree_map(_host, snap))


@dataclasses.dataclass
class TrainLoop:
  """Owns model/optimizer state, the loader, and the step function."""

  model: object
  tx: object
  mesh: object
  loader: object
  params: object
  opt_state: object
  rng: object
  step_fn: object
  samples_seen: int = 0
  step: int = 0
  # (per_rank_batch, seq_len) -> analytic FLOPs of one train step; set by
  # build() so run() can report MFU without re-deriving the model config.
  flops_fn: object = None
  dp_rank: int = 0
  dp_world: int = 1
  # Why the last run() stopped early (preemption / membership event), or
  # None when it ran to max_steps. The supervisor's relaunch signal.
  stop_reason: object = None
  _last_saved: int = dataclasses.field(default=-1, repr=False)
  # Most recent step loss, carried onto the ledger's checkpoint-boundary
  # fingerprint as context (never part of the alignment key).
  _last_loss: object = dataclasses.field(default=None, repr=False)

  @classmethod
  def build(cls, path, tokenizer, *, model_cfg, mesh, learning_rate=1e-4,
            warmup_steps=100, total_steps=10000, weight_decay=0.01,
            batch_size_per_rank=64, bin_size=None, max_seq_length=512,
            masking='dynamic', seed=127, samples_seen=0, loader_kwargs=None,
            max_predictions=None, data_format='pairs',
            block_diagonal=False, dp_rank=None, dp_world=None):
    import jax
    import optax

    from ..loader import (get_bert_pretrain_data_loader,
                          get_packed_pretrain_data_loader)
    from ..models import BertForPretraining
    from ..parallel import make_train_step
    from ..parallel.train import init_params

    model = BertForPretraining(model_cfg, mesh=mesh)
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    tx = optax.adamw(schedule, weight_decay=weight_decay)
    # Overridable for elastic resume: a fleet reformed at a different
    # world size passes its new coordinates explicitly (and the file-
    # backend multi-rank tests run several dp ranks inside independent
    # single-process jax worlds).
    dp_rank = jax.process_index() if dp_rank is None else dp_rank
    dp_world = jax.process_count() if dp_world is None else dp_world
    if block_diagonal and data_format != 'packed':
      raise ValueError("block_diagonal requires data_format='packed' "
                       '(pair shards carry no doc_offsets)')
    if path is None:
      # Loader-free loop: replay feeds batches from a hermetic bundle
      # (lddl-replay step --bundle), so no corpus is needed on disk.
      loader = None
    elif data_format == 'packed':
      # Long-context document-packed shards (preprocess_packed_pretrain):
      # always dynamic masking, no NSP pairs.
      if masking != 'dynamic':
        raise ValueError("data_format='packed' supports masking='dynamic' "
                         'only (no stored masks in packed shards)')
      loader = get_packed_pretrain_data_loader(
          path,
          dp_rank=dp_rank,
          dp_world_size=dp_world,
          batch_size_per_rank=batch_size_per_rank,
          tokenizer=tokenizer,
          max_seq_length=max_seq_length,
          bin_size=bin_size,
          base_seed=seed,
          samples_seen=samples_seen,
          block_diagonal=block_diagonal,
          **(loader_kwargs or {}))
    else:
      loader = get_bert_pretrain_data_loader(
          path,
          dp_rank=dp_rank,
          dp_world_size=dp_world,
          batch_size_per_rank=batch_size_per_rank,
          tokenizer=tokenizer,
          masking=masking,
          max_seq_length=max_seq_length,
          bin_size=bin_size,
          base_seed=seed,
          samples_seen=samples_seen,
          **(loader_kwargs or {}))
    params = init_params(model, mesh, jax.random.key(seed),
                         seq_len=min(128, max_seq_length))
    opt_state = _place_opt_state(jax.jit(tx.init)(params), params, mesh)
    if max_predictions is not None:
      from ..parallel.train import check_max_predictions
      check_max_predictions(
          max_predictions, max_seq_length, masking,
          mlm_probability=(loader_kwargs or {}).get('mlm_probability', 0.15))
    step_fn = make_train_step(model, tx, mesh,
                              max_predictions=max_predictions)
    global_batch = batch_size_per_rank * dp_world
    from ..models.flops import bert_pretrain_flops_per_step
    flops_fn = functools.partial(bert_pretrain_flops_per_step, model_cfg,
                                 max_predictions=max_predictions)
    return cls(model=model, tx=tx, mesh=mesh, loader=loader, params=params,
               opt_state=opt_state, rng=jax.random.key(seed + 1),
               step_fn=step_fn, samples_seen=samples_seen,
               step=samples_seen // global_batch, flops_fn=flops_fn,
               dp_rank=dp_rank, dp_world=dp_world)

  # ---- checkpointing ----

  def _manager(self, ckpt_dir, keep=3):
    import orbax.checkpoint as ocp
    return ocp.CheckpointManager(
        os.path.abspath(ckpt_dir),
        options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                             create=True))

  def save(self, ckpt_dir, keep=3, writer=None):
    """Write (params, opt_state, rng, counters) at the current step.

    With ``writer`` (an :class:`~lddl_tpu.training.elastic.
    AsyncCheckpointWriter`) the orbax write runs on the background
    thread over a donation-safe snapshot taken here, synchronously —
    the jitted step donates params/opt_state, so the *next* step call
    invalidates the live buffers and the copy cannot wait for the
    writer. Submit blocks only at the writer's bounded depth; a failed
    background write surfaces on the next :meth:`save`/``raise_pending``
    /``flush`` (first-error-wins).
    """
    import jax
    state = {'params': self.params, 'opt_state': self.opt_state,
             'rng': jax.random.key_data(self.rng)}
    meta = {'samples_seen': self.samples_seen, 'step': self.step}
    from ..telemetry.ledger import get_ledger
    ledger = get_ledger()
    if writer is not None:
      from ..parallel.train import snapshot_for_checkpoint
      from ..telemetry import get_telemetry
      snap = snapshot_for_checkpoint(state)
      if ledger.enabled:
        self._record_step_fingerprint(ledger, snap)
      writer.submit(self._write_ckpt, ckpt_dir, keep, self.step, snap, meta)
      get_telemetry().gauge('train.ckpt_backlog').set(writer.backlog)
    else:
      if ledger.enabled:
        from ..parallel.train import snapshot_for_checkpoint
        self._record_step_fingerprint(ledger,
                                      snapshot_for_checkpoint(state))
      self._write_ckpt(ckpt_dir, keep, self.step, state, meta)
    self._last_saved = self.step
    return self.step

  def _record_step_fingerprint(self, ledger, snap):
    """The ``step`` ledger boundary: a content fingerprint of the full
    train state (params + opt_state + rng, the donation-safe host
    snapshot the checkpoint writer serializes) at every checkpoint
    boundary, keyed by global step. Train state is rank-identical after
    the gradient all-reduce, so this is the boundary the cross-rank
    divergence verdict compares by default — and the one that catches a
    resumed/resharded run whose arithmetic drifted from the parent.
    Digest arithmetic lives in the module-level
    :func:`state_fingerprint` (shared with :mod:`lddl_tpu.replay`)."""
    digest = state_fingerprint(snap)
    coords = {'step': self.step, 'samples': self.samples_seen}
    if self._last_loss is not None:
      coords['loss'] = self._last_loss
    ledger.record('step', digest, **coords)

  def _write_ckpt(self, ckpt_dir, keep, step, state, meta):
    """The actual orbax write — runs inline (sync save) or on the
    async writer's thread, where a raised fault/IO error is retained
    first-error-wins instead of crashing the step loop."""
    import orbax.checkpoint as ocp
    from ..core import faults
    faults.inject('train.ckpt', rank=self.dp_rank)
    mngr = self._manager(ckpt_dir, keep)
    mngr.save(
        step,
        args=ocp.args.Composite(
            state=ocp.args.StandardSave(state),
            meta=ocp.args.JsonSave(meta)))
    mngr.wait_until_finished()
    mngr.close()

  @staticmethod
  def latest_meta(ckpt_dir, max_step=None):
    """(step, samples_seen) of the newest *readable* checkpoint, or None.

    ``max_step`` bounds the search to steps <= it — replay/bisect
    restores the newest ancestor of a target step this way.

    Robust by design — this is the first call of every restarted rank:
    directory reads retry transient IO errors with the comm layer's
    bounded backoff, and a half-finished newest step (a preemption
    landing mid-write) is skipped in favor of the next-older complete
    one rather than failing the resume.
    """
    import orbax.checkpoint as ocp

    from ..comm.backend import _retry_io
    if not os.path.isdir(ckpt_dir):
      return None
    mngr = _retry_io(
        lambda: ocp.CheckpointManager(os.path.abspath(ckpt_dir)),
        'open checkpoint dir')
    try:
      steps = sorted(_retry_io(mngr.all_steps, 'list checkpoint steps'),
                     reverse=True)
      if max_step is not None:
        steps = [s for s in steps if s <= max_step]
      for step in steps:
        try:
          meta = mngr.restore(step, args=ocp.args.Composite(
              meta=ocp.args.JsonRestore()))['meta']
          return meta['step'], meta['samples_seen']
        except Exception as e:
          # A half-written step dir (preemption mid-write): fall back to
          # the next-older step instead of failing the whole resume.
          logging.getLogger('lddl_tpu').warning(
              'checkpoint step %s in %s unreadable (%s: %s); trying an '
              'older step', step, ckpt_dir, type(e).__name__, e)
          continue
      return None
    finally:
      mngr.close()

  def restore(self, ckpt_dir, step=None):
    """Restore sharded state from a checkpoint in ``ckpt_dir``.

    ``step=None`` restores the newest step; an explicit ``step``
    restores that exact checkpoint (the time-travel entry point —
    ``lddl-replay`` restores ``S - 1`` to re-execute step ``S``). The
    device state lands on the loop's existing shardings, which may
    belong to a *different* mesh than the one the checkpoint was written
    on — ``build()`` lays the template tree out canonically on whatever
    mesh the resumed run has, and every restored leaf is re-placed
    through :func:`~lddl_tpu.parallel.mesh.reshard_pytree`, so
    world-size-changing resume (2 ranks die, restart on 1; or scale
    1 -> 8) is the same code path as same-size resume. The loader (when
    the loop has one) is re-seeked to the restored ``samples_seen``
    through the public positioning contract, so restoring an *older*
    step also rewinds the data stream.
    """
    import jax
    import orbax.checkpoint as ocp

    from ..comm.backend import _retry_io
    from ..parallel import reshard_pytree
    mngr = self._manager(ckpt_dir)
    if step is None:
      step = _retry_io(mngr.latest_step, 'find latest checkpoint')
    elif step not in _retry_io(mngr.all_steps, 'list checkpoint steps'):
      mngr.close()
      raise FileNotFoundError(
          f'no checkpoint for step {step} under {ckpt_dir}')
    if step is None:
      raise FileNotFoundError(f'no checkpoint under {ckpt_dir}')
    target = {'params': self.params, 'opt_state': self.opt_state,
              'rng': jax.random.key_data(self.rng)}
    restored = _retry_io(
        lambda: mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(target),
                meta=ocp.args.JsonRestore())), 'restore checkpoint')
    mngr.close()

    # Re-place every leaf onto the template's sharding: orbax restores
    # unsharded scalars (e.g. the optimizer step count) onto a single
    # device, which would then conflict with the mesh-sharded params
    # inside the jitted step — and on a resized fleet the template's
    # mesh is the *new* topology the leaves must land on.
    self.params = reshard_pytree(restored['state']['params'], self.mesh,
                                 like=self.params)
    self.opt_state = reshard_pytree(restored['state']['opt_state'],
                                    self.mesh, like=self.opt_state)
    # Replicate the restored key over the mesh: orbax hands back an array
    # committed to one device, and a committed single-device key conflicts
    # with mesh-sharded params inside the jitted step (a fresh
    # jax.random.key is uncommitted, so the bug only bites after restore
    # on multi-device meshes).
    from jax.sharding import NamedSharding, PartitionSpec
    self.rng = jax.device_put(
        jax.random.wrap_key_data(restored['state']['rng']),
        NamedSharding(self.mesh, PartitionSpec()))
    self.step = restored['meta']['step']
    self.samples_seen = restored['meta']['samples_seen']
    self._last_saved = self.step  # this step already exists on disk
    from .elastic import reseek_loader
    reseek_loader(self.loader, self.samples_seen, self.dp_world)
    return self

  def state_digest(self):
    """:func:`state_fingerprint` of the loop's live train state — equal
    to the ledger's ``step`` record when the loop sits at that step."""
    import jax

    from ..parallel.train import snapshot_for_checkpoint
    return state_fingerprint(snapshot_for_checkpoint(
        {'params': self.params, 'opt_state': self.opt_state,
         'rng': jax.random.key_data(self.rng)}))

  # ---- the loop ----

  def run(self, max_steps, ckpt_dir=None, ckpt_every=0, log_every=50,
          prefetch=2, membership=None, async_ckpt=None):
    """Train until ``max_steps`` (global); returns per-step loss list.

    Preemption-tolerant: a SIGTERM (or ``LDDL_PREEMPTION_FILE`` notice)
    stops the loop at the next step boundary behind one final
    synchronous checkpoint; a :class:`~lddl_tpu.training.elastic.
    RankMembership` passed as ``membership`` is polled at its heartbeat
    cadence and any fleet event (dead peer, shed verdict) likewise
    stops the loop checkpointed, with :attr:`stop_reason` telling the
    supervisor why. ``async_ckpt`` overrides ``LDDL_ASYNC_CKPT``:
    in-loop checkpoints ride the background writer, overlapping orbax
    IO with compute.
    """
    import jax

    from ..core import faults
    from ..loader.device import prefetch_to_device
    from ..telemetry import get_telemetry
    from ..telemetry.profiling import get_step_profiler
    from ..telemetry.sentinel import get_sentinel
    from ..telemetry.server import maybe_start_monitor
    from ..telemetry.trace import get_tracer
    from .elastic import (AsyncCheckpointWriter, PreemptionGuard,
                          async_ckpt_enabled)
    from .flight import get_flight_recorder

    # Live metrics endpoint (LDDL_MONITOR): no-op singleton when unset.
    maybe_start_monitor(rank=max(jax.process_index(), 0))
    # GET /profile?steps=N arms this; unarmed on_step() is two attribute
    # reads, so the hook costs nothing on unwatched runs.
    profiler = get_step_profiler()
    # Streaming anomaly sentinels + black-box recorder (LDDL_SENTINEL):
    # both resolve to shared no-op singletons when the gate is off.
    sentinel = get_sentinel()
    flight = get_flight_recorder()
    # A non-finite loss stops the run *regardless* of the sentinel gate
    # — training on garbage is never the right default. LDDL_NONFINITE=
    # ignore restores the old behavior (e.g. for loss-scaling probes).
    nonfinite_stop = (os.environ.get('LDDL_NONFINITE', '')
                      .strip().lower() != 'ignore')
    global_batch = self.loader.batch_size * max(self.dp_world, 1)
    tele = get_telemetry()
    tracer = get_tracer()
    data_wait_h = tele.histogram('train.data_wait_seconds')
    compute_h = tele.histogram('train.compute_seconds')
    step_h = tele.histogram('train.step_seconds')
    steps_c = tele.counter('train.steps')
    samples_c = tele.counter('train.samples')
    grad_norm_g = tele.gauge('train.grad_norm')
    tiles_total_c = tele.counter('train.attn_tiles_total')
    tiles_skipped_c = tele.counter('train.attn_tiles_skipped')
    peak_total = _peak_flops_total() if tele.enabled else None
    if _step_cache_enabled() and not isinstance(self.step_fn,
                                                CompiledStepCache):
      # Persisted on the loop (not run()-local) so repeated run() calls —
      # and every epoch within one — keep the warm per-bin executables.
      self.step_fn = CompiledStepCache(self.step_fn)
    self.stop_reason = None
    use_async = async_ckpt_enabled() if async_ckpt is None else async_ckpt
    writer = AsyncCheckpointWriter() if (ckpt_dir and use_async) else None
    guard = PreemptionGuard().install()
    # Membership poll cadence + the steps_per_sec window it publishes.
    poll_at = time.monotonic()
    rate_anchor = (self.step, time.monotonic())
    losses = []
    try:
      while self.step < max_steps and self.stop_reason is None:
        # The flight recorder tees the *host* iterator (device arrays
        # can't be packed); ordinal0 = the global step the next batch
        # feeds, so ring entries carry their ledger collate coordinate.
        stream = prefetch_to_device(
            flight.wrap_host_stream(iter(self.loader), self.loader,
                                    ordinal0=self.step),
            mesh=self.mesh, size=prefetch)
        t0 = time.perf_counter()
        steps_this_epoch = 0
        while True:
          # Pull the batch explicitly so the stall waiting on the input
          # pipeline (data wait) is timed separately from the step itself:
          # the split is the report's loader-vs-compute bottleneck signal.
          t_wait = time.perf_counter()
          tm_wait = time.monotonic() if tracer.enabled else 0.0
          try:
            batch = next(stream)
          except StopIteration:
            break
          t_step = time.perf_counter()
          tm_step = time.monotonic() if tracer.enabled else 0.0
          if tracer.enabled:
            tracer.complete('train.data_wait', tm_wait, tm_step - tm_wait,
                            args={'step': self.step})
          data_wait_h.observe(t_step - t_wait)
          # After the batch pull, before the step: a 'kill' here models a
          # rank dying mid-training, a 'term' models the preemption notice.
          faults.inject('train.step', rank=self.dp_rank)
          steps_this_epoch += 1
          step_no = self.step
          self.params, self.opt_state, metrics = self.step_fn(
              self.params, self.opt_state, self.rng, batch)
          # float() blocks until the device finishes the step, so the
          # compute span covers real execution, not just dispatch.
          loss = float(metrics['loss'])
          # The loss read above already paid the device sync; this one
          # is a host copy of an already-materialized scalar.
          gn = metrics.get('grad_norm')
          grad_norm = float(gn) if gn is not None else None
          losses.append(loss)
          self._last_loss = loss
          self.step += 1
          self.samples_seen += global_batch
          if not math.isfinite(loss) and nonfinite_stop:
            # Stop at the step boundary behind the trailing emergency
            # checkpoint (the preemption stop path) instead of training
            # on garbage. LDDL_NONFINITE=ignore opts out.
            self.stop_reason = 'nonfinite_loss'
          data_wait = t_step - t_wait
          trigger = sentinel.observe_step(step_no, loss=loss,
                                          grad_norm=grad_norm,
                                          data_wait=data_wait)
          flight.record_step(step_no, loss=loss, grad_norm=grad_norm,
                             data_wait=data_wait)
          if trigger is not None:
            incident = flight.capture(trigger)
            if incident:
              print(f'sentinel: {trigger["detector"]} fired at step '
                    f'{step_no} — incident captured to {incident}')
            else:
              print(f'sentinel: {trigger["detector"]} fired at step '
                    f'{step_no} ({trigger["reason"]})')
          finished_trace = profiler.on_step()
          if finished_trace:
            print(f'profiler: wrote trace for step {self.step} window to '
                  f'{finished_trace}')
          if tracer.enabled:
            tm_now = time.monotonic()
            tracer.complete('train.compute', tm_step, tm_now - tm_step,
                            args={'step': step_no})
            tracer.counter('train.samples_per_sec',
                           self.loader.batch_size / max(tm_now - tm_wait,
                                                        1e-9))
          if tele.enabled:
            now = time.perf_counter()
            compute_h.observe(now - t_step)
            step_h.observe(now - t_wait)
            steps_c.add(1)
            samples_c.add(self.loader.batch_size)
            if grad_norm is not None:
              grad_norm_g.set(grad_norm)
            tele.gauge('train.samples_per_sec').set(
                self.loader.batch_size / max(now - t_wait, 1e-9))
            if peak_total:
              # Prefer XLA's own cost model (captured at compile time by
              # the step cache) over the analytic estimate: the measured
              # numerator reflects fusion, remat, and the real partitioned
              # program, so MFU stops drifting from what the chip ran.
              measured = getattr(self.step_fn, 'last_costs', None)
              if measured is not None:
                numerator = measured[0]
              elif self.flops_fn is not None:
                b, s = batch['input_ids'].shape
                numerator = self.flops_fn(b, s)
              else:
                numerator = None
              if numerator:
                tele.gauge('train.mfu').set(
                    numerator / (max(now - t_wait, 1e-9) * peak_total))
            if 'segment_ids' in batch:
              # Host-side mirror of the kernel's tile-skip rule: the
              # goodput signal for how much attention work block-diagonal
              # packing actually removed this step.
              import numpy as np

              from ..ops.flash_attention import count_skippable_tiles
              total, skipped = count_skippable_tiles(
                  np.asarray(batch['segment_ids']))
              tiles_total_c.add(total)
              tiles_skipped_c.add(skipped)
          if log_every and self.step % log_every == 0:
            dt = time.perf_counter() - t0
            t0 = time.perf_counter()
            print(f'step={self.step} loss={loss:.4f} '
                  f'samples_seen={self.samples_seen} '
                  f'({log_every * global_batch / max(dt, 1e-9):.1f} '
                  'samples/s)')
          if writer is not None:
            # First-error-wins: a checkpoint that died in the background
            # fails the run at the next step, not at the final flush.
            writer.raise_pending()
          if guard.requested:
            self.stop_reason = 'preempted'
          elif membership is not None:
            now_m = time.monotonic()
            # lddl: noqa[LDA003] membership poll cadence: the clock only
            # rate-limits lease-store sweeps to one per heartbeat interval;
            # a late poll delays noticing an already-recorded fleet event,
            # it never changes any rank's verdict.
            if now_m >= poll_at:
              poll_at = now_m + membership.interval
              w_step, w_t = rate_anchor
              membership.publish_signals(
                  {'steps_per_sec':
                   (self.step - w_step) / max(now_m - w_t, 1e-9)})
              rate_anchor = (self.step, now_m)
              # Conditional assign: a quiet poll (None) must not wipe a
              # stop reason an earlier check set (e.g. nonfinite_loss).
              reason = membership.poll()
              if reason is not None:
                self.stop_reason = reason
          if self.stop_reason is not None:
            break
          if ckpt_dir and ckpt_every and self.step % ckpt_every == 0:
            self.save(ckpt_dir, writer=writer)
            flight.note_checkpoint(ckpt_dir, self.step)
          if self.step >= max_steps:
            break
        stream.close()
        if steps_this_epoch == 0 and self.stop_reason is None:
          raise ValueError(
              'loader yielded zero batches for a full epoch (dataset smaller '
              'than one global batch?); refusing to spin — reduce '
              '--batch-size or provide more data')
      # A capture armed near the end of the run may still be tracing; jax
      # allows one trace per process, so close it before returning.
      profiler.close()
      if writer is not None:
        # Bounded by the already-submitted saves; raises the first
        # retained background failure.
        writer.flush()
      # Skip when the in-loop ckpt_every save (or the restore we started
      # from) already covers this step: orbax refuses duplicate steps.
      # After a preemption or membership stop this synchronous trailing
      # save IS the emergency checkpoint — complete before the return.
      if ckpt_dir and self._last_saved != self.step:
        self.save(ckpt_dir)
        flight.note_checkpoint(ckpt_dir, self.step)
    finally:
      guard.uninstall()
      if writer is not None:
        # Idempotent after flush(); raise_errors=False so cleanup
        # never masks an exception already propagating.
        writer.close(raise_errors=False)
    if self.stop_reason is not None:
      print(f'stopping early: {self.stop_reason} '
            f'(step={self.step} samples_seen={self.samples_seen})')
    return losses


def _peak_flops_total():
  """Per-process peak FLOP/s for the MFU denominator: per-device peak x
  local device count. ``LDDL_PEAK_TFLOPS`` (per device, in TFLOP/s)
  overrides the chip table — required on hosts the table cannot identify
  (CPU runs, unreleased chips), where it returns None and MFU is
  omitted."""
  import jax

  from ..models.flops import peak_flops_per_device
  env = os.environ.get('LDDL_PEAK_TFLOPS')
  per_device = float(env) * 1e12 if env else peak_flops_per_device()
  if not per_device:
    return None
  return per_device * jax.local_device_count()


def export_telemetry(comm):
  """Per-rank JSONL + rank-0 merged stall report, when telemetry is on.

  Every rank writes ``telemetry.rank<R>.jsonl`` under
  ``LDDL_TELEMETRY_DIR`` (skipped when unset), then the snapshots are
  merged over the run's own comm backend and rank 0 prints the
  cross-rank report. When ``LDDL_TRACE`` is on, the rank's event buffer
  is exported to ``trace.rank<R>.jsonl`` alongside (merge offline with
  ``telemetry-trace``). No-op (and free) when both are off.
  """
  from ..telemetry import get_telemetry, rank_file_name
  from ..telemetry.trace import get_tracer, trace_file_name
  tele = get_telemetry()
  tracer = get_tracer()
  out_dir = os.environ.get('LDDL_TELEMETRY_DIR')
  if tracer.enabled and out_dir:
    os.makedirs(out_dir, exist_ok=True)
    tracer.set_identity(rank=comm.rank)
    tracer.write_jsonl(trace_file_name(out_dir, comm.rank), rank=comm.rank)
  if not tele.enabled:
    return None
  if out_dir:
    os.makedirs(out_dir, exist_ok=True)
    tele.write_jsonl(rank_file_name(out_dir, comm.rank), rank=comm.rank)
  from ..telemetry.report import aggregate_over_comm, render_report
  merged = aggregate_over_comm(comm)
  if comm.rank == 0:
    print(render_report(merged))
  return merged


MODEL_SIZES = {
    'tiny': dict(hidden_size=128, num_layers=2, num_heads=2,
                 intermediate_size=512),
    'base': dict(hidden_size=768, num_layers=12, num_heads=12,
                 intermediate_size=3072),
    'large': dict(hidden_size=1024, num_layers=24, num_heads=16,
                  intermediate_size=4096),
}


def attach_args(parser):
  parser.add_argument('--path', required=True, help='balanced shard dir')
  parser.add_argument('--vocab-file', default=None)
  parser.add_argument('--tokenizer', default=None)
  parser.add_argument('--model', choices=sorted(MODEL_SIZES),
                      default='base')
  parser.add_argument('--attention',
                      choices=['dense', 'flash', 'ring', 'ring_flash'],
                      default='dense')
  parser.add_argument('--remat', action='store_true')
  parser.add_argument('--prng', default='threefry',
                      choices=['threefry', 'rbg'],
                      help="jax PRNG impl; 'rbg' makes per-step dropout "
                      'draws ~free on TPU (+2 MFU points measured at '
                      's=512, benchmarks/results/mfu_v5e_scan_512_r5.txt)')
  parser.add_argument('--dp', type=int, default=1)
  parser.add_argument('--fsdp', type=int, default=1)
  parser.add_argument('--tp', type=int, default=1)
  parser.add_argument('--sp', type=int, default=1)
  parser.add_argument('--batch-size', type=int, default=64,
                      help='per-process samples per step')
  parser.add_argument('--bin-size', type=int, default=None)
  parser.add_argument('--max-seq-length', type=int, default=512)
  parser.add_argument('--masking', choices=['dynamic', 'static'],
                      default='dynamic')
  parser.add_argument('--data-format', choices=['pairs', 'packed'],
                      default='pairs',
                      help="'pairs': NSP-pair shards (preprocess_bert_"
                      "pretrain); 'packed': long-context document-packed "
                      'id shards (preprocess_packed_pretrain, s=8k-32k)')
  parser.add_argument('--block-diagonal', action='store_true',
                      help="packed rows only: decode per-doc segment ids "
                      'from the stored doc_offsets, restrict attention to '
                      'within-document pairs (flash/ring skip cross-doc '
                      'tiles), and normalize the MLM loss per document '
                      '(arXiv:2107.02027)')
  parser.add_argument('--steps', type=int, default=1000)
  parser.add_argument('--learning-rate', type=float, default=1e-4)
  parser.add_argument('--warmup-steps', type=int, default=100)
  parser.add_argument('--weight-decay', type=float, default=0.01)
  parser.add_argument('--seed', type=int, default=127)
  parser.add_argument('--max-predictions', type=int, default=None,
                      help='masked-only MLM head: compute vocab logits '
                           'only at this many gathered MLM positions '
                           'per row (identical loss, ~6x less head '
                           'compute/HBM; size generously for dynamic '
                           'masking)')
  parser.add_argument('--checkpoint-dir', default=None)
  parser.add_argument('--checkpoint-every', type=int, default=500)
  parser.add_argument('--log-every', type=int, default=50)
  parser.add_argument('--resume', action='store_true',
                      help='resume from the newest checkpoint in '
                           '--checkpoint-dir (model state AND data '
                           'stream position)')
  parser.add_argument('--comm', choices=['null', 'file', 'jax'],
                      default='null')
  return parser


def main(args=None):
  if args is None or isinstance(args, list):
    args = attach_args(argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)).parse_args(
            args)
  import jax

  if getattr(args, 'prng', 'threefry') != 'threefry':
    jax.config.update('jax_default_prng_impl', args.prng)

  from ..comm import get_backend
  from ..models import BertConfig
  from ..parallel import make_mesh, mesh_summary
  from ..tokenization.wordpiece import load_bert_tokenizer

  comm = get_backend(args.comm)  # bootstraps jax.distributed under --comm jax
  from ..telemetry.trace import get_tracer
  tracer = get_tracer()
  if tracer.enabled:
    # Identity up front, so the periodic crash-tail flushes during the
    # run already land at this rank's canonical trace file.
    tracer.set_identity(rank=comm.rank)
  tokenizer = load_bert_tokenizer(
      vocab_file=args.vocab_file, hub_name=args.tokenizer, backend='hf')
  vocab = ((tokenizer.vocab_size + 63) // 64) * 64
  cfg = BertConfig(
      vocab_size=vocab,
      max_position_embeddings=max(args.max_seq_length, 512),
      attention_impl=args.attention,
      remat=args.remat,
      **MODEL_SIZES[args.model])
  mesh = make_mesh(data=args.dp, fsdp=args.fsdp, tensor=args.tp,
                   seq=args.sp)
  print(f'mesh: {mesh_summary(mesh)}; model={args.model} '
        f'attention={args.attention}')

  samples_seen = 0
  resume = False
  if args.resume and args.checkpoint_dir:
    meta = TrainLoop.latest_meta(args.checkpoint_dir)
    if meta is not None:
      _, samples_seen = meta
      resume = True
      print(f'resuming from samples_seen={samples_seen}')

  loop = TrainLoop.build(
      args.path, tokenizer, model_cfg=cfg, mesh=mesh,
      learning_rate=args.learning_rate, warmup_steps=args.warmup_steps,
      total_steps=args.steps, weight_decay=args.weight_decay,
      batch_size_per_rank=args.batch_size, bin_size=args.bin_size,
      max_seq_length=args.max_seq_length, masking=args.masking,
      seed=args.seed, samples_seen=samples_seen,
      max_predictions=args.max_predictions,
      data_format=args.data_format,
      block_diagonal=args.block_diagonal)
  if resume:
    loop.restore(args.checkpoint_dir)
  from .elastic import maybe_membership
  membership = maybe_membership(comm, step=loop.step)
  try:
    losses = loop.run(args.steps, ckpt_dir=args.checkpoint_dir,
                      ckpt_every=args.checkpoint_every,
                      log_every=args.log_every, membership=membership)
  finally:
    if membership is not None:
      membership.stop()
  export_telemetry(comm)
  if losses:
    print(json.dumps({'final_step': loop.step,
                      'final_loss': round(losses[-1], 4),
                      'samples_seen': loop.samples_seen}))
  return loop


if __name__ == '__main__':
  main()

"""Preemption-tolerant elastic training: the robustness substrate the
train loop rides (PR 9 built the same machinery for preprocessing).

Three pieces, composable and individually env-gated:

- :class:`AsyncCheckpointWriter` — orbax saves overlapped with compute
  on :class:`~lddl_tpu.pipeline.pool.AsyncShardWriter`'s bounded-depth /
  first-error-wins write-back discipline (``LDDL_ASYNC_CKPT``). The
  step loop only ever blocks at backpressure (a full queue) and a lost
  background write surfaces as an exception on the very next step.
- :class:`PreemptionGuard` — SIGTERM (and an optional maintenance-
  notice file, ``LDDL_PREEMPTION_FILE``) sets a flag the step loop
  checks at every step boundary; the loop then flushes the writer and
  lands one final synchronous emergency checkpoint before the host
  dies.
- :class:`RankMembership` — lease-store-backed train-fleet membership
  on the comm layer's :class:`~lddl_tpu.comm.HeartbeatPump` + positive-
  death-probe machinery. Detects a dead rank within a heartbeat
  interval (pid beacon on same-host worlds, counter staleness across
  hosts), and feeds the fleet's published progress signals through the
  pure :func:`~lddl_tpu.telemetry.live.straggler_scores` arithmetic to
  a CAS-arbitrated verdict that sheds a sick rank instead of hanging
  on it.

The recovery policy is **checkpoint-and-reform**: any membership event
(dead rank, shed verdict, preemption notice) stops every surviving rank
at the next step boundary behind a complete checkpoint, and the job
supervisor relaunches the fleet — at any world size — where each rank
rejoins by restoring that checkpoint. World-size-changing resume works
because the checkpoint's ``samples_seen`` counter is global (world-
size-independent) and restore re-places state onto the new mesh
(:func:`~lddl_tpu.parallel.mesh.reshard_pytree`).
"""

import json
import os
import signal
import threading
import time

from ..comm.backend import HeartbeatPump, comm_heartbeat_interval
from ..core import faults
from ..pipeline.pool import AsyncShardWriter, WriteBackError  # noqa: F401
from ..telemetry import get_telemetry


def async_ckpt_enabled():
  """Background checkpoint write-back (env ``LDDL_ASYNC_CKPT``,
  default off: synchronous saves are the conservative baseline — see
  PERF.md for the measured overlap win)."""
  return os.environ.get('LDDL_ASYNC_CKPT', '').strip().lower() in (
      '1', 'true', 'on', 'yes')


def _async_ckpt_depth():
  """Bounded queue depth for in-flight checkpoints (env
  ``LDDL_ASYNC_CKPT_DEPTH``, default 1: one checkpoint writing while
  the next accumulates — each queued save holds a full state snapshot
  in host memory, so depth is deliberately tiny)."""
  try:
    return max(1, int(os.environ.get('LDDL_ASYNC_CKPT_DEPTH', '1')))
  except ValueError:
    return 1


def elastic_train_enabled(comm):
  """Whether the train loop should run lease-based rank membership
  (env ``LDDL_ELASTIC_TRAIN``): '0'/'1' force it, unset/auto enables it
  only where the claim substrate is first-class (the backend's
  ``elastic_default``, today the FileBackend)."""
  v = os.environ.get('LDDL_ELASTIC_TRAIN', '').strip().lower()
  if v in ('0', 'false', 'off', 'no'):
    return False
  if v in ('1', 'true', 'on', 'yes'):
    return True
  return getattr(comm, 'elastic_default', False)


def shed_threshold():
  """Straggler score (fleet-median rate / own rate) at or above which
  the fleet sheds the slowest rank (env ``LDDL_SHED_SCORE``; 0/unset
  disables shedding — death detection alone never needs it)."""
  try:
    return max(0.0, float(os.environ.get('LDDL_SHED_SCORE', '0')))
  except ValueError:
    return 0.0


def reseek_loader(loader, samples_seen, dp_world=1):
  """Position ``loader`` at the global ``samples_seen`` counter via the
  public ``seek(epoch, batch_index)`` contract.

  The elastic resume path: a reformed fleet restores a checkpoint whose
  ``samples_seen`` is world-size-independent, and each rank's loader
  must continue from the matching ``(epoch, batch_index)`` coordinate —
  the same arithmetic as :meth:`~lddl_tpu.loader.binned.BinnedIterator.
  epoch_and_offset_of`, expressed against the loader protocol so every
  seekable loader (bert / packed / multiprocess / synthetic) resumes
  identically. Poking ``_batches_consumed`` directly is deprecated.

  Returns the ``(epoch, batch_index)`` it seeked to, or None for a
  loader that carries no positioning contract (raw iterables).
  """
  if loader is None or not hasattr(loader, 'seek'):
    return None
  global_batch = loader.batch_size * max(int(dp_world), 1)
  samples_per_epoch = loader.batches_per_epoch * global_batch
  if samples_per_epoch <= 0:
    return None
  epoch = samples_seen // samples_per_epoch
  index = (samples_seen % samples_per_epoch) // global_batch
  loader.seek(epoch, index)
  return epoch, index


class AsyncCheckpointWriter(AsyncShardWriter):
  """Background orbax-save lane: the shard writer's overlap-and-flush
  discipline pointed at checkpoints.

  Jobs are whole checkpoint writes (manager save + wait + close) over a
  donation-safe state snapshot taken synchronously at submit time
  (:func:`~lddl_tpu.parallel.train.snapshot_for_checkpoint`); the step
  loop overlaps the serialization/IO with compute and only blocks when
  ``LDDL_ASYNC_CKPT_DEPTH`` saves are already in flight. Completions
  bill ``train.ckpt_writes`` (not the pool's straggler counter); the
  queue depth is exported as the ``train.ckpt_backlog`` gauge by the
  submitter.
  """

  def __init__(self, max_pending=None):
    super().__init__(max_pending or _async_ckpt_depth(),
                     counter='train.ckpt_writes',
                     thread_name='lddl-ckpt-write')


class PreemptionGuard:
  """Turn a preemption notice into a flag the step loop can act on.

  SIGTERM is the TPU/GCE spot-instance contract (a grace window before
  the host dies); ``LDDL_PREEMPTION_FILE`` covers schedulers that
  signal maintenance by touching a file instead. The signal handler
  only sets an event — all real work (writer flush + emergency
  checkpoint) happens on the main thread at the next step boundary, so
  a signal landing mid-XLA-dispatch can never corrupt device state.
  Install/uninstall are no-ops off the main thread (Python restricts
  handler registration to it); the notice-file path still works there.
  """

  def __init__(self, signum=signal.SIGTERM, notice_file=None):
    self._signum = signum
    self._notice = (notice_file if notice_file is not None
                    else os.environ.get('LDDL_PREEMPTION_FILE') or None)
    self._flag = threading.Event()
    self._prev = None
    self._installed = False
    self._counted = False
    self._preempt_c = get_telemetry().counter('train.elastic.preemptions')

  def install(self):
    if threading.current_thread() is threading.main_thread():
      self._prev = signal.signal(self._signum, self._on_signal)
      self._installed = True
    return self

  def uninstall(self):
    if self._installed:
      signal.signal(self._signum,
                    self._prev if self._prev is not None else signal.SIG_DFL)
      self._installed = False

  def _on_signal(self, signum, frame):
    self._flag.set()

  @property
  def requested(self):
    """Whether a preemption notice has arrived (signal or notice file).
    Counted once into ``train.elastic.preemptions`` on first
    observation."""
    if not self._flag.is_set() and self._notice and \
        os.path.exists(self._notice):
      self._flag.set()
    if self._flag.is_set() and not self._counted:
      self._counted = True
      self._preempt_c.add(1)
    return self._flag.is_set()


class RankMembership:
  """Lease-store view of which train ranks are alive, slow, or shed.

  Key grammar (one namespace per run, ``train.membership``; rides the
  comm backend's :meth:`~lddl_tpu.comm.CommBackend.lease_store`)::

    member.rank<r>  json {'pid', 'joined_step'}   idempotent publish
    hb.rank<r>      ascii beat counter            HeartbeatPump
    sig.rank<r>     json windowed progress rates  idempotent publish
    shed.rank<r>    ascii proposer rank           CAS: one verdict winner

  Death detection reuses the lease substrate's two-tier discipline: the
  positive death probe (pid beacon, same-host worlds) fires within one
  poll; the heartbeat-counter staleness timeout (observer's own clock,
  skew-immune) backstops cross-host worlds. Shedding is deterministic
  fleet-wide because the inputs are *published* signals every rank
  reads identically, the score arithmetic
  (:func:`~lddl_tpu.telemetry.live.straggler_scores`) is pure, and the
  ``shed`` CAS picks exactly one verdict writer — ranks obey the CAS
  record, never their transient local computation.

  Membership only ever *observes*: no collectives, no unbounded waits
  (LDA009 root — survivors must make progress while a peer is dead).
  A restarted rank rejoins by republishing its member record and
  heartbeat (the changed counter un-ages it); records of ranks beyond
  the current world size are ignored, so a reformed smaller fleet is
  not haunted by the old incarnation's keys.
  """

  def __init__(self, store, rank, world, interval=None, timeout=None,
               shed_score=None, telemetry=None):
    from ..pipeline.executor import lease_timeout
    self._store = store
    self._rank = rank
    self._world = world
    self.interval = (comm_heartbeat_interval() if interval is None
                     else interval)
    self._timeout = lease_timeout() if timeout is None else timeout
    self._shed_score = shed_threshold() if shed_score is None else shed_score
    self._hb_seen = {}  # rank -> (counter value, monotonic when it changed)
    self._counted_dead = set()
    self._pump = None
    tele = telemetry if telemetry is not None else get_telemetry()
    self._dead_c = tele.counter('train.elastic.dead_ranks')
    self._sheds_c = tele.counter('train.elastic.sheds')
    self._rejoins_c = tele.counter('train.elastic.rejoins')

  def start(self, step=0):
    """Join the fleet: publish the member record and start the
    heartbeat pump. ``step > 0`` marks a rejoin (a restarted rank
    re-entering at the last checkpointed step)."""
    self._store.publish(
        f'member.rank{self._rank}',
        json.dumps({'pid': os.getpid(), 'joined_step': int(step)}).encode())
    if step > 0:
      self._rejoins_c.add(1)
    self._pump = HeartbeatPump(self._store, self.interval,
                               fault_site='train.heartbeat')
    return self

  def stop(self):
    if self._pump is not None:
      self._pump.stop()
      self._pump = None

  def members(self):
    """Ranks with a member record, restricted to the current world size
    (stale records from a larger previous incarnation are ignored)."""
    out = []
    for key in self._store.list('member.rank'):
      suffix = key[len('member.rank'):]
      if suffix.isdigit() and int(suffix) < self._world:
        out.append(int(suffix))
    return sorted(out)

  def _peer_stale(self, r):
    if self._store.owner_dead(r):
      return True  # positive death signal: no need to wait out the lease
    hb = self._store.read_heartbeat(r)
    now = time.monotonic()
    prev = self._hb_seen.get(r)
    if prev is None or prev[0] != hb:
      self._hb_seen[r] = (hb, now)
      return False
    # Staleness verdict: a peer is declared dead only on a heartbeat
    # counter silent past the lease timeout (or the positive death probe
    # above), measured on this observer's own clock. The consequence is
    # a checkpoint-and-stop every survivor reaches independently — clock
    # skew can cost an early reform, never divergent training state.
    return now - prev[1] > self._timeout

  def dead_ranks(self):
    """Peers that are positively dead or heartbeat-silent past the
    timeout (sorted; never includes this rank)."""
    return sorted(r for r in self.members()
                  if r != self._rank and self._peer_stale(r))

  def publish_signals(self, signals):
    """Publish this rank's windowed progress rates (the straggler
    inputs — e.g. ``{'steps_per_sec': 3.2}``)."""
    self._store.publish(f'sig.rank{self._rank}',
                        json.dumps(signals).encode())

  def read_signals(self):
    """All ranks' published signal dicts, ``{rank: signals}``."""
    out = {}
    for key in self._store.list('sig.rank'):
      suffix = key[len('sig.rank'):]
      if not suffix.isdigit() or int(suffix) >= self._world:
        continue
      raw = self._store.read(key)
      if raw is None:
        continue
      try:
        out[int(suffix)] = json.loads(raw)
      except (ValueError, UnicodeDecodeError):
        continue
    return out

  def propose_shed(self):
    """Score the fleet from published signals; CAS a shed verdict when
    the slowest rank's score reaches the threshold. Returns the rank a
    *new* verdict was recorded against (this proposer won the CAS), or
    None."""
    if self._shed_score <= 0:
      return None
    signals = self.read_signals()
    if len(signals) < 2:
      return None  # no fleet to compare against
    from ..telemetry.live import straggler_scores
    verdict = straggler_scores(signals)
    slowest = verdict['slowest']
    if slowest is None or verdict['scores'][slowest] < self._shed_score:
      return None
    if self._store.try_claim(f'shed.rank{slowest}') is None:
      self._sheds_c.add(1)
      return slowest
    return None

  def shed_ranks(self):
    """Ranks with a recorded shed verdict (sorted)."""
    out = []
    for key in self._store.list('shed.rank'):
      suffix = key[len('shed.rank'):]
      if suffix.isdigit() and int(suffix) < self._world:
        out.append(int(suffix))
    return sorted(out)

  def poll(self):
    """One membership sweep. Returns a stop-reason string when the
    fleet must checkpoint-and-reform (a peer died, or a shed verdict
    exists — including against this rank), else None."""
    self.propose_shed()
    shed = self.shed_ranks()
    if shed:
      return 'shed:rank' + ','.join(map(str, shed))
    dead = self.dead_ranks()
    new = [r for r in dead if r not in self._counted_dead]
    if new:
      self._counted_dead.update(new)
      self._dead_c.add(len(new))
    if dead:
      return 'dead_rank:' + ','.join(map(str, dead))
    return None


def maybe_membership(comm, step=0, **kwargs):
  """A started :class:`RankMembership` for this run's comm backend, or
  None when elastic training is off, the world is single-rank, or the
  backend has no lease substrate."""
  if comm.world_size <= 1 or not elastic_train_enabled(comm):
    return None
  store = comm.lease_store('train.membership')
  if store is None:
    return None
  return RankMembership(store, comm.rank, comm.world_size,
                        **kwargs).start(step=step)

"""Batched MLM masking over padded id matrices.

Semantics (per row, matching the reference recipe
``lddl/dask/bert/pretrain.py:182-238``): the row is the assembled
``[CLS] A [SEP] B [SEP]`` sequence; ``k = max(1, round(len * ratio))``
non-special positions are drawn uniformly without replacement; each drawn
position becomes ``[MASK]`` with p=0.8, a uniform-random vocab id with
p=0.1, or stays itself with p=0.1.

Two interchangeable backends with identical *semantics* but independent
RNG streams (bits differ; each is deterministic given its seed):
  - host: vectorized numpy using Philox counter RNG.
  - device: jit-compiled JAX using threefry, runs on the TPU. The whole
    partition is one ``[N, L]`` program — MXU-friendly static shapes,
    batch padded to a bucket size to bound recompilation.
"""

import os

import numpy as np


_LINK_OK_CACHE = {}


def _device_link_usable(min_mb_per_s=100.0):
  """One-time probe: is the host<->device link fast enough to win?

  Offloading pays for itself only when transfers beat the host's vectorized
  numpy path. On a real TPU-VM (PCIe, GB/s) this passes instantly; over a
  development tunnel (single-digit MB/s downloads) it fails and 'auto'
  stays on the host. Cached per process.
  """
  key = 'probe'
  if key in _LINK_OK_CACHE:
    return _LINK_OK_CACHE[key]
  import time
  import jax
  try:
    x = np.zeros((256, 1024), np.int32)  # 1 MB
    d = jax.device_put(x)
    d.block_until_ready()
    t0 = time.perf_counter()
    np.asarray(jax.device_put(x))
    dt = time.perf_counter() - t0
    ok = (2 * x.nbytes / 1e6) / dt >= min_mb_per_s
  except Exception:
    ok = False
  _LINK_OK_CACHE[key] = ok
  return ok


def resolve_mask_backend(backend='auto'):
  """'auto' -> 'device' when an accelerator with a usable host link is
  attached, else 'host'."""
  if backend != 'auto':
    return backend
  try:
    import jax
    platform = jax.default_backend()
  except Exception:
    return 'host'
  if platform not in ('tpu', 'gpu'):
    return 'host'
  return 'device' if _device_link_usable() else 'host'


def ragged_indices(lengths):
  """(row_idx, within_row_idx) index arrays for ragged row extraction."""
  lengths = np.asarray(lengths, dtype=np.int64)
  n = len(lengths)
  total = int(lengths.sum())
  starts = np.zeros(n, dtype=np.int64)
  np.cumsum(lengths[:-1], out=starts[1:])
  row_idx = np.repeat(np.arange(n, dtype=np.int64), lengths)
  col_idx = np.arange(total, dtype=np.int64) - np.repeat(starts, lengths)
  return row_idx, col_idx


def assemble_pair_matrix(flat_ids, a_ranges, b_ranges, cls_id, sep_id,
                         max_len, pad_id=0):
  """Assemble ``[CLS] A [SEP] B [SEP]`` rows into a padded int32 matrix.

  ``a_ranges``/``b_ranges``: int64 ``[N, 2]`` (start, end) index ranges
  into ``flat_ids``. Returns (ids_mat [N, max_len], row_len [N], na [N]).
  """
  a_ranges = np.asarray(a_ranges, dtype=np.int64).reshape(-1, 2)
  b_ranges = np.asarray(b_ranges, dtype=np.int64).reshape(-1, 2)
  n = len(a_ranges)
  na = (a_ranges[:, 1] - a_ranges[:, 0]).astype(np.int32)
  nb = (b_ranges[:, 1] - b_ranges[:, 0]).astype(np.int32)
  row_len = (na + nb + 3).astype(np.int32)
  if n and row_len.max() > max_len:
    raise ValueError(f'pair of {row_len.max()} tokens exceeds max_len '
                     f'{max_len}')
  mat = np.full((n, max_len), pad_id, dtype=np.int32)
  if n == 0:
    return mat, row_len, na
  rows = np.arange(n)
  na64, nb64 = na.astype(np.int64), nb.astype(np.int64)
  ra, ca = ragged_indices(na64)
  mat[ra, ca + 1] = flat_ids[a_ranges[ra, 0] + ca]
  rb, cb = ragged_indices(nb64)
  mat[rb, cb + 2 + na64[rb]] = flat_ids[b_ranges[rb, 0] + cb]
  mat[rows, 0] = cls_id
  mat[rows, 1 + na64] = sep_id
  mat[rows, row_len.astype(np.int64) - 1] = sep_id
  return mat, row_len, na


def _special_and_valid(ids_shape_l, row_len, na):
  pos = np.arange(ids_shape_l, dtype=np.int32)[None, :]
  row_len = row_len[:, None]
  na = na[:, None]
  is_special = (pos == 0) | (pos == 1 + na) | (pos == row_len - 1)
  valid = (pos < row_len) & ~is_special
  return valid


_TOPK_NATIVE = None  # None = unprobed, False = unavailable


def _select_topk(keys, k, n, l):
  """(rows, cols, picked_bool): the k[r] smallest keys of each row, in
  row-major ascending (row, col) order — identical to np.nonzero on the
  picked matrix. Native C++ per-row nth_element when the toolchain is
  available; numpy argpartition otherwise (same output)."""
  global _TOPK_NATIVE
  if _TOPK_NATIVE is None:
    try:
      from ..native.build import load_library
      _TOPK_NATIVE = load_library()
    except Exception:  # no toolchain: fall back quietly, like pairing
      _TOPK_NATIVE = False
  if _TOPK_NATIVE:
    import ctypes
    i64p = ctypes.POINTER(ctypes.c_int64)
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    # Clamp here, before the offsets are sized from k — the C++ clamp
    # alone would leave out-of-range rows with unwritten output slots.
    k64 = np.clip(np.asarray(k, dtype=np.int64), 0, l)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(k64, out=offsets[1:])
    cols = np.empty(int(offsets[-1]), dtype=np.int64)
    # Modest thread cap (wordpiece precedent): the executor already runs
    # one worker process per core, so per-call threads must not multiply
    # against that.
    _TOPK_NATIVE.lddl_mask_topk(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        k64.ctypes.data_as(i64p), n, l, offsets.ctypes.data_as(i64p),
        cols.ctypes.data_as(i64p), min(8, os.cpu_count() or 1))
    rows = np.repeat(np.arange(n, dtype=np.int64), k64)
    picked = np.zeros((n, l), dtype=bool)
    picked[rows, cols] = True
    return rows, cols, picked
  kmax = int(k.max())
  picked = np.zeros((n, l), dtype=bool)
  if kmax < l:
    part = np.argpartition(keys, kmax, axis=1)[:, :kmax]
    vals = np.take_along_axis(keys, part, axis=1)
    sel = np.take_along_axis(part, np.argsort(vals, axis=1), axis=1)
  else:
    sel = np.argsort(keys, axis=1)
  in_k = np.arange(sel.shape[1], dtype=np.int64)[None, :] < k[:, None]
  rr, cc = np.nonzero(in_k)
  picked[rr, sel[rr, cc]] = True
  pr, pc = np.nonzero(picked)
  return pr, pc, picked


def _philox4x32_np(c0, c1, c2, c3, k0, k1):
  """Vectorized Philox4x32-10 over uint32 arrays — the numpy mirror of
  ``philox4x32`` in ``native/src/masking.cpp`` (same round function and
  key schedule, bit-for-bit). Returns the four uint32 output lanes."""
  c0 = np.asarray(c0, np.uint32)
  c1 = np.asarray(c1, np.uint32)
  c2 = np.asarray(c2, np.uint32)
  c3 = np.asarray(c3, np.uint32)
  M0, M1 = np.uint64(0xD2511F53), np.uint64(0xCD9E8D57)
  for i in range(10):
    # Key schedule in Python ints (explicit uint32 wrap, no numpy
    # overflow warnings): round i uses (k0 + i*W0, k1 + i*W1).
    ki0 = np.uint32((int(k0) + i * 0x9E3779B9) & 0xffffffff)
    ki1 = np.uint32((int(k1) + i * 0xBB67AE85) & 0xffffffff)
    p0 = c0.astype(np.uint64) * M0
    p1 = c2.astype(np.uint64) * M1
    hi0, lo0 = (p0 >> np.uint64(32)).astype(np.uint32), p0.astype(np.uint32)
    hi1, lo1 = (p1 >> np.uint64(32)).astype(np.uint32), p1.astype(np.uint32)
    c0, c1, c2, c3 = hi1 ^ c1 ^ ki0, lo1, hi0 ^ c3 ^ ki1, lo0
  return c0, c1, c2, c3


# decide thresholds: floor(0.8 * 2**32) and floor(0.9 * 2**32).
_MASK_THRESHOLD = np.uint32(3435973836)
_RAND_THRESHOLD = np.uint32(3865470566)
_MASK_DOMAIN = np.uint32(0x6d61736b)  # "mask"


def _pick_counts(na, nb, masked_lm_ratio, max_predictions):
  """Per-row pick count: ``max(1, rint(row_len * ratio))`` clamped to the
  valid-position count and ``max_predictions`` (same clamp as
  :func:`mask_batch_host`)."""
  row_len = na + nb + 3
  k = np.maximum(1, np.rint(row_len * masked_lm_ratio).astype(np.int64))
  if max_predictions is not None:
    k = np.minimum(k, max_predictions)
  return np.minimum(k, na + nb)


def _mask_partition_numpy(flat_ids, a_ranges, b_ranges, na, nb, offs_a,
                          offs_b, k, offs_k, seed, vocab_size, mask_id):
  """Numpy mirror of ``lddl_mask_partition`` — identical draw scheme,
  bit-identical outputs (parity-tested). Vectorized across rows; the
  partial Fisher-Yates runs as ``kmax`` (~20) batched swap steps."""
  n = len(na)
  L = na + nb
  ra, ca = ragged_indices(na)
  flat_a = flat_ids[a_ranges[ra, 0] + ca]
  rb, cb = ragged_indices(nb)
  flat_b = flat_ids[b_ranges[rb, 0] + cb]
  total_k = int(offs_k[-1])
  if total_k == 0:
    return (flat_a, flat_b, np.zeros(0, np.uint16), np.zeros(0, np.int32))
  kmax = int(k.max())
  rows = np.arange(n, dtype=np.uint32)
  t_grid = np.arange(kmax, dtype=np.uint32)
  x0, x1, x2, _ = _philox4x32_np(
      np.broadcast_to(t_grid[None, :], (n, kmax)),
      np.broadcast_to(rows[:, None], (n, kmax)), _MASK_DOMAIN, np.uint32(0),
      np.uint32(seed & 0xffffffff), np.uint32((int(seed) >> 32) & 0xffffffff))
  # Partial Fisher-Yates over the valid-position indices [0, L).
  Lmax = int(L.max())
  arr = np.broadcast_to(np.arange(Lmax, dtype=np.int32), (n, Lmax)).copy()
  v_mat = np.zeros((n, kmax), dtype=np.int32)
  for t in range(kmax):
    act = np.nonzero(k > t)[0]
    span = (L[act] - t).astype(np.uint64)
    j = t + ((x0[act, t].astype(np.uint64) * span) >> np.uint64(32)).astype(
        np.int64)
    a_t = arr[act, t].copy()
    a_j = arr[act, j]
    arr[act, t] = a_j
    arr[act, j] = a_t
    v_mat[act, t] = a_j
  rand_mat = ((x2.astype(np.uint64) * np.uint64(vocab_size))
              >> np.uint64(32)).astype(np.int32)
  # Sort each row's picks by position (values are unique — no tie issue).
  active = t_grid[None, :] < k[:, None]
  v_sort = np.where(active, v_mat, np.iinfo(np.int32).max)
  order = np.argsort(v_sort, axis=1)
  v_sorted = np.take_along_axis(v_sort, order, axis=1)
  d_sorted = np.take_along_axis(x1, order, axis=1)
  r_sorted = np.take_along_axis(rand_mat, order, axis=1)
  sel = active  # after argsort the first k[r] slots per row are the picks
  ri = np.repeat(np.arange(n, dtype=np.int64), k)
  v = v_sorted[sel]
  decide = d_sorted[sel]
  rand_ids = r_sorted[sel]
  in_a = v < na[ri]
  pos = np.where(in_a, v + 1, v + 2).astype(np.uint16)
  src = np.where(in_a, a_ranges[ri, 0] + v, b_ranges[ri, 0] + v - na[ri])
  label_ids = flat_ids[src].astype(np.int32)
  new_ids = np.where(decide < _MASK_THRESHOLD, np.int32(mask_id),
                     np.where(decide >= _RAND_THRESHOLD, rand_ids,
                              label_ids))
  tgt_a = offs_a[ri] + v
  tgt_b = offs_b[ri] + v - na[ri]
  flat_a[tgt_a[in_a]] = new_ids[in_a]
  flat_b[tgt_b[~in_a]] = new_ids[~in_a]
  return flat_a, flat_b, pos, label_ids


def _check_offsets(name, offs, lens):
  """Caller-provided output offsets must be the exact cumsum of the
  segment lengths: the native kernel scatters through them unchecked, so
  a mismatched array means silent out-of-bounds writes, not an error."""
  offs = np.asarray(offs)
  n = len(lens)
  if offs.shape != (n + 1,):
    raise ValueError(
        f'{name} must have shape ({n + 1},), got {offs.shape}')
  if int(offs[0]) != 0 or not np.array_equal(np.diff(offs), lens):
    raise ValueError(
        f'{name} is not the cumulative sum of the segment lengths '
        '(expected offs[0] == 0 and diff(offs) == lengths)')


def mask_partition_host(flat_ids, a_ranges, b_ranges, *, masked_lm_ratio,
                        vocab_size, mask_id, seed, max_predictions=None,
                        offs_a=None, offs_b=None):
  """Fused ragged host masking for a whole partition.

  One native C++ pass (``lddl_mask_partition``) gathers the A/B id
  columns, draws masked positions via partial Fisher-Yates on a
  counter-based Philox4x32-10 stream (k draws per row instead of a dense
  [N, L] uniform matrix), applies the 80/10/10 recipe, and emits sorted
  positions + label ids — no padded id matrix is ever materialized.
  The numpy fallback produces bit-identical outputs when no toolchain is
  available.

  Determinism contract: bit-identical given (seed, inputs) within a
  framework version; the stream is NOT the padded-matrix
  :func:`mask_batch_host` stream (version-pinned, see MIGRATING.md).

  Returns ``(flat_a, flat_b, positions, label_ids, k)`` — ``flat_a`` /
  ``flat_b`` are the post-masking ragged id columns (offsets = cumsum of
  na/nb), ``positions`` uint16 / ``label_ids`` int32 are ragged by ``k``.
  """
  a_ranges = np.ascontiguousarray(a_ranges, dtype=np.int64).reshape(-1, 2)
  b_ranges = np.ascontiguousarray(b_ranges, dtype=np.int64).reshape(-1, 2)
  flat_ids = np.ascontiguousarray(flat_ids, dtype=np.int32)
  n = len(a_ranges)
  na = a_ranges[:, 1] - a_ranges[:, 0]
  nb = b_ranges[:, 1] - b_ranges[:, 0]
  if offs_a is None:
    offs_a = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(na, out=offs_a[1:])
  else:
    _check_offsets('offs_a', offs_a, na)
  if offs_b is None:
    offs_b = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(nb, out=offs_b[1:])
  else:
    _check_offsets('offs_b', offs_b, nb)
  k = _pick_counts(na, nb, masked_lm_ratio, max_predictions)
  offs_k = np.zeros(n + 1, dtype=np.int64)
  np.cumsum(k, out=offs_k[1:])
  global _TOPK_NATIVE
  if _TOPK_NATIVE is None:
    try:
      from ..native.build import load_library
      _TOPK_NATIVE = load_library()
    except Exception:
      _TOPK_NATIVE = False
  if not _TOPK_NATIVE or n == 0:
    flat_a, flat_b, pos, label_ids = _mask_partition_numpy(
        flat_ids, a_ranges, b_ranges, na, nb, offs_a, offs_b, k, offs_k,
        seed, vocab_size, mask_id)
    return flat_a, flat_b, pos, label_ids, k
  import ctypes
  c = ctypes
  i32p = c.POINTER(c.c_int32)
  i64p = c.POINTER(c.c_int64)
  offs_a = np.ascontiguousarray(offs_a, dtype=np.int64)
  offs_b = np.ascontiguousarray(offs_b, dtype=np.int64)
  flat_a = np.empty(int(offs_a[-1]), dtype=np.int32)
  flat_b = np.empty(int(offs_b[-1]), dtype=np.int32)
  pos = np.empty(int(offs_k[-1]), dtype=np.uint16)
  label_ids = np.empty(int(offs_k[-1]), dtype=np.int32)
  _TOPK_NATIVE.lddl_mask_partition(
      flat_ids.ctypes.data_as(i32p), a_ranges.ctypes.data_as(i64p),
      b_ranges.ctypes.data_as(i64p), n, offs_a.ctypes.data_as(i64p),
      offs_b.ctypes.data_as(i64p), k.ctypes.data_as(i64p),
      offs_k.ctypes.data_as(i64p), c.c_uint64(int(seed) & (2**64 - 1)),
      int(vocab_size), int(mask_id), flat_a.ctypes.data_as(i32p),
      flat_b.ctypes.data_as(i32p),
      pos.ctypes.data_as(c.POINTER(c.c_uint16)),
      label_ids.ctypes.data_as(i32p), min(8, os.cpu_count() or 1))
  return flat_a, flat_b, pos, label_ids, k


def mask_batch_host(ids_mat, row_len, na, *, masked_lm_ratio, vocab_size,
                    mask_id, np_rng, max_predictions=None):
  """Vectorized numpy masking. Returns (masked_mat, picked_mask).

  Determinism contract: bit-identical for a given (seed, inputs) within a
  framework version. The draw layout is NOT stable across versions (the
  decide/replacement draws are taken sparsely at picked positions), so a
  shard regenerated with the same seed under a different version may carry
  different mask bits — pair structure and all non-mask columns are
  unaffected. Matches the repo-wide masking contract
  (tests/test_fast_pipeline.py: "masking bits differ across backends;
  pair structure must not").
  """
  n, l = ids_mat.shape
  if n == 0:
    return ids_mat.copy(), np.zeros((0, l), dtype=bool)
  valid = _special_and_valid(l, row_len, na)
  u = np_rng.random((n, l))
  u[~valid] = 2.0
  k = np.maximum(1, np.rint(row_len * masked_lm_ratio).astype(np.int64))
  if max_predictions is not None:
    k = np.minimum(k, max_predictions)
  k = np.minimum(k, valid.sum(axis=1))
  # The k smallest valid draws per row win. Sort tie-free uint64 keys
  # (positive-float bit patterns order like the floats; the lane index
  # replaces the low mantissa bits) so the result is deterministic across
  # numpy versions — equal float64 draws would otherwise tie-break by sort
  # implementation. argpartition moves the kmax smallest to the front in
  # O(l); only that prefix needs the real sort.
  lane_bits = max(1, (l - 1)).bit_length()
  keys = (u.view(np.uint64) & ~np.uint64((1 << lane_bits) - 1)
          | np.arange(l, dtype=np.uint64)[None, :])
  # Select the k smallest keys per row. Invalid lanes carry the float 2.0
  # bit pattern — larger than any valid [0, 1) draw — and k is clamped to
  # the per-row valid count above, so the selection can never touch an
  # invalid lane. The native path (nth_element per row, C++) and the
  # numpy path (argpartition) produce the identical picked set, emitted
  # in row-major ascending order so the downstream decide/replacement
  # draws line up draw-for-draw either way.
  pr, pc, picked = _select_topk(keys, k, n, l)
  # decide / replacement draws only at picked positions (~ratio of the
  # matrix) instead of dense (n, l) matrices.
  decide = np_rng.random(len(pr))
  rand_ids = np_rng.integers(0, vocab_size, len(pr), dtype=np.int32)
  masked = ids_mat.copy()
  to_mask = decide < 0.8
  masked[pr[to_mask], pc[to_mask]] = mask_id
  keep_random = decide >= 0.9
  masked[pr[keep_random], pc[keep_random]] = rand_ids[keep_random]
  return masked, picked


def _device_kernel(ids_mat, row_len, na, key, *, masked_lm_ratio, vocab_size,
                   mask_id, max_predictions):
  import jax
  import jax.numpy as jnp
  n, l = ids_mat.shape
  pos = jnp.arange(l, dtype=jnp.int32)[None, :]
  rl = row_len[:, None]
  nacol = na[:, None]
  is_special = (pos == 0) | (pos == 1 + nacol) | (pos == rl - 1)
  valid = (pos < rl) & ~is_special
  ku, kd, kr = jax.random.split(key, 3)
  u = jax.random.uniform(ku, (n, l), dtype=jnp.float32)
  u = jnp.where(valid, u, 2.0)
  k = jnp.maximum(1, jnp.rint(row_len * masked_lm_ratio).astype(jnp.int32))
  if max_predictions is not None:
    k = jnp.minimum(k, max_predictions)
  k = jnp.minimum(k, valid.sum(axis=1).astype(jnp.int32))
  order = jnp.argsort(u, axis=1)
  ranks = jnp.argsort(order, axis=1)
  picked = (ranks < k[:, None]) & valid
  decide = jax.random.uniform(kd, (n, l), dtype=jnp.float32)
  rand_ids = jax.random.randint(kr, (n, l), 0, vocab_size, dtype=jnp.int32)
  masked = jnp.where(picked & (decide < 0.8), mask_id,
                     jnp.where(picked & (decide >= 0.9), rand_ids, ids_mat))
  return masked, picked


_jitted_kernel = None


def _get_device_kernel():
  global _jitted_kernel
  if _jitted_kernel is None:
    import jax
    _jitted_kernel = jax.jit(
        _device_kernel,
        static_argnames=('masked_lm_ratio', 'vocab_size', 'mask_id',
                         'max_predictions'))
  return _jitted_kernel


def _bucket(n, minimum=512):
  """Round up to bound jit recompilation: powers of two up to 8192, then
  multiples of 8192."""
  b = minimum
  while b < n and b < 8192:
    b *= 2
  if b >= n:
    return b
  return ((n + 8191) // 8192) * 8192


def mask_batch_device(ids_mat, row_len, na, *, masked_lm_ratio, vocab_size,
                      mask_id, seed, max_predictions=None):
  """JAX masking on the default device. Deterministic given ``seed``.

  Rows are padded up to a bucketed batch size (padding rows have
  ``row_len``=3 so they pick nothing that survives the slice back).
  """
  import jax
  import numpy as np_
  n, l = ids_mat.shape
  if n == 0:
    return ids_mat.copy(), np.zeros((0, l), dtype=bool)
  nb = _bucket(n)
  if nb != n:
    ids_mat = np_.concatenate(
        [ids_mat, np_.zeros((nb - n, l), dtype=ids_mat.dtype)])
    row_len = np_.concatenate([row_len, np_.full(nb - n, 3, row_len.dtype)])
    na = np_.concatenate([na, np_.zeros(nb - n, na.dtype)])
  key = jax.random.PRNGKey(seed)
  masked, picked = _get_device_kernel()(
      ids_mat, row_len, na, key,
      masked_lm_ratio=float(masked_lm_ratio), vocab_size=int(vocab_size),
      mask_id=int(mask_id), max_predictions=max_predictions)
  masked = np_.asarray(masked)[:n]
  picked = np_.asarray(picked)[:n]
  return masked, picked


def _partition_kernel(flat, a0, a1, b0, b1, key, *, seq_len, masked_lm_ratio,
                      vocab_size, mask_id, cls_id, sep_id, max_pred):
  """Fused device program: assemble [CLS] A [SEP] B [SEP] rows by gather,
  draw masking, and emit a compact delta (sorted picked positions + the
  post-masking ids there). Never materializes the id matrix on the host.
  """
  import jax
  import jax.numpy as jnp
  la = a1 - a0
  lb = b1 - b0
  row_len = la + lb + 3
  l = seq_len
  pos = jnp.arange(l, dtype=jnp.int32)[None, :]
  lac = la[:, None]
  in_a = (pos >= 1) & (pos < 1 + lac)
  in_b = (pos >= 2 + lac) & (pos < 2 + lac + lb[:, None])
  gather_idx = jnp.where(in_a, a0[:, None] + pos - 1,
                         jnp.where(in_b, b0[:, None] + pos - 2 - lac, 0))
  vals = jnp.take(flat, gather_idx, mode='clip').astype(jnp.int32)
  is_sep = (pos == 1 + lac) | (pos == row_len[:, None] - 1)
  mat = jnp.where(pos == 0, cls_id,
                  jnp.where(is_sep, sep_id,
                            jnp.where(in_a | in_b, vals, 0)))
  valid = in_a | in_b  # exactly the non-special, in-range positions
  ku, kd, kr = jax.random.split(key, 3)
  u = jax.random.uniform(ku, mat.shape, dtype=jnp.float32)
  u = jnp.where(valid, u, 2.0)
  k = jnp.maximum(1, jnp.rint(row_len * masked_lm_ratio).astype(jnp.int32))
  k = jnp.minimum(k, jnp.minimum(valid.sum(axis=1).astype(jnp.int32),
                                 max_pred))
  order = jnp.argsort(u, axis=1)
  ranks = jnp.argsort(order, axis=1)
  picked = (ranks < k[:, None]) & valid
  decide = jax.random.uniform(kd, mat.shape, dtype=jnp.float32)
  rand_ids = jax.random.randint(kr, mat.shape, 0, vocab_size,
                                dtype=jnp.int32)
  masked = jnp.where(picked & (decide < 0.8), mask_id,
                     jnp.where(picked & (decide >= 0.9), rand_ids, mat))
  pos_sorted = jnp.sort(jnp.where(picked, pos, l), axis=1)[:, :max_pred]
  new_ids = jnp.take_along_axis(masked, jnp.minimum(pos_sorted, l - 1),
                                axis=1)
  return pos_sorted.astype(jnp.int16), new_ids, k


_jitted_partition = None


def _get_partition_kernel():
  global _jitted_partition
  if _jitted_partition is None:
    import jax
    _jitted_partition = jax.jit(
        _partition_kernel,
        static_argnames=('seq_len', 'masked_lm_ratio', 'vocab_size',
                         'mask_id', 'cls_id', 'sep_id', 'max_pred'))
  return _jitted_partition


def mask_partition_device(flat_ids, a_ranges, b_ranges, *, seq_len,
                          masked_lm_ratio, vocab_size, mask_id, cls_id,
                          sep_id, seed, max_predictions=None):
  """Device masking for a whole partition from flat ids + segment ranges.

  Uploads the flat id array (uint16 when the vocab allows) and the int32
  range columns; downloads only (positions int16 [N, P], post-masking ids
  [N, P], k [N]) — ~10x less transfer than shipping padded id matrices
  both ways. Deterministic given ``seed``.

  Returns (positions, new_ids, k) as numpy arrays sliced to the true N.
  """
  import jax
  a_ranges = np.asarray(a_ranges, dtype=np.int32).reshape(-1, 2)
  b_ranges = np.asarray(b_ranges, dtype=np.int32).reshape(-1, 2)
  n = len(a_ranges)
  max_pred = max(1, int(round(seq_len * masked_lm_ratio)) + 1)
  if max_predictions is not None:
    max_pred = min(max_pred, max_predictions)
  if n == 0:
    return (np.zeros((0, max_pred), np.int16),
            np.zeros((0, max_pred), np.int32), np.zeros(0, np.int32))
  nb = _bucket(n)
  a0 = np.zeros(nb, np.int32)
  a1 = np.ones(nb, np.int32)
  b0 = np.zeros(nb, np.int32)
  b1 = np.ones(nb, np.int32)
  a0[:n], a1[:n] = a_ranges[:, 0], a_ranges[:, 1]
  b0[:n], b1[:n] = b_ranges[:, 0], b_ranges[:, 1]
  flat = np.ascontiguousarray(flat_ids)
  if vocab_size <= np.iinfo(np.uint16).max + 1:
    flat = flat.astype(np.uint16)
  # Pad the flat id array to a bucketed length too — jit caches by shape,
  # and every partition has a unique token count. Safe: the kernel gathers
  # with mode='clip' and padded rows read index 0.
  flat_cap = 1 << 16
  while flat_cap < len(flat):
    flat_cap *= 2
  if flat_cap != len(flat):
    flat = np.concatenate([flat, np.zeros(flat_cap - len(flat), flat.dtype)])
  key = jax.random.PRNGKey(seed)
  positions, new_ids, k = _get_partition_kernel()(
      flat, a0, a1, b0, b1, key, seq_len=int(seq_len),
      masked_lm_ratio=float(masked_lm_ratio), vocab_size=int(vocab_size),
      mask_id=int(mask_id), cls_id=int(cls_id), sep_id=int(sep_id),
      max_pred=max_pred)
  return (np.asarray(positions)[:n], np.asarray(new_ids)[:n],
          np.asarray(k)[:n])


def mask_batch(ids_mat, row_len, na, *, masked_lm_ratio, vocab_size, mask_id,
               seed, backend='auto', max_predictions=None):
  """Dispatch to the resolved backend. Host RNG is Philox keyed on seed."""
  backend = resolve_mask_backend(backend)
  if backend == 'device':
    return mask_batch_device(
        ids_mat, row_len, na, masked_lm_ratio=masked_lm_ratio,
        vocab_size=vocab_size, mask_id=mask_id, seed=seed,
        max_predictions=max_predictions)
  np_rng = np.random.Generator(np.random.Philox(key=np.uint64(seed)))
  return mask_batch_host(
      ids_mat, row_len, na, masked_lm_ratio=masked_lm_ratio,
      vocab_size=vocab_size, mask_id=mask_id, np_rng=np_rng,
      max_predictions=max_predictions)

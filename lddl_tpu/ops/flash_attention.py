"""Flash attention as a Pallas TPU kernel (forward + backward).

The attention score matrix is the one O(s^2) memory object in BERT-style
training; XLA materializes it per layer (``models/bert.py`` dense path).
This kernel never does: softmax runs online over key blocks with a
running (max, sum, accumulator) in VMEM, so per-core attention memory is
O(block^2) regardless of sequence length, and the backward pass
recomputes probabilities blockwise from the saved log-sum-exp instead of
storing them.

Layout: inputs ``[batch, heads, seq, head_dim]`` are flattened to
``[batch*heads, seq, head_dim]``; the grid walks (batch*heads,
q-blocks, k-blocks) for forward/dq and (batch*heads, k-blocks,
q-blocks) for dk/dv — the contracted sequence axis is the *innermost*
(sequential) grid dimension, with the running state (max/sum/acc or
gradient accumulators) in VMEM scratch that persists across those
steps. VMEM residency per grid step is one 128-row q/output tile plus
one kv block of up to ``_BLOCK_KV_FWD``/``_BLOCK_KV_BWD`` (4096/2048)
keys — a few MB total, independent of sequence length (an earlier
revision held full per-head K/V in VMEM, capping single-chip sequences
at ~8k; the grid-blocked form runs 32k+). K/V lengths that don't divide
into whole blocks are padded up to the next block boundary with
-inf-biased columns (``_kv_blocking``), never dropped to slow 128-wide
blocks.

Masking: a key-side additive bias ``[batch, seq]`` (0 = attend, -1e9 =
padding) — the same semantics as the dense path and the ring
(:mod:`lddl_tpu.parallel.ring`) path. Ring composes with this kernel
(``ring_attention(block_impl='flash')`` /
``BertConfig(attention_impl='ring_flash')``): ring shards the sequence
across chips and rotates K/V, each chip's local block runs here via
:func:`flash_attention_with_lse`, and the (out, lse) pair enters ring's
streaming-softmax merge exactly.

Block-diagonal packed attention: optional per-token ``segment_ids``
(doc index per token, -1 = padding — the packed loader derives them
from the stored ``doc_offsets``) restrict attention to within-document
pairs. Because a packed row's doc ids are monotone, every q/kv block
covers a contiguous id interval, so a (q-block, kv-block) tile whose
intervals are disjoint provably contains only masked pairs — the
kernels *skip* such tiles entirely (``pl.when`` around the whole tile
body: no MXU issue, no accumulator update), and only boundary-straddling
tiles pay the elementwise ``q_seg == kv_seg`` additive -1e9 bias on top
of the key-side padding bias. A row packing k documents therefore runs
~1/k of its attention tiles instead of computing and masking all of
them — the "no cross-contamination" masking of arXiv:2107.02027 as a
speedup rather than a cost.

Differentiation is a ``jax.custom_vjp``: forward saves (out, lse); the
backward runs two Pallas kernels — dq over q-blocks, (dk, dv) over
k-blocks — each recomputing P = exp(s - lse) blockwise.

Off TPU the kernels run in Pallas interpret mode, so the CPU test suite
exercises the identical code path.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9
# Softmax-denominator floor: a q row whose every tile was skipped (only
# padding rows qualify — a real token always overlaps its own document)
# ends the kv sweep with l == 0; the floor turns its 0/0 output into an
# exact 0 (and its lse finite) so the sliced-away row cannot leak NaN
# into `delta` in the backward pass. Real rows always have l >= 1
# (softmax includes the row max), so the floor never perturbs them.
_L_FLOOR = 1e-30


def _interpret():
  return jax.devices()[0].platform != 'tpu'


def _padded_len(s):
  """Kernel sequence length: rounded up so BlockSpec blocks tile the
  array exactly — a block extending past the array end has undefined
  out-of-bounds contents, which would corrupt the tail q/kv block. The
  wrapper pads inputs to this length — padded key columns carry a -inf
  bias, padded query rows are sliced away."""
  if s <= 128:
    return ((s + 7) // 8) * 8  # sublane-tile multiple
  return ((s + 127) // 128) * 128


# Tuned on v5e: the q block sets the output tile (128 = one MXU tile of
# rows); the kv block is the unit streamed through the innermost grid
# dimension — larger blocks amortize per-grid-step overhead (128-wide kv
# blocks measured 3-4x slower than 2048-wide at s>=2048) while VMEM use
# stays modest (2 x block_k x 64 x 2B double-buffered ~= 1 MB at 2048).
# Env overrides (LDDL_FLASH_BLOCK_{Q,KV_FWD,KV_BWD}) support per-shape
# retuning without code edits — short sequences want smaller kv blocks,
# and block-diagonal packed rows skip at tile granularity, so many small
# documents per row skip more with smaller kv blocks.
_BLOCK_Q = int(os.environ.get('LDDL_FLASH_BLOCK_Q', 128))
_BLOCK_KV_FWD = int(os.environ.get('LDDL_FLASH_BLOCK_KV_FWD', 4096))
_BLOCK_KV_BWD = int(os.environ.get('LDDL_FLASH_BLOCK_KV_BWD', 2048))
# Segmented (block-diagonal) runs cap kv blocks finer: a tile can only
# skip whole, so the skip granularity IS the kv block — a 4096-wide
# block over a row packing 16 x ~512-token docs straddles ~8 documents
# and never skips, while 512-wide blocks skip ~7/8 of the grid. The
# extra per-block overhead is repaid as soon as rows pack >~2 docs.
_BLOCK_KV_SEG = int(os.environ.get('LDDL_FLASH_BLOCK_KV_SEG', 512))


def _kv_blocking(s_kv_pad, cap):
  """(block, padded_kv): a kv block <= cap (multiple of 128, or the whole
  length when it fits in one block) and the kv length rounded up to a
  whole number of blocks. Rather than requiring the block to divide the
  incoming length (which collapses to slow 128-wide blocks whenever the
  length has no large divisor), the caller pads K/V/bias up to
  ``padded_kv`` — masked padding columns cost at most one extra
  fractional block of compute (<= ~6% at s >= 2k)."""
  if s_kv_pad <= cap:
    return s_kv_pad, s_kv_pad
  n_steps = -(-s_kv_pad // cap)
  block = -(-s_kv_pad // (n_steps * 128)) * 128
  return block, block * n_steps


def _pad_kv(k, v, bias, kv_seg, padded_kv):
  s_kv = k.shape[1]
  if padded_kv == s_kv:
    return k, v, bias, kv_seg
  grow = ((0, 0), (0, padded_kv - s_kv), (0, 0))
  seg_grow = ((0, 0), (0, 0), (0, padded_kv - s_kv))
  return (jnp.pad(k, grow), jnp.pad(v, grow),
          jnp.pad(bias, seg_grow, constant_values=NEG_INF),
          None if kv_seg is None else jnp.pad(kv_seg, seg_grow,
                                              constant_values=-1.0))


def _seg_interval(seg):
  """(lo, hi) of the real (non-padding) segment ids in a tile row.

  Padding entries carry -1: excluding them from ``lo`` (and letting
  them drag ``hi`` down) makes an all-padding block's interval empty
  (lo > hi), so it reports disjoint against everything — padding-only
  tiles skip for free."""
  real = seg >= 0
  lo = jnp.min(jnp.where(real, seg, jnp.float32(2**30)))
  hi = jnp.max(jnp.where(real, seg, jnp.float32(-1)))
  return lo, hi


def _tile_live(qseg_ref, kseg_ref):
  """Scalar: does this (q-block, kv-block) tile contain any same-doc
  pair? Doc ids are monotone within a packed row, so each block spans a
  contiguous id interval and interval overlap is exact."""
  qlo, qhi = _seg_interval(qseg_ref[0, 0, :])
  klo, khi = _seg_interval(kseg_ref[0, 0, :])
  return (qlo <= khi) & (klo <= qhi)


def _seg_bias(qseg_ref, kseg_ref):
  """Elementwise cross-document mask for boundary-straddling tiles."""
  qseg = qseg_ref[0, 0, :]
  kseg = kseg_ref[0, 0, :]
  return jnp.where(qseg[:, None] == kseg[None, :], 0.0, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, qseg_ref, kseg_ref,
                o_ref, lse_ref, m_ref, l_ref, acc_ref, *, scale):
  """Grid (bh, q-blocks, kv-blocks); kv is the innermost (sequential)
  dimension. The running (max, sum, accumulator) lives in VMEM scratch,
  which persists across grid steps: reset on the first kv block,
  updated by every *live* tile (cross-doc tiles skip the whole body),
  finalized into (o, lse) on the last."""
  j = pl.program_id(2)

  @pl.when(j == 0)
  def _init():
    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

  def _tile():
    q = q_ref[0].astype(jnp.float32)  # [bq, d]
    k_blk = k_ref[0].astype(jnp.float32)  # [bk, d]
    v_blk = v_ref[0].astype(jnp.float32)
    scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
    scores = scores + bias_ref[0, 0, :].astype(jnp.float32)[None, :]
    if qseg_ref is not None:
      scores = scores + _seg_bias(qseg_ref, kseg_ref)
    m = m_ref[...]
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    alpha = jnp.exp(m - m_new)
    m_ref[...] = m_new
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
        p, v_blk, preferred_element_type=jnp.float32)

  if qseg_ref is None:
    _tile()
  else:
    pl.when(_tile_live(qseg_ref, kseg_ref))(_tile)

  @pl.when(j == pl.num_programs(2) - 1)
  def _finalize():
    l = jnp.maximum(l_ref[...], _L_FLOOR)
    o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
    lse_ref[0] = m_ref[...] + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, qseg_ref, kseg_ref, do_ref,
               lse_ref, delta_ref, dq_ref, dq_acc_ref, *, scale):
  """Grid (bh, q-blocks, kv-blocks), kv innermost; dq accumulates in
  scratch across the kv sweep. Cross-doc tiles contribute exactly zero
  (P underflows against their -1e9 bias) so they are skipped whole."""
  j = pl.program_id(2)

  @pl.when(j == 0)
  def _init():
    dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

  def _tile():
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]      # [bq, 1]
    delta = delta_ref[0]  # [bq, 1]
    k_blk = k_ref[0].astype(jnp.float32)
    v_blk = v_ref[0].astype(jnp.float32)
    scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
    scores = scores + bias_ref[0, 0, :].astype(jnp.float32)[None, :]
    if qseg_ref is not None:
      scores = scores + _seg_bias(qseg_ref, kseg_ref)
    p = jnp.exp(scores - lse)
    dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dq_acc_ref[...] = dq_acc_ref[...] + jnp.dot(
        ds, k_blk, preferred_element_type=jnp.float32)

  if qseg_ref is None:
    _tile()
  else:
    pl.when(_tile_live(qseg_ref, kseg_ref))(_tile)

  @pl.when(j == pl.num_programs(2) - 1)
  def _finalize():
    dq_ref[0] = (dq_acc_ref[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, qseg_ref, kseg_ref, do_ref,
                lse_ref, delta_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                *, scale):
  """Grid (bh, kv-blocks, q-blocks), q innermost; dk/dv accumulate in
  scratch across the q sweep while the (k, v) block stays resident."""
  i = pl.program_id(2)

  @pl.when(i == 0)
  def _init():
    dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
    dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

  def _tile():
    k_blk = k_ref[0].astype(jnp.float32)  # [bk, d]
    v_blk = v_ref[0].astype(jnp.float32)
    bias = bias_ref[0, 0, :].astype(jnp.float32)[None, :]
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    # Rows beyond the real sequence carry lse from padded-q garbage; their
    # dO is zero (cotangents of padding outputs are never produced by the
    # loss) so they contribute nothing — but guard exp() overflow anyway.
    scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
    scores = scores + bias
    if qseg_ref is not None:
      scores = scores + _seg_bias(qseg_ref, kseg_ref)
    p = jnp.exp(jnp.minimum(scores - lse, 30.0))
    dv_acc_ref[...] = dv_acc_ref[...] + jnp.dot(
        p.T, do, preferred_element_type=jnp.float32)
    dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dk_acc_ref[...] = dk_acc_ref[...] + jnp.dot(
        ds.T, q, preferred_element_type=jnp.float32)

  if qseg_ref is None:
    _tile()
  else:
    pl.when(_tile_live(qseg_ref, kseg_ref))(_tile)

  @pl.when(i == pl.num_programs(2) - 1)
  def _finalize():
    dk_ref[0] = (dk_acc_ref[...] * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _plain(kernel):
  """The segment-free variant of a kernel: same body, no seg refs in the
  pallas_call signature (and the static ``qseg_ref is None`` branch
  keeps the whole skip/bias machinery out of the trace)."""

  def wrapped(q_ref, k_ref, v_ref, bias_ref, *rest, **kw):
    return kernel(q_ref, k_ref, v_ref, bias_ref, None, None, *rest, **kw)

  return wrapped


# Layout note for the BlockSpecs below: TPU lowering requires each
# block's last two dims to be (multiple-of-8, multiple-of-128) or equal
# to the array dims, so scalar rows ride as trailing-singleton 3-D
# arrays — bias/segment ids ``[b, 1, s]``, lse/delta ``[bh, s_q, 1]``.


def _qkv_specs(block_q, block_k, d, heads):
  """Shared specs for the (bh, q-blocks, kv-blocks) grid used by both
  the forward and dq pallas_calls — one point of truth so their block
  shapes and index maps cannot desynchronize. Returns
  (q_spec, kv_spec, bias_spec, qseg_spec, row_spec)."""
  q_spec = pl.BlockSpec((1, block_q, d), lambda i, b, j: (i, b, 0))
  kv_spec = pl.BlockSpec((1, block_k, d), lambda i, b, j: (i, j, 0))
  bias_spec = pl.BlockSpec((1, 1, block_k), lambda i, b, j: (i // heads, 0, j))
  qseg_spec = pl.BlockSpec((1, 1, block_q), lambda i, b, j: (i // heads, 0, b))
  row_spec = pl.BlockSpec((1, block_q, 1), lambda i, b, j: (i, b, 0))
  return q_spec, kv_spec, bias_spec, qseg_spec, row_spec


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _flash_pair(q, k, v, bias, q_seg, kv_seg, heads):
  """(out, lse) with gradients defined for both outputs — lse cotangents
  arise when results of separate flash calls are merged downstream (the
  ring composition's streaming-softmax combine). ``q_seg``/``kv_seg``
  are either both None (full attention) or float32 ``[b, 1, s]`` doc
  ids (-1 = padding) enabling the block-diagonal tile skip."""
  return _flash_fwd_impl(q, k, v, bias, q_seg, kv_seg, heads)


def _flash_fwd_impl(q, k, v, bias, q_seg, kv_seg, heads):
  bh, s_q, d = q.shape
  block_q = min(_BLOCK_Q, s_q)
  cap = _BLOCK_KV_FWD if q_seg is None else min(_BLOCK_KV_FWD, _BLOCK_KV_SEG)
  block_k, padded_kv = _kv_blocking(k.shape[1], cap)
  k, v, bias, kv_seg = _pad_kv(k, v, bias, kv_seg, padded_kv)
  grid = (bh, pl.cdiv(s_q, block_q), pl.cdiv(padded_kv, block_k))
  q_spec, kv_spec, bias_spec, qseg_spec, _ = _qkv_specs(
      block_q, block_k, d, heads)
  if q_seg is None:
    kernel, in_specs = _plain(_fwd_kernel), [q_spec, kv_spec, kv_spec,
                                             bias_spec]
    inputs = (q, k, v, bias)
  else:
    kernel = _fwd_kernel
    in_specs = [q_spec, kv_spec, kv_spec, bias_spec, qseg_spec, bias_spec]
    inputs = (q, k, v, bias, q_seg, kv_seg)
  out, lse = pl.pallas_call(
      functools.partial(kernel, scale=1.0 / d**0.5),
      grid=grid,
      in_specs=in_specs,
      out_specs=[
          pl.BlockSpec((1, block_q, d), lambda i, b, j: (i, b, 0)),
          pl.BlockSpec((1, block_q, 1), lambda i, b, j: (i, b, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
          jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_q, 1), jnp.float32),
          pltpu.VMEM((block_q, 1), jnp.float32),
          pltpu.VMEM((block_q, d), jnp.float32),
      ],
      interpret=_interpret(),
  )(*inputs)
  return out, lse


def _flash_fwd(q, k, v, bias, q_seg, kv_seg, heads):
  out, lse = _flash_fwd_impl(q, k, v, bias, q_seg, kv_seg, heads)
  return (out, lse), (q, k, v, bias, q_seg, kv_seg, out, lse)


def _flash_bwd(heads, res, cotangents):
  q, k, v, bias, q_seg, kv_seg, out, lse = res
  g, g_lse = cotangents
  bh, s_q, d = q.shape
  s_kv = k.shape[1]
  block_q = min(_BLOCK_Q, s_q)
  cap = _BLOCK_KV_BWD if q_seg is None else min(_BLOCK_KV_BWD, _BLOCK_KV_SEG)
  block_k, padded_kv = _kv_blocking(s_kv, cap)
  k, v, bias_padded, kv_seg_padded = _pad_kv(k, v, bias, kv_seg, padded_kv)
  g = g.astype(q.dtype)
  # d(out)/dS = P(delta-terms); d(lse)/dS = P — so an lse cotangent folds
  # into the shared (dp - delta) factor as delta -= g_lse.
  delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                  axis=-1, keepdims=True)  # [bh, s, 1]
  delta = delta - g_lse.astype(jnp.float32)
  scale = 1.0 / d**0.5
  segmented = q_seg is not None

  # dq: grid (bh, q-blocks, kv-blocks), kv innermost.
  q_spec, kv_spec, bias_spec, qseg_spec, row_blocked = _qkv_specs(
      block_q, block_k, d, heads)
  if segmented:
    dq_kernel = _dq_kernel
    dq_specs = [q_spec, kv_spec, kv_spec, bias_spec, qseg_spec, bias_spec,
                q_spec, row_blocked, row_blocked]
    dq_inputs = (q, k, v, bias_padded, q_seg, kv_seg_padded, g, lse, delta)
  else:
    dq_kernel = _plain(_dq_kernel)
    dq_specs = [q_spec, kv_spec, kv_spec, bias_spec, q_spec,
                row_blocked, row_blocked]
    dq_inputs = (q, k, v, bias_padded, g, lse, delta)
  dq = pl.pallas_call(
      functools.partial(dq_kernel, scale=scale),
      grid=(bh, pl.cdiv(s_q, block_q), pl.cdiv(padded_kv, block_k)),
      in_specs=dq_specs,
      out_specs=pl.BlockSpec((1, block_q, d), lambda i, b, j: (i, b, 0)),
      out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
      scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
      interpret=_interpret(),
  )(*dq_inputs)

  # dk/dv: grid (bh, kv-blocks, q-blocks), q innermost; the (k, v) block
  # stays resident across the q sweep.
  q_by_i = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
  kv_by_j = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
  bias_by_j = pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b // heads, 0, j))
  qseg_by_i = pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b // heads, 0, i))
  row_by_i = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
  if segmented:
    dkv_kernel = _dkv_kernel
    dkv_specs = [q_by_i, kv_by_j, kv_by_j, bias_by_j, qseg_by_i, bias_by_j,
                 q_by_i, row_by_i, row_by_i]
    dkv_inputs = (q, k, v, bias_padded, q_seg, kv_seg_padded, g, lse, delta)
  else:
    dkv_kernel = _plain(_dkv_kernel)
    dkv_specs = [q_by_i, kv_by_j, kv_by_j, bias_by_j, q_by_i,
                 row_by_i, row_by_i]
    dkv_inputs = (q, k, v, bias_padded, g, lse, delta)
  dk, dv = pl.pallas_call(
      functools.partial(dkv_kernel, scale=scale),
      grid=(bh, pl.cdiv(padded_kv, block_k), pl.cdiv(s_q, block_q)),
      in_specs=dkv_specs,
      out_specs=[kv_by_j, kv_by_j],
      out_shape=[
          jax.ShapeDtypeStruct((bh, padded_kv, d), q.dtype),
          jax.ShapeDtypeStruct((bh, padded_kv, d), q.dtype),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_k, d), jnp.float32),
          pltpu.VMEM((block_k, d), jnp.float32),
      ],
      interpret=_interpret(),
  )(*dkv_inputs)
  return (dq, dk[:, :s_kv, :], dv[:, :s_kv, :], jnp.zeros_like(bias),
          None if q_seg is None else jnp.zeros_like(q_seg),
          None if kv_seg is None else jnp.zeros_like(kv_seg))


_flash_pair.defvjp(_flash_fwd, _flash_bwd)


def _prep_segments(segment_ids, s, s_pad):
  """[b, s] int doc ids -> the kernel's padded float32 [b, 1, s_pad] row
  (float so the custom_vjp cotangent is an ordinary zeros array; doc
  ids are < 65536, exact in float32). Pads extend with -1."""
  seg = jnp.asarray(segment_ids).astype(jnp.float32)[:, None, :]
  if s_pad != s:
    seg = jnp.pad(seg, ((0, 0), (0, 0), (0, s_pad - s)),
                  constant_values=-1.0)
  return seg


def flash_attention_with_lse(q, k, v, attention_mask=None,
                             q_segment_ids=None, kv_segment_ids=None):
  """Like :func:`flash_attention` but also returns the per-query
  log-sum-exp ``[batch, heads, seq]`` (float32) — the quantity needed to
  exactly merge attention results computed over disjoint key sets (ring
  attention's streaming-softmax combine). Gradients flow through both
  outputs.
  """
  b, h, s_q, d = q.shape
  s_kv = k.shape[2]
  if (q_segment_ids is None) != (kv_segment_ids is None):
    raise ValueError('q_segment_ids and kv_segment_ids must be given '
                     'together (self-attention passes the same array)')
  if attention_mask is None:
    bias = jnp.zeros((b, s_kv), jnp.float32)
  else:
    bias = jnp.where(attention_mask != 0, 0.0, NEG_INF).astype(jnp.float32)
  bias = bias[:, None, :]  # [b, 1, s_kv]: TPU block-tiling-friendly layout
  sq_pad, skv_pad = _padded_len(s_q), _padded_len(s_kv)
  if sq_pad != s_q:
    q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_pad - s_q), (0, 0)))
  if skv_pad != s_kv:
    kv_pad = ((0, 0), (0, 0), (0, skv_pad - s_kv), (0, 0))
    k = jnp.pad(k, kv_pad)
    v = jnp.pad(v, kv_pad)
    bias = jnp.pad(bias, ((0, 0), (0, 0), (0, skv_pad - s_kv)),
                   constant_values=NEG_INF)
  q_seg = kv_seg = None
  if q_segment_ids is not None:
    q_seg = _prep_segments(q_segment_ids, s_q, sq_pad)
    kv_seg = _prep_segments(kv_segment_ids, s_kv, skv_pad)
  out, lse = _flash_pair(q.reshape(b * h, sq_pad, d),
                         k.reshape(b * h, skv_pad, d),
                         v.reshape(b * h, skv_pad, d), bias, q_seg, kv_seg,
                         h)
  out = out.reshape(b, h, sq_pad, d)[:, :, :s_q, :]
  lse = lse.reshape(b, h, sq_pad)[:, :, :s_q]
  return out, lse


def flash_attention(q, k, v, attention_mask=None, q_segment_ids=None,
                    kv_segment_ids=None):
  """Blockwise-softmax attention; drop-in for the dense einsum path.

  ``q, k, v``: ``[batch, heads, seq, head_dim]``; ``attention_mask``:
  ``[batch, seq]`` with 1 = attend, 0 = padding (key side). Optional
  ``q_segment_ids``/``kv_segment_ids`` ``[batch, seq]`` int32 (doc index
  per token, -1 = padding) restrict attention block-diagonally to
  same-document pairs, skipping provably cross-document tiles (see
  module docstring). Returns the context ``[batch, heads, seq,
  head_dim]`` in the input dtype.
  """
  return flash_attention_with_lse(q, k, v, attention_mask, q_segment_ids,
                                  kv_segment_ids)[0]


def segment_block_intervals(segment_ids, block):
  """Per-block (lo, hi) doc-id intervals of a ``[b, s]`` id array —
  numpy, the host-side mirror of the kernel's ``_seg_interval``. The
  array is padded with -1 up to a whole number of blocks."""
  import numpy as np
  seg = np.asarray(segment_ids)
  b, s = seg.shape
  s_pad = -(-s // block) * block
  if s_pad != s:
    seg = np.pad(seg, ((0, 0), (0, s_pad - s)), constant_values=-1)
  tiles = seg.reshape(b, s_pad // block, block)
  real = tiles >= 0
  lo = np.where(real, tiles, 2**30).min(axis=2)
  hi = np.where(real, tiles, -1).max(axis=2)
  return lo, hi


def count_skippable_tiles(segment_ids, block_q=None, block_k=None):
  """(total, skipped) forward-grid tile counts for a ``[b, s]``
  segment-id batch under the kernel's interval-disjointness rule — the
  exact host-side account of the tiles the Pallas grid will skip (per
  (batch, q-block, kv-block); multiply by heads for per-head counts;
  the fraction is heads-invariant). Feeds the ``train.attn_tiles_*``
  telemetry counters and the benchmark skip-fraction columns."""
  s = int(segment_ids.shape[1])
  s_pad = _padded_len(s)
  if block_q is None:
    block_q = min(_BLOCK_Q, s_pad)
  if block_k is None:
    block_k, s_pad = _kv_blocking(s_pad, min(_BLOCK_KV_FWD, _BLOCK_KV_SEG))
  import numpy as np
  seg = np.asarray(segment_ids)
  if s_pad != s:
    seg = np.pad(seg, ((0, 0), (0, s_pad - s)), constant_values=-1)
  qlo, qhi = segment_block_intervals(seg, block_q)
  klo, khi = segment_block_intervals(seg, block_k)
  live = ((qlo[:, :, None] <= khi[:, None, :]) &
          (klo[:, None, :] <= qhi[:, :, None]))
  total = int(live.size)
  return total, total - int(live.sum())


def make_flash_attention(mesh, q_spec=None, mask_spec=None,
                         with_segment_ids=False):
  """Wrap :func:`flash_attention` in ``shard_map`` for jitted use over a
  mesh: batch over (data, fsdp), heads over tensor — a ``pallas_call``
  has no GSPMD partitioning rule, so without this the compiler would
  replicate q/k/v onto every chip. The sequence axis must be unsharded
  (flash is per-chip block math; sequence sharding is ring attention's
  job — use ``attention_impl='ring_flash'`` for both).

  ``with_segment_ids=True`` returns a wrapper taking an extra
  ``segment_ids`` ``[batch, seq]`` operand (used for both q and kv —
  self-attention), sharded like the mask.
  """
  from jax.sharding import PartitionSpec as P

  from ..core.compat import shard_map
  if dict(zip(mesh.axis_names, mesh.devices.shape)).get('seq', 1) > 1:
    raise ValueError(
        "flash attention does not shard the sequence axis; use "
        "attention_impl='ring_flash' on meshes with seq > 1")
  names = set(mesh.axis_names)
  batch_axes = tuple(a for a in ('data', 'fsdp') if a in names) or None
  head_axis = 'tensor' if 'tensor' in names else None
  q_spec = q_spec or P(batch_axes, head_axis, None, None)
  mask_spec = mask_spec or P(batch_axes, None)

  if with_segment_ids:
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(q_spec, q_spec, q_spec, mask_spec, mask_spec),
        out_specs=q_spec,
        check=False)
    def _sharded_seg(q, k, v, mask, segment_ids):
      return flash_attention(q, k, v, mask, segment_ids, segment_ids)

    return _sharded_seg

  @functools.partial(
      shard_map,
      mesh=mesh,
      in_specs=(q_spec, q_spec, q_spec, mask_spec),
      out_specs=q_spec,
      check=False)
  def _sharded(q, k, v, mask):
    return flash_attention(q, k, v, mask)

  return _sharded

"""Flash attention as a Pallas TPU kernel (forward + backward).

The attention score matrix is the one O(s^2) memory object in BERT-style
training; XLA materializes it per layer (``models/bert.py`` dense path).
This kernel never does: softmax runs online over key blocks with a
running (max, sum, accumulator) in VMEM, so per-core attention memory is
O(block^2) regardless of sequence length, and the backward pass
recomputes probabilities blockwise from the saved log-sum-exp instead of
storing them.

Layout: inputs ``[batch, heads, seq, head_dim]`` are flattened to
``[batch*heads, seq, head_dim]``; the grid walks (batch*heads,
q-blocks, k-blocks) for forward/dq and (batch*heads, k-blocks,
q-blocks) for dk/dv — the contracted sequence axis is the *innermost*
(sequential) grid dimension, with the running state (max/sum/acc or
gradient accumulators) in VMEM scratch that persists across those
steps. VMEM residency per grid step is one 128-row q/output tile plus
one kv block of up to ``_BLOCK_KV_FWD``/``_BLOCK_KV_BWD`` (4096/2048)
keys — a few MB total, independent of sequence length (an earlier
revision held full per-head K/V in VMEM, capping single-chip sequences
at ~8k; the grid-blocked form runs 32k+). K/V lengths that don't divide
into whole blocks are padded up to the next block boundary with
-inf-biased columns (``_kv_blocking``), never dropped to slow 128-wide
blocks.

Masking: a key-side additive bias ``[batch, seq]`` (0 = attend, -1e9 =
padding) — the same semantics as the dense path and the ring
(:mod:`lddl_tpu.parallel.ring`) path. Ring composes with this kernel
(``ring_attention(block_impl='flash')`` /
``BertConfig(attention_impl='ring_flash')``): ring shards the sequence
across chips and rotates K/V, each chip's local block runs here via
:func:`flash_attention_with_lse`, and the (out, lse) pair enters ring's
streaming-softmax merge exactly.

Differentiation is a ``jax.custom_vjp``: forward saves (out, lse); the
backward runs two Pallas kernels — dq over q-blocks, (dk, dv) over
k-blocks — each recomputing P = exp(s - lse) blockwise.

Off TPU the kernels run in Pallas interpret mode, so the CPU test suite
exercises the identical code path.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9


def _interpret():
  return jax.devices()[0].platform != 'tpu'


def _padded_len(s):
  """Kernel sequence length: rounded up so BlockSpec blocks tile the
  array exactly — a block extending past the array end has undefined
  out-of-bounds contents, which would corrupt the tail q/kv block. The
  wrapper pads inputs to this length — padded key columns carry a -inf
  bias, padded query rows are sliced away."""
  if s <= 128:
    return ((s + 7) // 8) * 8  # sublane-tile multiple
  return ((s + 127) // 128) * 128


# Tuned on v5e: the q block sets the output tile (128 = one MXU tile of
# rows); the kv block is the unit streamed through the innermost grid
# dimension — larger blocks amortize per-grid-step overhead (128-wide kv
# blocks measured 3-4x slower than 2048-wide at s>=2048) while VMEM use
# stays modest (2 x block_k x 64 x 2B double-buffered ~= 1 MB at 2048).
# Env overrides (LDDL_FLASH_BLOCK_{Q,KV_FWD,KV_BWD}) support per-shape
# retuning without code edits — short sequences want smaller kv blocks.
_BLOCK_Q = int(os.environ.get('LDDL_FLASH_BLOCK_Q', 128))
_BLOCK_KV_FWD = int(os.environ.get('LDDL_FLASH_BLOCK_KV_FWD', 4096))
_BLOCK_KV_BWD = int(os.environ.get('LDDL_FLASH_BLOCK_KV_BWD', 2048))


def _kv_blocking(s_kv_pad, cap):
  """(block, padded_kv): a kv block <= cap (multiple of 128, or the whole
  length when it fits in one block) and the kv length rounded up to a
  whole number of blocks. Rather than requiring the block to divide the
  incoming length (which collapses to slow 128-wide blocks whenever the
  length has no large divisor), the caller pads K/V/bias up to
  ``padded_kv`` — masked padding columns cost at most one extra
  fractional block of compute (<= ~6% at s >= 2k)."""
  if s_kv_pad <= cap:
    return s_kv_pad, s_kv_pad
  n_steps = -(-s_kv_pad // cap)
  block = -(-s_kv_pad // (n_steps * 128)) * 128
  return block, block * n_steps


def _pad_kv(k, v, bias, padded_kv):
  s_kv = k.shape[1]
  if padded_kv == s_kv:
    return k, v, bias
  grow = ((0, 0), (0, padded_kv - s_kv), (0, 0))
  return (jnp.pad(k, grow), jnp.pad(v, grow),
          jnp.pad(bias, ((0, 0), (0, 0), (0, padded_kv - s_kv)),
                  constant_values=NEG_INF))


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_ref, l_ref, acc_ref, *, scale):
  """Grid (bh, q-blocks, kv-blocks); kv is the innermost (sequential)
  dimension. The running (max, sum, accumulator) lives in VMEM scratch,
  which persists across grid steps: reset on the first kv block,
  finalized into (o, lse) on the last."""
  j = pl.program_id(2)

  @pl.when(j == 0)
  def _init():
    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

  q = q_ref[0].astype(jnp.float32)  # [bq, d]
  k_blk = k_ref[0].astype(jnp.float32)  # [bk, d]
  v_blk = v_ref[0].astype(jnp.float32)
  scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
  scores = scores + bias_ref[0, 0, :].astype(jnp.float32)[None, :]
  m = m_ref[...]
  m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
  p = jnp.exp(scores - m_new)
  alpha = jnp.exp(m - m_new)
  l_new = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
  acc_new = acc_ref[...] * alpha + jnp.dot(p, v_blk,
                                           preferred_element_type=jnp.float32)
  m_ref[...] = m_new
  l_ref[...] = l_new
  acc_ref[...] = acc_new

  @pl.when(j == pl.num_programs(2) - 1)
  def _finalize():
    o_ref[0] = (acc_new / l_new).astype(o_ref.dtype)
    lse_ref[0] = m_new + jnp.log(l_new)


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc_ref, *, scale):
  """Grid (bh, q-blocks, kv-blocks), kv innermost; dq accumulates in
  scratch across the kv sweep."""
  j = pl.program_id(2)

  @pl.when(j == 0)
  def _init():
    dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

  q = q_ref[0].astype(jnp.float32)
  do = do_ref[0].astype(jnp.float32)
  lse = lse_ref[0]      # [bq, 1]
  delta = delta_ref[0]  # [bq, 1]
  k_blk = k_ref[0].astype(jnp.float32)
  v_blk = v_ref[0].astype(jnp.float32)
  scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
  scores = scores + bias_ref[0, 0, :].astype(jnp.float32)[None, :]
  p = jnp.exp(scores - lse)
  dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
  ds = p * (dp - delta)
  dq_acc = dq_acc_ref[...] + jnp.dot(ds, k_blk,
                                     preferred_element_type=jnp.float32)
  dq_acc_ref[...] = dq_acc

  @pl.when(j == pl.num_programs(2) - 1)
  def _finalize():
    dq_ref[0] = (dq_acc * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, scale):
  """Grid (bh, kv-blocks, q-blocks), q innermost; dk/dv accumulate in
  scratch across the q sweep while the (k, v) block stays resident."""
  i = pl.program_id(2)

  @pl.when(i == 0)
  def _init():
    dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
    dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

  k_blk = k_ref[0].astype(jnp.float32)  # [bk, d]
  v_blk = v_ref[0].astype(jnp.float32)
  bias = bias_ref[0, 0, :].astype(jnp.float32)[None, :]
  q = q_ref[0].astype(jnp.float32)
  do = do_ref[0].astype(jnp.float32)
  lse = lse_ref[0]
  delta = delta_ref[0]
  # Rows beyond the real sequence carry lse from padded-q garbage; their
  # dO is zero (cotangents of padding outputs are never produced by the
  # loss) so they contribute nothing — but guard exp() overflow anyway.
  scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
  scores = scores + bias
  p = jnp.exp(jnp.minimum(scores - lse, 30.0))
  dv_acc = dv_acc_ref[...] + jnp.dot(p.T, do,
                                     preferred_element_type=jnp.float32)
  dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
  ds = p * (dp - delta)
  dk_acc = dk_acc_ref[...] + jnp.dot(ds.T, q,
                                     preferred_element_type=jnp.float32)
  dk_acc_ref[...] = dk_acc
  dv_acc_ref[...] = dv_acc

  @pl.when(i == pl.num_programs(2) - 1)
  def _finalize():
    dk_ref[0] = (dk_acc * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


# Layout note for the BlockSpecs below: TPU lowering requires each
# block's last two dims to be (multiple-of-8, multiple-of-128) or equal
# to the array dims, so scalar rows ride as trailing-singleton 3-D
# arrays — bias ``[b, 1, s_kv]``, lse/delta ``[bh, s_q, 1]``.


def _qkv_specs(block_q, block_k, d, heads):
  """Shared specs for the (bh, q-blocks, kv-blocks) grid used by both
  the forward and dq pallas_calls — one point of truth so their block
  shapes and index maps cannot desynchronize. Returns
  (q_spec, kv_spec, bias_spec, row_spec)."""
  q_spec = pl.BlockSpec((1, block_q, d), lambda i, b, j: (i, b, 0))
  kv_spec = pl.BlockSpec((1, block_k, d), lambda i, b, j: (i, j, 0))
  bias_spec = pl.BlockSpec((1, 1, block_k), lambda i, b, j: (i // heads, 0, j))
  row_spec = pl.BlockSpec((1, block_q, 1), lambda i, b, j: (i, b, 0))
  return q_spec, kv_spec, bias_spec, row_spec


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash_pair(q, k, v, bias, heads):
  """(out, lse) with gradients defined for both outputs — lse cotangents
  arise when results of separate flash calls are merged downstream (the
  ring composition's streaming-softmax combine)."""
  return _flash_fwd_impl(q, k, v, bias, heads)


def _flash_fwd_impl(q, k, v, bias, heads):
  bh, s_q, d = q.shape
  block_q = min(_BLOCK_Q, s_q)
  block_k, padded_kv = _kv_blocking(k.shape[1], _BLOCK_KV_FWD)
  k, v, bias = _pad_kv(k, v, bias, padded_kv)
  grid = (bh, pl.cdiv(s_q, block_q), pl.cdiv(padded_kv, block_k))
  q_spec, kv_spec, bias_spec, _ = _qkv_specs(block_q, block_k, d, heads)
  out, lse = pl.pallas_call(
      functools.partial(_fwd_kernel, scale=1.0 / d**0.5),
      grid=grid,
      in_specs=[q_spec, kv_spec, kv_spec, bias_spec],
      out_specs=[
          pl.BlockSpec((1, block_q, d), lambda i, b, j: (i, b, 0)),
          pl.BlockSpec((1, block_q, 1), lambda i, b, j: (i, b, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
          jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_q, 1), jnp.float32),
          pltpu.VMEM((block_q, 1), jnp.float32),
          pltpu.VMEM((block_q, d), jnp.float32),
      ],
      interpret=_interpret(),
  )(q, k, v, bias)
  return out, lse


def _flash_fwd(q, k, v, bias, heads):
  out, lse = _flash_fwd_impl(q, k, v, bias, heads)
  return (out, lse), (q, k, v, bias, out, lse)


def _flash_bwd(heads, res, cotangents):
  q, k, v, bias, out, lse = res
  g, g_lse = cotangents
  bh, s_q, d = q.shape
  s_kv = k.shape[1]
  block_q = min(_BLOCK_Q, s_q)
  block_k, padded_kv = _kv_blocking(s_kv, _BLOCK_KV_BWD)
  k, v, bias_padded = _pad_kv(k, v, bias, padded_kv)
  g = g.astype(q.dtype)
  # d(out)/dS = P(delta-terms); d(lse)/dS = P — so an lse cotangent folds
  # into the shared (dp - delta) factor as delta -= g_lse.
  delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                  axis=-1, keepdims=True)  # [bh, s, 1]
  delta = delta - g_lse.astype(jnp.float32)
  scale = 1.0 / d**0.5

  # dq: grid (bh, q-blocks, kv-blocks), kv innermost.
  q_spec, kv_spec, bias_spec, row_blocked = _qkv_specs(
      block_q, block_k, d, heads)
  dq = pl.pallas_call(
      functools.partial(_dq_kernel, scale=scale),
      grid=(bh, pl.cdiv(s_q, block_q), pl.cdiv(padded_kv, block_k)),
      in_specs=[q_spec, kv_spec, kv_spec, bias_spec, q_spec,
                row_blocked, row_blocked],
      out_specs=pl.BlockSpec((1, block_q, d), lambda i, b, j: (i, b, 0)),
      out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
      scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
      interpret=_interpret(),
  )(q, k, v, bias_padded, g, lse, delta)

  # dk/dv: grid (bh, kv-blocks, q-blocks), q innermost; the (k, v) block
  # stays resident across the q sweep.
  q_by_i = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
  kv_by_j = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
  bias_by_j = pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b // heads, 0, j))
  row_by_i = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
  dk, dv = pl.pallas_call(
      functools.partial(_dkv_kernel, scale=scale),
      grid=(bh, pl.cdiv(padded_kv, block_k), pl.cdiv(s_q, block_q)),
      in_specs=[q_by_i, kv_by_j, kv_by_j, bias_by_j, q_by_i,
                row_by_i, row_by_i],
      out_specs=[kv_by_j, kv_by_j],
      out_shape=[
          jax.ShapeDtypeStruct((bh, padded_kv, d), q.dtype),
          jax.ShapeDtypeStruct((bh, padded_kv, d), q.dtype),
      ],
      scratch_shapes=[
          pltpu.VMEM((block_k, d), jnp.float32),
          pltpu.VMEM((block_k, d), jnp.float32),
      ],
      interpret=_interpret(),
  )(q, k, v, bias_padded, g, lse, delta)
  return dq, dk[:, :s_kv, :], dv[:, :s_kv, :], jnp.zeros_like(bias)


_flash_pair.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_with_lse(q, k, v, attention_mask=None):
  """Like :func:`flash_attention` but also returns the per-query
  log-sum-exp ``[batch, heads, seq]`` (float32) — the quantity needed to
  exactly merge attention results computed over disjoint key sets (ring
  attention's streaming-softmax combine). Gradients flow through both
  outputs.
  """
  b, h, s_q, d = q.shape
  s_kv = k.shape[2]
  if attention_mask is None:
    bias = jnp.zeros((b, s_kv), jnp.float32)
  else:
    bias = jnp.where(attention_mask != 0, 0.0, NEG_INF).astype(jnp.float32)
  bias = bias[:, None, :]  # [b, 1, s_kv]: TPU block-tiling-friendly layout
  sq_pad, skv_pad = _padded_len(s_q), _padded_len(s_kv)
  if sq_pad != s_q:
    q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_pad - s_q), (0, 0)))
  if skv_pad != s_kv:
    kv_pad = ((0, 0), (0, 0), (0, skv_pad - s_kv), (0, 0))
    k = jnp.pad(k, kv_pad)
    v = jnp.pad(v, kv_pad)
    bias = jnp.pad(bias, ((0, 0), (0, 0), (0, skv_pad - s_kv)),
                   constant_values=NEG_INF)
  out, lse = _flash_pair(q.reshape(b * h, sq_pad, d),
                         k.reshape(b * h, skv_pad, d),
                         v.reshape(b * h, skv_pad, d), bias, h)
  out = out.reshape(b, h, sq_pad, d)[:, :, :s_q, :]
  lse = lse.reshape(b, h, sq_pad)[:, :, :s_q]
  return out, lse


def flash_attention(q, k, v, attention_mask=None):
  """Blockwise-softmax attention; drop-in for the dense einsum path.

  ``q, k, v``: ``[batch, heads, seq, head_dim]``; ``attention_mask``:
  ``[batch, seq]`` with 1 = attend, 0 = padding (key side). Returns the
  context ``[batch, heads, seq, head_dim]`` in the input dtype.
  """
  return flash_attention_with_lse(q, k, v, attention_mask)[0]


def make_flash_attention(mesh, q_spec=None, mask_spec=None):
  """Wrap :func:`flash_attention` in ``shard_map`` for jitted use over a
  mesh: batch over (data, fsdp), heads over tensor — a ``pallas_call``
  has no GSPMD partitioning rule, so without this the compiler would
  replicate q/k/v onto every chip. The sequence axis must be unsharded
  (flash is per-chip block math; sequence sharding is ring attention's
  job — use ``attention_impl='ring_flash'`` for both).
  """
  from jax.sharding import PartitionSpec as P
  if dict(zip(mesh.axis_names, mesh.devices.shape)).get('seq', 1) > 1:
    raise ValueError(
        "flash attention does not shard the sequence axis; use "
        "attention_impl='ring_flash' on meshes with seq > 1")
  names = set(mesh.axis_names)
  batch_axes = tuple(a for a in ('data', 'fsdp') if a in names) or None
  head_axis = 'tensor' if 'tensor' in names else None
  q_spec = q_spec or P(batch_axes, head_axis, None, None)
  mask_spec = mask_spec or P(batch_axes, None)

  @functools.partial(
      jax.shard_map,
      mesh=mesh,
      in_specs=(q_spec, q_spec, q_spec, mask_spec),
      out_specs=q_spec,
      check_vma=False)
  def _sharded(q, k, v, mask):
    return flash_attention(q, k, v, mask)

  return _sharded

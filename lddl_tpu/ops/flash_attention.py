"""Flash attention as a Pallas TPU kernel (forward + backward).

The attention score matrix is the one O(s^2) memory object in BERT-style
training; XLA materializes it per layer (``models/bert.py`` dense path).
This kernel never does: softmax runs online over key blocks with a
running (max, sum, accumulator) in VMEM, so per-core attention memory is
O(block^2) regardless of sequence length, and the backward pass
recomputes probabilities blockwise from the saved log-sum-exp instead of
storing them.

Layout: inputs ``[batch, heads, seq, head_dim]`` are flattened to
``[batch*heads, seq, head_dim]``; the grid walks (batch*heads, q-blocks)
for forward/dq and (batch*heads, k-blocks) for dk/dv, with full per-head
K/V resident in VMEM (fine through multi-k sequences: 2048 x 64 x 4B =
512 KB/head-operand) and 128-wide blocks feeding the MXU.

Masking: a key-side additive bias ``[batch, seq]`` (0 = attend, -1e9 =
padding) — the same semantics as the dense path and the ring
(:mod:`lddl_tpu.parallel.ring`) path. Ring composes with this kernel
(``ring_attention(block_impl='flash')`` /
``BertConfig(attention_impl='ring_flash')``): ring shards the sequence
across chips and rotates K/V, each chip's local block runs here via
:func:`flash_attention_with_lse`, and the (out, lse) pair enters ring's
streaming-softmax merge exactly.

Differentiation is a ``jax.custom_vjp``: forward saves (out, lse); the
backward runs two Pallas kernels — dq over q-blocks, (dk, dv) over
k-blocks — each recomputing P = exp(s - lse) blockwise.

Off TPU the kernels run in Pallas interpret mode, so the CPU test suite
exercises the identical code path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e9


def _interpret():
  return jax.devices()[0].platform != 'tpu'


def _padded_len(s):
  """Kernel sequence length: a multiple of the block size so every
  ``pl.ds`` slice is in bounds (pallas clamps out-of-bounds dynamic
  slices, which would silently shift tail-block data instead of
  erroring). The wrapper pads inputs to this length — padded key columns
  carry a -inf bias, padded query rows are sliced away."""
  if s <= 128:
    return ((s + 7) // 8) * 8  # sublane-tile multiple
  return ((s + 127) // 128) * 128


def _block_sizes(s):
  return min(128, s), min(128, s)


def _col_bias(bias_ref, j0, width):
  return bias_ref[0, 0, pl.ds(j0, width)].astype(jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *, s_kv,
                scale, block_k):
  q = q_ref[0].astype(jnp.float32)  # [bq, d]
  bq, d = q.shape
  m = jnp.full((bq, 1), NEG_INF, jnp.float32)
  l = jnp.zeros((bq, 1), jnp.float32)
  acc = jnp.zeros((bq, d), jnp.float32)
  for j in range(pl.cdiv(s_kv, block_k)):
    j0 = j * block_k
    k_blk = k_ref[0, pl.ds(j0, block_k), :].astype(jnp.float32)
    v_blk = v_ref[0, pl.ds(j0, block_k), :].astype(jnp.float32)
    scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
    scores = scores + _col_bias(bias_ref, j0, block_k)[None, :]
    m_blk = jnp.max(scores, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(scores - m_new)
    alpha = jnp.exp(m - m_new)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * alpha + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
    m = m_new
  o_ref[0] = (acc / l).astype(o_ref.dtype)
  lse_ref[0] = m + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, s_kv, scale, block_k):
  q = q_ref[0].astype(jnp.float32)
  do = do_ref[0].astype(jnp.float32)
  lse = lse_ref[0]      # [bq, 1]
  delta = delta_ref[0]  # [bq, 1]
  dq = jnp.zeros_like(q)
  for j in range(pl.cdiv(s_kv, block_k)):
    j0 = j * block_k
    k_blk = k_ref[0, pl.ds(j0, block_k), :].astype(jnp.float32)
    v_blk = v_ref[0, pl.ds(j0, block_k), :].astype(jnp.float32)
    scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
    scores = scores + _col_bias(bias_ref, j0, block_k)[None, :]
    p = jnp.exp(scores - lse)
    dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dq = dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)
  dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, s_q, scale, block_q):
  k_blk = k_ref[0].astype(jnp.float32)  # [bk, d]
  v_blk = v_ref[0].astype(jnp.float32)
  bk, d = k_blk.shape
  j0 = pl.program_id(1) * bk
  bias = _col_bias(bias_ref, j0, bk)[None, :]
  dk = jnp.zeros((bk, d), jnp.float32)
  dv = jnp.zeros((bk, d), jnp.float32)
  for i in range(pl.cdiv(s_q, block_q)):
    i0 = i * block_q
    q = q_ref[0, pl.ds(i0, block_q), :].astype(jnp.float32)
    do = do_ref[0, pl.ds(i0, block_q), :].astype(jnp.float32)
    lse = lse_ref[0, pl.ds(i0, block_q), :]
    delta = delta_ref[0, pl.ds(i0, block_q), :]
    # Rows beyond the real sequence carry lse from padded-q garbage; their
    # dO is zero (cotangents of padding outputs are never produced by the
    # loss) so they contribute nothing — but guard exp() overflow anyway.
    scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
    scores = scores + bias
    p = jnp.exp(jnp.minimum(scores - lse, 30.0))
    dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
    dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
  dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
  dv_ref[0] = dv.astype(dv_ref.dtype)


def _specs(s_q, s_kv, d, heads, block_q):
  """(blocked q-side spec, full kv-side spec, bias spec) for grid
  (bh, q-blocks).

  Layout note: TPU lowering requires each block's last two dims to be
  (multiple-of-8, multiple-of-128) or equal to the array dims, so scalar
  rows ride as trailing-singleton 3-D arrays — bias ``[b, 1, s_kv]``,
  lse/delta ``[bh, s_q, 1]``."""
  blocked = pl.BlockSpec((1, block_q, d), lambda i, b: (i, b, 0))
  full = pl.BlockSpec((1, s_kv, d), lambda i, b: (i, 0, 0))
  bias = pl.BlockSpec((1, 1, s_kv), lambda i, b: (i // heads, 0, 0))
  return blocked, full, bias


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash_pair(q, k, v, bias, heads):
  """(out, lse) with gradients defined for both outputs — lse cotangents
  arise when results of separate flash calls are merged downstream (the
  ring composition's streaming-softmax combine)."""
  return _flash_fwd_impl(q, k, v, bias, heads)


def _flash_fwd_impl(q, k, v, bias, heads):
  bh, s_q, d = q.shape
  s_kv = k.shape[1]
  block_q, _ = _block_sizes(s_q)
  _, block_k = _block_sizes(s_kv)
  grid = (bh, pl.cdiv(s_q, block_q))
  q_spec, full_spec, bias_spec = _specs(s_q, s_kv, d, heads, block_q)
  out, lse = pl.pallas_call(
      functools.partial(_fwd_kernel, s_kv=s_kv, scale=1.0 / d**0.5,
                        block_k=block_k),
      grid=grid,
      in_specs=[q_spec, full_spec, full_spec, bias_spec],
      out_specs=[
          pl.BlockSpec((1, block_q, d), lambda i, b: (i, b, 0)),
          pl.BlockSpec((1, block_q, 1), lambda i, b: (i, b, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
          jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32),
      ],
      interpret=_interpret(),
  )(q, k, v, bias)
  return out, lse


def _flash_fwd(q, k, v, bias, heads):
  out, lse = _flash_fwd_impl(q, k, v, bias, heads)
  return (out, lse), (q, k, v, bias, out, lse)


def _flash_bwd(heads, res, cotangents):
  q, k, v, bias, out, lse = res
  g, g_lse = cotangents
  bh, s_q, d = q.shape
  s_kv = k.shape[1]
  block_q, _ = _block_sizes(s_q)
  _, block_k = _block_sizes(s_kv)
  g = g.astype(q.dtype)
  # d(out)/dS = P(delta-terms); d(lse)/dS = P — so an lse cotangent folds
  # into the shared (dp - delta) factor as delta -= g_lse.
  delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                  axis=-1, keepdims=True)  # [bh, s, 1]
  delta = delta - g_lse.astype(jnp.float32)
  scale = 1.0 / d**0.5
  q_spec, full_spec, bias_spec = _specs(s_q, s_kv, d, heads, block_q)
  q_full = pl.BlockSpec((1, s_q, d), lambda i, b: (i, 0, 0))
  row_blocked = pl.BlockSpec((1, block_q, 1), lambda i, b: (i, b, 0))
  row_full = pl.BlockSpec((1, s_q, 1), lambda i, b: (i, 0, 0))

  dq = pl.pallas_call(
      functools.partial(_dq_kernel, s_kv=s_kv, scale=scale, block_k=block_k),
      grid=(bh, pl.cdiv(s_q, block_q)),
      in_specs=[q_spec, full_spec, full_spec, bias_spec, q_spec,
                row_blocked, row_blocked],
      out_specs=pl.BlockSpec((1, block_q, d), lambda i, b: (i, b, 0)),
      out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
      interpret=_interpret(),
  )(q, k, v, bias, g, lse, delta)

  k_spec = pl.BlockSpec((1, block_k, d), lambda i, b: (i, b, 0))
  dk, dv = pl.pallas_call(
      functools.partial(_dkv_kernel, s_q=s_q, scale=scale, block_q=block_q),
      grid=(bh, pl.cdiv(s_kv, block_k)),
      in_specs=[q_full, k_spec, k_spec, bias_spec, q_full,
                row_full, row_full],
      out_specs=[k_spec, k_spec],
      out_shape=[
          jax.ShapeDtypeStruct((bh, s_kv, d), q.dtype),
          jax.ShapeDtypeStruct((bh, s_kv, d), q.dtype),
      ],
      interpret=_interpret(),
  )(q, k, v, bias, g, lse, delta)
  return dq, dk, dv, jnp.zeros_like(bias)


_flash_pair.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_with_lse(q, k, v, attention_mask=None):
  """Like :func:`flash_attention` but also returns the per-query
  log-sum-exp ``[batch, heads, seq]`` (float32) — the quantity needed to
  exactly merge attention results computed over disjoint key sets (ring
  attention's streaming-softmax combine). Gradients flow through both
  outputs.
  """
  b, h, s_q, d = q.shape
  s_kv = k.shape[2]
  if attention_mask is None:
    bias = jnp.zeros((b, s_kv), jnp.float32)
  else:
    bias = jnp.where(attention_mask != 0, 0.0, NEG_INF).astype(jnp.float32)
  bias = bias[:, None, :]  # [b, 1, s_kv]: TPU block-tiling-friendly layout
  sq_pad, skv_pad = _padded_len(s_q), _padded_len(s_kv)
  if sq_pad != s_q:
    q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_pad - s_q), (0, 0)))
  if skv_pad != s_kv:
    kv_pad = ((0, 0), (0, 0), (0, skv_pad - s_kv), (0, 0))
    k = jnp.pad(k, kv_pad)
    v = jnp.pad(v, kv_pad)
    bias = jnp.pad(bias, ((0, 0), (0, 0), (0, skv_pad - s_kv)),
                   constant_values=NEG_INF)
  out, lse = _flash_pair(q.reshape(b * h, sq_pad, d),
                         k.reshape(b * h, skv_pad, d),
                         v.reshape(b * h, skv_pad, d), bias, h)
  out = out.reshape(b, h, sq_pad, d)[:, :, :s_q, :]
  lse = lse.reshape(b, h, sq_pad)[:, :, :s_q]
  return out, lse


def flash_attention(q, k, v, attention_mask=None):
  """Blockwise-softmax attention; drop-in for the dense einsum path.

  ``q, k, v``: ``[batch, heads, seq, head_dim]``; ``attention_mask``:
  ``[batch, seq]`` with 1 = attend, 0 = padding (key side). Returns the
  context ``[batch, heads, seq, head_dim]`` in the input dtype.
  """
  return flash_attention_with_lse(q, k, v, attention_mask)[0]


def make_flash_attention(mesh, q_spec=None, mask_spec=None):
  """Wrap :func:`flash_attention` in ``shard_map`` for jitted use over a
  mesh: batch over (data, fsdp), heads over tensor — a ``pallas_call``
  has no GSPMD partitioning rule, so without this the compiler would
  replicate q/k/v onto every chip. The sequence axis must be unsharded
  (flash is per-chip block math; sequence sharding is ring attention's
  job — use ``attention_impl='ring_flash'`` for both).
  """
  from jax.sharding import PartitionSpec as P
  if dict(zip(mesh.axis_names, mesh.devices.shape)).get('seq', 1) > 1:
    raise ValueError(
        "flash attention does not shard the sequence axis; use "
        "attention_impl='ring_flash' on meshes with seq > 1")
  names = set(mesh.axis_names)
  batch_axes = tuple(a for a in ('data', 'fsdp') if a in names) or None
  head_axis = 'tensor' if 'tensor' in names else None
  q_spec = q_spec or P(batch_axes, head_axis, None, None)
  mask_spec = mask_spec or P(batch_axes, None)

  @functools.partial(
      jax.shard_map,
      mesh=mesh,
      in_specs=(q_spec, q_spec, q_spec, mask_spec),
      out_specs=q_spec,
      check_vma=False)
  def _sharded(q, k, v, mask):
    return flash_attention(q, k, v, mask)

  return _sharded

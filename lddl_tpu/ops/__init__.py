"""Device-side (JAX) and vectorized host kernels for the data pipeline.

The reference's hot loops are per-token Python (masking,
``lddl/dask/bert/pretrain.py:182-238``); here they are batched array
programs: one masking call per partition over a padded ``[N, L]`` id
matrix, jit-compiled onto the TPU when one is attached (host numpy
otherwise).
"""

from .masking import (  # noqa: F401
    assemble_pair_matrix,
    mask_batch,
    mask_batch_device,
    mask_batch_host,
    mask_partition_device,
    mask_partition_host,
    resolve_mask_backend,
)

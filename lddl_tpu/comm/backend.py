"""Host-level collective communication backends.

The reference stack needs collectives in three places, all for *control and
metadata* (never bulk data, which moves through the shared filesystem):

  - preprocessing bootstrap + task distribution (dask-mpi,
    reference ``lddl/dask/bert/pretrain.py:573-576``),
  - the load balancer's per-file sample-count Allreduce + barriers
    (reference ``lddl/dask/load_balance.py:210-223``),
  - dataset-init metadata all-reduce in the loaders
    (reference ``lddl/torch/datasets.py:163-193``).

On TPU pods the idiomatic substrate is ``jax.distributed`` +
``multihost_utils`` over ICI/DCN — that is :class:`JaxProcessBackend`.
:class:`NullBackend` serves single-process runs, and :class:`FileBackend`
provides a dependency-free shared-filesystem rendezvous so multi-process
behavior is testable on one machine without MPI/NCCL (mirroring the
reference's "N local processes" test pattern).
"""

import os
import pickle
import tempfile
import time

import numpy as np


class CommBackend:
  """Protocol: rank/world_size + tiny-metadata collectives."""

  @property
  def rank(self):
    raise NotImplementedError

  @property
  def world_size(self):
    raise NotImplementedError

  def allgather_object(self, obj):
    """Gather one picklable object per rank; returns list ordered by rank."""
    raise NotImplementedError

  def allreduce_sum(self, array):
    """Element-wise sum of a small numpy array across ranks."""
    arrays = self.allgather_object(np.asarray(array))
    out = arrays[0].copy()
    for a in arrays[1:]:
      out += a
    return out

  def broadcast_object(self, obj, root=0):
    return self.allgather_object(obj)[root]

  def barrier(self):
    self.allgather_object(None)


class NullBackend(CommBackend):
  """Single-process world."""

  @property
  def rank(self):
    return 0

  @property
  def world_size(self):
    return 1

  def allgather_object(self, obj):
    return [obj]

  def barrier(self):
    pass


class FileBackend(CommBackend):
  """Shared-filesystem rendezvous collectives.

  Each collective op gets a monotonically increasing sequence number; rank r
  writes ``op<seq>.rank<r>`` and spin-waits for all peers. Files are written
  atomically (tmp + rename) so partially-written payloads are never read.
  Intended for local multi-process tests and small CPU clusters with a
  shared FS — TPU pods should use :class:`JaxProcessBackend`.
  """

  def __init__(self, rendezvous_dir, rank, world_size, timeout=120.0,
               poll_interval=0.005, run_id=None):
    self._dir = rendezvous_dir
    os.makedirs(rendezvous_dir, exist_ok=True)
    self._rank = rank
    self._world_size = world_size
    self._timeout = timeout
    self._poll = poll_interval
    self._seq = 0
    # Namespace op files by run id so a reused rendezvous dir (e.g. after a
    # crash/restart) never reads a previous run's stale payloads. All ranks
    # of one run must agree on run_id (env LDDL_COMM_RUN_ID, or a job id).
    self._run_id = run_id if run_id is not None else os.environ.get(
        'LDDL_COMM_RUN_ID', 'run0')

  @property
  def rank(self):
    return self._rank

  @property
  def world_size(self):
    return self._world_size

  def _path(self, seq, rank):
    return os.path.join(self._dir, f'{self._run_id}.op{seq}.rank{rank}')

  def allgather_object(self, obj):
    seq = self._seq
    self._seq += 1
    payload = pickle.dumps(obj)
    fd, tmp = tempfile.mkstemp(dir=self._dir)
    with os.fdopen(fd, 'wb') as f:
      f.write(payload)
    os.rename(tmp, self._path(seq, self._rank))
    results = []
    deadline = time.monotonic() + self._timeout
    for r in range(self._world_size):
      p = self._path(seq, r)
      while not os.path.exists(p):
        if time.monotonic() > deadline:
          raise TimeoutError(
              f'rank {self._rank}: timed out waiting for rank {r} at '
              f'collective #{seq} (dir={self._dir})')
        time.sleep(self._poll)
      with open(p, 'rb') as f:
        results.append(pickle.loads(f.read()))
    return results


class JaxProcessBackend(CommBackend):
  """Host-level collectives over a JAX multi-process (TPU pod) runtime.

  Requires ``jax.distributed.initialize()`` to have been called (the
  framework's CLIs do this when ``--comm jax`` is selected). Collectives
  ride XLA's ICI/DCN transport via ``multihost_utils``.
  """

  def __init__(self):
    import jax
    self._jax = jax

  @property
  def rank(self):
    return self._jax.process_index()

  @property
  def world_size(self):
    return self._jax.process_count()

  def allgather_object(self, obj):
    from jax.experimental import multihost_utils
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # Pad to the max payload size across ranks so shapes are uniform.
    sizes = multihost_utils.process_allgather(
        np.array([payload.size], dtype=np.int64))
    max_size = int(np.max(sizes))
    padded = np.zeros((max_size,), dtype=np.uint8)
    padded[:payload.size] = payload
    gathered = multihost_utils.process_allgather(padded)
    flat_sizes = np.asarray(sizes).reshape(-1)
    return [
        pickle.loads(gathered[r, :int(flat_sizes[r])].tobytes())
        for r in range(self.world_size)
    ]

  def allreduce_sum(self, array):
    from jax.experimental import multihost_utils
    # process_allgather stacks along a new leading axis (one row per process).
    gathered = multihost_utils.process_allgather(np.asarray(array))
    return np.sum(np.asarray(gathered), axis=0)

  def barrier(self):
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices('lddl_tpu_barrier')


def get_backend(name=None, **kwargs):
  """Construct a backend by name (default from ``LDDL_COMM`` env, else null).

  Names: ``null`` | ``file`` | ``jax``.
  """
  name = name or os.environ.get('LDDL_COMM', 'null')
  if name == 'null':
    return NullBackend()
  if name == 'file':
    return FileBackend(
        kwargs.get('rendezvous_dir') or os.environ['LDDL_COMM_DIR'],
        kwargs.get('rank', int(os.environ.get('LDDL_RANK', '0'))),
        kwargs.get('world_size', int(os.environ.get('LDDL_WORLD_SIZE', '1'))),
        run_id=kwargs.get('run_id'),
    )
  if name == 'jax':
    return JaxProcessBackend()
  raise ValueError(f'unknown comm backend {name!r}')

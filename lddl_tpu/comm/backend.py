"""Host-level collective communication backends.

The reference stack needs collectives in three places, all for *control and
metadata* (never bulk data, which moves through the shared filesystem):

  - preprocessing bootstrap + task distribution (dask-mpi,
    reference ``lddl/dask/bert/pretrain.py:573-576``),
  - the load balancer's per-file sample-count Allreduce + barriers
    (reference ``lddl/dask/load_balance.py:210-223``),
  - dataset-init metadata all-reduce in the loaders
    (reference ``lddl/torch/datasets.py:163-193``).

On TPU pods the idiomatic substrate is ``jax.distributed`` +
``multihost_utils`` over ICI/DCN — that is :class:`JaxProcessBackend`.
:class:`NullBackend` serves single-process runs, and :class:`FileBackend`
provides a dependency-free shared-filesystem rendezvous so multi-process
behavior is testable on one machine without MPI/NCCL (mirroring the
reference's "N local processes" test pattern).
"""

import os
import pickle
import random
import tempfile
import threading
import time

import numpy as np

from ..core import faults
from ..telemetry import get_telemetry
from ..telemetry.trace import get_tracer


def comm_timeout(default=120.0):
  """Collective timeout in seconds (env ``LDDL_COMM_TIMEOUT``)."""
  try:
    return float(os.environ.get('LDDL_COMM_TIMEOUT', default))
  except ValueError:
    return default


def comm_heartbeat_interval(default=1.0):
  """Liveness cadence in seconds (env ``LDDL_COMM_HEARTBEAT``): how often
  FileBackend probes a silent peer's death beacon while waiting, and how
  often the executor's lease heartbeat pump republishes its counter.
  Probing more or less often changes only failure-detection latency,
  never any result."""
  try:
    return max(0.05, float(os.environ.get('LDDL_COMM_HEARTBEAT', default)))
  except ValueError:
    return default


def _retry_io(fn, what, retries=3, base_delay=0.01):
  """Run ``fn()`` retrying transient ``OSError`` with bounded backoff.

  Shared filesystems (the FileBackend's whole substrate) throw spurious
  EIO/ESTALE/ENOENT during rename races and NFS attribute-cache misses;
  one failed stat must not abort a run the lease protocol could finish.
  Bounded: a persistent error still surfaces, with the original
  traceback, after ``retries`` attempts.
  """
  for attempt in range(retries + 1):
    try:
      return fn()
    except OSError:
      if attempt == retries:
        raise
      get_telemetry().counter('comm.io_retries').add(1)
      time.sleep(base_delay * (2 ** attempt))


def jitter_source(seed=None):
  """A dedicated, seeded ``random.Random`` for retry jitter.

  Backoff jitter must never touch the global RNG (data order is
  deterministic by contract) and must still differ across processes so
  a thundering herd decorrelates — seeding from the pid gives both.
  """
  return random.Random(os.getpid() if seed is None else seed)


def backoff_delay(attempt, base=0.05, cap=2.0, jitter=None):
  """Exponential backoff delay for retry ``attempt`` (0-based), capped,
  with optional multiplicative jitter in [0.5, 1.5) drawn from a
  :func:`jitter_source`. Jitter changes only retry *timing* — every
  delay stays within [0.5 * base, 1.5 * cap] — never any result."""
  delay = min(cap, base * (2 ** attempt))
  if jitter is not None:
    delay *= 0.5 + jitter.random()
  return delay


class LeaseStaleness:
  """The fleet-wide lease-revocation verdict, factored for every lease
  consumer (the elastic executor's ``_LeaseClaimer``, the data
  service's ``_ServeClaimer``).

  An owner is stale when the substrate proves it dead (pid beacon) or
  its heartbeat *counter* has not moved for the lease timeout measured
  on the observer's own monotonic clock — counters, not timestamps, so
  cross-host clock skew can never manufacture a revocation.
  """

  def __init__(self, store, timeout):
    self._store = store
    self._timeout = timeout
    self._hb_seen = {}  # owner -> (counter value, monotonic when it changed)

  def stale(self, owner):
    if self._store.owner_dead(owner):
      return True  # positive death signal: no need to wait out the lease
    hb = self._store.read_heartbeat(owner)
    now = time.monotonic()
    prev = self._hb_seen.get(owner)
    if prev is None or prev[0] != hb:
      self._hb_seen[owner] = (hb, now)
      return False
    # lddl: noqa[LDA003] lease staleness: survivors revoke only on a
    # heartbeat counter silent past the lease timeout (or the positive
    # death probe above). Racing observers converge on the same verdict
    # via the revoke CAS, and re-execution is idempotent — outputs are
    # f(task, global_index) behind atomic renames — so clock skew can
    # cost duplicated work, never divergent bytes.
    if now - prev[1] > self._timeout:
      return True
    return False


class CommBackend:
  """Protocol: rank/world_size + tiny-metadata collectives."""

  #: Whether the elastic lease-claimed executor path should use this
  #: backend's lease store by default (LDDL_ELASTIC=auto). True only
  #: where the claim/heartbeat substrate is first-class (FileBackend).
  elastic_default = False

  def lease_store(self, namespace):
    """A :class:`LeaseStore` over this backend's substrate for one map
    phase (``namespace`` must be identical across ranks), or None when
    the backend has no CAS/KV substrate — the executor then falls back
    to the static stride."""
    return None

  @property
  def rank(self):
    raise NotImplementedError

  @property
  def world_size(self):
    raise NotImplementedError

  def allgather_object(self, obj):
    """Gather one picklable object per rank; returns list ordered by rank."""
    raise NotImplementedError

  @property
  def collective_seq(self):
    """Monotonic count of collectives issued so far, or None if the
    backend does not sequence them. The same counter trace alignment
    keys on — consumers tagging gathered payloads with it can reject
    entries from mismatched rounds."""
    return None

  def allreduce_sum(self, array):
    """Element-wise sum of a small numpy array across ranks."""
    arrays = self.allgather_object(np.asarray(array))
    out = arrays[0].copy()
    for a in arrays[1:]:
      out += a
    return out

  def broadcast_object(self, obj, root=0):
    return self.allgather_object(obj)[root]

  def barrier(self):
    self.allgather_object(None)


class NullBackend(CommBackend):
  """Single-process world."""

  @property
  def rank(self):
    return 0

  @property
  def world_size(self):
    return 1

  def allgather_object(self, obj):
    return [obj]

  def barrier(self):
    pass


class FileBackend(CommBackend):
  """Shared-filesystem rendezvous collectives.

  Each collective op gets a monotonically increasing sequence number; rank r
  writes ``op<seq>.rank<r>`` and spin-waits for all peers. Files are written
  atomically (tmp + rename) so partially-written payloads are never read.
  Intended for local multi-process tests and small CPU clusters with a
  shared FS — TPU pods should use :class:`JaxProcessBackend`.
  """

  elastic_default = True

  def __init__(self, rendezvous_dir, rank, world_size, timeout=None,
               poll_interval=0.005, run_id=None):
    self._dir = rendezvous_dir
    os.makedirs(rendezvous_dir, exist_ok=True)
    self._rank = rank
    self._world_size = world_size
    # Explicit ctor args win; otherwise env-tunable (LDDL_COMM_TIMEOUT /
    # LDDL_COMM_HEARTBEAT) so a slow shared mount can stretch both the
    # collective deadline and the liveness cadence without code changes.
    self._timeout = comm_timeout() if timeout is None else timeout
    self._liveness_interval = comm_heartbeat_interval()
    self._poll = poll_interval
    self._seq = 0
    self._gc_upto = 0  # own op files below this seq have been deleted
    # Namespace op files by run id so a reused rendezvous dir (e.g. after a
    # crash/restart) never reads a previous run's stale payloads. All ranks
    # of one run must agree on run_id (env LDDL_COMM_RUN_ID, or a job id).
    self._run_id = run_id if run_id is not None else os.environ.get(
        'LDDL_COMM_RUN_ID', 'run0')
    # Liveness beacon: pid@pidns@starttime, written once. Peers in the
    # SAME pid namespace use it to fail fast (naming the dead rank) when
    # a rank is SIGKILLed mid-run instead of stalling until the
    # collective timeout. The pid-namespace token (readlink of
    # /proc/self/ns/pid) — not the hostname — gates the probe: two
    # containers or cloned VMs sharing a rendezvous mount can share a
    # hostname while their pids are mutually meaningless, which would
    # make a hostname-gated probe kill healthy runs. The process start
    # time (field 22 of /proc/<pid>/stat) detects pid reuse. Cross-
    # namespace peers rely on the timeout, as before.
    self._pidns = self._pid_namespace()
    self._starttime = self._pid_starttime(os.getpid())
    self._write_atomic(
        f'{os.getpid()}@{self._pidns}@{self._starttime}'.encode(),
        self._alive_path(self._rank))

  @property
  def rank(self):
    return self._rank

  @property
  def world_size(self):
    return self._world_size

  @property
  def collective_seq(self):
    return self._seq

  def _path(self, seq, rank):
    return os.path.join(self._dir, f'{self._run_id}.op{seq}.rank{rank}')

  def _progress_path(self, rank):
    return os.path.join(self._dir, f'{self._run_id}.progress.rank{rank}')

  def _alive_path(self, rank):
    return os.path.join(self._dir, f'{self._run_id}.alive.rank{rank}')

  @staticmethod
  def _pid_namespace():
    """Identity of this process's pid namespace ('' when unavailable —
    then the beacon never gates a probe and the timeout rules)."""
    try:
      return os.readlink('/proc/self/ns/pid')
    except OSError:
      return ''

  @staticmethod
  def _pid_starttime(pid):
    """Kernel start time of ``pid`` (clock ticks since boot; field 22 of
    /proc/<pid>/stat), or '' when unreadable. Distinguishes a reused pid
    from the original process."""
    try:
      with open(f'/proc/{pid}/stat', 'rb') as f:
        data = f.read()
      return data[data.rfind(b')') + 2:].split()[19].decode()
    except (OSError, IndexError):
      return ''

  @classmethod
  def _pid_dead(cls, pid, starttime):
    """Positive death signal for a pid in our namespace: process gone,
    a zombie (SIGKILLed but not yet reaped by its launcher —
    ``kill(pid, 0)`` still succeeds on zombies, so read the /proc state
    instead), or a different process now wearing the pid (start-time
    mismatch). Any probe uncertainty returns False (timeout backstops).
    """
    try:
      with open(f'/proc/{pid}/stat', 'rb') as f:
        data = f.read()
    except FileNotFoundError:
      return True
    except OSError:
      return False
    tail = data[data.rfind(b')') + 2:].split()
    if tail and tail[0] == b'Z':
      return True
    return bool(starttime) and cls._pid_starttime(pid) not in ('', starttime)

  def peer_positively_dead(self, r):
    """Positive death probe for rank ``r`` via its liveness beacon: True
    only when the beacon names a same-pid-namespace process that is
    provably gone (or a zombie, or a reused pid). Missing beacon,
    foreign namespace, or any probe error all return False — absence of
    proof is never treated as death. Shared by the collective fail-fast
    path and the lease stores' stale-owner revocation."""
    try:
      with open(self._alive_path(r), 'rb') as f:
        pid_s, pidns, starttime = f.read().decode().split('@', 2)
      if not self._pidns or pidns != self._pidns or not pid_s.isdigit():
        return False
      return self._pid_dead(int(pid_s), starttime)
    except Exception:
      return False  # beacon unreadable / not started yet: timeout rules

  def _check_peer_alive(self, r, seq):
    """Raise (naming the rank) when a same-pid-namespace peer's process
    is dead. Only a *positive* death signal raises: a missing or
    foreign-namespace beacon, or any probe error, keeps the normal
    timeout path.
    """
    if self.peer_positively_dead(r):
      pid_s = self._beacon_pid(r)
      # Death is only an error if the peer died *without* publishing
      # this collective. A peer whose last act was writing its payload
      # for #seq and exiting cleanly (e.g. last rank of a finishing job)
      # races this probe: its file may have appeared between our stat
      # poll and this liveness check, so re-check before raising.
      if os.path.exists(self._path(seq, r)):
        return
      raise RuntimeError(
          f'rank {self._rank}: rank {r} (pid {pid_s}) died before '
          f'collective #{seq}; failing fast instead of waiting out the '
          f'{self._timeout:.0f}s timeout (dir={self._dir})')

  def _beacon_pid(self, r):
    """Rank ``r``'s beacon pid string, for error messages only ('?'
    when the beacon is unreadable)."""
    try:
      with open(self._alive_path(r), 'rb') as f:
        return f.read().decode().split('@', 2)[0]
    except (OSError, UnicodeDecodeError):
      return '?'

  def _write_atomic(self, payload, dst):

    def _attempt():
      # Inside the retry closure: an injected transient write error must
      # exercise the same bounded-backoff path a real EIO flap would.
      faults.inject('comm.write', rank=self._rank)
      fd, tmp = tempfile.mkstemp(dir=self._dir)
      with os.fdopen(fd, 'wb') as f:
        f.write(payload)
      os.rename(tmp, dst)

    _retry_io(_attempt, f'atomic write {os.path.basename(dst)}')

  def _read_payload(self, path):
    """Read a published payload file, retrying transient filesystem
    errors. The file provably exists (we stat-polled it into view), so
    even a mid-rename ENOENT flap on NFS is transient, not absence."""

    def _attempt():
      with open(path, 'rb') as f:
        return f.read()

    return pickle.loads(
        _retry_io(_attempt, f'payload read {os.path.basename(path)}'))

  def _collect_garbage(self, seq):
    """Delete this rank's op files that no peer can still need.

    A peer whose progress marker reads ``s`` has *completed* every
    collective below ``s`` (it writes the marker before publishing its
    payload for ``s``), so it will never re-read files of seq < s. Each
    rank deletes only its own files, so deletion races cannot occur.
    Without this, a long run grows one file per rank per collective
    forever.
    """
    min_seq = seq
    for r in range(self._world_size):
      if r == self._rank:
        continue
      try:
        with open(self._progress_path(r), 'rb') as f:
          min_seq = min(min_seq, int(f.read()))
      except (OSError, ValueError):
        return  # peer not started yet (or marker mid-rename): nothing safe
    for s in range(self._gc_upto, min_seq):
      try:
        os.remove(self._path(s, self._rank))
      except OSError:
        pass
    self._gc_upto = max(self._gc_upto, min_seq)

  def allgather_object(self, obj):
    tele = get_telemetry()
    tracer = get_tracer()
    t_start = time.monotonic() if (tele.enabled or tracer.enabled) else 0.0
    seq = self._seq
    self._seq += 1
    # Publish progress (highest collective this rank has *entered* — all
    # below are fully read) before the payload, then reap dead files.
    self._write_atomic(str(seq).encode(), self._progress_path(self._rank))
    self._collect_garbage(seq)
    self._write_atomic(pickle.dumps(obj), self._path(seq, self._rank))
    results = []
    deadline = time.monotonic() + self._timeout
    for r in range(self._world_size):
      p = self._path(seq, r)
      # Exponential backoff from the base poll up to 50 ms: N waiting
      # ranks each stat-polling every 5 ms measurably steals CPU from the
      # ranks still working when cores are scarce (an 8-process run on
      # one core spent most of its wall-clock here); long waits back off,
      # short waits stay snappy.
      delay = self._poll
      last_liveness = time.monotonic()
      while not os.path.exists(p):
        now = time.monotonic()
        # lddl: noqa[LDA003] timeout detection: this branch only aborts
        # a stuck collective (raises), it never silently diverges ranks.
        if now > deadline:
          raise TimeoutError(
              f'rank {self._rank}: timed out waiting for rank {r} at '
              f'collective #{seq} (dir={self._dir})')
        # lddl: noqa[LDA003] liveness-probe rate limit: probing more or
        # less often changes only failure latency, never the result.
        if now - last_liveness >= self._liveness_interval:
          self._check_peer_alive(r, seq)  # cheap: one stat + /proc read
          last_liveness = now
        time.sleep(delay)
        # Never poll faster than the configured interval: backoff only
        # coarsens waits, it must not override a deliberately slow poll
        # (e.g. a rendezvous dir on NFS).
        delay = min(delay * 2, max(self._poll, 0.05))
      results.append(self._read_payload(p))
    if tele.enabled:
      # Collective latency includes peer wait, so cross-rank spread here
      # is the straggler signal the report surfaces per rank.
      tele.histogram('comm.allgather_seconds').observe(
          time.monotonic() - t_start)
      tele.counter('comm.allgathers').add(1)
    if tracer.enabled:
      # The seq number keys cross-rank event matching: all ranks finish
      # collective #seq within one collective latency, so the trace
      # merger refines per-rank clock offsets from these events.
      tracer.complete('comm.allgather', t_start,
                      time.monotonic() - t_start, args={'seq': seq})
    return results

  def lease_store(self, namespace):
    """Lease/claim substrate for one elastic map phase, rooted at
    ``<rendezvous>/<run_id>.elastic.<namespace>/``. Keyed on run_id like
    the op files: restarting with the same run_id *resumes* (completion
    manifests from the previous incarnation are honored), a fresh run_id
    starts clean."""
    root = os.path.join(self._dir, f'{self._run_id}.elastic.{namespace}')
    return FileLeaseStore(root, self._rank,
                          dead_probe=self.peer_positively_dead)


class LeaseStore:
  """Claim/heartbeat/manifest primitives for one elastic map phase.

  Key grammar (shared by both implementations; ``gi`` = global task
  index, ``gen`` = revocation generation)::

    claim.<gi>.g<gen>   ascii owner rank       CAS: first writer wins
    revoke.<gi>.g<gen>  ascii revoker rank     CAS: invalidates <gen>
    done.<gi>           pickled task result    idempotent atomic publish
    hb.rank<r>          ascii counter          mutable heartbeat

  Claims and revokes are write-once (CAS) so every rank agrees on one
  owner per (gi, gen) and one revocation winner; ``done`` manifests and
  heartbeats are idempotent overwrites. Values never need deletion
  within a phase — a namespace is cheap and garbage-collects with its
  rendezvous directory / coordination service.
  """

  rank = 0
  #: Directory workers can publish ``done.<gi>`` manifests into via the
  #: write-back-ordered path (None: only the parent process can publish).
  manifest_root = None

  def try_claim(self, key):
    """Atomically create ``key`` owned by this rank. Returns None on
    success (we own it) or the owning rank (>= 0; -1 when the owner is
    momentarily unreadable)."""
    raise NotImplementedError

  def publish(self, key, payload):
    """Idempotent atomic write of ``payload`` (bytes) at ``key``."""
    raise NotImplementedError

  def read(self, key):
    """Payload bytes at ``key``, or None when absent."""
    raise NotImplementedError

  def list(self, prefix):
    """Sorted keys in this namespace starting with ``prefix``."""
    raise NotImplementedError

  def heartbeat(self, value):
    self.publish(f'hb.rank{self.rank}', str(int(value)).encode())

  def read_heartbeat(self, r):
    raw = self.read(f'hb.rank{r}')
    try:
      return None if raw is None else int(raw)
    except ValueError:
      return None

  def owner_dead(self, r):
    """Positive-signal death probe for rank ``r`` (False when the
    substrate cannot prove death — staleness timeouts then rule)."""
    return False


class HeartbeatPump:
  """Background lease heartbeat for one elastic phase or train fleet.

  Republishes a monotonically increasing counter every interval while
  the rank executes — the main thread may block for minutes inside pool
  waits or compiled train steps, so liveness cannot ride the claim or
  collective traffic itself. The value is a counter, not a timestamp:
  observers measure staleness of an *unchanging* counter on their own
  clock, so cross-host clock skew can never manufacture a revocation.

  ``fault_site``: optional :mod:`lddl_tpu.core.faults` site injected
  inside the republish attempt (the train membership pump passes
  ``train.heartbeat``), so kill-style specs can silence a rank's
  liveness and raise-style specs exercise the absorbed-transient path
  a flaky substrate would.
  """

  def __init__(self, store, interval, fault_site=None):
    self._store = store
    self._interval = interval
    self._fault_site = fault_site
    self._stop = threading.Event()
    self._beats = 0
    # First beat lands before any claim this rank makes: a peer that
    # sees our claim can always already see a heartbeat to age.
    self._store.heartbeat(0)
    self._thread = threading.Thread(
        target=self._run, name='lddl-lease-hb', daemon=True)
    self._thread.start()

  def _run(self):
    while not self._stop.wait(self._interval):
      self._beats += 1
      try:
        if self._fault_site:
          faults.inject(self._fault_site,
                        rank=getattr(self._store, 'rank', 0))
        self._store.heartbeat(self._beats)
      except OSError:
        continue  # transient substrate flap: the next beat retries

  def stop(self):
    self._stop.set()
    self._thread.join(timeout=5.0)


class FileLeaseStore(LeaseStore):
  """Shared-filesystem lease store: one flat directory per phase.

  CAS is ``os.link(tmp, dst)`` — atomic create-*with*-content, so a
  reader that wins the EEXIST race never observes an empty claim file
  (an O_EXCL-create-then-write scheme would have that window). All
  writes ride the same bounded transient-error retry as the collective
  substrate.
  """

  def __init__(self, root, rank, dead_probe=None):
    self.root = root
    self.rank = rank
    self.manifest_root = root
    self._dead_probe = dead_probe
    os.makedirs(root, exist_ok=True)

  def _p(self, key):
    return os.path.join(self.root, key)

  def try_claim(self, key):
    dst = self._p(key)

    def _attempt():
      fd, tmp = tempfile.mkstemp(dir=self.root)
      try:
        with os.fdopen(fd, 'wb') as f:
          f.write(str(self.rank).encode())
        try:
          os.link(tmp, dst)
          return None
        except FileExistsError:
          return self._read_owner(dst)
      finally:
        os.unlink(tmp)

    return _retry_io(_attempt, f'claim {key}')

  def _read_owner(self, dst):
    def _attempt():
      with open(dst, 'rb') as f:
        return f.read()
    try:
      return int(_retry_io(_attempt, 'claim owner read').decode())
    except (OSError, ValueError, UnicodeDecodeError):
      return -1  # owner momentarily unreadable: foreign, identity unknown

  def publish(self, key, payload):
    dst = self._p(key)

    def _attempt():
      fd, tmp = tempfile.mkstemp(dir=self.root)
      with os.fdopen(fd, 'wb') as f:
        f.write(payload)
      os.rename(tmp, dst)

    _retry_io(_attempt, f'publish {key}')

  def read(self, key):
    path = self._p(key)

    def _attempt():
      try:
        with open(path, 'rb') as f:
          return f.read()
      except FileNotFoundError:
        return None  # absence is an answer, not a transient error

    return _retry_io(_attempt, f'read {key}')

  def list(self, prefix):
    return _retry_io(
        lambda: sorted(
            n for n in os.listdir(self.root) if n.startswith(prefix)),
        f'list {prefix}')

  def owner_dead(self, r):
    return bool(self._dead_probe and self._dead_probe(r))


class KVLeaseStore(LeaseStore):
  """Best-effort lease store over the jax coordination-service KV.

  The coordination service rejects ``InsertKeyValue`` on an existing
  key, which is the CAS :meth:`try_claim` leans on. Should a runtime
  silently overwrite instead, two ranks may both believe they won a
  claim and both execute the partition — duplicated work, never wrong
  bytes: task outputs are ``f(task, global_index)`` and shard writes are
  atomic renames, so re-execution is idempotent by construction. No
  cross-host pid probe exists here, so :meth:`owner_dead` always defers
  to the heartbeat-staleness path.
  """

  def __init__(self, client, namespace, rank):
    self._client = client
    self._pfx = f'lddl/el/{namespace}/'
    self.rank = rank

  def try_claim(self, key):
    try:
      self._client.key_value_set_bytes(
          self._pfx + key, str(self.rank).encode())
      return None
    except Exception:
      raw = self.read(key)
      try:
        return -1 if raw is None else int(raw)
      except ValueError:
        return -1

  def publish(self, key, payload):
    try:
      self._client.key_value_set_bytes(self._pfx + key, bytes(payload))
    except Exception:
      # Existing key (heartbeat republish / idempotent manifest rewrite):
      # delete+set. Only this rank writes its own mutable keys, so the
      # non-atomic pair cannot interleave with another writer.
      self._client.key_value_delete(self._pfx + key)
      self._client.key_value_set_bytes(self._pfx + key, bytes(payload))

  def read(self, key, timeout_ms=50):
    try:
      return self._client.blocking_key_value_get_bytes(
          self._pfx + key, timeout_ms)
    except Exception:
      return None  # missing key surfaces as a get timeout

  def list(self, prefix):
    try:
      entries = self._client.key_value_dir_get_bytes(self._pfx)
    except Exception:
      return []
    out = []
    for key, _value in entries:
      if isinstance(key, bytes):
        key = key.decode()
      if key.startswith(self._pfx):
        key = key[len(self._pfx):]
      if key.startswith(prefix):
        out.append(key)
    return sorted(out)


def ensure_jax_distributed():
  """Initialize the ``jax.distributed`` runtime once (idempotent).

  Resolution order:
    1. already initialized — no-op;
    2. explicit ``LDDL_COORDINATOR_ADDRESS`` / ``LDDL_NUM_PROCESSES`` /
       ``LDDL_PROCESS_ID`` env config (for CPU clusters and tests) — a
       failure here raises, explicit config must not degrade silently;
    3. ``jax.distributed.initialize()`` auto-detection (TPU pod metadata,
       SLURM, …); when no cluster is detected the process continues
       single-process with a warning.

  Returns True when the multi-process runtime is up, False for the
  single-process fallback.
  """
  import jax

  from ..core.compat import distributed_is_initialized
  if distributed_is_initialized():
    return True
  addr = os.environ.get('LDDL_COORDINATOR_ADDRESS')
  if addr:
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(os.environ['LDDL_NUM_PROCESSES']),
        process_id=int(os.environ['LDDL_PROCESS_ID']))
    return True
  try:
    jax.distributed.initialize()
    return True
  except ValueError as e:
    # Only the specific "no cluster environment detected" outcome (jax
    # leaves coordinator_address unset when auto-detection finds nothing)
    # may degrade to single-process — e.g. `--comm jax` on a lone TPU-VM.
    # Anything else (coordinator unreachable, pod metadata timeout) means
    # a real multi-process world exists and MUST fail loudly: a host that
    # silently continued as world_size=1 would race the true rank 0 over
    # the shared sink while the other hosts hang waiting for it.
    if 'coordinator_address' not in str(e):
      raise
    import warnings
    warnings.warn(
        f'jax.distributed.initialize() found no cluster ({e}); '
        'continuing single-process')
    return False


#: Per-collective wait bound on the coordination-service fallback path.
#: Generous because host-level collectives gate whole pipeline stages
#: (a rank can legitimately arrive minutes after the first one).
_KV_TIMEOUT_MS = int(os.environ.get('LDDL_COMM_KV_TIMEOUT_MS', '600000'))


class JaxProcessBackend(CommBackend):
  """Host-level collectives over a JAX multi-process (TPU pod) runtime.

  Construction initializes ``jax.distributed`` via
  :func:`ensure_jax_distributed` (idempotent), so selecting ``--comm jax``
  in any CLI is sufficient — no separate bootstrap call. Collectives ride
  XLA's ICI/DCN transport via ``multihost_utils`` — except when the XLA
  backend has no cross-process collectives at all (the CPU backend: the
  jit psum under ``multihost_utils`` raises INVALID_ARGUMENT). There the
  same metadata-sized payloads move through the coordination service's
  KV store and ``wait_at_barrier``, which exist on every distributed
  runtime regardless of device platform, so ``--comm jax`` worlds are
  testable on CPU-only hosts.
  """

  def __init__(self, initialize=True):
    import jax
    self._jax = jax
    # Collective sequence number for trace-event matching across ranks
    # (all ranks issue the same collective sequence by construction).
    # The KV fallback also keys its store entries / barrier ids on it.
    self._seq = 0
    if initialize:
      ensure_jax_distributed()

  @property
  def rank(self):
    return self._jax.process_index()

  @property
  def world_size(self):
    return self._jax.process_count()

  @property
  def collective_seq(self):
    return self._seq

  def _kv_client(self):
    """Coordination-service client when XLA can't do the collective."""
    if self._jax.default_backend() != 'cpu' or self.world_size <= 1:
      return None
    from ..core.compat import distributed_client
    return distributed_client()

  def lease_store(self, namespace):
    """KV-backed lease store (any device platform — the coordination
    service exists on every multi-process runtime), or None when no
    distributed client is reachable (single-process: nothing to lease)."""
    if self.world_size <= 1:
      return None
    try:
      from ..core.compat import distributed_client
      client = distributed_client()
    except Exception:
      return None
    if client is None:
      return None
    return KVLeaseStore(client, namespace, self.rank)

  def _kv_allgather(self, payload, seq):
    """All ranks' bytes via the KV store: set own key, blocking-get all
    ranks' keys (the blocking get is the synchronization), then a
    trailing barrier so every rank can delete its own key without
    racing a slower reader."""
    client = self._kv_client()
    base = f'lddl/ag/{seq}'
    client.key_value_set_bytes(f'{base}/{self.rank}', bytes(payload))
    out = [
        client.blocking_key_value_get_bytes(f'{base}/{r}', _KV_TIMEOUT_MS)
        for r in range(self.world_size)
    ]
    client.wait_at_barrier(f'lddl_ag_done_{seq}', _KV_TIMEOUT_MS)
    client.key_value_delete(f'{base}/{self.rank}')
    return out

  def allgather_object(self, obj):
    tele = get_telemetry()
    tracer = get_tracer()
    t_start = time.monotonic() if (tele.enabled or tracer.enabled) else 0.0
    seq = self._seq
    self._seq += 1
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    if self._kv_client() is not None:
      out = [pickle.loads(blob) for blob in self._kv_allgather(payload, seq)]
    else:
      from jax.experimental import multihost_utils
      # Pad to the max payload size across ranks so shapes are uniform.
      sizes = multihost_utils.process_allgather(
          np.array([payload.size], dtype=np.int64))
      max_size = int(np.max(sizes))
      padded = np.zeros((max_size,), dtype=np.uint8)
      padded[:payload.size] = payload
      gathered = multihost_utils.process_allgather(padded)
      flat_sizes = np.asarray(sizes).reshape(-1)
      out = [
          pickle.loads(gathered[r, :int(flat_sizes[r])].tobytes())
          for r in range(self.world_size)
      ]
    if tele.enabled:
      tele.histogram('comm.allgather_seconds').observe(
          time.monotonic() - t_start)
      tele.counter('comm.allgathers').add(1)
    if tracer.enabled:
      tracer.complete('comm.allgather', t_start,
                      time.monotonic() - t_start, args={'seq': seq})
    return out

  def allreduce_sum(self, array):
    if self._kv_client() is not None:
      seq = self._seq
      self._seq += 1
      payload = np.frombuffer(pickle.dumps(np.asarray(array)), dtype=np.uint8)
      rows = [pickle.loads(b) for b in self._kv_allgather(payload, seq)]
      return np.sum(np.stack(rows, axis=0), axis=0)
    from jax.experimental import multihost_utils
    # process_allgather stacks along a new leading axis (one row per process).
    gathered = multihost_utils.process_allgather(np.asarray(array))
    return np.sum(np.asarray(gathered), axis=0)

  def barrier(self):
    tracer = get_tracer()
    seq = self._seq
    self._seq += 1
    t0 = time.monotonic() if tracer.enabled else 0.0
    with get_telemetry().histogram('comm.barrier_seconds').time():
      client = self._kv_client()
      if client is not None:
        client.wait_at_barrier(f'lddl_barrier_{seq}', _KV_TIMEOUT_MS)
      else:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices('lddl_tpu_barrier')
    if tracer.enabled:
      tracer.complete('comm.barrier', t0, time.monotonic() - t0,
                      args={'seq': seq})


def get_backend(name=None, **kwargs):
  """Construct a backend by name (default from ``LDDL_COMM`` env, else null).

  Names: ``null`` | ``file`` | ``jax``.
  """
  name = name or os.environ.get('LDDL_COMM', 'null')
  if name == 'null':
    return NullBackend()
  if name == 'file':
    return FileBackend(
        kwargs.get('rendezvous_dir') or os.environ['LDDL_COMM_DIR'],
        kwargs.get('rank', int(os.environ.get('LDDL_RANK', '0'))),
        kwargs.get('world_size', int(os.environ.get('LDDL_WORLD_SIZE', '1'))),
        run_id=kwargs.get('run_id'),
    )
  if name == 'jax':
    return JaxProcessBackend()
  raise ValueError(f'unknown comm backend {name!r}')

from .backend import (
    CommBackend,
    FileBackend,
    FileLeaseStore,
    HeartbeatPump,
    JaxProcessBackend,
    KVLeaseStore,
    LeaseStore,
    NullBackend,
    comm_heartbeat_interval,
    comm_timeout,
    ensure_jax_distributed,
    get_backend,
)

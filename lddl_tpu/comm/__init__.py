from .backend import (
    CommBackend,
    FileBackend,
    JaxProcessBackend,
    NullBackend,
    get_backend,
)

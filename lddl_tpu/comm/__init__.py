from .backend import (
    CommBackend,
    FileBackend,
    JaxProcessBackend,
    NullBackend,
    ensure_jax_distributed,
    get_backend,
)

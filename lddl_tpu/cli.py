"""Console entry points (reference ``setup.py:63-74``'s 8 scripts + the
fork's CodeBERT wrapper), all thin delegates:

  download_wikipedia / download_books / download_common_crawl /
  download_open_webtext          -> lddl_tpu.download.*
  preprocess_bert_pretrain       -> lddl_tpu.preprocess.bert
  preprocess_bart_pretrain       -> lddl_tpu.preprocess.bart
  preprocess_codebert_pretrain   -> lddl_tpu.preprocess.codebert
  preprocess_packed_pretrain     -> lddl_tpu.preprocess.packed (long-context)
  balance_shards                 -> lddl_tpu.balance   (reference name:
                                    balance_dask_output)
  generate_num_samples_cache     -> lddl_tpu.balance
  telemetry_report               -> lddl_tpu.telemetry.report (merge
                                    per-rank telemetry JSONL into a
                                    per-stage bottleneck summary)
  telemetry_trace                -> lddl_tpu.telemetry.trace (merge
                                    per-rank trace JSONL into one
                                    clock-aligned Chrome-trace JSON
                                    for Perfetto / chrome://tracing)
  lddl_analyze                   -> lddl_tpu.analysis.cli (SPMD
                                    determinism & resource-safety
                                    linter; the tier-1 self-check gate)
  lddl_monitor                   -> lddl_tpu.telemetry.monitor (live
                                    dashboard over LDDL_MONITOR
                                    endpoints: rates, verdict,
                                    stragglers, goodput)
  lddl_perf                      -> lddl_tpu.telemetry.perf (robust
                                    perf-regression gate over bench
                                    history; --gate for CI, --audit
                                    folds determinism verification in)
  lddl_audit                     -> lddl_tpu.telemetry.audit (diff/
                                    verify determinism ledgers; bisects
                                    the first divergent batch/step,
                                    exits nonzero for CI)
  lddl_data_server               -> lddl_tpu.loader.service (fault-
                                    tolerant network batch service:
                                    serve one loader's deterministic
                                    stream to N lease-claiming clients)
  lddl_replay                    -> lddl_tpu.replay.cli (deterministic
                                    time-travel: rematerialize any
                                    recorded batch or train step from
                                    the ledger; hermetic repro bundles;
                                    loss-spike bisection)
  lddl_incident                  -> lddl_tpu.training.flight (flight-
                                    recorder incidents: list/show
                                    captured anomalies, shell replay/
                                    bisect straight into lddl-replay)

Runnable as ``python -m lddl_tpu.cli <name> [args...]`` or via the
installed console scripts.
"""

import sys


def download_wikipedia(args=None):
  from .download.wikipedia import main
  main(args)


def download_books(args=None):
  from .download.books import main
  main(args)


def download_common_crawl(args=None):
  from .download.common_crawl import main
  main(args)


def download_open_webtext(args=None):
  from .download.openwebtext import main
  main(args)


def preprocess_bert_pretrain(args=None):
  from .preprocess.bert import main
  main(args)


def preprocess_bart_pretrain(args=None):
  from .preprocess.bart import main
  main(args)


def preprocess_codebert_pretrain(args=None):
  from .preprocess.codebert import main
  main(args)


def preprocess_packed_pretrain(args=None):
  from .preprocess.packed import main
  main(args)


def prepare_codesearchnet(args=None):
  from .download.codesearchnet import main
  main(args)


def pretrain_bert(args=None):
  from .training.pretrain import main
  main(args)


def balance_shards(args=None):
  from .balance import main
  main(args)


def generate_num_samples_cache(args=None):
  from .balance import cache_main
  cache_main(args)


def telemetry_report(args=None):
  from .telemetry.report import main
  return main(args)


def telemetry_trace(args=None):
  from .telemetry.trace import main
  return main(args)


def lddl_analyze(args=None):
  from .analysis.cli import main
  return main(args)


def lddl_monitor(args=None):
  from .telemetry.monitor import main
  return main(args)


def lddl_perf(args=None):
  from .telemetry.perf import main
  return main(args)


def lddl_audit(args=None):
  from .telemetry.audit import main
  return main(args)


def lddl_data_server(args=None):
  from .loader.service import main
  return main(args)


def lddl_replay(args=None):
  from .replay.cli import main
  return main(args)


def lddl_incident(args=None):
  from .training.flight import main
  return main(args)


_COMMANDS = {
    'download_wikipedia': download_wikipedia,
    'download_books': download_books,
    'download_common_crawl': download_common_crawl,
    'download_open_webtext': download_open_webtext,
    'preprocess_bert_pretrain': preprocess_bert_pretrain,
    'preprocess_bart_pretrain': preprocess_bart_pretrain,
    'preprocess_codebert_pretrain': preprocess_codebert_pretrain,
    'preprocess_packed_pretrain': preprocess_packed_pretrain,
    'prepare_codesearchnet': prepare_codesearchnet,
    'pretrain_bert': pretrain_bert,
    'balance_shards': balance_shards,
    'balance_dask_output': balance_shards,  # reference-compatible alias
    'generate_num_samples_cache': generate_num_samples_cache,
    'telemetry_report': telemetry_report,
    'telemetry-report': telemetry_report,  # dash-form alias
    'telemetry_trace': telemetry_trace,
    'telemetry-trace': telemetry_trace,  # dash-form alias
    'lddl_analyze': lddl_analyze,
    'lddl-analyze': lddl_analyze,  # dash-form alias
    'lddl_monitor': lddl_monitor,
    'lddl-monitor': lddl_monitor,  # dash-form alias
    'lddl_perf': lddl_perf,
    'lddl-perf': lddl_perf,  # dash-form alias
    'lddl_audit': lddl_audit,
    'lddl-audit': lddl_audit,  # dash-form alias
    'lddl_data_server': lddl_data_server,
    'lddl-data-server': lddl_data_server,  # dash-form alias
    'lddl_replay': lddl_replay,
    'lddl-replay': lddl_replay,  # dash-form alias
    'lddl_incident': lddl_incident,
    'lddl-incident': lddl_incident,  # dash-form alias
}


def main():
  if len(sys.argv) < 2 or sys.argv[1] not in _COMMANDS:
    names = '\n  '.join(sorted(_COMMANDS))
    print(f'usage: python -m lddl_tpu.cli <command> [args...]\n'
          f'commands:\n  {names}')
    return 2
  return _COMMANDS[sys.argv[1]](sys.argv[2:])


if __name__ == '__main__':
  sys.exit(main())

"""Rebuild a recorded batch by replaying the deterministic draw sequence.

Every batch coordinate the ledger records — a ``(epoch, index)`` collate
key or an ``(epoch, gi)`` serve frame — names a position in a loader's
deterministic stream: same shards, same seed, same draw sequence.
Rematerialization is *build the loader the run used, replay its epoch's
draw sequence to the coordinate, take that batch* — then prove the
reconstruction by fingerprinting it with the ledger's own digest
arithmetic and comparing against the recorded line. (The loaders'
public ``seek`` contract positions the stream at the epoch start; the
mid-epoch skip path is the *resume* contract, whose shuffle buffer
restarts fresh and is deliberately not byte-identical.)

Serve frames replay through the same path: the data service's global
index ``gi`` is the serial step of the server's loader for the epoch
(service.py's degraded fallback re-derives batches from exactly this
``f(epoch, gi)`` identity), so replaying to step ``gi`` on a loader
built from the server's spec reproduces the frame that crossed the
wire.
"""

import random


class ReplayMismatch(ValueError):
  """A reconstructed artifact's fingerprint differs from the recorded
  one — raised with the exact coordinate in the message."""


def format_coordinate(coord):
  """``{'epoch': 0, 'index': 3}`` -> ``'epoch=0, index=3'`` (the
  rendered-key grammar of ``lddl-audit``)."""
  return ', '.join(f'{f}={v}' for f, v in dict(coord).items())


def _check_algo(run):
  """Refuse to verify against a run hashed with an algorithm this
  process cannot reproduce (xxh64 ledger, blake2b8-only host)."""
  from ..telemetry.audit import run_algo
  from ..telemetry.ledger import ALGO
  algo = run_algo(run)
  if algo and algo != ALGO:
    raise ValueError(
        f'ledger was hashed with {algo} but this process fingerprints '
        f'with {ALGO}; reconstruction cannot be verified here')
  return algo or ALGO


def lookup_digest(run, key, boundary=None):
  """The single digest recorded at ``key`` in ``run`` (a
  :func:`~lddl_tpu.telemetry.audit.load_run` dict). Raises
  :class:`LookupError` when the coordinate was never recorded and
  :class:`ReplayMismatch` when the run recorded *conflicting* digests
  for it (the coordinate is not trustworthy enough to replay against).
  Returns ``(digest, [(rank, record), ...])``."""
  from ..telemetry.audit import lookup_records
  hits = lookup_records(run, key, boundary=boundary)
  if not hits:
    where = f' at boundary {boundary}' if boundary else ''
    raise LookupError(
        f'no ledger record at ({format_coordinate(key)}){where}')
  digests = sorted({rec['digest'] for _, rec in hits})
  if len(digests) > 1:
    raise ReplayMismatch(
        f'ledger records conflicting digests at '
        f'({format_coordinate(key)}): {digests} — run lddl-audit first')
  return digests[0], hits


def corpus_shard_format(build_kwargs):
  """``(shard_format, duplicate_factor)`` of the corpus a loader spec
  points at, or ``None`` when the spec has no shard directory (synthetic
  factories). Replay is format-transparent — the dataset expands
  mask-delta rows and the collate reconstructs them, so a recorded
  coordinate replays byte-identically from either format of the same
  logical corpus — but the verdict should say which format actually
  backed the reconstruction."""
  path = dict(build_kwargs).get('path')
  if path is None:
    return None
  try:
    from ..core.utils import get_all_parquets_under
    from ..pipeline.shard_format import scan_shard_format
    return scan_shard_format(get_all_parquets_under(path))
  except (OSError, ValueError):
    return None


def rematerialize_batch(factory, build_kwargs, epoch, index):
  """Build the loader ``factory(**build_kwargs)`` names, drive its
  epoch-``epoch`` draw sequence from batch 0, and return the batch at
  collate coordinate ``(epoch, index)``.

  Driving from the epoch start — not ``seek(epoch, index)`` — is what
  makes the reconstruction byte-identical: seek's skip contract
  repositions the datasets but restarts the shuffle buffer fresh (the
  documented resume semantics, loader/binned.py), which reorders rows
  relative to the uninterrupted stream that produced the ledger line.
  The cost stays one collate, not ``index`` of them: ``iter_steps``'s
  worker-sharding contract advances the full deterministic row stream
  but collates only the shard's steps, and shard ``(index, index+1)``
  collates ``index`` first.

  The factory is the same ``(module, attr)`` spec the worker/service
  layers use, so any loader a run can be fed from can be replayed from
  — synthetic included.
  """
  from ..loader.workers import _resolve_factory
  loader = _resolve_factory(tuple(factory))(**build_kwargs)
  index = int(index)
  loader.seek(int(epoch), 0)
  for step, batch in loader.iter_steps((index, index + 1)):
    return batch  # the first collated step IS `index`
  raise LookupError(
      f'loader produced no batch at epoch={epoch}, index={index} '
      '(dataset shorter than the recorded run?)')


#: Boundaries whose coordinates name a batch position this module can
#: rematerialize. ``serve.*`` keys are ``(epoch, gi)`` and gi is the
#: serial step; ``collate`` keys are ``(epoch, index)`` directly.
REPLAYABLE_BOUNDARIES = ('collate', 'serve.tx', 'serve.rx')


def _batch_position(key):
  """Map a lineage key tuple to the ``(epoch, batch_index)`` seek
  target, or None for boundaries with no batch position (shard paths,
  device frames, train steps)."""
  d = dict(key)
  if 'epoch' in d and 'index' in d:
    return d['epoch'], d['index']
  if 'epoch' in d and 'gi' in d:
    return d['epoch'], d['gi']
  return None


def replay_coordinate(ledger_path, key, factory, build_kwargs,
                      boundary=None, rank=None):
  """Rematerialize the batch at ``key`` and verify it against the
  ledger at ``ledger_path``.

  Returns a result dict (coordinate, boundary, recorded / reconstructed
  digests, match verdict, algo). Raises :class:`LookupError` for an
  unrecorded coordinate, :class:`ValueError` for an algorithm the host
  cannot reproduce, and :class:`ReplayMismatch` is **not** raised here
  — mismatch is a verdict, so CI callers can render it; use
  ``result['match']``.
  """
  from ..telemetry.audit import load_run
  from ..telemetry.ledger import fingerprint_batch
  run = load_run(ledger_path, rank=rank)
  algo = _check_algo(run)
  digest, hits = lookup_digest(run, tuple(key), boundary=boundary)
  pos = _batch_position(tuple(key))
  if pos is None:
    raise ValueError(
        f'coordinate ({format_coordinate(key)}) has no batch position; '
        "replay batch coordinates are (epoch, index) or (epoch, gi) — "
        "use 'lddl-replay step' for step coordinates")
  batch = rematerialize_batch(factory, build_kwargs, *pos)
  actual = fingerprint_batch(batch)
  fmt = corpus_shard_format(build_kwargs)
  return {
      'coordinate': dict(tuple(key)),
      'boundary': boundary or hits[0][1]['boundary'],
      'recorded': digest,
      'reconstructed': actual,
      'match': actual == digest,
      'algo': algo,
      'shard_format': fmt[0] if fmt else None,
      'batch': batch,
  }


def replay_smoke(ledger_path, factory, build_kwargs, seed=0, rank=None):
  """One random recorded coordinate per boundary, replayed and verified
  — the ``lddl-perf --replay-smoke`` gate.

  Batch-position boundaries (:data:`REPLAYABLE_BOUNDARIES`) are
  rematerialized through :func:`rematerialize_batch`; boundaries with
  no batch position (``shard``/``device``/``step``) are reported
  ``skipped`` — they need the original shard files or a checkpoint, not
  just the loader spec. Returns ``(results, rc)`` where ``rc`` is 0
  when every replayed coordinate matched (skips don't fail) and 1 on
  any mismatch. Deterministic under ``seed``.
  """
  from ..telemetry.audit import load_run
  from ..telemetry.ledger import fingerprint_batch, record_key
  run = load_run(ledger_path, rank=rank)
  _check_algo(run)
  rnd = random.Random(seed)
  by_boundary = {}
  for r in sorted(run):
    for rec in run[r]['records']:
      k = record_key(rec)
      if k is not None:
        by_boundary.setdefault(rec['boundary'], {})[(r, k)] = rec
  results, rc = {}, 0
  for bd in sorted(by_boundary):
    table = by_boundary[bd]
    if bd not in REPLAYABLE_BOUNDARIES:
      results[bd] = {'status': 'skipped',
                     'reason': 'no batch position (needs shards or a '
                               'checkpoint, not a loader spec)'}
      continue
    rec_rank, key = rnd.choice(sorted(table))
    pos = _batch_position(key)
    if pos is None:
      results[bd] = {'status': 'skipped', 'reason': 'incomplete key'}
      continue
    # Collate records are per-dp-rank streams: rebuild *that* rank's
    # loader. Serve frames come off the server's single loader, so the
    # spec is used as-is.
    kwargs = dict(build_kwargs)
    if bd == 'collate':
      kwargs.setdefault('dp_rank', rec_rank)
    try:
      batch = rematerialize_batch(factory, kwargs, *pos)
    except Exception as e:  # an unreplayable spec is a failed smoke
      results[bd] = {'status': 'error', 'coordinate': dict(key),
                     'error': f'{type(e).__name__}: {e}'}
      rc = 1
      continue
    actual = fingerprint_batch(batch)
    recorded = table[(rec_rank, key)]['digest']
    ok = actual == recorded
    fmt = corpus_shard_format(kwargs)
    results[bd] = {'status': 'ok' if ok else 'mismatch',
                   'coordinate': dict(key), 'rank': rec_rank,
                   'recorded': recorded, 'reconstructed': actual,
                   'shard_format': fmt[0] if fmt else None}
    if not ok:
      rc = 1
  return results, rc

"""Re-execute recorded train steps and bisect loss spikes.

The ledger's ``step`` boundary fingerprints the full train state
(params + opt_state + rng) at every checkpoint boundary. Because the
jitted step folds its dropout key from the optimizer's own step counter
(:func:`~lddl_tpu.parallel.train._train_step_body`) and the loaders are
coordinate-addressable, *state at step S* is a pure function of
*(checkpoint at S0 < S, batches S0..S-1)* — so any recorded step can be
re-executed bit-for-bit on a fresh process: restore the newest
checkpoint at or below ``S - 1``, drive the jitted step through the
:class:`~lddl_tpu.training.pretrain.CompiledStepCache` over the
deterministic batch stream (or a hermetic bundle's batches, no corpus
needed), and diff :func:`~lddl_tpu.training.pretrain.state_fingerprint`
against the recorded line.

``bisect`` rides the same machinery: replay a step window, find the
largest per-step loss jump, and name the ``(epoch, index)`` batch
coordinate that fed it — optionally re-scoring that batch per sample
(:func:`~lddl_tpu.parallel.train.pretrain_loss` on singleton slices,
the packed-sequence per-doc loss normalization included) to attribute
the spike below batch granularity.
"""


def _wrap_step_cache(loop):
  from ..training.pretrain import CompiledStepCache, _step_cache_enabled
  if _step_cache_enabled() and not isinstance(loop.step_fn,
                                              CompiledStepCache):
    loop.step_fn = CompiledStepCache(loop.step_fn)


def _global_batch_of(loop, batch):
  if loop.loader is not None:
    per_rank = loop.loader.batch_size
  else:
    arr = next(v for v in batch.values() if hasattr(v, 'shape'))
    per_rank = int(arr.shape[0])
  return per_rank * max(loop.dp_world, 1)


def replay_steps(loop, target_step, batches=None, prefetch=2):
  """Advance ``loop`` from its current (restored) step to ``target_step``.

  Mirrors the live loop's step execution exactly — same
  device-placement path (:func:`~lddl_tpu.loader.device.
  prefetch_to_device`), same step-cache wrapping, rng passed through
  unchanged (the step fn folds in the optimizer count itself) — so the
  resulting state is bit-identical to the recorded run's. ``batches``
  (host batches, e.g. from a bundle) overrides the loop's loader; they
  must cover ``target_step - loop.step`` steps. Returns
  ``[(step, loss), ...]`` keyed like the ledger (the loss of *reaching*
  step S).
  """
  from ..core import faults
  from ..loader.device import prefetch_to_device
  _wrap_step_cache(loop)
  if loop.step >= target_step:
    raise ValueError(
        f'loop is at step {loop.step}, at/past target {target_step}; '
        'restore an older checkpoint first')
  if batches is not None and len(batches) < target_step - loop.step:
    raise ValueError(
        f'{len(batches)} bundled batch(es) cannot cover steps '
        f'{loop.step + 1}..{target_step}')
  if batches is None and loop.loader is None:
    raise ValueError(
        'loop has no loader (built with path=None); step replay needs '
        'bundled batches')

  def _source():
    if batches is not None:
      for b in batches:
        yield b
    else:
      while True:  # epoch-iterable loader: chain epochs like run() does
        yield from iter(loop.loader)

  stream = prefetch_to_device(_source(), mesh=loop.mesh, size=prefetch)
  losses = []
  try:
    while loop.step < target_step:
      try:
        batch = next(stream)
      except StopIteration:
        raise ValueError(
            f'batch stream ended at step {loop.step} before target '
            f'{target_step}')
      faults.inject('replay.step', rank=loop.dp_rank, gi=loop.step)
      loop.params, loop.opt_state, metrics = loop.step_fn(
          loop.params, loop.opt_state, loop.rng, batch)
      loss = float(metrics['loss'])
      loop.step += 1
      loop.samples_seen += _global_batch_of(loop, batch)
      loop._last_loss = loss
      losses.append((loop.step, loss))
  finally:
    close = getattr(stream, 'close', None)
    if close is not None:
      close()
  return losses


def replay_step_coordinate(loop, ckpt_dir, target_step, ledger_path=None,
                           batches=None, prefetch=2, rank=None):
  """Rematerialize train state at ``step=target_step`` and (optionally)
  verify it against a ledger's recorded ``step`` fingerprint.

  Restores the newest checkpoint at or below ``target_step - 1`` from
  ``ckpt_dir``, replays forward, and fingerprints the resulting state.
  With ``ledger_path`` the result carries ``recorded``/``match`` — the
  acceptance check that a replayed step reproduces the recorded
  fingerprint bit-for-bit.
  """
  target_step = int(target_step)
  meta = type(loop).latest_meta(ckpt_dir, max_step=target_step - 1)
  if meta is None:
    raise FileNotFoundError(
        f'no checkpoint at or below step {target_step - 1} under '
        f'{ckpt_dir}')
  loop.restore(ckpt_dir, step=meta[0])
  losses = replay_steps(loop, target_step, batches=batches,
                        prefetch=prefetch)
  digest = loop.state_digest()
  from ..telemetry.ledger import ALGO
  out = {'step': target_step, 'restored_step': meta[0], 'digest': digest,
         'losses': losses, 'algo': ALGO}
  if ledger_path is not None:
    from ..telemetry.audit import load_run
    from .rematerialize import _check_algo, lookup_digest
    run = load_run(ledger_path, rank=rank)
    _check_algo(run)
    recorded, _ = lookup_digest(run, (('step', target_step),),
                                boundary='step')
    out['recorded'] = recorded
    out['match'] = digest == recorded
  return out


def bisect_window(loop, ckpt_dir, lo, hi, prefetch=2, per_sample=False):
  """Walk steps ``(lo, hi]`` and attribute the largest loss jump.

  Restores the newest checkpoint at or below ``lo``, replays through
  ``hi`` collecting per-step losses, and reports the step with the
  largest positive loss delta plus the ``(epoch, index)`` collate
  coordinate of the batch that fed it (step ``S`` consumes this rank's
  batch ordinal ``S - 1`` — one batch per rank per global step).
  ``per_sample=True`` additionally re-restores at the spike step's
  predecessor and scores the spike batch row by row with the
  pre-spike params, naming the sample index that contributed most.
  """
  lo, hi = int(lo), int(hi)
  if hi <= lo:
    raise ValueError(f'empty bisect window ({lo}, {hi}]')
  meta = type(loop).latest_meta(ckpt_dir, max_step=lo)
  if meta is None:
    raise FileNotFoundError(
        f'no checkpoint at or below step {lo} under {ckpt_dir}')
  loop.restore(ckpt_dir, step=meta[0])
  losses = replay_steps(loop, hi, prefetch=prefetch)
  by_step = dict(losses)
  deltas = [(by_step[s] - by_step[s - 1], s)
            for s in range(max(lo, meta[0] + 1) + 1, hi + 1)
            if s in by_step and s - 1 in by_step]
  if not deltas:
    raise ValueError(
        f'window ({lo}, {hi}] left no consecutive step pair to compare '
        f'(restored at {meta[0]})')
  delta, spike = max(deltas)
  out = {'window': [lo, hi], 'restored_step': meta[0],
         'losses': losses, 'spike_step': spike,
         'spike_loss': by_step[spike], 'delta': delta}
  if loop.loader is not None:
    epoch, index = loop.loader.coordinate_of_batch(spike - 1)
    out['batch_coordinate'] = {'epoch': epoch, 'index': index}
    if per_sample:
      out['per_sample'] = _per_sample_losses(loop, ckpt_dir, spike)
      out['spike_sample'] = max(
          range(len(out['per_sample'])), key=out['per_sample'].__getitem__)
  return out


def _per_sample_losses(loop, ckpt_dir, spike_step):
  """Loss of each row of the batch feeding ``spike_step``, scored with
  the params the spike step started from (leaves ``loop`` positioned at
  ``spike_step - 1``). Single-host only — the eager forward pass runs
  outside the jitted/partitioned step."""
  from ..parallel.train import pretrain_loss
  meta = type(loop).latest_meta(ckpt_dir, max_step=spike_step - 1)
  loop.restore(ckpt_dir, step=meta[0])
  if loop.step < spike_step - 1:
    replay_steps(loop, spike_step - 1)
  epoch, index = loop.loader.coordinate_of_batch(spike_step - 1)
  loop.loader.seek(epoch, index)
  batch = next(iter(loop.loader.iter_steps((0, 1))))[1]
  if not isinstance(batch, dict):
    raise ValueError('per-sample attribution supports dict batches only '
                     '(micro-batch loaders yield lists)')
  rows = batch['input_ids'].shape[0]
  out = []
  for i in range(rows):
    one = {k: (v[i:i + 1] if hasattr(v, 'shape') and v.shape
               and v.shape[0] == rows else v)
           for k, v in batch.items()}
    loss, _ = pretrain_loss(loop.model, loop.params, one)
    out.append(float(loss))
  return out

"""``lddl-replay``: deterministic time-travel over a recorded run.

Subcommands (the coordinate grammar is ``lddl-audit``'s rendered key
form, e.g. ``epoch=0,index=3`` / ``epoch=1,gi=7`` / ``step=42``):

- ``batch LEDGER --key epoch=E,index=I <loader spec>`` — rematerialize
  the recorded batch by replaying the deterministic draw sequence to
  its coordinate, fingerprint it, and verdict against the ledger line
  (exit 0 match, 1 mismatch, 2 usage);
- ``bundle LEDGER --key ... --out DIR <loader spec>`` — same, then emit
  a hermetic repro bundle (packed batch bytes + Philox inputs +
  checkpoint ref + ledger excerpt — replayable with no corpus). A
  mismatching reconstruction refuses to bundle;
- ``step --checkpoint-dir D --step S [--ledger L] <loader spec |
  --bundle DIR>`` — restore the newest checkpoint <= S-1, re-execute to
  S through the jitted step, and diff the state fingerprint against the
  recorded ``step=S`` ledger line;
- ``bisect --checkpoint-dir D --lo A --hi B <loader spec>`` — walk the
  step window, report the largest loss jump and the batch (optionally
  sample) coordinate that fed it;
- ``smoke LEDGER <loader spec>`` — one random coordinate per boundary,
  replayed and verified (the ``lddl-perf --replay-smoke`` gate's
  engine).

The loader spec mirrors ``lddl-data-server``: ``--path`` (BERT shards)
/ ``--synthetic`` / ``--factory MODULE:ATTR --kwargs-json ...``.
"""

import argparse
import json
import sys


def _attach_loader_args(p):
  p.add_argument('--path', default=None,
                 help='balanced shard directory (BERT pretrain loader)')
  p.add_argument('--vocab-file', default=None)
  p.add_argument('--batch-size', type=int, default=64)
  p.add_argument('--bin-size', type=int, default=None)
  p.add_argument('--max-seq-length', type=int, default=512)
  p.add_argument('--base-seed', type=int, default=12345)
  p.add_argument('--masking', default='static',
                 choices=('static', 'dynamic'))
  p.add_argument('--dp-rank', type=int, default=0)
  p.add_argument('--dp-world', type=int, default=1)
  p.add_argument('--synthetic', action='store_true',
                 help='replay the SyntheticBatchLoader stream')
  p.add_argument('--steps', type=int, default=256,
                 help='steps per epoch in --synthetic mode')
  p.add_argument('--factory', default=None, metavar='MODULE:ATTR',
                 help='replay an arbitrary loader factory')
  p.add_argument('--kwargs-json', default='{}',
                 help='JSON kwargs for --factory')


def loader_spec(args):
  """CLI args -> ``(factory, build_kwargs)`` for
  :func:`~lddl_tpu.replay.rematerialize.rematerialize_batch` — the same
  three loader sources ``lddl-data-server`` accepts."""
  if args.synthetic:
    return ('lddl_tpu.testing', 'get_synthetic_batch_loader'), dict(
        batch_size=args.batch_size, seq_len=args.max_seq_length,
        steps=args.steps)
  if args.factory:
    module, _, attr = args.factory.partition(':')
    return (module, attr), json.loads(args.kwargs_json)
  if not args.path:
    raise SystemExit('lddl-replay: need --path, --synthetic, or '
                     '--factory')
  from ..comm import NullBackend
  return ('lddl_tpu.loader.bert', 'get_bert_pretrain_data_loader'), dict(
      path=args.path, batch_size_per_rank=args.batch_size,
      vocab_file=args.vocab_file, bin_size=args.bin_size,
      max_seq_length=args.max_seq_length, base_seed=args.base_seed,
      masking=args.masking, dp_rank=args.dp_rank,
      dp_world_size=args.dp_world, comm=NullBackend())


def _attach_model_args(p):
  from ..training.pretrain import MODEL_SIZES
  p.add_argument('--tokenizer', default=None)
  p.add_argument('--vocab-size', type=int, default=None,
                 help='padded vocab size, replacing --vocab-file '
                      '(bundle replay needs no tokenizer)')
  p.add_argument('--model', choices=sorted(MODEL_SIZES), default='base')
  p.add_argument('--attention',
                 choices=['dense', 'flash', 'ring', 'ring_flash'],
                 default='dense')
  p.add_argument('--remat', action='store_true')
  p.add_argument('--dp', type=int, default=1)
  p.add_argument('--fsdp', type=int, default=1)
  p.add_argument('--tp', type=int, default=1)
  p.add_argument('--sp', type=int, default=1)
  p.add_argument('--data-format', choices=['pairs', 'packed'],
                 default='pairs')
  p.add_argument('--block-diagonal', action='store_true')
  p.add_argument('--seed', type=int, default=127)
  p.add_argument('--learning-rate', type=float, default=1e-4)
  p.add_argument('--warmup-steps', type=int, default=100)
  p.add_argument('--total-steps', type=int, default=1000,
                 help='the recorded run\'s --steps (the LR schedule '
                      'depends on it; must match for bit-identity)')
  p.add_argument('--weight-decay', type=float, default=0.01)
  p.add_argument('--max-predictions', type=int, default=None)
  p.add_argument('--prefetch', type=int, default=2)


def build_loop(args):
  """Reconstruct the recorded run's :class:`~lddl_tpu.training.
  pretrain.TrainLoop` from CLI args — every knob the LR schedule, model
  shapes, or data stream depend on must match the original run, or the
  replayed arithmetic (correctly) diverges."""
  from ..models import BertConfig
  from ..parallel import make_mesh
  from ..training.pretrain import MODEL_SIZES, TrainLoop
  tokenizer, vocab = None, args.vocab_size
  if vocab is None:
    from ..tokenization.wordpiece import load_bert_tokenizer
    tokenizer = load_bert_tokenizer(
        vocab_file=args.vocab_file, hub_name=args.tokenizer, backend='hf')
    vocab = ((tokenizer.vocab_size + 63) // 64) * 64
  cfg = BertConfig(
      vocab_size=vocab,
      max_position_embeddings=max(args.max_seq_length, 512),
      attention_impl=args.attention,
      remat=args.remat,
      **MODEL_SIZES[args.model])
  mesh = make_mesh(data=args.dp, fsdp=args.fsdp, tensor=args.tp,
                   seq=args.sp)
  return TrainLoop.build(
      args.path, tokenizer, model_cfg=cfg, mesh=mesh,
      learning_rate=args.learning_rate, warmup_steps=args.warmup_steps,
      total_steps=args.total_steps, weight_decay=args.weight_decay,
      batch_size_per_rank=args.batch_size, bin_size=args.bin_size,
      max_seq_length=args.max_seq_length, masking=args.masking,
      seed=args.seed, max_predictions=args.max_predictions,
      data_format=args.data_format, block_diagonal=args.block_diagonal)


def _parse_key(spec):
  from ..telemetry.audit import parse_key
  try:
    return parse_key(spec)
  except ValueError as e:
    raise SystemExit(f'lddl-replay: {e}')


def _print_result(result, as_json):
  out = {k: v for k, v in result.items() if k != 'batch'}
  if as_json:
    print(json.dumps(out, indent=2, default=str))
    return
  from .rematerialize import format_coordinate
  coord = format_coordinate(out.get('coordinate', {'step': out.get('step')}))
  if out.get('match'):
    print(f'lddl-replay: ({coord}) reconstructed bit-identical — '
          f'{out["reconstructed" if "reconstructed" in out else "digest"]} '
          f'({out["algo"]})')
  elif 'match' in out:
    print(f'lddl-replay: ({coord}) MISMATCH — recorded '
          f'{out["recorded"]}, reconstructed '
          f'{out.get("reconstructed", out.get("digest"))}')
  else:
    print(json.dumps(out, indent=2, default=str))


def _cmd_batch(args):
  from .rematerialize import replay_coordinate
  key = _parse_key(args.key)
  factory, kwargs = loader_spec(args)
  result = replay_coordinate(args.ledger, key, factory, kwargs,
                             boundary=args.boundary, rank=args.rank)
  _print_result(result, args.as_json)
  return 0 if result['match'] else 1


def _cmd_bundle(args):
  from ..telemetry.audit import load_run
  from .bundle import write_bundle
  from .rematerialize import lookup_digest, replay_coordinate
  key = _parse_key(args.key)
  factory, kwargs = loader_spec(args)
  result = replay_coordinate(args.ledger, key, factory, kwargs,
                             boundary=args.boundary, rank=args.rank)
  if not result['match']:
    _print_result(result, args.as_json)
    print('lddl-replay: refusing to bundle a mismatching reconstruction',
          file=sys.stderr)
    return 1
  _, hits = lookup_digest(load_run(args.ledger, rank=args.rank),
                          key, boundary=args.boundary)
  coord = dict(key)
  philox = {'base_seed': kwargs.get('base_seed', args.base_seed),
            'dp_rank': kwargs.get('dp_rank', args.dp_rank),
            'epoch': coord.get('epoch'),
            'step': coord.get('index', coord.get('gi'))}
  checkpoint = None
  if args.checkpoint_dir:
    checkpoint = {'dir': args.checkpoint_dir, 'step': args.checkpoint_step}
  out = write_bundle(
      args.out, result['batch'], coord, digest=result['recorded'],
      philox=philox, checkpoint=checkpoint,
      ledger_excerpt=[dict(rec, rank=r) for r, rec in hits])
  print(f'lddl-replay: bundle written to {out}')
  return 0


def _cmd_step(args):
  from .steps import replay_step_coordinate
  batches = None
  if args.bundle:
    from .bundle import read_bundle
    _, batch = read_bundle(args.bundle)
    batches = [batch]
  loop = build_loop(args)
  result = replay_step_coordinate(
      loop, args.checkpoint_dir, args.step, ledger_path=args.ledger,
      batches=batches, prefetch=args.prefetch, rank=args.rank)
  result['coordinate'] = {'step': args.step}
  _print_result(result, args.as_json)
  if 'match' not in result:
    return 0  # no ledger to verdict against; the replay itself succeeded
  return 0 if result['match'] else 1


def _cmd_bisect(args):
  from .steps import bisect_window
  loop = build_loop(args)
  result = bisect_window(loop, args.checkpoint_dir, args.lo, args.hi,
                         prefetch=args.prefetch,
                         per_sample=args.per_sample)
  if args.as_json:
    print(json.dumps(result, indent=2, default=str))
  else:
    print(f'lddl-replay: spike at step {result["spike_step"]} '
          f'(loss {result["spike_loss"]:.4f}, jump +{result["delta"]:.4f} '
          f'over window ({args.lo}, {args.hi}])')
    if 'batch_coordinate' in result:
      c = result['batch_coordinate']
      print(f'  fed by batch epoch={c["epoch"]}, index={c["index"]}')
    if 'spike_sample' in result:
      print(f'  dominant sample: row {result["spike_sample"]} '
            f'(per-sample loss '
            f'{result["per_sample"][result["spike_sample"]]:.4f})')
  return 0


def _cmd_smoke(args):
  from .rematerialize import replay_smoke
  factory, kwargs = loader_spec(args)
  results, rc = replay_smoke(args.ledger, factory, kwargs,
                             seed=args.seed, rank=args.rank)
  if args.as_json:
    print(json.dumps(results, indent=2, default=str))
  else:
    for bd, r in sorted(results.items()):
      print(f'{bd}: {r["status"]}' +
            (f' at {r["coordinate"]}' if 'coordinate' in r else '') +
            (f' — {r.get("error") or r.get("reason", "")}'
             if r['status'] not in ('ok',) else ''))
  return rc


def attach_args(parser):
  sub = parser.add_subparsers(dest='command')

  p = sub.add_parser('batch', help='rematerialize + verify one recorded '
                                   'batch coordinate')
  p.add_argument('ledger', help='ledger directory or rank file')
  p.add_argument('--key', required=True, metavar='LINEAGE_KEY',
                 help="e.g. 'epoch=0,index=3' or 'epoch=1,gi=7'")
  p.add_argument('--boundary', default=None)
  p.add_argument('--rank', type=int, default=None)
  p.add_argument('--json', action='store_true', dest='as_json')
  _attach_loader_args(p)

  p = sub.add_parser('bundle', help='emit a hermetic repro bundle for a '
                                    'verified coordinate')
  p.add_argument('ledger')
  p.add_argument('--key', required=True, metavar='LINEAGE_KEY')
  p.add_argument('--out', required=True, help='bundle directory to write')
  p.add_argument('--boundary', default=None)
  p.add_argument('--rank', type=int, default=None)
  p.add_argument('--checkpoint-dir', default=None,
                 help='checkpoint ref to embed (step replay later)')
  p.add_argument('--checkpoint-step', type=int, default=None)
  p.add_argument('--json', action='store_true', dest='as_json')
  _attach_loader_args(p)

  p = sub.add_parser('step', help='re-execute a recorded train step and '
                                  'diff its state fingerprint')
  p.add_argument('--checkpoint-dir', required=True)
  p.add_argument('--step', type=int, required=True)
  p.add_argument('--ledger', default=None,
                 help='verdict against this run\'s step records')
  p.add_argument('--bundle', default=None,
                 help='feed the step from a repro bundle (no corpus)')
  p.add_argument('--rank', type=int, default=None)
  p.add_argument('--json', action='store_true', dest='as_json')
  _attach_loader_args(p)
  _attach_model_args(p)

  p = sub.add_parser('bisect', help='walk a step window, attribute the '
                                    'largest loss jump')
  p.add_argument('--checkpoint-dir', required=True)
  p.add_argument('--lo', type=int, required=True)
  p.add_argument('--hi', type=int, required=True)
  p.add_argument('--per-sample', action='store_true',
                 help='re-score the spike batch row by row')
  p.add_argument('--json', action='store_true', dest='as_json')
  _attach_loader_args(p)
  _attach_model_args(p)

  p = sub.add_parser('smoke', help='replay one random coordinate per '
                                   'boundary (the lddl-perf gate)')
  p.add_argument('ledger')
  p.add_argument('--seed', type=int, default=0)
  p.add_argument('--rank', type=int, default=None)
  p.add_argument('--json', action='store_true', dest='as_json')
  _attach_loader_args(p)
  return parser


def main(argv=None):
  parser = attach_args(argparse.ArgumentParser(
      prog='lddl-replay',
      description='deterministic time-travel: rematerialize any batch '
                  'or train step a recorded run consumed',
      formatter_class=argparse.RawDescriptionHelpFormatter))
  args = parser.parse_args(argv)
  cmds = {'batch': _cmd_batch, 'bundle': _cmd_bundle, 'step': _cmd_step,
          'bisect': _cmd_bisect, 'smoke': _cmd_smoke}
  fn = cmds.get(args.command)
  if fn is None:
    parser.print_usage(sys.stderr)
    return 2
  from .rematerialize import ReplayMismatch
  try:
    return fn(args)
  except ReplayMismatch as e:
    # A named fingerprint mismatch is a *verdict* (CI-gateable), not a
    # usage error.
    print(f'lddl-replay: {e}', file=sys.stderr)
    return 1
  except (FileNotFoundError, LookupError, ValueError) as e:
    print(f'lddl-replay: {e}', file=sys.stderr)
    return 2


if __name__ == '__main__':
  sys.exit(main())

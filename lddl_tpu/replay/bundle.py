"""Hermetic repro bundles: a recorded batch, portable without the corpus.

A bundle is a directory of two files:

- ``bundle.json`` — manifest: format version, digest algorithm, the
  ledger coordinate, the recorded fingerprint, the packed-batch spec
  (the shm/wire spec of :mod:`lddl_tpu.loader.shm`, JSON-encoded), the
  RNG/Philox inputs that parameterized collate (base seed, dp rank,
  epoch, step — the exact Philox key material dynamic masking derives
  its counters from), an optional checkpoint ref (directory + step) for
  step replay, and the ledger excerpt (the raw recorded lines) it was
  cut from;
- ``batch.bin`` — the packed batch payload, byte-identical to what a
  shm slot or a service frame carries.

``read_bundle`` re-fingerprints the payload against the manifest before
handing the batch out, so a bundle damaged in storage or transit is
rejected with the mismatch named at its exact coordinate — the same
refusal discipline as the wire integrity check. The ``replay.read``
fault site drills exactly this.
"""

import json
import os

from .rematerialize import ReplayMismatch, format_coordinate

#: Bump on any incompatible manifest/payload layout change; readers
#: refuse newer-versioned bundles instead of misparsing them.
BUNDLE_VERSION = 1

_MANIFEST = 'bundle.json'
_PAYLOAD = 'batch.bin'


def _spec_to_json(spec):
  """Packed-batch spec -> JSON-able form (tuples become lists; 'py'
  leaves must be JSON-encodable or the bundle write fails loudly)."""
  kind = spec[0]
  if kind == 'nd':
    return ['nd', spec[1], list(spec[2]), spec[3]]
  if kind == 'map':
    return ['map', [[k, _spec_to_json(s)] for k, s in spec[1]]]
  if kind == 'seq':
    return ['seq', bool(spec[1]), [_spec_to_json(s) for s in spec[2]]]
  if kind == 'py':
    return ['py', spec[1]]
  raise ValueError(f'unknown spec node kind {kind!r}')


def _spec_from_json(node):
  kind = node[0]
  if kind == 'nd':
    return ('nd', node[1], tuple(node[2]), node[3])
  if kind == 'map':
    return ('map', [(k, _spec_from_json(s)) for k, s in node[1]])
  if kind == 'seq':
    return ('seq', bool(node[1]), [_spec_from_json(s) for s in node[2]])
  if kind == 'py':
    return ('py', node[1])
  raise ValueError(f'unknown spec node kind {kind!r}')


def write_bundle(out_dir, batch, coordinate, *, digest=None, philox=None,
                 checkpoint=None, ledger_excerpt=None):
  """Pack ``batch`` into a bundle directory at ``out_dir`` (created).

  ``coordinate`` is the ledger key dict (e.g. ``{'epoch': 0,
  'index': 3}``). ``digest`` defaults to the payload's own fingerprint
  — pass the *recorded* ledger digest when bundling a verified replay
  so the bundle carries the run's ground truth, not a re-derivation.
  Returns the bundle directory path.
  """
  from ..loader.service import pack_batch
  from ..telemetry.ledger import ALGO, fingerprint_packed
  spec, payload = pack_batch(batch)
  manifest = {
      'version': BUNDLE_VERSION,
      'algo': ALGO,
      'coordinate': dict(coordinate),
      'digest': digest or fingerprint_packed(spec, payload),
      'spec': _spec_to_json(spec),
      'payload_bytes': len(payload),
      'philox': dict(philox) if philox else None,
      'checkpoint': dict(checkpoint) if checkpoint else None,
      'ledger_excerpt': list(ledger_excerpt or ()),
  }
  os.makedirs(out_dir, exist_ok=True)
  with open(os.path.join(out_dir, _PAYLOAD), 'wb') as f:
    f.write(payload)
  with open(os.path.join(out_dir, _MANIFEST), 'w') as f:
    json.dump(manifest, f, indent=2, default=str)
    f.write('\n')
  return out_dir


def read_bundle(bundle_dir, verify=True):
  """Load a bundle -> ``(manifest, batch)``.

  ``verify=True`` (default, and what every CLI path uses)
  re-fingerprints the payload and raises :class:`ReplayMismatch` naming
  the exact coordinate when it no longer matches the manifest. A
  manifest hashed with an algorithm this host cannot reproduce refuses
  to verify rather than comparing apples to oranges.
  """
  from ..core import faults
  from ..loader.service import unpack_batch
  from ..telemetry.ledger import ALGO, fingerprint_packed
  path = os.path.join(bundle_dir, _MANIFEST)
  if not os.path.isfile(path):
    raise FileNotFoundError(f'not a bundle (no {_MANIFEST}): {bundle_dir}')
  with open(path) as f:
    manifest = json.load(f)
  if manifest.get('version', 0) > BUNDLE_VERSION:
    raise ValueError(
        f'bundle {bundle_dir} has version {manifest["version"]}; this '
        f'reader understands <= {BUNDLE_VERSION}')
  coord = manifest.get('coordinate') or {}
  with open(os.path.join(bundle_dir, _PAYLOAD), 'rb') as f:
    payload = bytearray(f.read())
  # The storage-corruption drill: flip a payload byte after the read,
  # before verification — a damaged bundle must be *rejected*, never
  # silently replayed.
  faults.corrupt_bytes('replay.read', payload, **coord)
  faults.inject('replay.read', **coord)
  spec = _spec_from_json(manifest['spec'])
  if verify:
    if manifest.get('algo') and manifest['algo'] != ALGO:
      raise ValueError(
          f'bundle hashed with {manifest["algo"]} but this process '
          f'fingerprints with {ALGO}; cannot verify')
    actual = fingerprint_packed(spec, payload)
    if actual != manifest['digest']:
      raise ReplayMismatch(
          f'bundle payload rejected at ({format_coordinate(coord)}): '
          f'recorded {manifest["digest"]}, got {actual} — the bundle '
          'is corrupt')
  return manifest, unpack_batch(spec, payload)

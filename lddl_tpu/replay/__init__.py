"""Deterministic time-travel: make the determinism ledger *executable*.

PR 15's ledger records a fingerprint lineage (shard → collate → serve →
device → step) at every pipeline boundary; this package rematerializes
any recorded coordinate on demand — the one-command-reproduction end
state reproducible-pipeline work argues for (PAPERS.md 2604.21275):

- :mod:`.rematerialize` — drive the loaders' public
  ``seek(epoch, batch_index)`` contract to rebuild exactly the batch a
  ledger line fingerprinted, and verify the reconstruction against the
  recorded digest;
- :mod:`.bundle` — hermetic repro bundles: packed batch bytes +
  RNG/Philox inputs + checkpoint ref + ledger excerpt, replayable on a
  machine that has never seen the corpus;
- :mod:`.steps` — re-execute a recorded train step (checkpoint restore
  at ``S - 1`` + one jitted step through the
  :class:`~lddl_tpu.training.pretrain.CompiledStepCache`) and diff the
  resulting state fingerprint against the ledger's ``step`` record;
  ``bisect`` walks a step window and attributes a loss spike to the
  batch (and optionally the sample) that moved it;
- :mod:`.cli` — the ``lddl-replay`` console entry tying it together.
"""

from .bundle import BUNDLE_VERSION, read_bundle, write_bundle
from .rematerialize import (ReplayMismatch, format_coordinate,
                            lookup_digest, rematerialize_batch,
                            replay_coordinate, replay_smoke)

__all__ = [
    'BUNDLE_VERSION',
    'ReplayMismatch',
    'format_coordinate',
    'lookup_digest',
    'read_bundle',
    'rematerialize_batch',
    'replay_coordinate',
    'replay_smoke',
    'write_bundle',
]

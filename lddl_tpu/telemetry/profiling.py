"""On-demand ``jax.profiler`` capture, shared by bench and live runs.

Two entry points over one code path:

  - :func:`trace_capture` — a context manager around one profiled region
    (``benchmarks/train_bench.py --profile-dir`` uses this); no-op when
    the directory is falsy, so callers never branch.
  - :class:`StepProfiler` — the live-run half: ``GET /profile?steps=N``
    on the monitor endpoint (or ``lddl-monitor --profile N``) *arms* the
    profiler, and the train loop's per-step ``on_step()`` hook starts a
    trace at the next step boundary and stops it N steps later. Traces
    land under ``LDDL_TELEMETRY_DIR/profiles/`` (same layout the bench
    context manager uses), numbered per capture, so a long pretrain can
    be profiled without a restart and costs nothing while unarmed: the
    unarmed ``on_step`` path is two attribute reads.

The profiler singleton is plain state, not a thread or a socket — with
``LDDL_MONITOR`` unset nothing ever arms it, preserving the PR 7 no-op
guarantees (pinned by tests/test_monitor.py and tests/test_roofline.py).
"""

import contextlib
import os
import threading


@contextlib.contextmanager
def trace_capture(trace_dir):
  """Profile the enclosed region into ``trace_dir`` (TensorBoard /
  Perfetto layout). Falsy ``trace_dir`` → no-op, zero overhead."""
  if not trace_dir:
    yield None
    return
  import jax
  os.makedirs(trace_dir, exist_ok=True)
  jax.profiler.start_trace(trace_dir)
  try:
    yield trace_dir
  finally:
    jax.profiler.stop_trace()


def default_profile_dir():
  """Where live captures go: ``$LDDL_TELEMETRY_DIR/profiles`` (cwd-
  relative ``lddl_profiles/`` when the telemetry dir is unset)."""
  base = os.environ.get('LDDL_TELEMETRY_DIR')
  return os.path.join(base, 'profiles') if base else 'lddl_profiles'


class StepProfiler:
  """Arms ``jax.profiler`` for the next N train steps.

  ``arm()`` is called from the monitor's HTTP thread; ``on_step()`` from
  the train loop. The hot path (unarmed) reads two attributes and
  returns — no lock. The armed transitions take ``_lock`` so an arm
  racing a step boundary cannot double-start a trace; jax allows only
  one active trace per process.
  """

  def __init__(self):
    self._lock = threading.Lock()
    self._armed_steps = 0      # steps requested, not yet started
    self._active_steps = 0     # steps remaining in a running trace
    self._out_dir = None
    self._capture_index = 0
    self.last_trace_dir = None

  def arm(self, steps, out_dir=None):
    """Request a capture of the next ``steps`` train steps; returns the
    directory the trace will land in. Re-arming while armed or active
    replaces the pending request (it does not extend a running trace)."""
    steps = max(1, int(steps))
    with self._lock:
      self._out_dir = out_dir or default_profile_dir()
      self._armed_steps = steps
      return self._out_dir

  def on_step(self):
    """Call once per train step, at the step boundary. Returns the trace
    directory when this call *finished* a capture, else None."""
    if not self._armed_steps and not self._active_steps:
      return None
    with self._lock:
      if self._armed_steps and not self._active_steps:
        import jax
        n = self._capture_index
        self._capture_index += 1
        trace_dir = os.path.join(self._out_dir or default_profile_dir(),
                                 f'capture{n:04d}')
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        self.last_trace_dir = trace_dir
        self._active_steps = self._armed_steps
        self._armed_steps = 0
        return None
      if self._active_steps:
        self._active_steps -= 1
        if self._active_steps == 0:
          import jax
          jax.profiler.stop_trace()
          return self.last_trace_dir
      return None

  def close(self):
    """Stop any in-flight trace (train-loop teardown); idempotent."""
    with self._lock:
      self._armed_steps = 0
      if self._active_steps:
        self._active_steps = 0
        import jax
        try:
          jax.profiler.stop_trace()
        except RuntimeError:
          # jax raises when no trace is running — a crash between our
          # start and this stop already tore the session down; the goal
          # (no trace left open) holds either way.
          pass

  @property
  def armed(self):
    return bool(self._armed_steps or self._active_steps)


_profiler = None
_profiler_lock = threading.Lock()


def get_step_profiler():
  """The process-wide :class:`StepProfiler` (created on first use; plain
  state, no threads)."""
  global _profiler
  if _profiler is None:
    with _profiler_lock:
      if _profiler is None:
        _profiler = StepProfiler()
  return _profiler


def _reset_for_tests():
  global _profiler
  if _profiler is not None:
    _profiler.close()
  _profiler = None

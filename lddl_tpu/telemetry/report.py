"""Cross-rank aggregation + the ``telemetry-report`` CLI.

Per-rank JSONL snapshots (written by :meth:`Telemetry.write_jsonl`)
merge into one view: counters and histograms add exactly across ranks,
gauges keep mean/min/max. The renderer groups metrics into the pipeline
stages they instrument (``preprocess`` executor phases, ``loader``,
``comm``, ``train``) and names the bottleneck stage — per-stage
throughput plus cross-rank stall attribution (per-rank data-wait and
collective-latency totals expose stragglers that rank-merged means
hide).

Aggregation has two transports:

  - offline: ``python -m lddl_tpu.cli telemetry-report --dir <dir>``
    globs ``telemetry.rank*.jsonl`` (any rank count, no live job
    needed);
  - live: :func:`aggregate_over_comm` rides the job's own
    ``CommBackend.allgather_object`` so rank 0 can print the merged
    report at the end of a run.
"""

import argparse
import glob
import json
import math
import os
import sys


def load_rank_files(directory):
  """Parse every ``telemetry.rank*.jsonl`` under ``directory``; returns
  a list of metric-line lists (one per file)."""
  paths = sorted(glob.glob(os.path.join(directory, 'telemetry.rank*.jsonl')))
  if not paths:
    raise FileNotFoundError(
        f'no telemetry.rank*.jsonl files under {directory} '
        '(run with LDDL_TELEMETRY=1 and LDDL_TELEMETRY_DIR set)')
  out = []
  for p in paths:
    lines = []
    with open(p) as f:
      for ln, line in enumerate(f, start=1):
        if not line.strip():
          continue
        try:
          lines.append(json.loads(line))
        except ValueError:
          # A SIGKILLed exporter can leave a torn trailing line; keep
          # the readable prefix instead of failing the whole report.
          print(f'telemetry-report: skipping unparseable line {ln} of '
                f'{p} (truncated write?)', file=sys.stderr)
    out.append(lines)
  return out


def _merge_histogram(agg, line):
  agg['count'] += line.get('count', 0)
  agg['sum'] += line.get('sum', 0.0)
  if line.get('count'):
    agg['min'] = min(agg['min'], line['min'])
    agg['max'] = max(agg['max'], line['max'])
  for k, v in (line.get('buckets') or {}).items():
    agg['buckets'][k] = agg['buckets'].get(k, 0) + v


def merge_metric_lines(rank_lines):
  """Merge per-rank metric-line lists into ``{name: merged}``.

  Counters/histograms sum; gauges combine mean/min/max over ranks.
  Every merged entry carries ``per_rank`` (rank -> that rank's fields)
  for stall attribution.
  """
  merged = {'ranks': [], 'metrics': {}}
  for lines in rank_lines:
    for line in lines:
      if line.get('kind') == 'meta':
        merged['ranks'].append(line.get('rank', 0))
        continue
      name, kind = line['name'], line['kind']
      m = merged['metrics'].get(name)
      if m is None:
        if kind == 'counter':
          m = {'kind': kind, 'total': 0, 'per_rank': {}}
        elif kind == 'gauge':
          m = {'kind': kind, 'sum': 0.0, 'count': 0, 'min': math.inf,
               'max': -math.inf, 'per_rank': {}}
        else:
          m = {'kind': kind, 'count': 0, 'sum': 0.0, 'min': math.inf,
               'max': -math.inf, 'buckets': {}, 'per_rank': {}}
        merged['metrics'][name] = m
      rank = line.get('rank', 0)
      m['per_rank'][rank] = {
          k: v for k, v in line.items() if k not in ('kind', 'rank', 'name')}
      if kind == 'counter':
        m['total'] += line.get('total', 0)
      elif kind == 'gauge':
        if line.get('count'):
          m['sum'] += line.get('mean', line.get('value', 0.0)) * line['count']
          m['count'] += line['count']
          m['min'] = min(m['min'], line.get('min', line['value']))
          m['max'] = max(m['max'], line.get('max', line['value']))
      else:
        _merge_histogram(m, line)
  for m in merged['metrics'].values():
    if m['kind'] == 'gauge' and m['count']:
      m['mean'] = m['sum'] / m['count']
  merged['ranks'] = sorted(set(merged['ranks'])) or sorted(
      {r for m in merged['metrics'].values() for r in m['per_rank']})
  return merged


def aggregate_over_comm(comm, telemetry=None, rank=None):
  """Allgather every rank's live snapshot and return the merged view
  (identical structure to merging the JSONL files offline)."""
  from .metrics import get_telemetry
  telemetry = telemetry or get_telemetry()
  rank = comm.rank if rank is None else rank
  snapshots = comm.allgather_object(telemetry.snapshot_lines(rank=rank))
  return merge_metric_lines(snapshots)


def _fmt_secs(s):
  if s is None or s != s:
    return '--'
  if s < 1e-3:
    return f'{s * 1e6:.0f}us'
  if s < 1.0:
    return f'{s * 1e3:.1f}ms'
  return f'{s:.2f}s'


def _hist_line(name, m):
  mean = m['sum'] / m['count'] if m['count'] else None
  return (f'  {name}: n={m["count"]} total={_fmt_secs(m["sum"])} '
          f'mean={_fmt_secs(mean)} max={_fmt_secs(m["max"] if m["count"] else None)}')


def _stage_of(name):
  head = name.split('.', 1)[0]
  return {'pipeline': 'preprocess', 'loader': 'loader', 'comm': 'comm',
          'train': 'train'}.get(head, head)


def summarize_stages(merged):
  """Per-stage totals + the bottleneck verdict. Returns a dict:
  ``{'stages': {stage: seconds}, 'bottleneck': str, 'detail': str}``."""
  metrics = merged['metrics']
  stages = {}

  def _hsum(name):
    m = metrics.get(name)
    return m['sum'] if m and m['kind'] == 'histogram' else 0.0

  for name, m in metrics.items():
    if m['kind'] != 'histogram':
      continue
    # Stage cost model: time actually spent inside that stage's spans.
    # map_seconds wraps task_seconds; count only the inner task time so
    # preprocess isn't double-billed.
    if name.startswith('pipeline.') and name.endswith('.task_seconds'):
      stages['preprocess'] = stages.get('preprocess', 0.0) + m['sum']
    elif name.startswith('loader.') and 'stall' not in name:
      stages['loader'] = stages.get('loader', 0.0) + m['sum']
    elif name.startswith('comm.'):
      stages['comm'] = stages.get('comm', 0.0) + m['sum']

  data_wait = _hsum('train.data_wait_seconds')
  compute = _hsum('train.compute_seconds')
  if data_wait or compute:
    stages['train.data_wait'] = data_wait
    stages['train.compute'] = compute
    frac = data_wait / max(data_wait + compute, 1e-12)
    if frac > 0.3:
      bottleneck = 'loader (training steps wait on input data)'
      detail = (f'{100 * frac:.0f}% of step time is data wait '
                f'({_fmt_secs(data_wait)} of '
                f'{_fmt_secs(data_wait + compute)})')
    else:
      bottleneck = 'compute (input pipeline keeps the chips busy)'
      detail = (f'data wait is {100 * frac:.0f}% of step time '
                f'({_fmt_secs(data_wait)} of '
                f'{_fmt_secs(data_wait + compute)})')
    return {'stages': stages, 'bottleneck': bottleneck, 'detail': detail}
  if not stages:
    return {'stages': stages, 'bottleneck': 'unknown (no stage timings)',
            'detail': ''}
  worst = max(stages, key=stages.get)
  return {'stages': stages,
          'bottleneck': worst,
          'detail': f'{worst} holds the largest total span time '
                    f'({_fmt_secs(stages[worst])})'}


def render_report(merged):
  """Human-readable per-stage summary of a merged snapshot."""
  metrics = merged['metrics']
  ranks = merged['ranks']
  out = [f'telemetry report — {len(ranks)} rank(s): {ranks}']

  by_stage = {}
  for name in sorted(metrics):
    by_stage.setdefault(_stage_of(name), []).append(name)

  # -- preprocess stages: per-stage task latency + throughput --
  if 'preprocess' in by_stage:
    out.append('\n[preprocess pipeline]')
    labels = sorted({n.split('.')[1] for n in by_stage['preprocess']})
    for label in labels:
      tasks = metrics.get(f'pipeline.{label}.tasks', {}).get('total', 0)
      th = metrics.get(f'pipeline.{label}.task_seconds')
      wall = metrics.get(f'pipeline.{label}.map_seconds')
      rate = None
      if wall and wall['count'] and wall['sum'] > 0:
        # map_seconds is per-rank wall time; ranks overlap, so the rate
        # uses the slowest rank's wall (the stage's critical path).
        slowest = max(
            (pr.get('sum', 0.0) for pr in wall['per_rank'].values()),
            default=wall['sum'])
        rate = tasks / slowest if slowest > 0 else None
      out.append(f'  stage {label}: {tasks} tasks'
                 + (f', {rate:.2f} tasks/s' if rate else ''))
      if th and th['count']:
        out.append(_hist_line(f'{label}.task_seconds', th))

  # -- loader --
  if 'loader' in by_stage:
    out.append('\n[loader]')
    rows = metrics.get('loader.rows', {}).get('total', 0)
    batches = metrics.get('loader.batches', {}).get('total', 0)
    out.append(f'  rows={rows} batches={batches}')
    for name in by_stage['loader']:
      m = metrics[name]
      if m['kind'] != 'histogram' or not m['count']:
        continue
      out.append(_hist_line(name, m))
    stall = metrics.get('loader.pull_stall_seconds')
    if stall and stall['count']:
      per_rank = {r: _fmt_secs(pr.get('sum', 0.0))
                  for r, pr in sorted(stall['per_rank'].items())}
      out.append(f'  stall by rank: {per_rank}')

  # -- comm --
  if 'comm' in by_stage:
    out.append('\n[comm]')
    for name in by_stage['comm']:
      m = metrics[name]
      if m['kind'] == 'histogram' and m['count']:
        out.append(_hist_line(name, m))
        per_rank = {r: _fmt_secs(pr.get('sum', 0.0))
                    for r, pr in sorted(m['per_rank'].items())}
        out.append(f'    by rank: {per_rank}')

  # -- train --
  if 'train' in by_stage:
    out.append('\n[train]')
    steps = metrics.get('train.steps', {}).get('total', 0)
    samples = metrics.get('train.samples', {}).get('total', 0)
    step_h = metrics.get('train.step_seconds')
    if step_h and step_h['count']:
      mean = step_h['sum'] / step_h['count']
      out.append(f'  steps={steps} samples={samples} '
                 f'mean step={_fmt_secs(mean)}')
    for name in ('train.data_wait_seconds', 'train.compute_seconds'):
      m = metrics.get(name)
      if m and m['count']:
        out.append(_hist_line(name, m))
    wait = metrics.get('train.data_wait_seconds')
    if wait and wait['count']:
      per_rank = {r: _fmt_secs(pr.get('sum', 0.0))
                  for r, pr in sorted(wait['per_rank'].items())}
      out.append(f'  data wait by rank: {per_rank}')
    mfu = metrics.get('train.mfu')
    if mfu and mfu.get('count'):
      out.append(f'  MFU: mean={100 * mfu["mean"]:.2f}% '
                 f'min={100 * mfu["min"]:.2f}% max={100 * mfu["max"]:.2f}%')
    tput = metrics.get('train.samples_per_sec')
    if tput and tput.get('count'):
      out.append(f'  throughput: {tput["mean"]:.1f} samples/s '
                 f'(max {tput["max"]:.1f})')
    tiles = metrics.get('train.attn_tiles_total', {}).get('total', 0)
    if tiles:
      skipped = metrics.get('train.attn_tiles_skipped',
                            {}).get('total', 0)
      out.append(f'  attention tiles: {tiles} total, {skipped} skipped '
                 f'({100 * skipped / tiles:.1f}% block-diagonal skip)')

  ft_counters = {
      'partitions claimed': 'pipeline.elastic.claims',
      'partitions re-executed': 'pipeline.elastic.reexecutions',
      'leases revoked': 'pipeline.elastic.revokes',
      'resume-skipped': 'pipeline.elastic.resume_skipped',
      'pool workers respawned': 'pipeline.pool.respawns',
      'comm IO retries': 'comm.io_retries',
      'train preemptions': 'train.elastic.preemptions',
      'train dead ranks': 'train.elastic.dead_ranks',
      'train ranks shed': 'train.elastic.sheds',
      'train rank rejoins': 'train.elastic.rejoins',
      'async ckpt writes': 'train.ckpt_writes',
  }
  ft_lines = []
  for title, name in ft_counters.items():
    total = metrics.get(name, {}).get('total', 0)
    if total:
      ft_lines.append(f'  {title}: {total}')
  if ft_lines:
    out.append('\n[fault tolerance]')
    out.extend(ft_lines)

  verdict = summarize_stages(merged)
  out.append('\n[bottleneck]')
  out.append(f'  {verdict["bottleneck"]}')
  if verdict['detail']:
    out.append(f'  {verdict["detail"]}')
  return '\n'.join(out)


def attach_args(parser):
  parser.add_argument('--dir', required=True,
                      help='directory holding telemetry.rank*.jsonl files')
  parser.add_argument('--json', action='store_true',
                      help='print the merged snapshot as JSON instead of '
                           'the human-readable report')
  return parser


def main(args=None):
  parser = attach_args(argparse.ArgumentParser(
      description=__doc__,
      formatter_class=argparse.RawDescriptionHelpFormatter))
  args = parser.parse_args(args)
  try:
    rank_lines = load_rank_files(args.dir)
  except FileNotFoundError as e:
    # An operator pointing at the wrong dir should get one clear line
    # and a distinct exit code, not a traceback or an empty report.
    print(f'telemetry-report: {e}', file=sys.stderr)
    return 2
  merged = merge_metric_lines(rank_lines)
  if args.json:
    print(json.dumps(merged, default=str, indent=2))
  else:
    print(render_report(merged))
  return 0


if __name__ == '__main__':
  sys.exit(main())

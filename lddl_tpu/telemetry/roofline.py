"""Roofline-aware device accounting: XLA step costs, chip peaks, HBM.

The host-side observability plane (PR 7) can say *which stage* of the
pipeline is slow; it cannot say whether the device itself is running
against its compute ceiling or its memory ceiling, because its only
device-cost signal is a formula-estimated FLOP count divided by a
hand-set peak. This module adds the device-side half:

  - **Exact per-step costs** from ``compiled.cost_analysis()``: XLA's
    own FLOP and bytes-accessed totals for the optimized, SPMD-
    partitioned program, captured once per (bin, shape) entry at
    :class:`~lddl_tpu.training.pretrain.CompiledStepCache` compile time
    (:func:`compiled_step_costs`). Steady-state cost per step is two
    counter adds (``train.xla_flops`` / ``train.xla_bytes``).
  - **Windowed roofline verdict** (:func:`roofline_verdict`): achieved
    FLOP/s and bytes/s over the monitor's snapshot window vs the chip
    peaks (:func:`resolve_peaks`), arithmetic intensity vs machine
    balance, and a bound class — ``compute-bound`` / ``memory-bound`` /
    ``input-bound`` — the distinction "Demystifying BERT"
    (arXiv:2104.08335) shows flips with sequence length and batch shape.
  - **HBM telemetry** (:func:`sample_hbm`): ``device.memory_stats()``
    bytes-in-use / peak / limit gauges plus an OOM-headroom meter,
    sampled at the scrape cadence (each ``/snapshot``), so an unwatched
    process does no periodic device polling at all.

Everything here is poll-driven or compile-time: with ``LDDL_MONITOR``
and ``LDDL_TELEMETRY`` unset nothing in this module runs.
"""

import math
import os


# ---------------------------------------------------------------------------
# exact per-step costs from the compiled executable


def compiled_step_costs(compiled):
  """(flops, bytes_accessed) of a compiled executable's *per-device*
  partitioned module, or None when the runtime exposes no cost model.

  ``cost_analysis()`` reports the post-optimization HLO module that one
  device actually runs (an SPMD-partitioned program reports ~1/N of the
  global math), so callers accounting whole-process work multiply by
  the local device count. Returns None rather than raising on any
  backend that lacks the analysis (an unsupported platform must not
  break the train loop).
  """
  fn = getattr(compiled, 'cost_analysis', None)
  if fn is None:
    return None
  try:
    analysis = fn()
  except Exception:
    return None
  if isinstance(analysis, (list, tuple)):
    analysis = analysis[0] if analysis else None
  if not isinstance(analysis, dict):
    return None
  flops = analysis.get('flops')
  if not flops or flops <= 0:
    return None
  return float(flops), float(analysis.get('bytes accessed') or 0.0)


# ---------------------------------------------------------------------------
# chip peaks (table + env overrides), cached per process

_peaks_cache = None


def resolve_peaks(refresh=False):
  """Per-process peak table for the roofline axes, resolved once.

  Returns ``{'flops_per_sec', 'hbm_bytes_per_sec', 'balance',
  'device_kind', 'local_devices'}`` where the peaks are *process totals*
  (per-device peak × local device count) and ``balance`` is the ridge
  point in FLOPs/byte. ``LDDL_PEAK_TFLOPS`` / ``LDDL_PEAK_HBM_GBPS``
  (per device) override the chip table — required on hosts the table
  cannot identify (CPU runs, unreleased chips), where the corresponding
  axis is None and the verdict degrades honestly.
  """
  global _peaks_cache
  if _peaks_cache is not None and not refresh:
    return _peaks_cache
  import jax

  from ..models.flops import peak_flops_per_device, peak_hbm_bytes_per_device
  device = jax.devices()[0]
  n = jax.local_device_count()
  env_flops = os.environ.get('LDDL_PEAK_TFLOPS')
  env_bw = os.environ.get('LDDL_PEAK_HBM_GBPS')
  per_dev_flops = (float(env_flops) * 1e12 if env_flops else
                   peak_flops_per_device(device))
  per_dev_bw = (float(env_bw) * 1e9 if env_bw else
                peak_hbm_bytes_per_device(device))
  _peaks_cache = {
      'flops_per_sec': per_dev_flops * n if per_dev_flops else None,
      'hbm_bytes_per_sec': per_dev_bw * n if per_dev_bw else None,
      'balance': (per_dev_flops / per_dev_bw
                  if per_dev_flops and per_dev_bw else None),
      'device_kind': device.device_kind,
      'local_devices': n,
  }
  return _peaks_cache


# ---------------------------------------------------------------------------
# the windowed verdict


def _counter_total(metrics, name):
  m = metrics.get(name)
  return m.get('total', 0) if m and m.get('kind') == 'counter' else 0


def _hist_sum(metrics, name):
  m = metrics.get(name)
  return m.get('sum', 0.0) if m and m.get('kind') == 'histogram' else 0.0


# An input-starved device is neither compute- nor memory-bound no matter
# what its arithmetic intensity says; same threshold the stage verdict
# uses for its loader-vs-compute call.
_INPUT_BOUND_WAIT_FRAC = 0.3


def roofline_verdict(merged, window_sec, peaks=None):
  """Bound-class verdict over a merged (windowed) metrics dict.

  ``merged`` is :func:`~.report.merge_metric_lines` output — pass the
  monitor window's delta for "right now", or a cumulative snapshot for
  run-mean. Reads the ``train.xla_flops`` / ``train.xla_bytes`` counters
  the compiled-step cache feeds and the data-wait/compute split, and
  compares against ``peaks`` (default: :func:`resolve_peaks`).

  Returns ``{'bound', 'detail', 'flops', 'bytes', 'flops_per_sec',
  'bytes_per_sec', 'flops_frac', 'bw_frac', 'arithmetic_intensity',
  'machine_balance', 'wait_frac', 'window_sec'}`` — fractions None when
  the corresponding peak is unknown; ``bound`` is ``'compute-bound'``,
  ``'memory-bound'``, ``'input-bound'``, or an ``'unknown (...)'``
  explanation when the window carries no cost deltas.
  """
  metrics = merged.get('metrics', {})
  flops = _counter_total(metrics, 'train.xla_flops')
  nbytes = _counter_total(metrics, 'train.xla_bytes')
  wait = _hist_sum(metrics, 'train.data_wait_seconds')
  compute = _hist_sum(metrics, 'train.compute_seconds')
  out = {
      'flops': flops, 'bytes': nbytes,
      'flops_per_sec': None, 'bytes_per_sec': None,
      'flops_frac': None, 'bw_frac': None,
      'arithmetic_intensity': None, 'machine_balance': None,
      'wait_frac': None, 'window_sec': window_sec,
  }
  if not flops or window_sec <= 0:
    out['bound'] = ('unknown (no compiled-step cost deltas in the window '
                    '— is the train loop running with the step cache on?)')
    out['detail'] = ''
    return out
  if peaks is None:
    peaks = resolve_peaks()
  peak_flops = peaks.get('flops_per_sec')
  peak_bw = peaks.get('hbm_bytes_per_sec')
  out['machine_balance'] = peaks.get('balance')
  out['flops_per_sec'] = flops / window_sec
  out['bytes_per_sec'] = nbytes / window_sec
  if nbytes:
    out['arithmetic_intensity'] = flops / nbytes
  if peak_flops:
    out['flops_frac'] = out['flops_per_sec'] / peak_flops
  if peak_bw:
    out['bw_frac'] = out['bytes_per_sec'] / peak_bw
  if wait or compute:
    out['wait_frac'] = wait / max(wait + compute, 1e-12)

  if out['wait_frac'] is not None and \
      out['wait_frac'] > _INPUT_BOUND_WAIT_FRAC:
    out['bound'] = 'input-bound'
    out['detail'] = (f'{100 * out["wait_frac"]:.0f}% of step time is data '
                     'wait; the device ceiling is not the limiter')
    return out
  ai, balance = out['arithmetic_intensity'], out['machine_balance']
  if ai is not None and balance is not None:
    if ai >= balance:
      out['bound'] = 'compute-bound'
      out['detail'] = (f'arithmetic intensity {ai:.0f} FLOPs/byte >= '
                       f'machine balance {balance:.0f}'
                       + (f'; {100 * out["flops_frac"]:.0f}% of peak FLOPs'
                          if out['flops_frac'] is not None else ''))
    else:
      out['bound'] = 'memory-bound'
      out['detail'] = (f'arithmetic intensity {ai:.0f} FLOPs/byte < '
                       f'machine balance {balance:.0f}'
                       + (f'; {100 * out["bw_frac"]:.0f}% of peak HBM '
                          'bandwidth'
                          if out['bw_frac'] is not None else ''))
    return out
  out['bound'] = ('unknown (chip peaks unresolved — set LDDL_PEAK_TFLOPS '
                  'and LDDL_PEAK_HBM_GBPS)')
  out['detail'] = ''
  return out


def bound_class(merged, window_sec, peaks=None):
  """Just the bound-class string (bench stamps, dashboards)."""
  return roofline_verdict(merged, window_sec, peaks=peaks)['bound']


# ---------------------------------------------------------------------------
# HBM telemetry (device.memory_stats), sampled at the scrape cadence

_MEMORY_STATS_KEYS = ('bytes_in_use', 'peak_bytes_in_use', 'bytes_limit')

_hbm_supported = None  # None: not yet probed this process


def sample_hbm(telemetry=None):
  """Sample ``device.memory_stats()`` into ``hbm.*`` gauges; returns the
  summary dict (or None where the runtime exposes no memory stats, e.g.
  the CPU backend — probed once, then free).

  Gauges (set only when telemetry is enabled):

    - ``hbm.bytes_in_use`` / ``hbm.peak_bytes_in_use`` /
      ``hbm.bytes_limit`` — summed over local devices;
    - ``hbm.headroom_frac`` — the OOM-headroom meter: worst-case (min
      over devices) ``1 - peak_bytes_in_use / bytes_limit``; a run that
      ever neared its limit shows it here even between scrapes, because
      ``peak_bytes_in_use`` is the allocator's high-water mark.
  """
  global _hbm_supported
  if _hbm_supported is False:
    return None
  try:
    import jax
    devices = jax.local_devices()
    per_device = [d.memory_stats() for d in devices]
  except Exception:
    _hbm_supported = False
    return None
  if not per_device or any(s is None for s in per_device):
    _hbm_supported = False
    return None
  _hbm_supported = True
  totals = {k: 0 for k in _MEMORY_STATS_KEYS}
  headroom = math.inf
  for stats in per_device:
    for k in _MEMORY_STATS_KEYS:
      totals[k] += int(stats.get(k, 0) or 0)
    limit = stats.get('bytes_limit') or 0
    if limit:
      headroom = min(headroom,
                     1.0 - (stats.get('peak_bytes_in_use', 0) or 0) / limit)
  summary = {
      'bytes_in_use': totals['bytes_in_use'],
      'peak_bytes_in_use': totals['peak_bytes_in_use'],
      'bytes_limit': totals['bytes_limit'],
      'headroom_frac': headroom if math.isfinite(headroom) else None,
      'devices': len(per_device),
  }
  if telemetry is None:
    from .metrics import get_telemetry
    telemetry = get_telemetry()
  if telemetry.enabled:
    telemetry.gauge('hbm.bytes_in_use').set(summary['bytes_in_use'])
    telemetry.gauge('hbm.peak_bytes_in_use').set(
        summary['peak_bytes_in_use'])
    telemetry.gauge('hbm.bytes_limit').set(summary['bytes_limit'])
    if summary['headroom_frac'] is not None:
      telemetry.gauge('hbm.headroom_frac').set(summary['headroom_frac'])
  return summary


def _reset_for_tests():
  """Clear the cached peak table and HBM support probe (tests flip env
  overrides and fake platforms; the caches must re-resolve)."""
  global _peaks_cache, _hbm_supported
  _peaks_cache = None
  _hbm_supported = None

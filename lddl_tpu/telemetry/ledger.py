"""Streaming determinism ledger: content fingerprints at pipeline
boundaries.

Every fault-tolerance contract in this stack — elastic preprocessing,
preemption-tolerant training, the network data service — promises
"byte-identical to the fault-free run", and until now that promise was
only checked inside the test suite. This module turns it into a runtime
fact: cheap content fingerprints computed at each pipeline boundary and
appended to a per-rank, crash-durable ``ledger.rank<R>.jsonl`` under
``LDDL_TELEMETRY_DIR``, so any two runs (or any two ranks) can be
diffed after the fact (``lddl-audit``, :mod:`.audit`) or compared live
(:func:`divergence_over_comm`, the ``lddl-monitor`` DIVERGED panel).

Instrumented boundaries and their coordinate keys:

  ``shard``      Parquet shard write           key: ``path`` (basename)
  ``collate``    loader batch, consumption
                 order (parent side)           key: ``(epoch, index)``
  ``serve.tx``   data-service frame, server
                 side, pre-send                key: ``gi``
  ``serve.rx``   the same frame, client side,
                 post-receive                  key: ``gi``
  ``device``     host batch entering the
                 device prefetcher             key: ``index``
  ``step``       train state at checkpoint
                 boundaries (loss + param
                 checksum from
                 ``snapshot_for_checkpoint``)  key: ``step``

Fingerprints are representation-independent: :func:`fingerprint_batch`
walks a live batch object (dicts / sequences / ndarrays) and
:func:`fingerprint_packed` walks a packed ``_pack_into`` spec over its
buffer, feeding the hash identical bytes (structure tags, dtype, shape,
raw C-order array bytes) — so the worker's shm slot, the data service's
wire frame, and a plain in-process batch of equal content all produce
the same digest, and the transport can be audited end to end without
ever re-packing. The hash is xxh64 when the ``xxhash`` wheel is
importable, else stdlib ``blake2b`` (8-byte digest); the ledger meta
line records which, and the auditor refuses to compare mixed-algorithm
ledgers. Never builtin ``hash()`` — it is salted per interpreter
(``PYTHONHASHSEED``) and can never be a stable fingerprint (lint rule
LDA013 enforces this tree-wide).

Discipline mirrors :mod:`.metrics` / :mod:`.trace` exactly:

1. **Disabled must cost ~nothing.** With ``LDDL_LEDGER`` unset
   (default) :func:`get_ledger` hands out the shared
   :data:`NOOP_LEDGER` singleton — zero threads, zero files, empty
   methods; instrument sites guard fingerprint computation behind
   ``ledger.enabled`` so disabled runs never hash a byte.
2. **Enabled stays cheap.** One lock, one hand-assembled JSON line,
   one ``os.write`` to an ``O_APPEND`` fd per record (atomic at line
   granularity, so forked pool workers can share the rank file); the
   measured cost is recorded in PERF.md.
3. **Crash-durable.** Every record reaches the kernel before
   ``record()`` returns (a SIGKILLed process loses nothing already
   recorded); ``LDDL_LEDGER_FSYNC=N`` additionally fsyncs every N
   records for machine-crash durability, and :meth:`Ledger.flush`
   always fsyncs.

Per boundary the ledger also maintains a rolling digest
(``roll_n = H(roll_{n-1} || digest_n)``) plus a bounded window of
recent ``(key, digest)`` pairs (``LDDL_LEDGER_WINDOW``, default 64) —
the live-exchange payload: :func:`divergence_over_comm` allgathers it
with the backend's collective seq (the same seq-keying trace alignment
and the straggler table use) and every rank computes the identical
divergence verdict. Cross-rank comparison only applies to boundaries
that are replicated across ranks by contract — data-parallel ranks
legitimately consume different batches — so the replicated set defaults
to ``step`` (train state is rank-identical after the gradient
all-reduce) and is overridable via ``LDDL_LEDGER_REPLICATED``.
"""

import collections
import hashlib
import json
import os
import threading
import time

import numpy as np

from .metrics import get_telemetry

try:
  import xxhash as _xxhash
except ImportError:  # no new deps: blake2b is stdlib and always present
  _xxhash = None

#: Name of the digest algorithm in use, recorded in every ledger meta
#: line; the auditor refuses to diff ledgers with mismatched algorithms.
ALGO = 'xxh64' if _xxhash is not None else 'blake2b8'

#: Coordinate fields that key a record for cross-run alignment, in
#: significance order; any other keyword to ``record()`` (``samples``,
#: ``loss``…) rides along as context without affecting alignment.
KEY_FIELDS = ('epoch', 'index', 'gi', 'step', 'path')


def _hasher():
  if _xxhash is not None:
    return _xxhash.xxh64()
  return hashlib.blake2b(digest_size=8)


def fingerprint_bytes(*chunks):
  """Hex digest over raw byte chunks (buffer-protocol objects)."""
  h = _hasher()
  for c in chunks:
    h.update(c)
  return h.hexdigest()


def fingerprint_file(path, chunk_bytes=1 << 20):
  """Streaming hex digest of a file's exact bytes (the shard boundary:
  what a resumed run would re-read from disk)."""
  h = _hasher()
  with open(path, 'rb') as f:
    for chunk in iter(lambda: f.read(chunk_bytes), b''):
      h.update(chunk)
  return h.hexdigest()


def _feed_batch(h, obj):
  """Feed ``obj`` to hasher ``h`` in the canonical structure walk.

  Must stay in lockstep with :func:`_feed_packed`: both reduce a batch
  to the same byte stream, whichever representation it arrives in.
  """
  if isinstance(obj, np.ndarray):
    h.update(f'nd{obj.dtype.str}{tuple(obj.shape)!r}'.encode())
    h.update(np.ascontiguousarray(obj).data)
    return
  if isinstance(obj, dict):
    h.update(b'map')
    for k, v in obj.items():
      h.update(f'k{k!r}'.encode())
      _feed_batch(h, v)
    return
  if isinstance(obj, (list, tuple)):
    h.update(f'seq{isinstance(obj, tuple)}'.encode())
    for v in obj:
      _feed_batch(h, v)
    return
  h.update(f'py{obj!r}'.encode())


def _feed_packed(h, spec, buf):
  """Feed a packed ``_pack_into`` spec over ``buf`` to hasher ``h``.

  Hashes only array content at the spec's offsets (never slot padding),
  so the digest is independent of slot base offsets and alignment — a
  shm slot and a wire frame of the same batch hash identically.
  """
  kind = spec[0]
  if kind == 'nd':
    _, dtype, shape, offset = spec
    nbytes = int(np.dtype(dtype).itemsize * int(np.prod(shape, dtype=np.int64)))
    h.update(f'nd{dtype}{tuple(shape)!r}'.encode())
    h.update(memoryview(buf)[offset:offset + nbytes])
    return
  if kind == 'map':
    h.update(b'map')
    for k, s in spec[1]:
      h.update(f'k{k!r}'.encode())
      _feed_packed(h, s, buf)
    return
  if kind == 'seq':
    _, is_tuple, specs = spec
    h.update(f'seq{bool(is_tuple)}'.encode())
    for s in specs:
      _feed_packed(h, s, buf)
    return
  h.update(f'py{spec[1]!r}'.encode())  # 'py'


def fingerprint_batch(obj):
  """Digest of a live batch (dicts / sequences / ndarrays / leaves)."""
  h = _hasher()
  _feed_batch(h, obj)
  return h.hexdigest()


def fingerprint_packed(spec, buf):
  """Digest of a packed batch from its ``_pack_into`` spec + buffer;
  equal to :func:`fingerprint_batch` of the original object."""
  h = _hasher()
  _feed_packed(h, spec, buf)
  return h.hexdigest()


def first_ndarray(obj):
  """The first ndarray leaf of a live batch in canonical walk order
  (None when there is none) — the live-batch twin of
  :func:`first_array_span`, for aiming the ``ledger.corrupt`` fault at
  unpacked batches."""
  if isinstance(obj, np.ndarray):
    return obj
  if isinstance(obj, dict):
    values = obj.values()
  elif isinstance(obj, (list, tuple)):
    values = obj
  else:
    return None
  for v in values:
    arr = first_ndarray(v)
    if arr is not None:
      return arr
  return None


def first_array_span(spec):
  """``(offset, nbytes)`` of the first ndarray leaf in a packed spec
  (None when the batch carries no arrays). This is where the
  ``ledger.corrupt`` fault flips its byte: aiming at real array content
  rather than byte 0 of the slot, which may be padding the fingerprint
  deliberately ignores."""
  kind = spec[0]
  if kind == 'nd':
    _, dtype, shape, offset = spec
    return offset, int(
        np.dtype(dtype).itemsize * int(np.prod(shape, dtype=np.int64)))
  if kind == 'map':
    for _, s in spec[1]:
      span = first_array_span(s)
      if span is not None:
        return span
  elif kind == 'seq':
    for s in spec[2]:
      span = first_array_span(s)
      if span is not None:
        return span
  return None


def record_key(rec):
  """The alignment key of a ledger record: the :data:`KEY_FIELDS`
  values it carries, in canonical order (None when it carries none —
  the auditor then falls back to per-boundary sequence position)."""
  key = tuple((f, rec[f]) for f in KEY_FIELDS if f in rec)
  return key or None


def ledger_file_name(directory, rank):
  """Canonical per-rank ledger path (what ``lddl-audit`` globs)."""
  return os.path.join(directory, f'ledger.rank{rank}.jsonl')


def replicated_boundaries():
  """Boundaries whose streams are rank-identical by contract, i.e. the
  only ones the cross-rank divergence verdict may compare (env
  ``LDDL_LEDGER_REPLICATED``, comma-separated; default ``step``)."""
  spec = os.environ.get('LDDL_LEDGER_REPLICATED', 'step')
  return tuple(b.strip() for b in spec.split(',') if b.strip())


class NoopLedger:
  """The disabled ledger: zero files, zero state, empty methods."""

  __slots__ = ()
  enabled = False

  def record(self, boundary, digest, **coords):
    return None

  def signals(self):
    return {}

  def set_fleet_verdict(self, verdict):
    pass

  def fleet_verdict(self):
    return None

  def flush(self):
    pass

  def close(self):
    pass


NOOP_LEDGER = NoopLedger()

_DEFAULT_WINDOW = 64


class _Stream:
  """Per-boundary rolling state."""

  __slots__ = ('count', 'rolling', 'recent')

  def __init__(self, window):
    self.count = 0
    self.rolling = ''
    self.recent = collections.deque(maxlen=window)  # (key-list, digest)


class Ledger:
  """An enabled determinism ledger (one per process).

  Appends one JSON line per record to ``ledger.rank<R>.jsonl`` via a
  single ``os.write`` on an ``O_APPEND`` fd — atomic at line
  granularity, so a forked pool worker inheriting the fd (or a spawned
  one reopening the same path) interleaves cleanly with the parent.
  Rolling digests and record counts are per-process per-boundary; the
  auditor aligns multi-process boundaries (``shard``) by key, not by
  rolling chain.
  """

  enabled = True

  def __init__(self, directory=None, rank=None, window=None):
    if directory is None:
      directory = os.environ.get('LDDL_TELEMETRY_DIR') or '.'
    if rank is None:
      rank = int(os.environ.get('LDDL_RANK', '0') or 0)
    if window is None:
      try:
        window = int(os.environ.get('LDDL_LEDGER_WINDOW', _DEFAULT_WINDOW))
      except ValueError:
        window = _DEFAULT_WINDOW
    self.rank = rank
    self.window = max(2, window)
    self.path = ledger_file_name(directory, rank)
    os.makedirs(directory, exist_ok=True)
    # lddl: noqa[LDA004] the fd lives as long as the ledger singleton;
    # close() releases it on disable()/interpreter exit.
    self._fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                       0o644)
    try:
      fsync_every = int(os.environ.get('LDDL_LEDGER_FSYNC', '0'))
    except ValueError:
      fsync_every = 0
    self._fsync_every = fsync_every
    self._since_fsync = 0
    self._lock = threading.Lock()
    self._streams = {}
    self._fleet_verdict = None
    self._records_c = get_telemetry().counter('ledger.records')
    os.write(self._fd, (json.dumps(
        {'kind': 'meta', 'rank': rank, 'pid': os.getpid(), 'algo': ALGO,
         'window': self.window, 'unix_time': time.time()},
        sort_keys=True) + '\n').encode())

  def record(self, boundary, digest, **coords):
    """Append one fingerprint record; returns the stream's new rolling
    digest. ``coords`` key fields (:data:`KEY_FIELDS`) align the record
    across runs/ranks; other keywords are carried as context."""
    with self._lock:
      st = self._streams.get(boundary)
      if st is None:
        st = self._streams[boundary] = _Stream(self.window)
      st.count += 1
      st.rolling = fingerprint_bytes(st.rolling.encode(), digest.encode())
      st.recent.append(([coords[f] for f in KEY_FIELDS if f in coords],
                        digest))
      # Hand-assembled JSON: boundary/digest/rolling are safe token/hex
      # strings, so only coordinate values need real escaping. Saves a
      # json.dumps per batch on the hot path.
      line = (f'{{"boundary":"{boundary}","digest":"{digest}",'
              f'"n":{st.count},"rolling":"{st.rolling}"')
      for k, v in coords.items():
        if v is True or v is False:
          line += f',"{k}":{"true" if v else "false"}'
        elif isinstance(v, (int, float)):
          line += f',"{k}":{v}'
        else:
          line += f',"{k}":{json.dumps(str(v))}'
      os.write(self._fd, (line + '}\n').encode())
      if self._fsync_every:
        self._since_fsync += 1
        if self._since_fsync >= self._fsync_every:
          self._since_fsync = 0
          os.fsync(self._fd)
      self._records_c.add(1)
      return st.rolling

  def signals(self):
    """Per-boundary live state for the divergence exchange / the
    ``/snapshot`` payload: ``{boundary: {count, rolling, recent}}``."""
    with self._lock:
      return {
          b: {'count': st.count, 'rolling': st.rolling,
              'recent': [[k, d] for k, d in st.recent]}
          for b, st in self._streams.items()
      }

  def set_fleet_verdict(self, verdict):
    """Stash the latest cross-rank divergence verdict (from
    :func:`divergence_over_comm`) so local verdict consumers
    (``live_verdict`` → ``/snapshot``) can surface it without a
    collective of their own."""
    with self._lock:
      self._fleet_verdict = verdict

  def fleet_verdict(self):
    with self._lock:
      return self._fleet_verdict

  def flush(self):
    """fsync the ledger fd (machine-crash durability point)."""
    with self._lock:
      if self._fd is not None:
        os.fsync(self._fd)

  def close(self):
    with self._lock:
      if self._fd is not None:
        try:
          os.fsync(self._fd)
        except OSError:
          pass
        os.close(self._fd)
        self._fd = None


# ---------------------------------------------------------------------------
# divergence verdicts


def compare_signals(per_rank, replicated=None):
  """Cross-rank divergence verdict from gathered :meth:`Ledger.signals`.

  ``per_rank``: ``{rank: signals dict}``. Only boundaries in
  ``replicated`` (default :func:`replicated_boundaries`) are compared —
  everything else legitimately differs across data-parallel ranks.
  Pure arithmetic over the gathered state: every rank computes the
  identical verdict.

  Per compared boundary:

    - ranks at different counts are ``lagging`` (progress skew, not
      divergence — the straggler table's job);
    - equal counts with equal rolling digests are ``ok``;
    - equal counts with different rolling digests are ``diverged``, and
      the earliest key in the recent-window overlap whose digests
      differ names the first divergent batch (``first`` is None when
      the divergence predates the retained window).

  Returns ``{'status': 'ok'|'diverged'|None, 'boundaries': {...},
  'first': {...}|None}``; status None when no boundary was comparable.
  """
  if replicated is None:
    replicated = replicated_boundaries()
  boundaries = {}
  first_overall = None
  status = None
  for b in replicated:
    ranks = {r: s[b] for r, s in per_rank.items() if s and b in s}
    if len(ranks) < 2:
      continue
    counts = {r: st['count'] for r, st in ranks.items()}
    entry = {'counts': counts, 'first': None}
    if len(set(counts.values())) > 1:
      entry['status'] = 'lagging'
    elif len({st['rolling'] for st in ranks.values()}) == 1:
      entry['status'] = 'ok'
    else:
      entry['status'] = 'diverged'
      # Earliest key (by key order) seen by >= 2 ranks with differing
      # digests inside the retained windows.
      by_key = {}
      for r, st in ranks.items():
        for key, digest in st.get('recent') or []:
          by_key.setdefault(tuple(key), {})[r] = digest
      divergent = sorted(
          k for k, ds in by_key.items()
          if len(ds) > 1 and len(set(ds.values())) > 1)
      if divergent:
        k = divergent[0]
        entry['first'] = {'key': list(k),
                          'digests': {r: d
                                      for r, d in sorted(
                                          by_key[k].items())}}
    boundaries[b] = entry
    if entry['status'] == 'diverged':
      status = 'diverged'
      if first_overall is None:
        first_overall = {'boundary': b, **(entry['first'] or {'key': None})}
    elif status is None:
      status = 'ok'
  return {'status': status, 'boundaries': boundaries,
          'first': first_overall}


def divergence_over_comm(comm, ledger=None, telemetry=None):
  """Fleet divergence verdict over the run's own comm backend.

  Every rank contributes its ledger signals; the allgather rides the
  backend's normal collective stream tagged with the collective seq
  (the discipline :func:`~.live.straggler_over_comm` and trace
  alignment share), all ranks compute the identical verdict, and the
  result is stashed on the ledger for ``/snapshot`` consumers plus
  counted into ``ledger.divergences`` when it names a divergence.
  No-op (returns None) when the ledger is disabled.
  """
  led = ledger if ledger is not None else get_ledger()
  if not led.enabled:
    return None
  seq = getattr(comm, 'collective_seq', None)
  gathered = comm.allgather_object(
      {'rank': comm.rank, 'seq': seq, 'ledger': led.signals()})
  verdict = compare_signals({e['rank']: e['ledger'] for e in gathered})
  seqs = {e['seq'] for e in gathered if e.get('seq') is not None}
  verdict['seq'] = max(seqs) if seqs else None
  if len(seqs) > 1:
    verdict['seq_mismatch'] = sorted(seqs)
  led.set_fleet_verdict(verdict)
  tele = telemetry if telemetry is not None else get_telemetry()
  if tele.enabled and verdict['status'] == 'diverged':
    tele.counter('ledger.divergences').add(1)
  return verdict


def determinism_verdict(ledger=None):
  """The ``verdict.determinism`` block for :func:`~.live.live_verdict`:
  this process's per-boundary stream heads plus the latest fleet
  verdict (if a :func:`divergence_over_comm` round stored one). None
  when the ledger is disabled — quiet dashboards by default."""
  led = ledger if ledger is not None else get_ledger()
  if not led.enabled:
    return None
  signals = led.signals()
  fleet = led.fleet_verdict()
  status = (fleet or {}).get('status') or ('ok' if signals else 'idle')
  return {
      'status': status,
      'streams': {
          b: {'count': st['count'], 'rolling': st['rolling'],
              'last': st['recent'][-1] if st['recent'] else None}
          for b, st in signals.items()
      },
      'fleet': fleet,
  }


# ---------------------------------------------------------------------------
# process-global gate (the metrics.py / trace.py discipline)


_ENV = 'LDDL_LEDGER'
_active = None  # None: not yet resolved from the environment
# First resolution can race: producer/writer threads and the main loop
# all call get_ledger() lazily. The lock makes the install atomic.
_active_lock = threading.Lock()


def get_ledger():
  """The process-global ledger: :class:`Ledger` when enabled (env
  ``LDDL_LEDGER`` truthy or :func:`enable_ledger` called), else the
  shared :data:`NOOP_LEDGER` singleton."""
  global _active
  with _active_lock:
    if _active is None:
      spec = os.environ.get(_ENV, '').strip().lower()
      _active = (Ledger() if spec in ('1', 'true', 'on', 'yes')
                 else NOOP_LEDGER)
    return _active


def enable_ledger(**kwargs):
  """Switch the ledger on (fresh instance unless already enabled)."""
  global _active
  with _active_lock:
    if _active is None or not _active.enabled:
      _active = Ledger(**kwargs)
    return _active


def disable_ledger():
  """Switch the ledger off (instrument sites see :data:`NOOP_LEDGER`);
  closes the active file first."""
  global _active
  with _active_lock:
    if _active is not None and _active.enabled:
      _active.close()
    _active = NOOP_LEDGER
    return _active

"""Pipeline-wide telemetry: metrics, timing spans, and cross-rank
stall attribution.

The observability layer every perf round reports through: counters,
gauges, and log-bucketed histograms with monotonic-clock timing spans,
threaded through the pipeline executor, the loader stack, the comm
backends, and the training loop. Disabled by default and a strict
no-op when off (env ``LDDL_TELEMETRY=0``/unset): the disabled path is
shared immutable singletons — no locks, no per-event allocation — so
hot loops can stay instrumented unconditionally.

Per-rank snapshots export as JSONL (``telemetry.rank<R>.jsonl``);
cross-rank aggregation rides :meth:`CommBackend.allgather_object`;
``python -m lddl_tpu.cli telemetry-report`` merges rank files into a
per-stage summary naming the bottleneck stage.

A sibling event-level layer (:mod:`.trace`, env ``LDDL_TRACE``) records
*when* things happened into a bounded ring buffer per process
(``trace.rank<R>[.pid<P>].jsonl``); ``python -m lddl_tpu.cli
telemetry-trace`` merges all ranks into one clock-aligned
Chrome-trace-format JSON for Perfetto / ``chrome://tracing``.

The live plane (:mod:`.live` + :mod:`.server`, env ``LDDL_MONITOR``)
serves the same registry *during* the run: windowed snapshot deltas
feeding the report's bottleneck verdict online, per-rank straggler
scores over the comm backend, goodput/padding-efficiency meters, and a
per-process HTTP endpoint (JSON ``/snapshot`` + Prometheus
``/metrics``) that ``python -m lddl_tpu.cli lddl-monitor`` turns into a
refreshing terminal dashboard. Same no-op discipline: unset means zero
threads, zero sockets.

The device-side plane (:mod:`.roofline` + :mod:`.profiling` +
:mod:`.perf`) closes the loop against the chip itself: exact per-step
FLOPs/bytes from ``compiled.cost_analysis()`` feeding a windowed
roofline verdict (compute- vs memory- vs input-bound) and the measured
MFU numerator, ``device.memory_stats()`` HBM gauges at the scrape
cadence, on-demand ``jax.profiler`` capture armed over the monitor's
``/profile`` endpoint, and the ``lddl-perf`` regression gate over bench
history.

The determinism plane (:mod:`.ledger` + :mod:`.audit`, env
``LDDL_LEDGER``) turns the stack's byte-identity contracts into
runtime-verified facts: streaming content fingerprints at every
pipeline boundary appended to crash-durable ``ledger.rank<R>.jsonl``
files, cross-run/cross-rank diffing with first-divergence bisection
(``lddl-audit``), and live divergence verdicts over the comm backend
feeding ``verdict.determinism`` and the monitor's DIVERGED panel. Same
no-op discipline: unset means zero files, zero hashing.
"""

from .metrics import (
    NOOP,
    NoopTelemetry,
    Telemetry,
    diff_snapshot_lines,
    disable,
    enable,
    get_telemetry,
    rank_file_name,
)
from .live import (
    SnapshotWindow,
    goodput_meters,
    live_status,
    live_verdict,
    rank_signals,
    stage_rates,
    straggler_over_comm,
    straggler_scores,
)
from .server import (
    NOOP_MONITOR,
    MonitorServer,
    NoopMonitor,
    get_monitor,
    maybe_start_monitor,
    prometheus_lines,
    stop_monitor,
)
from .report import (
    aggregate_over_comm,
    load_rank_files,
    merge_metric_lines,
    render_report,
)
from .roofline import (
    compiled_step_costs,
    resolve_peaks,
    roofline_verdict,
    sample_hbm,
)
from .profiling import (
    StepProfiler,
    get_step_profiler,
    trace_capture,
)
from .trace import (
    NOOP_TRACER,
    NoopTracer,
    Tracer,
    disable_trace,
    enable_trace,
    get_tracer,
    load_trace_files,
    merge_trace_files,
    trace_file_name,
)
from .ledger import (
    NOOP_LEDGER,
    Ledger,
    NoopLedger,
    compare_signals,
    determinism_verdict,
    disable_ledger,
    divergence_over_comm,
    enable_ledger,
    fingerprint_batch,
    fingerprint_bytes,
    fingerprint_file,
    fingerprint_packed,
    get_ledger,
    ledger_file_name,
)

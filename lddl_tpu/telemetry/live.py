"""Streaming verdict engine: windowed rates, straggler scores, goodput.

The PR 1-2 telemetry stack is post-hoc — metrics land in per-rank JSONL
and verdicts are computed after the run. The ROADMAP directions that
consume telemetry (cross-rank work stealing, loader-as-a-data-service)
need the same signals *while the run is going*: who is slow right now,
is the pipeline loader- or compute-bound right now, how much of the
padded-token budget is real work. This module derives all of that from
the existing :class:`~lddl_tpu.telemetry.metrics.Telemetry` registry —
no new lock, no sampler thread of its own; whoever polls (the
``LDDL_MONITOR`` HTTP server, a test, rank 0's aggregation round)
drives the sampling cadence.

Three layers, each a pure function of registry snapshots:

  - :class:`SnapshotWindow` — a bounded deque of ``snapshot_lines()``
    captures; ``delta()`` subtracts oldest from newest
    (:func:`~.metrics.diff_snapshot_lines`), so every rate is over the
    window's *monotonic* span, never wall clock;
  - :func:`live_verdict` / :func:`stage_rates` — the windowed delta
    merged through the offline report machinery
    (:func:`~.report.merge_metric_lines` + ``summarize_stages``), i.e.
    the exact bottleneck logic the post-hoc report applies, online;
  - :func:`rank_signals` / :func:`straggler_scores` — per-rank
    task-completion / write-back / row / step rates vs the fleet
    median, aggregated over the run's own comm backend
    (:func:`straggler_over_comm`) with the same seq-keyed discipline
    trace alignment uses, and :func:`goodput_meters` — padding
    efficiency, step-cache hit rate, h2d/compute overlap, queue/slot
    backpressure.
"""

import collections
import math
import os
import time

from .metrics import diff_snapshot_lines, get_telemetry
from .report import merge_metric_lines, summarize_stages


class SnapshotWindow:
  """Rolling registry captures; rates/percentiles over the last N.

  ``sample()`` appends the live registry's ``snapshot_lines()`` (each
  capture carries its own ``(unix, monotonic)`` anchor pair);
  ``push()`` accepts pre-built lines for synthetic/offline use.
  ``delta()`` diffs the oldest retained capture against the newest, so
  the window span grows until the deque is full and then slides.
  """

  def __init__(self, capacity=12):
    if capacity < 2:
      raise ValueError(f'window capacity must be >= 2, got {capacity}')
    self._snaps = collections.deque(maxlen=capacity)

  def __len__(self):
    return len(self._snaps)

  def sample(self, telemetry=None, rank=0):
    """Capture the live registry (or ``telemetry``) into the window."""
    tele = telemetry if telemetry is not None else get_telemetry()
    lines = tele.snapshot_lines(rank=rank)
    if lines:
      self._snaps.append(lines)
    return lines

  def push(self, lines):
    """Append pre-built snapshot lines (tests, replayed JSONL)."""
    self._snaps.append(lines)

  def delta(self):
    """Windowed delta lines (oldest -> newest), or None if < 2 samples."""
    if len(self._snaps) < 2:
      return None
    return diff_snapshot_lines(self._snaps[0], self._snaps[-1])

  def window_sec(self):
    """Monotonic span the current delta covers (0.0 if < 2 samples)."""
    d = self.delta()
    if d is None:
      return 0.0
    for line in d:
      if line.get('kind') == 'meta':
        return line.get('window_sec', 0.0)
    return 0.0


def _merged_delta(window):
  d = window.delta()
  if d is None:
    return None, 0.0
  merged = merge_metric_lines([d])
  sec = window.window_sec()
  return merged, sec


def stage_rates(window):
  """Per-counter events/sec over the window: ``{name: rate}``.

  Histogram names get ``<name>.rate`` (occurrences/sec) plus
  ``<name>.mean`` (mean seconds within the window) so per-stage span
  costs read online the way the report prints them post-hoc.
  """
  merged, sec = _merged_delta(window)
  if merged is None or sec <= 0:
    return {}
  rates = {}
  for name, m in merged['metrics'].items():
    if m['kind'] == 'counter':
      if m['total']:
        rates[name] = m['total'] / sec
    elif m['kind'] == 'histogram' and m['count']:
      rates[name + '.rate'] = m['count'] / sec
      rates[name + '.mean'] = m['sum'] / m['count']
  return rates


def live_verdict(window):
  """The post-hoc bottleneck verdict, computed over the live window.

  Returns ``summarize_stages``' dict plus ``window_sec`` and — when the
  train loop's compiled-step cache is feeding XLA cost counters — a
  ``roofline`` sub-verdict (achieved vs peak FLOP/s and bytes/s,
  arithmetic intensity vs machine balance, bound class). Falls back to
  ``{'bottleneck': 'unknown (window warming up)'}`` until the window
  holds two samples.
  """
  from .ledger import determinism_verdict
  merged, sec = _merged_delta(window)
  if merged is None:
    return {'stages': {}, 'bottleneck': 'unknown (window warming up)',
            'detail': '', 'window_sec': 0.0, 'roofline': None,
            'serve': None, 'determinism': determinism_verdict()}
  verdict = summarize_stages(merged)
  verdict['window_sec'] = sec
  from .roofline import roofline_verdict
  verdict['roofline'] = roofline_verdict(merged, sec)
  verdict['serve'] = serve_verdict(merged, sec)
  # None whenever LDDL_LEDGER is off: determinism checking is opt-in
  # and a quiet dashboard must stay quiet.
  verdict['determinism'] = determinism_verdict()
  return verdict


def serve_verdict(merged, sec):
  """Data-service sub-verdict over a windowed delta: delivery rate plus
  the fault-churn counters (re-serves to recovering clients, lease
  revocations of dead ones, degrade/re-attach transitions). None when
  the window saw no ``serve.*`` activity — quiet dashboards for the
  overwhelming majority of runs that never serve over the wire."""
  metrics = merged['metrics']
  served = _counter_total(metrics, 'serve.batches_served')
  pulls = _counter_total(metrics, 'serve.client_pulls')
  meters = {
      'batches_served': served,
      'batches_per_sec': served / sec if sec > 0 else None,
      'client_pulls': pulls,
      'reserves': _counter_total(metrics, 'serve.reserves'),
      'lease_revokes': _counter_total(metrics, 'serve.lease_revokes'),
      'fallbacks': _counter_total(metrics, 'serve.fallbacks'),
      'reattaches': _counter_total(metrics, 'serve.reattaches'),
      'clients': _gauge(metrics, 'serve.clients'),
      'backlog': _gauge(metrics, 'serve.backlog'),
  }
  active = (served or pulls or meters['reserves'] or
            meters['lease_revokes'] or meters['fallbacks'] or
            meters['reattaches'] or meters['clients'] is not None or
            meters['backlog'] is not None)
  return meters if active else None


# ---------------------------------------------------------------------------
# goodput / padding-efficiency meters


def _counter_total(metrics, name):
  m = metrics.get(name)
  return m.get('total', 0) if m and m['kind'] == 'counter' else 0


def _hist_sum(metrics, name):
  m = metrics.get(name)
  return m.get('sum', 0.0) if m and m['kind'] == 'histogram' else 0.0


def _gauge(metrics, name):
  m = metrics.get(name)
  if not m or m['kind'] != 'gauge':
    return None
  if 'mean' in m:
    return {'mean': m['mean'], 'min': m['min'], 'max': m['max']}
  v = m.get('value')
  return None if v is None else {'mean': v, 'min': v, 'max': v}


def goodput_meters(merged):
  """Efficiency meters from a merged metrics dict (cumulative snapshot
  or windowed delta — both work; pass the delta for \"right now\").

  Returns a dict of named meters, each ``None`` when its inputs are not
  instrumented in this process:

    - ``padding_efficiency``: real tokens / padded token slots across
      the binned collates (per-bin breakdown under ``per_bin``) — the
      live accounting for the waste binning exists to eliminate;
    - ``step_cache_hit_rate``: warm-executable fraction of train steps;
    - ``h2d_overlap_fraction``: 1 - data_wait/h2d — how much of the
      host-to-device transfer hides behind compute;
    - ``attn_tile_skip_fraction``: fraction of attention-grid tiles the
      block-diagonal packed path skipped outright (cross-document
      tiles the flash/ring kernels never compute) — 0 under full
      attention, approaches (k-1)/k at k docs per packed row;
    - ``queue_depth`` / ``shm_slot_occupancy`` / ``writer_backlog`` /
      ``ckpt_backlog``: backpressure gauges (mean/min/max) from the
      loader transport, the async shard writer, and the async
      checkpoint writer.
  """
  metrics = merged['metrics']
  out = {}

  real_total, padded_total, per_bin = 0, 0, {}
  for name, m in metrics.items():
    if m['kind'] != 'counter' or not name.startswith('loader.tokens_real.s'):
      continue
    seq = name[len('loader.tokens_real.s'):]
    real = m['total']
    padded = _counter_total(metrics, f'loader.tokens_padded.s{seq}')
    real_total += real
    padded_total += padded
    if padded:
      per_bin[f's{seq}'] = real / padded
  if padded_total:
    out['padding_efficiency'] = real_total / padded_total
    out['padding_efficiency_per_bin'] = per_bin
    out['tokens_real'] = real_total
    out['tokens_padded'] = padded_total
  else:
    out['padding_efficiency'] = None

  hits = _counter_total(metrics, 'train.step_cache_hits')
  misses = _counter_total(metrics, 'train.step_cache_misses')
  out['step_cache_hit_rate'] = (
      hits / (hits + misses) if hits + misses else None)

  h2d = _hist_sum(metrics, 'train.h2d_seconds')
  wait = _hist_sum(metrics, 'train.data_wait_seconds')
  if h2d > 0:
    # The producer thread transfers batch k+1 while the main thread
    # computes batch k; the part that did NOT hide behind compute is
    # exactly what the main thread then waits out as data_wait.
    out['h2d_overlap_fraction'] = max(0.0, min(1.0, 1.0 - wait / h2d))
  else:
    out['h2d_overlap_fraction'] = None

  tiles = _counter_total(metrics, 'train.attn_tiles_total')
  skipped = _counter_total(metrics, 'train.attn_tiles_skipped')
  out['attn_tile_skip_fraction'] = skipped / tiles if tiles else None

  out['queue_depth'] = _gauge(metrics, 'loader.queue_depth')
  out['shm_slot_occupancy'] = _gauge(metrics, 'loader.shm_slot_occupancy')
  out['writer_backlog'] = _gauge(metrics, 'pipeline.pool.writer_backlog')
  out['ckpt_backlog'] = _gauge(metrics, 'train.ckpt_backlog')

  out['mfu'] = _gauge(metrics, 'train.mfu')
  # Global gradient norm (parallel/train.py exports it from the jitted
  # step): the live training-health meter the sentinel's grad_spike
  # detector watches, surfaced here for the monitor's per-rank line.
  out['grad_norm'] = _gauge(metrics, 'train.grad_norm')
  # Device-memory meters: the prefetcher's live-array accounting (the
  # measured form of the "steady-state HBM = 2 batches" donation claim)
  # and the allocator's own view sampled from device.memory_stats().
  out['device_live_bytes'] = _gauge(metrics, 'loader.device_live_bytes')
  out['device_live_batches'] = _gauge(metrics, 'loader.device_live_batches')
  hbm = {
      'bytes_in_use': _gauge(metrics, 'hbm.bytes_in_use'),
      'peak_bytes_in_use': _gauge(metrics, 'hbm.peak_bytes_in_use'),
      'bytes_limit': _gauge(metrics, 'hbm.bytes_limit'),
      'headroom_frac': _gauge(metrics, 'hbm.headroom_frac'),
  }
  out['hbm'] = hbm if any(v is not None for v in hbm.values()) else None

  # Fault-tolerance meters: lease churn of the elastic executor plus the
  # local recovery counters (pool respawns, retried comm IO). All-zero
  # (the healthy fast path) reports None so dashboards stay quiet.
  ft = {
      'claims': _counter_total(metrics, 'pipeline.elastic.claims'),
      'reexecutions': _counter_total(metrics,
                                     'pipeline.elastic.reexecutions'),
      'revokes': _counter_total(metrics, 'pipeline.elastic.revokes'),
      'resume_skipped': _counter_total(metrics,
                                       'pipeline.elastic.resume_skipped'),
      'pool_respawns': _counter_total(metrics, 'pipeline.pool.respawns'),
      'io_retries': _counter_total(metrics, 'comm.io_retries'),
      'train_preemptions': _counter_total(metrics,
                                          'train.elastic.preemptions'),
      'train_dead_ranks': _counter_total(metrics,
                                         'train.elastic.dead_ranks'),
      'train_sheds': _counter_total(metrics, 'train.elastic.sheds'),
      'train_rejoins': _counter_total(metrics, 'train.elastic.rejoins'),
      'async_ckpt_writes': _counter_total(metrics, 'train.ckpt_writes'),
  }
  out['fault_tolerance'] = ft if any(ft.values()) else None

  # Data-service meters (lddl-data-server / network transport clients):
  # delivery volume, the re-serve/revoke churn dead consumers cause, and
  # the degraded-mode transitions. None when this process neither serves
  # nor pulls batches over the wire.
  serve = {
      'batches_served': _counter_total(metrics, 'serve.batches_served'),
      'reserves': _counter_total(metrics, 'serve.reserves'),
      'lease_claims': _counter_total(metrics, 'serve.lease_claims'),
      'lease_revokes': _counter_total(metrics, 'serve.lease_revokes'),
      'client_pulls': _counter_total(metrics, 'serve.client_pulls'),
      'fallbacks': _counter_total(metrics, 'serve.fallbacks'),
      'reattaches': _counter_total(metrics, 'serve.reattaches'),
      'clients': _gauge(metrics, 'serve.clients'),
      'backlog': _gauge(metrics, 'serve.backlog'),
  }
  instrumented = (serve['clients'] is not None or
                  serve['backlog'] is not None or
                  any(isinstance(v, int) and v for v in serve.values()))
  out['serve'] = serve if instrumented else None
  return out


# ---------------------------------------------------------------------------
# straggler scores


# Counter families whose windowed rate is a per-rank progress signal.
# Executor task completion and background write-back lead (the work-
# stealing consumer's signals); loader rows and train steps cover runs
# without a preprocess phase.
_SIGNAL_STEPS = 'steps_per_sec'


def rank_signals(window):
  """This rank's progress rates over its window: the straggler inputs.

  ``{'tasks_per_sec', 'writes_per_sec', 'rows_per_sec',
  'steps_per_sec'}`` — each None when that subsystem produced no events
  in the window, so the fleet comparison only weighs signals a rank
  actually runs.
  """
  merged, sec = _merged_delta(window)
  out = {'tasks_per_sec': None, 'writes_per_sec': None,
         'rows_per_sec': None, _SIGNAL_STEPS: None}
  if merged is None or sec <= 0:
    return out
  metrics = merged['metrics']
  tasks = sum(m['total'] for name, m in metrics.items()
              if m['kind'] == 'counter' and name.startswith('pipeline.') and
              name.endswith('.tasks'))
  if tasks:
    out['tasks_per_sec'] = tasks / sec
  writes = _counter_total(metrics, 'pipeline.pool.writes')
  if writes:
    out['writes_per_sec'] = writes / sec
  rows = _counter_total(metrics, 'loader.rows')
  if rows:
    out['rows_per_sec'] = rows / sec
  steps = _counter_total(metrics, 'train.steps')
  if steps:
    out[_SIGNAL_STEPS] = steps / sec
  return out


def straggler_scores(per_rank_signals):
  """Deterministic per-rank slowness scores vs the fleet median.

  ``per_rank_signals``: ``{rank: rank_signals()-dict}``. For every
  signal at least two ranks report, each rank scores
  ``median_rate / own_rate`` (> 1 means slower than the fleet median;
  a rank reporting zero progress on a signal others advance scores
  ``inf``). A rank's overall score is its worst signal. Pure arithmetic
  over the gathered rates — every rank computes the identical table.

  Returns ``{'scores': {rank: score}, 'signals': {rank: {signal:
  per-signal score}}, 'slowest': rank_or_None}``; ``slowest`` is only
  named when some rank scores > 1 (ties break to the lowest rank).
  """
  signal_names = set()
  for sig in per_rank_signals.values():
    signal_names.update(k for k, v in sig.items() if v is not None)
  per_signal = {}  # signal -> {rank: score}
  for name in sorted(signal_names):
    rates = {r: s.get(name) for r, s in per_rank_signals.items()
             if s.get(name) is not None}
    # A signal only one rank runs (e.g. only rank 0 trains) carries no
    # fleet comparison; require a quorum of two.
    if len(rates) < 2:
      continue
    ordered = sorted(rates.values())
    mid = len(ordered) // 2
    median = (ordered[mid] if len(ordered) % 2 else
              (ordered[mid - 1] + ordered[mid]) / 2.0)
    if median <= 0:
      continue
    per_signal[name] = {
        r: (median / rate if rate > 0 else math.inf)
        for r, rate in rates.items()
    }
  scores = {}
  for rank in per_rank_signals:
    mine = [tbl[rank] for tbl in per_signal.values() if rank in tbl]
    scores[rank] = max(mine) if mine else 1.0
  slowest = None
  flagged = [r for r in sorted(scores) if scores[r] > 1.0]
  if flagged:
    slowest = max(flagged, key=lambda r: (scores[r], -r))
  by_rank = {r: {name: tbl[r] for name, tbl in per_signal.items()
                 if r in tbl} for r in per_rank_signals}
  return {'scores': scores, 'signals': by_rank, 'slowest': slowest}


def straggler_over_comm(comm, window, telemetry=None):
  """Fleet straggler table over the run's own comm backend.

  Every rank contributes its windowed :func:`rank_signals`; the
  allgather rides the backend's normal collective stream, and each
  entry is tagged with the backend's collective sequence number (the
  same seq-keying trace alignment uses) so a consumer merging tables
  from different rounds can reject mismatched ones. All ranks compute
  the identical score table; the result is also exported into the
  registry as ``straggler.rank<R>.score`` gauges so the future
  cross-rank stealer (and the JSONL export) can consume it without
  re-gathering.
  """
  signals = rank_signals(window)
  seq = getattr(comm, 'collective_seq', None)
  gathered = comm.allgather_object(
      {'rank': comm.rank, 'seq': seq, 'signals': signals})
  seqs = {e['seq'] for e in gathered if e.get('seq') is not None}
  result = straggler_scores({e['rank']: e['signals'] for e in gathered})
  result['seq'] = max(seqs) if seqs else None
  if len(seqs) > 1:
    # Backends bump seq per collective, and this allgather IS one
    # collective all ranks issue together, so the tags agree by
    # construction; disagreement means a caller mixed backends/rounds.
    result['seq_mismatch'] = sorted(seqs)
  tele = telemetry if telemetry is not None else get_telemetry()
  if tele.enabled:
    for rank, score in result['scores'].items():
      if math.isfinite(score):
        tele.gauge(f'straggler.rank{rank}.score').set(score)
  return result


# ---------------------------------------------------------------------------
# the one-call status payload the monitor server serves


def live_status(window, rank=0, telemetry=None, include_metrics=True):
  """Everything the ``/snapshot`` endpoint serves, as one JSON-able dict.

  Samples the registry into ``window`` first (the poller's cadence IS
  the window cadence), then derives rates/verdict/goodput from the
  windowed delta and this rank's straggler signals from the same
  window. HBM gauges are refreshed from ``device.memory_stats()``
  immediately before the capture, so device-memory telemetry runs at
  exactly the scrape cadence — an unwatched process never polls the
  device. ``include_metrics=False`` drops the full cumulative dump for
  lightweight dashboards.
  """
  from .roofline import sample_hbm
  tele = telemetry if telemetry is not None else get_telemetry()
  hbm = sample_hbm(tele)
  lines = window.sample(telemetry=tele, rank=rank)
  status = {
      'rank': rank,
      'pid': os.getpid(),
      'unix_time': time.time(),
      'monotonic': time.monotonic(),
      'window_sec': window.window_sec(),
      'window_samples': len(window),
      'rates': stage_rates(window),
      'verdict': live_verdict(window),
      'signals': rank_signals(window),
  }
  status['hbm'] = hbm
  merged_cum = merge_metric_lines([lines]) if lines else {'metrics': {}}
  status['goodput'] = goodput_meters(merged_cum)
  from .ledger import get_ledger
  ledger = get_ledger()
  if ledger.enabled:
    # Raw per-boundary stream heads for the monitor's client-side
    # cross-rank comparison (compare_signals over every polled rank) —
    # the same payload divergence_over_comm allgathers in-run.
    status['ledger'] = ledger.signals()
  from .sentinel import sentinel_status
  sent = sentinel_status()
  if sent is not None:
    # Trigger counts + registered incident dirs for the monitor's
    # INCIDENT panel; absent entirely when LDDL_SENTINEL is off.
    status['sentinel'] = sent
  if include_metrics:
    status['metrics'] = lines
  return status

"""``lddl-monitor``: terminal dashboard over live monitor endpoints.

Attaches to a running job — either explicit ``--url`` endpoints or a
``--dir`` of ``monitor.rank*.json`` announce files (what each
``LDDL_MONITOR`` server writes into ``LDDL_MONITOR_DIR`` /
``LDDL_TELEMETRY_DIR``) — polls every rank's ``/snapshot``, and
repaints a plain-text dashboard (ANSI clear + home; deliberately no
curses): per-stage rates, the live bottleneck verdict, the fleet
straggler table (computed client-side from every rank's windowed
signals, same arithmetic the in-run aggregation uses), and goodput
meters. ``--once`` renders a single frame; ``--once --json`` emits the
full merged payload for scripting/CI.

Unix-socket endpoints (``unix:/path``) are reached through a raw
``http.client`` connection bound to ``AF_UNIX`` — no extra deps.
"""

import argparse
import glob
import http.client
import json
import os
import socket
import sys
import time
import urllib.request

from .ledger import compare_signals
from .live import straggler_scores


class _UnixHTTPConnection(http.client.HTTPConnection):

  def __init__(self, path, timeout):
    super().__init__('localhost', timeout=timeout)
    self._path = path

  def connect(self):
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(self.timeout)
    sock.connect(self._path)
    self.sock = sock


def _fetch_json(url, request_path, timeout=5.0):
  """GET ``<url><request_path>`` -> parsed JSON dict. ``url`` is either
  an ``http://host:port`` endpoint or ``unix:/path/to.sock``."""
  if url.startswith('unix:'):
    conn = _UnixHTTPConnection(url[len('unix:'):], timeout)
    try:
      conn.request('GET', request_path)
      resp = conn.getresponse()
      if resp.status != 200:
        raise RuntimeError(f'{url}{request_path} -> HTTP {resp.status}')
      return json.loads(resp.read().decode('utf-8'))
    finally:
      conn.close()
  with urllib.request.urlopen(url.rstrip('/') + request_path,
                              timeout=timeout) as resp:
    return json.loads(resp.read().decode('utf-8'))


def fetch_snapshot(url, timeout=5.0):
  """GET ``<url>/snapshot`` -> parsed JSON dict."""
  return _fetch_json(url, '/snapshot', timeout=timeout)


def fetch_profile(url, steps, timeout=5.0):
  """Arm the endpoint's profiler: GET ``<url>/profile?steps=N``."""
  return _fetch_json(url, f'/profile?steps={int(steps)}', timeout=timeout)


def _announced_dead(info):
  """True when an announce file names a pid we can *prove* died (same
  pid namespace + positive /proc probe — the comm beacons' discipline).
  Old-format announces without the identity fields are never flagged."""
  pid = info.get('pid')
  pidns = info.get('pidns')
  if not isinstance(pid, int) or not pidns:
    return False
  from ..comm.backend import FileBackend
  ours = FileBackend._pid_namespace()
  if not ours or pidns != ours:
    return False
  return FileBackend._pid_dead(pid, info.get('pid_starttime') or '')


def discover_announcements(directory):
  """Parsed announce files under ``directory`` (rank order), each with a
  ``dead`` flag from the pid probe."""
  paths = sorted(glob.glob(os.path.join(directory, 'monitor.rank*.json')))
  out = []
  for p in paths:
    try:
      with open(p) as f:
        info = json.load(f)
    except (OSError, ValueError):
      continue  # being rewritten or already torn down; next poll catches up
    if info.get('url'):
      info['dead'] = _announced_dead(info)
      out.append(info)
  return out


def discover_serve_announcements(directory):
  """Parsed ``serve.pid*.json`` data-server announces under
  ``directory``, each with a ``dead`` flag from the same positive-death
  pid probe the monitor announces use. Lets the dashboard list live
  ``lddl-data-server`` endpoints next to rank endpoints and fold a
  SIGKILLed server into the error list instead of connection noise."""
  from ..loader.service import discover_data_servers
  return discover_data_servers(directory)


def discover_endpoints(directory, include_dead=False):
  """Endpoint URLs from announce files under ``directory``, rank order.

  A SIGKILLed rank cannot remove its announce file; its pid probe proves
  it dead, and the stale endpoint is skipped (flagged upstream by
  :func:`discover_announcements`) instead of being polled into a
  timeout.
  """
  return [info['url'] for info in discover_announcements(directory)
          if include_dead or not info['dead']]


def poll_fleet(urls, timeout=5.0):
  """One round: every reachable rank's snapshot + the fleet view.

  Returns ``{'ranks': {rank: snapshot}, 'errors': {url: str},
  'straggler': straggler_scores(...), 'verdict': <rank verdicts>}``.
  The straggler table is recomputed here from each rank's windowed
  signals — identical arithmetic to the in-run
  :func:`~.live.straggler_over_comm` path, so dashboard and stealer
  agree.
  """
  ranks, errors = {}, {}
  for url in urls:
    try:
      snap = fetch_snapshot(url, timeout=timeout)
      ranks[snap.get('rank', len(ranks))] = snap
    except (OSError, RuntimeError, ValueError) as e:
      errors[url] = str(e)
  fleet = {
      'ranks': ranks,
      'errors': errors,
      'straggler': straggler_scores(
          {r: s.get('signals', {}) for r, s in ranks.items()})
      if len(ranks) > 1 else None,
      'verdicts': {r: s.get('verdict', {}).get('bottleneck', 'unknown')
                   for r, s in ranks.items()},
  }
  # Cross-rank determinism: compare the ledger stream heads every rank
  # exports in its snapshot — identical arithmetic to the in-run
  # divergence_over_comm path, so dashboard and run agree. None when no
  # rank runs with LDDL_LEDGER (the ledger key is absent).
  ledgers = {r: s.get('ledger') for r, s in ranks.items() if s.get('ledger')}
  fleet['determinism'] = (compare_signals(ledgers)
                          if len(ledgers) > 1 else None)
  # Sentinel triggers + incidents per rank (live_status only exports
  # the key when LDDL_SENTINEL is on). None when no rank runs armed.
  sentinels = {r: s.get('sentinel') for r, s in ranks.items()
               if s.get('sentinel')}
  fleet['sentinel'] = sentinels or None
  return fleet


def _fmt_rate(v):
  if v is None:
    return '-'
  if v >= 100:
    return f'{v:,.0f}'
  return f'{v:.2f}'


def render_frame(fleet, clear=True):
  """The plain-text dashboard for one poll round."""
  out = []
  if clear:
    out.append('\x1b[2J\x1b[H')
  out.append('lddl-monitor · %d rank(s) · %s' %
             (len(fleet['ranks']), time.strftime('%H:%M:%S')))
  for info in fleet.get('data_servers') or []:
    if not info.get('dead'):
      out.append(f'  data-server {info.get("url")} '
                 f'(pid {info.get("pid")})')
  for url, err in sorted(fleet['errors'].items()):
    out.append(f'  !! {url}: {err}')
  for rank in sorted(fleet['ranks']):
    snap = fleet['ranks'][rank]
    verdict = snap.get('verdict', {})
    out.append('')
    out.append(f'rank {rank} (pid {snap.get("pid")}) · window '
               f'{snap.get("window_sec", 0.0):.1f}s '
               f'({snap.get("window_samples", 0)} samples)')
    out.append(f'  verdict: {verdict.get("bottleneck", "unknown")}')
    if verdict.get('detail'):
      out.append(f'    {verdict["detail"]}')
    roof = verdict.get('roofline') or {}
    bound = roof.get('bound')
    if bound and not str(bound).startswith('unknown'):
      line = f'  roofline: {bound}'
      if roof.get('flops_per_sec'):
        line += f' · {roof["flops_per_sec"] / 1e12:.2f} TFLOP/s'
        if roof.get('flops_frac') is not None:
          line += f' ({roof["flops_frac"]:.1%} of peak)'
      if roof.get('bytes_per_sec'):
        line += f' · {roof["bytes_per_sec"] / 1e9:.1f} GB/s'
        if roof.get('bw_frac') is not None:
          line += f' ({roof["bw_frac"]:.1%} of peak)'
      if roof.get('arithmetic_intensity') is not None and \
          roof.get('machine_balance') is not None:
        line += (f' · AI {roof["arithmetic_intensity"]:.0f} vs balance '
                 f'{roof["machine_balance"]:.0f} FLOPs/byte')
      out.append(line)
      if roof.get('detail'):
        out.append(f'    {roof["detail"]}')
    hbm = snap.get('hbm')
    if hbm:
      line = (f'  hbm: {hbm.get("bytes_in_use", 0) / 2**30:.2f} GiB in '
              f'use · peak {hbm.get("peak_bytes_in_use", 0) / 2**30:.2f} '
              'GiB')
      if hbm.get('bytes_limit'):
        line += f' · limit {hbm["bytes_limit"] / 2**30:.2f} GiB'
      if hbm.get('headroom_frac') is not None:
        line += f' · headroom {hbm["headroom_frac"]:.1%}'
      out.append(line)
    rates = snap.get('rates', {})
    shown = sorted(n for n in rates if not n.endswith('.mean'))[:12]
    for name in shown:
      unit = '/s' if not name.endswith('.rate') else ' spans/s'
      out.append(f'  {name:<44s} {_fmt_rate(rates[name]):>12s}{unit}')
    good = snap.get('goodput', {})
    meters = []
    if good.get('padding_efficiency') is not None:
      meters.append(f'padding-eff {good["padding_efficiency"]:.1%}')
    if good.get('step_cache_hit_rate') is not None:
      meters.append(f'step-cache {good["step_cache_hit_rate"]:.1%}')
    if good.get('h2d_overlap_fraction') is not None:
      meters.append(f'h2d-overlap {good["h2d_overlap_fraction"]:.1%}')
    if good.get('attn_tile_skip_fraction') is not None:
      meters.append(f'attn-tiles-skipped {good["attn_tile_skip_fraction"]:.1%}')
    if good.get('mfu'):
      meters.append(f'mfu {good["mfu"]["mean"]:.1%}')
    if good.get('grad_norm'):
      meters.append(f'grad-norm {good["grad_norm"]["mean"]:.3g}')
    if good.get('device_live_batches'):
      meters.append(f'device-live {good["device_live_batches"]["mean"]:.1f}'
                    ' batches')
    for g in ('queue_depth', 'shm_slot_occupancy', 'ckpt_backlog'):
      if good.get(g):
        meters.append(f'{g} {good[g]["mean"]:.1f}')
    if meters:
      out.append('  goodput: ' + ' · '.join(meters))
    ft = good.get('fault_tolerance')
    if ft:
      parts = [f'{k.replace("_", "-")} {v}' for k, v in ft.items() if v]
      out.append('  fault-tolerance: ' + ' · '.join(parts))
    srv = verdict.get('serve')
    if srv:
      line = '  serve:'
      if srv.get('clients') is not None:
        line += f' {srv["clients"]["mean"]:.0f} client(s)'
      if srv.get('batches_per_sec') is not None:
        line += f' · {_fmt_rate(srv["batches_per_sec"])} batches/s'
      for label, key in (('re-serves', 'reserves'),
                         ('lease-revokes', 'lease_revokes'),
                         ('fallbacks', 'fallbacks'),
                         ('re-attaches', 'reattaches')):
        if srv.get(key):
          line += f' · {label} {srv[key]}'
      if srv.get('backlog') is not None:
        line += f' · backlog {srv["backlog"]["mean"]:.1f}'
      out.append(line)
  strag = fleet.get('straggler')
  if strag:
    out.append('')
    out.append('straggler scores (fleet-median / own rate; >1 = slow):')
    for rank in sorted(strag['scores']):
      mark = '  <-- slowest' if rank == strag['slowest'] else ''
      out.append(f'  rank {rank}: {strag["scores"][rank]:.3f}{mark}')
  det = fleet.get('determinism')
  if det and det.get('status') == 'diverged':
    out.append('')
    out.append('!! DIVERGED — ranks no longer byte-identical:')
    first = det.get('first') or {}
    line = f'  first divergence: boundary {first.get("boundary", "?")}'
    if first.get('key'):
      line += ' at ' + ', '.join(str(k) for k in first['key'])
    digests = first.get('digests') or {}
    if digests:
      line += ' — rank ' + ' vs rank '.join(
          f'{r} {d}' for r, d in sorted(digests.items()))
    if not first.get('key'):
      line += ' (first differing batch predates the retained window; ' \
              'run lddl-audit on the ledgers for the exact coordinate)'
    out.append(line)
    for b, entry in sorted((det.get('boundaries') or {}).items()):
      out.append(f'  {b}: {entry.get("status")} · counts '
                 f'{entry.get("counts")}')
  elif det and det.get('status') == 'ok':
    out.append('')
    out.append('determinism: ok (replicated ledger streams agree)')
  fired = {r: s for r, s in (fleet.get('sentinel') or {}).items()
           if s.get('triggers') or s.get('incidents')}
  if fired:
    out.append('')
    out.append('!! INCIDENT — sentinel trigger(s):')
    for rank in sorted(fired):
      s = fired[rank]
      last = s.get('last') or {}
      line = f'  rank {rank}: {s.get("triggers", 0)} trigger(s)'
      if last:
        line += (f' · last {last.get("detector", "?")} at step '
                 f'{last.get("step")}')
      out.append(line)
      if last.get('reason'):
        out.append(f'    {last["reason"]}')
      for inc in (s.get('incidents') or [])[-3:]:
        out.append(f'    incident {inc.get("dir")} — triage with: '
                   f'lddl-incident show {inc.get("dir")}')
  return '\n'.join(out)


def attach_args(parser):
  parser.add_argument('--url', action='append', default=[],
                      help='monitor endpoint (http://host:port or '
                           'unix:/path); repeatable')
  parser.add_argument('--dir', default=None,
                      help='directory of monitor.rank*.json announce files '
                           '(LDDL_MONITOR_DIR / LDDL_TELEMETRY_DIR)')
  parser.add_argument('--interval', type=float, default=2.0,
                      help='seconds between repaints (default 2)')
  parser.add_argument('--timeout', type=float, default=5.0,
                      help='per-endpoint HTTP timeout')
  parser.add_argument('--once', action='store_true',
                      help='render a single frame and exit')
  parser.add_argument('--json', action='store_true',
                      help='with --once: emit the merged fleet payload '
                           'as JSON instead of the dashboard')
  parser.add_argument('--profile', type=int, default=None, metavar='STEPS',
                      help='arm every live endpoint\'s jax.profiler for '
                           'the next STEPS train steps and exit')
  return parser


def main(args=None):
  parser = attach_args(argparse.ArgumentParser(
      description=__doc__,
      formatter_class=argparse.RawDescriptionHelpFormatter))
  args = parser.parse_args(args)
  if not args.url and not args.dir:
    print('lddl-monitor: provide --url and/or --dir', file=sys.stderr)
    return 2

  def _endpoints():
    """(live urls, {stale url: why}) — explicit --url endpoints are
    trusted; discovered ones are pid-probed and provably-dead announcers
    are reported instead of polled into a timeout."""
    urls = list(args.url)
    dead = {}
    if args.dir:
      for info in discover_announcements(args.dir):
        if info['dead']:
          dead[info['url']] = (f'announcer pid {info.get("pid")} is dead '
                               '(stale announce file); skipped')
        elif info['url'] not in urls:
          urls.append(info['url'])
      # Data-server announces: live ones are listed in the frame header
      # (their own monitor endpoint, if any, rides the monitor.rank*
      # announce above); a SIGKILLed server's stale announce becomes a
      # fleet error instead of every client's connection noise.
      for info in discover_serve_announcements(args.dir):
        if info['dead']:
          dead[f'data-server {info["url"]}'] = (
              f'data server pid {info.get("pid")} is dead '
              '(stale serve announce); clients will degrade to their '
              'local loaders')
    return urls, dead

  if args.profile is not None:
    if args.profile < 1:
      print('lddl-monitor: --profile wants a positive step count',
            file=sys.stderr)
      return 2
    urls, dead = _endpoints()
    for url, why in sorted(dead.items()):
      print(f'lddl-monitor: {url}: {why}', file=sys.stderr)
    if not urls:
      print('lddl-monitor: no live endpoints to profile', file=sys.stderr)
      return 2
    rc = 0
    for url in urls:
      try:
        resp = fetch_profile(url, args.profile, timeout=args.timeout)
        print(f'{url}: armed {resp.get("armed_steps")} step(s) -> '
              f'{resp.get("trace_dir")}')
      except (OSError, RuntimeError, ValueError) as e:
        print(f'{url}: {e}', file=sys.stderr)
        rc = 1
    return rc

  while True:
    urls, dead = _endpoints()
    if not urls and not dead:
      print(f'lddl-monitor: no endpoints found '
            f'(no monitor.rank*.json in {args.dir})', file=sys.stderr)
      return 2
    fleet = poll_fleet(urls, timeout=args.timeout)
    fleet['errors'].update(dead)
    if args.dir:
      fleet['data_servers'] = discover_serve_announcements(args.dir)
    if args.once:
      if args.json:
        print(json.dumps(fleet, default=str, indent=2))
      else:
        print(render_frame(fleet, clear=False))
      return 0 if fleet['ranks'] else 1
    print(render_frame(fleet, clear=True), flush=True)
    time.sleep(args.interval)


if __name__ == '__main__':
  sys.exit(main())

"""``lddl-audit``: cross-run / cross-rank determinism auditing.

Consumes the per-rank ``ledger.rank<R>.jsonl`` files the determinism
ledger (:mod:`.ledger`, env ``LDDL_LEDGER``) streams at every pipeline
boundary and turns the repo's byte-identity contracts into a checkable
verdict:

  - ``lddl-audit diff A B`` — align two runs (directories) or two rank
    files record-by-record and bisect the **first divergent
    coordinate** per boundary, reported in pipeline lineage order
    (shard → collate → serve → device → step) so the earliest boundary
    that broke names the culprit stage;
  - ``lddl-audit verify RUN REF`` — verify a resumed / resharded /
    degraded-fallback run against its parent (reference) run's ledger:
    every coordinate both runs recorded must carry the same digest
    (the child typically covers a subset — it resumed mid-stream — so
    coverage is reported but only *conflicts* fail);
  - ``lddl-audit show DIR`` — per-boundary stream summary of one run.

Alignment is key-based (:func:`~.ledger.record_key`: ``(epoch,
index)`` for collates, ``gi`` for service frames, ``step`` for train
records, shard ``path``), so restarts that re-record a coordinate are
handled — and a coordinate recorded twice *within one run* with two
different digests (a replayed batch that came back different) is
itself a divergence. Mixed-algorithm ledgers refuse to compare:
fingerprints are only meaningful under one hash.

Exit codes (CI contract, same shape as ``telemetry-report``):
``0`` consistent, ``1`` divergence found, ``2`` usage / no input.
"""

import argparse
import glob
import json
import os
import re
import sys

from .ledger import KEY_FIELDS, record_key

#: Pipeline lineage order: the earliest diverging boundary in this
#: order names the stage that introduced the divergence (everything
#: downstream inherits it).
BOUNDARY_ORDER = ('shard', 'collate', 'serve.tx', 'serve.rx', 'device',
                  'step')

#: Boundaries whose records form an unordered set (keyed, written by
#: many pool workers) rather than a sequenced stream.
_UNORDERED = ('shard',)


def _boundary_sort(b):
  try:
    return (BOUNDARY_ORDER.index(b), b)
  except ValueError:
    return (len(BOUNDARY_ORDER), b)


def load_ledger_file(path):
  """Parse one ledger JSONL file -> ``{'meta': [...], 'records': [...],
  'bad_lines': N}``. Torn lines (a process SIGKILLed mid-append) are
  tolerated and counted, never fatal — the ledger is exactly the
  artifact that must survive crashes."""
  meta, records, bad = [], [], 0
  with open(path) as f:
    for line in f:
      line = line.strip()
      if not line:
        continue
      try:
        d = json.loads(line)
      except ValueError:
        bad += 1
        continue
      if 'boundary' in d:
        records.append(d)
      elif d.get('kind') == 'meta':
        meta.append(d)
  return {'meta': meta, 'records': records, 'bad_lines': bad}


def load_run(path, rank=None):
  """Load a run's ledgers: ``path`` is a directory of
  ``ledger.rank*.jsonl`` files or a single file. Returns
  ``{rank: parsed-file-dict}``."""
  if os.path.isdir(path):
    pattern = (f'ledger.rank{rank}.jsonl' if rank is not None
               else 'ledger.rank*.jsonl')
    paths = sorted(glob.glob(os.path.join(path, pattern)))
    if not paths:
      raise FileNotFoundError(
          f'no {pattern} under {path} '
          '(run with LDDL_LEDGER=1 and LDDL_TELEMETRY_DIR set)')
    out = {}
    for p in paths:
      m = re.search(r'ledger\.rank(\d+)\.jsonl$', p)
      out[int(m.group(1)) if m else len(out)] = load_ledger_file(p)
    return out
  if not os.path.exists(path):
    raise FileNotFoundError(f'no such ledger: {path}')
  parsed = load_ledger_file(path)
  r = parsed['meta'][0].get('rank', 0) if parsed['meta'] else 0
  return {r: parsed}


def run_algo(run):
  """The (single) digest algorithm a run's meta lines declare, or None
  when no meta line survived."""
  algos = {m.get('algo') for parsed in run.values()
           for m in parsed['meta'] if m.get('algo')}
  if len(algos) > 1:
    raise ValueError(f'mixed digest algorithms in one run: {sorted(algos)}')
  return algos.pop() if algos else None


def index_records(parsed):
  """Key-indexed view of one rank's records:
  ``{boundary: {key: record}}`` plus intra-run conflicts (one key, two
  digests — a replay that came back different)."""
  by_boundary, conflicts = {}, []
  seq = {}
  for rec in parsed['records']:
    b = rec['boundary']
    key = record_key(rec)
    if key is None:
      seq[b] = seq.get(b, 0) + 1
      key = (('#seq', seq[b]),)
    table = by_boundary.setdefault(b, {})
    prev = table.get(key)
    if prev is not None and prev['digest'] != rec['digest']:
      conflicts.append({'boundary': b, 'key': _key_dict(key),
                        'digests': [prev['digest'], rec['digest']]})
    table[key] = rec
  return by_boundary, conflicts


def _key_dict(key):
  return {f: v for f, v in key}


def _fmt_key(key):
  return '(' + ', '.join(f'{f}={v}' for f, v in key) + ')'


def parse_key(spec):
  """Parse a lineage-key spec string into the canonical key tuple
  :func:`~.ledger.record_key` produces.

  The grammar is the rendered key form without the parens:
  ``"epoch=0,index=3"`` (a collate coordinate), ``"epoch=1,gi=7"`` (a
  serve frame), ``"step=42"`` (a train step), ``"path=shard-00.parquet"``
  (a shard). Field order is normalized to :data:`~.ledger.KEY_FIELDS`;
  every field but ``path`` is coerced to int. This is the shared
  coordinate grammar of ``lddl-audit show --key`` and ``lddl-replay``.
  """
  fields = {}
  for part in str(spec).split(','):
    part = part.strip()
    if not part:
      continue
    if '=' not in part:
      raise ValueError(f'bad key spec {spec!r}: expected field=value, '
                       f'got {part!r}')
    f, v = part.split('=', 1)
    f = f.strip()
    if f not in KEY_FIELDS:
      raise ValueError(f'bad key spec {spec!r}: unknown field {f!r} '
                       f'(known: {", ".join(KEY_FIELDS)})')
    fields[f] = v.strip() if f == 'path' else int(v)
  if not fields:
    raise ValueError(f'bad key spec {spec!r}: no fields')
  return tuple((f, fields[f]) for f in KEY_FIELDS if f in fields)


def lookup_records(run, key, boundary=None):
  """All records in ``run`` (a :func:`load_run` dict) whose lineage key
  equals ``key``, as ``(rank, record)`` pairs in file order —
  ``lddl-audit show --key``'s and replay's coordinate-lookup path.
  ``boundary`` restricts to one boundary name."""
  out = []
  for rank in sorted(run):
    for rec in run[rank]['records']:
      if boundary is not None and rec['boundary'] != boundary:
        continue
      if record_key(rec) == key:
        out.append((rank, rec))
  return out


def diff_indexed(a, b, boundaries=None):
  """First divergence per boundary between two key-indexed views.

  Returns a list of finding dicts, pipeline-lineage ordered. A finding
  is either a digest mismatch at a common key (``kind='divergence'``,
  with the *first* such key in key order) or, for sequenced
  boundaries, a note that one side stops early (``kind='truncated'`` —
  informational, not a failure: a shorter run is not a divergent one).
  """
  findings = []
  names = boundaries or sorted(set(a) | set(b), key=_boundary_sort)
  for bd in names:
    ta, tb = a.get(bd, {}), b.get(bd, {})
    if not ta or not tb:
      continue
    common = sorted(set(ta) & set(tb))
    mismatches = [k for k in common
                  if ta[k]['digest'] != tb[k]['digest']]
    if mismatches:
      k = mismatches[0]
      findings.append({
          'kind': 'divergence', 'boundary': bd, 'key': _key_dict(k),
          'key_str': _fmt_key(k),
          'digest_a': ta[k]['digest'], 'digest_b': tb[k]['digest'],
          'mismatched_keys': len(mismatches), 'common_keys': len(common),
      })
    elif bd not in _UNORDERED and len(ta) != len(tb):
      findings.append({
          'kind': 'truncated', 'boundary': bd,
          'records_a': len(ta), 'records_b': len(tb),
          'common_keys': len(common),
      })
  findings.sort(key=lambda f: _boundary_sort(f['boundary']))
  return findings


def wire_mismatches(run):
  """Intra-run wire-integrity check: the data service fingerprints every
  frame twice — ``serve.tx`` on the server pre-send, ``serve.rx`` on the
  client post-receive — so a frame damaged in between (wire fault,
  corrupted buffer) shows as one coordinate carrying two digests inside
  a single run, no reference run needed. Records are pooled across the
  run's rank files: server and client are usually different processes
  of the same run."""
  tx, rx = {}, {}
  for parsed in run.values():
    indexed, _ = index_records(parsed)
    for key, rec in indexed.get('serve.tx', {}).items():
      tx.setdefault(key, rec['digest'])
    for key, rec in indexed.get('serve.rx', {}).items():
      rx.setdefault(key, rec['digest'])
  return [{'kind': 'wire', 'boundary': 'serve.rx', 'key': _key_dict(k),
           'key_str': _fmt_key(k), 'digest_tx': tx[k],
           'digest_rx': rx[k]}
          for k in sorted(set(tx) & set(rx)) if tx[k] != rx[k]]


def _align_single_rank(run_a, run_b):
  """When two single-rank inputs carry different rank ids, the caller
  is comparing two *rank files* (the cross-rank audit) or a recovered
  rank against a differently-numbered parent; align them positionally
  under the first input's rank id."""
  if not (set(run_a) & set(run_b)) and len(run_a) == 1 and len(run_b) == 1:
    return {next(iter(run_a)): next(iter(run_b.values()))}
  return run_b


def audit_diff(run_a, run_b, boundaries=None):
  """Diff two runs rank-by-rank. Returns
  ``{'ranks': {rank: findings}, 'conflicts': [...], 'wire': [...],
  'divergent': bool, 'first': finding|None}`` where ``first`` is the
  earliest divergence in pipeline lineage order across all compared
  ranks."""
  try:
    alg_a, alg_b = run_algo(run_a), run_algo(run_b)
  except ValueError as e:
    raise ValueError(str(e))
  if alg_a and alg_b and alg_a != alg_b:
    raise ValueError(
        f'cannot compare ledgers hashed with different algorithms: '
        f'{alg_a} vs {alg_b}')
  run_b = _align_single_rank(run_a, run_b)
  out = {'ranks': {}, 'conflicts': [], 'wire': [], 'divergent': False,
         'first': None}
  out['wire'] = [
      dict(m, run=side)
      for side, run in (('a', run_a), ('b', run_b))
      for m in wire_mismatches(run)
  ]
  for rank in sorted(set(run_a) & set(run_b)):
    ia, ca = index_records(run_a[rank])
    ib, cb = index_records(run_b[rank])
    out['conflicts'].extend(
        dict(c, rank=rank, run=side)
        for side, cs in (('a', ca), ('b', cb)) for c in cs)
    findings = diff_indexed(ia, ib, boundaries)
    out['ranks'][rank] = findings
    for f in findings:
      if f['kind'] != 'divergence':
        continue
      out['divergent'] = True
      if (out['first'] is None or
          _boundary_sort(f['boundary']) <
          _boundary_sort(out['first']['boundary'])):
        out['first'] = dict(f, rank=rank)
  if out['conflicts'] or out['wire']:
    out['divergent'] = True
  if out['first'] is None and out['wire']:
    out['first'] = dict(out['wire'][0], rank=None,
                        digest_a=out['wire'][0]['digest_tx'],
                        digest_b=out['wire'][0]['digest_rx'])
  return out


def audit_verify(run, reference, boundaries=None):
  """Verify a recovered run against its reference: every coordinate
  both runs recorded must agree. Subset coverage is normal (the child
  resumed mid-stream); only conflicting digests fail. Returns the
  :func:`audit_diff` dict plus per-rank coverage counts."""
  reference = _align_single_rank(run, reference)
  result = audit_diff(run, reference, boundaries)
  coverage = {}
  for rank in sorted(set(run) & set(reference)):
    ia, _ = index_records(run[rank])
    ib, _ = index_records(reference[rank])
    cov = {}
    for bd in sorted(set(ia) | set(ib), key=_boundary_sort):
      ka, kb = set(ia.get(bd, {})), set(ib.get(bd, {}))
      cov[bd] = {'common': len(ka & kb), 'run_only': len(ka - kb),
                 'reference_only': len(kb - ka)}
    coverage[rank] = cov
  result['coverage'] = coverage
  # Truncation findings are expected on the verify path (the child is
  # shorter or longer than its parent by construction); only real
  # divergences and intra-run conflicts fail.
  result['divergent'] = (bool(result['conflicts']) or
                         bool(result['wire']) or any(
      f['kind'] == 'divergence'
      for fs in result['ranks'].values() for f in fs))
  return result


# ---------------------------------------------------------------------------
# CLI


def _render_findings(result, label_a='A', label_b='B'):
  lines = []
  for rank in sorted(result['ranks']):
    for f in result['ranks'][rank]:
      if f['kind'] == 'divergence':
        lines.append(
            f'rank {rank} · {f["boundary"]}: DIVERGED at {f["key_str"]} '
            f'— {label_a}={f["digest_a"]} {label_b}={f["digest_b"]} '
            f'({f["mismatched_keys"]}/{f["common_keys"]} keys differ)')
      else:
        lines.append(
            f'rank {rank} · {f["boundary"]}: lengths differ '
            f'({label_a}={f["records_a"]} {label_b}={f["records_b"]} '
            f'records; {f["common_keys"]} common keys all agree)')
  for c in result['conflicts']:
    lines.append(
        f'run {c["run"]} rank {c["rank"]} · {c["boundary"]}: intra-run '
        f'conflict at {_fmt_key(tuple(c["key"].items()))} — replayed '
        f'coordinate produced {c["digests"][0]} then {c["digests"][1]}')
  for w in result.get('wire', ()):
    lines.append(
        f'run {w["run"]} · wire: frame damaged in flight at '
        f'{w["key_str"]} — serve.tx={w["digest_tx"]} '
        f'serve.rx={w["digest_rx"]}')
  if result['first']:
    f = result['first']
    where = (f'on rank {f["rank"]}' if f.get('rank') is not None
             else 'on the wire')
    lines.append(
        f'first divergence (pipeline order): {f["boundary"]} '
        f'{f["key_str"]} {where} — everything downstream '
        'inherits it')
  return lines


def _cmd_diff(args, verify=False):
  try:
    run_a = load_run(args.a, rank=args.rank)
    run_b = load_run(args.b, rank=args.rank)
    result = (audit_verify if verify else audit_diff)(
        run_a, run_b, args.boundary or None)
  except (FileNotFoundError, ValueError) as e:
    print(f'lddl-audit: {e}', file=sys.stderr)
    return 2
  if not result['ranks'] and not result['wire']:
    print(f'lddl-audit: no common ranks between {args.a} ({sorted(run_a)}) '
          f'and {args.b} ({sorted(run_b)})', file=sys.stderr)
    return 2
  if args.as_json:
    print(json.dumps(result, indent=2, default=str))
  else:
    labels = (('run', 'reference') if verify else ('A', 'B'))
    for line in _render_findings(result, *labels):
      print(line)
    if verify:
      for rank, cov in sorted(result['coverage'].items()):
        parts = []
        for bd, c in cov.items():
          s = f'{bd}: {c["common"]} common'
          extra = [f'{c[k]} {label}' for k, label in
                   (('run_only', 'run-only'),
                    ('reference_only', 'ref-only')) if c[k]]
          parts.append(s + (f' ({", ".join(extra)})' if extra else ''))
        print(f'rank {rank} coverage: ' + '; '.join(parts))
    if not result['divergent']:
      print('lddl-audit: ledgers consistent '
            f'({len(result["ranks"])} rank(s) compared)')
  return 1 if result['divergent'] else 0


def _cmd_show(args):
  try:
    run = load_run(args.dir, rank=args.rank)
  except FileNotFoundError as e:
    print(f'lddl-audit: {e}', file=sys.stderr)
    return 2
  if getattr(args, 'key', None):
    # Single-coordinate pull: the replay lookup path on the CLI. Exit 0
    # with the matching lines, 1 when the coordinate was never recorded.
    try:
      key = parse_key(args.key)
    except ValueError as e:
      print(f'lddl-audit: {e}', file=sys.stderr)
      return 2
    hits = lookup_records(run, key, boundary=args.boundary or None)
    for rank, rec in hits:
      print(json.dumps(dict(rec, rank=rank), default=str))
    if not hits:
      print(f'lddl-audit: no record at {_fmt_key(key)} in {args.dir}',
            file=sys.stderr)
      return 1
    return 0
  for rank, parsed in sorted(run.items()):
    indexed, conflicts = index_records(parsed)
    algo = parsed['meta'][0].get('algo') if parsed['meta'] else '?'
    print(f'rank {rank} · {len(parsed["records"])} records · algo {algo}'
          + (f' · {parsed["bad_lines"]} torn line(s) tolerated'
             if parsed['bad_lines'] else ''))
    for bd in sorted(indexed, key=_boundary_sort):
      table = indexed[bd]
      tail = [r for r in parsed['records'] if r['boundary'] == bd][-1]
      print(f'  {bd}: {len(table)} coordinate(s), rolling '
            f'{tail.get("rolling", "?")}')
    for c in conflicts:
      print(f'  !! intra-run conflict in {c["boundary"]} at '
            f'{_fmt_key(tuple(c["key"].items()))}: {c["digests"]}')
  for w in wire_mismatches(run):
    print(f'!! wire mismatch at {w["key_str"]}: '
          f'serve.tx {w["digest_tx"]} != serve.rx {w["digest_rx"]}')
  return 0


def attach_args(parser):
  sub = parser.add_subparsers(dest='command')
  for name, doc in (('diff', 'first divergent coordinate between two '
                             'runs (or two rank files)'),
                    ('verify', 'verify a recovered run against its '
                               'reference run')):
    p = sub.add_parser(name, help=doc)
    p.add_argument('a', metavar='RUN' if name == 'verify' else 'A',
                   help='ledger directory or ledger.rank<R>.jsonl file')
    p.add_argument('b', metavar='REFERENCE' if name == 'verify' else 'B',
                   help='ledger directory or ledger.rank<R>.jsonl file')
    p.add_argument('--rank', type=int, default=None,
                   help='compare only this rank')
    p.add_argument('--boundary', action='append', default=[],
                   help='restrict to a boundary (repeatable)')
    p.add_argument('--json', action='store_true', dest='as_json',
                   help='emit the full result as JSON')
  p = sub.add_parser('show', help='per-boundary summary of one run')
  p.add_argument('dir', help='ledger directory or file')
  p.add_argument('--rank', type=int, default=None)
  p.add_argument('--key', default=None, metavar='LINEAGE_KEY',
                 help="pull one coordinate's record lines instead of "
                      "the summary (e.g. 'epoch=0,index=3', 'step=42')")
  p.add_argument('--boundary', default=None,
                 help='with --key: restrict the lookup to one boundary')
  return parser


def main(argv=None):
  parser = attach_args(argparse.ArgumentParser(
      prog='lddl-audit',
      description='determinism-ledger auditing: diff runs, verify '
                  'recovery paths, bisect the first divergent batch',
      formatter_class=argparse.RawDescriptionHelpFormatter))
  args = parser.parse_args(argv)
  if args.command == 'diff':
    return _cmd_diff(args, verify=False)
  if args.command == 'verify':
    return _cmd_diff(args, verify=True)
  if args.command == 'show':
    return _cmd_show(args)
  parser.print_usage(sys.stderr)
  return 2


if __name__ == '__main__':
  sys.exit(main())

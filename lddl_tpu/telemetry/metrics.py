"""Low-overhead metrics core: counters, gauges, histograms, spans.

Design constraints, in priority order:

1. **Disabled must cost ~nothing.** The loader row stream and the
   collate run millions of events per epoch; with telemetry off
   (default) every metric handle is a shared immutable singleton whose
   methods are empty — one dynamic dispatch per event, no lock, no
   allocation (``tests/test_telemetry.py`` asserts the allocation-free
   property directly). Instrument sites fetch handles *once* per
   stream/loop and call methods on the cached handle.
2. **Enabled stays cheap.** Per-event updates are plain attribute
   writes (GIL-consistent; metric objects are process-local and the
   export path snapshots, never mutates). Histograms keep count / sum /
   min / max plus power-of-two log buckets — O(1) per observation, no
   sample retention — enough for rate, mean, and coarse tail
   percentiles in the report.
3. **Multi-process friendly.** Worker processes inherit
   ``LDDL_TELEMETRY`` and accumulate into their own registry; each
   process exports its own JSONL and the report merges (histograms and
   counters merge exactly; gauges merge as last/min/max).

The process-global registry is resolved lazily from ``LDDL_TELEMETRY``
(truthy: ``1``/``true``/``on``) and can be flipped programmatically via
:func:`enable` / :func:`disable` — handles are fetched per
stream/iterator, so a flip takes effect for everything built after it.
"""

import json
import math
import os
import tempfile
import threading
import time


class _NoopTimer:
  """Reusable no-op context manager (one shared instance, never mutated)."""

  __slots__ = ()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    return False


class _NoopMetric:
  """Counter/gauge/histogram stand-in whose every method is empty."""

  __slots__ = ()

  def add(self, n=1):
    pass

  def set(self, value):
    pass

  def observe(self, value):
    pass

  def time(self):
    return _NOOP_TIMER


_NOOP_TIMER = _NoopTimer()
_NOOP_METRIC = _NoopMetric()


class NoopTelemetry:
  """The disabled registry: hands out the shared no-op singletons."""

  __slots__ = ()
  enabled = False

  def counter(self, name):
    return _NOOP_METRIC

  def gauge(self, name):
    return _NOOP_METRIC

  def histogram(self, name):
    return _NOOP_METRIC

  def span(self, name):
    return _NOOP_TIMER

  def snapshot_lines(self, rank=0):
    return []

  def write_jsonl(self, path, rank=0):
    return None


NOOP = NoopTelemetry()


class Counter:
  """Monotonic event/volume counter."""

  __slots__ = ('total',)

  def __init__(self):
    self.total = 0

  def add(self, n=1):
    self.total += n

  def to_dict(self):
    return {'total': self.total}


class Gauge:
  """Last-value metric with min/max/sum/count for cross-rank merging."""

  __slots__ = ('value', 'min', 'max', 'sum', 'count')

  def __init__(self):
    self.value = None
    self.min = math.inf
    self.max = -math.inf
    self.sum = 0.0
    self.count = 0

  def set(self, value):
    v = float(value)
    self.value = v
    if v < self.min:
      self.min = v
    if v > self.max:
      self.max = v
    self.sum += v
    self.count += 1

  def to_dict(self):
    if self.count == 0:
      return {'value': None, 'count': 0}
    return {'value': self.value, 'min': self.min, 'max': self.max,
            'mean': self.sum / self.count, 'count': self.count}


class _SpanTimer:
  """Context manager that observes its monotonic wall time into ``hist``."""

  __slots__ = ('_hist', '_t0')

  def __init__(self, hist):
    self._hist = hist
    self._t0 = 0.0

  def __enter__(self):
    self._t0 = time.monotonic()
    return self

  def __exit__(self, *exc):
    self._hist.observe(time.monotonic() - self._t0)
    return False


class Histogram:
  """count/sum/min/max + power-of-two log buckets.

  Bucket ``e`` counts observations in ``[2**e, 2**(e+1))`` (e.g. for
  seconds, bucket -10 is ~1-2 ms). Exact zero / negative values land in
  a dedicated ``zero`` bucket so timing jitter can't produce a math
  domain error. Buckets merge across ranks by key-wise addition, so the
  merged percentile estimate is as good as any single rank's.
  """

  __slots__ = ('count', 'sum', 'min', 'max', 'buckets')

  def __init__(self):
    self.count = 0
    self.sum = 0.0
    self.min = math.inf
    self.max = -math.inf
    self.buckets = {}

  def observe(self, value):
    v = float(value)
    self.count += 1
    self.sum += v
    if v < self.min:
      self.min = v
    if v > self.max:
      self.max = v
    e = math.frexp(v)[1] - 1 if v > 0.0 else 'zero'
    b = self.buckets
    b[e] = b.get(e, 0) + 1

  def time(self):
    """A fresh span context manager feeding this histogram."""
    return _SpanTimer(self)

  def percentile(self, q):
    """Upper-bound estimate of the ``q``-quantile (0..1) from buckets."""
    if self.count == 0:
      return None
    target = q * self.count
    seen = 0
    numeric = sorted(k for k in self.buckets if k != 'zero')
    if 'zero' in self.buckets:
      seen += self.buckets['zero']
      if seen >= target:
        return 0.0
    for e in numeric:
      seen += self.buckets[e]
      if seen >= target:
        # The bucket upper bound can exceed every observed value (a
        # single 1.1s observation lands in [1, 2) but max is 1.1), so
        # never report a quantile above the observed max.
        return min(float(2.0 ** (e + 1)), self.max)
    return self.max

  def to_dict(self):
    if self.count == 0:
      return {'count': 0, 'sum': 0.0, 'buckets': {}}
    return {'count': self.count, 'sum': self.sum, 'min': self.min,
            'max': self.max,
            'buckets': {str(k): v for k, v in self.buckets.items()}}


_KINDS = {'counter': Counter, 'gauge': Gauge, 'histogram': Histogram}


class Telemetry:
  """An enabled metric registry (one per process)."""

  enabled = True

  def __init__(self):
    self._metrics = {}  # name -> (kind, metric)

  def _get(self, kind, name):
    entry = self._metrics.get(name)
    if entry is None:
      entry = (kind, _KINDS[kind]())
      self._metrics[name] = entry
    elif entry[0] != kind:
      raise ValueError(
          f'metric {name!r} already registered as {entry[0]}, not {kind}')
    return entry[1]

  def counter(self, name):
    return self._get('counter', name)

  def gauge(self, name):
    return self._get('gauge', name)

  def histogram(self, name):
    return self._get('histogram', name)

  def span(self, name):
    """Context manager timing one occurrence into histogram ``name``."""
    return self._get('histogram', name).time()

  def snapshot_lines(self, rank=0):
    """One JSON-able dict per metric (the JSONL wire format)."""
    # unix_time and monotonic are sampled together: the pair anchors
    # this process's monotonic clock on the unix timeline so trace and
    # metric snapshots from different ranks can be cross-aligned.
    lines = [{'kind': 'meta', 'rank': rank, 'pid': os.getpid(),
              'unix_time': time.time(), 'monotonic': time.monotonic()}]
    for name in sorted(self._metrics):
      kind, metric = self._metrics[name]
      line = {'kind': kind, 'rank': rank, 'name': name}
      line.update(metric.to_dict())
      lines.append(line)
    return lines

  def write_jsonl(self, path, rank=0):
    """Atomically write this process's snapshot as JSONL to ``path``."""
    payload = '\n'.join(
        json.dumps(line) for line in self.snapshot_lines(rank)) + '\n'
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    # mkstemp (not a pid-suffixed name): two threads of one process
    # exporting concurrently must not clobber each other's tmp file.
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + '.tmp.')
    with os.fdopen(fd, 'w') as f:
      f.write(payload)
      # fsync before the rename: os.replace is atomic for the *name*,
      # but a machine crash between rename and writeback can land the
      # new name on truncated content. Durable-then-visible instead.
      f.flush()
      os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def rank_file_name(directory, rank):
  """Canonical per-rank export path (what ``telemetry-report`` globs)."""
  return os.path.join(directory, f'telemetry.rank{rank}.jsonl')


def diff_snapshot_lines(old, new):
  """Windowed delta between two :meth:`Telemetry.snapshot_lines` captures.

  Returns lines in the same wire format (so they feed straight into
  :func:`~lddl_tpu.telemetry.report.merge_metric_lines` and the verdict
  logic), but with cumulative kinds reduced to the window:

    - the meta line carries ``window_sec`` — the *monotonic* distance
      between the two captures, so rates derived from the delta never
      depend on wall clock;
    - counters subtract (``total`` = events inside the window);
    - histograms subtract count/sum/buckets; min/max are not windowable
      from cumulative state, so the new capture's values pass through as
      a conservative envelope;
    - gauges pass through the new capture (last-value semantics).

  Metrics that first appear in ``new`` diff against zero. A negative
  ``window_sec`` (monotonic clocks from different boots) clamps to
  zero. A cumulative metric that went *backwards* means the process
  restarted and its registry reset — the old anchor belongs to a dead
  incarnation, so the window re-anchors at the reset: the new
  cumulative value passes through as the window's delta (every event
  it counts happened since the restart, which is inside this window)
  and the line is marked ``reset: true``. The old clamp-to-zero
  behavior made a restarted rank read as 0 events/sec for a full
  window, which ``straggler_scores`` maps to ``inf`` — a false
  straggler verdict against the one rank that just recovered.
  """
  old_by_name, old_meta = {}, None
  for line in old:
    if line.get('kind') == 'meta':
      old_meta = line
    else:
      old_by_name[line['name']] = line
  out = []
  for line in new:
    if line.get('kind') == 'meta':
      meta = dict(line)
      if old_meta is not None:
        meta['window_sec'] = max(
            line.get('monotonic', 0.0) - old_meta.get('monotonic', 0.0), 0.0)
      else:
        meta['window_sec'] = 0.0
      out.append(meta)
      continue
    prev = old_by_name.get(line['name'])
    kind = line['kind']
    if kind == 'gauge' or prev is None:
      out.append(dict(line))
      continue
    d = dict(line)
    if kind == 'counter':
      if line.get('total', 0) < prev.get('total', 0):
        d['total'] = line.get('total', 0)  # re-anchor at the restart
        d['reset'] = True
      else:
        d['total'] = line.get('total', 0) - prev.get('total', 0)
    elif kind == 'histogram':
      if line.get('count', 0) < prev.get('count', 0):
        # Re-anchor: the new capture IS the since-restart window.
        d['reset'] = True
      else:
        d['count'] = line.get('count', 0) - prev.get('count', 0)
        d['sum'] = max(line.get('sum', 0.0) - prev.get('sum', 0.0), 0.0)
        old_b = prev.get('buckets') or {}
        d['buckets'] = {
            k: v - old_b.get(k, 0)
            for k, v in (line.get('buckets') or {}).items()
            if v - old_b.get(k, 0) > 0
        }
        if d['count'] == 0:
          d.pop('min', None)
          d.pop('max', None)
    out.append(d)
  return out


_ENV = 'LDDL_TELEMETRY'
_active = None  # None: not yet resolved from the environment
# First resolution can race: writer threads fetch counters lazily while
# the main loop resolves the registry. The lock makes install atomic.
_active_lock = threading.Lock()


def get_telemetry():
  """The process-global registry: :class:`Telemetry` when enabled (env
  ``LDDL_TELEMETRY`` truthy or :func:`enable` called), else the shared
  :data:`NOOP` singleton."""
  global _active
  with _active_lock:
    if _active is None:
      spec = os.environ.get(_ENV, '').strip().lower()
      _active = Telemetry() if spec in ('1', 'true', 'on', 'yes') else NOOP
    return _active


def enable():
  """Switch telemetry on (fresh registry unless already enabled)."""
  global _active
  with _active_lock:
    if _active is None or not _active.enabled:
      _active = Telemetry()
    return _active


def disable():
  """Switch telemetry off (instrument sites see :data:`NOOP` again)."""
  global _active
  with _active_lock:
    _active = NOOP
    return _active

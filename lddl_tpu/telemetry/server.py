"""In-run metrics endpoint: per-process HTTP server gated by LDDL_MONITOR.

Same discipline as ``LDDL_TELEMETRY``: with the gate unset (default)
everything here collapses to a shared immutable no-op singleton — zero
threads, zero sockets, zero allocations on the instrument side (the
no-op asserts in ``tests/test_monitor.py`` pin this). With it set, the
process runs exactly ONE extra daemon thread: a single-threaded
``http.server`` loop on a loopback TCP port or unix socket. There is no
sampler thread — each ``/snapshot`` or ``/metrics`` request samples the
registry into the rolling :class:`~.live.SnapshotWindow`, so the
scraper's cadence IS the windowing cadence and an unwatched process
does no periodic work at all.

``LDDL_MONITOR`` spec forms:

  - ``1`` / ``true`` / ``on`` / ``yes`` — loopback TCP on an ephemeral
    port (the announce file tells ``lddl-monitor`` where);
  - ``<port>`` — loopback TCP on that port (port 0 = ephemeral); ranks
    beyond 0 offset by their rank so one spec serves a local fleet;
  - ``<host>:<port>`` — explicit bind (same rank offset);
  - anything containing ``/`` — a unix-domain socket path, suffixed
    ``.rank<R>`` per rank.

Each server announces itself by writing
``monitor.rank<R>.pid<P>.json`` (url/rank/pid, plus the pid's
namespace and /proc starttime so discovery can tell a dead announcer
from a live one — the same positive-death identity the comm backend's
beacons use) into ``LDDL_MONITOR_DIR`` (falling back to
``LDDL_TELEMETRY_DIR``), and removes it on stop — ``lddl-monitor
--dir`` discovers a fleet from those files and skips announcers whose
pid provably died.

Endpoints:

  - ``GET /snapshot`` — :func:`~.live.live_status` as JSON: windowed
    rates, the live bottleneck verdict (with its roofline sub-verdict),
    straggler signals, goodput meters, HBM gauges, the sentinel block
    (trigger counts + incident dirs, present only when
    ``LDDL_SENTINEL`` is armed — see :mod:`.sentinel`), plus the
    cumulative registry dump;
  - ``GET /metrics``  — Prometheus text exposition of the cumulative
    registry (counters/gauges/histograms with cumulative ``le`` buckets
    derived from the power-of-two log buckets);
  - ``GET /healthz``  — liveness probe;
  - ``GET /profile?steps=N`` — arm ``jax.profiler`` for the next N
    train steps (trace written under ``LDDL_TELEMETRY_DIR/profiles/``;
    see :mod:`.profiling`).
"""

import atexit
import http.server
import json
import math
import os
import socket
import socketserver
import threading
import time

from . import metrics as _metrics
from .live import SnapshotWindow, live_status

_ENV = 'LDDL_MONITOR'
_DIR_ENV = 'LDDL_MONITOR_DIR'


class NoopMonitor:
  """The disabled monitor: every method empty, no state, no thread."""

  __slots__ = ()
  enabled = False
  url = None

  def start(self, rank=0):
    return self

  def stop(self):
    pass


NOOP_MONITOR = NoopMonitor()


def _sanitize(name):
  """Metric name -> Prometheus-legal: ``lddl_`` prefix, [a-zA-Z0-9_]."""
  return 'lddl_' + ''.join(
      c if c.isalnum() or c == '_' else '_' for c in name)


def prometheus_lines(snapshot_lines):
  """Render ``Telemetry.snapshot_lines()`` as Prometheus text exposition.

  Log buckets (power-of-two exponents) become cumulative ``le`` buckets
  — coarse, but honest: every ``le`` boundary is a real bucket edge the
  histogram actually tracked.
  """
  out = []
  for line in snapshot_lines:
    kind = line.get('kind')
    if kind == 'meta':
      out.append('# lddl meta rank=%s pid=%s' %
                 (line.get('rank'), line.get('pid')))
      continue
    name = _sanitize(line['name'])
    if kind == 'counter':
      out.append(f'# TYPE {name}_total counter')
      out.append(f'{name}_total {line.get("total", 0)}')
    elif kind == 'gauge':
      v = line.get('value')
      if v is None:
        continue
      out.append(f'# TYPE {name} gauge')
      out.append(f'{name} {v}')
    elif kind == 'histogram':
      out.append(f'# TYPE {name} histogram')
      buckets = line.get('buckets') or {}
      zero = buckets.get('zero', 0)
      numeric = sorted(int(k) for k in buckets if k != 'zero')
      cum = zero
      if zero:
        out.append(f'{name}_bucket{{le="0.0"}} {cum}')
      for e in numeric:
        cum += buckets[str(e)] if str(e) in buckets else buckets.get(e, 0)
        out.append(f'{name}_bucket{{le="{float(2.0 ** (e + 1))}"}} {cum}')
      out.append(f'{name}_bucket{{le="+Inf"}} {line.get("count", 0)}')
      out.append(f'{name}_sum {line.get("sum", 0.0)}')
      out.append(f'{name}_count {line.get("count", 0)}')
  return '\n'.join(out) + '\n'


class _Handler(http.server.BaseHTTPRequestHandler):
  # The monitor must never write request logs into the job's stdout.
  def log_message(self, fmt, *args):  # noqa: A002 - base class signature
    pass

  def _send(self, body, content_type):
    data = body.encode('utf-8')
    self.send_response(200)
    self.send_header('Content-Type', content_type)
    self.send_header('Content-Length', str(len(data)))
    self.end_headers()
    self.wfile.write(data)

  def do_GET(self):  # noqa: N802 - http.server API
    mon = self.server.monitor
    path = self.path.split('?', 1)[0]
    try:
      if path == '/healthz':
        self._send('ok\n', 'text/plain; charset=utf-8')
      elif path == '/metrics':
        tele = _metrics.get_telemetry()
        self._send(prometheus_lines(tele.snapshot_lines(rank=mon.rank)),
                   'text/plain; version=0.0.4; charset=utf-8')
      elif path == '/snapshot':
        with mon.window_lock:
          status = live_status(mon.window, rank=mon.rank)
        self._send(json.dumps(status, default=_json_default),
                   'application/json')
      elif path == '/profile':
        from urllib.parse import parse_qs
        from .profiling import get_step_profiler
        query = parse_qs(self.path.partition('?')[2])
        try:
          steps = int(query.get('steps', ['1'])[0])
        except (ValueError, IndexError):
          steps = 0
        if steps < 1:
          self.send_error(400, 'bad steps= value (want a positive int)')
          return
        trace_dir = get_step_profiler().arm(steps)
        self._send(json.dumps({'armed_steps': steps,
                               'trace_dir': trace_dir,
                               'rank': mon.rank}),
                   'application/json')
      else:
        self.send_error(404, 'unknown endpoint (try /snapshot, /metrics, '
                             '/healthz, /profile)')
    except BrokenPipeError:
      pass  # scraper went away mid-response; nothing to clean up


def _json_default(o):
  if isinstance(o, float) and not math.isfinite(o):
    return str(o)
  return str(o)


class _TcpServer(socketserver.TCPServer):
  allow_reuse_address = True
  daemon_threads = True


class _UnixServer(socketserver.UnixStreamServer):
  daemon_threads = True

  def server_bind(self):
    try:
      os.unlink(self.server_address)
    except FileNotFoundError:
      pass
    super().server_bind()


def _parse_spec(spec, rank):
  """LDDL_MONITOR value -> ('tcp', (host, port)) | ('unix', path)."""
  s = spec.strip()
  low = s.lower()
  if '/' in s:
    return 'unix', f'{s}.rank{rank}'
  if low in ('1', 'true', 'on', 'yes'):
    return 'tcp', ('127.0.0.1', 0)
  if ':' in s:
    host, _, port = s.rpartition(':')
    p = int(port)
    return 'tcp', (host, p + rank if p else 0)
  p = int(s)
  return 'tcp', ('127.0.0.1', p + rank if p else 0)


class MonitorServer:
  """One daemon thread serving this process's registry over HTTP."""

  enabled = True

  def __init__(self, spec, rank=0):
    self._spec = spec
    self.rank = rank
    self.window = SnapshotWindow()
    self.window_lock = threading.Lock()
    self._httpd = None
    self._thread = None
    self._announce_path = None
    self.url = None

  def start(self, rank=None):
    if self._thread is not None:
      return self
    if rank is not None:
      self.rank = rank
    kind, addr = _parse_spec(self._spec, self.rank)
    if kind == 'unix':
      self._httpd = _UnixServer(addr, _Handler, bind_and_activate=True)
      self.url = f'unix:{addr}'
    else:
      self._httpd = _TcpServer(addr, _Handler, bind_and_activate=True)
      host, port = self._httpd.server_address[:2]
      self.url = f'http://{host}:{port}'
    self._httpd.monitor = self
    self._thread = threading.Thread(
        target=self._httpd.serve_forever, kwargs={'poll_interval': 0.25},
        name=f'lddl-monitor-rank{self.rank}', daemon=True)
    self._thread.start()
    self._announce()
    # Clean exits must not leave stale announce files / unix sockets
    # behind for lddl-monitor to trip over. stop() is idempotent, so a
    # prior explicit stop makes this a no-op.
    atexit.register(self.stop)
    return self

  def _announce(self):
    directory = (os.environ.get(_DIR_ENV, '').strip() or
                 os.environ.get('LDDL_TELEMETRY_DIR', '').strip())
    if not directory:
      return
    os.makedirs(directory, exist_ok=True)
    self._announce_path = os.path.join(
        directory, f'monitor.rank{self.rank}.pid{os.getpid()}.json')
    # Ship the announcer's full pid identity (namespace + /proc
    # starttime, the comm beacons' positive-death triple) so discovery
    # can prove a SIGKILLed announcer dead instead of timing out on its
    # stale endpoint.
    from ..comm.backend import FileBackend
    payload = json.dumps({'url': self.url, 'rank': self.rank,
                          'pid': os.getpid(),
                          'pidns': FileBackend._pid_namespace(),
                          'pid_starttime':
                              FileBackend._pid_starttime(os.getpid()),
                          'started_unix': time.time()})
    tmp = self._announce_path + '.tmp'
    with open(tmp, 'w') as f:
      f.write(payload)
    os.replace(tmp, self._announce_path)

  def stop(self):
    if self._httpd is None:
      return
    self._httpd.shutdown()
    self._thread.join(timeout=5.0)
    self._httpd.server_close()
    if isinstance(self._httpd, _UnixServer):
      try:
        os.unlink(self._httpd.server_address)
      except OSError:
        pass
    if self._announce_path:
      try:
        os.unlink(self._announce_path)
      except OSError:
        pass
      self._announce_path = None
    self._httpd = None
    self._thread = None
    self.url = None


_active = None  # None: not yet resolved from the environment


def get_monitor():
  """The process-global monitor: a started :class:`MonitorServer` when
  ``LDDL_MONITOR`` is set (or :func:`maybe_start_monitor` forced one),
  else the shared :data:`NOOP_MONITOR` singleton. Resolution is lazy
  and cached, mirroring :func:`~.metrics.get_telemetry`."""
  global _active
  if _active is None:
    spec = os.environ.get(_ENV, '').strip()
    if spec and spec.lower() not in ('0', 'false', 'off', 'no'):
      _active = MonitorServer(spec)
    else:
      _active = NOOP_MONITOR
  return _active


def maybe_start_monitor(rank=0):
  """Start the monitor server for this process if (and only if) the
  gate is set. Idempotent — entry points (executor construction, the
  train loop, the loader builder) all call it; the first one wins and
  later calls return the same instance. With the gate unset this is a
  single dict lookup returning the no-op singleton."""
  mon = get_monitor()
  if mon.enabled:
    mon.start(rank=rank)
  return mon


def stop_monitor():
  """Stop and forget the active server (tests; atexit-ish cleanup).
  The next :func:`get_monitor` re-resolves from the environment."""
  global _active
  if _active is not None and _active.enabled:
    _active.stop()
  _active = None

"""Event-level execution tracing: bounded ring buffer -> Perfetto.

Where :mod:`.metrics` answers *how much* time each stage cost in
aggregate, this module answers *when*: every instrumented touchpoint
(executor stage tasks, loader pulls and collates, comm collectives,
train-step phases) can record timestamped events into a bounded
in-process ring buffer, and ``python -m lddl_tpu.cli telemetry-trace``
merges every rank's buffer into one Chrome-trace-format JSON loadable
in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.

Design constraints mirror ``metrics.py`` exactly, in priority order:

1. **Disabled must cost ~nothing.** With ``LDDL_TRACE`` off (default)
   :func:`get_tracer` hands out the shared :data:`NOOP_TRACER`
   singleton whose every method is empty — one dynamic dispatch per
   event, no lock, no allocation (asserted by ``tests/test_trace.py``).
   Instrument sites fetch the tracer once per stream/loop and guard any
   per-event ``args`` dict construction behind ``tracer.enabled``.
2. **Enabled stays bounded.** Events append to a ``deque(maxlen=N)``
   (``LDDL_TRACE_BUFFER``, default 65536): a long run keeps the most
   recent window instead of growing without limit — exactly the tail
   you want when diagnosing where a run stalled or died.
3. **Crash-usable.** When ``LDDL_TELEMETRY_DIR`` is set, the recorder
   opportunistically flushes its buffer to
   ``trace.rank<R>[.pid<P>].jsonl`` every ``LDDL_TRACE_FLUSH_SEC``
   seconds (checked every few hundred events, amortized to ~nothing),
   so a SIGKILLed rank still leaves a readable tail on disk.

Timestamps are raw ``time.monotonic()`` seconds. Every trace file's
meta line carries a ``(anchor_unix, anchor_monotonic)`` pair sampled
together at recorder creation; the merger maps each file onto the unix
timeline via its anchor and then *refines* per-rank offsets from
matched collective events (``comm.allgather``/``comm.barrier`` carry a
sequence number, and all ranks complete collective ``#seq`` within one
collective latency of each other), so cross-host unix-clock skew does
not smear the merged timeline.
"""

import collections
import glob
import json
import os
import statistics
import sys
import tempfile
import threading
import time


class _NoopSpan:
  """Reusable no-op context manager (one shared instance, never mutated)."""

  __slots__ = ()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    return False


_NOOP_SPAN = _NoopSpan()


class NoopTracer:
  """The disabled recorder: every method is empty, every handle shared."""

  __slots__ = ()
  enabled = False

  def set_identity(self, rank=None, per_pid=None):
    pass

  def reset(self, rank=None, per_pid=None):
    pass

  def span(self, name, args=None):
    return _NOOP_SPAN

  def complete(self, name, start, duration, tid=None, args=None):
    pass

  def instant(self, name, args=None):
    pass

  def counter(self, name, value):
    pass

  def event_dicts(self):
    return []

  def flush(self, directory=None):
    return None

  def write_jsonl(self, path, rank=None):
    return None


NOOP_TRACER = NoopTracer()


class _Span:
  """Context manager recording one complete ('X') event into ``tracer``."""

  __slots__ = ('_tracer', '_name', '_args', '_t0')

  def __init__(self, tracer, name, args):
    self._tracer = tracer
    self._name = name
    self._args = args
    self._t0 = 0.0

  def __enter__(self):
    self._t0 = time.monotonic()
    return self

  def __exit__(self, *exc):
    self._tracer.complete(self._name, self._t0,
                          time.monotonic() - self._t0, args=self._args)
    return False


_DEFAULT_MAX_EVENTS = 65536
# Auto-flush clock check is amortized over this many events so the
# per-event record cost stays one deque append + one int increment.
_FLUSH_CHECK_EVERY = 64


class Tracer:
  """An enabled trace recorder (one per process).

  Events are stored as tuples ``(ph, name, ts, dur, tid, args)`` with
  ``ts``/``dur`` in monotonic seconds; ``tid`` is the recording thread's
  ident unless the caller supplies one (the pipeline executor passes the
  pool worker's pid so pooled task spans land on per-worker lanes).
  """

  enabled = True

  def __init__(self, max_events=None, rank=None, flush_interval=None):
    if max_events is None:
      max_events = int(os.environ.get('LDDL_TRACE_BUFFER',
                                      _DEFAULT_MAX_EVENTS))
    self._events = collections.deque(maxlen=max_events)
    self.anchor_unix = time.time()
    self.anchor_monotonic = time.monotonic()
    self.main_thread = threading.get_ident()
    self.rank = (rank if rank is not None
                 else int(os.environ.get('LDDL_RANK', '0') or 0))
    self.per_pid = False
    if flush_interval is None:
      flush_interval = float(os.environ.get('LDDL_TRACE_FLUSH_SEC', '5'))
    self._flush_interval = flush_interval
    self._last_flush = time.monotonic()
    self._since_check = 0

  def set_identity(self, rank=None, per_pid=None):
    """Set the rank (and per-pid file suffixing) used by auto-flush."""
    if rank is not None:
      self.rank = rank
    if per_pid is not None:
      self.per_pid = per_pid

  def reset(self, rank=None, per_pid=None):
    """Fresh buffer + anchor: called by forked/spawned child processes
    (loader workers) that inherited the parent's recorder so the child's
    file holds only its own events under its own anchor."""
    self._events.clear()
    self.anchor_unix = time.time()
    self.anchor_monotonic = time.monotonic()
    self.main_thread = threading.get_ident()
    self._last_flush = time.monotonic()
    self._since_check = 0
    self.set_identity(rank=rank, per_pid=per_pid)

  # ---- recording ----

  def span(self, name, args=None):
    """Context manager recording one complete event for its wall time."""
    return _Span(self, name, args)

  def complete(self, name, start, duration, tid=None, args=None):
    """A 'X' event with explicit monotonic ``start`` and ``duration``."""
    self._record(('X', name, start, duration,
                  threading.get_ident() if tid is None else tid, args))

  def instant(self, name, args=None):
    self._record(('i', name, time.monotonic(), None,
                  threading.get_ident(), args))

  def counter(self, name, value):
    """A counter-track sample ('C'): queue depth, samples/s, ..."""
    self._record(('C', name, time.monotonic(), None, 0, float(value)))

  def _record(self, ev):
    self._events.append(ev)  # GIL-atomic; maxlen bounds memory
    self._since_check += 1
    if self._since_check >= _FLUSH_CHECK_EVERY:
      self._since_check = 0
      if time.monotonic() - self._last_flush >= self._flush_interval:
        self.flush()

  # ---- export ----

  def event_dicts(self):
    """The buffer as JSON-able dicts (the JSONL wire format)."""
    # deque appends from other threads (the prefetch producer records
    # h2d spans) can race iteration; retry on the rare mid-mutation.
    for _ in range(8):
      try:
        events = list(self._events)
        break
      except RuntimeError:
        continue
    else:
      events = []
    out = []
    for ph, name, ts, dur, tid, args in events:
      d = {'ph': ph, 'name': name, 'ts': ts, 'tid': tid}
      if dur is not None:
        d['dur'] = dur
      if ph == 'C':
        d['value'] = args
      elif args:
        d['args'] = args
      out.append(d)
    return out

  def meta_line(self, rank=None):
    return {'kind': 'meta', 'rank': self.rank if rank is None else rank,
            'pid': os.getpid(), 'main_thread': self.main_thread,
            'unix_time': time.time(), 'monotonic': time.monotonic(),
            'anchor_unix': self.anchor_unix,
            'anchor_monotonic': self.anchor_monotonic,
            'clock': 'monotonic_seconds'}

  def flush(self, directory=None):
    """Write the current buffer under ``LDDL_TELEMETRY_DIR`` (or
    ``directory``) at this process's canonical path; no-op without a
    destination. Called opportunistically from the record path so
    crashed processes leave a usable tail."""
    self._last_flush = time.monotonic()
    directory = directory or os.environ.get('LDDL_TELEMETRY_DIR')
    if not directory:
      return None
    return self.write_jsonl(trace_file_name(
        directory, self.rank, pid=os.getpid() if self.per_pid else None))

  def write_jsonl(self, path, rank=None):
    """Atomically write meta line + events as JSONL to ``path``."""
    lines = [self.meta_line(rank)] + self.event_dicts()
    payload = '\n'.join(json.dumps(line) for line in lines) + '\n'
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + '.tmp.')
    with os.fdopen(fd, 'w') as f:
      f.write(payload)
      # Durable-then-visible (same as MetricsRegistry.write_jsonl): the
      # rename must never make a name point at unwritten-back content.
      f.flush()
      os.fsync(f.fileno())
    os.replace(tmp, path)
    self._last_flush = time.monotonic()
    return path


def trace_file_name(directory, rank, pid=None):
  """Canonical per-process export path (what ``telemetry-trace`` globs):
  ``trace.rank<R>.jsonl`` for the rank's main process,
  ``trace.rank<R>.pid<P>.jsonl`` for its loader workers."""
  if pid is None:
    return os.path.join(directory, f'trace.rank{rank}.jsonl')
  return os.path.join(directory, f'trace.rank{rank}.pid{pid}.jsonl')


_ENV = 'LDDL_TRACE'
_active = None  # None: not yet resolved from the environment


def get_tracer():
  """The process-global recorder: :class:`Tracer` when enabled (env
  ``LDDL_TRACE`` truthy or :func:`enable_trace` called), else the shared
  :data:`NOOP_TRACER` singleton."""
  global _active
  if _active is None:
    spec = os.environ.get(_ENV, '').strip().lower()
    _active = Tracer() if spec in ('1', 'true', 'on', 'yes') else NOOP_TRACER
  return _active


def enable_trace(**kwargs):
  """Switch tracing on (fresh recorder unless already enabled)."""
  global _active
  if _active is None or not _active.enabled:
    _active = Tracer(**kwargs)
  return _active


def disable_trace():
  """Switch tracing off (instrument sites see :data:`NOOP_TRACER`)."""
  global _active
  _active = NOOP_TRACER
  return _active


# ---- cross-rank merge -> Chrome trace format ----

# Collective completions used for clock refinement: every rank finishes
# collective #seq within one collective latency of the others.
_ALIGN_NAMES = ('comm.allgather', 'comm.barrier')


def load_trace_files(directory):
  """Parse every ``trace.rank*.jsonl`` under ``directory``; returns a
  list of ``(meta, events)`` pairs (one per file)."""
  paths = sorted(glob.glob(os.path.join(directory, 'trace.rank*.jsonl')))
  if not paths:
    raise FileNotFoundError(
        f'no trace.rank*.jsonl files under {directory} '
        '(run with LDDL_TRACE=1 and LDDL_TELEMETRY_DIR set)')
  out = []
  for p in paths:
    meta, events = None, []
    with open(p) as f:
      for ln, line in enumerate(f, start=1):
        if not line.strip():
          continue
        try:
          d = json.loads(line)
        except ValueError:
          # A SIGKILLed writer can leave a torn trailing line; the rest
          # of the file is intact and far more useful than an abort.
          print(f'telemetry-trace: skipping unparseable line {ln} of '
                f'{p} (truncated write?)', file=sys.stderr)
          continue
        if d.get('kind') == 'meta':
          meta = d
        else:
          events.append(d)
    if meta is None:  # tolerate a truncated crash tail with no meta
      meta = {'rank': 0, 'pid': 0, 'anchor_unix': 0.0,
              'anchor_monotonic': 0.0}
    out.append((meta, events))
  return out


def _anchor_offset(meta):
  """Seconds to add to a file's monotonic timestamps to land on its own
  host's unix timeline."""
  return meta.get('anchor_unix', 0.0) - meta.get('anchor_monotonic', 0.0)


def compute_rank_offsets(files):
  """Per-rank clock corrections (seconds) refined from matched
  collective events.

  Anchors place every file on its host's unix timeline, but hosts'
  unix clocks can disagree by far more than a collective latency. All
  ranks complete collective ``#seq`` within one collective latency of
  each other, so for each non-reference rank the median of
  ``t_ref(seq) - t_rank(seq)`` over matched completions estimates that
  rank's residual clock skew. Returns ``{rank: correction}`` to *add*
  to anchor-aligned times (reference rank = lowest rank; missing or
  unmatched ranks get no correction).
  """
  by_rank = {}
  for meta, events in files:
    r = meta.get('rank', 0)
    off = _anchor_offset(meta)
    for ev in events:
      args = ev.get('args') or {}
      if ev.get('name') in _ALIGN_NAMES and args.get('seq') is not None:
        key = (ev['name'], args['seq'])
        by_rank.setdefault(r, {})[key] = (
            ev.get('ts', 0.0) + (ev.get('dur') or 0.0) + off)
  ranks = sorted(by_rank)
  if not ranks:
    return {}
  ref = by_rank[ranks[0]]
  corrections = {}
  for r in ranks[1:]:
    deltas = [ref[k] - t for k, t in by_rank[r].items() if k in ref]
    if deltas:
      corrections[r] = statistics.median(deltas)
  return corrections


def merge_trace_files(files, verdict=None):
  """Merge per-process trace files into one Chrome-trace JSON object.

  Lanes: rank -> Chrome ``pid`` (one process row per rank), each
  recording (process, thread) pair -> a compact ``tid`` lane within it.
  Counter events ('C') render as per-rank counter tracks. ``verdict``
  (a :func:`lddl_tpu.telemetry.report.summarize_stages` dict) is
  embedded under ``metadata.lddl.bottleneck``.
  """
  corrections = compute_rank_offsets(files)
  aligned = []  # (rank, file_pid, main_thread, event, unix_time)
  for meta, events in files:
    r = meta.get('rank', 0)
    off = _anchor_offset(meta) + corrections.get(r, 0.0)
    mt = meta.get('main_thread')
    fpid = meta.get('pid', 0)
    for ev in events:
      aligned.append((r, fpid, mt, ev, ev.get('ts', 0.0) + off))
  t0 = min((t for *_, t in aligned), default=0.0)
  ranks = sorted({meta.get('rank', 0) for meta, _ in files})

  out = []
  for r in ranks:
    out.append({'ph': 'M', 'name': 'process_name', 'pid': r, 'tid': 0,
                'ts': 0, 'args': {'name': f'rank {r}'}})
    out.append({'ph': 'M', 'name': 'process_sort_index', 'pid': r, 'tid': 0,
                'ts': 0, 'args': {'sort_index': r}})

  lanes = {}  # (rank, file_pid, raw_tid) -> (compact tid, label)

  def lane(r, fpid, mt, raw_tid):
    key = (r, fpid, raw_tid)
    entry = lanes.get(key)
    if entry is None:
      label = (f'pid {fpid}' if raw_tid == mt
               else f'pid {fpid} t{raw_tid}')
      entry = (len(lanes) + 1, label)
      lanes[key] = entry
    return entry[0]

  for r, fpid, mt, ev, t in aligned:
    ph = ev.get('ph')
    ts_us = (t - t0) * 1e6
    if ph == 'C':
      out.append({'ph': 'C', 'name': ev['name'], 'pid': r, 'tid': 0,
                  'ts': ts_us, 'args': {'value': ev.get('value', 0.0)}})
      continue
    d = {'ph': ph, 'name': ev['name'],
         'cat': ev['name'].split('.', 1)[0], 'pid': r,
         'tid': lane(r, fpid, mt, ev.get('tid', 0)), 'ts': ts_us}
    if ph == 'X':
      d['dur'] = (ev.get('dur') or 0.0) * 1e6
    elif ph == 'i':
      d['s'] = 't'  # thread-scoped instant
    if ev.get('args'):
      d['args'] = ev['args']
    out.append(d)

  for (r, fpid, _raw), (tid, label) in lanes.items():
    out.append({'ph': 'M', 'name': 'thread_name', 'pid': r, 'tid': tid,
                'ts': 0, 'args': {'name': label}})
  out.sort(key=lambda e: (e.get('ts', 0), e['pid'], e['tid']))

  meta_out = {'ranks': ranks,
              'clock_corrections': {str(k): v
                                    for k, v in corrections.items()},
              'trace_time_origin_unix': t0}
  if verdict is not None:
    meta_out['bottleneck'] = verdict
  return {'traceEvents': out, 'displayTimeUnit': 'ms',
          'metadata': {'lddl': meta_out}}


def attach_args(parser):
  parser.add_argument('--dir', required=True,
                      help='directory holding trace.rank*.jsonl files '
                           '(and optionally telemetry.rank*.jsonl for '
                           'the embedded bottleneck verdict)')
  parser.add_argument('--output', '-o', default=None,
                      help='output path for the merged Chrome-trace '
                           'JSON (default <dir>/trace.merged.json)')
  return parser


def main(args=None):
  import argparse
  parser = attach_args(argparse.ArgumentParser(
      description=__doc__,
      formatter_class=argparse.RawDescriptionHelpFormatter))
  args = parser.parse_args(args)
  try:
    files = load_trace_files(args.dir)
  except FileNotFoundError as e:
    # Same contract as telemetry-report: one clear line, exit code 2.
    print(f'telemetry-trace: {e}', file=sys.stderr)
    return 2
  verdict = None
  try:  # metrics snapshots are optional company for the trace files
    from .report import load_rank_files, merge_metric_lines, summarize_stages
    verdict = summarize_stages(merge_metric_lines(load_rank_files(args.dir)))
  except FileNotFoundError:
    pass
  doc = merge_trace_files(files, verdict=verdict)
  out = args.output or os.path.join(args.dir, 'trace.merged.json')
  with open(out, 'w') as f:
    json.dump(doc, f)
  print(f'wrote {out}: {len(doc["traceEvents"])} events from '
        f'{len(files)} process file(s), ranks {doc["metadata"]["lddl"]["ranks"]}'
        ' — load in https://ui.perfetto.dev or chrome://tracing')
  return 0


if __name__ == '__main__':
  sys.exit(main())

"""Streaming anomaly sentinels over signals the train loop already pays for.

The observability plane so far only *explains* runs after the fact:
roofline verdicts classify a finished window, ``lddl-audit`` compares
ledgers post-hoc, ``lddl-replay bisect`` needs a human to notice the
divergence first. This module watches a *live* run. Each detector is a
cheap online test over a scalar the step loop already produced — no new
device reads, no threads, no I/O on the hot path:

  ``nonfinite_loss``    loss is NaN/Inf (the silent-NaN bug: before
                        this PR a NaN flowed into the loss list and
                        training continued on garbage)
  ``loss_spike``        robust z-score of the latest loss against a
                        windowed median ± MAD baseline — the same
                        arithmetic ``lddl-perf`` uses to judge bench
                        history, pointed at the live loss stream
  ``grad_spike``        same test over ``train.grad_norm`` (exported by
                        parallel/train.py's step metrics); a non-finite
                        grad norm fires unconditionally
  ``data_stall``        one batch wait exceeded a wall-time budget —
                        the input pipeline wedged, not the model
  ``hbm_headroom``      roofline ``sample_hbm`` headroom collapsed
                        below a floor (probed every N steps; the probe
                        is the only detector that costs a device query)
  ``serve_backlog``     the data service's in-memory window hit its
                        runaway threshold (observed at the producer's
                        backlog-gauge site, on the server thread)
  ``ledger_divergence`` the determinism ledger's *live* fleet verdict
                        (monitor cross-rank comparison) reads
                        'diverged'

Gate discipline matches the ledger/monitor/profiler subsystems exactly:
``LDDL_SENTINEL`` unset → a shared immutable no-op singleton whose
``observe_step`` is an empty method (~100 ns); ``LDDL_SENTINEL=1``
enables every detector; a comma list (``LDDL_SENTINEL=nonfinite_loss,
loss_spike``) enables a subset. Thresholds tune via ``LDDL_SENTINEL_*``
env knobs or constructor kwargs (kwargs win).

A trigger is a plain dict (detector, step, reason, value, window
stats). The sentinel itself only *detects* — capture is the flight
recorder's job (training/flight.py), which registers each incident back
here via :meth:`Sentinel.note_incident` so ``sentinel_status()`` can
surface triggers and incident paths to ``live_status`` → ``/snapshot``
→ the ``lddl-monitor`` INCIDENT panel without an import cycle.

Fault drill: a ``raise:sentinel.trigger`` spec in ``LDDL_FAULTS`` is
caught inside ``observe_step`` and converted into a forced trigger
(detector ``injected``, cooldown bypassed) — the supported way to
force-fire the whole capture path in tests and game-days.
"""

import json
import math
import os
import threading
import time

from ..core import faults
from .metrics import get_telemetry
from .perf import robust_stats

_ENV = 'LDDL_SENTINEL'

#: Every detector, in the order ``LDDL_SENTINEL=1`` enables them.
DETECTORS = ('nonfinite_loss', 'loss_spike', 'grad_spike', 'data_stall',
             'hbm_headroom', 'serve_backlog', 'ledger_divergence')

#: How many incident registrations ``note_incident`` retains.
MAX_INCIDENT_NOTES = 16


def _env_float(name, default):
  raw = os.environ.get(name, '').strip()
  try:
    return float(raw) if raw else default
  except ValueError:
    return default


def _env_int(name, default):
  raw = os.environ.get(name, '').strip()
  try:
    return int(raw) if raw else default
  except ValueError:
    return default


class NoopSentinel:
  """Shared inert sentinel: every observation is an empty method."""

  __slots__ = ()
  enabled = False
  detectors = ()
  triggers = 0

  def observe_step(self, step, loss=None, grad_norm=None, data_wait=None):
    return None

  def observe_backlog(self, backlog):
    return None

  def note_incident(self, path, trigger):
    return None

  def status(self):
    return None


NOOP_SENTINEL = NoopSentinel()


class Sentinel:
  """Online anomaly detectors over the step loop's existing scalars.

  One instance per process; ``observe_step`` runs on the training
  thread, ``observe_backlog`` on the data service's producer thread,
  ``note_incident``/``status`` from wherever the flight recorder and
  monitor live — shared mutable state is lock-protected, but the
  per-step fast path (window append + median/MAD over ≤ ``window``
  floats) takes the lock only to publish a fire.
  """

  enabled = True

  def __init__(self, detectors=None, window=None, warmup=None,
               z_threshold=None, min_rel=None, stall_sec=None,
               headroom_min=None, backlog_max=None, cooldown=None,
               hbm_every=None):
    spec = detectors if detectors is not None else DETECTORS
    unknown = [d for d in spec if d not in DETECTORS]
    if unknown:
      raise ValueError(
          f'unknown sentinel detector(s) {unknown}; choose from '
          f'{list(DETECTORS)}')
    self.detectors = tuple(spec)
    self._det = frozenset(self.detectors)
    self.window = window if window is not None else _env_int(
        'LDDL_SENTINEL_WINDOW', 64)
    self.warmup = warmup if warmup is not None else _env_int(
        'LDDL_SENTINEL_WARMUP', 16)
    self.z_threshold = z_threshold if z_threshold is not None else _env_float(
        'LDDL_SENTINEL_Z', 8.0)
    self.min_rel = min_rel if min_rel is not None else _env_float(
        'LDDL_SENTINEL_MIN_REL', 0.5)
    self.stall_sec = stall_sec if stall_sec is not None else _env_float(
        'LDDL_SENTINEL_STALL_SEC', 60.0)
    self.headroom_min = (headroom_min if headroom_min is not None
                         else _env_float('LDDL_SENTINEL_HEADROOM', 0.03))
    self.backlog_max = backlog_max if backlog_max is not None else _env_int(
        'LDDL_SENTINEL_BACKLOG', 256)
    self.cooldown = cooldown if cooldown is not None else _env_int(
        'LDDL_SENTINEL_COOLDOWN', 32)
    self.hbm_every = hbm_every if hbm_every is not None else _env_int(
        'LDDL_SENTINEL_HBM_EVERY', 32)
    self._losses = []    # bounded manually: pop(0) past self.window
    self._grads = []
    self._cooldown_until = None   # step number triggers are muted below
    self._backlog_muted = False   # backlog refires once per excursion
    self._diverged_seq = None     # last fleet-verdict seq already fired
    self.triggers = 0
    self.last_trigger = None
    self.incidents = []
    self._lock = threading.Lock()
    tele = get_telemetry()
    self._trigger_c = tele.counter('sentinel.triggers')

  # -- firing

  def _fire(self, detector, step, reason, value=None, stats=None,
            force=False):
    """Publish a trigger dict, honoring the per-step cooldown.

    ``force`` (fault-injected triggers) bypasses the cooldown so a
    drill always exercises the capture path.
    """
    with self._lock:
      if (not force and step is not None
          and self._cooldown_until is not None
          and step < self._cooldown_until):
        return None
      if step is not None:
        self._cooldown_until = step + self.cooldown
      trigger = {
          'detector': detector,
          'step': step,
          'reason': reason,
          'value': value,
          'unix_time': time.time(),
      }
      if stats:
        trigger['stats'] = stats
      self.triggers += 1
      self.last_trigger = trigger
      self._trigger_c.add(1)
      return dict(trigger)

  def _spike(self, detector, series, value, step, label):
    """Robust z-test of ``value`` against the windowed baseline —
    the lddl-perf decision rule, upward-only (a loss/grad *drop* is
    good news)."""
    if len(series) < self.warmup:
      return None
    med, mad = robust_stats(series)
    scale = max(1.4826 * mad, self.min_rel * abs(med), 1e-12)
    z = (value - med) / scale
    rel = (value - med) / abs(med) if med else 0.0
    if z > self.z_threshold and rel > self.min_rel:
      return self._fire(
          detector, step,
          f'{label} {value:.6g} spiked over window median {med:.6g} '
          f'(robust z={z:.1f}, +{100 * rel:.0f}%)',
          value=value,
          stats={'median': med, 'mad': mad, 'robust_z': round(z, 3),
                 'rel_change': round(rel, 4), 'window': len(series)})
    return None

  # -- observations

  def observe_step(self, step, loss=None, grad_norm=None, data_wait=None):
    """One training step's signals. Returns a trigger dict when a
    detector fires (at most one per call; earlier detectors win) or
    None. Never raises — a sentinel must not take down the run it
    watches."""
    step = int(step)
    try:
      faults.inject('sentinel.trigger', step=step)
    except OSError as exc:
      return self._fire('injected', step, f'fault-injected trigger: {exc}',
                        force=True)
    det = self._det
    fired = None
    if loss is not None:
      loss = float(loss)
      if not math.isfinite(loss):
        if 'nonfinite_loss' in det:
          fired = self._fire('nonfinite_loss', step,
                             f'loss is non-finite ({loss!r})', value=loss)
      else:
        if fired is None and 'loss_spike' in det:
          fired = self._spike('loss_spike', self._losses, loss, step, 'loss')
        self._losses.append(loss)
        if len(self._losses) > self.window:
          self._losses.pop(0)
    if grad_norm is not None:
      grad_norm = float(grad_norm)
      if not math.isfinite(grad_norm):
        if fired is None and 'grad_spike' in det:
          fired = self._fire('grad_spike', step,
                             f'grad norm is non-finite ({grad_norm!r})',
                             value=grad_norm)
      else:
        if fired is None and 'grad_spike' in det:
          fired = self._spike('grad_spike', self._grads, grad_norm, step,
                              'grad norm')
        self._grads.append(grad_norm)
        if len(self._grads) > self.window:
          self._grads.pop(0)
    if (fired is None and data_wait is not None and 'data_stall' in det
        and float(data_wait) >= self.stall_sec):
      fired = self._fire(
          'data_stall', step,
          f'batch wait {float(data_wait):.1f}s exceeded the '
          f'{self.stall_sec:.0f}s stall budget', value=float(data_wait))
    if (fired is None and 'hbm_headroom' in det and self.hbm_every > 0
        and step % self.hbm_every == 0):
      fired = self._check_hbm(step)
    if fired is None and 'ledger_divergence' in det:
      fired = self._check_divergence(step)
    return fired

  def _check_hbm(self, step):
    try:
      from .roofline import sample_hbm
      summary = sample_hbm()
    except Exception:
      return None  # no HBM introspection on this platform
    if not summary:
      return None
    headroom = summary.get('headroom_frac')
    if headroom is not None and headroom < self.headroom_min:
      return self._fire(
          'hbm_headroom', step,
          f'HBM headroom {100 * headroom:.1f}% below the '
          f'{100 * self.headroom_min:.1f}% floor', value=headroom,
          stats={k: summary.get(k) for k in
                 ('peak_bytes_in_use', 'bytes_limit', 'devices')
                 if summary.get(k) is not None})
    return None

  def _check_divergence(self, step):
    """Fire once per *new* diverged fleet verdict — the monitor stashes
    its cross-rank comparison into the ledger (``set_fleet_verdict``)
    and bumps a sequence number; refiring on the same verdict would
    dump an identical incident every step."""
    from .ledger import get_ledger
    led = get_ledger()
    if not led.enabled:
      return None
    verdict = led.fleet_verdict()
    if not verdict or verdict.get('status') != 'diverged':
      return None
    seq = verdict.get('seq', json.dumps(verdict, sort_keys=True,
                                        default=str))
    with self._lock:
      if seq == self._diverged_seq:
        return None
      self._diverged_seq = seq
    return self._fire(
        'ledger_divergence', step,
        'live fleet verdict reads diverged: '
        + str(verdict.get('detail') or verdict.get('boundary') or ''),
        value=None, stats={'verdict': verdict}, force=True)

  def observe_backlog(self, backlog):
    """Data-service producer hook: fires when the in-memory window hits
    the runaway threshold, then mutes until the backlog recovers below
    half the threshold (one trigger per excursion, not per batch)."""
    if 'serve_backlog' not in self._det:
      return None
    backlog = int(backlog)
    with self._lock:
      if backlog < self.backlog_max:
        if backlog <= self.backlog_max // 2:
          self._backlog_muted = False
        return None
      if self._backlog_muted:
        return None
      self._backlog_muted = True
    return self._fire(
        'serve_backlog', None,
        f'serve backlog {backlog} reached the runaway threshold '
        f'{self.backlog_max}', value=backlog, force=True)

  # -- incident registry (written by the flight recorder)

  def note_incident(self, path, trigger):
    with self._lock:
      self.incidents.append({
          'dir': str(path),
          'detector': trigger.get('detector'),
          'step': trigger.get('step'),
          'unix_time': time.time(),
      })
      del self.incidents[:-MAX_INCIDENT_NOTES]

  def status(self):
    """Snapshot for ``live_status``/``/snapshot``: detectors, trigger
    count, last trigger, registered incident dirs."""
    with self._lock:
      return {
          'detectors': list(self.detectors),
          'triggers': self.triggers,
          'last': dict(self.last_trigger) if self.last_trigger else None,
          'incidents': [dict(i) for i in self.incidents],
      }


# -- module gate (ledger.py discipline: resolve once, Noop when unset)

_active = None
# Producer/heartbeat threads and the main loop race to the first
# get_sentinel(); the lock makes the lazy install atomic.
_active_lock = threading.Lock()


def _parse_spec(spec):
  """``LDDL_SENTINEL`` grammar → detector tuple or None (disabled)."""
  s = spec.strip().lower()
  if s in ('', '0', 'false', 'off', 'no'):
    return None
  if s in ('1', 'true', 'on', 'yes', 'all'):
    return DETECTORS
  return tuple(n.strip() for n in s.split(',') if n.strip())


def get_sentinel():
  """The process sentinel: a live :class:`Sentinel` when
  ``LDDL_SENTINEL`` is set, else the shared :data:`NOOP_SENTINEL`."""
  global _active
  with _active_lock:
    if _active is None:
      names = _parse_spec(os.environ.get(_ENV, ''))
      _active = Sentinel(detectors=names) if names else NOOP_SENTINEL
    return _active


def enable_sentinel(**kwargs):
  """Force-enable (tests): installs and returns a fresh sentinel."""
  global _active
  with _active_lock:
    _active = Sentinel(**kwargs)
    return _active


def disable_sentinel():
  """Force-disable and drop the active instance (tests)."""
  global _active
  with _active_lock:
    _active = NOOP_SENTINEL


def sentinel_status():
  """``live_status`` hook: the active sentinel's status dict, or None
  when the gate is off (so quiet dashboards stay quiet)."""
  sent = get_sentinel()
  return sent.status() if sent.enabled else None

"""``lddl-perf``: robust perf-regression detection over bench history.

The repo records a perf trajectory nothing reads: per-round
``BENCH_r*.json`` (one throughput number each), ``MULTICHIP_r*.json``
(multi-device smoke pass/fail), and — new in this PR — a bench-history
JSONL that ``bench.py`` appends every run. This module turns that
history into a CI gate: for each metric series it asks whether the
*latest* point is a cliff relative to the prior points, using
median ± MAD robust statistics (a cliff in the history must not poison
the baseline that judges it, and real trajectories are noisy — the
recorded rounds swing 0.8 → 16 MB/s/chip as PRs land, which any
mean ± stddev test would misread).

Decision rule, per series (latest point x, baseline = prior points):

  scale = max(1.4826 * MAD, min_rel_drop * |median|)
  regression iff  (median - x) * direction > 0            (got worse)
             and |x - median| / scale > threshold          (far outside
                                                            usual noise)
             and |x - median| / |median| > min_rel_drop    (and by a
                                                            margin anyone
                                                            cares about)

``direction`` is inferred from the metric name (latency/seconds/ms →
lower-is-better; everything else higher-is-better). The MAD floor keeps
a near-constant series (MAD ≈ 0) from flagging measurement jitter.
"""

import argparse
import glob
import json
import os
import sys

DEFAULT_THRESHOLD = 4.0
DEFAULT_MIN_REL_DROP = 0.05
MIN_POINTS = 4

_HIGHER_IS_BETTER_HINTS = ('per_sec', 'per_s', 'throughput', 'goodput',
                           'mfu', 'rate', '_ok', 'samples', 'frac')
_LOWER_IS_BETTER_HINTS = ('latency', 'seconds', '_ms', '_sec', 'wait',
                          'stall', 'overhead', 'bytes_in_use')


def metric_direction(name):
  """+1 when higher is better, -1 when lower is better. Throughput-ish
  hints are checked first: '_sec' must not claim 'mb_per_sec'."""
  low = name.lower()
  if any(h in low for h in _HIGHER_IS_BETTER_HINTS):
    return 1
  return -1 if any(h in low for h in _LOWER_IS_BETTER_HINTS) else 1


def _median(values):
  s = sorted(values)
  n = len(s)
  mid = n // 2
  return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_stats(values):
  """(median, MAD) of a series."""
  med = _median(values)
  return med, _median([abs(v - med) for v in values])


def judge_series(name, values, threshold=DEFAULT_THRESHOLD,
                 min_rel_drop=DEFAULT_MIN_REL_DROP, min_points=MIN_POINTS):
  """Judge the last point of ``values`` against the rest.

  Returns a verdict dict (``status``: ``ok`` / ``regression`` /
  ``insufficient-data``) with the statistics that justified it.
  """
  out = {'metric': name, 'points': len(values),
         'latest': values[-1] if values else None}
  if len(values) < min_points:
    out['status'] = 'insufficient-data'
    return out
  baseline = values[:-1]
  latest = values[-1]
  med, mad = robust_stats(baseline)
  scale = max(1.4826 * mad, min_rel_drop * abs(med), 1e-12)
  z = (latest - med) / scale
  direction = metric_direction(name)
  rel_change = (latest - med) / abs(med) if med else 0.0
  worse = direction * z < 0
  out.update(baseline_median=round(med, 6), baseline_mad=round(mad, 6),
             robust_z=round(z, 3), rel_change=round(rel_change, 4),
             direction='higher-is-better' if direction > 0
             else 'lower-is-better')
  if worse and abs(z) > threshold and abs(rel_change) > min_rel_drop:
    out['status'] = 'regression'
  else:
    out['status'] = 'ok'
  return out


# ---------------------------------------------------------------------------
# history loaders: BENCH_r*.json, MULTICHIP_r*.json, bench-history JSONL


def _numeric_items(d, prefix=''):
  for k, v in d.items():
    if isinstance(v, bool):
      yield prefix + k, 1.0 if v else 0.0
    elif isinstance(v, (int, float)):
      yield prefix + k, float(v)


def load_bench_rounds(root):
  """Series from ``BENCH_r*.json`` driver rounds: the headline metric by
  its own name, plus any extra numeric keys in ``parsed``."""
  series = {}
  for path in sorted(glob.glob(os.path.join(root, 'BENCH_r*.json'))):
    try:
      with open(path) as f:
        rec = json.load(f)
    except (OSError, ValueError):
      continue
    parsed = rec.get('parsed') or {}
    metric = parsed.get('metric')
    if metric and isinstance(parsed.get('value'), (int, float)):
      series.setdefault(metric, []).append(float(parsed['value']))
    for k, v in _numeric_items(parsed):
      if k in ('value', 'vs_baseline') or k == 'metric':
        continue
      series.setdefault(k, []).append(v)
  return series


def load_multichip_rounds(root):
  """``MULTICHIP_r*.json`` pass/fail as a 1.0/0.0 series (skipped rounds
  are excluded rather than counted as failures)."""
  values = []
  for path in sorted(glob.glob(os.path.join(root, 'MULTICHIP_r*.json'))):
    try:
      with open(path) as f:
        rec = json.load(f)
    except (OSError, ValueError):
      continue
    if rec.get('skipped'):
      continue
    values.append(1.0 if rec.get('ok') else 0.0)
  return {'multichip_smoke_ok': values} if values else {}


def load_history_jsonl(path):
  """Series from the bench-history JSONL ``bench.py`` appends: every
  numeric field of each record, keyed by field name (nested ``parsed``
  dicts flattened one level)."""
  series = {}
  try:
    with open(path) as f:
      lines = f.read().splitlines()
  except OSError:
    return series
  for line in lines:
    line = line.strip()
    if not line:
      continue
    try:
      rec = json.loads(line)
    except ValueError:
      continue
    if not isinstance(rec, dict):
      continue
    flat = dict(_numeric_items(rec))
    parsed = rec.get('parsed')
    if isinstance(parsed, dict):
      flat.update(_numeric_items(parsed))
    metric = rec.get('metric')
    if not metric and isinstance(parsed, dict):
      metric = parsed.get('metric')
    if isinstance(metric, str) and 'value' in flat:
      flat[metric] = flat.pop('value')
    for k, v in flat.items():
      if k in ('n', 'rc', 'vs_baseline'):
        continue
      series.setdefault(k, []).append(v)
  return series


def append_history(path, record):
  """Append one bench record to the history JSONL (used by bench.py)."""
  parent = os.path.dirname(path)
  if parent:
    os.makedirs(parent, exist_ok=True)
  with open(path, 'a') as f:
    f.write(json.dumps(record, sort_keys=True) + '\n')


# ---------------------------------------------------------------------------
# CLI


def gather_series(root, history=None):
  series = load_bench_rounds(root)
  for name, values in load_multichip_rounds(root).items():
    series.setdefault(name, []).extend(values)
  if history is None:
    candidate = os.path.join(root, 'bench_history.jsonl')
    history = candidate if os.path.exists(candidate) else None
  if history:
    for name, values in load_history_jsonl(history).items():
      series.setdefault(name, []).extend(values)
  return series


def attach_args(parser):
  parser.add_argument('--root', default='.',
                      help='directory holding BENCH_r*.json / '
                           'MULTICHIP_r*.json (default: cwd)')
  parser.add_argument('--history', default=None,
                      help='bench-history JSONL (default: '
                           '<root>/bench_history.jsonl when present)')
  parser.add_argument('--threshold', type=float, default=DEFAULT_THRESHOLD,
                      help='robust-z threshold (default %(default)s)')
  parser.add_argument('--min-rel-drop', type=float,
                      default=DEFAULT_MIN_REL_DROP,
                      help='ignore changes smaller than this fraction of '
                           'the baseline median (default %(default)s)')
  parser.add_argument('--min-points', type=int, default=MIN_POINTS,
                      help='series shorter than this are not judged '
                           '(default %(default)s)')
  parser.add_argument('--gate', action='store_true',
                      help='exit 1 when any series regressed (CI mode); '
                           'also runs the thread-graph concurrency lint '
                           '(LDA014–LDA018) over the package and fails '
                           'on any unsuppressed finding')
  parser.add_argument('--audit', nargs='+', metavar='LEDGER',
                      help='also run the determinism auditor over these '
                           'ledger paths: one path self-checks the run '
                           '(replay conflicts, wire damage), two paths '
                           'verify the first against the second '
                           '(lddl-audit verify). Under --gate the audit '
                           'exit code folds into the return code, so one '
                           'command gates perf and determinism.')
  parser.add_argument('--replay-smoke', action='store_true',
                      help='with --audit: also replay one random '
                           'recorded coordinate per ledger boundary '
                           '(lddl-replay smoke) and fold the verdict '
                           'into the gate exit code — the audit proves '
                           'the lineage is consistent, the smoke proves '
                           'it is still *executable*')
  parser.add_argument('--replay-factory', default=None,
                      metavar='MODULE:ATTR',
                      help='loader factory the smoke rebuilds batches '
                           'with (default: the synthetic loader)')
  parser.add_argument('--replay-kwargs-json', default='{}',
                      help='JSON kwargs for --replay-factory')
  parser.add_argument('--incidents', default=None, metavar='DIR',
                      help='also scan this flight-recorder incident tree '
                           '(training/flight.py): any incident manifest '
                           'present fails --gate with its trigger and '
                           'one-command replay printed — a run that '
                           'tripped a sentinel must not pass CI')
  parser.add_argument('--json', action='store_true', dest='as_json',
                      help='emit the full verdict list as JSON')
  return parser


def run_audit(paths):
  """Run the determinism auditor for ``--audit`` and return its exit code.

  One path self-diffs the run (catches intra-run replay conflicts and
  serve.tx/serve.rx wire damage with no reference needed); two paths
  verify the first against the second. More than two is a usage error
  (exit 2) — verify compares exactly one run against one reference.
  """
  from lddl_tpu.telemetry.audit import main as audit_main
  if len(paths) == 1:
    return audit_main(['diff', paths[0], paths[0]])
  if len(paths) == 2:
    return audit_main(['verify', paths[0], paths[1]])
  print('lddl-perf: --audit takes one ledger path (self-check) or two '
        '(run, reference)', file=sys.stderr)
  return 2


def check_incidents(root):
  """``--incidents``: scan a flight-recorder tree and report every
  incident manifest. Returns ``(rc, count)`` — rc 1 when any incident
  (or unreadable manifest) exists, each printed with its trigger and
  the one-command replay so the CI log IS the triage entry point."""
  from lddl_tpu.training.flight import replay_command, scan_incidents
  incidents = scan_incidents(root)
  for inc in incidents:
    man = inc.get('manifest')
    if man is None:
      print(f'lddl-perf: incident {inc["dir"]}: unreadable manifest '
            f'({inc.get("error")})', file=sys.stderr)
      continue
    trig = man.get('trigger') or {}
    print(f'lddl-perf: incident {inc["dir"]}: '
          f'{trig.get("detector", "?")} at step {man.get("step")} — '
          f'{trig.get("reason", "")}', file=sys.stderr)
    cmd = replay_command(inc['dir'], man)
    if cmd:
      print(f'lddl-perf:   replay: {cmd}', file=sys.stderr)
  if incidents:
    print(f'lddl-perf: {len(incidents)} incident(s) under {root}',
          file=sys.stderr)
    return 1, len(incidents)
  return 0, 0


_CONC_VERDICT = None


def check_concurrency():
  """``--gate``: run the thread-graph concurrency rules (LDA014–LDA018)
  over the installed package. Returns ``(rc, count)`` — rc 1 when any
  *unsuppressed* race/lifecycle/lock-order/signal/blocking finding
  exists, each rendered with its labeled chains. A perf number captured
  on a tree with an open deadlock or torn-read finding is not a number
  CI should bless.

  The verdict is memoized per process (the installed tree does not
  change under us), so repeated --gate invocations — the test suite,
  a CI script gating several artifact dirs — lint once; with
  ``LDDL_ANALYZE_CACHE`` set even that first lint reuses parsed facts.
  """
  global _CONC_VERDICT
  if _CONC_VERDICT is not None:
    return _CONC_VERDICT
  try:
    from lddl_tpu.analysis import (CONCURRENCY_RULE_IDS, analyze_package,
                                   cache_from_env)
    unsuppressed, _ = analyze_package(cache=cache_from_env())
  except Exception as e:  # analyzer itself must never crash the gate
    print(f'lddl-perf: concurrency lint unavailable: {e}', file=sys.stderr)
    return 0, 0
  conc = [f for f in unsuppressed if f.rule_id in CONCURRENCY_RULE_IDS]
  for f in conc:
    print(f'lddl-perf: concurrency finding:\n{f.render()}', file=sys.stderr)
  if conc:
    print(f'lddl-perf: {len(conc)} unsuppressed concurrency finding(s)',
          file=sys.stderr)
  _CONC_VERDICT = (1 if conc else 0, len(conc))
  return _CONC_VERDICT


def run_replay_smoke(ledger_path, factory_spec=None, kwargs_json='{}'):
  """``--replay-smoke``: one random recorded coordinate per boundary,
  rematerialized and verified against its ledger line (skips
  boundaries with no batch position). Returns the smoke exit code —
  0 all replayed coordinates matched, 1 on any mismatch/error."""
  from lddl_tpu.replay.rematerialize import replay_smoke
  if factory_spec:
    module, _, attr = factory_spec.partition(':')
    factory, kwargs = (module, attr), json.loads(kwargs_json)
  else:
    factory = ('lddl_tpu.testing', 'get_synthetic_batch_loader')
    kwargs = json.loads(kwargs_json)
  try:
    results, rc = replay_smoke(ledger_path, factory, kwargs)
  except (FileNotFoundError, ValueError, LookupError) as e:
    print(f'lddl-perf: replay smoke failed: {e}', file=sys.stderr)
    return 2
  for bd, r in sorted(results.items()):
    extra = ''
    if 'coordinate' in r:
      extra = f' at {r["coordinate"]}'
    if r['status'] not in ('ok', 'skipped'):
      extra += f' — {r.get("error", "digest mismatch")}'
    print(f'lddl-perf: replay-smoke {bd}: {r["status"]}{extra}')
  return rc


def main(argv=None):
  args = attach_args(argparse.ArgumentParser(
      prog='lddl-perf',
      description='robust perf-regression check over bench history')) \
      .parse_args(argv)
  # Determinism leg first: its findings print even when the perf leg
  # later bails on missing history, so CI logs always show both verdicts.
  audit_rc = run_audit(args.audit) if args.audit else 0
  if args.replay_smoke:
    if not args.audit:
      print('lddl-perf: --replay-smoke requires --audit (the smoke '
            'replays that ledger)', file=sys.stderr)
      return 2
    smoke_rc = run_replay_smoke(args.audit[0], args.replay_factory,
                                args.replay_kwargs_json)
    audit_rc = audit_rc or smoke_rc
  incident_rc, incident_count = 0, 0
  if args.incidents:
    incident_rc, incident_count = check_incidents(args.incidents)
  # Concurrency leg only under --gate: it re-lints the whole package
  # (cheap when LDDL_ANALYZE_CACHE is warm), which a report-only
  # invocation shouldn't pay for.
  conc_rc, conc_count = check_concurrency() if args.gate else (0, 0)
  series = gather_series(args.root, args.history)
  if not series:
    if args.incidents:
      # The incident leg can verdict without bench history: a clean
      # training run may predate any bench rounds, and a tripped
      # sentinel must fail the gate either way.
      print(f'lddl-perf: no bench history under {args.root!r}; '
            'judging incidents only', file=sys.stderr)
      return (incident_rc or audit_rc or conc_rc) if args.gate else 0
    print(f'lddl-perf: no bench history under {args.root!r} '
          '(expected BENCH_r*.json / MULTICHIP_r*.json / '
          'bench_history.jsonl)', file=sys.stderr)
    return 2
  verdicts = [judge_series(name, values, threshold=args.threshold,
                           min_rel_drop=args.min_rel_drop,
                           min_points=args.min_points)
              for name, values in sorted(series.items())]
  regressions = [v for v in verdicts if v['status'] == 'regression']
  if args.as_json:
    out = {'verdicts': verdicts, 'regressions': len(regressions)}
    if args.audit:
      out['audit_exit'] = audit_rc
    if args.incidents:
      out['incidents'] = incident_count
    if args.gate:
      out['concurrency_findings'] = conc_count
    print(json.dumps(out, indent=2))
  else:
    for v in verdicts:
      line = f'{v["status"]:>18}  {v["metric"]}  n={v["points"]}'
      if 'robust_z' in v:
        line += (f'  latest={v["latest"]:g}  median={v["baseline_median"]:g}'
                 f'  z={v["robust_z"]:+.2f}  rel={v["rel_change"]:+.1%}'
                 f'  [{v["direction"]}]')
      print(line)
    if regressions:
      names = ', '.join(v['metric'] for v in regressions)
      print(f'lddl-perf: {len(regressions)} regression(s): {names}',
            file=sys.stderr)
    if args.audit and audit_rc == 0:
      print('lddl-perf: determinism audit ok')
    if args.incidents and incident_rc == 0:
      print(f'lddl-perf: no incidents under {args.incidents}')
    if args.gate and conc_rc == 0:
      print('lddl-perf: concurrency lint clean')
  # One command, one verdict: under --gate a determinism failure or a
  # captured incident is a gate failure exactly like a perf regression
  # (perf's code wins when several fired, so CI triage starts from the
  # regression list).
  rc = 1 if (args.gate and regressions) else 0
  if args.gate and incident_rc and not rc:
    rc = incident_rc
  if args.gate and audit_rc and not rc:
    rc = audit_rc
  if args.gate and conc_rc and not rc:
    rc = conc_rc
  return rc


if __name__ == '__main__':
  sys.exit(main())

"""Sentence segmentation.

The reference depends on nltk's punkt models downloaded at run time
(reference ``lddl/dask/bert/pretrain.py:86,583``). TPU-VM fleets are often
egress-restricted, so the default here is a self-contained rule-based
segmenter; punkt is used transparently when its model data is already
installed.
"""

import re

_ABBREVIATIONS = {
    'mr', 'mrs', 'ms', 'dr', 'prof', 'sr', 'jr', 'st', 'vs', 'etc', 'inc',
    'ltd', 'co', 'corp', 'dept', 'univ', 'assn', 'bros', 'e.g', 'i.e', 'cf',
    'al', 'ave', 'blvd', 'rd', 'fig', 'no', 'vol', 'pp', 'op', 'cit', 'ca',
    'gen', 'col', 'sgt', 'capt', 'lt', 'cmdr', 'adm', 'gov', 'sen', 'rep',
    'rev', 'hon', 'pres', 'supt', 'det', 'mt', 'ft', 'approx',
}

# A sentence ends at [.!?]+ (optionally followed by closing quotes/brackets)
# when followed by whitespace and an upper-case letter, digit, or opening
# quote.
_BOUNDARY = re.compile(r'([.!?]+[\'")\]]*)\s+(?=["\'(\[]?[A-Z0-9])')


def _looks_like_abbreviation(text_before):
  last = text_before.rsplit(None, 1)[-1] if text_before.strip() else ''
  last = last.lstrip('("\'[')
  core = last[:-1] if last.endswith('.') else last
  core_l = core.lower()
  if core_l in _ABBREVIATIONS:
    return True
  # Single capital letter ("A."), or dotted initialisms ("U.S.").
  if len(core) == 1 and core.isalpha() and core.isupper():
    return True
  if re.fullmatch(r'(?:[A-Za-z]\.)+[A-Za-z]?', core):
    return True
  return False


def _rule_based_split(text):
  sentences = []
  start = 0
  for m in _BOUNDARY.finditer(text):
    end = m.end(1)
    if text[end - 1] == '.' or (m.group(1) and m.group(1)[0] == '.'):
      if _looks_like_abbreviation(text[start:end]):
        continue
    piece = text[start:end].strip()
    if piece:
      sentences.append(piece)
    start = m.end()
  tail = text[start:].strip()
  if tail:
    sentences.append(tail)
  return sentences


_nltk_punkt = None


def _try_punkt():
  global _nltk_punkt
  if _nltk_punkt is None:
    try:
      import nltk
      # Probe by actually segmenting: nltk's data requirements differ across
      # versions (punkt vs punkt_tab), so a data.find() check is unreliable.
      nltk.tokenize.sent_tokenize('Probe one. Probe two.')
      _nltk_punkt = nltk.tokenize.sent_tokenize
    except Exception:
      _nltk_punkt = False
  return _nltk_punkt


def resolve_backend(backend='auto'):
  """Resolve 'auto' to the concrete backend this host would use.

  Pipelines must resolve once (and broadcast) before fanning out, so that
  the segmentation — and therefore shard content — never depends on which
  worker host happens to have nltk data installed.
  """
  if backend == 'auto':
    return 'punkt' if _try_punkt() else 'rules'
  return backend


def split_sentences(text, backend='auto'):
  """Split a document into sentences.

  backend: 'auto' (punkt when its data is usable, else rules),
  'punkt', or 'rules'.
  """
  backend = resolve_backend(backend)
  if backend == 'punkt':
    import nltk
    return nltk.tokenize.sent_tokenize(text)
  return _rule_based_split(text)

"""WordPiece tokenization facade.

One tokenizer object flows through preprocessing and loading. Backends:
  - 'hf': HuggingFace ``BertTokenizerFast`` (Rust) constructed from a local
    vocab file or hub name (reference ``lddl/dask/bert/pretrain.py:584-587``).
  - 'native': this repo's C++ trie encoder (``lddl_tpu/native``), used for
    the hot preprocessing loop when built.

The facade exposes exactly what the framework needs: ``tokenize``,
``convert_tokens_to_ids``, id-ordered ``vocab_words`` (for deterministic
random-word masking draws), and the special tokens.
"""

import operator
import os


class BertWordPiece:

  def __init__(self, hf_tokenizer, native_encoder=None):
    self._hf = hf_tokenizer
    self._native = native_encoder
    vocab = hf_tokenizer.get_vocab()
    self._vocab_words = [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])]
    # Local id<->token maps: plain list/dict lookups beat per-call HF
    # round-trips by an order of magnitude in the hot loops.
    self._token_to_id = dict(vocab)
    self._unk_id = self._token_to_id.get(hf_tokenizer.unk_token, 0)

  @property
  def hf(self):
    return self._hf

  @property
  def native(self):
    """The native C++ encoder, or None when running on the HF backend."""
    return self._native

  @property
  def vocab_words(self):
    """Vocabulary tokens ordered by token id."""
    return self._vocab_words

  @property
  def vocab_size(self):
    return len(self._vocab_words)

  @property
  def cls_token(self):
    return self._hf.cls_token

  @property
  def sep_token(self):
    return self._hf.sep_token

  @property
  def mask_token(self):
    return self._hf.mask_token

  @property
  def cls_token_id(self):
    return self._hf.cls_token_id

  @property
  def sep_token_id(self):
    return self._hf.sep_token_id

  @property
  def mask_token_id(self):
    return self._hf.mask_token_id

  @property
  def pad_token_id(self):
    return self._hf.pad_token_id

  def tokenize(self, text, max_length=None):
    if self._native is not None:
      tokens = self._native.tokenize(text)
      return tokens[:max_length] if max_length else tokens
    if max_length is not None:
      return self._hf.tokenize(text, max_length=max_length, truncation=True)
    return self._hf.tokenize(text)

  def batch_tokenize(self, texts, max_length=None):
    """Tokenize many texts in one backend call (the per-call Python overhead
    of ``tokenize`` dominates at corpus scale; reference tokenizes one
    sentence at a time, ``lddl/dask/bert/pretrain.py:79-80``)."""
    if not texts:
      return []
    if self._native is not None:
      out = self._native.batch_tokenize(texts)
      return [t[:max_length] if max_length else t for t in out]
    # Call the Rust tokenizer directly: transformers' BatchEncoding wrapper
    # (_convert_encoding) costs ~25% extra on top of encode_batch itself.
    encodings = self._hf.backend_tokenizer.encode_batch(
        list(texts), add_special_tokens=False)
    words = self._vocab_words
    if max_length is not None:
      return [[words[i] for i in e.ids[:max_length]] for e in encodings]
    return [[words[i] for i in e.ids] for e in encodings]

  def encode_batch_ids(self, texts, max_tokens=None):
    """Tokenize many texts straight to ids.

    Returns (flat int32 ids, int64 [n+1] offsets) — the representation the
    fast preprocess pipeline works in (no Python token strings at all).
    """
    import numpy as np
    if not len(texts):
      return np.zeros(0, np.int32), np.zeros(1, np.int64)
    if self._native is not None:
      return self._native.encode_batch_ids(texts, max_tokens=max_tokens)
    encodings = self._hf.backend_tokenizer.encode_batch(
        list(texts), add_special_tokens=False)
    id_lists = [
        e.ids[:max_tokens] if max_tokens is not None else e.ids
        for e in encodings
    ]
    offsets = np.zeros(len(id_lists) + 1, dtype=np.int64)
    np.cumsum([len(ids) for ids in id_lists], out=offsets[1:])
    total = int(offsets[-1])
    flat = np.fromiter((i for ids in id_lists for i in ids),
                       dtype=np.int32, count=total)
    return flat, offsets

  def decode_join(self, ids, offsets):
    """Inverse of :meth:`encode_batch_ids` into space-joined strings."""
    joiner = self._get_joiner()
    if joiner is not None:
      return joiner.decode_join(ids, offsets)
    words = self._vocab_words
    return [
        ' '.join(words[i] for i in ids[offsets[k]:offsets[k + 1]])
        for k in range(len(offsets) - 1)
    ]

  def decode_join_buffers(self, ids, offsets):
    """ids ranges -> Arrow string-column (offsets, data) buffers, or None
    when the native library is unavailable (callers fall back to
    :meth:`decode_join`)."""
    joiner = self._get_joiner()
    if joiner is None:
      return None
    return joiner.decode_join_buffers(ids, offsets)

  def columnar_emit(self, columns, positions=None):
    """Fused native Arrow-column build (see
    :meth:`lddl_tpu.native.wordpiece.NativeWordPiece.columnar_emit`), or
    ``None`` when the native library is unavailable — callers fall back
    to :meth:`decode_join_buffers` / numpy framing."""
    joiner = self._get_joiner()
    if joiner is None:
      return None
    return joiner.columnar_emit(columns, positions=positions)

  def _get_joiner(self):
    """A native decoder even on the hf backend (built from vocab_words);
    None when the native library cannot be built."""
    if self._native is not None:
      return self._native
    if not hasattr(self, '_joiner'):
      try:
        from ..native import NativeWordPiece
        self._joiner = NativeWordPiece(self._vocab_words, lowercase=False)
      except Exception:
        self._joiner = None
    return self._joiner

  def convert_tokens_to_ids(self, tokens):
    t2i = self._token_to_id
    # itemgetter runs the whole lookup at C speed (~2x a Python listcomp,
    # and this is the loader collate's hottest call); fall back to the
    # .get() path only when some token is actually out-of-vocab.
    if len(tokens) > 1:
      try:
        return list(operator.itemgetter(*tokens)(t2i))
      except KeyError:
        pass
    unk = self._unk_id
    return [t2i.get(t, unk) for t in tokens]

  def get_special_tokens_mask(self, ids):
    return self._hf.get_special_tokens_mask(ids, already_has_special_tokens=True)


def _is_wordpiece_model(hf):
  try:
    return hf.backend_tokenizer.model.__class__.__name__ == 'WordPiece'
  except Exception:
    return False


def load_bert_tokenizer(vocab_file=None, hub_name=None, lowercase=True,
                        backend='auto'):
  """Build a :class:`BertWordPiece` from a local vocab file (preferred on
  egress-restricted TPU fleets) or a hub model name.

  backend:
    'native' — this repo's C++ encoder (raises if it cannot be used);
    'hf'     — HuggingFace fast tokenizer only;
    'auto'   — native when the model is WordPiece and the library builds,
               hf otherwise.

  Hub names resolve through ``AutoTokenizer`` so non-WordPiece checkpoints
  (e.g. ``microsoft/codebert-base``'s RoBERTa BPE) load correctly; local
  ``vocab_file`` always means BERT WordPiece.
  """
  if vocab_file is not None:
    from transformers import BertTokenizerFast
    hf = BertTokenizerFast(
        vocab_file=os.path.abspath(os.path.expanduser(vocab_file)),
        do_lower_case=lowercase)
  elif hub_name is not None:
    from transformers import AutoTokenizer
    hf = AutoTokenizer.from_pretrained(hub_name, use_fast=True,
                                       do_lower_case=lowercase)
    if not hf.is_fast:
      raise ValueError(
          f'{hub_name} produced a slow tokenizer; batch tokenization '
          'requires a fast (Rust) backend')
  else:
    raise ValueError('need vocab_file or hub_name')
  native = None
  if backend == 'native':
    if not _is_wordpiece_model(hf):
      raise ValueError(
          'tokenizer-backend native supports WordPiece models only '
          f'(got {hf.backend_tokenizer.model.__class__.__name__})')
    from ..native import NativeWordPiece
    native = NativeWordPiece.from_hf(hf)
  elif backend == 'auto' and _is_wordpiece_model(hf):
    try:
      from ..native import NativeWordPiece
      native = NativeWordPiece.from_hf(hf)
    except Exception:
      native = None  # no compiler on this host; hf covers correctness
  return BertWordPiece(hf, native_encoder=native)

"""WordPiece tokenization facade.

One tokenizer object flows through preprocessing and loading. Backends:
  - 'hf': HuggingFace ``BertTokenizerFast`` (Rust) constructed from a local
    vocab file or hub name (reference ``lddl/dask/bert/pretrain.py:584-587``).
  - 'native': this repo's C++ trie encoder (``lddl_tpu/native``), used for
    the hot preprocessing loop when built.

The facade exposes exactly what the framework needs: ``tokenize``,
``convert_tokens_to_ids``, id-ordered ``vocab_words`` (for deterministic
random-word masking draws), and the special tokens.
"""

import os


class BertWordPiece:

  def __init__(self, hf_tokenizer, native_encoder=None):
    self._hf = hf_tokenizer
    self._native = native_encoder
    vocab = hf_tokenizer.get_vocab()
    self._vocab_words = [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])]
    # Local id<->token maps: plain list/dict lookups beat per-call HF
    # round-trips by an order of magnitude in the hot loops.
    self._token_to_id = dict(vocab)
    self._unk_id = self._token_to_id.get(hf_tokenizer.unk_token, 0)

  @property
  def hf(self):
    return self._hf

  @property
  def vocab_words(self):
    """Vocabulary tokens ordered by token id."""
    return self._vocab_words

  @property
  def vocab_size(self):
    return len(self._vocab_words)

  @property
  def cls_token(self):
    return self._hf.cls_token

  @property
  def sep_token(self):
    return self._hf.sep_token

  @property
  def mask_token(self):
    return self._hf.mask_token

  @property
  def cls_token_id(self):
    return self._hf.cls_token_id

  @property
  def sep_token_id(self):
    return self._hf.sep_token_id

  @property
  def mask_token_id(self):
    return self._hf.mask_token_id

  @property
  def pad_token_id(self):
    return self._hf.pad_token_id

  def tokenize(self, text, max_length=None):
    if self._native is not None:
      tokens = self._native.tokenize(text)
      return tokens[:max_length] if max_length else tokens
    if max_length is not None:
      return self._hf.tokenize(text, max_length=max_length, truncation=True)
    return self._hf.tokenize(text)

  def batch_tokenize(self, texts, max_length=None):
    """Tokenize many texts in one backend call (the per-call Python overhead
    of ``tokenize`` dominates at corpus scale; reference tokenizes one
    sentence at a time, ``lddl/dask/bert/pretrain.py:79-80``)."""
    if not texts:
      return []
    if self._native is not None:
      out = self._native.batch_tokenize(texts)
      return [t[:max_length] if max_length else t for t in out]
    # Call the Rust tokenizer directly: transformers' BatchEncoding wrapper
    # (_convert_encoding) costs ~25% extra on top of encode_batch itself.
    encodings = self._hf.backend_tokenizer.encode_batch(
        list(texts), add_special_tokens=False)
    words = self._vocab_words
    if max_length is not None:
      return [[words[i] for i in e.ids[:max_length]] for e in encodings]
    return [[words[i] for i in e.ids] for e in encodings]

  def convert_tokens_to_ids(self, tokens):
    t2i, unk = self._token_to_id, self._unk_id
    return [t2i.get(t, unk) for t in tokens]

  def get_special_tokens_mask(self, ids):
    return self._hf.get_special_tokens_mask(ids, already_has_special_tokens=True)


def load_bert_tokenizer(vocab_file=None, hub_name=None, lowercase=True,
                        backend='hf'):
  """Build a :class:`BertWordPiece` from a local vocab file (preferred on
  egress-restricted TPU fleets) or a hub model name."""
  from transformers import BertTokenizerFast
  if vocab_file is not None:
    hf = BertTokenizerFast(
        vocab_file=os.path.abspath(os.path.expanduser(vocab_file)),
        do_lower_case=lowercase)
  elif hub_name is not None:
    hf = BertTokenizerFast.from_pretrained(hub_name, do_lower_case=lowercase)
  else:
    raise ValueError('need vocab_file or hub_name')
  native = None
  if backend == 'native':
    from ..native import wordpiece as native_wp
    native = native_wp.NativeWordPiece.from_hf(hf)
  return BertWordPiece(hf, native_encoder=native)

from .sentences import split_sentences
from .wordpiece import BertWordPiece, load_bert_tokenizer

"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context support the reference lacks entirely (SURVEY.md §2.2: no
sequence/context parallelism anywhere in LDDL). Each device holds a
``[batch, heads, seq/N, head_dim]`` shard of Q, K, V; K/V blocks (and the
key-side mask) rotate around the ``seq`` ring via ``lax.ppermute`` over
ICI neighbors while a streaming log-sum-exp accumulator keeps the softmax
exact — full K/V is never materialized on any chip, so max sequence length
scales linearly with the ring size at constant per-chip memory.

Numerics: scores and accumulators run in float32 regardless of input
dtype (bfloat16 Q/K/V is fine); output is cast back to the input dtype.

Usage: call :func:`ring_attention` *inside* ``jax.shard_map`` (it uses the
collective axis name), or use :func:`make_ring_attention` to wrap it for a
mesh and call it from jitted GSPMD code.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _block_attn(q, k, v, bias, scale):
  """One block's contribution: returns (scores_max, exp_scores @ v, denom)."""
  s = jnp.einsum('bhqd,bhkd->bhqk', q, k, preferred_element_type=jnp.float32)
  s = s * scale
  if bias is not None:
    s = s + bias
  m = jnp.max(s, axis=-1, keepdims=True)
  p = jnp.exp(s - m)
  o = jnp.einsum('bhqk,bhkd->bhqd', p, v.astype(jnp.float32))
  return m, o, jnp.sum(p, axis=-1, keepdims=True)


def ring_attention(q, k, v, kv_mask=None, axis_name='seq',
                   block_impl='dense'):
  """Exact softmax attention with K/V sharded along ``axis_name``.

  Shapes (per-device shards): q,k,v ``[b, h, s_block, d]``; ``kv_mask``
  ``[b, s_block]`` with 1 = attend, 0 = padding (it rotates with K/V).
  Must run inside ``shard_map`` with ``axis_name`` bound.

  ``block_impl``: the per-chip block-attention kernel — 'dense' (einsum;
  materializes the per-shard score matrix) or 'flash'
  (:func:`lddl_tpu.ops.flash_attention.flash_attention_with_lse`; the
  flash (out, lse) pair enters the streaming-softmax merge as
  ``(m=lse, o=out, l=1)``, keeping per-chip attention memory O(block^2)
  on top of ring's cross-chip O(s/N) sharding).
  """
  n = lax.axis_size(axis_name)
  scale = 1.0 / (q.shape[-1] ** 0.5)
  qf = q.astype(jnp.float32)
  neg = jnp.float32(-1e9)

  def bias_of(mask):
    if mask is None:
      return None
    return jnp.where(mask, 0.0, neg)[:, None, None, :].astype(jnp.float32)

  if block_impl == 'flash':
    from ..ops.flash_attention import flash_attention_with_lse

    def block(k_blk, v_blk, mask_blk):
      out, lse = flash_attention_with_lse(q, k_blk, v_blk, mask_blk)
      # Flash output is already normalized by its own denominator:
      # (m=lse, o=out, l=1) merges exactly — exp(lse - M) * out carries
      # the true exp(m - M) * unnormalized sum.
      lse = lse[..., None]
      return lse, out.astype(jnp.float32), jnp.ones_like(lse)
  elif block_impl == 'dense':
    def block(k_blk, v_blk, mask_blk):
      return _block_attn(qf, k_blk, v_blk, bias_of(mask_blk), scale)
  else:
    raise ValueError(f'unknown block_impl {block_impl!r}')

  perm = [(i, (i + 1) % n) for i in range(n)]

  def body(i, carry):
    del i
    k_blk, v_blk, mask_blk, m_acc, o_acc, l_acc = carry
    m_blk, o_blk, l_blk = block(k_blk, v_blk, mask_blk)
    m_new = jnp.maximum(m_acc, m_blk)
    alpha = jnp.exp(m_acc - m_new)
    beta = jnp.exp(m_blk - m_new)
    o_acc = o_acc * alpha + o_blk * beta
    l_acc = l_acc * alpha + l_blk * beta
    k_blk = lax.ppermute(k_blk, axis_name, perm)
    v_blk = lax.ppermute(v_blk, axis_name, perm)
    if mask_blk is not None:
      mask_blk = lax.ppermute(mask_blk, axis_name, perm)
    return k_blk, v_blk, mask_blk, m_new, o_acc, l_acc

  b, h, s, d = q.shape
  m0 = jnp.full((b, h, s, 1), -jnp.inf, dtype=jnp.float32)
  o0 = jnp.zeros((b, h, s, d), dtype=jnp.float32)
  l0 = jnp.zeros((b, h, s, 1), dtype=jnp.float32)
  carry = (k, v, kv_mask, m0, o0, l0)
  if n == 1:
    carry = body(0, carry)
    _, _, _, _, o_acc, l_acc = carry
  else:
    _, _, _, _, o_acc, l_acc = lax.fori_loop(0, n, body, carry)
  return (o_acc / jnp.maximum(l_acc, 1e-20)).astype(q.dtype)


def make_ring_attention(mesh, q_spec=None, mask_spec=None, axis_name='seq',
                        block_impl='dense'):
  """Wrap :func:`ring_attention` in ``shard_map`` for use from jitted code.

  ``q_spec`` defaults to ``P(('data','fsdp'), 'tensor', 'seq', None)`` —
  batch over dp, heads over tensor parallelism, sequence over the ring.
  ``block_impl='flash'`` runs each chip's block attention as the Pallas
  flash kernel.
  """
  q_spec = q_spec or P(('data', 'fsdp'), 'tensor', axis_name, None)
  mask_spec = mask_spec or P(('data', 'fsdp'), axis_name)

  @functools.partial(
      jax.shard_map,
      mesh=mesh,
      in_specs=(q_spec, q_spec, q_spec, mask_spec),
      out_specs=q_spec,
      check_vma=False)
  def _sharded(q, k, v, kv_mask):
    return ring_attention(q, k, v, kv_mask, axis_name=axis_name,
                          block_impl=block_impl)

  return _sharded

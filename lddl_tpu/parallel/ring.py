"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context support the reference lacks entirely (SURVEY.md §2.2: no
sequence/context parallelism anywhere in LDDL). Each device holds a
``[batch, heads, seq/N, head_dim]`` shard of Q, K, V; K/V blocks (and the
key-side mask) rotate around the ``seq`` ring via ``lax.ppermute`` over
ICI neighbors while a streaming log-sum-exp accumulator keeps the softmax
exact — full K/V is never materialized on any chip, so max sequence length
scales linearly with the ring size at constant per-chip memory.

Block-diagonal packed rows compose with the ring: per-token kv segment
ids (doc index, -1 = padding) rotate alongside K/V, and a rotated shard
whose doc-id interval is disjoint from the local q shard's is skipped
*before* the local block kernel runs — the ppermute still fires (the
ring rotation is collective) but the chip spends no attention FLOPs on
a shard it provably can't attend to. Shards that partially overlap fall
through to the local flash kernel, which skips at (q-block, kv-block)
tile granularity (:mod:`lddl_tpu.ops.flash_attention`).

Numerics: scores and accumulators run in float32 regardless of input
dtype (bfloat16 Q/K/V is fine); output is cast back to the input dtype.

Usage: call :func:`ring_attention` *inside* ``shard_map`` (it uses the
collective axis name), or use :func:`make_ring_attention` to wrap it for a
mesh and call it from jitted GSPMD code.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.compat import axis_size


def _block_attn(q, k, v, bias, scale):
  """One block's contribution: returns (scores_max, exp_scores @ v, denom)."""
  s = jnp.einsum('bhqd,bhkd->bhqk', q, k, preferred_element_type=jnp.float32)
  s = s * scale
  if bias is not None:
    s = s + bias
  m = jnp.max(s, axis=-1, keepdims=True)
  p = jnp.exp(s - m)
  o = jnp.einsum('bhqk,bhkd->bhqd', p, v.astype(jnp.float32))
  return m, o, jnp.sum(p, axis=-1, keepdims=True)


def _shard_interval(seg):
  """Per-batch-row (lo, hi) doc-id interval of a ``[b, s_shard]`` segment
  shard. Padding (-1) is excluded from ``lo`` and drags ``hi`` to -1, so
  an all-padding shard reports an empty interval (lo > hi) and tests
  disjoint against everything."""
  real = seg >= 0
  lo = jnp.min(jnp.where(real, seg, jnp.int32(2**30)), axis=1)
  hi = jnp.max(jnp.where(real, seg, jnp.int32(-1)), axis=1)
  return lo, hi


def ring_attention(q, k, v, kv_mask=None, axis_name='seq',
                   block_impl='dense', q_segment_ids=None,
                   kv_segment_ids=None):
  """Exact softmax attention with K/V sharded along ``axis_name``.

  Shapes (per-device shards): q,k,v ``[b, h, s_block, d]``; ``kv_mask``
  ``[b, s_block]`` with 1 = attend, 0 = padding (it rotates with K/V).
  Must run inside ``shard_map`` with ``axis_name`` bound.

  ``block_impl``: the per-chip block-attention kernel — 'dense' (einsum;
  materializes the per-shard score matrix) or 'flash'
  (:func:`lddl_tpu.ops.flash_attention.flash_attention_with_lse`; the
  flash (out, lse) pair enters the streaming-softmax merge as
  ``(m=lse, o=out, l=1)``, keeping per-chip attention memory O(block^2)
  on top of ring's cross-chip O(s/N) sharding).

  ``q_segment_ids`` / ``kv_segment_ids``: optional ``[b, s_block]``
  int32 per-token doc ids (-1 = padding) restricting attention to
  same-document pairs. The kv ids rotate with K/V; a rotated shard whose
  id interval is disjoint from the local q shard's contributes an exact
  zero and is skipped without running the block kernel.
  """
  if (q_segment_ids is None) != (kv_segment_ids is None):
    raise ValueError('q_segment_ids and kv_segment_ids must be given '
                     'together')
  n = axis_size(axis_name)
  scale = 1.0 / (q.shape[-1] ** 0.5)
  qf = q.astype(jnp.float32)
  neg = jnp.float32(-1e9)

  def bias_of(mask, kv_seg):
    bias = None
    if mask is not None:
      bias = jnp.where(mask, 0.0, neg)[:, None, None, :].astype(jnp.float32)
    if kv_seg is not None:
      same = q_segment_ids[:, None, :, None] == kv_seg[:, None, None, :]
      seg_bias = jnp.where(same, 0.0, neg)
      bias = seg_bias if bias is None else bias + seg_bias
    return bias

  if block_impl == 'flash':
    from ..ops.flash_attention import flash_attention_with_lse

    def block(k_blk, v_blk, mask_blk, kv_seg_blk):
      out, lse = flash_attention_with_lse(
          q, k_blk, v_blk, mask_blk,
          q_segment_ids if kv_seg_blk is not None else None, kv_seg_blk)
      # Flash output is already normalized by its own denominator:
      # (m=lse, o=out, l=1) merges exactly — exp(lse - M) * out carries
      # the true exp(m - M) * unnormalized sum.
      lse = lse[..., None]
      return lse, out.astype(jnp.float32), jnp.ones_like(lse)
  elif block_impl == 'dense':
    def block(k_blk, v_blk, mask_blk, kv_seg_blk):
      return _block_attn(qf, k_blk, v_blk, bias_of(mask_blk, kv_seg_blk),
                         scale)
  else:
    raise ValueError(f'unknown block_impl {block_impl!r}')

  b, h, s, d = q.shape

  if q_segment_ids is not None:
    q_lo, q_hi = _shard_interval(q_segment_ids)

    def guarded_block(k_blk, v_blk, mask_blk, kv_seg_blk):
      kv_lo, kv_hi = _shard_interval(kv_seg_blk)
      live = jnp.any((q_lo <= kv_hi) & (kv_lo <= q_hi))

      def skip(_):
        # Finite -1e9 max (not -inf): against the -inf initial
        # accumulator, exp(-inf - -inf) would be NaN in the merge.
        return (jnp.full((b, h, s, 1), neg),
                jnp.zeros((b, h, s, d), jnp.float32),
                jnp.zeros((b, h, s, 1), jnp.float32))

      return lax.cond(live,
                      lambda _: block(k_blk, v_blk, mask_blk, kv_seg_blk),
                      skip, operand=None)
  else:
    guarded_block = block

  perm = [(i, (i + 1) % n) for i in range(n)]

  def body(i, carry):
    del i
    k_blk, v_blk, mask_blk, kv_seg_blk, m_acc, o_acc, l_acc = carry
    m_blk, o_blk, l_blk = guarded_block(k_blk, v_blk, mask_blk, kv_seg_blk)
    m_new = jnp.maximum(m_acc, m_blk)
    alpha = jnp.exp(m_acc - m_new)
    beta = jnp.exp(m_blk - m_new)
    o_acc = o_acc * alpha + o_blk * beta
    l_acc = l_acc * alpha + l_blk * beta
    k_blk = lax.ppermute(k_blk, axis_name, perm)
    v_blk = lax.ppermute(v_blk, axis_name, perm)
    if mask_blk is not None:
      mask_blk = lax.ppermute(mask_blk, axis_name, perm)
    if kv_seg_blk is not None:
      kv_seg_blk = lax.ppermute(kv_seg_blk, axis_name, perm)
    return k_blk, v_blk, mask_blk, kv_seg_blk, m_new, o_acc, l_acc

  m0 = jnp.full((b, h, s, 1), -jnp.inf, dtype=jnp.float32)
  o0 = jnp.zeros((b, h, s, d), dtype=jnp.float32)
  l0 = jnp.zeros((b, h, s, 1), dtype=jnp.float32)
  carry = (k, v, kv_mask, kv_segment_ids, m0, o0, l0)
  if n == 1:
    carry = body(0, carry)
    _, _, _, _, _, o_acc, l_acc = carry
  else:
    _, _, _, _, _, o_acc, l_acc = lax.fori_loop(0, n, body, carry)
  return (o_acc / jnp.maximum(l_acc, 1e-20)).astype(q.dtype)


def make_ring_attention(mesh, q_spec=None, mask_spec=None, axis_name='seq',
                        block_impl='dense', with_segment_ids=False):
  """Wrap :func:`ring_attention` in ``shard_map`` for use from jitted code.

  ``q_spec`` defaults to ``P(('data','fsdp'), 'tensor', 'seq', None)`` —
  batch over dp, heads over tensor parallelism, sequence over the ring.
  ``block_impl='flash'`` runs each chip's block attention as the Pallas
  flash kernel. ``with_segment_ids=True`` returns a wrapper taking an
  extra ``segment_ids`` ``[batch, seq]`` operand (used for both q and
  kv — self-attention), sharded like the mask.
  """
  from ..core.compat import shard_map
  q_spec = q_spec or P(('data', 'fsdp'), 'tensor', axis_name, None)
  mask_spec = mask_spec or P(('data', 'fsdp'), axis_name)

  if with_segment_ids:
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(q_spec, q_spec, q_spec, mask_spec, mask_spec),
        out_specs=q_spec,
        check=False)
    def _sharded_seg(q, k, v, kv_mask, segment_ids):
      return ring_attention(q, k, v, kv_mask, axis_name=axis_name,
                            block_impl=block_impl,
                            q_segment_ids=segment_ids,
                            kv_segment_ids=segment_ids)

    return _sharded_seg

  @functools.partial(
      shard_map,
      mesh=mesh,
      in_specs=(q_spec, q_spec, q_spec, mask_spec),
      out_specs=q_spec,
      check=False)
  def _sharded(q, k, v, kv_mask):
    return ring_attention(q, k, v, kv_mask, axis_name=axis_name,
                          block_impl=block_impl)

  return _sharded

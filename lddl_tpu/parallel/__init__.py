"""Device-mesh parallelism: mesh construction, sharding rules, ring
attention for sequence/context parallelism, and the sharded train step.

The reference has no model-side parallelism of its own — its torch_mp
frontend merely *feeds* Megatron TP/PP groups (reference
``lddl/torch_mp/bert.py:217-223``). Here the training side is first-class:
a ``jax.sharding.Mesh`` with data / fsdp / tensor / sequence axes, XLA
collectives over ICI, and ring attention for long-context scaling.
"""

from .mesh import (MESH_AXES, batch_pspec, canonical_batch_spec, make_mesh,
                   match_partition_rules, mesh_summary, reshard_pytree)
from .ring import ring_attention
from .train import (init_params, make_scan_train_step, make_train_step,
                    shard_batch, snapshot_for_checkpoint,
                    stack_batch_window)

__all__ = [
    'MESH_AXES', 'batch_pspec', 'canonical_batch_spec', 'make_mesh',
    'match_partition_rules', 'mesh_summary', 'reshard_pytree',
    'ring_attention', 'init_params', 'make_train_step',
    'make_scan_train_step', 'shard_batch', 'snapshot_for_checkpoint',
    'stack_batch_window'
]

"""Sharded BERT-pretraining train step.

The reference stops at the DataLoader boundary; its consumers (NVIDIA BERT
training recipes) own the step. Here the step is part of the framework so
the binned loader's static-shape contract can be demonstrated end-to-end:
one jitted program per bin shape, params laid out by
:func:`lddl_tpu.models.spec_for_param` over the
(data, fsdp, tensor, seq) mesh, gradients reduced by GSPMD over ICI.

Loss = masked-LM cross entropy (ignore label -100, mean over masked
positions) + next-sentence-prediction cross entropy — the standard BERT
pretraining objective over exactly the dict the loader yields.
"""

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..loader.bert import IGNORE_INDEX
from ..models import spec_for_param
from .mesh import canonical_batch_spec


def param_shardings(mesh, abs_params):
  """NamedSharding tree for a (possibly abstract) param tree."""
  flat = jax.tree_util.tree_flatten_with_path(abs_params)[0]
  tree = jax.tree_util.tree_structure(abs_params)
  shardings = [
      NamedSharding(mesh,
                    spec_for_param([getattr(k, 'key', k) for k in path],
                                   leaf.shape)) for path, leaf in flat
  ]
  return jax.tree_util.tree_unflatten(tree, shardings)


def init_params(model, mesh, rng, seq_len=128, batch=2):
  """Initialize params directly into their mesh placement: the init
  computation is jitted with ``out_shardings`` so no single device ever
  holds the full parameter set."""
  dummy = {
      'input_ids': jnp.zeros((batch, seq_len), jnp.int32),
      'token_type_ids': jnp.zeros((batch, seq_len), jnp.int32),
      'attention_mask': jnp.ones((batch, seq_len), jnp.int32),
  }

  def init_fn():
    return model.init(rng, dummy['input_ids'], dummy['token_type_ids'],
                      dummy['attention_mask'])['params']

  abs_params = jax.eval_shape(init_fn)
  shardings = param_shardings(mesh, abs_params)
  return jax.jit(init_fn, out_shardings=shardings)()


def snapshot_for_checkpoint(tree):
  """Donation-safe copy of a state pytree for background checkpointing.

  :func:`make_train_step` donates params/opt_state, so the *next* step
  call invalidates the buffers a background checkpoint writer would
  still be serializing. The snapshot must therefore happen
  synchronously at submit time: fully-addressable leaves come back as
  host numpy arrays (the single-host case — orbax then serializes host
  memory and never touches the donated originals); multi-host global
  arrays get an on-device copy that preserves their sharding in fresh
  buffers, so donating the originals is harmless. Non-array leaves
  pass through.
  """

  def _copy(x):
    if not isinstance(x, jax.Array):
      return x
    if x.is_fully_addressable:
      return jax.device_get(x)
    return jnp.copy(x)

  return jax.tree_util.tree_map(_copy, tree)


def per_doc_mlm_loss(mlm_ce, masked, seg, num_docs_cap):
  """Packing-aware MLM normalization (arXiv:2107.02027 §3.2).

  The naive packed loss is a masked-token mean over the whole batch,
  which weights a document by its masked-token count — long documents
  dominate, and the objective drifts from what the same documents would
  contribute trained unpacked (each sequence normalized by its own mask
  count). Here each document contributes its own masked-mean CE and the
  batch loss is the mean over documents with >= 1 MLM target, so packed
  and unpacked training optimize the same per-sequence objective.

  ``seg``: doc index per column (aligned with ``mlm_ce``/``masked``;
  callers using the masked-only head gather it at ``mlm_positions``).
  ``num_docs_cap``: static upper bound on docs per row (the sequence
  length serves — doc ids are strictly below it).
  """
  b = masked.shape[0]
  ids = (jnp.clip(seg, 0, num_docs_cap - 1) +
         num_docs_cap * jnp.arange(b, dtype=seg.dtype)[:, None])
  w = masked.astype(jnp.float32).reshape(-1)
  ce_sum = jax.ops.segment_sum(mlm_ce.reshape(-1) * w, ids.reshape(-1),
                               num_segments=b * num_docs_cap)
  cnt = jax.ops.segment_sum(w, ids.reshape(-1),
                            num_segments=b * num_docs_cap)
  has = cnt > 0
  per_doc = jnp.where(has, ce_sum / jnp.maximum(cnt, 1.0), 0.0)
  return per_doc.sum() / jnp.maximum(has.sum(), 1)


def pretrain_loss(model, params, batch, dropout_rng=None,
                  max_predictions=None):
  """Scalar loss + metrics dict for one batch.

  ``max_predictions=P`` selects the masked-only MLM head: the first P
  masked positions per row are gathered and only their ``[b, P, V]``
  logits are computed — numerically the same MLM cross entropy (CE is
  only ever evaluated at masked positions), at a fraction of the head
  FLOPs/HBM. Choose P at least the masking budget: static masking caps
  predictions at round(s·ratio)(+cap) so any P >= that bound is exact;
  dynamic masking is Bernoulli per position, so rows in the far binomial
  tail (> P masked) would silently drop their overflow targets — size P
  with headroom there.

  A ``segment_ids`` batch key (packed loader, ``block_diagonal=True``)
  switches on both block-diagonal attention in the model and the
  :func:`per_doc_mlm_loss` normalization.
  """
  deterministic = dropout_rng is None
  rngs = None if deterministic else {'dropout': dropout_rng}
  labels = batch['labels']
  mlm_positions = None
  if max_predictions is not None:
    # The first P masked column indices per row, padded with arbitrary
    # unmasked columns whose gathered labels are IGNORE_INDEX (stable
    # argsort of the ~masked bitmap = masked columns first, in order).
    p = min(max_predictions, labels.shape[1])
    mlm_positions = jnp.argsort(
        labels == IGNORE_INDEX, axis=1, stable=True,
    )[:, :p].astype(jnp.int32)
    labels = jnp.take_along_axis(labels, mlm_positions, axis=1)
  segment_ids = batch.get('segment_ids')
  mlm_logits, nsp_logits = model.apply(
      {'params': params},
      batch['input_ids'],
      batch['token_type_ids'],
      batch['attention_mask'],
      deterministic=deterministic,
      mlm_positions=mlm_positions,
      segment_ids=segment_ids,
      rngs=rngs)
  masked = labels != IGNORE_INDEX
  safe_labels = jnp.where(masked, labels, 0)
  mlm_ce = optax.softmax_cross_entropy_with_integer_labels(
      mlm_logits, safe_labels)
  denom = jnp.maximum(masked.sum(), 1)
  if segment_ids is not None:
    seg = segment_ids
    if mlm_positions is not None:
      seg = jnp.take_along_axis(segment_ids, mlm_positions, axis=1)
    mlm_loss = per_doc_mlm_loss(mlm_ce, masked, seg,
                                num_docs_cap=batch['input_ids'].shape[1])
  else:
    mlm_loss = jnp.where(masked, mlm_ce, 0.0).sum() / denom
  nsp_loss = optax.softmax_cross_entropy_with_integer_labels(
      nsp_logits, batch['next_sentence_labels']).mean()
  mlm_acc = jnp.where(masked,
                      jnp.argmax(mlm_logits, -1) == labels, False).sum() / denom
  return mlm_loss + nsp_loss, {
      'mlm_loss': mlm_loss,
      'nsp_loss': nsp_loss,
      'mlm_acc': mlm_acc,
  }


def _train_step_body(model, tx, params, opt_state, rng, batch,
                     max_predictions=None):
  """One un-jitted train step — the single definition both
  :func:`make_train_step` and :func:`make_scan_train_step` compile, so the
  per-step and scan-window paths stay provably identical."""
  rng = jax.random.fold_in(
      rng, opt_state[0].count if hasattr(opt_state[0], 'count') else 0)

  def loss_fn(p):
    return pretrain_loss(model, p, batch, dropout_rng=rng,
                         max_predictions=max_predictions)

  (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
  # Global gradient norm of the *raw* grads (pre-optimizer): one fused
  # reduction inside the compiled step, read on the host for free once
  # the loss scalar has already forced the device sync. This is the
  # sentinel's grad_spike signal and the train.grad_norm gauge.
  metrics['grad_norm'] = optax.global_norm(grads)
  updates, opt_state = tx.update(grads, opt_state, params)
  params = optax.apply_updates(params, updates)
  metrics['loss'] = loss
  return params, opt_state, metrics


def check_max_predictions(max_predictions, seq_len, masking,
                          mlm_probability=0.15):
  """Warn when a masked-only head budget under-covers the masking mode.

  Static masking caps per-row predictions at ``round(s·ratio)(+1)``;
  dynamic masking is per-position Bernoulli, so its count has a binomial
  tail — require ~4 standard deviations of headroom before calling the
  budget safe. An under-sized P silently drops the overflow targets from
  loss and gradients, which is exactly the quiet failure this warning
  exists to surface.
  """
  budget = round(seq_len * mlm_probability) + 1
  if masking == 'dynamic':
    sd = (seq_len * mlm_probability * (1 - mlm_probability)) ** 0.5
    budget = int(seq_len * mlm_probability + 4 * sd) + 1
  if max_predictions < min(budget, seq_len):
    import warnings
    warnings.warn(
        f'max_predictions={max_predictions} is below the {masking}-masking '
        f'budget ~{budget} for seq_len {seq_len}: rows with more masked '
        'positions silently drop their overflow MLM targets from the loss')


def make_train_step(model, tx, mesh, max_predictions=None):
  """Returns ``step(params, opt_state, rng, batch) ->
  (params, opt_state, metrics)``, jitted with donated state.

  Batches arrive sharded ``P(('data','fsdp'), 'seq')`` (the loader's
  device pipeline does this); params carry their own shardings from
  :func:`init_params`, so jit needs no in_shardings — placement is taken
  from the arguments and GSPMD inserts every collective.
  ``max_predictions`` selects the masked-only MLM head (see
  :func:`pretrain_loss`).
  """

  @functools.partial(jax.jit, donate_argnums=(0, 1))
  def step(params, opt_state, rng, batch):
    return _train_step_body(model, tx, params, opt_state, rng, batch,
                            max_predictions)

  return step


def make_scan_train_step(model, tx, mesh, max_predictions=None):
  """Returns ``run(params, opt_state, rng, batches) ->
  (params, opt_state, last_metrics)`` where every array in ``batches``
  carries a leading steps axis: one compiled program executes the whole
  window via ``lax.scan``, so per-step dispatch cost amortizes across the
  window.

  This is the measurement mode a dispatch-latency-bound link needs (a
  tunneled or remote chip pays ~tens of ms per program launch): with K
  steps in one program, launch cost is paid once per window instead of
  once per step, so the observed step time converges to device compute
  time. It is also the idiomatic shape for production TPU training loops
  (device-resident multi-batch windows).
  """

  @functools.partial(jax.jit, donate_argnums=(0, 1))
  def run(params, opt_state, rng, batches):

    def body(carry, batch):
      params, opt_state, metrics = _train_step_body(model, tx, carry[0],
                                                    carry[1], rng, batch,
                                                    max_predictions)
      return (params, opt_state), metrics

    (params, opt_state), metrics = jax.lax.scan(body, (params, opt_state),
                                                batches)
    return params, opt_state, jax.tree.map(lambda m: m[-1], metrics)

  return run


def stack_batch_window(batches, mesh):
  """Stack K host batch dicts into one device-resident window with a
  leading steps axis (replicated over the mesh; each step's slice keeps
  the canonical batch layout)."""
  import numpy as np
  stacked = {
      k: np.stack([b[k] for b in batches]) for k in batches[0]
  }
  return {
      k: jax.device_put(
          v,
          NamedSharding(
              mesh,
              P(None, *canonical_batch_spec(mesh, v.shape[1:]))))
      for k, v in stacked.items()
  }


def shard_batch(batch, mesh):
  """Place a host batch dict onto the mesh with the canonical data layout."""
  return {
      k: jax.device_put(
          v, NamedSharding(mesh, canonical_batch_spec(mesh, v.shape)))
      for k, v in batch.items()
  }

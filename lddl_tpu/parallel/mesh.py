"""Mesh construction and canonical sharding specs.

Axes (any can be size 1):
  data    pure data parallelism (gradients all-reduced over ICI/DCN)
  fsdp    data parallelism with parameter/optimizer sharding (ZeRO-style);
          batch is sharded over (data, fsdp) jointly
  tensor  tensor (Megatron-style) parallelism inside attention/MLP blocks
  seq     sequence/context parallelism: activations sharded along sequence,
          attention runs as a ring over this axis

The reference's dp_rank-feeding contract (all model-parallel ranks of one
data-parallel group receive identical batches, ``torch_mp/bert.py:217-223``)
holds here by construction: the loader shards batches as
``P(('data','fsdp'), 'seq')`` and XLA replicates them over ``tensor``.
"""

import collections
import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ('data', 'fsdp', 'tensor', 'seq')


def make_mesh(data=1, fsdp=1, tensor=1, seq=1, devices=None):
  """Build a Mesh over ``devices`` (default: all).

  Any leftover device factor is folded into ``data`` when data is left at
  its default, so ``make_mesh()`` is pure data parallelism over every chip.
  Axis order puts ``tensor`` and ``seq`` innermost, where ICI neighbors
  are, so the high-bandwidth collectives (tensor all-reduces, ring
  permutes) ride the fastest links.
  """
  devices = np.asarray(devices if devices is not None else jax.devices())
  n = devices.size
  model = fsdp * tensor * seq
  if data == 1 and n % model == 0:
    data = n // model
  if data * model != n:
    raise ValueError(
        f'mesh data={data} fsdp={fsdp} tensor={tensor} seq={seq} != {n} '
        'devices')
  return Mesh(
      devices.reshape(data, fsdp, tensor, seq), MESH_AXES)


def batch_pspec(ndim=2, seq_dim=1):
  """PartitionSpec for a [batch, seq, ...] array: batch over (data, fsdp),
  sequence over seq, trailing dims replicated."""
  spec = [None] * ndim
  spec[0] = ('data', 'fsdp')
  if seq_dim is not None and ndim > seq_dim:
    spec[seq_dim] = 'seq'
  return P(*spec)


def batch_sharding(mesh, ndim=2, seq_dim=1):
  return NamedSharding(mesh, batch_pspec(ndim, seq_dim))


def canonical_batch_spec(mesh, shape, data_axis=None, seq_axis=None):
  """:func:`batch_pspec` restricted to what ``mesh`` and ``shape`` allow.

  The single source of truth for placing one batch array: dim 0 over the
  data axes the mesh actually has (``('data','fsdp')`` filtered to present
  axes, else the mesh's first axis), dim 1 over ``seq`` only when the dim
  is divisible by the seq-axis size (auxiliary 2-D arrays — padded
  position lists etc. — are replicated along seq instead of erroring),
  trailing dims replicated. ``data_axis`` (str or tuple) / ``seq_axis``
  override; ``seq_axis=False`` forbids seq sharding.
  """
  names = set(mesh.axis_names)
  if data_axis is None:
    present = tuple(a for a in ('data', 'fsdp') if a in names)
    data_axis = present if present else mesh.axis_names[0]
  if seq_axis is None and 'seq' in names:
    seq_axis = 'seq'
  if seq_axis:
    axes = (seq_axis,) if isinstance(seq_axis, str) else tuple(seq_axis)
    seq_size = int(np.prod([mesh.shape[a] for a in axes]))
  else:
    seq_axis, seq_size = None, 1
  spec = [None] * len(shape)
  spec[0] = data_axis
  if seq_axis is not None and len(shape) > 1 and shape[1] % seq_size == 0:
    spec[1] = seq_axis
  return P(*spec)


def mesh_summary(mesh):
  shape = collections.OrderedDict(zip(mesh.axis_names, mesh.devices.shape))
  return ', '.join(f'{k}={v}' for k, v in shape.items())


def _leaf_name(path):
  """'/'-joined tree path of one pytree leaf (dict keys, attribute
  names, and sequence indices all stringify)."""
  parts = []
  for p in path:
    for attr in ('key', 'name', 'idx'):
      v = getattr(p, attr, None)
      if v is not None:
        parts.append(str(v))
        break
    else:
      parts.append(str(p))
  return '/'.join(parts)


def match_partition_rules(rules, tree):
  """Map every leaf of ``tree`` to a ``PartitionSpec`` by regex rules.

  ``rules`` is an ordered ``[(pattern, PartitionSpec), ...]``; each
  leaf's '/'-joined tree path is searched against the patterns in order
  and the first match wins — the rescalable-placement idiom of the
  DrJAX-style resharding resume (PAPERS.md, arXiv:2403.07128), where a
  checkpoint restored onto a *different* mesh re-derives every leaf's
  layout from its name instead of from the dead run's device topology.
  Scalar (0-d) leaves are replicated without consulting the rules; a
  non-scalar leaf no rule matches raises — silently replicating a large
  tensor is exactly the quiet OOM this API exists to prevent.
  """
  from jax.tree_util import tree_flatten_with_path, tree_unflatten
  flat, treedef = tree_flatten_with_path(tree)
  specs = []
  for path, leaf in flat:
    if getattr(leaf, 'ndim', 0) == 0:
      specs.append(P())
      continue
    name = _leaf_name(path)
    for pattern, spec in rules:
      if re.search(pattern, name):
        specs.append(spec)
        break
    else:
      raise ValueError(f'no partition rule matches leaf {name!r}')
  return tree_unflatten(treedef, specs)


def reshard_pytree(tree, mesh, like=None, rules=None):
  """Re-place every leaf of ``tree`` onto ``mesh``.

  The world-size-resharding primitive of checkpoint restore: state
  written on one mesh is laid out onto the (possibly differently sized
  or shaped) mesh of the resumed run. Placement comes from exactly one
  of:

  - ``like``: a template tree already living on ``mesh`` — each leaf
    adopts the matching template leaf's sharding (the restore path,
    where ``TrainLoop.build`` has already produced the new mesh's
    canonical layout);
  - ``rules``: ``[(regex, PartitionSpec), ...]`` resolved by
    :func:`match_partition_rules` against leaf tree paths.
  """
  if (like is None) == (rules is None):
    raise ValueError('pass exactly one of like= / rules=')
  if like is not None:
    return jax.tree_util.tree_map(
        lambda n, o: jax.device_put(n, o.sharding), tree, like)
  specs = match_partition_rules(rules, tree)
  return jax.tree_util.tree_map(
      lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)

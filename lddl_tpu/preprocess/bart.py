"""BART pretraining preprocessor.

Lighter pipeline than BERT's (reference ``lddl/dask/bart/pretrain.py``):
sentence-split each document, then greedily aggregate sentences into
chunks whose whitespace-token count reaches ``target_seq_length - 3``
(reference ``_aggregate_sentences``, ``bart/pretrain.py:88-128``); no
tokenizer, no masking, no binning. Output schema matches the reference
(``bart/pretrain.py:136-152``): one ``sentences`` string column.

The denoising noise itself (span infilling etc.) is applied at load time
by the trainer, not here — same division of labor as the reference.
"""

import argparse
import dataclasses
import functools
import time

import pyarrow as pa

from ..pipeline.executor import Executor
from ..pipeline.parquet_io import write_samples_partition
from ..pipeline.pool import current_writer
from ..pipeline.shuffle import gather_partition
from ..tokenization import split_sentences
from .common import run_shuffled
from .readers import read_corpus, split_id_text


def aggregate_sentences(sentences, target_seq_length):
  """Greedy chunks of sentences by whitespace token count (reference
  ``bart/pretrain.py:88-128``; the -3 accounts for [CLS]/[SEP]/[SEP])."""
  results = []
  target = target_seq_length - 3
  chunk, num_tokens = '', 0
  for sentence in sentences:
    chunk += ' ' + sentence
    num_tokens += len(sentence.split())
    if num_tokens >= target:
      results.append({'sentences': chunk, 'num_tokens': num_tokens})
      chunk, num_tokens = '', 0
  if num_tokens > 0:
    results.append({'sentences': chunk, 'num_tokens': num_tokens})
  return results


def sequences_from_lines(lines, target_seq_length, sentence_backend='rules'):
  out = []
  for line in lines:
    _, text = split_id_text(line)
    if not text:
      continue
    sents = [s.strip() for s in split_sentences(text, backend=sentence_backend)]
    out.extend(aggregate_sentences([s for s in sents if s],
                                   target_seq_length))
  return out


BART_SCHEMA = pa.schema([('sentences', pa.string())])


@dataclasses.dataclass(frozen=True)
class BartPretrainConfig:
  target_seq_length: int = 128
  sentence_backend: str = 'rules'
  seed: int = 12345
  output_format: str = 'parquet'


def _process_partition(tgt_idx, global_idx, spill_dir, out_dir, cfg):
  del global_idx
  lines = gather_partition(tgt_idx, spill_dir, cfg.seed)
  seqs = sequences_from_lines(
      lines, cfg.target_seq_length, sentence_backend=cfg.sentence_backend)
  rows = [{'sentences': s['sentences']} for s in seqs]
  out = write_samples_partition(
      rows, BART_SCHEMA, out_dir, tgt_idx, output_format=cfg.output_format,
      writer=current_writer())
  return {b: n for b, (_, n) in out.items()}


def run(corpus, sink_dir, cfg, executor=None, num_shuffle_partitions=None):
  """Shuffle -> aggregate -> Parquet shards; returns per-partition counts."""
  return run_shuffled(
      corpus,
      sink_dir,
      functools.partial(_process_partition, out_dir=sink_dir, cfg=cfg),
      cfg.seed,
      executor=executor,
      num_shuffle_partitions=num_shuffle_partitions)


def attach_args(parser):
  parser.add_argument('--wikipedia', type=str, default=None)
  parser.add_argument('--books', type=str, default=None)
  parser.add_argument('--common-crawl', type=str, default=None)
  parser.add_argument('--open-webtext', type=str, default=None)
  parser.add_argument('--source', type=str, default=None)
  parser.add_argument('--sink', type=str, required=True)
  parser.add_argument('--num-blocks', type=int, default=None)
  parser.add_argument('--block-size', type=str, default=None)
  parser.add_argument('--sample-ratio', type=float, default=0.9)
  parser.add_argument('--seed', type=int, default=12345)
  parser.add_argument('--target-seq-length', type=int, default=128)
  parser.add_argument('--sentence-backend', type=str, default='auto',
                      choices=['auto', 'punkt', 'rules'])
  parser.add_argument('--output-format', type=str, default='parquet',
                      choices=['parquet', 'txt'])
  parser.add_argument('--num-workers', type=int, default=None)
  parser.add_argument('--comm', type=str, default='null',
                      choices=['null', 'file', 'jax'])
  return parser


def main(args=None):
  parser = attach_args(
      argparse.ArgumentParser(
          description=__doc__,
          formatter_class=argparse.ArgumentDefaultsHelpFormatter))
  args = parser.parse_args(args)
  from ..comm import get_backend
  from ..core.utils import parse_str_of_num_bytes
  dirs = [
      d for d in (args.wikipedia, args.books, args.common_crawl,
                  args.open_webtext, args.source) if d is not None
  ]
  if not dirs:
    parser.error('need at least one source dir')
  comm = get_backend(args.comm)
  executor = Executor(comm=comm, num_local_workers=args.num_workers)
  corpus = read_corpus(
      dirs,
      num_blocks=args.num_blocks or 4 * executor.num_local_workers *
      comm.world_size,
      block_size=(parse_str_of_num_bytes(args.block_size)
                  if args.block_size else None),
      sample_ratio=args.sample_ratio,
      sample_seed=args.seed,
  )
  backend = args.sentence_backend
  if backend == 'auto':
    from ..tokenization.sentences import resolve_backend
    backend = comm.broadcast_object(resolve_backend(), root=0)
  cfg = BartPretrainConfig(
      target_seq_length=args.target_seq_length,
      sentence_backend=backend,
      seed=args.seed,
      output_format=args.output_format)
  t0 = time.perf_counter()
  counts = run(corpus, args.sink, cfg, executor=executor)
  if comm.rank == 0:
    total = sum(n for c in counts for n in c.values())
    print(f'preprocessed {total} sequences into {len(counts)} partitions '
          f'in {time.perf_counter() - t0:.1f}s')


if __name__ == '__main__':
  main()

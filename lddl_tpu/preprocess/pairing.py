"""Offset-based NSP pair planning.

``plan_pairs_from_document`` is a draw-for-draw mirror of
``bert.create_pairs_from_document`` (the reference recipe,
``lddl/dask/bert/pretrain.py:241-365``) that operates on flat token-id
arrays + sentence offsets instead of Python token lists. Because chunk
sentences are consecutive, every segment is a contiguous *range* into the
flat id array — pairing becomes integer bookkeeping, and the expensive
token-list splicing of the reference disappears entirely.

Given the same ``rng``, the planned (A, B, is_random_next) are identical
to the materialized pairs of the slow path (tested:
``tests/test_fast_pipeline.py``).
"""

import numpy as np


class TokenizedDocs:
  """Partition of documents as one flat id array + offsets.

  flat_ids: int32 [total_tokens]
  sent_offsets: int64 [n_sents + 1] — token ranges per sentence
  doc_sent_start: int64 [n_docs + 1] — sentence index ranges per doc
  (documents with zero sentences must already be dropped).
  """

  __slots__ = ('flat_ids', 'sent_offsets', 'doc_sent_start')

  def __init__(self, flat_ids, sent_offsets, doc_counts):
    self.flat_ids = np.ascontiguousarray(flat_ids, dtype=np.int32)
    self.sent_offsets = np.ascontiguousarray(sent_offsets, dtype=np.int64)
    doc_counts = np.asarray(doc_counts, dtype=np.int64)
    if (doc_counts == 0).any():
      raise ValueError('drop zero-sentence documents before planning')
    self.doc_sent_start = np.zeros(len(doc_counts) + 1, dtype=np.int64)
    np.cumsum(doc_counts, out=self.doc_sent_start[1:])

  def __len__(self):
    return len(self.doc_sent_start) - 1

  def num_sentences(self, d):
    return int(self.doc_sent_start[d + 1] - self.doc_sent_start[d])


def _truncate_counters(la, lb, max_num_tokens, rng):
  """Mirror of ``truncate_seq_pair``: returns (front_a, back_a, front_b,
  back_b) removal counts with the identical rng draw sequence."""
  fa = ba = fb = bb = 0
  while la + lb > max_num_tokens:
    if la > lb:
      if rng.random() < 0.5:
        fa += 1
      else:
        ba += 1
      la -= 1
    else:
      if rng.random() < 0.5:
        fb += 1
      else:
        bb += 1
      lb -= 1
  return fa, ba, fb, bb


def plan_pairs_from_document(docs, document_index, rng, out,
                             max_seq_length=128, short_seq_prob=0.1):
  """Plan pairs for one document, appending (a0, a1, b0, b1, is_random)
  tuples to ``out``. Draw-for-draw mirror of
  ``bert.create_pairs_from_document``."""
  soff = docs.sent_offsets
  ds = docs.doc_sent_start[document_index]
  n_sent = int(docs.doc_sent_start[document_index + 1] - ds)
  max_num_tokens = max_seq_length - 3
  target_seq_length = max_num_tokens
  if rng.random() < short_seq_prob:
    target_seq_length = rng.randint(2, max_num_tokens)

  chunk_first = 0  # sentence index (doc-local) of first sentence in chunk
  chunk_n = 0
  chunk_len = 0
  i = 0
  while i < n_sent:
    if chunk_n == 0:
      chunk_first = i
    sent_len = int(soff[ds + i + 1] - soff[ds + i])
    chunk_n += 1
    chunk_len += sent_len
    if i == n_sent - 1 or chunk_len >= target_seq_length:
      if chunk_n:
        a_end = 1 if chunk_n < 2 else rng.randint(1, chunk_n - 1)
        a0 = int(soff[ds + chunk_first])
        a1 = int(soff[ds + chunk_first + a_end])
        la = a1 - a0
        if chunk_n == 1 or rng.random() < 0.5:
          is_random_next = True
          target_b_length = target_seq_length - la
          random_document_index = document_index
          for _ in range(10):
            candidate = rng.randint(0, len(docs) - 1)
            if candidate != document_index:
              random_document_index = candidate
              break
          if random_document_index == document_index:
            is_random_next = False
          rds = docs.doc_sent_start[random_document_index]
          rn = int(docs.doc_sent_start[random_document_index + 1] - rds)
          start = rng.randint(0, rn - 1)
          # First sentence j >= start where cumulative length reaches
          # target_b_length (the slow path always takes >= 1 sentence).
          b0 = int(soff[rds + start])
          ends = soff[rds + start + 1:rds + rn + 1]
          j = int(np.searchsorted(ends, b0 + max(target_b_length, 1)))
          j = min(j, rn - start - 1)
          b1 = int(ends[j])
          # Unused trailing chunk sentences are replayed.
          i -= chunk_n - a_end
        else:
          is_random_next = False
          b0 = a1
          b1 = int(soff[ds + chunk_first + chunk_n])
        lb = b1 - b0
        fa, ba, fb, bb = _truncate_counters(la, lb, max_num_tokens, rng)
        a0 += fa
        a1 -= ba
        b0 += fb
        b1 -= bb
        if a1 > a0 and b1 > b0:
          out.append((a0, a1, b0, b1, is_random_next))
      chunk_n = 0
      chunk_len = 0
    i += 1


_NATIVE_PLANNER = None  # unresolved; False once probing failed


def _native_planner():
  """Resolve the native planner once per process; None when the native
  toolchain is unavailable (first failure warns, then stays on Python)."""
  global _NATIVE_PLANNER
  if _NATIVE_PLANNER is None:
    import os
    if os.environ.get('LDDL_PAIRING') == 'python':
      _NATIVE_PLANNER = False
    else:
      try:
        from ..native.build import load_library
        from ..native.pairing import plan_pairs_partition_native
        load_library()  # g++ build happens here, inside the guard
        _NATIVE_PLANNER = plan_pairs_partition_native
      except Exception as e:  # no g++ / build failure
        import warnings
        warnings.warn(f'native pair planner unavailable ({e}); '
                      'planning pairs in Python')
        _NATIVE_PLANNER = False
  return _NATIVE_PLANNER or None


def plan_pairs_partition(docs, rng, max_seq_length=128, short_seq_prob=0.1,
                         duplicate_factor=1, backend='auto'):
  """Plan all pairs of a partition (``duplicate_factor`` passes over all
  documents, like the slow path's outer loop).

  Returns (a_ranges int64 [n,2], b_ranges int64 [n,2], is_random_next
  bool [n]). ``backend='auto'`` uses the native planner when buildable
  (bit-identical outputs and rng stream — ``src/pairing.cpp``; set env
  ``LDDL_PAIRING=python`` to force the Python path); 'python' forces this
  module's loop.
  """
  if max_seq_length < 5:
    # The short-seq draw is randint(2, max_seq_length - 3); below 5 the
    # range is empty and CPython raises — validate up front so the native
    # planner (which cannot raise mid-plan) never sees the degenerate
    # config.
    raise ValueError(f'max_seq_length must be >= 5, got {max_seq_length}')
  if backend == 'auto':
    native = _native_planner()
    if native is not None:
      return native(docs, rng, max_seq_length=max_seq_length,
                    short_seq_prob=short_seq_prob,
                    duplicate_factor=duplicate_factor)
  out = []
  for _ in range(duplicate_factor):
    for di in range(len(docs)):
      plan_pairs_from_document(docs, di, rng, out,
                               max_seq_length=max_seq_length,
                               short_seq_prob=short_seq_prob)
  if not out:
    empty = np.zeros((0, 2), dtype=np.int64)
    return empty, empty.copy(), np.zeros(0, dtype=bool)
  arr = np.asarray(out, dtype=np.int64)
  return (arr[:, 0:2].copy(), arr[:, 2:4].copy(),
          arr[:, 4].astype(bool))

"""CodeBERT pretraining preprocessor (bimodal docstring/code pairs).

Capability parity: the fork's ``lddl/dask/bert/pretrain_codebert.py``.
Input: CRLF-delimited ``id<CODESPLIT>docstring<CODESPLIT>code`` records
(see :func:`lddl_tpu.preprocess.readers.read_code`). Per record
(reference ``pretrain_codebert.py:343-442``):

  - docstring and code are each split into line "sentences" and tokenized;
  - a *doc segment* is built from leading docstring lines, capped at
    ``64 if max_seq_length >= 512 else 32`` tokens (with 10% probability
    just the first docstring line — the short-seq analogue);
  - code lines slide through chunks: a chunk flushes when it would exceed
    ``max_seq_length - doc_len - specials``, emitting one instance, and
    the overflowing last line carries over into the next chunk so long
    functions yield multiple overlapping pairs;
  - chunks shorter than 16 code tokens are dropped (except the first);
  - output schema {id, doc, code, num_tokens}, optionally binned the same
    way as BERT shards. MLM masks are applied dynamically at load time.

Unlike the reference (unseeded global ``random`` in Dask workers), every
draw threads a per-partition RNG: reruns are deterministic.
"""

import argparse
import dataclasses
import functools
import time

import pyarrow as pa

from ..core import attach_bool_arg
from ..core.random import rng_from_key
from ..pipeline.executor import Executor
from ..pipeline.parquet_io import write_samples_partition, write_table_partition
from ..pipeline.pool import current_writer
from ..pipeline.shard_format import (DELTA, MATERIALIZED, tag_schema,
                                     tag_table)
from ..pipeline.shuffle import gather_partition
from .common import run_shuffled
from .readers import read_code, split_id_code_docstring

MIN_CODE_TOKENS = 16


@dataclasses.dataclass(frozen=True)
class CodeDocument:
  doc_id: str
  doc_segments: tuple  # tuple of token tuples (docstring lines)
  code_segments: tuple  # tuple of token tuples (code lines)


def truncate_seq(tokens, max_num_tokens, rng):
  """Random front/back pops until the sequence fits (reference
  ``pretrain_codebert.py:236-247``)."""
  while len(tokens) > max_num_tokens:
    if rng.random() < 0.5:
      del tokens[0]
    else:
      tokens.pop()


def _parse_records(records):
  """Shared record parsing: (doc_id, n_doc, n_code) triples plus the flat
  list of line strings in tokenization order."""
  parsed = []
  all_strs = []
  for rec in records:
    split = split_id_code_docstring(rec)
    if split is None:
      continue
    doc_id, docstring, code = split
    doc_lines = [s.strip() for s in docstring.split('\n')]
    doc_lines = [s for s in doc_lines if s]
    code_lines = [s.strip() for s in code.split('\n')]
    code_lines = [s for s in code_lines if s]
    parsed.append((doc_id, len(doc_lines), len(code_lines)))
    all_strs.extend(doc_lines)
    all_strs.extend(code_lines)
  return parsed, all_strs


def documents_from_records(records, tokenizer, max_length=512):
  """Parse + batch-tokenize bimodal records into CodeDocuments."""
  parsed, all_strs = _parse_records(records)
  all_tokens = tokenizer.batch_tokenize(all_strs, max_length=max_length)
  documents, pos = [], 0
  for doc_id, n_doc, n_code in parsed:
    doc_toks = tuple(
        tuple(t) for t in all_tokens[pos:pos + n_doc] if t)
    pos += n_doc
    code_toks = tuple(
        tuple(t) for t in all_tokens[pos:pos + n_code] if t)
    pos += n_code
    if code_toks:
      documents.append(CodeDocument(doc_id, doc_toks, code_toks))
  return documents


def documents_from_records_ids(records, tokenizer, max_length=512):
  """Id-range variant of :func:`documents_from_records` for the fused
  columnar path: the same token stream, but segments stay ``(start, end)``
  ranges into one flat int32 id array — no Python token strings. Returns
  ``(documents, flat_ids)``. A document's kept segments are contiguous in
  ``flat_ids`` (dropped empty lines have zero width), which is what lets
  the pairing below concatenate segments by merging ranges."""
  parsed, all_strs = _parse_records(records)
  flat, offsets = tokenizer.encode_batch_ids(all_strs, max_tokens=max_length)
  documents, pos = [], 0
  for doc_id, n_doc, n_code in parsed:
    doc_segs = tuple(
        (int(offsets[k]), int(offsets[k + 1]))
        for k in range(pos, pos + n_doc)
        if offsets[k + 1] > offsets[k])
    pos += n_doc
    code_segs = tuple(
        (int(offsets[k]), int(offsets[k + 1]))
        for k in range(pos, pos + n_code)
        if offsets[k + 1] > offsets[k])
    pos += n_code
    if code_segs:
      documents.append(CodeDocument(doc_id, doc_segs, code_segs))
  return documents, flat


def build_doc_segment(document, max_doc_seq_length, short_seq_prob, rng):
  """Leading docstring lines capped at max_doc_seq_length tokens; with
  probability short_seq_prob just the first line (reference
  ``pretrain_codebert.py:369-398``)."""
  segs = document.doc_segments
  if not segs:
    return []
  if rng.random() < short_seq_prob:
    doc_tokens = list(segs[0])
  else:
    doc_tokens = []
    chunk, length = [], 0
    for i, seg in enumerate(segs):
      chunk.append(seg)
      length += len(seg)
      if i == len(segs) - 1 or length > max_doc_seq_length:
        end = len(chunk) - 1 if (length > max_doc_seq_length and
                                 len(chunk) > 1) else len(chunk)
        for s in chunk[:end]:
          doc_tokens.extend(s)
        break
  truncate_seq(doc_tokens, max_doc_seq_length, rng)
  return doc_tokens


def create_pairs_from_document(document, rng, max_seq_length=512,
                               short_seq_prob=0.1):
  """Sliding code-chunk pairing with carry-over (reference
  ``pretrain_codebert.py:343-442``)."""
  special = 3 if document.doc_segments else 2
  max_num_tokens = max_seq_length - special
  max_doc_seq_length = 64 if max_seq_length >= 512 else 32
  doc_tokens = build_doc_segment(document, max_doc_seq_length,
                                 short_seq_prob, rng)
  doc_len = len(doc_tokens)
  target = max_num_tokens

  instances = []
  chunk, length = [], doc_len
  for i, seg in enumerate(document.code_segments):
    chunk.append(seg)
    length += len(seg)
    if i == len(document.code_segments) - 1 or length > target:
      if chunk:
        carry = (length > max_num_tokens and len(chunk) > 1)
        code_tokens = [t for s in chunk for t in s]
        truncate_seq(code_tokens, max_num_tokens - doc_len, rng)
        if code_tokens and (not instances or
                            len(code_tokens) >= MIN_CODE_TOKENS):
          instances.append({
              'id': document.doc_id,
              'doc': ' '.join(doc_tokens),
              'code': ' '.join(code_tokens),
              'num_tokens': doc_len + len(code_tokens) + special,
          })
        chunk = [chunk[-1]] if carry else []
        length = sum(len(s) for s in chunk) + doc_len
  return instances


def truncate_range(start, end, max_num_tokens, rng):
  """Range form of :func:`truncate_seq`: the draw sequence depends only on
  the current length, so trimming endpoints consumes exactly the same rng
  stream as popping list elements."""
  while end - start > max_num_tokens:
    if rng.random() < 0.5:
      start += 1
    else:
      end -= 1
  return start, end


def build_doc_range(document, max_doc_seq_length, short_seq_prob, rng):
  """Range form of :func:`build_doc_segment` over contiguous id segments."""
  segs = document.doc_segments
  if not segs:
    return 0, 0
  if rng.random() < short_seq_prob:
    start, end = segs[0]
  else:
    chunk_n, length = 0, 0
    for i, (s, e) in enumerate(segs):
      chunk_n += 1
      length += e - s
      if i == len(segs) - 1 or length > max_doc_seq_length:
        last = chunk_n - 1 if (length > max_doc_seq_length and
                               chunk_n > 1) else chunk_n
        start, end = segs[0][0], segs[last - 1][1]
        break
  return truncate_range(start, end, max_doc_seq_length, rng)


def create_pair_ranges(document, rng, max_seq_length=512,
                       short_seq_prob=0.1):
  """Range form of :func:`create_pairs_from_document`: identical draws and
  carry-over semantics, but yields ``((doc_start, doc_end),
  (code_start, code_end), num_tokens)`` triples into the flat id array
  instead of materialized string dicts."""
  special = 3 if document.doc_segments else 2
  max_num_tokens = max_seq_length - special
  max_doc_seq_length = 64 if max_seq_length >= 512 else 32
  ds, de = build_doc_range(document, max_doc_seq_length, short_seq_prob, rng)
  doc_len = de - ds
  target = max_num_tokens

  pairs = []
  segs = document.code_segments
  first, count, length = 0, 0, doc_len
  for i, (s, e) in enumerate(segs):
    if count == 0:
      first = i
    count += 1
    length += e - s
    if i == len(segs) - 1 or length > target:
      carry = (length > max_num_tokens and count > 1)
      cs, ce = truncate_range(segs[first][0], segs[i][1],
                              max_num_tokens - doc_len, rng)
      if ce > cs and (not pairs or ce - cs >= MIN_CODE_TOKENS):
        pairs.append(((ds, de), (cs, ce), doc_len + (ce - cs) + special))
      if carry:
        first, count, length = i, 1, doc_len + (e - s)
      else:
        count, length = 0, doc_len
  return pairs


CODEBERT_SCHEMA = pa.schema([
    ('id', pa.string()),
    ('doc', pa.string()),
    ('code', pa.string()),
    ('num_tokens', pa.uint16()),
])


@dataclasses.dataclass(frozen=True)
class CodebertPretrainConfig:
  vocab_file: str = None
  tokenizer_name: str = 'microsoft/codebert-base'
  tokenizer_backend: str = 'hf'
  lowercase: bool = False  # code is case-sensitive; codebert-base is cased
  target_seq_length: int = 512
  short_seq_prob: float = 0.1
  duplicate_factor: int = 1
  bin_size: int = None
  seed: int = 12345
  output_format: str = 'parquet'
  # 'auto' resolves to 'delta' for duplicate_factor>1 (one stored pass,
  # expanded by the loader; dynamic masking differentiates the copies).
  shard_format: str = 'auto'

  @property
  def nbins(self):
    if self.bin_size is None:
      return None
    if self.target_seq_length % self.bin_size != 0:
      raise ValueError('bin_size must divide target_seq_length')
    return self.target_seq_length // self.bin_size


def resolve_shard_format(cfg):
  """'auto' -> 'delta' iff ``duplicate_factor > 1``.

  CodeBERT masks dynamically at load time, so the materialized dup loop
  only re-plans the same records with a continuing rng (slightly jittered
  chunking per pass). The delta format stores one pass and lets the
  loader expand each row ``duplicate_factor`` times — the copies share
  the pairing and are differentiated by the dynamic mask draw, which is
  what the duplicate-factor recipe is for.
  """
  fmt = cfg.shard_format
  if fmt == 'auto':
    return DELTA if cfg.duplicate_factor > 1 else MATERIALIZED
  if fmt not in (MATERIALIZED, DELTA):
    raise ValueError(f'unknown shard format {fmt!r}')
  return fmt


def _get_tokenizer(cfg):
  from .common import get_cached_tokenizer
  return get_cached_tokenizer(
      vocab_file=cfg.vocab_file,
      hub_name=None if cfg.vocab_file else cfg.tokenizer_name,
      lowercase=cfg.lowercase,
      backend=cfg.tokenizer_backend)


def _warmup_worker(cfg):
  """Persistent-pool warmup hook: cache the tokenizer in each worker
  before its first task (see bert._warmup_worker)."""
  tokenizer = _get_tokenizer(cfg)
  tokenizer.batch_tokenize(['warmup'])


def _columnar_available(tokenizer):
  """True when the fused native columnar path can run: exercises the real
  ``LDDL_NATIVE_COLUMNAR`` gate + native-library probe on an empty column,
  so the path decision happens before any rng draw."""
  import numpy as np

  from .common import fused_string_columns
  return fused_string_columns(
      tokenizer, [(np.zeros(0, np.int32), np.zeros(1, np.int64))]) is not None


def _build_partition_table(records, tokenizer, rng, cfg):
  """Fused fast path: pair ranges over one flat id array -> a single native
  columnar emit for the doc/code columns -> Arrow table. No id->string
  decode in Python and no per-instance dicts; shards are byte-identical to
  the dict path (same tokenization caps, same rng draw sequence, same
  schema and column order)."""
  import numpy as np

  from ..ops.masking import ragged_indices
  from .common import fused_string_columns

  fmt = resolve_shard_format(cfg)
  passes = 1 if fmt == DELTA else cfg.duplicate_factor
  documents, flat = documents_from_records_ids(
      records, tokenizer, max_length=cfg.target_seq_length)
  ids_col, triples = [], []
  for _ in range(passes):
    for document in documents:
      for tr in create_pair_ranges(document, rng,
                                   max_seq_length=cfg.target_seq_length,
                                   short_seq_prob=cfg.short_seq_prob):
        ids_col.append(document.doc_id)
        triples.append(tr)
  if not triples:
    return tag_table(CODEBERT_SCHEMA.empty_table(), fmt,
                     cfg.duplicate_factor)

  def _flatten(ranges):
    ranges = np.asarray(ranges, dtype=np.int64)
    lens = ranges[:, 1] - ranges[:, 0]
    offs = np.zeros(len(ranges) + 1, dtype=np.int64)
    np.cumsum(lens, out=offs[1:])
    row, col = ragged_indices(lens)
    return flat[ranges[row, 0] + col], offs

  doc_flat, doc_offs = _flatten([t[0] for t in triples])
  code_flat, code_offs = _flatten([t[1] for t in triples])
  emitted = fused_string_columns(
      tokenizer, [(doc_flat, doc_offs), (code_flat, code_offs)])
  if emitted is not None:
    (string_parts, _) = emitted

    def _col(part):
      oo, dd = part
      return pa.StringArray.from_buffers(
          len(oo) - 1, pa.py_buffer(oo), pa.py_buffer(dd))

    doc_col, code_col = _col(string_parts[0]), _col(string_parts[1])
  else:  # native vanished between probe and emit; decode in Python
    doc_col = pa.array(tokenizer.decode_join(doc_flat, doc_offs),
                       type=pa.string())
    code_col = pa.array(tokenizer.decode_join(code_flat, code_offs),
                        type=pa.string())
  return tag_table(
      pa.table({
          'id': pa.array(ids_col, type=pa.string()),
          'doc': doc_col,
          'code': code_col,
          'num_tokens': pa.array([t[2] for t in triples], type=pa.uint16()),
      }), fmt, cfg.duplicate_factor)


def _process_partition(tgt_idx, global_idx, spill_dir, out_dir, cfg,
                       delimiter='\r\n'):
  del global_idx
  tokenizer = _get_tokenizer(cfg)
  records = gather_partition(tgt_idx, spill_dir, cfg.seed,
                             delimiter=delimiter)
  rng = rng_from_key(cfg.seed, 'code-pairs', tgt_idx)
  if _columnar_available(tokenizer):
    table = _build_partition_table(records, tokenizer, rng, cfg)
    out = write_table_partition(
        table,
        out_dir,
        tgt_idx,
        bin_size=cfg.bin_size,
        nbins=cfg.nbins,
        output_format=cfg.output_format,
        writer=current_writer(),
    )
    return {b: n for b, (_, n) in out.items()}
  documents = documents_from_records(records, tokenizer,
                                     max_length=cfg.target_seq_length)
  fmt = resolve_shard_format(cfg)
  passes = 1 if fmt == DELTA else cfg.duplicate_factor
  instances = []
  for _ in range(passes):
    for document in documents:
      instances.extend(
          create_pairs_from_document(
              document,
              rng,
              max_seq_length=cfg.target_seq_length,
              short_seq_prob=cfg.short_seq_prob))
  out = write_samples_partition(
      instances,
      tag_schema(CODEBERT_SCHEMA, fmt, cfg.duplicate_factor),
      out_dir,
      tgt_idx,
      bin_size=cfg.bin_size,
      nbins=cfg.nbins,
      output_format=cfg.output_format,
      writer=current_writer(),
  )
  return {b: n for b, (_, n) in out.items()}


def run(corpus, sink_dir, cfg, executor=None, num_shuffle_partitions=None):
  # The gather delimiter must match what scatter wrote: the corpus's own.
  return run_shuffled(
      corpus,
      sink_dir,
      functools.partial(_process_partition, out_dir=sink_dir, cfg=cfg,
                        delimiter=corpus.delimiter),
      cfg.seed,
      executor=executor,
      num_shuffle_partitions=num_shuffle_partitions,
      warmup=functools.partial(_warmup_worker, cfg),
      warmup_key=('codebert-warmup', cfg))


def attach_args(parser):
  parser.add_argument('--source', type=str, required=True,
                      help='dir of CRLF-delimited <CODESPLIT> shards')
  parser.add_argument('--sink', type=str, required=True)
  parser.add_argument('--num-blocks', type=int, default=None)
  parser.add_argument('--block-size', type=str, default=None)
  parser.add_argument('--sample-ratio', type=float, default=1.0)
  parser.add_argument('--seed', type=int, default=12345)
  parser.add_argument('--vocab-file', type=str, default=None)
  parser.add_argument('--tokenizer', type=str,
                      default='microsoft/codebert-base')
  parser.add_argument('--tokenizer-backend', type=str, default='hf',
                      choices=['hf', 'native'])
  attach_bool_arg(parser, 'lowercase', default=False,
                  help_str='lowercase code (codebert-base is cased)')
  parser.add_argument('--target-seq-length', type=int, default=512)
  parser.add_argument('--short-seq-prob', type=float, default=0.1)
  parser.add_argument('--duplicate-factor', type=int, default=1)
  parser.add_argument('--shard-format', type=str, default='auto',
                      choices=['auto', 'materialized', 'delta'],
                      help='delta stores one pairing pass and the loader '
                      'expands it duplicate_factor times (dynamic masking '
                      'differentiates copies); auto: delta iff '
                      'duplicate_factor>1')
  parser.add_argument('--bin-size', type=int, default=None)
  parser.add_argument('--output-format', type=str, default='parquet',
                      choices=['parquet', 'txt'])
  parser.add_argument('--num-workers', type=int, default=None)
  parser.add_argument('--comm', type=str, default='null',
                      choices=['null', 'file', 'jax'])
  attach_bool_arg(parser, 'verbose', default=False)
  return parser


def main(args=None):
  parser = attach_args(
      argparse.ArgumentParser(
          description=__doc__,
          formatter_class=argparse.ArgumentDefaultsHelpFormatter))
  args = parser.parse_args(args)
  from ..comm import get_backend
  from ..core.utils import parse_str_of_num_bytes
  comm = get_backend(args.comm)
  executor = Executor(comm=comm, num_local_workers=args.num_workers)
  corpus = read_code(
      args.source,
      num_blocks=args.num_blocks or 4 * executor.num_local_workers *
      comm.world_size,
      block_size=(parse_str_of_num_bytes(args.block_size)
                  if args.block_size else None),
      sample_ratio=args.sample_ratio,
      sample_seed=args.seed,
  )
  cfg = CodebertPretrainConfig(
      vocab_file=args.vocab_file,
      tokenizer_name=args.tokenizer,
      tokenizer_backend=args.tokenizer_backend,
      lowercase=args.lowercase,
      target_seq_length=args.target_seq_length,
      short_seq_prob=args.short_seq_prob,
      duplicate_factor=args.duplicate_factor,
      bin_size=args.bin_size,
      seed=args.seed,
      output_format=args.output_format,
      shard_format=args.shard_format)
  t0 = time.perf_counter()
  counts = run(corpus, args.sink, cfg, executor=executor)
  if comm.rank == 0:
    total = sum(n for c in counts for n in c.values())
    print(f'preprocessed {total} pairs into {len(counts)} partitions '
          f'in {time.perf_counter() - t0:.1f}s')


if __name__ == '__main__':
  main()

"""CodeBERT pretraining preprocessor (bimodal docstring/code pairs).

Capability parity: the fork's ``lddl/dask/bert/pretrain_codebert.py``.
Input: CRLF-delimited ``id<CODESPLIT>docstring<CODESPLIT>code`` records
(see :func:`lddl_tpu.preprocess.readers.read_code`). Per record
(reference ``pretrain_codebert.py:343-442``):

  - docstring and code are each split into line "sentences" and tokenized;
  - a *doc segment* is built from leading docstring lines, capped at
    ``64 if max_seq_length >= 512 else 32`` tokens (with 10% probability
    just the first docstring line — the short-seq analogue);
  - code lines slide through chunks: a chunk flushes when it would exceed
    ``max_seq_length - doc_len - specials``, emitting one instance, and
    the overflowing last line carries over into the next chunk so long
    functions yield multiple overlapping pairs;
  - chunks shorter than 16 code tokens are dropped (except the first);
  - output schema {id, doc, code, num_tokens}, optionally binned the same
    way as BERT shards. MLM masks are applied dynamically at load time.

Unlike the reference (unseeded global ``random`` in Dask workers), every
draw threads a per-partition RNG: reruns are deterministic.
"""

import argparse
import dataclasses
import functools
import time

import pyarrow as pa

from ..core import attach_bool_arg
from ..core.random import rng_from_key
from ..pipeline.executor import Executor
from ..pipeline.parquet_io import write_samples_partition
from ..pipeline.pool import current_writer
from ..pipeline.shuffle import gather_partition
from .common import run_shuffled
from .readers import read_code, split_id_code_docstring

MIN_CODE_TOKENS = 16


@dataclasses.dataclass(frozen=True)
class CodeDocument:
  doc_id: str
  doc_segments: tuple  # tuple of token tuples (docstring lines)
  code_segments: tuple  # tuple of token tuples (code lines)


def truncate_seq(tokens, max_num_tokens, rng):
  """Random front/back pops until the sequence fits (reference
  ``pretrain_codebert.py:236-247``)."""
  while len(tokens) > max_num_tokens:
    if rng.random() < 0.5:
      del tokens[0]
    else:
      tokens.pop()


def documents_from_records(records, tokenizer, max_length=512):
  """Parse + batch-tokenize bimodal records into CodeDocuments."""
  parsed = []
  all_strs = []
  for rec in records:
    split = split_id_code_docstring(rec)
    if split is None:
      continue
    doc_id, docstring, code = split
    doc_lines = [s.strip() for s in docstring.split('\n')]
    doc_lines = [s for s in doc_lines if s]
    code_lines = [s.strip() for s in code.split('\n')]
    code_lines = [s for s in code_lines if s]
    parsed.append((doc_id, len(doc_lines), len(code_lines)))
    all_strs.extend(doc_lines)
    all_strs.extend(code_lines)
  all_tokens = tokenizer.batch_tokenize(all_strs, max_length=max_length)
  documents, pos = [], 0
  for doc_id, n_doc, n_code in parsed:
    doc_toks = tuple(
        tuple(t) for t in all_tokens[pos:pos + n_doc] if t)
    pos += n_doc
    code_toks = tuple(
        tuple(t) for t in all_tokens[pos:pos + n_code] if t)
    pos += n_code
    if code_toks:
      documents.append(CodeDocument(doc_id, doc_toks, code_toks))
  return documents


def build_doc_segment(document, max_doc_seq_length, short_seq_prob, rng):
  """Leading docstring lines capped at max_doc_seq_length tokens; with
  probability short_seq_prob just the first line (reference
  ``pretrain_codebert.py:369-398``)."""
  segs = document.doc_segments
  if not segs:
    return []
  if rng.random() < short_seq_prob:
    doc_tokens = list(segs[0])
  else:
    doc_tokens = []
    chunk, length = [], 0
    for i, seg in enumerate(segs):
      chunk.append(seg)
      length += len(seg)
      if i == len(segs) - 1 or length > max_doc_seq_length:
        end = len(chunk) - 1 if (length > max_doc_seq_length and
                                 len(chunk) > 1) else len(chunk)
        for s in chunk[:end]:
          doc_tokens.extend(s)
        break
  truncate_seq(doc_tokens, max_doc_seq_length, rng)
  return doc_tokens


def create_pairs_from_document(document, rng, max_seq_length=512,
                               short_seq_prob=0.1):
  """Sliding code-chunk pairing with carry-over (reference
  ``pretrain_codebert.py:343-442``)."""
  special = 3 if document.doc_segments else 2
  max_num_tokens = max_seq_length - special
  max_doc_seq_length = 64 if max_seq_length >= 512 else 32
  doc_tokens = build_doc_segment(document, max_doc_seq_length,
                                 short_seq_prob, rng)
  doc_len = len(doc_tokens)
  target = max_num_tokens

  instances = []
  chunk, length = [], doc_len
  for i, seg in enumerate(document.code_segments):
    chunk.append(seg)
    length += len(seg)
    if i == len(document.code_segments) - 1 or length > target:
      if chunk:
        carry = (length > max_num_tokens and len(chunk) > 1)
        code_tokens = [t for s in chunk for t in s]
        truncate_seq(code_tokens, max_num_tokens - doc_len, rng)
        if code_tokens and (not instances or
                            len(code_tokens) >= MIN_CODE_TOKENS):
          instances.append({
              'id': document.doc_id,
              'doc': ' '.join(doc_tokens),
              'code': ' '.join(code_tokens),
              'num_tokens': doc_len + len(code_tokens) + special,
          })
        chunk = [chunk[-1]] if carry else []
        length = sum(len(s) for s in chunk) + doc_len
  return instances


CODEBERT_SCHEMA = pa.schema([
    ('id', pa.string()),
    ('doc', pa.string()),
    ('code', pa.string()),
    ('num_tokens', pa.uint16()),
])


@dataclasses.dataclass(frozen=True)
class CodebertPretrainConfig:
  vocab_file: str = None
  tokenizer_name: str = 'microsoft/codebert-base'
  tokenizer_backend: str = 'hf'
  lowercase: bool = False  # code is case-sensitive; codebert-base is cased
  target_seq_length: int = 512
  short_seq_prob: float = 0.1
  duplicate_factor: int = 1
  bin_size: int = None
  seed: int = 12345
  output_format: str = 'parquet'

  @property
  def nbins(self):
    if self.bin_size is None:
      return None
    if self.target_seq_length % self.bin_size != 0:
      raise ValueError('bin_size must divide target_seq_length')
    return self.target_seq_length // self.bin_size


def _get_tokenizer(cfg):
  from .common import get_cached_tokenizer
  return get_cached_tokenizer(
      vocab_file=cfg.vocab_file,
      hub_name=None if cfg.vocab_file else cfg.tokenizer_name,
      lowercase=cfg.lowercase,
      backend=cfg.tokenizer_backend)


def _warmup_worker(cfg):
  """Persistent-pool warmup hook: cache the tokenizer in each worker
  before its first task (see bert._warmup_worker)."""
  tokenizer = _get_tokenizer(cfg)
  tokenizer.batch_tokenize(['warmup'])


def _process_partition(tgt_idx, global_idx, spill_dir, out_dir, cfg,
                       delimiter='\r\n'):
  del global_idx
  tokenizer = _get_tokenizer(cfg)
  records = gather_partition(tgt_idx, spill_dir, cfg.seed,
                             delimiter=delimiter)
  documents = documents_from_records(records, tokenizer,
                                     max_length=cfg.target_seq_length)
  rng = rng_from_key(cfg.seed, 'code-pairs', tgt_idx)
  instances = []
  for _ in range(cfg.duplicate_factor):
    for document in documents:
      instances.extend(
          create_pairs_from_document(
              document,
              rng,
              max_seq_length=cfg.target_seq_length,
              short_seq_prob=cfg.short_seq_prob))
  out = write_samples_partition(
      instances,
      CODEBERT_SCHEMA,
      out_dir,
      tgt_idx,
      bin_size=cfg.bin_size,
      nbins=cfg.nbins,
      output_format=cfg.output_format,
      writer=current_writer(),
  )
  return {b: n for b, (_, n) in out.items()}


def run(corpus, sink_dir, cfg, executor=None, num_shuffle_partitions=None):
  # The gather delimiter must match what scatter wrote: the corpus's own.
  return run_shuffled(
      corpus,
      sink_dir,
      functools.partial(_process_partition, out_dir=sink_dir, cfg=cfg,
                        delimiter=corpus.delimiter),
      cfg.seed,
      executor=executor,
      num_shuffle_partitions=num_shuffle_partitions,
      warmup=functools.partial(_warmup_worker, cfg),
      warmup_key=('codebert-warmup', cfg))


def attach_args(parser):
  parser.add_argument('--source', type=str, required=True,
                      help='dir of CRLF-delimited <CODESPLIT> shards')
  parser.add_argument('--sink', type=str, required=True)
  parser.add_argument('--num-blocks', type=int, default=None)
  parser.add_argument('--block-size', type=str, default=None)
  parser.add_argument('--sample-ratio', type=float, default=1.0)
  parser.add_argument('--seed', type=int, default=12345)
  parser.add_argument('--vocab-file', type=str, default=None)
  parser.add_argument('--tokenizer', type=str,
                      default='microsoft/codebert-base')
  parser.add_argument('--tokenizer-backend', type=str, default='hf',
                      choices=['hf', 'native'])
  attach_bool_arg(parser, 'lowercase', default=False,
                  help_str='lowercase code (codebert-base is cased)')
  parser.add_argument('--target-seq-length', type=int, default=512)
  parser.add_argument('--short-seq-prob', type=float, default=0.1)
  parser.add_argument('--duplicate-factor', type=int, default=1)
  parser.add_argument('--bin-size', type=int, default=None)
  parser.add_argument('--output-format', type=str, default='parquet',
                      choices=['parquet', 'txt'])
  parser.add_argument('--num-workers', type=int, default=None)
  parser.add_argument('--comm', type=str, default='null',
                      choices=['null', 'file', 'jax'])
  attach_bool_arg(parser, 'verbose', default=False)
  return parser


def main(args=None):
  parser = attach_args(
      argparse.ArgumentParser(
          description=__doc__,
          formatter_class=argparse.ArgumentDefaultsHelpFormatter))
  args = parser.parse_args(args)
  from ..comm import get_backend
  from ..core.utils import parse_str_of_num_bytes
  comm = get_backend(args.comm)
  executor = Executor(comm=comm, num_local_workers=args.num_workers)
  corpus = read_code(
      args.source,
      num_blocks=args.num_blocks or 4 * executor.num_local_workers *
      comm.world_size,
      block_size=(parse_str_of_num_bytes(args.block_size)
                  if args.block_size else None),
      sample_ratio=args.sample_ratio,
      sample_seed=args.seed,
  )
  cfg = CodebertPretrainConfig(
      vocab_file=args.vocab_file,
      tokenizer_name=args.tokenizer,
      tokenizer_backend=args.tokenizer_backend,
      lowercase=args.lowercase,
      target_seq_length=args.target_seq_length,
      short_seq_prob=args.short_seq_prob,
      duplicate_factor=args.duplicate_factor,
      bin_size=args.bin_size,
      seed=args.seed,
      output_format=args.output_format)
  t0 = time.perf_counter()
  counts = run(corpus, args.sink, cfg, executor=executor)
  if comm.rank == 0:
    total = sum(n for c in counts for n in c.values())
    print(f'preprocessed {total} pairs into {len(counts)} partitions '
          f'in {time.perf_counter() - t0:.1f}s')


if __name__ == '__main__':
  main()

"""Long-context packed-document preprocessor.

The NSP pair pipeline tops out at phase-2 lengths (seq 512) by design;
long-context training (s = 8k-32k, the flagship ring/flash capability)
needs rows that long. This preprocessor greedily concatenates whole
tokenized documents into rows of up to ``target_seq_length`` tokens —
the long-context analogue of the BART sentence aggregator (reference
``lddl/dask/bart/pretrain.py:88-128``) but token-id based and binned.
No reference counterpart exists: the reference has no long-context data
path at all.

Row layout: ``[CLS] doc [SEP] doc [SEP] ...`` — documents longer than
the row budget are split into budget-sized chunks (standard packing).
On-disk schema (Parquet, ``part.N.parquet_<bin>`` naming, so the
balancer and loader shard machinery apply unchanged):

  input_ids:   binary  np.save-wire uint16 — token ids of the whole
               packed row, specials included (vocabs > 65536 and rows >
               65535 tokens are rejected loudly; widen the wire format
               if ever needed)
  doc_offsets: binary  np.save-wire uint16 — start index of each
               document's first token within the row (for consumers
               that want block-diagonal attention; training defaults to
               full attention over the packed row)
  num_tokens:  uint16

Ids (not token strings) on disk: at 8k-32k tokens/row, re-tokenizing
strings at load time would dominate the collate; the loader memory-maps
the wire format straight into the batch matrix
(:mod:`lddl_tpu.loader.packed`).
"""

import argparse
import dataclasses
import time

import numpy as np
import pyarrow as pa

from ..core import attach_bool_arg
from ..core.utils import u16_batch_binary_parts
from ..pipeline.executor import Executor
from ..pipeline.parquet_io import write_table_partition
from ..pipeline.pool import current_writer
from ..pipeline.shuffle import gather_partition
from .common import run_shuffled
from .readers import read_corpus, split_id_text


@dataclasses.dataclass(frozen=True)
class PackedPretrainConfig:
  vocab_file: str = None
  tokenizer_name: str = None
  lowercase: bool = True
  tokenizer_backend: str = 'auto'
  sentence_backend: str = 'auto'
  target_seq_length: int = 8192
  bin_size: int = None
  seed: int = 12345
  output_format: str = 'parquet'

  @property
  def nbins(self):
    if self.bin_size is None:
      return None
    if self.target_seq_length % self.bin_size != 0:
      raise ValueError('bin_size must divide target_seq_length')
    return self.target_seq_length // self.bin_size


def pack_documents(docs, cls_id, sep_id, target_seq_length):
  """Greedy packing: (flat row ids, row offsets, flat doc starts, doc
  start offsets) — all numpy, no per-token Python.

  ``docs``: :class:`~lddl_tpu.preprocess.pairing.TokenizedDocs`. Each
  row is ``[CLS] d0 [SEP] d1 [SEP] ...``; a document that cannot fit in
  the remaining budget starts a new row; one longer than a whole row is
  split into budget-sized chunks. Every row ends with [SEP].
  """
  if target_seq_length < 3:
    # [CLS] + >=1 token + [SEP]; below that `space` never goes positive
    # and the packing loop cannot make progress.
    raise ValueError('target_seq_length must be >= 3')
  soff = docs.sent_offsets
  dstart = docs.doc_sent_start
  flat = docs.flat_ids
  budget = target_seq_length
  rows = []          # list of np arrays (documents' pieces, with specials)
  row_lens = []      # running token count per emitted row
  doc_marks = []     # per row: list of doc start positions
  cur = [np.array([cls_id], dtype=np.int32)]
  cur_len = 1
  cur_marks = []

  def flush():
    nonlocal cur, cur_len, cur_marks
    if cur_len > 1:
      rows.append(np.concatenate(cur))
      row_lens.append(cur_len)
      doc_marks.append(cur_marks)
    cur = [np.array([cls_id], dtype=np.int32)]
    cur_len = 1
    cur_marks = []

  sep = np.array([sep_id], dtype=np.int32)
  for d in range(len(docs)):
    t0 = int(soff[dstart[d]])
    t1 = int(soff[dstart[d + 1]])
    ids = flat[t0:t1]
    while len(ids):
      space = budget - cur_len - 1  # room for the trailing [SEP]
      if space <= 0:
        flush()
        continue
      if len(ids) > space and cur_len > 1 and len(ids) <= budget - 2:
        # The doc overflows this row's remainder but fits a fresh row
        # whole ([CLS] + doc + [SEP] <= budget): start a new row rather
        # than splitting it — only docs longer than a whole row are
        # chunked. cur_len > 1 guarantees progress: an empty row is
        # never flushed, so a doc is only deferred once.
        flush()
        continue
      piece, ids = ids[:space], ids[space:]
      cur_marks.append(cur_len)
      cur.append(piece)
      cur.append(sep)
      cur_len += len(piece) + 1
      if cur_len >= budget:
        flush()
  flush()

  n = len(rows)
  row_offsets = np.zeros(n + 1, dtype=np.int64)
  np.cumsum(np.asarray(row_lens, dtype=np.int64), out=row_offsets[1:])
  flat_rows = (np.concatenate(rows) if rows else np.zeros(0, np.int32))
  mark_counts = np.asarray([len(m) for m in doc_marks], dtype=np.int64)
  mark_offsets = np.zeros(n + 1, dtype=np.int64)
  np.cumsum(mark_counts, out=mark_offsets[1:])
  flat_marks = (np.concatenate([np.asarray(m, np.int64) for m in doc_marks])
                if n else np.zeros(0, np.int64))
  return flat_rows, row_offsets, flat_marks, mark_offsets


def _binary_column(values_u16, offsets):
  """np.save-wire binary column from flat '<u2' values + offsets."""
  boffs, bdata = u16_batch_binary_parts(values_u16, offsets)
  if int(boffs[-1]) > np.iinfo(np.int32).max:
    raise ValueError('packed column exceeds 2 GiB (Arrow int32 offset '
                     'limit); use more/smaller partitions')
  return pa.BinaryArray.from_buffers(
      pa.binary(), len(offsets) - 1,
      [None, pa.py_buffer(boffs.astype(np.int32)), pa.py_buffer(bdata)])


def _process_partition(tgt_idx, global_idx, spill_dir, out_dir, cfg):
  del global_idx
  from .bert import encode_documents, _get_tokenizer
  tokenizer = _get_tokenizer(cfg)
  if tokenizer.vocab_size > np.iinfo(np.uint16).max + 1:
    raise NotImplementedError(
        'packed preprocessor stores uint16 ids; vocab exceeds 65536')
  lines = gather_partition(tgt_idx, spill_dir, cfg.seed)
  doc_texts = []
  for line in lines:
    _, text = split_id_text(line)
    if text:
      doc_texts.append(text)
  docs = encode_documents(doc_texts, tokenizer,
                          sentence_backend=cfg.sentence_backend)
  if len(docs) == 0:
    table = pa.table({
        'input_ids': pa.array([], type=pa.binary()),
        'doc_offsets': pa.array([], type=pa.binary()),
        'num_tokens': pa.array([], type=pa.uint16()),
    })
  else:
    flat_rows, row_offsets, flat_marks, mark_offsets = pack_documents(
        docs, tokenizer.cls_token_id, tokenizer.sep_token_id,
        cfg.target_seq_length)
    num_tokens = np.diff(row_offsets)
    table = pa.table({
        'input_ids': _binary_column(flat_rows.astype('<u2'), row_offsets),
        'doc_offsets': _binary_column(flat_marks.astype('<u2'),
                                      mark_offsets),
        'num_tokens': pa.array(num_tokens.astype(np.uint16),
                               type=pa.uint16()),
    })
  out = write_table_partition(
      table, out_dir, tgt_idx, bin_size=cfg.bin_size, nbins=cfg.nbins,
      output_format=cfg.output_format, writer=current_writer())
  return {b: nrows for b, (_, nrows) in out.items()}


def run(corpus, sink_dir, cfg, executor=None, num_shuffle_partitions=None):
  """Full packed preprocess: global doc shuffle -> tokenize -> greedy
  pack -> (binned) Parquet. Returns per-partition sample counts."""
  import functools

  executor = executor or Executor()
  if cfg.target_seq_length > np.iinfo(np.uint16).max:
    raise ValueError('target_seq_length > 65535 would overflow the uint16 '
                     'num_tokens/input_ids wire format')
  if cfg.sentence_backend == 'auto':
    from ..tokenization.sentences import resolve_backend
    resolved = executor.comm.broadcast_object(resolve_backend(), root=0)
    cfg = dataclasses.replace(cfg, sentence_backend=resolved)
  if cfg.tokenizer_backend == 'auto':
    from .bert import _get_tokenizer
    local = None
    if executor.comm.rank == 0:
      local = 'native' if _get_tokenizer(cfg).native is not None else 'hf'
    resolved = executor.comm.broadcast_object(local, root=0)
    cfg = dataclasses.replace(cfg, tokenizer_backend=resolved)
  return run_shuffled(
      corpus,
      sink_dir,
      functools.partial(_process_partition, out_dir=sink_dir, cfg=cfg),
      cfg.seed,
      executor=executor,
      num_shuffle_partitions=num_shuffle_partitions)


def attach_args(parser):
  parser.add_argument('--source', type=str, default=None,
                      help='generic one-doc-per-line source dir')
  parser.add_argument('--wikipedia', type=str, default=None)
  parser.add_argument('--books', type=str, default=None)
  parser.add_argument('--common-crawl', type=str, default=None)
  parser.add_argument('--open-webtext', type=str, default=None)
  parser.add_argument('--sink', type=str, required=True)
  parser.add_argument('--num-blocks', type=int, default=None)
  parser.add_argument('--sample-ratio', type=float, default=0.9)
  parser.add_argument('--seed', type=int, default=12345)
  parser.add_argument('--vocab-file', type=str, default=None)
  parser.add_argument('--tokenizer', type=str, default=None)
  parser.add_argument('--tokenizer-backend', type=str, default='auto',
                      choices=['auto', 'hf', 'native'])
  parser.add_argument('--sentence-backend', type=str, default='auto',
                      choices=['auto', 'punkt', 'rules'])
  parser.add_argument('--target-seq-length', type=int, default=8192)
  parser.add_argument('--bin-size', type=int, default=None)
  attach_bool_arg(parser, 'lowercase', default=True)
  parser.add_argument('--output-format', type=str, default='parquet',
                      choices=['parquet', 'txt'])
  parser.add_argument('--num-workers', type=int, default=None)
  parser.add_argument('--comm', type=str, default='null',
                      choices=['null', 'file', 'jax'])
  return parser


def main(args=None):
  parser = attach_args(
      argparse.ArgumentParser(
          description=__doc__,
          formatter_class=argparse.ArgumentDefaultsHelpFormatter))
  args = parser.parse_args(args)
  from ..comm import get_backend

  dirs = [
      d for d in (args.wikipedia, args.books, args.common_crawl,
                  args.open_webtext, args.source) if d is not None
  ]
  if not dirs:
    parser.error('need at least one source dir')
  if not args.vocab_file and not args.tokenizer:
    parser.error('need --vocab-file or --tokenizer')
  comm = get_backend(args.comm)
  executor = Executor(comm=comm, num_local_workers=args.num_workers)
  corpus = read_corpus(
      dirs,
      num_blocks=args.num_blocks or 4 * executor.num_local_workers *
      comm.world_size,
      sample_ratio=args.sample_ratio,
      sample_seed=args.seed,
  )
  cfg = PackedPretrainConfig(
      vocab_file=args.vocab_file,
      tokenizer_name=args.tokenizer,
      lowercase=args.lowercase,
      tokenizer_backend=args.tokenizer_backend,
      sentence_backend=args.sentence_backend,
      target_seq_length=args.target_seq_length,
      bin_size=args.bin_size,
      seed=args.seed,
      output_format=args.output_format,
  )
  t0 = time.perf_counter()
  counts = run(corpus, args.sink, cfg, executor=executor)
  if comm.rank == 0:
    total = sum(n for c in counts for n in c.values())
    print(f'packed {total} rows into {len(counts)} partitions '
          f'in {time.perf_counter() - t0:.1f}s')


if __name__ == '__main__':
  main()

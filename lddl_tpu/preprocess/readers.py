"""Corpus readers: one-document-per-line text shards -> planned partitions.

Input contract (shared with the downloaders; reference
``lddl/dask/readers.py:60-147``): each ``.txt`` shard under a source
directory holds one document per line, and the first whitespace-separated
token of the line is the document id.

The reference builds dask bags; here a :class:`Corpus` is a *plan* — a list
of byte-slice partitions plus deterministic per-partition sampling — that
the executor materializes anywhere.
"""

import dataclasses
import os

from ..core import get_all_txt_files_under
from ..core.random import rng_from_key
from ..pipeline.partition import (
    estimate_block_size,
    plan_text_partitions,
    read_lines,
    read_records,
)

CODE_SPLIT = '<CODESPLIT>'


def split_id_text(raw_text):
  """Split a document line into (doc_id, text)."""
  parts = raw_text.split(None, 1)
  if len(parts) < 2:
    return parts[0] if parts else '', ''
  return parts[0], parts[1]


def split_id_code_docstring(raw_text):
  """Split a bimodal code record into (id, docstring, code) on the
  ``<CODESPLIT>`` separator (reference ``lddl/dask/readers.py:150-151``)."""
  parts = raw_text.split(CODE_SPLIT)
  if len(parts) != 3:
    return None
  return tuple(parts)


@dataclasses.dataclass(frozen=True)
class Corpus:
  """A partitioned view of one or more source directories."""

  partitions: tuple  # tuple of tuples of TextSlice
  sample_ratio: float = 1.0
  sample_seed: int = 12345
  delimiter: str = '\n'  # record delimiter ('\r\n' for the code corpus)

  @property
  def num_partitions(self):
    return len(self.partitions)

  def read_partition(self, idx):
    """Yield the (possibly subsampled) raw document lines of partition idx."""
    return read_partition_lines(self.partitions[idx], idx, self.sample_ratio,
                                self.sample_seed, self.delimiter)


def read_partition_lines(part_slices, idx, sample_ratio, sample_seed,
                         delimiter='\n'):
  """Yield one partition's (possibly subsampled) document lines.

  Module-level so distributed tasks can carry just their own slices plus
  scalar sampling parameters instead of the whole corpus plan.
  """
  rng = rng_from_key(sample_seed, 'corpus-sample', idx)
  for s in part_slices:
    records = (read_lines(s) if delimiter == '\n' else
               read_records(s, delimiter=delimiter))
    for line in records:
      if sample_ratio >= 1.0 or rng.random() < sample_ratio:
        yield line


def read_corpus(dirs, num_blocks=None, block_size=None, sample_ratio=1.0,
                sample_seed=12345, delimiter='\n'):
  """Plan a corpus from source directories of one-doc-per-line txt shards.

  Exactly one of num_blocks/block_size controls partition granularity
  (reference ``lddl/dask/readers.py:48-70``).
  """
  paths = []
  for d in ([dirs] if isinstance(dirs, str) else dirs):
    if d is None:
      continue
    found = get_all_txt_files_under(d)
    if not found:
      raise ValueError(f'no .txt shards found under {d!r}')
    paths.extend(found)
  if block_size is None:
    if num_blocks is None:
      raise ValueError('need num_blocks or block_size')
    block_size = estimate_block_size(paths, num_blocks)
  slices = plan_text_partitions(paths, block_size)
  return Corpus(
      partitions=tuple((s,) for s in slices),
      sample_ratio=sample_ratio,
      sample_seed=sample_seed,
      delimiter=delimiter,
  )


def read_wikipedia(path, lang='en', **kwargs):
  return read_corpus(os.path.join(path, lang), **kwargs)


def read_books(path, **kwargs):
  return read_corpus(os.path.join(path, 'source'), **kwargs)


def read_common_crawl(path, **kwargs):
  return read_corpus(path, **kwargs)


def read_open_webtext(path, **kwargs):
  return read_corpus(path, **kwargs)


def read_code(path, **kwargs):
  """Bimodal code corpus: CRLF-delimited ``id<CODESPLIT>doc<CODESPLIT>code``
  records whose content contains plain newlines (reference
  ``lddl/dask/readers.py:130-139``)."""
  kwargs.setdefault('delimiter', '\r\n')
  return read_corpus(path, **kwargs)

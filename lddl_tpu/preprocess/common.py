"""Shared preprocess orchestration: the shuffle-spill run skeleton and the
per-process tokenizer cache used by every preprocessor frontend."""

import functools
import os
import shutil

from ..pipeline.executor import Executor
from ..pipeline.shuffle import shuffle_corpus

_TOKENIZER_CACHE = {}


def get_cached_tokenizer(vocab_file=None, hub_name=None, lowercase=True,
                         backend='hf'):
  """One tokenizer per (vocab, name, case, backend) per worker process."""
  key = (vocab_file, hub_name, lowercase, backend)
  if key not in _TOKENIZER_CACHE:
    from ..tokenization.wordpiece import load_bert_tokenizer
    _TOKENIZER_CACHE[key] = load_bert_tokenizer(
        vocab_file=vocab_file,
        hub_name=hub_name,
        lowercase=lowercase,
        backend=backend)
  return _TOKENIZER_CACHE[key]


def run_shuffled(corpus, sink_dir, process_partition, seed, executor=None,
                 num_shuffle_partitions=None):
  """Global shuffle -> ``process_partition(tgt_idx, global_idx)`` fan-out.

  ``process_partition`` must be a picklable callable taking
  ``(tgt_idx, global_idx, spill_dir)`` (use ``functools.partial`` to bind
  config). Pre-cleans stale spills from a previous crashed/re-partitioned
  run, removes the plaintext spill copy on success, and returns the
  task-ordered result list.
  """
  executor = executor or Executor()
  os.makedirs(sink_dir, exist_ok=True)
  spill_dir = os.path.join(sink_dir, '_shuffle_spill')
  if executor.comm.rank == 0 and os.path.isdir(spill_dir):
    shutil.rmtree(spill_dir)
  executor.comm.barrier()
  n = shuffle_corpus(
      executor, corpus, spill_dir, seed, num_targets=num_shuffle_partitions)
  task = functools.partial(process_partition, spill_dir=spill_dir)
  results = executor.map(task, list(range(n)), label='process')
  if executor.comm.rank == 0:
    shutil.rmtree(spill_dir, ignore_errors=True)
  return results

"""Shared preprocess orchestration: the shuffle-spill run skeleton and the
per-process tokenizer cache used by every preprocessor frontend."""

import functools
import os
import shutil

from ..pipeline.executor import Executor
from ..pipeline.shuffle import shuffle_corpus

_TOKENIZER_CACHE = {}


def native_columnar_enabled():
  """The ``LDDL_NATIVE_COLUMNAR`` gate for the fused native
  encode->columnar shard assembly (default on; the native library being
  unavailable still falls back per call, so 'on' is always safe).
  Outputs are byte-identical either way — the gate exists for A/B
  benchmarking and as an escape hatch."""
  return os.environ.get('LDDL_NATIVE_COLUMNAR', '').strip().lower() not in (
      '0', 'false', 'off', 'no')


def fused_string_columns(tokenizer, columns, positions=None):
  """Gate + fallback probe for the fused columnar build.

  Returns ``(string_parts, pos_parts)`` from the tokenizer's native
  :meth:`columnar_emit`, or ``None`` when the gate is off or the native
  library is unavailable (callers use the per-column
  ``decode_join_buffers`` + numpy-framing path instead).
  """
  if not native_columnar_enabled():
    return None
  emit = getattr(tokenizer, 'columnar_emit', None)
  if emit is None:
    return None
  return emit(columns, positions=positions)


def get_cached_tokenizer(vocab_file=None, hub_name=None, lowercase=True,
                         backend='hf'):
  """One tokenizer per (vocab, name, case, backend) per worker process."""
  key = (vocab_file, hub_name, lowercase, backend)
  if key not in _TOKENIZER_CACHE:
    from ..tokenization.wordpiece import load_bert_tokenizer
    _TOKENIZER_CACHE[key] = load_bert_tokenizer(
        vocab_file=vocab_file,
        hub_name=hub_name,
        lowercase=lowercase,
        backend=backend)
  return _TOKENIZER_CACHE[key]


def spill_partition_bytes(spill_dir, tgt_idx, global_idx):
  """LPT cost key for the process phase: total spilled bytes destined for
  output partition ``tgt_idx``. Pure function of on-disk state every rank
  shares, so all ranks derive the same ordering; falls back to the task
  index when the partition received no spills."""
  tgt_dir = os.path.join(spill_dir, f'tgt{tgt_idx}')
  if not os.path.isdir(tgt_dir):
    return global_idx
  total = 0
  for name in sorted(os.listdir(tgt_dir)):
    if name.endswith('.txt'):
      try:
        total += os.path.getsize(os.path.join(tgt_dir, name))
      except OSError:
        pass
  return total if total > 0 else global_idx


def run_shuffled(corpus, sink_dir, process_partition, seed, executor=None,
                 num_shuffle_partitions=None, warmup=None, warmup_key=None):
  """Global shuffle -> ``process_partition(tgt_idx, global_idx)`` fan-out.

  ``process_partition`` must be a picklable callable taking
  ``(tgt_idx, global_idx, spill_dir)`` (use ``functools.partial`` to bind
  config). ``warmup`` (optional, picklable, zero-arg) is registered on the
  executor's persistent pool so every worker pre-loads its tokenizer /
  native encoder once per pool lifetime — pass a stable ``warmup_key`` so
  repeated runs on one executor don't re-broadcast it. Pre-cleans stale
  spills from a previous crashed/re-partitioned run, removes the plaintext
  spill copy on success, and returns the task-ordered result list. An
  executor created here (none passed in) is closed before returning.
  """
  owned = executor is None
  executor = executor or Executor()
  try:
    if warmup is not None:
      executor.set_warmup(warmup, key=warmup_key)
    os.makedirs(sink_dir, exist_ok=True)
    spill_dir = os.path.join(sink_dir, '_shuffle_spill')
    # A restarted elastic run resumes from the scatter phase's completion
    # manifests — the spills backing already-manifested scatter tasks are
    # inputs the resume still needs, so only pre-clean when this is a
    # fresh (or statically scheduled) run.
    resuming = executor.resume_pending('scatter')
    if not resuming and executor.comm.rank == 0 and os.path.isdir(spill_dir):
      shutil.rmtree(spill_dir)
    executor.comm.barrier()
    n = shuffle_corpus(
        executor, corpus, spill_dir, seed, num_targets=num_shuffle_partitions)
    task = functools.partial(process_partition, spill_dir=spill_dir)
    results = executor.map(
        task, list(range(n)), label='process',
        cost_key=functools.partial(_process_cost, spill_dir))
    if executor.comm.rank == 0:
      shutil.rmtree(spill_dir, ignore_errors=True)
    return results
  finally:
    if owned:
      executor.close()


def _process_cost(spill_dir, tgt_idx, global_idx):
  return spill_partition_bytes(spill_dir, tgt_idx, global_idx)

from .readers import Corpus, split_id_text

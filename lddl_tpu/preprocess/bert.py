"""BERT pretraining preprocessor.

Turns one-document-per-line corpora into next-sentence-prediction pairs with
optional static MLM masking and sequence-length binning, written as Parquet
shards. Output schema and on-disk naming are interoperable with the
reference (``lddl/dask/bert/pretrain.py:444-498``):

  A: str                     space-joined WordPiece tokens of segment A
  B: str                     space-joined WordPiece tokens of segment B
  is_random_next: bool       NSP label
  num_tokens: uint16         len(A) + len(B) + 3 ([CLS] + 2x[SEP])
  [masked_lm_positions: binary   serialized uint16 positions into the
                                 assembled [CLS] A [SEP] B [SEP] sequence]
  [masked_lm_labels: str         space-joined original tokens]
  [bin_id: int64             when binned]

Pairing follows the standard BERT recipe (segment chunks to a target
length, 50% random-next B, random front/back truncation; reference
``pretrain.py:241-365``) — but every random draw here threads an explicit
per-partition RNG, so unlike the reference (which uses the unseeded global
``random`` inside Dask workers) the whole pipeline is deterministic given
(seed, corpus): identical reruns produce identical shards.
"""

import argparse
import dataclasses
import functools
import time

import numpy as np
import pyarrow as pa

from ..core import attach_bool_arg, serialize_np_array
from ..core.random import rng_from_key
from ..core.utils import (binary_column_from_parts, npy_batch_binary_parts,
                          u16_batch_binary_parts)
from ..pipeline.executor import Executor
from ..pipeline.parquet_io import write_samples_partition, write_table_partition
from ..pipeline.pool import current_writer
from ..pipeline.shard_format import (DELTA, DELTA_COLUMNS, MATERIALIZED,
                                     tag_table)
from ..pipeline.shuffle import gather_partition
from ..tokenization import split_sentences
from .common import run_shuffled
from .readers import read_corpus, split_id_text


@dataclasses.dataclass(frozen=True)
class Document:
  doc_id: str
  sentences: tuple  # tuple of tuples of tokens

  def __len__(self):
    return len(self.sentences)

  def __getitem__(self, i):
    return self.sentences[i]


def documents_from_lines(lines, tokenizer, max_length=512,
                         sentence_backend='auto'):
  """Parse raw document lines into tokenized Documents.

  All sentences of all documents are tokenized in a single batched backend
  call, then redistributed — the partition-level equivalent of the
  reference's per-sentence ``tokenizer.tokenize`` loop
  (``lddl/dask/bert/pretrain.py:77-97``).
  """
  doc_ids, doc_sentence_strs = [], []
  for line in lines:
    doc_id, text = split_id_text(line)
    if not text:
      continue
    sents = [s.strip() for s in split_sentences(text, backend=sentence_backend)]
    sents = [s for s in sents if s]
    if sents:
      doc_ids.append(doc_id)
      doc_sentence_strs.append(sents)
  flat = [s for sents in doc_sentence_strs for s in sents]
  flat_tokens = tokenizer.batch_tokenize(flat, max_length=max_length)
  documents = []
  pos = 0
  for doc_id, sents in zip(doc_ids, doc_sentence_strs):
    toks = [tuple(t) for t in flat_tokens[pos:pos + len(sents)]]
    pos += len(sents)
    toks = [t for t in toks if t]
    if toks:
      documents.append(Document(doc_id, tuple(toks)))
  return documents


def truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, rng):
  """Randomly trim the longer segment from the front or back until the pair
  fits (reference ``pretrain.py:161-176``)."""
  while len(tokens_a) + len(tokens_b) > max_num_tokens:
    trunc = tokens_a if len(tokens_a) > len(tokens_b) else tokens_b
    if rng.random() < 0.5:
      del trunc[0]
    else:
      trunc.pop()


def create_masked_lm_predictions(tokens_a, tokens_b, masked_lm_ratio,
                                 vocab_words, rng, max_predictions=None):
  """Static MLM masking over the assembled [CLS] A [SEP] B [SEP] sequence.

  Standard 80/10/10 recipe (reference ``pretrain.py:182-238``). Positions
  index the assembled sequence. Returns the masked A/B token lists plus
  sorted (positions, labels).
  """
  n_a, n_b = len(tokens_a), len(tokens_b)
  tokens = ['[CLS]'] + list(tokens_a) + ['[SEP]'] + list(tokens_b) + ['[SEP]']
  cand = [i for i, t in enumerate(tokens) if t not in ('[CLS]', '[SEP]')]
  rng.shuffle(cand)
  num_to_predict = max(1, int(round(len(tokens) * masked_lm_ratio)))
  if max_predictions is not None:
    num_to_predict = min(num_to_predict, max_predictions)
  picked = sorted(cand[:num_to_predict])
  labels = [tokens[i] for i in picked]
  for i in picked:
    r = rng.random()
    if r < 0.8:
      tokens[i] = '[MASK]'
    elif r < 0.9:
      pass  # keep original
    else:
      tokens[i] = vocab_words[rng.randrange(len(vocab_words))]
  return (
      tokens[1:1 + n_a],
      tokens[2 + n_a:2 + n_a + n_b],
      picked,
      labels,
  )


def create_masked_lm_predictions_np(tokens_a, tokens_b, masked_lm_ratio,
                                    vocab_words, np_rng,
                                    max_predictions=None):
  """Vectorized 80/10/10 masking: one ``Generator.choice`` + one uniform
  draw per instance instead of a Python shuffle over every candidate
  position (the reference's per-token loop, ``pretrain.py:182-238``, is
  the second-hottest preprocess cost after tokenization)."""
  n_a, n_b = len(tokens_a), len(tokens_b)
  tokens = ['[CLS]'] + list(tokens_a) + ['[SEP]'] + list(tokens_b) + ['[SEP]']
  cand = np.concatenate(
      [np.arange(1, 1 + n_a), np.arange(2 + n_a, 2 + n_a + n_b)])
  num_to_predict = max(1, int(round(len(tokens) * masked_lm_ratio)))
  if max_predictions is not None:
    num_to_predict = min(num_to_predict, max_predictions)
  num_to_predict = min(num_to_predict, cand.size)
  picked = np.sort(np_rng.choice(cand, size=num_to_predict, replace=False))
  labels = [tokens[i] for i in picked]
  decide = np_rng.random(num_to_predict)
  rand_ids = np_rng.integers(0, len(vocab_words), num_to_predict)
  for j, i in enumerate(picked):
    if decide[j] < 0.8:
      tokens[i] = '[MASK]'
    elif decide[j] < 0.9:
      pass  # keep original
    else:
      tokens[i] = vocab_words[rand_ids[j]]
  return (
      tokens[1:1 + n_a],
      tokens[2 + n_a:2 + n_a + n_b],
      picked.tolist(),
      labels,
  )


def create_pairs_from_document(
    all_documents,
    document_index,
    rng,
    max_seq_length=128,
    short_seq_prob=0.1,
    masking=False,
    masked_lm_ratio=0.15,
    vocab_words=None,
    np_rng=None,
):
  """NSP pair construction for one document (reference
  ``pretrain.py:241-365``): accumulate sentence chunks up to a target
  length, split at a random point into A, and with probability 0.5 replace
  the continuation by sentences from a random other document in the
  partition."""
  document = all_documents[document_index]
  max_num_tokens = max_seq_length - 3
  target_seq_length = max_num_tokens
  if rng.random() < short_seq_prob:
    target_seq_length = rng.randint(2, max_num_tokens)

  instances = []
  chunk = []
  chunk_len = 0
  i = 0
  while i < len(document):
    chunk.append(document[i])
    chunk_len += len(document[i])
    if i == len(document) - 1 or chunk_len >= target_seq_length:
      if chunk:
        a_end = 1 if len(chunk) < 2 else rng.randint(1, len(chunk) - 1)
        tokens_a = [t for seg in chunk[:a_end] for t in seg]
        tokens_b = []
        if len(chunk) == 1 or rng.random() < 0.5:
          # Random next: fill B from a random other document.
          is_random_next = True
          target_b_length = target_seq_length - len(tokens_a)
          random_document_index = document_index
          for _ in range(10):
            candidate = rng.randint(0, len(all_documents) - 1)
            if candidate != document_index:
              random_document_index = candidate
              break
          if random_document_index == document_index:
            is_random_next = False
          random_document = all_documents[random_document_index]
          start = rng.randint(0, len(random_document) - 1)
          for j in range(start, len(random_document)):
            tokens_b.extend(random_document[j])
            if len(tokens_b) >= target_b_length:
              break
          # Unused trailing segments of the chunk are replayed.
          i -= len(chunk) - a_end
        else:
          is_random_next = False
          tokens_b = [t for seg in chunk[a_end:] for t in seg]
        truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, rng)
        if tokens_a and tokens_b:
          if masking:
            if np_rng is not None:
              tokens_a, tokens_b, positions, labels = (
                  create_masked_lm_predictions_np(tokens_a, tokens_b,
                                                  masked_lm_ratio,
                                                  vocab_words, np_rng))
            else:
              tokens_a, tokens_b, positions, labels = (
                  create_masked_lm_predictions(tokens_a, tokens_b,
                                               masked_lm_ratio, vocab_words,
                                               rng))
          instance = {
              'A': ' '.join(tokens_a),
              'B': ' '.join(tokens_b),
              'is_random_next': is_random_next,
              'num_tokens': len(tokens_a) + len(tokens_b) + 3,
          }
          if masking:
            instance['masked_lm_positions'] = serialize_np_array(
                np.asarray(positions, dtype=np.uint16))
            instance['masked_lm_labels'] = ' '.join(labels)
          instances.append(instance)
      chunk = []
      chunk_len = 0
    i += 1
  return instances


def encode_documents(doc_texts, tokenizer, sentence_backend='rules',
                     max_length=512):
  """Raw document texts -> :class:`~lddl_tpu.preprocess.pairing.TokenizedDocs`.

  With the native tokenizer and the 'rules' sentence backend the whole
  front end (segmentation + WordPiece) is one multithreaded C call;
  otherwise sentences are split in Python and encoded via the tokenizer's
  batched id path. Zero-sentence documents are dropped (mirror of
  ``documents_from_lines``).
  """
  from .pairing import TokenizedDocs
  if tokenizer.native is not None and sentence_backend == 'rules':
    flat, sent_offsets, doc_counts = tokenizer.native.encode_docs(
        doc_texts, max_tokens_per_sent=max_length)
  else:
    sents_per_doc = []
    for text in doc_texts:
      sents = [s.strip() for s in split_sentences(text,
                                                  backend=sentence_backend)]
      sents_per_doc.append([s for s in sents if s])
    flat_sents = [s for sents in sents_per_doc for s in sents]
    flat, offsets = tokenizer.encode_batch_ids(flat_sents,
                                               max_tokens=max_length)
    lens = np.diff(offsets)
    keep = lens > 0
    sent_offsets = np.concatenate(
        [[0], np.cumsum(lens[keep])]).astype(np.int64)
    doc_counts = np.zeros(len(doc_texts), dtype=np.int64)
    pos = 0
    for d, sents in enumerate(sents_per_doc):
      doc_counts[d] = int(keep[pos:pos + len(sents)].sum())
      pos += len(sents)
  nonempty = doc_counts > 0
  return TokenizedDocs(flat, sent_offsets, doc_counts[nonempty])


def _string_column(tokenizer, flat_ids, offsets):
  """Ragged id ranges -> Arrow string column of space-joined tokens
  (zero-copy from native buffers when available)."""
  bufs = tokenizer.decode_join_buffers(flat_ids, offsets)
  if bufs is not None:
    out_offsets, data = bufs
    return pa.StringArray.from_buffers(
        len(out_offsets) - 1, pa.py_buffer(out_offsets.tobytes()),
        pa.py_buffer(data.tobytes()))
  return pa.array(tokenizer.decode_join(flat_ids, offsets), type=pa.string())


def resolve_shard_format(cfg):
  """Resolve ``cfg.shard_format`` ('auto' | 'materialized' | 'delta').

  'auto' picks delta exactly where it wins: fast-engine static masking
  with ``duplicate_factor > 1`` (the dup copies of a pair differ only by
  their mask, so storing the base once plus per-copy deltas cuts write
  bytes ~duplicate_factor×). Explicit 'delta' is validated loudly: an
  unmasked run has no mask delta to store (unmasked dup copies differ by
  their *pairing*, which delta cannot represent), and the python engine
  materializes per-document instances with no columnar delta path.
  """
  fmt = cfg.shard_format
  if fmt == 'auto':
    if cfg.masking and cfg.duplicate_factor > 1 and cfg.engine == 'fast':
      return DELTA
    return MATERIALIZED
  if fmt == DELTA:
    if not cfg.masking:
      raise ValueError(
          '--shard-format delta requires --masking: unmasked duplicate '
          'copies differ by pairing, not by a mask delta')
    if cfg.engine != 'fast':
      raise ValueError(
          "--shard-format delta requires the fast engine (engine='fast')")
  elif fmt != MATERIALIZED:
    raise ValueError(f'unknown shard format {fmt!r}')
  return fmt


def _fused_string_col(parts):
  """(offsets, utf8 data) from the native fused assembler -> Arrow column."""
  out_offsets, data = parts
  return pa.StringArray.from_buffers(
      len(out_offsets) - 1, pa.py_buffer(out_offsets), pa.py_buffer(data))


def process_partition_columnar(doc_texts, tokenizer, cfg, rng, mask_seed):
  """The fast path: tokenize -> plan pairs -> batched (device) masking ->
  Arrow table. Returns a ``pyarrow.Table`` matching :func:`bert_schema`
  for the resolved shard format, tagged via
  :func:`~lddl_tpu.pipeline.shard_format.tag_table`.

  This is the TPU-first redesign of the reference's per-partition hot loop
  (``lddl/dask/bert/pretrain.py:77-97,182-238``): token ids end-to-end,
  contiguous-range pair planning, one batched masking call on the
  accelerator, and zero-copy Arrow column assembly.

  Masked runs with ``duplicate_factor > 1`` plan the base pairs ONCE and
  tile the ranges copy-adjacent (p0c0, p0c1, ..., p0c{dup-1}, p1c0, ...);
  the counter-based Philox mask stream is keyed by row index, so each
  tiled copy draws an independent mask for free. This holds for BOTH
  shard formats, which is what makes them logically equivalent
  row-for-row — the delta format just stores each base once plus the
  per-copy (positions, new_ids, label_ids, k) deltas instead of
  materializing dup masked rows.
  """
  from ..ops import masking as _masking_ops
  from .pairing import plan_pairs_partition

  from ..ops.masking import (mask_partition_device, mask_partition_host,
                             resolve_mask_backend)

  shard_format = resolve_shard_format(cfg)
  delta = shard_format == DELTA

  docs = encode_documents(doc_texts, tokenizer,
                          sentence_backend=cfg.sentence_backend)
  if len(docs) == 0:
    return tag_table(
        bert_schema(cfg.masking, shard_format).empty_table(),
        shard_format, cfg.duplicate_factor)
  # Masked dup>1: plan base pairs once and tile copy-adjacent (see
  # docstring). Unmasked dup>1 keeps the legacy per-copy planning passes
  # (one continuing rng stream), matching the python engine pass-for-pass.
  plan_once = cfg.masking and cfg.duplicate_factor > 1
  base_a, base_b, base_irn = plan_pairs_partition(
      docs, rng, max_seq_length=cfg.target_seq_length,
      short_seq_prob=cfg.short_seq_prob,
      duplicate_factor=1 if plan_once else cfg.duplicate_factor)
  dup = cfg.duplicate_factor if plan_once else 1
  nbase = len(base_a)
  if plan_once and dup > 1:
    a_ranges = np.repeat(base_a, dup, axis=0)
    b_ranges = np.repeat(base_b, dup, axis=0)
    is_random_next = np.repeat(np.asarray(base_irn), dup)
  else:
    a_ranges, b_ranges, is_random_next = base_a, base_b, base_irn
  flat_ids = docs.flat_ids
  n = len(a_ranges)
  na = (a_ranges[:, 1] - a_ranges[:, 0]).astype(np.int64)
  nb = (b_ranges[:, 1] - b_ranges[:, 0]).astype(np.int64)
  row_len = na + nb + 3
  if n and int(row_len.max()) > cfg.target_seq_length:
    # Fail loudly at preprocess time (the padded-matrix path used to
    # enforce this in assemble_pair_matrix): oversized rows would break
    # downstream binning/collate shape assumptions silently.
    raise ValueError(f'pair of {int(row_len.max())} tokens exceeds '
                     f'target_seq_length {cfg.target_seq_length}')
  mask_mode = resolve_mask_backend(cfg.mask_backend) if cfg.masking else None
  offs_a = np.zeros(n + 1, dtype=np.int64)
  np.cumsum(na, out=offs_a[1:])
  offs_b = np.zeros(n + 1, dtype=np.int64)
  np.cumsum(nb, out=offs_b[1:])

  newv = None
  if mask_mode == 'host':
    # Fused ragged path: one native pass gathers A/B, draws k Fisher-
    # Yates picks per row from a counter-based Philox stream, applies
    # 80/10/10, and emits sorted positions + label ids — no padded id
    # matrix, no dense [N, L] uniform draws (see ops/masking.py
    # mask_partition_host; numpy fallback is bit-identical).
    flat_a, flat_b, ci, label_ids, k = mask_partition_host(
        flat_ids, a_ranges, b_ranges, masked_lm_ratio=cfg.masked_lm_ratio,
        vocab_size=tokenizer.vocab_size, mask_id=tokenizer.mask_token_id,
        seed=mask_seed, offs_a=offs_a, offs_b=offs_b)
    if delta:
      # The host kernel applies the delta in place; re-read the post-mask
      # ids at the picked positions so the delta columns can store them.
      ri = np.repeat(np.arange(n, dtype=np.int64), k)
      ci64 = ci.astype(np.int64)
      in_a = ci64 < 1 + na[ri]
      idx_a = offs_a[ri] + ci64 - 1
      idx_b = offs_b[ri] + ci64 - 2 - na[ri]
      newv = np.where(in_a, flat_a[np.where(in_a, idx_a, 0)],
                      flat_b[np.where(in_a, 0, idx_b)])
  else:
    if not delta:
      # Ragged gather straight from the flat partition ids (no id matrix).
      ra, ca = _masking_ops.ragged_indices(na)
      flat_a = flat_ids[a_ranges[ra, 0] + ca]
      rb, cb = _masking_ops.ragged_indices(nb)
      flat_b = flat_ids[b_ranges[rb, 0] + cb]
    if mask_mode == 'device':
      positions, new_ids, kk = mask_partition_device(
          flat_ids, a_ranges, b_ranges, seq_len=cfg.target_seq_length,
          masked_lm_ratio=cfg.masked_lm_ratio,
          vocab_size=tokenizer.vocab_size,
          mask_id=tokenizer.mask_token_id,
          cls_id=tokenizer.cls_token_id, sep_id=tokenizer.sep_token_id,
          seed=mask_seed)
      k = kk.astype(np.int64)
      pm = np.arange(positions.shape[1])[None, :] < k[:, None]
      ri = np.nonzero(pm)[0]
      ci = positions[pm].astype(np.int64)  # sorted within each row
      in_a = ci < 1 + na[ri]
      if not delta:
        # Original (label) ids, read from the flat array via the ranges
        # (the delta format stores no labels — collate recovers them).
        idx_a = a_ranges[ri, 0] + ci - 1
        idx_b = b_ranges[ri, 0] + ci - 2 - na[ri]
        label_ids = np.where(
            in_a, flat_ids[np.where(in_a, idx_a, 0)],
            flat_ids[np.where(in_a, 0, idx_b)]).astype(np.int32)
      newv = new_ids[pm].astype(flat_ids.dtype)
      if not delta:
        # Apply the post-masking ids into the ragged A/B columns.
        tgt_a = offs_a[ri] + ci - 1
        flat_a[tgt_a[in_a]] = newv[in_a]
        tgt_b = offs_b[ri] + ci - 2 - na[ri]
        flat_b[tgt_b[~in_a]] = newv[~in_a]

  offs_l = None
  if cfg.masking:
    offs_l = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(k, out=offs_l[1:])

  from .common import fused_string_columns

  if delta:
    # Delta format: one physical row per BASE pair. The A/B strings come
    # from the unmasked base ids; the dup per-copy mask deltas are packed
    # ragged into four binary columns. Tiled rows are copy-adjacent, so
    # each base row's delta span is a pure stride view: offs_l[::dup].
    base_na = na[::dup]
    base_nb = nb[::dup]
    boffs_a = np.zeros(nbase + 1, dtype=np.int64)
    np.cumsum(base_na, out=boffs_a[1:])
    boffs_b = np.zeros(nbase + 1, dtype=np.int64)
    np.cumsum(base_nb, out=boffs_b[1:])
    ra, ca = _masking_ops.ragged_indices(base_na)
    base_flat_a = flat_ids[base_a[ra, 0] + ca]
    rb, cb = _masking_ops.ragged_indices(base_nb)
    base_flat_b = flat_ids[base_b[rb, 0] + cb]
    fused = fused_string_columns(
        tokenizer, [(base_flat_a, boffs_a), (base_flat_b, boffs_b)])
    if fused is not None:
      string_parts, _ = fused
      col_a = _fused_string_col(string_parts[0])
      col_b = _fused_string_col(string_parts[1])
    else:
      col_a = _string_column(tokenizer, base_flat_a, boffs_a)
      col_b = _string_column(tokenizer, base_flat_b, boffs_b)
    cols = {
        'A': col_a,
        'B': col_b,
        'is_random_next': pa.array(np.asarray(base_irn)),
        'num_tokens': pa.array((base_na + base_nb + 3).astype(np.uint16),
                               type=pa.uint16()),
    }
    doffs = offs_l[::dup]
    koffs = np.arange(nbase + 1, dtype=np.int64) * dup
    # No label column: the label at a masked position is the original
    # token, which the collate reads out of input_ids before applying
    # the delta. Post-mask ids fit u2 whenever the vocab does.
    new_dt = '<u2' if tokenizer.vocab_size <= 1 << 16 else '<i4'
    for name, vals, offs, dt in (
        ('mask_delta_positions', ci, doffs, '<u2'),
        ('mask_delta_new_ids', newv, doffs, new_dt),
        ('mask_delta_k', k, koffs, '<u2')):
      bo, bd = npy_batch_binary_parts(vals, offs, dt)
      cols[name] = binary_column_from_parts(bo, bd, nbase, name)
    return tag_table(pa.table(cols), DELTA, dup)

  # Fused native columnar assembly (LDDL_NATIVE_COLUMNAR, default on):
  # every string column and the npy-framed positions column in one native
  # round trip — no numpy capacity/framing passes, no buffer re-copies.
  # Bytes are identical to the per-column fallback below (tested), so the
  # shard contract f(task, global_index) is unchanged.
  emit_cols = [(flat_a, offs_a), (flat_b, offs_b)]
  if cfg.masking:
    emit_cols.append((label_ids, offs_l))
  fused = fused_string_columns(
      tokenizer, emit_cols,
      positions=(ci, offs_l) if cfg.masking else None)
  if fused is not None:
    string_parts, pos_parts = fused
    cols = {
        'A': _fused_string_col(string_parts[0]),
        'B': _fused_string_col(string_parts[1]),
        'is_random_next': pa.array(is_random_next),
        'num_tokens': pa.array(row_len.astype(np.uint16), type=pa.uint16()),
    }
    if cfg.masking:
      boffs, bdata = pos_parts
      cols['masked_lm_positions'] = binary_column_from_parts(
          boffs, bdata, n, 'masked_lm_positions')
      cols['masked_lm_labels'] = _fused_string_col(string_parts[2])
    return tag_table(pa.table(cols), MATERIALIZED, cfg.duplicate_factor)

  cols = {
      'A': _string_column(tokenizer, flat_a, offs_a),
      'B': _string_column(tokenizer, flat_b, offs_b),
      'is_random_next': pa.array(is_random_next),
      'num_tokens': pa.array(row_len.astype(np.uint16), type=pa.uint16()),
  }
  if cfg.masking:
    boffs, bdata = u16_batch_binary_parts(ci, offs_l)
    cols['masked_lm_positions'] = binary_column_from_parts(
        boffs, bdata, n, 'masked_lm_positions')
    cols['masked_lm_labels'] = _string_column(tokenizer, label_ids, offs_l)
  return tag_table(pa.table(cols), MATERIALIZED, cfg.duplicate_factor)


def bert_schema(masking, shard_format=MATERIALIZED):
  fields = [
      ('A', pa.string()),
      ('B', pa.string()),
      ('is_random_next', pa.bool_()),
      ('num_tokens', pa.uint16()),
  ]
  if shard_format == DELTA:
    if not masking:
      raise ValueError('delta shard format requires masking')
    fields += [(name, pa.binary()) for name in DELTA_COLUMNS]
  elif masking:
    fields += [
        ('masked_lm_positions', pa.binary()),
        ('masked_lm_labels', pa.string()),
    ]
  return pa.schema(fields)


@dataclasses.dataclass(frozen=True)
class BertPretrainConfig:
  vocab_file: str = None
  tokenizer_name: str = None
  lowercase: bool = True
  tokenizer_backend: str = 'auto'
  sentence_backend: str = 'auto'
  engine: str = 'fast'  # 'fast' (columnar/device) | 'python' (reference-style)
  mask_backend: str = 'auto'  # 'device' | 'host' | 'auto'
  target_seq_length: int = 128
  short_seq_prob: float = 0.1
  duplicate_factor: int = 5
  masking: bool = False
  masked_lm_ratio: float = 0.15
  bin_size: int = None
  seed: int = 12345
  output_format: str = 'parquet'
  # 'auto' resolves to 'delta' for fast-engine masked duplicate_factor>1
  # runs (see resolve_shard_format), 'materialized' otherwise.
  shard_format: str = 'auto'

  @property
  def nbins(self):
    if self.bin_size is None:
      return None
    if self.target_seq_length % self.bin_size != 0:
      raise ValueError('bin_size must divide target_seq_length')
    return self.target_seq_length // self.bin_size


def _get_tokenizer(cfg):
  from .common import get_cached_tokenizer
  return get_cached_tokenizer(
      vocab_file=cfg.vocab_file,
      hub_name=cfg.tokenizer_name,
      lowercase=cfg.lowercase,
      backend=cfg.tokenizer_backend)


def _warmup_worker(cfg):
  """Persistent-pool warmup hook: build (and cache) the tokenizer in the
  worker before its first task, so vocab load + native-encoder
  construction are paid once per worker per pool lifetime instead of
  inside task one of every phase."""
  tokenizer = _get_tokenizer(cfg)
  tokenizer.batch_tokenize(['warmup'])


def _mask_seed(seed, tgt_idx):
  """Per-partition masking seed, independent of the pairing rng stream."""
  return int(
      np.random.SeedSequence([seed, tgt_idx, 0x6d61736b]).generate_state(1)[0])


def _process_partition(tgt_idx, global_idx, spill_dir, out_dir, cfg):
  """Worker task: shuffled lines of one partition -> pair instances ->
  (binned) Parquet. Returns {bin_id_or_None: num_samples}."""
  del global_idx
  tokenizer = _get_tokenizer(cfg)
  lines = gather_partition(tgt_idx, spill_dir, cfg.seed)
  rng = rng_from_key(cfg.seed, 'pairs', tgt_idx)

  if cfg.engine == 'fast':
    doc_texts = []
    for line in lines:
      _, text = split_id_text(line)
      if text:
        doc_texts.append(text)
    table = process_partition_columnar(doc_texts, tokenizer, cfg, rng,
                                       _mask_seed(cfg.seed, tgt_idx))
    out = write_table_partition(
        table,
        out_dir,
        tgt_idx,
        bin_size=cfg.bin_size,
        nbins=cfg.nbins,
        output_format=cfg.output_format,
        writer=current_writer(),
    )
    return {b: nrows for b, (_, nrows) in out.items()}

  documents = documents_from_lines(
      lines, tokenizer, sentence_backend=cfg.sentence_backend)
  np_rng = np.random.Generator(
      np.random.Philox(key=[np.uint64(cfg.seed),
                            np.uint64(tgt_idx)]))
  instances = []
  for _ in range(cfg.duplicate_factor):
    for di in range(len(documents)):
      instances.extend(
          create_pairs_from_document(
              documents,
              di,
              rng,
              max_seq_length=cfg.target_seq_length,
              short_seq_prob=cfg.short_seq_prob,
              masking=cfg.masking,
              masked_lm_ratio=cfg.masked_lm_ratio,
              vocab_words=tokenizer.vocab_words,
              np_rng=np_rng,
          ))
  out = write_samples_partition(
      instances,
      bert_schema(cfg.masking),
      out_dir,
      tgt_idx,
      bin_size=cfg.bin_size,
      nbins=cfg.nbins,
      output_format=cfg.output_format,
      writer=current_writer(),
  )
  return {b: n for b, (_, n) in out.items()}


def run(corpus, sink_dir, cfg, executor=None, num_shuffle_partitions=None):
  """Execute the full preprocess: global doc shuffle -> pair/mask/bin ->
  Parquet shards under ``sink_dir``. Returns per-partition sample counts."""
  executor = executor or Executor()
  if cfg.sentence_backend == 'auto':
    # Resolve once and broadcast so segmentation (and thus shard content)
    # never depends on which worker host has nltk data installed.
    from ..tokenization.sentences import resolve_backend
    resolved = executor.comm.broadcast_object(resolve_backend(), root=0)
    cfg = dataclasses.replace(cfg, sentence_backend=resolved)
  if cfg.tokenizer_backend == 'auto':
    # Same principle: 'auto' must not resolve per worker (native needs a
    # compiler; a heterogeneous fleet would silently emit mixed token
    # streams for exotic scripts). Probe once on root, broadcast the
    # decision; a worker that then cannot honor it fails loudly.
    local = None
    if executor.comm.rank == 0:
      local = 'native' if _get_tokenizer(cfg).native is not None else 'hf'
    resolved = executor.comm.broadcast_object(local, root=0)
    cfg = dataclasses.replace(cfg, tokenizer_backend=resolved)
  if cfg.masking and cfg.engine == 'fast' and cfg.mask_backend == 'auto':
    # Masking backends have independent RNG streams, so which one runs is
    # part of the output contract: resolve once here, not per pool worker
    # (workers racing for an exclusive accelerator would otherwise make
    # shard bits depend on OS scheduling). Pool workers cannot share one
    # chip, so 'device' only applies to single-worker executors until the
    # per-host device feeder lands.
    local = None
    if executor.comm.rank == 0:
      from ..ops.masking import resolve_mask_backend
      local = resolve_mask_backend('auto')
      if local == 'device' and executor.num_local_workers > 1:
        local = 'host'
    resolved = executor.comm.broadcast_object(local, root=0)
    cfg = dataclasses.replace(cfg, mask_backend=resolved)
  # Resolve the shard format once up front: it is part of the output
  # contract (and invalid combinations — delta without masking, delta on
  # the python engine — must fail loudly before any worker starts).
  cfg = dataclasses.replace(cfg, shard_format=resolve_shard_format(cfg))
  if executor.comm.rank == 0:
    mask = (cfg.mask_backend
            if cfg.masking and cfg.engine == 'fast' else 'off')
    print(f'preprocess backends: tokenizer={cfg.tokenizer_backend} '
          f'sentences={cfg.sentence_backend} mask={mask} '
          f'format={cfg.shard_format}')
  return run_shuffled(
      corpus,
      sink_dir,
      functools.partial(_process_partition, out_dir=sink_dir, cfg=cfg),
      cfg.seed,
      executor=executor,
      num_shuffle_partitions=num_shuffle_partitions,
      warmup=functools.partial(_warmup_worker, cfg),
      warmup_key=('bert-warmup', cfg))


def attach_args(parser):
  parser.add_argument('--wikipedia', type=str, default=None)
  parser.add_argument('--books', type=str, default=None)
  parser.add_argument('--common-crawl', type=str, default=None)
  parser.add_argument('--open-webtext', type=str, default=None)
  parser.add_argument('--source', type=str, default=None,
                      help='generic one-doc-per-line source dir')
  parser.add_argument('--sink', type=str, required=True)
  parser.add_argument('--num-blocks', type=int, default=None)
  parser.add_argument('--block-size', type=str, default=None,
                      help='bytes per partition, accepts n[KMG]')
  parser.add_argument('--sample-ratio', type=float, default=0.9)
  parser.add_argument('--seed', type=int, default=12345)
  parser.add_argument('--vocab-file', type=str, default=None)
  parser.add_argument('--tokenizer', type=str, default=None,
                      help='HF hub tokenizer name (needs egress)')
  parser.add_argument('--tokenizer-backend', type=str, default='auto',
                      choices=['auto', 'hf', 'native'])
  parser.add_argument('--engine', type=str, default='fast',
                      choices=['fast', 'python'],
                      help='fast: columnar ids + batched/device masking; '
                      'python: reference-style per-document loop')
  parser.add_argument('--mask-backend', type=str, default='auto',
                      choices=['auto', 'device', 'host'],
                      help='where batched MLM masking runs (fast engine)')
  parser.add_argument('--sentence-backend', type=str, default='auto',
                      choices=['auto', 'punkt', 'rules'])
  parser.add_argument('--target-seq-length', type=int, default=128)
  parser.add_argument('--short-seq-prob', type=float, default=0.1)
  parser.add_argument('--duplicate-factor', type=int, default=5)
  parser.add_argument('--bin-size', type=int, default=None)
  parser.add_argument('--masked-lm-ratio', type=float, default=0.15)
  parser.add_argument('--shard-format', type=str, default='auto',
                      choices=['auto', 'materialized', 'delta'],
                      help='on-disk shard layout: materialized stores every '
                      'masked duplicate row in full; delta stores each base '
                      'pair once plus per-copy mask deltas (~duplicate_factor'
                      'x fewer write bytes). auto: delta for fast-engine '
                      'masked duplicate_factor>1 runs, else materialized')
  attach_bool_arg(parser, 'masking', default=False,
                  help_str='store static MLM masks')
  attach_bool_arg(parser, 'lowercase', default=True)
  parser.add_argument('--output-format', type=str, default='parquet',
                      choices=['parquet', 'txt'])
  parser.add_argument('--num-workers', type=int, default=None,
                      help='local worker processes (default: all cores)')
  parser.add_argument('--comm', type=str, default='null',
                      choices=['null', 'file', 'jax'])
  return parser


def main(args=None):
  parser = attach_args(
      argparse.ArgumentParser(
          description=__doc__,
          formatter_class=argparse.ArgumentDefaultsHelpFormatter))
  args = parser.parse_args(args)
  from ..core.utils import parse_str_of_num_bytes
  from ..comm import get_backend

  dirs = [
      d for d in (args.wikipedia, args.books, args.common_crawl,
                  args.open_webtext, args.source) if d is not None
  ]
  if not dirs:
    parser.error('need at least one source dir')
  if not args.vocab_file and not args.tokenizer:
    parser.error('need --vocab-file or --tokenizer')
  comm = get_backend(args.comm)
  executor = Executor(comm=comm, num_local_workers=args.num_workers)
  block_size = (parse_str_of_num_bytes(args.block_size)
                if args.block_size else None)
  corpus = read_corpus(
      dirs,
      num_blocks=args.num_blocks or 4 * executor.num_local_workers *
      comm.world_size,
      block_size=block_size,
      sample_ratio=args.sample_ratio,
      sample_seed=args.seed,
  )
  cfg = BertPretrainConfig(
      vocab_file=args.vocab_file,
      tokenizer_name=args.tokenizer,
      lowercase=args.lowercase,
      tokenizer_backend=args.tokenizer_backend,
      sentence_backend=args.sentence_backend,
      engine=args.engine,
      mask_backend=args.mask_backend,
      target_seq_length=args.target_seq_length,
      short_seq_prob=args.short_seq_prob,
      duplicate_factor=args.duplicate_factor,
      masking=args.masking,
      masked_lm_ratio=args.masked_lm_ratio,
      bin_size=args.bin_size,
      seed=args.seed,
      output_format=args.output_format,
      shard_format=args.shard_format,
  )
  t0 = time.perf_counter()
  with executor:
    counts = run(corpus, args.sink, cfg, executor=executor)
  if comm.rank == 0:
    total = sum(n for c in counts for n in c.values())
    print(f'preprocessed {total} samples into {len(counts)} partitions '
          f'in {time.perf_counter() - t0:.1f}s')


if __name__ == '__main__':
  main()

"""BERT pretraining preprocessor.

Turns one-document-per-line corpora into next-sentence-prediction pairs with
optional static MLM masking and sequence-length binning, written as Parquet
shards. Output schema and on-disk naming are interoperable with the
reference (``lddl/dask/bert/pretrain.py:444-498``):

  A: str                     space-joined WordPiece tokens of segment A
  B: str                     space-joined WordPiece tokens of segment B
  is_random_next: bool       NSP label
  num_tokens: uint16         len(A) + len(B) + 3 ([CLS] + 2x[SEP])
  [masked_lm_positions: binary   serialized uint16 positions into the
                                 assembled [CLS] A [SEP] B [SEP] sequence]
  [masked_lm_labels: str         space-joined original tokens]
  [bin_id: int64             when binned]

Pairing follows the standard BERT recipe (segment chunks to a target
length, 50% random-next B, random front/back truncation; reference
``pretrain.py:241-365``) — but every random draw here threads an explicit
per-partition RNG, so unlike the reference (which uses the unseeded global
``random`` inside Dask workers) the whole pipeline is deterministic given
(seed, corpus): identical reruns produce identical shards.
"""

import argparse
import dataclasses
import functools
import time

import numpy as np
import pyarrow as pa

from ..core import attach_bool_arg, serialize_np_array
from ..core.random import rng_from_key
from ..pipeline.executor import Executor
from ..pipeline.parquet_io import write_samples_partition
from ..pipeline.shuffle import gather_partition
from ..tokenization import split_sentences
from .common import run_shuffled
from .readers import read_corpus, split_id_text


@dataclasses.dataclass(frozen=True)
class Document:
  doc_id: str
  sentences: tuple  # tuple of tuples of tokens

  def __len__(self):
    return len(self.sentences)

  def __getitem__(self, i):
    return self.sentences[i]


def documents_from_lines(lines, tokenizer, max_length=512,
                         sentence_backend='auto'):
  """Parse raw document lines into tokenized Documents.

  All sentences of all documents are tokenized in a single batched backend
  call, then redistributed — the partition-level equivalent of the
  reference's per-sentence ``tokenizer.tokenize`` loop
  (``lddl/dask/bert/pretrain.py:77-97``).
  """
  doc_ids, doc_sentence_strs = [], []
  for line in lines:
    doc_id, text = split_id_text(line)
    if not text:
      continue
    sents = [s.strip() for s in split_sentences(text, backend=sentence_backend)]
    sents = [s for s in sents if s]
    if sents:
      doc_ids.append(doc_id)
      doc_sentence_strs.append(sents)
  flat = [s for sents in doc_sentence_strs for s in sents]
  flat_tokens = tokenizer.batch_tokenize(flat, max_length=max_length)
  documents = []
  pos = 0
  for doc_id, sents in zip(doc_ids, doc_sentence_strs):
    toks = [tuple(t) for t in flat_tokens[pos:pos + len(sents)]]
    pos += len(sents)
    toks = [t for t in toks if t]
    if toks:
      documents.append(Document(doc_id, tuple(toks)))
  return documents


def truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, rng):
  """Randomly trim the longer segment from the front or back until the pair
  fits (reference ``pretrain.py:161-176``)."""
  while len(tokens_a) + len(tokens_b) > max_num_tokens:
    trunc = tokens_a if len(tokens_a) > len(tokens_b) else tokens_b
    if rng.random() < 0.5:
      del trunc[0]
    else:
      trunc.pop()


def create_masked_lm_predictions(tokens_a, tokens_b, masked_lm_ratio,
                                 vocab_words, rng, max_predictions=None):
  """Static MLM masking over the assembled [CLS] A [SEP] B [SEP] sequence.

  Standard 80/10/10 recipe (reference ``pretrain.py:182-238``). Positions
  index the assembled sequence. Returns the masked A/B token lists plus
  sorted (positions, labels).
  """
  n_a, n_b = len(tokens_a), len(tokens_b)
  tokens = ['[CLS]'] + list(tokens_a) + ['[SEP]'] + list(tokens_b) + ['[SEP]']
  cand = [i for i, t in enumerate(tokens) if t not in ('[CLS]', '[SEP]')]
  rng.shuffle(cand)
  num_to_predict = max(1, int(round(len(tokens) * masked_lm_ratio)))
  if max_predictions is not None:
    num_to_predict = min(num_to_predict, max_predictions)
  picked = sorted(cand[:num_to_predict])
  labels = [tokens[i] for i in picked]
  for i in picked:
    r = rng.random()
    if r < 0.8:
      tokens[i] = '[MASK]'
    elif r < 0.9:
      pass  # keep original
    else:
      tokens[i] = vocab_words[rng.randrange(len(vocab_words))]
  return (
      tokens[1:1 + n_a],
      tokens[2 + n_a:2 + n_a + n_b],
      picked,
      labels,
  )


def create_masked_lm_predictions_np(tokens_a, tokens_b, masked_lm_ratio,
                                    vocab_words, np_rng,
                                    max_predictions=None):
  """Vectorized 80/10/10 masking: one ``Generator.choice`` + one uniform
  draw per instance instead of a Python shuffle over every candidate
  position (the reference's per-token loop, ``pretrain.py:182-238``, is
  the second-hottest preprocess cost after tokenization)."""
  n_a, n_b = len(tokens_a), len(tokens_b)
  tokens = ['[CLS]'] + list(tokens_a) + ['[SEP]'] + list(tokens_b) + ['[SEP]']
  cand = np.concatenate(
      [np.arange(1, 1 + n_a), np.arange(2 + n_a, 2 + n_a + n_b)])
  num_to_predict = max(1, int(round(len(tokens) * masked_lm_ratio)))
  if max_predictions is not None:
    num_to_predict = min(num_to_predict, max_predictions)
  num_to_predict = min(num_to_predict, cand.size)
  picked = np.sort(np_rng.choice(cand, size=num_to_predict, replace=False))
  labels = [tokens[i] for i in picked]
  decide = np_rng.random(num_to_predict)
  rand_ids = np_rng.integers(0, len(vocab_words), num_to_predict)
  for j, i in enumerate(picked):
    if decide[j] < 0.8:
      tokens[i] = '[MASK]'
    elif decide[j] < 0.9:
      pass  # keep original
    else:
      tokens[i] = vocab_words[rand_ids[j]]
  return (
      tokens[1:1 + n_a],
      tokens[2 + n_a:2 + n_a + n_b],
      picked.tolist(),
      labels,
  )


def create_pairs_from_document(
    all_documents,
    document_index,
    rng,
    max_seq_length=128,
    short_seq_prob=0.1,
    masking=False,
    masked_lm_ratio=0.15,
    vocab_words=None,
    np_rng=None,
):
  """NSP pair construction for one document (reference
  ``pretrain.py:241-365``): accumulate sentence chunks up to a target
  length, split at a random point into A, and with probability 0.5 replace
  the continuation by sentences from a random other document in the
  partition."""
  document = all_documents[document_index]
  max_num_tokens = max_seq_length - 3
  target_seq_length = max_num_tokens
  if rng.random() < short_seq_prob:
    target_seq_length = rng.randint(2, max_num_tokens)

  instances = []
  chunk = []
  chunk_len = 0
  i = 0
  while i < len(document):
    chunk.append(document[i])
    chunk_len += len(document[i])
    if i == len(document) - 1 or chunk_len >= target_seq_length:
      if chunk:
        a_end = 1 if len(chunk) < 2 else rng.randint(1, len(chunk) - 1)
        tokens_a = [t for seg in chunk[:a_end] for t in seg]
        tokens_b = []
        if len(chunk) == 1 or rng.random() < 0.5:
          # Random next: fill B from a random other document.
          is_random_next = True
          target_b_length = target_seq_length - len(tokens_a)
          random_document_index = document_index
          for _ in range(10):
            candidate = rng.randint(0, len(all_documents) - 1)
            if candidate != document_index:
              random_document_index = candidate
              break
          if random_document_index == document_index:
            is_random_next = False
          random_document = all_documents[random_document_index]
          start = rng.randint(0, len(random_document) - 1)
          for j in range(start, len(random_document)):
            tokens_b.extend(random_document[j])
            if len(tokens_b) >= target_b_length:
              break
          # Unused trailing segments of the chunk are replayed.
          i -= len(chunk) - a_end
        else:
          is_random_next = False
          tokens_b = [t for seg in chunk[a_end:] for t in seg]
        truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, rng)
        if tokens_a and tokens_b:
          if masking:
            if np_rng is not None:
              tokens_a, tokens_b, positions, labels = (
                  create_masked_lm_predictions_np(tokens_a, tokens_b,
                                                  masked_lm_ratio,
                                                  vocab_words, np_rng))
            else:
              tokens_a, tokens_b, positions, labels = (
                  create_masked_lm_predictions(tokens_a, tokens_b,
                                               masked_lm_ratio, vocab_words,
                                               rng))
          instance = {
              'A': ' '.join(tokens_a),
              'B': ' '.join(tokens_b),
              'is_random_next': is_random_next,
              'num_tokens': len(tokens_a) + len(tokens_b) + 3,
          }
          if masking:
            instance['masked_lm_positions'] = serialize_np_array(
                np.asarray(positions, dtype=np.uint16))
            instance['masked_lm_labels'] = ' '.join(labels)
          instances.append(instance)
      chunk = []
      chunk_len = 0
    i += 1
  return instances


def bert_schema(masking):
  fields = [
      ('A', pa.string()),
      ('B', pa.string()),
      ('is_random_next', pa.bool_()),
      ('num_tokens', pa.uint16()),
  ]
  if masking:
    fields += [
        ('masked_lm_positions', pa.binary()),
        ('masked_lm_labels', pa.string()),
    ]
  return pa.schema(fields)


@dataclasses.dataclass(frozen=True)
class BertPretrainConfig:
  vocab_file: str = None
  tokenizer_name: str = None
  lowercase: bool = True
  tokenizer_backend: str = 'hf'
  sentence_backend: str = 'auto'
  target_seq_length: int = 128
  short_seq_prob: float = 0.1
  duplicate_factor: int = 5
  masking: bool = False
  masked_lm_ratio: float = 0.15
  bin_size: int = None
  seed: int = 12345
  output_format: str = 'parquet'

  @property
  def nbins(self):
    if self.bin_size is None:
      return None
    if self.target_seq_length % self.bin_size != 0:
      raise ValueError('bin_size must divide target_seq_length')
    return self.target_seq_length // self.bin_size


def _get_tokenizer(cfg):
  from .common import get_cached_tokenizer
  return get_cached_tokenizer(
      vocab_file=cfg.vocab_file,
      hub_name=cfg.tokenizer_name,
      lowercase=cfg.lowercase,
      backend=cfg.tokenizer_backend)


def _process_partition(tgt_idx, global_idx, spill_dir, out_dir, cfg):
  """Worker task: shuffled lines of one partition -> pair instances ->
  (binned) Parquet. Returns {bin_id_or_None: num_samples}."""
  del global_idx
  tokenizer = _get_tokenizer(cfg)
  lines = gather_partition(tgt_idx, spill_dir, cfg.seed)
  documents = documents_from_lines(
      lines, tokenizer, sentence_backend=cfg.sentence_backend)
  rng = rng_from_key(cfg.seed, 'pairs', tgt_idx)
  np_rng = np.random.Generator(
      np.random.Philox(key=[np.uint64(cfg.seed),
                            np.uint64(tgt_idx)]))
  instances = []
  for _ in range(cfg.duplicate_factor):
    for di in range(len(documents)):
      instances.extend(
          create_pairs_from_document(
              documents,
              di,
              rng,
              max_seq_length=cfg.target_seq_length,
              short_seq_prob=cfg.short_seq_prob,
              masking=cfg.masking,
              masked_lm_ratio=cfg.masked_lm_ratio,
              vocab_words=tokenizer.vocab_words,
              np_rng=np_rng,
          ))
  out = write_samples_partition(
      instances,
      bert_schema(cfg.masking),
      out_dir,
      tgt_idx,
      bin_size=cfg.bin_size,
      nbins=cfg.nbins,
      output_format=cfg.output_format,
  )
  return {b: n for b, (_, n) in out.items()}


def run(corpus, sink_dir, cfg, executor=None, num_shuffle_partitions=None):
  """Execute the full preprocess: global doc shuffle -> pair/mask/bin ->
  Parquet shards under ``sink_dir``. Returns per-partition sample counts."""
  executor = executor or Executor()
  if cfg.sentence_backend == 'auto':
    # Resolve once and broadcast so segmentation (and thus shard content)
    # never depends on which worker host has nltk data installed.
    from ..tokenization.sentences import resolve_backend
    resolved = executor.comm.broadcast_object(resolve_backend(), root=0)
    cfg = dataclasses.replace(cfg, sentence_backend=resolved)
  return run_shuffled(
      corpus,
      sink_dir,
      functools.partial(_process_partition, out_dir=sink_dir, cfg=cfg),
      cfg.seed,
      executor=executor,
      num_shuffle_partitions=num_shuffle_partitions)


def attach_args(parser):
  parser.add_argument('--wikipedia', type=str, default=None)
  parser.add_argument('--books', type=str, default=None)
  parser.add_argument('--common-crawl', type=str, default=None)
  parser.add_argument('--open-webtext', type=str, default=None)
  parser.add_argument('--source', type=str, default=None,
                      help='generic one-doc-per-line source dir')
  parser.add_argument('--sink', type=str, required=True)
  parser.add_argument('--num-blocks', type=int, default=None)
  parser.add_argument('--block-size', type=str, default=None,
                      help='bytes per partition, accepts n[KMG]')
  parser.add_argument('--sample-ratio', type=float, default=0.9)
  parser.add_argument('--seed', type=int, default=12345)
  parser.add_argument('--vocab-file', type=str, default=None)
  parser.add_argument('--tokenizer', type=str, default=None,
                      help='HF hub tokenizer name (needs egress)')
  parser.add_argument('--tokenizer-backend', type=str, default='hf',
                      choices=['hf', 'native'])
  parser.add_argument('--sentence-backend', type=str, default='auto',
                      choices=['auto', 'punkt', 'rules'])
  parser.add_argument('--target-seq-length', type=int, default=128)
  parser.add_argument('--short-seq-prob', type=float, default=0.1)
  parser.add_argument('--duplicate-factor', type=int, default=5)
  parser.add_argument('--bin-size', type=int, default=None)
  parser.add_argument('--masked-lm-ratio', type=float, default=0.15)
  attach_bool_arg(parser, 'masking', default=False,
                  help_str='store static MLM masks')
  attach_bool_arg(parser, 'lowercase', default=True)
  parser.add_argument('--output-format', type=str, default='parquet',
                      choices=['parquet', 'txt'])
  parser.add_argument('--num-workers', type=int, default=None,
                      help='local worker processes (default: all cores)')
  parser.add_argument('--comm', type=str, default='null',
                      choices=['null', 'file', 'jax'])
  return parser


def main(args=None):
  parser = attach_args(
      argparse.ArgumentParser(
          description=__doc__,
          formatter_class=argparse.ArgumentDefaultsHelpFormatter))
  args = parser.parse_args(args)
  from ..core.utils import parse_str_of_num_bytes
  from ..comm import get_backend

  dirs = [
      d for d in (args.wikipedia, args.books, args.common_crawl,
                  args.open_webtext, args.source) if d is not None
  ]
  if not dirs:
    parser.error('need at least one source dir')
  if not args.vocab_file and not args.tokenizer:
    parser.error('need --vocab-file or --tokenizer')
  comm = get_backend(args.comm)
  executor = Executor(comm=comm, num_local_workers=args.num_workers)
  block_size = (parse_str_of_num_bytes(args.block_size)
                if args.block_size else None)
  corpus = read_corpus(
      dirs,
      num_blocks=args.num_blocks or 4 * executor.num_local_workers *
      comm.world_size,
      block_size=block_size,
      sample_ratio=args.sample_ratio,
      sample_seed=args.seed,
  )
  cfg = BertPretrainConfig(
      vocab_file=args.vocab_file,
      tokenizer_name=args.tokenizer,
      lowercase=args.lowercase,
      tokenizer_backend=args.tokenizer_backend,
      sentence_backend=args.sentence_backend,
      target_seq_length=args.target_seq_length,
      short_seq_prob=args.short_seq_prob,
      duplicate_factor=args.duplicate_factor,
      masking=args.masking,
      masked_lm_ratio=args.masked_lm_ratio,
      bin_size=args.bin_size,
      seed=args.seed,
      output_format=args.output_format,
  )
  t0 = time.perf_counter()
  counts = run(corpus, args.sink, cfg, executor=executor)
  if comm.rank == 0:
    total = sum(n for c in counts for n in c.values())
    print(f'preprocessed {total} samples into {len(counts)} partitions '
          f'in {time.perf_counter() - t0:.1f}s')


if __name__ == '__main__':
  main()

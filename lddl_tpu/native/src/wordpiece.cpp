// lddl_tpu native host kernels: BERT text normalization, WordPiece
// longest-match encoding, rule-based sentence segmentation, and token-id ->
// space-joined-string decoding (emitting Arrow string-column buffers).
//
// This is the TPU-framework replacement for the per-sentence Python
// tokenize loop of the reference (lddl/dask/bert/pretrain.py:77-97): the
// whole partition is one C call, internally multithreaded, GIL-free.
// Exposed through a plain C ABI consumed with ctypes
// (lddl_tpu/native/wordpiece.py) -- no pybind11 dependency.
//
// Normalization parity: matches HuggingFace's BertNormalizer for ASCII,
// Latin-1/Latin-Extended-A accents, Greek/Cyrillic lowercase, combining
// marks, and CJK spacing. Exotic scripts outside those ranges pass through
// unchanged (divergence documented in lddl_tpu/native/wordpiece.py).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <functional>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- unicode

// Decode one UTF-8 codepoint starting at s[i]; advances i. Invalid bytes
// decode as 0xFFFD and advance by one.
inline uint32_t decode_utf8(const char* s, int64_t len, int64_t& i) {
  unsigned char c = s[i];
  if (c < 0x80) { i += 1; return c; }
  if ((c >> 5) == 0x6 && i + 1 < len) {
    uint32_t cp = ((c & 0x1F) << 6) | (s[i + 1] & 0x3F);
    i += 2; return cp;
  }
  if ((c >> 4) == 0xE && i + 2 < len) {
    uint32_t cp = ((c & 0x0F) << 12) | ((s[i + 1] & 0x3F) << 6) |
                  (s[i + 2] & 0x3F);
    i += 3; return cp;
  }
  if ((c >> 3) == 0x1E && i + 3 < len) {
    uint32_t cp = ((c & 0x07) << 18) | ((s[i + 1] & 0x3F) << 12) |
                  ((s[i + 2] & 0x3F) << 6) | (s[i + 3] & 0x3F);
    i += 4; return cp;
  }
  i += 1; return 0xFFFD;
}

inline void encode_utf8(uint32_t cp, std::string& out) {
  if (cp < 0x80) {
    out.push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

inline bool is_whitespace(uint32_t cp) {
  switch (cp) {
    case ' ': case '\t': case '\n': case '\r':
    case 0x00A0: case 0x1680: case 0x2028: case 0x2029:
    case 0x202F: case 0x205F: case 0x3000:
      return true;
    default:
      return cp >= 0x2000 && cp <= 0x200A;
  }
}

inline bool is_control(uint32_t cp) {
  if (cp == '\t' || cp == '\n' || cp == '\r') return false;  // treated as ws
  if (cp < 0x20 || cp == 0x7F) return true;
  // Common Cf (format) characters.
  if (cp == 0x00AD || cp == 0xFEFF) return true;
  if (cp >= 0x200B && cp <= 0x200F) return true;
  if (cp >= 0x202A && cp <= 0x202E) return true;
  if (cp >= 0x2060 && cp <= 0x2064) return true;
  return false;
}

inline bool is_cjk(uint32_t cp) {
  return (cp >= 0x4E00 && cp <= 0x9FFF) || (cp >= 0x3400 && cp <= 0x4DBF) ||
         (cp >= 0x20000 && cp <= 0x2A6DF) || (cp >= 0x2A700 && cp <= 0x2B73F) ||
         (cp >= 0x2B740 && cp <= 0x2B81F) || (cp >= 0x2B820 && cp <= 0x2CEAF) ||
         (cp >= 0xF900 && cp <= 0xFAFF) || (cp >= 0x2F800 && cp <= 0x2FA1F);
}

inline bool is_punctuation(uint32_t cp) {
  if ((cp >= 33 && cp <= 47) || (cp >= 58 && cp <= 64) ||
      (cp >= 91 && cp <= 96) || (cp >= 123 && cp <= 126))
    return true;
  // Common Unicode punctuation blocks / characters.
  if (cp >= 0x2010 && cp <= 0x2027) return true;   // dashes, quotes, bullets
  if (cp >= 0x2030 && cp <= 0x205E) return true;   // permille .. general punct
  if (cp == 0x00A1 || cp == 0x00A7 || cp == 0x00AB || cp == 0x00B6 ||
      cp == 0x00B7 || cp == 0x00BB || cp == 0x00BF)
    return true;
  if (cp >= 0x3001 && cp <= 0x3003) return true;   // CJK comma/stop
  if (cp >= 0x3008 && cp <= 0x3011) return true;   // CJK brackets
  if (cp >= 0x3014 && cp <= 0x301F) return true;
  if (cp == 0x30FB || cp == 0xFF01 || cp == 0xFF0C || cp == 0xFF0E ||
      cp == 0xFF1A || cp == 0xFF1B || cp == 0xFF1F)
    return true;
  return false;
}

// Combining diacritical marks (category Mn slices BertNormalizer strips
// after NFD when lowercasing).
inline bool is_combining_mark(uint32_t cp) {
  return (cp >= 0x0300 && cp <= 0x036F) || (cp >= 0x1AB0 && cp <= 0x1AFF) ||
         (cp >= 0x1DC0 && cp <= 0x1DFF) || (cp >= 0x20D0 && cp <= 0x20FF);
}

// Lowercase + accent-strip one codepoint. Returns 0 when the codepoint
// should be dropped (pure combining mark). Mirrors NFD-decompose ->
// drop-Mn -> lowercase for the Latin-1 Supplement and Latin Extended-A
// ranges, plus simple offset lowercasing for Greek/Cyrillic.
inline uint32_t lower_strip(uint32_t cp) {
  if (cp < 0x80) {
    if (cp >= 'A' && cp <= 'Z') return cp + 32;
    return cp;
  }
  if (is_combining_mark(cp)) return 0;
  if (cp >= 0xC0 && cp <= 0xFF) {  // Latin-1 Supplement letters
    static const char* tbl =
        // 0xC0..0xDF: À Á Â Ã Ä Å Æ Ç È É Ê Ë Ì Í Î Ï Ð Ñ Ò Ó Ô Õ Ö × Ø Ù Ú Û Ü Ý Þ ß
        "aaaaaa\0ceeeeiiii\0nooooo\0\0uuuuy\0\0"
        // 0xE0..0xFF mirrors with lowercase input (ÿ -> y)
        "aaaaaa\0ceeeeiiii\0nooooo\0\0uuuuy\0y";
    char t = tbl[cp - 0xC0];
    if (t) return static_cast<uint32_t>(t);
    // Non-decomposing letters: lowercase only.
    if (cp == 0xC6) return 0xE6;  // Æ
    if (cp == 0xD0) return 0xF0;  // Ð
    if (cp == 0xD7) return 0xD7;  // ×
    if (cp == 0xD8) return 0xF8;  // Ø
    if (cp == 0xDE) return 0xFE;  // Þ
    return cp;
  }
  if (cp >= 0x100 && cp <= 0x17F) {  // Latin Extended-A
    struct Range { uint32_t lo, hi; char base; };
    static const Range ranges[] = {
        {0x100, 0x105, 'a'}, {0x106, 0x10D, 'c'}, {0x10E, 0x111, 'd'},
        {0x112, 0x11B, 'e'}, {0x11C, 0x123, 'g'}, {0x124, 0x127, 'h'},
        {0x128, 0x131, 'i'}, {0x134, 0x135, 'j'}, {0x136, 0x138, 'k'},
        {0x139, 0x142, 'l'}, {0x143, 0x148, 'n'}, {0x14A, 0x14B, 'n'},
        {0x14C, 0x151, 'o'}, {0x154, 0x159, 'r'}, {0x15A, 0x161, 's'},
        {0x162, 0x167, 't'}, {0x168, 0x173, 'u'}, {0x174, 0x175, 'w'},
        {0x176, 0x178, 'y'}, {0x179, 0x17E, 'z'},
    };
    // Đ/đ (0x110/0x111) and ŋ do not NFD-decompose but lowercase within
    // their range mapping above is the accepted approximation.
    for (const auto& r : ranges)
      if (cp >= r.lo && cp <= r.hi) return static_cast<uint32_t>(r.base);
    return cp;
  }
  if (cp >= 0x391 && cp <= 0x3A9 && cp != 0x3A2) return cp + 0x20;  // Greek
  if (cp >= 0x410 && cp <= 0x42F) return cp + 0x20;  // Cyrillic А..Я
  if (cp >= 0x400 && cp <= 0x40F) return cp + 0x50;  // Ѐ..Џ
  return cp;
}

// ------------------------------------------------------------- vocabulary

struct SvHash {
  size_t operator()(std::string_view sv) const {
    // FNV-1a
    size_t h = 1469598103934665603ull;
    for (char c : sv) {
      h ^= static_cast<unsigned char>(c);
      h *= 1099511628211ull;
    }
    return h;
  }
};

std::atomic<uint64_t> g_model_gen{0};

struct Model {
  std::string vocab_blob;                 // concatenated token bytes
  std::vector<std::string_view> tokens;   // id -> token view into blob
  std::unordered_map<std::string_view, int32_t, SvHash> roots;
  std::unordered_map<std::string_view, int32_t, SvHash> suffixes;  // sans ##
  int32_t unk_id = 0;
  bool lowercase = true;
  int32_t max_input_chars = 100;
  // Longest vocab entry in bytes, split by table: substrings longer than
  // this cannot match, so the longest-match scan starts below it.
  int32_t max_root_bytes = 0;
  int32_t max_suffix_bytes = 0;
  // Unique instance tag (never reused, unlike the heap address) so
  // thread-local word caches can detect a model switch.
  uint64_t gen = ++g_model_gen;
  // Decode arena: per id, ' ' + token bytes padded to kDecodeStride so the
  // decode hot loop is one unconditional fixed-size copy (tokens longer
  // than kDecodeStride - 1 take the slow path; none exist in BERT vocabs,
  // where entries are <= max_input_chars wordpieces but practically < 30
  // bytes). decode_lens[id] = token byte length (without the space).
  static constexpr int32_t kDecodeStride = 32;
  std::vector<char> decode_arena;
  std::vector<int32_t> decode_lens;
};

// Per-thread memo of normalized-word bytes -> wordpiece ids. Natural text
// is Zipfian, so a small open-addressing table absorbs almost every word
// after the first few MB; a hit costs one hash + one memcmp instead of the
// longest-match probe loop. Purely an evaluation cache: values are the
// deterministic encode_word output, so cached and uncached paths are
// byte-identical.
struct WordCache {
  static constexpr uint32_t kSlots = 1u << 16;
  static constexpr uint32_t kMask = kSlots - 1;
  static constexpr size_t kMaxEntries = 48000;   // ~0.73 load factor cap
  static constexpr size_t kMaxKeyBytes = 64;     // don't cache pathological words
  // Don't pay the slot-table memset until the call has seen enough words
  // to plausibly amortize it (single-text tokenize calls never do).
  static constexpr uint64_t kActivateAfterWords = 64;
  struct Slot {
    int32_t key_off = -1;
    int32_t key_len = 0;
    int32_t ids_off = 0;
    int32_t ids_len = 0;
  };
  std::vector<Slot> slots;   // empty until activated
  std::string keys;
  std::vector<int32_t> ids;
  size_t entries = 0;
  uint64_t model_gen = 0;    // which Model the cached ids belong to
  uint64_t words_seen = 0;

  bool active() const { return !slots.empty(); }

  // Bind to a model; a switch (or first use) drops all cached entries.
  void attach(const Model& m) {
    if (model_gen != m.gen) {
      slots.clear();
      keys.clear();
      ids.clear();
      entries = 0;
      words_seen = 0;
      model_gen = m.gen;
    }
  }

  void note_word() {
    if (!active() && ++words_seen == kActivateAfterWords) {
      slots.assign(kSlots, Slot{});
      keys.reserve(1 << 18);
      ids.reserve(1 << 16);
    }
  }

  // Linear-probe to the slot holding `w` (found=true) or the first empty
  // slot (found=false). The entry cap keeps at least one slot empty, so the
  // probe always terminates.
  uint32_t probe(std::string_view w, bool& found) const {
    uint32_t idx = static_cast<uint32_t>(SvHash{}(w)) & kMask;
    while (true) {
      const Slot& s = slots[idx];
      if (s.key_off < 0) { found = false; return idx; }
      if (static_cast<size_t>(s.key_len) == w.size() &&
          std::memcmp(keys.data() + s.key_off, w.data(), w.size()) == 0) {
        found = true;
        return idx;
      }
      idx = (idx + 1) & kMask;
    }
  }

  void insert(uint32_t idx, std::string_view w, const int32_t* v, size_t n) {
    if (entries >= kMaxEntries || w.size() > kMaxKeyBytes) return;
    // (idx came from probe() on the active table, so slots is non-empty.)
    Slot& s = slots[idx];
    s.key_off = static_cast<int32_t>(keys.size());
    s.key_len = static_cast<int32_t>(w.size());
    keys.append(w.data(), w.size());
    s.ids_off = static_cast<int32_t>(ids.size());
    s.ids_len = static_cast<int32_t>(n);
    ids.insert(ids.end(), v, v + n);
    ++entries;
  }
};

// ------------------------------------------------------- word -> wordpiece

struct Word {
  // Normalized UTF-8 bytes plus codepoint boundary offsets.
  std::string bytes;
  std::vector<int32_t> cp_off;  // size = n_cp + 1
};

// One cache per OS thread, rebound (and flushed) on model switch. The
// calling thread keeps its cache warm across encode calls; short-lived
// worker threads get a fresh one, whose cost lazy activation bounds.
WordCache& local_word_cache(const Model& m) {
  static thread_local WordCache cache;
  cache.attach(m);
  return cache;
}

// Greedy longest-match (HF WordPiece::tokenize semantics): whole word
// becomes UNK if any position fails to match.
inline void encode_word(const Model& m, const Word& w,
                        std::vector<int32_t>& out) {
  int32_t n_cp = static_cast<int32_t>(w.cp_off.size()) - 1;
  if (n_cp == 0) return;
  if (n_cp > m.max_input_chars) {
    out.push_back(m.unk_id);
    return;
  }
  size_t mark = out.size();
  int32_t start = 0;
  while (start < n_cp) {
    int32_t end = n_cp;
    int32_t found = -1;
    const auto& map = (start == 0) ? m.roots : m.suffixes;
    // Substrings longer than the longest vocab entry can't match; skip
    // straight down to the first probe-able length.
    const int32_t max_bytes = (start == 0) ? m.max_root_bytes
                                           : m.max_suffix_bytes;
    while (end > start && w.cp_off[end] - w.cp_off[start] > max_bytes) --end;
    while (end > start) {
      std::string_view sub(w.bytes.data() + w.cp_off[start],
                           w.cp_off[end] - w.cp_off[start]);
      auto it = map.find(sub);
      if (it != map.end()) { found = it->second; break; }
      --end;
    }
    if (found < 0) {
      out.resize(mark);
      out.push_back(m.unk_id);
      return;
    }
    out.push_back(found);
    start = end;
  }
}

// Normalize + pre-tokenize + wordpiece one text into `out`.
inline void encode_text(const Model& m, const char* s, int64_t len,
                        std::vector<int32_t>& out, int32_t max_tokens,
                        WordCache& cache) {
  Word w;
  w.bytes.reserve(32);
  w.cp_off.reserve(33);
  size_t start_size = out.size();
  int64_t i = 0;
  auto flush_word = [&]() {
    if (!w.bytes.empty()) {
      if (cache.active()) {
        std::string_view key(w.bytes);
        bool found;
        uint32_t idx = cache.probe(key, found);
        if (found) {
          const WordCache::Slot& sl = cache.slots[idx];
          out.insert(out.end(), cache.ids.data() + sl.ids_off,
                     cache.ids.data() + sl.ids_off + sl.ids_len);
        } else {
          size_t before = out.size();
          encode_word(m, w, out);
          cache.insert(idx, key, out.data() + before, out.size() - before);
        }
      } else {
        encode_word(m, w, out);
        cache.note_word();
      }
      w.bytes.clear();
      w.cp_off.clear();
    }
  };
  w.cp_off.clear();
  auto push_cp = [&](uint32_t cp) {
    if (w.cp_off.empty()) w.cp_off.push_back(0);
    encode_utf8(cp, w.bytes);
    w.cp_off.push_back(static_cast<int32_t>(w.bytes.size()));
  };
  auto is_word_byte = [](unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9');
  };
  while (i < len) {
    if (max_tokens > 0 &&
        out.size() - start_size >= static_cast<size_t>(max_tokens))
      break;
    unsigned char c0 = static_cast<unsigned char>(s[i]);
    // Fast path for the dominant case: runs of lowercase ASCII letters /
    // digits append to the current word byte-for-byte (no decode,
    // classification, or re-encode), and a single space flushes. The
    // budget check above only changes value at flush boundaries, so
    // skipping it within a run leaves the output byte-identical.
    if (is_word_byte(c0)) {
      if (w.cp_off.empty()) w.cp_off.push_back(0);
      do {
        w.bytes.push_back(static_cast<char>(c0));
        w.cp_off.push_back(static_cast<int32_t>(w.bytes.size()));
        ++i;
        if (i >= len) break;
        c0 = static_cast<unsigned char>(s[i]);
      } while (is_word_byte(c0));
      continue;
    }
    if (c0 == ' ') {
      flush_word();
      ++i;
      continue;
    }
    uint32_t cp = decode_utf8(s, len, i);
    if (cp == 0 || cp == 0xFFFD || is_control(cp)) continue;
    if (is_whitespace(cp)) { flush_word(); continue; }
    if (m.lowercase) {
      cp = lower_strip(cp);
      if (cp == 0) continue;
    }
    if (is_cjk(cp) || is_punctuation(cp)) {
      flush_word();
      push_cp(cp);
      flush_word();
      continue;
    }
    push_cp(cp);
  }
  flush_word();
  if (max_tokens > 0 &&
      out.size() - start_size > static_cast<size_t>(max_tokens))
    out.resize(start_size + max_tokens);
}

// ------------------------------------------------------ sentence splitting
// Exact port of lddl_tpu/tokenization/sentences.py's rule-based splitter:
// boundary = [.!?]+['")\]]* whitespace+ (?=["'([]?[A-Z0-9]), except after
// abbreviations / initials when the boundary involves '.'.

inline bool abbrev_core_matches(std::string_view core) {
  static const char* kAbbrev[] = {
      "mr", "mrs", "ms", "dr", "prof", "sr", "jr", "st", "vs", "etc", "inc",
      "ltd", "co", "corp", "dept", "univ", "assn", "bros", "e.g", "i.e",
      "cf", "al", "ave", "blvd", "rd", "fig", "no", "vol", "pp", "op",
      "cit", "ca", "gen", "col", "sgt", "capt", "lt", "cmdr", "adm", "gov",
      "sen", "rep", "rev", "hon", "pres", "supt", "det", "mt", "ft",
      "approx"};
  std::string low(core);
  for (char& c : low)
    if (c >= 'A' && c <= 'Z') c += 32;
  for (const char* a : kAbbrev)
    if (low == a) return true;
  return false;
}

inline bool is_ascii_alpha(char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z');
}

// Mirror of _looks_like_abbreviation(text_before) in sentences.py.
inline bool looks_like_abbreviation(const char* s, int64_t start, int64_t end) {
  // Last whitespace-separated token of s[start:end] (Python rsplit(None,1)).
  int64_t e = end;
  while (e > start && static_cast<unsigned char>(s[e - 1]) <= ' ') --e;
  if (e == start) return false;
  int64_t b = e;
  while (b > start && static_cast<unsigned char>(s[b - 1]) > ' ') --b;
  // lstrip('("\'[')
  while (b < e && (s[b] == '(' || s[b] == '"' || s[b] == '\'' || s[b] == '['))
    ++b;
  if (b >= e) return false;
  int64_t core_end = (s[e - 1] == '.') ? e - 1 : e;
  std::string_view core(s + b, core_end - b);
  if (core.empty()) return false;
  if (abbrev_core_matches(core)) return true;
  if (core.size() == 1 && core[0] >= 'A' && core[0] <= 'Z') return true;
  // Dotted initialisms: (?:[A-Za-z]\.)+[A-Za-z]?
  {
    size_t i = 0;
    bool any = false;
    while (i + 1 < core.size() && is_ascii_alpha(core[i]) &&
           core[i + 1] == '.') {
      i += 2;
      any = true;
    }
    if (any) {
      if (i == core.size()) return true;
      if (i + 1 == core.size() && is_ascii_alpha(core[i])) return true;
    }
  }
  return false;
}

inline bool is_py_space(char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
         c == '\v';
}

// Emit [start,end) byte ranges of sentences in text (ASCII-rule splitter;
// multibyte UTF-8 content passes through inside sentences untouched).
inline void split_sentences_rule(const char* s, int64_t len,
                                 std::vector<int64_t>& bounds) {
  auto strip_range = [&](int64_t b, int64_t e, int64_t& ob, int64_t& oe) {
    while (b < e && is_py_space(s[b])) ++b;
    while (e > b && is_py_space(s[e - 1])) --e;
    ob = b; oe = e;
  };
  int64_t start = 0;
  int64_t i = 0;
  while (i < len) {
    char c = s[i];
    if (c != '.' && c != '!' && c != '?') { ++i; continue; }
    int64_t punct_start = i;
    while (i < len && (s[i] == '.' || s[i] == '!' || s[i] == '?')) ++i;
    int64_t group_mid = i;  // end of [.!?]+ run
    while (i < len && (s[i] == '\'' || s[i] == '"' || s[i] == ')' ||
                       s[i] == ']'))
      ++i;
    int64_t group_end = i;  // end of group(1)
    // \s+ (regex \s on str: space, \t..\r, \f, \v; ASCII view suffices here)
    int64_t ws_end = i;
    while (ws_end < len && is_py_space(s[ws_end])) ++ws_end;
    if (ws_end == i) { continue; }  // no whitespace: not a boundary
    // lookahead (?=["'([]?[A-Z0-9])
    int64_t la = ws_end;
    if (la < len && (s[la] == '"' || s[la] == '\'' || s[la] == '(' ||
                     s[la] == '['))
      ++la;
    if (!(la < len &&
          ((s[la] >= 'A' && s[la] <= 'Z') || (s[la] >= '0' && s[la] <= '9')))) {
      i = group_end;
      continue;
    }
    // Abbreviation guard applies when the group's last char or first char
    // is '.' (sentences.py:46-48).
    bool dotty = (s[group_end - 1] == '.') || (s[punct_start] == '.');
    if (dotty && looks_like_abbreviation(s, start, group_end)) {
      i = group_end;
      continue;
    }
    int64_t ob, oe;
    strip_range(start, group_end, ob, oe);
    if (oe > ob) { bounds.push_back(ob); bounds.push_back(oe); }
    start = ws_end;
    i = ws_end;
  }
  int64_t ob, oe;
  strip_range(start, len, ob, oe);
  if (oe > ob) { bounds.push_back(ob); bounds.push_back(oe); }
}

struct ThreadSlice {
  std::vector<int32_t> ids;
  std::vector<int64_t> seq_ends;    // per-sequence end offset (local)
  std::vector<int64_t> seq_owner;   // which input text produced it (docs mode)
};

// Shared decode loop: id ranges -> Arrow string-column buffers. Returns
// total data bytes, -1 on cap overflow, -2 past the int32 offset limit.
// (Body of lddl_decode_join, reused by the fused columnar emitter.)
int64_t decode_join_impl(const Model& m, const int32_t* ids,
                         const int64_t* offsets, int64_t n_seqs,
                         char* out_data, int64_t cap_data,
                         int32_t* out_offsets) {
  const int32_t nvocab = static_cast<int32_t>(m.tokens.size());
  const char* arena = m.decode_arena.data();
  const int32_t* lens = m.decode_lens.data();
  constexpr int32_t kStride = Model::kDecodeStride;
  int64_t pos = 0;
  out_offsets[0] = 0;
  for (int64_t s = 0; s < n_seqs; ++s) {
    for (int64_t k = offsets[s]; k < offsets[s + 1]; ++k) {
      const int32_t id = ids[k];
      const bool first = (k == offsets[s]);
      if (id >= 0 && id < nvocab && lens[id] < kStride - 1 &&
          pos + kStride + 1 <= cap_data) {
        // Hot path: one unconditional fixed-width copy of the arena slot
        // (' ' + token, zero-padded); the advance truncates the padding.
        // First-of-sequence reads from slot+1 to skip the space (the
        // trailing arena pad byte makes the over-read safe).
        std::memcpy(out_data + pos,
                    arena + static_cast<size_t>(id) * kStride + (first ? 1 : 0),
                    kStride);
        pos += lens[id] + (first ? 0 : 1);
      } else {
        // Exact path: long/invalid ids, or too close to the buffer end
        // for the wide store (callers leave slack, so this is rare).
        std::string_view tok = (id >= 0 && id < nvocab)
                                   ? m.tokens[id]
                                   : std::string_view("[UNK]");
        int64_t need = static_cast<int64_t>(tok.size()) + (first ? 0 : 1);
        if (pos + need > cap_data) return -1;
        if (!first) out_data[pos++] = ' ';
        std::memcpy(out_data + pos, tok.data(), tok.size());
        pos += static_cast<int64_t>(tok.size());
      }
    }
    // Arrow string offsets are int32; joined output past 2 GiB must fail
    // loudly (callers split the batch), never wrap into corrupt offsets.
    if (pos > INT32_MAX) return -2;
    out_offsets[s + 1] = static_cast<int32_t>(pos);
  }
  return pos;
}

// Exact joined-output byte count for one column of id ranges (token byte
// lengths + one separator between tokens of a sequence). The caller adds
// the wide-store slack itself.
int64_t decode_join_size(const Model& m, const int32_t* ids,
                         const int64_t* offsets, int64_t n_seqs) {
  const int32_t nvocab = static_cast<int32_t>(m.tokens.size());
  const int32_t* lens = m.decode_lens.data();
  int64_t total = 0;
  const int64_t n_ids = offsets[n_seqs];
  for (int64_t k = 0; k < n_ids; ++k) {
    const int32_t id = ids[k];
    total += (id >= 0 && id < nvocab) ? lens[id] : 5;  // '[UNK]'
  }
  for (int64_t s = 0; s < n_seqs; ++s) {
    const int64_t cnt = offsets[s + 1] - offsets[s];
    if (cnt > 1) total += cnt - 1;
  }
  return total;
}

// The exact .npy v1.0 header np.save writes for a 1-D '<u2' array of n
// elements (mirror of core/utils._npy_header — the fused positions
// column must be byte-identical to the numpy framing path). Writes into
// buf (>= 192 bytes is always enough) and returns the header length.
int64_t npy_header_u2(int64_t n, char* buf) {
  char body[96];
  int len0 = std::snprintf(
      body, sizeof(body),
      "{'descr': '<u2', 'fortran_order': False, 'shape': (%lld,), }",
      static_cast<long long>(n));
  int64_t pad = ((-(10 + len0 + 1)) % 64 + 64) % 64;
  int64_t body_len = len0 + pad + 1;
  std::memcpy(buf, "\x93NUMPY\x01\x00", 8);
  buf[8] = static_cast<char>(body_len & 0xFF);
  buf[9] = static_cast<char>((body_len >> 8) & 0xFF);
  std::memcpy(buf + 10, body, len0);
  std::memset(buf + 10 + len0, ' ', pad);
  buf[10 + len0 + pad] = '\n';
  return 10 + body_len;
}

void run_threads(int64_t n_items, int nthreads,
                 const std::function<void(int64_t, int64_t, int)>& body) {
  if (nthreads <= 1 || n_items <= 1) {
    body(0, n_items, 0);
    return;
  }
  if (nthreads > n_items) nthreads = static_cast<int>(n_items);
  std::vector<std::thread> threads;
  int64_t chunk = (n_items + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(n_items, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back(body, lo, hi, t);
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// Build a model. vocab_blob: concatenated UTF-8 token bytes; offsets:
// int64[n+1] boundaries; tokens are in id order.
void* lddl_wp_create(const char* vocab_blob, const int64_t* offsets,
                     int32_t n, int32_t unk_id, int32_t lowercase,
                     int32_t max_input_chars) {
  Model* m = new Model();
  m->vocab_blob.assign(vocab_blob, offsets[n]);
  m->tokens.resize(n);
  m->roots.reserve(n * 2);
  m->suffixes.reserve(n);
  for (int32_t i = 0; i < n; ++i) {
    std::string_view tok(m->vocab_blob.data() + offsets[i],
                         offsets[i + 1] - offsets[i]);
    m->tokens[i] = tok;
    if (tok.size() > 2 && tok[0] == '#' && tok[1] == '#') {
      m->suffixes.emplace(tok.substr(2), i);
      m->max_suffix_bytes = std::max<int32_t>(
          m->max_suffix_bytes, static_cast<int32_t>(tok.size()) - 2);
    } else {
      m->roots.emplace(tok, i);
      m->max_root_bytes = std::max<int32_t>(
          m->max_root_bytes, static_cast<int32_t>(tok.size()));
    }
  }
  m->unk_id = unk_id;
  m->lowercase = lowercase != 0;
  m->max_input_chars = max_input_chars;
  // One extra stride of zero padding: the first-of-sequence fast path
  // reads kDecodeStride bytes from slot + 1, which for the last id would
  // otherwise run one byte past the arena.
  m->decode_arena.assign(
      static_cast<size_t>(n + 1) * Model::kDecodeStride, 0);
  m->decode_lens.resize(n);
  for (int32_t i = 0; i < n; ++i) {
    char* slot = m->decode_arena.data() +
                 static_cast<size_t>(i) * Model::kDecodeStride;
    slot[0] = ' ';
    size_t len = std::min<size_t>(m->tokens[i].size(),
                                  Model::kDecodeStride - 1);
    std::memcpy(slot + 1, m->tokens[i].data(), len);
    m->decode_lens[i] = static_cast<int32_t>(m->tokens[i].size());
  }
  return m;
}

void lddl_wp_destroy(void* model) { delete static_cast<Model*>(model); }

// Encode n_texts texts (concatenated blob + int64[n+1] offsets).
// out_ids: int32 capacity `cap` (>= blob byte length is always enough);
// out_offsets: int64[n_texts+1]. max_tokens<=0 means unlimited.
// Returns total id count, or -1 if cap insufficient.
int64_t lddl_wp_encode_batch(void* model, const char* blob,
                             const int64_t* offsets, int64_t n_texts,
                             int32_t max_tokens, int32_t* out_ids,
                             int64_t cap, int64_t* out_offsets,
                             int32_t nthreads) {
  const Model& m = *static_cast<Model*>(model);
  std::vector<ThreadSlice> slices(std::max<int64_t>(
      1, std::min<int64_t>(nthreads <= 0 ? 1 : nthreads, n_texts)));
  int real_threads = static_cast<int>(slices.size());
  std::vector<std::pair<int64_t, int64_t>> ranges(real_threads);
  int64_t chunk = (n_texts + real_threads - 1) / real_threads;
  auto body = [&](int64_t lo, int64_t hi, int t) {
    ThreadSlice& sl = slices[t];
    ranges[t] = {lo, hi};
    sl.ids.reserve((offsets[hi] - offsets[lo]) / 4 + 16);
    WordCache& cache = local_word_cache(m);
    for (int64_t k = lo; k < hi; ++k) {
      encode_text(m, blob + offsets[k], offsets[k + 1] - offsets[k], sl.ids,
                  max_tokens, cache);
      sl.seq_ends.push_back(static_cast<int64_t>(sl.ids.size()));
    }
  };
  run_threads(n_texts, real_threads, body);
  int64_t total = 0;
  for (auto& sl : slices) total += static_cast<int64_t>(sl.ids.size());
  if (total > cap) return -1;
  int64_t pos = 0, seq = 0;
  out_offsets[0] = 0;
  for (int t = 0; t < real_threads; ++t) {
    ThreadSlice& sl = slices[t];
    if (!sl.ids.empty())
      std::memcpy(out_ids + pos, sl.ids.data(), sl.ids.size() * 4);
    for (int64_t e : sl.seq_ends) out_offsets[++seq] = pos + e;
    pos += static_cast<int64_t>(sl.ids.size());
  }
  return total;
}

// Sentence-split one text; writes up to cap (start,end) byte-range pairs.
// Returns number of sentences (caller retries with bigger buffer if > cap).
int64_t lddl_split_sentences(const char* text, int64_t len,
                             int64_t* out_bounds, int64_t cap) {
  std::vector<int64_t> bounds;
  split_sentences_rule(text, len, bounds);
  int64_t n = static_cast<int64_t>(bounds.size()) / 2;
  if (n <= cap)
    std::memcpy(out_bounds, bounds.data(), bounds.size() * sizeof(int64_t));
  return n;
}

// Full document front end: for each document (blob + offsets), rule-split
// into sentences and WordPiece-encode each sentence, dropping sentences
// that produce no tokens. Outputs ragged ids with per-sentence offsets and
// per-document sentence counts.
// Capacities: out_ids cap_ids (blob bytes is enough), out_sent_offsets
// cap_sents+1 entries, out_doc_counts int64[n_docs].
// Returns total ids, or -1 (cap_ids) / -2 (cap_sents) on overflow.
int64_t lddl_encode_docs(void* model, const char* blob,
                         const int64_t* offsets, int64_t n_docs,
                         int32_t max_tokens_per_sent, int32_t* out_ids,
                         int64_t cap_ids, int64_t* out_sent_offsets,
                         int64_t cap_sents, int64_t* out_doc_counts,
                         int32_t nthreads) {
  const Model& m = *static_cast<Model*>(model);
  int real_threads = static_cast<int>(std::max<int64_t>(
      1, std::min<int64_t>(nthreads <= 0 ? 1 : nthreads, n_docs)));
  struct DocSlice {
    std::vector<int32_t> ids;
    std::vector<int64_t> sent_ends;  // local id-offsets per kept sentence
    std::vector<int64_t> doc_counts;
  };
  std::vector<DocSlice> slices(real_threads);
  auto body = [&](int64_t lo, int64_t hi, int t) {
    DocSlice& sl = slices[t];
    std::vector<int64_t> bounds;
    WordCache& cache = local_word_cache(m);
    for (int64_t d = lo; d < hi; ++d) {
      const char* text = blob + offsets[d];
      int64_t len = offsets[d + 1] - offsets[d];
      bounds.clear();
      split_sentences_rule(text, len, bounds);
      int64_t kept = 0;
      for (size_t b = 0; b + 1 < bounds.size(); b += 2) {
        size_t before = sl.ids.size();
        encode_text(m, text + bounds[b], bounds[b + 1] - bounds[b], sl.ids,
                    max_tokens_per_sent, cache);
        if (sl.ids.size() > before) {
          sl.sent_ends.push_back(static_cast<int64_t>(sl.ids.size()));
          ++kept;
        }
      }
      sl.doc_counts.push_back(kept);
    }
  };
  run_threads(n_docs, real_threads, body);
  int64_t total_ids = 0, total_sents = 0, doc_i = 0;
  for (auto& sl : slices) {
    total_ids += static_cast<int64_t>(sl.ids.size());
    total_sents += static_cast<int64_t>(sl.sent_ends.size());
  }
  if (total_ids > cap_ids) return -1;
  if (total_sents > cap_sents) return -2;
  int64_t pos = 0, sent = 0;
  out_sent_offsets[0] = 0;
  for (auto& sl : slices) {
    if (!sl.ids.empty())
      std::memcpy(out_ids + pos, sl.ids.data(), sl.ids.size() * 4);
    for (int64_t e : sl.sent_ends) out_sent_offsets[++sent] = pos + e;
    for (int64_t c : sl.doc_counts) out_doc_counts[doc_i++] = c;
    pos += static_cast<int64_t>(sl.ids.size());
  }
  return total_ids;
}

// Decode: for each of n_seqs id ranges, emit the space-joined token string.
// Outputs Arrow string-column buffers: out_offsets int32[n_seqs+1] and
// out_data (cap_data bytes). Returns total data bytes, or -1 on overflow.
int64_t lddl_decode_join(void* model, const int32_t* ids,
                         const int64_t* offsets, int64_t n_seqs,
                         char* out_data, int64_t cap_data,
                         int32_t* out_offsets) {
  const Model& m = *static_cast<Model*>(model);
  return decode_join_impl(m, ids, offsets, n_seqs, out_data, cap_data,
                          out_offsets);
}

// ------------------------------------------------- fused columnar emit
// One sizes pass + one emit pass build every Arrow column of a shard
// directly from token ids: up to `ncols` string columns (per-column ids +
// int64[n+1] offsets) and optionally one npy-framed uint16 binary column
// (masked_lm_positions). This replaces, per column, the Python-side
// capacity LUT pass, the decode call, and the vectorized-numpy npy
// framing — all output bytes are identical to those paths.

// Sizes: out_caps[c] = exact joined bytes of column c plus wide-store
// slack (kDecodeStride + a final-token pad, rounded to 48 to match the
// Python caller's historical slack). When pos_offs is non-null,
// out_pos_boffs (int64[pos_n+1]) receives the npy-framed row byte
// offsets. Returns 0.
int64_t lddl_columnar_sizes(void* model, int32_t ncols,
                            const int32_t* const* ids,
                            const int64_t* const* offs, const int64_t* ns,
                            int64_t* out_caps, const int64_t* pos_offs,
                            int64_t pos_n, int64_t* out_pos_boffs) {
  const Model& m = *static_cast<Model*>(model);
  for (int32_t c = 0; c < ncols; ++c)
    out_caps[c] = decode_join_size(m, ids[c], offs[c], ns[c]) + 48;
  if (pos_offs != nullptr && out_pos_boffs != nullptr) {
    char hdr[192];
    int64_t prev_cnt = -1, prev_hdr = 0;
    out_pos_boffs[0] = 0;
    for (int64_t i = 0; i < pos_n; ++i) {
      const int64_t cnt = pos_offs[i + 1] - pos_offs[i];
      if (cnt != prev_cnt) {
        prev_hdr = npy_header_u2(cnt, hdr);
        prev_cnt = cnt;
      }
      out_pos_boffs[i + 1] = out_pos_boffs[i] + prev_hdr + 2 * cnt;
    }
  }
  return 0;
}

// Emit: fill each column's (int32[n+1] offsets, data) buffers and, when
// pos_vals is non-null, the positions binary data (headers + raw
// little-endian uint16 payloads at the boffs computed by the sizes
// pass). Column tasks run on up to `nthreads` threads. Returns 0, or the
// first column's negative rc (-1 capacity, -2 int32 offset overflow).
int64_t lddl_columnar_emit(void* model, int32_t ncols,
                           const int32_t* const* ids,
                           const int64_t* const* offs, const int64_t* ns,
                           int32_t* const* out_offs, char* const* out_data,
                           const int64_t* caps, const uint16_t* pos_vals,
                           const int64_t* pos_offs, int64_t pos_n,
                           const int64_t* pos_boffs, char* pos_data,
                           int32_t nthreads) {
  const Model& m = *static_cast<Model*>(model);
  const int64_t n_tasks = ncols + (pos_vals != nullptr ? 1 : 0);
  std::vector<int64_t> rc(n_tasks, 0);
  auto body = [&](int64_t lo, int64_t hi, int t) {
    (void)t;
    for (int64_t task = lo; task < hi; ++task) {
      if (task < ncols) {
        int64_t r = decode_join_impl(m, ids[task], offs[task], ns[task],
                                     out_data[task], caps[task],
                                     out_offs[task]);
        rc[task] = r < 0 ? r : 0;
      } else {
        char hdr[192];
        int64_t prev_cnt = -1, prev_hdr = 0;
        for (int64_t i = 0; i < pos_n; ++i) {
          const int64_t cnt = pos_offs[i + 1] - pos_offs[i];
          if (cnt != prev_cnt) {
            prev_hdr = npy_header_u2(cnt, hdr);
            prev_cnt = cnt;
          }
          char* row = pos_data + pos_boffs[i];
          std::memcpy(row, hdr, prev_hdr);
          std::memcpy(row + prev_hdr, pos_vals + pos_offs[i], 2 * cnt);
        }
      }
    }
  };
  run_threads(n_tasks, nthreads, body);
  for (int64_t r : rc)
    if (r < 0) return r;
  return 0;
}

int64_t lddl_native_abi_version() { return 4; }

}  // extern "C"

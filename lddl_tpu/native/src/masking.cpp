// lddl_tpu native host kernel: per-row top-k selection for MLM masking.
//
// Replaces the numpy argpartition + take_along_axis + argsort + nonzero
// chain in lddl_tpu/ops/masking.py's host path. Inputs are the tie-free
// uint64 sort keys (positive-float bit patterns with the lane index in
// the low bits — see mask_batch_host) and the per-row pick count k; the
// output is the picked (row, col) index pairs in row-major ascending
// order, exactly matching np.nonzero(picked) on the boolean matrix the
// numpy path builds — so the downstream decide/replacement RNG draws
// line up draw-for-draw and the masked output is bit-identical.

#include <algorithm>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

void topk_rows(const uint64_t* keys, const int64_t* k, int64_t lo,
               int64_t hi, int64_t l, const int64_t* out_offsets,
               int64_t* out_cols) {
  std::vector<uint64_t> scratch(l);
  for (int64_t r = lo; r < hi; ++r) {
    int64_t kk = k[r];
    if (kk <= 0) continue;
    if (kk > l) kk = l;
    const uint64_t* row = keys + r * l;
    // Keys are unique (lane index in the low bits), so the kth-smallest
    // value is a clean threshold: one nth_element on values, then one
    // ascending scan emits the picked columns already sorted.
    std::copy(row, row + l, scratch.begin());
    std::nth_element(scratch.begin(), scratch.begin() + (kk - 1),
                     scratch.end());
    uint64_t kth = scratch[kk - 1];
    int64_t* out = out_cols + out_offsets[r];
    for (int64_t j = 0; j < l; ++j)
      if (row[j] <= kth) *out++ = j;
  }
}

}  // namespace

extern "C" {

// keys: uint64[n*l] row-major; k: int64[n] (clamped to [0, l]);
// out_offsets: int64[n+1] exclusive prefix sums of k (caller-computed);
// out_cols: int64[out_offsets[n]]. Rows are emitted at their offset, so
// the flat (repeat(rows, k), out_cols) pairing is row-major ascending.
void lddl_mask_topk(const uint64_t* keys, const int64_t* k, int64_t n,
                    int64_t l, const int64_t* out_offsets, int64_t* out_cols,
                    int32_t nthreads) {
  if (nthreads <= 1 || n <= 1) {
    topk_rows(keys, k, 0, n, l, out_offsets, out_cols);
    return;
  }
  if (nthreads > n) nthreads = static_cast<int32_t>(n);
  std::vector<std::thread> threads;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int32_t t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back(topk_rows, keys, k, lo, hi, l, out_offsets,
                         out_cols);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"

// lddl_tpu native host kernels for MLM masking.
//
// lddl_mask_topk: per-row top-k selection (replaces the numpy
// argpartition + take_along_axis + argsort + nonzero chain in
// lddl_tpu/ops/masking.py's padded-matrix host path). Inputs are the
// tie-free uint64 sort keys (positive-float bit patterns with the lane
// index in the low bits — see mask_batch_host) and the per-row pick
// count k; the output is the picked (row, col) index pairs in row-major
// ascending order, exactly matching np.nonzero(picked) on the boolean
// matrix the numpy path builds — so the downstream decide/replacement
// RNG draws line up draw-for-draw and the masked output is bit-identical.
//
// lddl_mask_partition: the fused ragged path — gather A/B ids, draw the
// masked positions via partial Fisher-Yates with a counter-based
// Philox4x32-10 stream, apply the 80/10/10 recipe, and emit sorted
// positions + original label ids, all in one pass with no padded id
// matrix. The numpy fallback (ops/masking.py:_mask_partition_numpy)
// implements the identical draw scheme bit-for-bit; parity is tested
// (tests/test_fast_pipeline.py::TestRaggedMaskParity).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

void topk_rows(const uint64_t* keys, const int64_t* k, int64_t lo,
               int64_t hi, int64_t l, const int64_t* out_offsets,
               int64_t* out_cols) {
  std::vector<uint64_t> scratch(l);
  for (int64_t r = lo; r < hi; ++r) {
    int64_t kk = k[r];
    if (kk <= 0) continue;
    if (kk > l) kk = l;
    const uint64_t* row = keys + r * l;
    // Keys are unique (lane index in the low bits), so the kth-smallest
    // value is a clean threshold: one nth_element on values, then one
    // ascending scan emits the picked columns already sorted.
    std::copy(row, row + l, scratch.begin());
    std::nth_element(scratch.begin(), scratch.begin() + (kk - 1),
                     scratch.end());
    uint64_t kth = scratch[kk - 1];
    int64_t* out = out_cols + out_offsets[r];
    for (int64_t j = 0; j < l; ++j)
      if (row[j] <= kth) *out++ = j;
  }
}

// --- Philox4x32-10, the shared counter-based stream spec ---------------
//
// Round function and key schedule follow the standard Philox4x32
// construction (round i uses key (k0 + i*W0, k1 + i*W1)); this is the
// masking stream's own specification, mirrored exactly by the numpy
// fallback. Counter layout per draw t of row r:
//   (c0, c1, c2, c3) = (t, r, 0x6d61736b /* "mask" domain */, 0)
// One call yields 4 x 32 bits: x0 drives the Fisher-Yates index, x1 the
// 80/10/10 decide, x2 the replacement vocab id. Bounded draws use
// Lemire's multiply-shift ((uint64)x * n) >> 32; the residual bias at
// vocab-size scale (~30k / 2^32) is < 1e-5 and deterministic.

struct P4 {
  uint32_t v[4];
};

inline P4 philox4x32(uint32_t c0, uint32_t c1, uint32_t c2, uint32_t c3,
                     uint32_t k0, uint32_t k1) {
  for (uint32_t i = 0; i < 10; ++i) {
    uint32_t ki0 = k0 + i * 0x9E3779B9u;
    uint32_t ki1 = k1 + i * 0xBB67AE85u;
    uint64_t p0 = static_cast<uint64_t>(c0) * 0xD2511F53u;
    uint64_t p1 = static_cast<uint64_t>(c2) * 0xCD9E8D57u;
    uint32_t hi0 = static_cast<uint32_t>(p0 >> 32);
    uint32_t lo0 = static_cast<uint32_t>(p0);
    uint32_t hi1 = static_cast<uint32_t>(p1 >> 32);
    uint32_t lo1 = static_cast<uint32_t>(p1);
    c0 = hi1 ^ c1 ^ ki0;
    c1 = lo1;
    c2 = hi0 ^ c3 ^ ki1;
    c3 = lo0;
  }
  return {{c0, c1, c2, c3}};
}

// decide thresholds: floor(0.8 * 2^32) and floor(0.9 * 2^32).
constexpr uint32_t kMaskThreshold = 3435973836u;
constexpr uint32_t kRandThreshold = 3865470566u;

struct Pick {
  int32_t v;        // valid-position index in [0, na + nb)
  uint32_t decide;  // 80/10/10 draw
  int32_t rand_id;  // replacement id (used when decide >= kRandThreshold)
};

void mask_rows(const int32_t* flat_ids, const int64_t* a_ranges,
               const int64_t* b_ranges, int64_t lo, int64_t hi,
               const int64_t* offs_a, const int64_t* offs_b, const int64_t* k,
               const int64_t* offs_k, uint64_t seed, int32_t vocab_size,
               int32_t mask_id, int32_t* flat_a, int32_t* flat_b,
               uint16_t* pos_out, int32_t* label_out) {
  const uint32_t k0 = static_cast<uint32_t>(seed);
  const uint32_t k1 = static_cast<uint32_t>(seed >> 32);
  std::vector<int32_t> arr;
  std::vector<Pick> picks;
  for (int64_t r = lo; r < hi; ++r) {
    const int64_t a0 = a_ranges[2 * r], a1 = a_ranges[2 * r + 1];
    const int64_t b0 = b_ranges[2 * r], b1 = b_ranges[2 * r + 1];
    const int64_t na = a1 - a0, nb = b1 - b0;
    const int64_t L = na + nb;
    int32_t* outa = flat_a + offs_a[r];
    int32_t* outb = flat_b + offs_b[r];
    std::memcpy(outa, flat_ids + a0, na * sizeof(int32_t));
    std::memcpy(outb, flat_ids + b0, nb * sizeof(int32_t));
    int64_t kk = k[r];
    if (kk <= 0) continue;
    if (kk > L) kk = L;
    arr.resize(L);
    for (int64_t i = 0; i < L; ++i) arr[i] = static_cast<int32_t>(i);
    picks.clear();
    for (int64_t t = 0; t < kk; ++t) {
      P4 x = philox4x32(static_cast<uint32_t>(t), static_cast<uint32_t>(r),
                        0x6d61736bu, 0u, k0, k1);
      int64_t j =
          t + static_cast<int64_t>(
                  (static_cast<uint64_t>(x.v[0]) *
                   static_cast<uint64_t>(L - t)) >> 32);
      std::swap(arr[t], arr[j]);
      int32_t rid = static_cast<int32_t>(
          (static_cast<uint64_t>(x.v[2]) *
           static_cast<uint64_t>(vocab_size)) >> 32);
      picks.push_back({arr[t], x.v[1], rid});
    }
    std::sort(picks.begin(), picks.end(),
              [](const Pick& a, const Pick& b) { return a.v < b.v; });
    uint16_t* po = pos_out + offs_k[r];
    int32_t* lb = label_out + offs_k[r];
    for (size_t i = 0; i < picks.size(); ++i) {
      const Pick& p = picks[i];
      const bool in_a = p.v < na;
      // assembled position: [CLS] A [SEP] B [SEP]
      po[i] = static_cast<uint16_t>(in_a ? p.v + 1 : p.v + 2);
      int32_t* dst = in_a ? outa + p.v : outb + (p.v - na);
      lb[i] = *dst;
      if (p.decide < kMaskThreshold) {
        *dst = mask_id;
      } else if (p.decide >= kRandThreshold) {
        *dst = p.rand_id;
      }
    }
  }
}

}  // namespace

extern "C" {

// keys: uint64[n*l] row-major; k: int64[n] (clamped to [0, l]);
// out_offsets: int64[n+1] exclusive prefix sums of k (caller-computed);
// out_cols: int64[out_offsets[n]]. Rows are emitted at their offset, so
// the flat (repeat(rows, k), out_cols) pairing is row-major ascending.
void lddl_mask_topk(const uint64_t* keys, const int64_t* k, int64_t n,
                    int64_t l, const int64_t* out_offsets, int64_t* out_cols,
                    int32_t nthreads) {
  if (nthreads <= 1 || n <= 1) {
    topk_rows(keys, k, 0, n, l, out_offsets, out_cols);
    return;
  }
  if (nthreads > n) nthreads = static_cast<int32_t>(n);
  std::vector<std::thread> threads;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int32_t t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back(topk_rows, keys, k, lo, hi, l, out_offsets,
                         out_cols);
  }
  for (auto& th : threads) th.join();
}

// Fused ragged masking for one partition (see file header). Layout:
//   flat_ids: int32[] token-id pool; a_ranges/b_ranges: int64[n*2]
//   (start, end) ranges into it; offs_a/offs_b: int64[n+1] output offsets
//   (prefix sums of na/nb); k: int64[n] pick counts, pre-clamped by the
//   caller to [0, na+nb]; offs_k: int64[n+1] prefix sums of k.
// Outputs: flat_a/flat_b (post-masking ids, ragged by na/nb),
//   pos_out: uint16[offs_k[n]] picked positions in the assembled
//   [CLS] A [SEP] B [SEP] row, ascending per row;
//   label_out: int32[offs_k[n]] the pre-masking ids at those positions.
void lddl_mask_partition(const int32_t* flat_ids, const int64_t* a_ranges,
                         const int64_t* b_ranges, int64_t n,
                         const int64_t* offs_a, const int64_t* offs_b,
                         const int64_t* k, const int64_t* offs_k,
                         uint64_t seed, int32_t vocab_size, int32_t mask_id,
                         int32_t* flat_a, int32_t* flat_b, uint16_t* pos_out,
                         int32_t* label_out, int32_t nthreads) {
  if (nthreads <= 1 || n <= 1) {
    mask_rows(flat_ids, a_ranges, b_ranges, 0, n, offs_a, offs_b, k, offs_k,
              seed, vocab_size, mask_id, flat_a, flat_b, pos_out, label_out);
    return;
  }
  if (nthreads > n) nthreads = static_cast<int32_t>(n);
  std::vector<std::thread> threads;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int32_t t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back(mask_rows, flat_ids, a_ranges, b_ranges, lo, hi,
                         offs_a, offs_b, k, offs_k, seed, vocab_size, mask_id,
                         flat_a, flat_b, pos_out, label_out);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"

// Native NSP pair planner: a draw-for-draw mirror of the Python planner
// (lddl_tpu/preprocess/pairing.py), which itself mirrors the reference
// recipe (lddl/dask/bert/pretrain.py:241-365). The planner was the last
// pure-Python hot loop of the fast preprocess path (~40% of partition
// time including CPython Random overhead); running it natively keeps the
// outputs bit-identical because the embedded RNG reproduces CPython's
// random.Random exactly:
//
//   - MT19937 core identical to CPython _randommodule.c (same
//     regeneration and tempering);
//   - random()   = genrand_res53 (two 32-bit draws);
//   - getrandbits(k<=32) = genrand() >> (32-k);
//   - randint(a,b) = a + _randbelow(b-a+1) with CPython's
//     rejection-sampling _randbelow_with_getrandbits loop (the variable
//     draw count on rejection is part of the contract — a different
//     sampler would desynchronize every later draw).
//
// State is imported from Random.getstate() and exported back, so Python
// draws after the call continue the identical stream.

#include <algorithm>
#include <cstdint>
#include <cstring>

namespace {

struct PyRandom {
  uint32_t mt[624];
  int mti;

  uint32_t genrand() {
    constexpr uint32_t kMatrixA = 0x9908b0dfu;
    constexpr uint32_t kUpper = 0x80000000u;
    constexpr uint32_t kLower = 0x7fffffffu;
    if (mti >= 624) {
      int kk;
      uint32_t y;
      for (kk = 0; kk < 624 - 397; kk++) {
        y = (mt[kk] & kUpper) | (mt[kk + 1] & kLower);
        mt[kk] = mt[kk + 397] ^ (y >> 1) ^ ((y & 1u) ? kMatrixA : 0u);
      }
      for (; kk < 623; kk++) {
        y = (mt[kk] & kUpper) | (mt[kk + 1] & kLower);
        mt[kk] = mt[kk - 227] ^ (y >> 1) ^ ((y & 1u) ? kMatrixA : 0u);
      }
      y = (mt[623] & kUpper) | (mt[0] & kLower);
      mt[623] = mt[396] ^ (y >> 1) ^ ((y & 1u) ? kMatrixA : 0u);
      mti = 0;
    }
    uint32_t y = mt[mti++];
    y ^= y >> 11;
    y ^= (y << 7) & 0x9d2c5680u;
    y ^= (y << 15) & 0xefc60000u;
    y ^= y >> 18;
    return y;
  }

  double random01() {
    uint32_t a = genrand() >> 5, b = genrand() >> 6;
    return (a * 67108864.0 + b) * (1.0 / 9007199254740992.0);
  }

  // k must be in [1, 32] (all widths in the planner fit 32 bits).
  uint32_t getrandbits(int k) { return genrand() >> (32 - k); }

  int64_t randbelow(int64_t n) {
    // n < 1 would make __builtin_clzll(0) UB; callers validate
    // (max_seq_length >= 5 is enforced Python-side, mirroring CPython's
    // ValueError for an empty randint range), so this is pure defense.
    if (n < 1) return 0;
    int k = 64 - __builtin_clzll(static_cast<uint64_t>(n));  // bit_length
    uint32_t r = getrandbits(k);
    while (static_cast<int64_t>(r) >= n) r = getrandbits(k);
    return r;
  }

  int64_t randint(int64_t a, int64_t b) { return a + randbelow(b - a + 1); }
};

}  // namespace

extern "C" {

// Plans NSP pairs for a partition. Writes rows (a0, a1, b0, b1,
// is_random_next) into out[cap][5]; returns the row count, or -1 if cap
// would be exceeded (callers size cap = duplicate_factor * n_sents, an
// upper bound since every emitted pair permanently consumes >= 1
// sentence). mt_state[624] / mt_index are CPython Random state, updated
// in place.
int64_t lddl_plan_pairs(const int64_t* sent_offsets,
                        const int64_t* doc_sent_start, int64_t n_docs,
                        uint32_t* mt_state, int32_t* mt_index,
                        int32_t max_seq_length, double short_seq_prob,
                        int32_t duplicate_factor, int64_t* out, int64_t cap) {
  PyRandom rng;
  std::memcpy(rng.mt, mt_state, sizeof(rng.mt));
  rng.mti = *mt_index;
  int64_t n_out = 0;
  const int64_t max_num_tokens = max_seq_length - 3;

  for (int32_t pass = 0; pass < duplicate_factor; pass++) {
    for (int64_t d = 0; d < n_docs; d++) {
      const int64_t ds = doc_sent_start[d];
      const int64_t n_sent = doc_sent_start[d + 1] - ds;
      int64_t target_seq_length = max_num_tokens;
      if (rng.random01() < short_seq_prob)
        target_seq_length = rng.randint(2, max_num_tokens);

      int64_t chunk_first = 0, chunk_n = 0, chunk_len = 0;
      int64_t i = 0;
      while (i < n_sent) {
        if (chunk_n == 0) chunk_first = i;
        chunk_n += 1;
        chunk_len += sent_offsets[ds + i + 1] - sent_offsets[ds + i];
        if (i == n_sent - 1 || chunk_len >= target_seq_length) {
          // chunk_n >= 1 always holds here.
          int64_t a_end = chunk_n < 2 ? 1 : rng.randint(1, chunk_n - 1);
          int64_t a0 = sent_offsets[ds + chunk_first];
          int64_t a1 = sent_offsets[ds + chunk_first + a_end];
          const int64_t la = a1 - a0;
          bool is_random;
          int64_t b0, b1;
          if (chunk_n == 1 || rng.random01() < 0.5) {
            is_random = true;
            const int64_t target_b = target_seq_length - la;
            int64_t rd = d;
            for (int t = 0; t < 10; t++) {
              int64_t cand = rng.randint(0, n_docs - 1);
              if (cand != d) { rd = cand; break; }
            }
            if (rd == d) is_random = false;
            const int64_t rds = doc_sent_start[rd];
            const int64_t rn = doc_sent_start[rd + 1] - rds;
            const int64_t start = rng.randint(0, rn - 1);
            b0 = sent_offsets[rds + start];
            // First end >= b0 + max(target_b, 1), clamped to the last
            // sentence (numpy searchsorted side='left' == lower_bound).
            const int64_t* ends = sent_offsets + rds + start + 1;
            const int64_t m = rn - start;
            int64_t j = std::lower_bound(ends, ends + m,
                                         b0 + std::max<int64_t>(target_b, 1)) -
                        ends;
            j = std::min(j, rn - start - 1);
            b1 = ends[j];
            i -= chunk_n - a_end;  // unused trailing sentences replay
          } else {
            is_random = false;
            b0 = a1;
            b1 = sent_offsets[ds + chunk_first + chunk_n];
          }
          const int64_t lb = b1 - b0;
          int64_t fa = 0, ba = 0, fb = 0, bb = 0;
          {
            int64_t xa = la, xb = lb;
            while (xa + xb > max_num_tokens) {
              if (xa > xb) {
                if (rng.random01() < 0.5) fa++; else ba++;
                xa--;
              } else {
                if (rng.random01() < 0.5) fb++; else bb++;
                xb--;
              }
            }
          }
          a0 += fa;
          a1 -= ba;
          b0 += fb;
          b1 -= bb;
          if (a1 > a0 && b1 > b0) {
            if (n_out >= cap) return -1;
            int64_t* row = out + n_out * 5;
            row[0] = a0;
            row[1] = a1;
            row[2] = b0;
            row[3] = b1;
            row[4] = is_random ? 1 : 0;
            n_out++;
          }
          chunk_n = 0;
          chunk_len = 0;
        }
        i += 1;
      }
    }
  }
  std::memcpy(mt_state, rng.mt, sizeof(rng.mt));
  *mt_index = rng.mti;
  return n_out;
}

}  // extern "C"

"""Native (C++) host kernels for the TPU framework.

The reference is pure Python and gets its native speed from external
dependencies (HF tokenizers, pyarrow; SURVEY.md §2). This package owns the
in-repo native layer: a C++ WordPiece encoder + sentence segmenter +
string-column builder compiled to a shared library and driven through
ctypes (no pybind11 in the image).

The library is compiled on demand from ``src/wordpiece.cpp`` with g++ and
cached next to the source; ``python -m lddl_tpu.native.build`` prebuilds it
explicitly (setup.py runs this for wheels).
"""

from .build import load_library, build_library  # noqa: F401
from .wordpiece import NativeWordPiece  # noqa: F401

"""ctypes front end for the native WordPiece encoder.

``NativeWordPiece`` replaces the per-sentence Python tokenize loop of the
reference (``lddl/dask/bert/pretrain.py:79-91``) with one GIL-free,
multithreaded C call per partition. Output parity with HuggingFace's
``BertTokenizerFast`` is covered by tests (``tests/test_native.py``) for
ASCII/Latin accents/Greek/Cyrillic/CJK; codepoints outside those ranges
skip accent-stripping (pass through unchanged) — a documented divergence
for exotic scripts.
"""

import ctypes
import os

import numpy as np

from .build import load_library

_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)


def _offsets_blob(texts):
  """Concatenate texts -> (bytes blob, int64[n+1] offsets)."""
  encoded = [t.encode('utf-8') if isinstance(t, str) else t for t in texts]
  offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
  np.cumsum([len(e) for e in encoded], out=offsets[1:])
  return b''.join(encoded), offsets


class NativeWordPiece:
  """C++ trie/longest-match WordPiece over a fixed id-ordered vocabulary."""

  def __init__(self, vocab_words, unk_token='[UNK]', lowercase=True,
               max_input_chars_per_word=100, num_threads=None):
    self._lib = load_library()
    self._vocab_words = list(vocab_words)
    try:
      unk_id = self._vocab_words.index(unk_token)
    except ValueError:
      unk_id = 0
    blob, offsets = _offsets_blob(self._vocab_words)
    self._model = self._lib.lddl_wp_create(
        blob, offsets.ctypes.data_as(_i64p), len(self._vocab_words), unk_id,
        1 if lowercase else 0, max_input_chars_per_word)
    self._unk_id = unk_id
    self.lowercase = lowercase
    self._nthreads = num_threads or min(8, os.cpu_count() or 1)

  @classmethod
  def from_hf(cls, hf_tokenizer, num_threads=None):
    """Build from a HuggingFace BERT tokenizer (same id order and casing)."""
    vocab = hf_tokenizer.get_vocab()
    words = [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])]
    lowercase = getattr(hf_tokenizer, 'do_lower_case', True)
    return cls(words, unk_token=hf_tokenizer.unk_token, lowercase=lowercase,
               num_threads=num_threads)

  def __del__(self):
    model = getattr(self, '_model', None)
    if model:
      self._lib.lddl_wp_destroy(model)
      self._model = None

  # NativeWordPiece is rebuilt (cheaply) rather than shipped across process
  # boundaries: the ctypes model pointer is process-local.
  def __getstate__(self):
    raise TypeError('NativeWordPiece is not picklable; rebuild per process')

  @property
  def vocab_words(self):
    return self._vocab_words

  # ---------------------------------------------------------------- encode

  def encode_batch_ids(self, texts, max_tokens=None):
    """Encode texts -> (flat int32 ids, int64[n+1] offsets)."""
    if not len(texts):
      return np.zeros(0, np.int32), np.zeros(1, np.int64)
    blob, offsets = _offsets_blob(texts)
    cap = max(16, len(blob))
    out_ids = np.empty(cap, dtype=np.int32)
    out_offsets = np.empty(len(texts) + 1, dtype=np.int64)
    total = self._lib.lddl_wp_encode_batch(
        self._model, blob, offsets.ctypes.data_as(_i64p), len(texts),
        max_tokens or 0, out_ids.ctypes.data_as(_i32p), cap,
        out_offsets.ctypes.data_as(_i64p), self._nthreads)
    if total < 0:
      raise RuntimeError('native encode overflow (internal capacity bug)')
    return out_ids[:total].copy(), out_offsets

  def encode_docs(self, doc_texts, max_tokens_per_sent=None):
    """Sentence-split + encode documents in one native call.

    Returns (flat int32 ids, int64 sentence offsets into ids [n_sents+1],
    int64 per-doc sentence counts). Sentences yielding zero tokens are
    dropped (mirrors ``documents_from_lines``).
    """
    if not len(doc_texts):
      return (np.zeros(0, np.int32), np.zeros(1, np.int64),
              np.zeros(0, np.int64))
    blob, offsets = _offsets_blob(doc_texts)
    cap_ids = max(16, len(blob))
    cap_sents = len(blob) + len(doc_texts) + 1
    out_ids = np.empty(cap_ids, dtype=np.int32)
    out_sent_offsets = np.empty(cap_sents + 1, dtype=np.int64)
    out_doc_counts = np.empty(len(doc_texts), dtype=np.int64)
    total = self._lib.lddl_encode_docs(
        self._model, blob, offsets.ctypes.data_as(_i64p), len(doc_texts),
        max_tokens_per_sent or 0, out_ids.ctypes.data_as(_i32p), cap_ids,
        out_sent_offsets.ctypes.data_as(_i64p), cap_sents,
        out_doc_counts.ctypes.data_as(_i64p), self._nthreads)
    if total < 0:
      raise RuntimeError('native encode_docs overflow (internal capacity bug)')
    n_sents = int(out_doc_counts.sum())
    return (out_ids[:total].copy(), out_sent_offsets[:n_sents + 1].copy(),
            out_doc_counts)

  def split_sentences(self, text):
    """Rule-based sentence split (same semantics as the Python 'rules'
    backend in ``lddl_tpu/tokenization/sentences.py``)."""
    data = text.encode('utf-8')
    cap = max(8, len(data) // 2 + 1)
    out = np.empty(cap * 2, dtype=np.int64)
    n = self._lib.lddl_split_sentences(data, len(data),
                                       out.ctypes.data_as(_i64p), cap)
    if n > cap:  # pathological input; retry with exact size
      out = np.empty(n * 2, dtype=np.int64)
      n = self._lib.lddl_split_sentences(data, len(data),
                                         out.ctypes.data_as(_i64p), n)
    bounds = out[:n * 2].reshape(-1, 2)
    return [data[b:e].decode('utf-8') for b, e in bounds]

  # ------------------------------------------------- token-level interface

  def tokenize(self, text, max_length=None):
    ids, _ = self.encode_batch_ids([text], max_tokens=max_length)
    words = self._vocab_words
    return [words[i] for i in ids]

  def batch_tokenize(self, texts, max_length=None):
    ids, offsets = self.encode_batch_ids(texts, max_tokens=max_length)
    words = self._vocab_words
    flat = [words[i] for i in ids]
    return [flat[offsets[k]:offsets[k + 1]] for k in range(len(texts))]

  # ---------------------------------------------------------------- decode

  def decode_join_buffers(self, ids, offsets):
    """ids ranges -> Arrow string-column buffers (int32 offsets, utf8 data).

    Feed straight into ``pyarrow.StringArray.from_buffers`` for a zero-copy
    column of space-joined token strings.
    """
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets) - 1
    # Upper bound: every token expands to at most max(token_len) bytes plus
    # a separator; use sum of per-id lengths computed cheaply via a lens LUT.
    if not hasattr(self, '_lens_lut'):
      self._lens_lut = np.array([len(w.encode('utf-8')) for w in
                                 self._vocab_words], dtype=np.int64)
    n_ids = int(offsets[-1])
    if n_ids:
      # Out-of-range ids decode as '[UNK]' (5 bytes) in the native code;
      # clip them to that length here instead of mis-indexing the LUT.
      used = ids[:n_ids]
      in_range = (used >= 0) & (used < len(self._lens_lut))
      lens = np.where(in_range, self._lens_lut[np.where(in_range, used, 0)], 5)
      # +48 slack keeps the native wide-store fast path active through
      # the final tokens (it needs kDecodeStride+1 bytes of headroom).
      cap = int(lens.sum()) + n_ids + 48
    else:
      cap = 48
    out_data = np.empty(cap, dtype=np.uint8)
    out_offsets = np.empty(n + 1, dtype=np.int32)
    total = self._lib.lddl_decode_join(
        self._model, ids.ctypes.data_as(_i32p),
        offsets.ctypes.data_as(_i64p), n,
        out_data.ctypes.data_as(ctypes.c_char_p), cap,
        out_offsets.ctypes.data_as(_i32p))
    if total == -2:
      raise ValueError(
          'joined string column exceeds 2 GiB (Arrow int32 offset limit); '
          'split the partition into smaller batches')
    if total < 0:
      raise RuntimeError('native decode overflow (internal capacity bug)')
    return out_offsets, out_data[:total]

  def columnar_emit(self, columns, positions=None):
    """Fused Arrow-column build: many string columns (and optionally the
    npy-framed uint16 positions binary column) in one native round trip.

    ``columns`` is a sequence of ``(flat_ids, offsets)`` pairs (the
    :meth:`encode_batch_ids` representation); ``positions`` is an optional
    ``(values_u16, offsets)`` pair. Returns ``(string_parts, pos_parts)``
    where ``string_parts[i]`` is ``(out_offsets int32[n+1], data uint8)``
    — feed into ``pyarrow.StringArray.from_buffers`` — and ``pos_parts``
    is ``(boffs int64[n+1], data uint8)`` matching
    :func:`lddl_tpu.core.utils.u16_batch_binary_parts` byte-for-byte
    (``None`` when ``positions`` is ``None``).

    Versus per-column :meth:`decode_join_buffers` this skips the numpy
    capacity-LUT pass (sizes are computed natively, exactly) and the
    vectorized-numpy npy framing; output bytes are identical.
    """
    import ctypes as c
    cols = [(np.ascontiguousarray(ids, dtype=np.int32),
             np.ascontiguousarray(offs, dtype=np.int64))
            for ids, offs in columns]
    ncols = len(cols)
    ids_p = (c.c_void_p * max(ncols, 1))(
        *[a.ctypes.data for a, _ in cols] or [None])
    offs_p = (c.c_void_p * max(ncols, 1))(
        *[o.ctypes.data for _, o in cols] or [None])
    ns = np.array([len(o) - 1 for _, o in cols] or [0], dtype=np.int64)
    caps = np.zeros(max(ncols, 1), dtype=np.int64)
    if positions is not None:
      pos_vals = np.ascontiguousarray(positions[0], dtype='<u2')
      pos_offs = np.ascontiguousarray(positions[1], dtype=np.int64)
      if int(pos_offs[0]) != 0 or int(pos_offs[-1]) != len(pos_vals):
        # Offsets may describe a sub-span of values (mirror of
        # u16_batch_binary_parts' normalization).
        pos_vals = np.ascontiguousarray(pos_vals[pos_offs[0]:pos_offs[-1]])
        pos_offs = pos_offs - pos_offs[0]
      pos_n = len(pos_offs) - 1
      pos_boffs = np.zeros(pos_n + 1, dtype=np.int64)
      pos_offs_p = pos_offs.ctypes.data_as(_i64p)
      pos_boffs_p = pos_boffs.ctypes.data_as(_i64p)
    else:
      pos_vals = pos_offs = pos_boffs = None
      pos_n = 0
      pos_offs_p = pos_boffs_p = None
    self._lib.lddl_columnar_sizes(
        self._model, ncols, ids_p, offs_p, ns.ctypes.data_as(_i64p),
        caps.ctypes.data_as(_i64p), pos_offs_p, pos_n, pos_boffs_p)
    out = [(np.empty(int(ns[i]) + 1, dtype=np.int32),
            np.empty(int(caps[i]), dtype=np.uint8)) for i in range(ncols)]
    out_offs_p = (c.c_void_p * max(ncols, 1))(
        *[oo.ctypes.data for oo, _ in out] or [None])
    out_data_p = (c.c_void_p * max(ncols, 1))(
        *[od.ctypes.data for _, od in out] or [None])
    if positions is not None:
      pos_data = np.empty(int(pos_boffs[-1]), dtype=np.uint8)
      pos_vals_p = pos_vals.ctypes.data_as(c.POINTER(c.c_uint16))
      pos_data_p = pos_data.ctypes.data_as(c.c_char_p)
    else:
      pos_data = None
      pos_vals_p = pos_data_p = None
    rc = self._lib.lddl_columnar_emit(
        self._model, ncols, ids_p, offs_p, ns.ctypes.data_as(_i64p),
        out_offs_p, out_data_p, caps.ctypes.data_as(_i64p), pos_vals_p,
        pos_offs_p, pos_n, pos_boffs_p, pos_data_p, self._nthreads)
    if rc == -2:
      raise ValueError(
          'joined string column exceeds 2 GiB (Arrow int32 offset limit); '
          'split the partition into smaller batches')
    if rc < 0:
      raise RuntimeError('native columnar emit overflow (capacity bug)')
    string_parts = [(oo, od[:int(oo[-1])]) for oo, od in out]
    pos_parts = (pos_boffs, pos_data) if positions is not None else None
    return string_parts, pos_parts

  def decode_join(self, ids, offsets):
    """ids ranges -> list of space-joined token strings."""
    out_offsets, data = self.decode_join_buffers(ids, offsets)
    buf = data.tobytes()
    return [
        buf[out_offsets[k]:out_offsets[k + 1]].decode('utf-8')
        for k in range(len(out_offsets) - 1)
    ]

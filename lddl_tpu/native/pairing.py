"""ctypes wrapper for the native NSP pair planner.

Drop-in for :func:`lddl_tpu.preprocess.pairing.plan_pairs_partition`'s hot
loop: identical outputs and identical post-call ``rng`` state (the C++ side
embeds a CPython-exact ``random.Random``; see ``src/pairing.cpp``).
"""

import ctypes

import numpy as np

from .build import load_library


def plan_pairs_partition_native(docs, rng, max_seq_length=128,
                                short_seq_prob=0.1, duplicate_factor=1):
  """Native planner; same contract as the Python
  ``plan_pairs_partition`` (returns (a_ranges, b_ranges, is_random_next)
  and advances ``rng`` draw-for-draw)."""
  if max_seq_length < 5:
    # Same contract as the Python path: randint(2, max_seq_length - 3)
    # has an empty range below 5 and CPython raises — the C++ planner
    # cannot, so reject here before it runs.
    raise ValueError(f'max_seq_length must be >= 5, got {max_seq_length}')
  lib = load_library()
  version, state, gauss = rng.getstate()
  mt = np.array(state[:624], dtype=np.uint32)
  idx = ctypes.c_int32(state[624])

  n_docs = len(docs)
  n_sents = len(docs.sent_offsets) - 1
  cap = max(1, int(duplicate_factor) * n_sents)
  out = np.empty((cap, 5), dtype=np.int64)
  i64p = ctypes.POINTER(ctypes.c_int64)
  n = lib.lddl_plan_pairs(
      docs.sent_offsets.ctypes.data_as(i64p),
      docs.doc_sent_start.ctypes.data_as(i64p),
      ctypes.c_int64(n_docs),
      mt.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
      ctypes.byref(idx),
      ctypes.c_int32(max_seq_length),
      ctypes.c_double(short_seq_prob),
      ctypes.c_int32(duplicate_factor),
      out.ctypes.data_as(i64p),
      ctypes.c_int64(cap))
  if n < 0:
    raise RuntimeError(
        f'native pair planner overflowed its {cap}-row buffer '
        '(impossible for well-formed inputs: one pair consumes >= 1 '
        'sentence)')
  rng.setstate((version, tuple(int(x) for x in mt) + (int(idx.value),),
                gauss))
  if n == 0:
    empty = np.zeros((0, 2), dtype=np.int64)
    return empty, empty.copy(), np.zeros(0, dtype=bool)
  arr = out[:n]
  return (arr[:, 0:2].copy(), arr[:, 2:4].copy(),
          arr[:, 4].astype(bool))

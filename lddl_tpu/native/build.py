"""Build/load the native shared library.

Compiles ``src/wordpiece.cpp`` with g++ into ``_lddl_native.<abi>.so`` next
to this file. A content hash of the source is embedded in the filename so
editing the C++ transparently rebuilds; a file lock serializes concurrent
builders (many worker processes may race on first use).
"""

import ctypes
import glob
import hashlib
import os
import subprocess
import tempfile

_SRC_DIR = os.path.join(os.path.dirname(__file__), 'src')
_LIB_CACHE = {}


def _sources():
  return sorted(glob.glob(os.path.join(_SRC_DIR, '*.cpp')))


def _lib_path():
  h = hashlib.sha256()
  for src in _sources():
    with open(src, 'rb') as f:
      h.update(f.read())
  digest = h.hexdigest()[:12]
  return os.path.join(os.path.dirname(__file__), f'_lddl_native.{digest}.so')


def build_library(verbose=False):
  """Compile if needed; returns the .so path."""
  path = _lib_path()
  if os.path.exists(path):
    return path
  lock = path + '.lock'
  fd = os.open(lock, os.O_CREAT | os.O_RDWR)
  try:
    import fcntl
    fcntl.flock(fd, fcntl.LOCK_EX)
    if os.path.exists(path):
      return path
    with tempfile.TemporaryDirectory(dir=os.path.dirname(path)) as tmp:
      tmp_so = os.path.join(tmp, 'out.so')
      cmd = [
          'g++', '-O3', '-march=native', '-shared', '-fPIC', '-std=c++17',
          '-pthread', '-o', tmp_so, *_sources()
      ]
      if verbose:
        print('building native library:', ' '.join(cmd))
      subprocess.run(cmd, check=True, capture_output=not verbose)
      os.replace(tmp_so, path)  # atomic publish
    return path
  finally:
    os.close(fd)
    try:
      os.unlink(lock)
    except OSError:
      pass


def load_library():
  """Build (if needed) and dlopen the native library; cached per process."""
  path = build_library()
  lib = _LIB_CACHE.get(path)
  if lib is not None:
    return lib
  lib = ctypes.CDLL(path)
  c = ctypes
  lib.lddl_wp_create.restype = c.c_void_p
  lib.lddl_wp_create.argtypes = [
      c.c_char_p, c.POINTER(c.c_int64), c.c_int32, c.c_int32, c.c_int32,
      c.c_int32
  ]
  lib.lddl_wp_destroy.argtypes = [c.c_void_p]
  lib.lddl_wp_encode_batch.restype = c.c_int64
  lib.lddl_wp_encode_batch.argtypes = [
      c.c_void_p, c.c_char_p, c.POINTER(c.c_int64), c.c_int64, c.c_int32,
      c.POINTER(c.c_int32), c.c_int64, c.POINTER(c.c_int64), c.c_int32
  ]
  lib.lddl_split_sentences.restype = c.c_int64
  lib.lddl_split_sentences.argtypes = [
      c.c_char_p, c.c_int64, c.POINTER(c.c_int64), c.c_int64
  ]
  lib.lddl_encode_docs.restype = c.c_int64
  lib.lddl_encode_docs.argtypes = [
      c.c_void_p, c.c_char_p, c.POINTER(c.c_int64), c.c_int64, c.c_int32,
      c.POINTER(c.c_int32), c.c_int64, c.POINTER(c.c_int64), c.c_int64,
      c.POINTER(c.c_int64), c.c_int32
  ]
  lib.lddl_decode_join.restype = c.c_int64
  lib.lddl_decode_join.argtypes = [
      c.c_void_p, c.POINTER(c.c_int32), c.POINTER(c.c_int64), c.c_int64,
      c.c_char_p, c.c_int64, c.POINTER(c.c_int32)
  ]
  lib.lddl_native_abi_version.restype = c.c_int64
  lib.lddl_columnar_sizes.restype = c.c_int64
  lib.lddl_columnar_sizes.argtypes = [
      c.c_void_p, c.c_int32, c.POINTER(c.c_void_p), c.POINTER(c.c_void_p),
      c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.POINTER(c.c_int64),
      c.c_int64, c.POINTER(c.c_int64)
  ]
  lib.lddl_columnar_emit.restype = c.c_int64
  lib.lddl_columnar_emit.argtypes = [
      c.c_void_p, c.c_int32, c.POINTER(c.c_void_p), c.POINTER(c.c_void_p),
      c.POINTER(c.c_int64), c.POINTER(c.c_void_p), c.POINTER(c.c_void_p),
      c.POINTER(c.c_int64), c.POINTER(c.c_uint16), c.POINTER(c.c_int64),
      c.c_int64, c.POINTER(c.c_int64), c.c_char_p, c.c_int32
  ]
  lib.lddl_plan_pairs.restype = c.c_int64
  lib.lddl_plan_pairs.argtypes = [
      c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_int64,
      c.POINTER(c.c_uint32), c.POINTER(c.c_int32), c.c_int32, c.c_double,
      c.c_int32, c.POINTER(c.c_int64), c.c_int64
  ]
  lib.lddl_mask_topk.restype = None
  lib.lddl_mask_topk.argtypes = [
      c.POINTER(c.c_uint64), c.POINTER(c.c_int64), c.c_int64, c.c_int64,
      c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_int32
  ]
  lib.lddl_mask_partition.restype = None
  lib.lddl_mask_partition.argtypes = [
      c.POINTER(c.c_int32), c.POINTER(c.c_int64), c.POINTER(c.c_int64),
      c.c_int64, c.POINTER(c.c_int64), c.POINTER(c.c_int64),
      c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_uint64, c.c_int32,
      c.c_int32, c.POINTER(c.c_int32), c.POINTER(c.c_int32),
      c.POINTER(c.c_uint16), c.POINTER(c.c_int32), c.c_int32
  ]
  _LIB_CACHE[path] = lib
  return lib


if __name__ == '__main__':
  print(build_library(verbose=True))

"""Byte-range partitioning of one-document-per-line text shards.

Replaces ``dask.bag.read_text(blocksize=...)`` (reference
``lddl/dask/readers.py:48-70``) with an explicit plan: each partition is a
list of byte slices; slice boundaries are arbitrary, and the reader applies
the standard convention that a line straddling a slice's *start* belongs to
the previous slice, so no newline scanning is needed at planning time.
"""

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class TextSlice:
  path: str
  start: int
  end: int  # exclusive


def estimate_block_size(paths, num_blocks):
  """Total corpus bytes / desired block count (reference readers.py:48-57)."""
  total = sum(os.path.getsize(p) for p in paths)
  if num_blocks <= 0:
    raise ValueError('num_blocks must be positive')
  return max(1, -(-total // num_blocks))  # ceil div


def plan_text_partitions(paths, block_size):
  """One partition per ~block_size byte slice, in sorted path order."""
  partitions = []
  for path in sorted(paths):
    size = os.path.getsize(path)
    if size == 0:
      continue
    start = 0
    while start < size:
      end = min(start + block_size, size)
      partitions.append(TextSlice(path, start, end))
      start = end
  return partitions


def read_lines(text_slice, encoding='utf-8'):
  """Yield the complete '\\n'-separated lines owned by a slice.

  Ownership rule: a line belongs to the slice in which it *starts*. A slice
  whose start is mid-line skips to the next newline; a slice whose last line
  straddles its end reads past the end to finish that line. (Documents using
  other delimiters, e.g. the CRLF-delimited bimodal code corpus, have their
  own reader in :mod:`lddl_tpu.preprocess.readers`.)
  """
  with open(text_slice.path, 'rb') as f:
    pos = text_slice.start
    if pos > 0:
      f.seek(pos - 1)
      prev = f.read(1)
      if prev != b'\n':
        # We started mid-line: the line belongs to the previous slice.
        chunk = f.readline()
        pos += len(chunk)
    else:
      f.seek(0)
    while pos < text_slice.end:
      line = f.readline()
      if not line:
        break
      pos += len(line)
      text = line.decode(encoding).rstrip('\r\n')
      if text.strip():
        yield text

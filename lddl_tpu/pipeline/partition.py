"""Byte-range partitioning of one-document-per-line text shards.

Replaces ``dask.bag.read_text(blocksize=...)`` (reference
``lddl/dask/readers.py:48-70``) with an explicit plan: each partition is a
list of byte slices; slice boundaries are arbitrary, and the reader applies
the standard convention that a line straddling a slice's *start* belongs to
the previous slice, so no newline scanning is needed at planning time.
"""

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class TextSlice:
  path: str
  start: int
  end: int  # exclusive


def estimate_block_size(paths, num_blocks):
  """Total corpus bytes / desired block count (reference readers.py:48-57)."""
  total = sum(os.path.getsize(p) for p in paths)
  if num_blocks <= 0:
    raise ValueError('num_blocks must be positive')
  return max(1, -(-total // num_blocks))  # ceil div


def plan_text_partitions(paths, block_size):
  """One partition per ~block_size byte slice, in sorted path order."""
  partitions = []
  for path in sorted(paths):
    size = os.path.getsize(path)
    if size == 0:
      continue
    start = 0
    while start < size:
      end = min(start + block_size, size)
      partitions.append(TextSlice(path, start, end))
      start = end
  return partitions


def read_records(text_slice, delimiter='\r\n', encoding='utf-8',
                 chunk_size=1 << 16):
  """Yield the records owned by a slice for an arbitrary multi-byte record
  delimiter (the bimodal code corpus uses CRLF records whose *content*
  contains plain newlines; reference ``lddl/dask/readers.py:130-139``).

  Ownership rule matches :func:`read_lines`: a record belongs to the slice
  in which it starts (= the byte after its predecessor's delimiter).
  """
  dlm = delimiter.encode(encoding)
  nd = len(dlm)
  with open(text_slice.path, 'rb') as f:
    start = text_slice.start
    if start > 0:
      # Does a delimiter end exactly at `start`? Then a record starts here.
      f.seek(max(0, start - nd))
      head = f.read(min(nd, start))
      if head != dlm:
        # Mid-record: the true next record start is the end of the first
        # delimiter whose END lies strictly after `start` (a delimiter may
        # straddle the boundary, so back up nd-1 bytes before scanning).
        scan_pos = max(0, start - (nd - 1))
        f.seek(scan_pos)
        buf = b''
        found = -1
        while found < 0:
          chunk = f.read(chunk_size)
          if not chunk:
            return
          buf += chunk
          i = buf.find(dlm)
          while i >= 0:
            if scan_pos + i + nd > start:
              found = scan_pos + i + nd
              break
            i = buf.find(dlm, i + 1)
          if found < 0:
            # Keep only a possible straddling prefix of a delimiter
            # (nothing for a single-byte delimiter — buf[-0:] would keep
            # the whole buffer and corrupt scan_pos).
            keep = nd - 1
            scan_pos += len(buf) - keep
            buf = buf[len(buf) - keep:] if keep else b''
        start = found
    if start >= text_slice.end:
      return
    f.seek(start)
    data = f.read(text_slice.end - start)
    # Complete the trailing record (it started inside the slice).
    if not data.endswith(dlm):
      while True:
        search_from = max(0, len(data) - (nd - 1))
        chunk = f.read(chunk_size)
        if not chunk:
          break
        data += chunk
        i = data.find(dlm, search_from)
        if i >= 0:
          data = data[:i + nd]
          break
    for rec in data.split(dlm):
      text = rec.decode(encoding).strip()
      if text:
        yield text


def read_lines(text_slice, encoding='utf-8'):
  """Yield the complete '\\n'-separated lines owned by a slice.

  Ownership rule: a line belongs to the slice in which it *starts*. A slice
  whose start is mid-line skips to the next newline; a slice whose last line
  straddles its end reads past the end to finish that line. (Records with
  multi-byte delimiters go through :func:`read_records`.)
  """
  with open(text_slice.path, 'rb') as f:
    pos = text_slice.start
    if pos > 0:
      f.seek(pos - 1)
      prev = f.read(1)
      if prev != b'\n':
        # We started mid-line: the line belongs to the previous slice.
        chunk = f.readline()
        pos += len(chunk)
    else:
      f.seek(0)
    if pos >= text_slice.end:
      return
    # Chunked bulk reads with a carried remainder: the syscall win of
    # block reads at O(chunk) memory, not O(slice) (slices can be hundreds
    # of MB when few workers partition a large corpus).
    chunk_size = 8 << 20
    remaining = text_slice.end - pos
    # Newline-free chunks accumulate in a list (joined only once a newline
    # arrives), so a pathological single-line slice costs O(line) total
    # copying, not O(line * chunks).
    pending = []
    while remaining > 0:
      chunk = f.read(min(chunk_size, remaining))
      if not chunk:
        break
      remaining -= len(chunk)
      pending.append(chunk)
      if chunk.rfind(b'\n') < 0:
        continue
      data = b''.join(pending)
      nl = data.rfind(b'\n')
      pending = [data[nl + 1:]] if nl + 1 < len(data) else []
      for line in data[:nl].split(b'\n'):
        text = line.decode(encoding).rstrip('\r')
        if text.strip():
          yield text
    rem = b''.join(pending)
    if rem:
      # The final line straddles the slice end (or the file ends without a
      # newline): finish it, matching the ownership rule.
      rem += f.readline()
      for line in rem.split(b'\n'):
        text = line.decode(encoding).rstrip('\r')
        if text.strip():
          yield text

from .partition import TextSlice, estimate_block_size, plan_text_partitions, read_lines
from .executor import Executor
from .pool import AsyncShardWriter, PoolBroken, WorkerPool, current_writer
from .shuffle import shuffle_lines
from .parquet_io import write_samples_partition, write_shard_file, read_samples

from .partition import TextSlice, estimate_block_size, plan_text_partitions, read_lines
from .executor import Executor
from .shuffle import shuffle_lines
from .parquet_io import write_samples_partition, read_samples

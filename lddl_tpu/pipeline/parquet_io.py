"""Binned Parquet partition writer/reader.

Replaces the reference's forked dask internals (``to_parquet_binned`` /
``write_partition_binned``, reference ``lddl/dask/bert/binning.py:135-431``)
with a direct function: one call writes one input partition as one file per
sequence-length bin, named ``part.<partition>.parquet_<bin_id>`` (unbinned:
``part.<partition>.parquet``), preserving the reference's on-disk contract
so downstream balancer/loaders interoperate.

Bin math (reference ``binning.py:72-74``):
  ``bin_id = clamp((num_tokens - 1) // bin_size, 0, nbins - 1)``.

The bin split here is a vectorized numpy grouping over the partition's
``num_tokens`` column rather than a per-sample Python loop.
"""

import os
import pickle
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ..telemetry.ledger import fingerprint_file, get_ledger


def compute_bin_ids(num_tokens, bin_size, nbins):
  """Vectorized bin assignment; ``num_tokens`` is array-like of ints."""
  num_tokens = np.asarray(num_tokens, dtype=np.int64)
  return np.clip((num_tokens - 1) // bin_size, 0, nbins - 1)


def _default_compression():
  # lz4 writes at snappy speed but reads ~3x faster with slightly smaller
  # files (measured on this corpus: 100 vs 100 ms write, 24 vs 78 ms read,
  # 13.8 vs 14.4 MB) — the loader and balancer pay the read side on every
  # epoch. Still standard Parquet (any pyarrow reader, including the
  # reference's loaders, reads it transparently; the reference writes
  # snappy, binning.py:42-47, which remains supported via the
  # ``compression`` arguments). Falls back if the codec is absent.
  try:
    pa.Codec('lz4')
    return 'lz4'
  except Exception:
    try:
      pa.Codec('snappy')
      return 'snappy'
    except Exception:
      return None


def write_shard_file(table, path, output_format='parquet',
                     compression='default'):
  """Write one shard file atomically (tmp in the same dir, then rename).

  A preprocessor killed mid-write must never leave a truncated part file
  that shard discovery (which matches on the final extension only) would
  read as valid (same tmp+rename discipline as pipeline/shuffle.py). The
  leading dot plus '.tmp' extension keeps the tmp name invisible to
  get_all_parquets_under/get_all_txt_files_under even mid-write.

  Module-level (not a closure) so it is picklable and safe to hand to an
  ``AsyncShardWriter`` — the deferred write runs this exact function, so
  overlapped write-back changes *when* bytes land, never *what* lands.
  """
  if compression == 'default':
    compression = _default_compression()
  out_dir = os.path.dirname(path) or '.'
  # pid-unique tmp name: under the elastic executor a revoked-but-alive
  # owner can briefly race the re-executing survivor on the same shard;
  # both write identical bytes, but a *shared* tmp path would let one
  # truncate the other mid-write. Distinct tmps + atomic rename keep the
  # final file well-formed whichever rename lands last.
  tmp = os.path.join(out_dir, f'.{os.path.basename(path)}.{os.getpid()}.tmp')
  try:
    if output_format == 'parquet':
      # Dictionary encoding buys nothing on long, mostly-unique token
      # strings, and per-page statistics are never consulted by the
      # loader (row counts come from the footer) — both are pure
      # writer-side cost here.
      pq.write_table(table, tmp, compression=compression,
                     use_dictionary=False, write_statistics=False)
    elif output_format == 'txt':
      with open(tmp, 'w', encoding='utf-8') as f:
        for row in table.to_pylist():
          f.write(repr(row) + '\n')
    else:
      raise ValueError(f'unknown output_format {output_format!r}')
    ledger = get_ledger()
    if ledger.enabled:
      # The shard boundary: fingerprint the exact bytes about to be
      # renamed into place. File bytes, not table content — a
      # writer-version or codec change that alters the file is a real
      # difference a resumed run would re-read. Keyed by basename (the
      # name is deterministic); multi-process writers append to the
      # same rank ledger, so the auditor aligns this boundary by key.
      ledger.record('shard', fingerprint_file(tmp),
                    path=os.path.basename(path))
    os.rename(tmp, path)
  finally:
    if os.path.exists(tmp):
      os.remove(tmp)


def write_samples_partition(
    samples,
    schema,
    out_dir,
    partition_idx,
    bin_size=None,
    nbins=None,
    compression='default',
    output_format='parquet',
    writer=None,
):
  """Write one partition of sample dicts.

  ``samples``: list of dicts matching ``schema`` (a ``pyarrow.Schema``);
  for binned output every sample must have a ``num_tokens`` entry.
  Returns a dict ``{bin_id_or_None: (path, num_samples)}``. All ``nbins``
  files are written even when empty, so the global bin-id set is always
  contiguous (the balancer consolidates empties away). ``writer``: an
  optional ``pool.AsyncShardWriter`` — file writes are then deferred to
  its background thread (flushed at phase end) instead of blocking here.
  """
  cols = {
      field: pa.array([r[field] for r in samples],
                      type=schema.field(field).type)
      for field in schema.names
  }
  # Build against the caller's schema (not a re-inferred one) so schema
  # metadata — e.g. the shard-format tag (pipeline/shard_format.py) —
  # rides into the written file's footer.
  return write_table_partition(
      pa.table(cols, schema=schema),
      out_dir,
      partition_idx,
      bin_size=bin_size,
      nbins=nbins,
      compression=compression,
      output_format=output_format,
      writer=writer,
  )


def write_table_partition(
    table,
    out_dir,
    partition_idx,
    bin_size=None,
    nbins=None,
    compression='default',
    output_format='parquet',
    writer=None,
):
  """Columnar sibling of :func:`write_samples_partition`.

  ``table``: a ``pyarrow.Table`` for the whole partition (no ``bin_id``
  column; must contain ``num_tokens`` when binned). The bin split happens
  via one stable argsort + per-bin ``Table.take`` (Arrow C++), avoiding
  any per-row Python. Returns ``{bin_id_or_None: (path, num_samples)}``.
  With ``writer`` (a ``pool.AsyncShardWriter``), each shard write is
  submitted to the background writer thread instead of running inline —
  identical bytes (same :func:`write_shard_file`), just overlapped with
  the caller's next encode; the executor flushes writers before a phase
  completes.
  """
  if compression == 'default':
    compression = _default_compression()
  os.makedirs(out_dir, exist_ok=True)

  def _write(tbl, path):
    if writer is not None:
      writer.submit(write_shard_file, tbl, path,
                    output_format=output_format, compression=compression)
    else:
      write_shard_file(tbl, path, output_format=output_format,
                       compression=compression)

  ext = 'parquet' if output_format == 'parquet' else 'txt'
  if bin_size is None:
    path = os.path.join(out_dir, f'part.{partition_idx}.{ext}')
    _write(table, path)
    return {None: (path, table.num_rows)}

  if nbins is None:
    raise ValueError('nbins is required when bin_size is set')
  bin_ids = compute_bin_ids(table.column('num_tokens').to_numpy(), bin_size,
                            nbins)
  order = np.argsort(bin_ids, kind='stable')
  sorted_bins = bin_ids[order]
  boundaries = np.searchsorted(sorted_bins, np.arange(nbins + 1))
  out = {}
  for b in range(nbins):
    idx = order[boundaries[b]:boundaries[b + 1]]
    tbl = table.take(pa.array(idx, type=pa.int64()))
    tbl = tbl.append_column('bin_id',
                            pa.array(np.full(len(idx), b, dtype=np.int64)))
    path = os.path.join(out_dir, f'part.{partition_idx}.{ext}_{b}')
    _write(tbl, path)
    out[b] = (path, len(idx))
  return out


def read_samples(path, columns=None):
  """Read a Parquet shard back into a list of row dicts."""
  return pq.read_table(path, columns=columns).to_pylist()


# ---------------------------------------------------------------------------
# completion manifests (elastic executor / resumable preprocessing)


def manifest_key(global_index):
  """Completion-manifest key for one task of an elastic map phase."""
  return f'done.{int(global_index)}'


def write_manifest_file(manifest_root, global_index, payload):
  """Atomically publish one completion manifest (tmp + rename, same
  durability discipline as :func:`write_shard_file`). ``payload`` is the
  pickled task result; the manifest's *existence* is the completion bit
  the lease protocol and restart-resume key on, so it must only ever
  appear whole."""
  fd, tmp = tempfile.mkstemp(dir=manifest_root)
  with os.fdopen(fd, 'wb') as f:
    f.write(payload)
  os.rename(tmp, os.path.join(manifest_root, manifest_key(global_index)))


def publish_result_manifest(manifest_root, global_index, result):
  """Publish ``done.<gi>`` for a finished task, ordered after its shard
  writes.

  Runs inside the worker that executed the task. With an ambient
  :class:`~.pool.AsyncShardWriter` the manifest is *submitted* to the
  same FIFO queue the task's shard writes went through, so it can only
  land after they are durable; the job withholds publication when an
  earlier write on that queue already failed — a manifest must never
  vouch for shards that were not written. Without a writer the task's
  writes already completed inline, so the manifest is written directly.
  """
  payload = pickle.dumps(result)
  from .pool import current_writer
  writer = current_writer()
  if writer is not None:
    writer.submit(_manifest_write_job, manifest_root, global_index, payload)
  else:
    write_manifest_file(manifest_root, global_index, payload)


def _manifest_write_job(manifest_root, global_index, payload):
  # Executes on the writer thread, after every earlier job of this task.
  from .pool import current_writer
  writer = current_writer()
  if writer is not None and writer.failed:
    return  # an earlier shard write failed: the phase will fail and retry
  write_manifest_file(manifest_root, global_index, payload)


def read_result_manifest(store, global_index):
  """Unpickled result from a :class:`~..comm.backend.LeaseStore`
  manifest, or the sentinel ``MANIFEST_MISSING`` when absent (results
  may legitimately be None)."""
  raw = store.read(manifest_key(global_index))
  return MANIFEST_MISSING if raw is None else pickle.loads(raw)


#: Sentinel distinguishing "no manifest yet" from a published None result.
MANIFEST_MISSING = object()

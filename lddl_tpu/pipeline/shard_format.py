"""Shard format tags: materialized vs mask-delta Parquet corpora.

Two on-disk layouts share the shard/bin naming and balance contracts:

  - ``materialized`` — one physical row per training sample. Masked
    runs store the post-masking A/B token strings plus the
    ``masked_lm_positions``/``masked_lm_labels`` columns; this is the
    reference-compatible layout and the implicit format of every shard
    written before the tag existed (absent metadata == materialized).
  - ``delta`` — one physical row per *base* (unmasked) pair plus
    ``duplicate_factor`` tiny per-copy mask deltas packed into three
    npy-framed binary columns (``mask_delta_positions`` /
    ``mask_delta_new_ids`` / ``mask_delta_k``). No label column at all:
    the label at a masked position is the original token, which the
    collate reads out of the assembled input ids before applying the
    delta. The loader expands each physical row into
    ``duplicate_factor`` logical samples and reconstructs the masked
    row at collate time — byte-identical to what the materialized
    format would have collated (tests/test_shard_format.py), at ~1/dup
    of the write/storage/wire bytes.

The tag rides in the Arrow schema metadata (which Parquet round-trips
through its key-value metadata, and which ``Table.take`` /
``append_column`` / ``concat_tables`` all preserve), so it survives the
binned partition writer and the balancer unchanged.

Formats must not be mixed within one corpus: a delta row expands to
``dup`` samples while a materialized row is one sample, so a mixed file
set has no consistent sample arithmetic. The balancer and the loader
both refuse loudly (:func:`scan_shard_format`).
"""

import pyarrow.parquet as pq

MATERIALIZED = 'materialized'
DELTA = 'delta'

FORMAT_KEY = b'lddl_shard_format'
DUP_KEY = b'lddl_duplicate_factor'

#: The three ragged-packed delta columns of a delta-format BERT shard,
#: in schema order. Each holds npy-framed arrays (serialize_np_array
#: wire format, same as ``masked_lm_positions``): the concatenation of
#: the row's ``duplicate_factor`` per-copy segments for positions and
#: post-mask new ids, plus the per-copy segment lengths ``k``.
DELTA_COLUMNS = ('mask_delta_positions', 'mask_delta_new_ids',
                 'mask_delta_k')


def _tag_metadata(existing, shard_format, duplicate_factor):
  if shard_format not in (MATERIALIZED, DELTA):
    raise ValueError(f'unknown shard format {shard_format!r}')
  meta = dict(existing or {})
  meta[FORMAT_KEY] = shard_format.encode()
  meta[DUP_KEY] = str(int(duplicate_factor)).encode()
  return meta


def tag_table(table, shard_format, duplicate_factor):
  """Attach (merge) the shard-format tag into a table's schema metadata."""
  return table.replace_schema_metadata(
      _tag_metadata(table.schema.metadata, shard_format, duplicate_factor))


def tag_schema(schema, shard_format, duplicate_factor):
  """Schema-level sibling of :func:`tag_table` (for dict-path writers that
  hand a schema to ``write_samples_partition``)."""
  return schema.with_metadata(
      _tag_metadata(schema.metadata, shard_format, duplicate_factor))


def format_of_schema(schema):
  """``(shard_format, duplicate_factor)`` from an Arrow schema.

  Untagged schemas (every pre-tag shard, and the reference's own
  output) read as ``('materialized', 1)``. The duplicate factor is only
  meaningful for expansion under the delta format; materialized shards
  report whatever the writer stamped (provenance) but are never
  expanded.
  """
  meta = schema.metadata or {}
  fmt = meta.get(FORMAT_KEY, b'materialized').decode()
  if fmt not in (MATERIALIZED, DELTA):
    raise ValueError(f'unknown shard format tag {fmt!r} in schema metadata')
  dup = int(meta.get(DUP_KEY, b'1'))
  if dup < 1:
    raise ValueError(f'invalid duplicate_factor tag {dup}')
  return fmt, dup


def shard_format_of(path):
  """``(shard_format, duplicate_factor)`` of one Parquet shard, from the
  footer metadata only (no data pages are read)."""
  return format_of_schema(pq.read_schema(path))


def scan_shard_format(paths):
  """The single ``(shard_format, duplicate_factor)`` all ``paths`` agree
  on. Raises ``ValueError`` on a mixed corpus — materialized and delta
  shards have incompatible sample arithmetic (a delta row is
  ``duplicate_factor`` samples), so mixing them would silently corrupt
  balance/epoch accounting. Refusing here (balancer and loader both
  call this) is the documented contract (MIGRATING.md)."""
  if not paths:
    return MATERIALIZED, 1
  seen = {}
  for p in paths:
    fmt, dup = shard_format_of(p)
    # For materialized shards the stamped duplicate_factor is provenance
    # only (every row is already one sample), so differing stamps — or a
    # mix of tagged and legacy untagged shards — are compatible. For
    # delta shards dup IS the expansion factor, so it must agree.
    key = (fmt, dup if fmt == DELTA else 1)
    seen.setdefault(key, []).append(p)
  if len(seen) > 1:
    desc = '; '.join(
        f'{fmt} (dup={dup}): e.g. {ps[0]}' for (fmt, dup), ps in
        sorted(seen.items()))
    raise ValueError(
        f'mixed shard formats in one corpus: {desc} — materialized and '
        'delta shards may not be mixed (and delta shards must share one '
        'duplicate_factor); re-preprocess with a single --shard-format')
  return next(iter(seen))

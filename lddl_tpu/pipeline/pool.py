"""Persistent work-stealing worker pool + overlapped shard write-back.

The seed executor paid two structural taxes on every ``map()`` phase:

  1. **Pool churn** — a fresh ``ProcessPoolExecutor`` per phase, so every
     phase re-paid worker spawn (forkserver startup once jax is loaded)
     and a cold per-worker tokenizer/native-encoder warmup. The reference
     avoids exactly this with a long-lived Dask-distributed worker pool
     (``dask_mpi.initialize``); this module is the moral equivalent: one
     :class:`WorkerPool` per :class:`~.executor.Executor` lifetime,
     created lazily, reused across all phases, with registered warmup
     hooks run **once per worker per pool lifetime**.
  2. **Static dispatch + synchronous writes** — one future per task in
     submission order leaves a straggler tail when shards are size-skewed,
     and each task blocked on its own Parquet write. Here every rank owns
     a single shared task queue its workers pull from (idle workers
     "steal" whatever is next — dynamic load balance without any
     cross-rank coordination), tasks are enqueued in size-descending LPT
     order by the caller, and each worker owns an
     :class:`AsyncShardWriter` thread so the encode of task N+1 overlaps
     the Parquet write of task N.

Determinism contract: scheduling here is rank-local only. The cross-rank
task split stays the pure ``tasks[rank::world]`` stride computed in
``executor.py``, task outputs remain functions of ``(task, global_index)``
alone, and the deferred writes run the identical tmp+rename
``write_shard_file`` — so shard bytes are independent of worker count,
queue order, and write-back timing.
"""

import multiprocessing as _mp
import os
import queue as _queue
import shutil
import sys
import tempfile
import threading
import time
import traceback

from ..telemetry import get_telemetry


def _default_mp_context():
  """fork is fastest, but forking a process that has initialized JAX (its
  runtime holds locks in background threads) can deadlock the child — so
  once ``jax`` is imported anywhere in the process, pool workers come from
  a clean forkserver instead."""
  if 'jax' in sys.modules and 'forkserver' in _mp.get_all_start_methods():
    return _mp.get_context('forkserver')
  if 'jax' in sys.modules:
    return _mp.get_context('spawn')
  return None  # platform default (fork on Linux)


def write_back_enabled():
  """Overlapped write-back is on unless ``LDDL_WRITE_BACK`` disables it."""
  return os.environ.get('LDDL_WRITE_BACK', '').strip().lower() not in (
      '0', 'false', 'off')


def _write_back_depth():
  try:
    return max(1, int(os.environ.get('LDDL_WRITE_BACK_DEPTH', '2')))
  except ValueError:
    return 2


class WriteBackError(RuntimeError):
  """A deferred shard write failed on the background writer thread."""


class AsyncShardWriter:
  """Bounded background write-back: one thread draining a small job queue.

  Tasks submit ``(fn, args)`` write jobs (typically
  :func:`~.parquet_io.write_shard_file`) and continue computing; the
  queue bound provides backpressure so at most ``max_pending`` shard
  tables are ever held in memory. ``flush()`` blocks until every
  submitted job has run and re-raises the first failure — callers must
  flush before treating a phase's output as durable.

  ``counter``/``thread_name`` parameterize the telemetry identity so
  other write-back consumers (the trainer's async checkpoint writer)
  can reuse the same overlap-and-flush discipline without billing their
  completions to the pool's straggler signal.
  """

  def __init__(self, max_pending=None, counter='pipeline.pool.writes',
               thread_name='lddl-write-back'):
    self._q = _queue.Queue(max_pending or _write_back_depth())
    self._err = None
    # _err is written by the writer thread and read by flush()/failed on
    # the submitting thread; the lock makes first-failure-wins atomic.
    self._err_lock = threading.Lock()
    self._counter = counter
    self.backlog_hwm = 0  # max queue depth observed since last reset
    self._thread = threading.Thread(
        target=self._run, name=thread_name, daemon=True)
    self._thread.start()

  def _run(self):
    # Completed write-backs are the straggler signal for the write side
    # (windowed writes/sec vs the fleet median in telemetry.live); the
    # handle is fetched once per writer thread, off the submit path.
    writes = get_telemetry().counter(self._counter)
    while True:
      job = self._q.get()
      if job is None:
        self._q.task_done()
        return
      fn, args, kwargs = job
      try:
        fn(*args, **kwargs)
        writes.add(1)
      except BaseException:
        with self._err_lock:
          if self._err is None:  # first failure wins; later shards still run
            self._err = traceback.format_exc()
      finally:
        self._q.task_done()

  @property
  def failed(self):
    """Whether any submitted job has failed (first error is retained).
    The manifest job checks this before publishing: a completion
    manifest must never vouch for a shard write that did not land."""
    with self._err_lock:
      return self._err is not None

  @property
  def backlog(self):
    """Jobs currently queued (the checkpoint-backlog gauge input)."""
    return self._q.qsize()

  def _raise_pending(self):
    with self._err_lock:
      if self._err is not None:
        raise WriteBackError(
            'background shard write failed:\n' + self._err)

  def raise_pending(self):
    """Surface the first background failure, if any (first-error-wins).

    Cheap enough for a per-step check: one attribute test on the happy
    path. Callers overlapping writes with a compute loop poll this so a
    lost write stops the loop at the next step instead of at the next
    flush boundary.
    """
    self._raise_pending()

  def submit(self, fn, *args, **kwargs):
    """Enqueue one write job (blocks when ``max_pending`` are in flight)."""
    self._raise_pending()
    depth = self._q.qsize() + 1
    if depth > self.backlog_hwm:
      self.backlog_hwm = depth
    self._q.put((fn, args, kwargs))

  def flush(self):
    """Block until all submitted jobs ran; raise on any failure."""
    # lddl: noqa[LDA009] rank-local drain of this process's own writer
    # thread: every job's finally calls task_done(), so join() is bounded
    # by the already-submitted writes — no cross-rank peer is waited on.
    self._q.join()
    self._raise_pending()

  def take_backlog_hwm(self):
    """Read-and-reset the high-water mark (per-phase accounting)."""
    hwm, self.backlog_hwm = self.backlog_hwm, 0
    return hwm

  def close(self, raise_errors=True):
    """Drain, stop the thread, and (optionally) raise pending failures."""
    self._q.put(None)
    # lddl: noqa[LDA009] same rank-local writer-thread drain as flush():
    # the sentinel just enqueued guarantees the loop exits after the
    # backlog, and the thread join below carries a timeout.
    self._q.join()
    self._thread.join(timeout=30.0)
    if raise_errors:
      self._raise_pending()


# The per-process "ambient" writer tasks pick up via current_writer():
# inside a pool worker it is the worker's AsyncShardWriter (installed by
# _worker_main); in the serial path the executor installs one around its
# task loop; everywhere else it is None and writes stay synchronous.
_CURRENT_WRITER = None


def current_writer():
  """The ambient :class:`AsyncShardWriter` for this process, or None."""
  return _CURRENT_WRITER


def install_writer(writer):
  """Install ``writer`` as the ambient writer; returns the previous one."""
  global _CURRENT_WRITER
  previous, _CURRENT_WRITER = _CURRENT_WRITER, writer
  return previous


def _format_remote_error(exc):
  return ''.join(
      traceback.format_exception(type(exc), exc, exc.__traceback__))


def _worker_main(worker_id, task_q, result_q, barrier, warmups, scratch):
  """Pool worker loop: warm up once, then pull from the shared queue.

  Message protocol (task_q -> worker): ``('task', fn, gi, task, pos)``,
  ``('flush',)``, ``('call', fn)``, ``('stop',)``. Replies (result_q):
  ``('ready', wid, pid, err)``, ``('result', gi, res, err, t0, dt, pid,
  wid, pos, wait)``, ``('flush_ack', wid, backlog_hwm, err)``,
  ``('call_ack', wid, err)``. ``flush``/``call`` end on the shared
  barrier so each of the N tokens is consumed by a distinct worker.

  In-flight attribution rides a marker *file* (``scratch/inflight.<wid>``
  holding the last-started gi), written before each task executes — a
  queue message would race SIGKILL (the feeder thread may never flush
  it), but a rename survives any death, so the parent's respawn path can
  always name the task an abruptly dead worker was holding.
  """
  from ..core import faults
  err = None
  try:
    for fn in warmups:
      fn()
  except BaseException as e:  # noqa: BLE001 — report, parent decides
    err = _format_remote_error(e)
  writer = AsyncShardWriter() if write_back_enabled() else None
  install_writer(writer)
  result_q.put(('ready', worker_id, os.getpid(), err))
  idle_t0 = time.monotonic()
  while True:
    msg = task_q.get()
    wait = time.monotonic() - idle_t0
    kind = msg[0]
    if kind == 'task':
      _, fn, gi, task, pos = msg
      marker = os.path.join(scratch, f'inflight.{worker_id}')
      with open(marker + '.tmp', 'w') as f:
        f.write(str(gi))
      os.replace(marker + '.tmp', marker)
      res, terr = None, None
      t0 = time.monotonic()
      try:
        faults.inject('pool.task', gi=gi)
        res = fn(task, gi)
      except BaseException as e:  # noqa: BLE001
        terr = _format_remote_error(e)
      dt = time.monotonic() - t0
      result_q.put(('result', gi, res, terr, t0, dt, os.getpid(),
                    worker_id, pos, wait))
    elif kind == 'flush':
      ferr, hwm = None, 0
      if writer is not None:
        try:
          writer.flush()
        except BaseException as e:  # noqa: BLE001
          ferr = _format_remote_error(e)
        hwm = writer.take_backlog_hwm()
      result_q.put(('flush_ack', worker_id, hwm, ferr))
      barrier.wait()
    elif kind == 'call':
      cerr = None
      try:
        msg[1]()
      except BaseException as e:  # noqa: BLE001
        cerr = _format_remote_error(e)
      result_q.put(('call_ack', worker_id, cerr))
      barrier.wait()
    elif kind == 'stop':
      if writer is not None:
        writer.close(raise_errors=False)
      return
    idle_t0 = time.monotonic()


class PoolBroken(RuntimeError):
  """A pool worker died; the pool can no longer be trusted."""


class TaskFailed(RuntimeError):
  """A task raised inside a pool worker (remote traceback attached)."""


class WorkerPool:
  """A persistent set of worker processes fed from one shared task queue.

  Created once (lazily) per Executor and reused across every ``map()``
  phase: workers stay warm — the registered warmup hooks (tokenizer +
  native encoder) run exactly once per worker per pool lifetime, at
  startup, and late hooks via :meth:`broadcast`. Dispatch is
  work-stealing by construction: all workers pull from the same queue,
  so a worker that finishes early immediately takes the next pending
  task instead of idling behind a static stride assignment.
  """

  def __init__(self, num_workers, mp_context=None, warmups=()):
    ctx = mp_context or _default_mp_context() or _mp.get_context()
    self._ctx = ctx
    self.num_workers = num_workers
    self.start_method = getattr(ctx, '_name', None) or _mp.get_start_method()
    self._task_q = ctx.Queue()
    self._result_q = ctx.Queue()
    self._barrier = ctx.Barrier(num_workers + 1)
    self._scratch = tempfile.mkdtemp(prefix='lddl-pool-')
    self._closed = False
    # Full warmup history (ctor hooks + later broadcasts): a respawned
    # worker must replay all of it to match its peers' warm state.
    self._warmups = list(warmups)
    self._procs = []
    for w in range(num_workers):
      self._procs.append(self._spawn_worker(w))
    self.worker_pids = [None] * num_workers
    try:
      for _ in range(num_workers):
        msg = self._next_result()
        if msg[0] != 'ready':
          raise PoolBroken(f'unexpected startup message {msg[0]!r}')
        if msg[3] is not None:
          raise PoolBroken(
              f'worker {msg[1]} warmup failed:\n{msg[3]}')
        self.worker_pids[msg[1]] = msg[2]
    except BaseException:
      self.shutdown(force=True)
      raise

  def _spawn_worker(self, wid):
    p = self._ctx.Process(
        target=_worker_main,
        args=(wid, self._task_q, self._result_q, self._barrier,
              tuple(self._warmups), self._scratch),
        name=f'lddl-pool-{wid}',
        daemon=True)
    p.start()
    return p

  def _respawn(self, wid):
    """Replace dead worker ``wid`` with a fresh process (same queues,
    same barrier slot, full warmup replay). Its 'ready' message arrives
    asynchronously on the result queue."""
    self._procs[wid].join(timeout=5.0)
    self.worker_pids[wid] = None
    self._procs[wid] = self._spawn_worker(wid)
    get_telemetry().counter('pipeline.pool.respawns').add(1)

  def _next_result(self, allow_dead=False):
    """Next message off the result queue, raising if a worker died
    (instead of hanging forever on a queue a dead worker will never
    feed). With ``allow_dead`` a death is returned as
    ``('worker_died', [wid, ...])`` for the caller's recovery path
    instead of raising. The queue is provably drained at that point
    (1s of Empty), so any result the dead worker managed to flush has
    already been consumed."""
    while True:
      try:
        return self._result_q.get(timeout=1.0)
      except _queue.Empty:
        dead = [w for w, p in enumerate(self._procs) if not p.is_alive()]
        if not dead:
          continue
        if allow_dead:
          return ('worker_died', dead)
        named = [(self._procs[w].name, self._procs[w].exitcode)
                 for w in dead]
        raise PoolBroken(
            f'pool worker(s) died: {named}; the phase cannot complete')

  def _barrier_wait(self):
    try:
      self._barrier.wait(timeout=60.0)
    except threading.BrokenBarrierError:
      raise PoolBroken('pool workers failed to reach the phase barrier')

  def run_stream(self, fn, puller, on_result=None):
    """Feed the pool incrementally from ``puller`` until it runs dry.

    ``puller()`` returns the next ``(gi, task, cost)`` to run or None
    when nothing more is currently available (the elastic executor's
    lease claimer hands out work this way — a partition is only pulled
    once its claim is won, so claim order adapts to execution speed).
    At most ``num_workers + 2`` tasks are in flight; each completion
    pulls the next. Returns the raw result records (completion order).

    Single-worker-death recovery: a worker that dies *while executing a
    task* (its in-flight marker file names the task) is respawned and
    the task re-enqueued, once — the transient-OOM shape. A task that
    kills its worker twice, more than ``num_workers`` respawns in one
    stream, simultaneous multi-worker death, or a death with no task
    attributable all raise :class:`PoolBroken`: those are systemic, not
    transient.
    """
    if self._closed:
      raise PoolBroken('pool already shut down')
    max_inflight = self.num_workers + 2
    enq = {}  # gi -> (task, original queue position)
    awaiting = set()  # gis whose first result has not arrived
    retried = set()  # gis re-enqueued after killing their worker
    records = []
    respawns = 0
    pos = 0
    exhausted = False

    def _fill():
      nonlocal pos, exhausted
      while not exhausted and len(awaiting) < max_inflight:
        item = puller()
        if item is None:
          exhausted = True
          return
        gi, task, _cost = item
        enq[gi] = (task, pos)
        awaiting.add(gi)
        self._task_q.put(('task', fn, gi, task, pos))
        pos += 1

    _fill()
    while awaiting:
      msg = self._next_result(allow_dead=True)
      kind = msg[0]
      if kind == 'worker_died':
        respawns += 1
        if respawns > max(1, self.num_workers):
          raise PoolBroken(
              f'{respawns} worker deaths in one phase; respawn budget '
              'exhausted — failing instead of masking a systemic crash')
        self._recover_dead_worker(msg[1], fn, enq, awaiting, retried)
        continue
      if kind == 'ready':
        # A respawned worker finished its warmup replay.
        if msg[3] is not None:
          raise PoolBroken(
              f'respawned worker {msg[1]} warmup failed:\n{msg[3]}')
        self.worker_pids[msg[1]] = msg[2]
        continue
      gi = msg[1]
      if gi not in awaiting:
        continue  # duplicate: worker died after its result, retry also ran
      awaiting.discard(gi)
      records.append(msg)
      if on_result is not None:
        on_result(msg)
      _fill()
    return records

  def _read_inflight(self, wid):
    """The gi named by dead worker ``wid``'s in-flight marker, or None.
    Consumes the marker so a stale value can never attribute a later
    death of the respawned worker."""
    marker = os.path.join(self._scratch, f'inflight.{wid}')
    try:
      with open(marker) as f:
        gi = int(f.read())
      os.unlink(marker)
      return gi
    except (OSError, ValueError):
      return None

  def _recover_dead_worker(self, dead, fn, enq, awaiting, retried):
    if len(dead) > 1:
      named = [(self._procs[w].name, self._procs[w].exitcode) for w in dead]
      raise PoolBroken(
          f'pool workers died together: {named}; not a single-worker '
          'transient — the phase cannot be trusted')
    wid = dead[0]
    gi = self._read_inflight(wid)
    if gi is None:
      # No task ever started on this worker (death during warmup replay
      # or while idle before its first pull): nothing can be safely
      # retried because nothing is attributable.
      named = (self._procs[wid].name, self._procs[wid].exitcode)
      raise PoolBroken(
          f'pool worker died outside any attributed task: {named}; '
          'the phase cannot complete safely')
    if gi not in awaiting:
      gi = None  # its result landed before death: nothing to retry
    if gi is not None and gi in retried:
      raise PoolBroken(
          f'task (global index {gi}) killed its worker twice; '
          'not a transient — escalating')
    self._respawn(wid)
    if gi is None:
      return
    retried.add(gi)
    task, original_pos = enq[gi]
    self._task_q.put(('task', fn, gi, task, original_pos))

  def flush_round(self):
    """Drain every worker's write-back queue and collect per-worker
    backlog high-water marks: exactly num_workers flush tokens, each
    consumed by a distinct worker (a worker that took one parks on the
    barrier and cannot take another), so every queue is provably drained
    before a phase's results are treated as durable. Returns
    ``(hwms, flush_errs)``."""
    for _ in range(self.num_workers):
      self._task_q.put(('flush',))
    hwms, flush_errs = [], []
    while len(hwms) < self.num_workers:
      msg = self._next_result()
      if msg[0] == 'ready':
        # A worker respawned at the tail of a stream may deliver its
        # 'ready' here; it still consumes its flush token afterwards.
        if msg[3] is not None:
          raise PoolBroken(
              f'respawned worker {msg[1]} warmup failed:\n{msg[3]}')
        self.worker_pids[msg[1]] = msg[2]
        continue
      hwms.append(msg[2])
      if msg[3] is not None:
        flush_errs.append(msg[3])
    self._barrier_wait()
    return hwms, flush_errs

  def run_phase(self, fn, items, on_result=None):
    """Run ``fn(task, global_index)`` for every ``(gi, task, cost)``.

    Tasks are fed in size-descending (LPT) order of ``cost`` (ties
    broken by ascending ``gi``, so the order is deterministic) onto the
    shared queue; idle workers steal from the head. Returns
    ``(records, backlog_hwms)`` where each record is the raw ``result``
    message and ``backlog_hwms`` is the per-worker write-back queue
    high-water mark for the phase. Raises :class:`TaskFailed` /
    :class:`WriteBackError` after the phase fully drains (so the pool
    stays reusable even when a task fails).
    """
    ordered = iter(sorted(items, key=lambda it: (-it[2], it[0])))
    records = self.run_stream(fn, lambda: next(ordered, None),
                              on_result=on_result)
    hwms, flush_errs = self.flush_round()
    failed = sorted((m for m in records if m[3] is not None),
                    key=lambda m: m[1])
    if failed:
      gi, err = failed[0][1], failed[0][3]
      raise TaskFailed(
          f'task (global index {gi}) failed in pool worker:\n{err}')
    if flush_errs:
      raise WriteBackError(
          'deferred shard write(s) failed:\n' + '\n'.join(flush_errs))
    return records, hwms

  def broadcast(self, fn):
    """Run ``fn()`` once on every worker (late warmup hooks). Recorded
    in the warmup history so a respawned worker replays it too."""
    if self._closed:
      raise PoolBroken('pool already shut down')
    self._warmups.append(fn)
    for _ in range(self.num_workers):
      self._task_q.put(('call', fn))
    errs = []
    for _ in range(self.num_workers):
      msg = self._next_result()
      if msg[2] is not None:
        errs.append(msg[2])
    self._barrier_wait()
    if errs:
      raise PoolBroken('worker warmup broadcast failed:\n' + '\n'.join(errs))

  def shutdown(self, force=False):
    """Stop all workers. Idempotent; ``force`` skips the polite stop."""
    if self._closed:
      return
    self._closed = True
    if not force:
      try:
        for _ in self._procs:
          self._task_q.put(('stop',))
      except (OSError, ValueError):
        force = True
    for p in self._procs:
      # force: don't wait at all — surviving workers are still blocked on
      # the task queue (no stop token was sent) and will never exit on
      # their own; an unbounded join here deadlocks the teardown.
      p.join(timeout=0 if force else 10.0)
      if p.is_alive():
        p.terminate()
    for p in self._procs:
      if p.is_alive():
        p.join(timeout=10.0)
    self._task_q.close()
    self._result_q.close()
    shutil.rmtree(self._scratch, ignore_errors=True)

"""Deterministic two-phase global shuffle of text lines on disk.

Replaces the reference's Dask dataframe shuffle trick
(``_shuffle_bag_texts``: bag -> dataframe with a random column -> shuffle ->
sample(1.0), reference ``lddl/dask/bert/pretrain.py:100-111``) with an
explicit scatter/gather through spill files:

  phase A (scatter): each input partition assigns every line a target
    output partition with a seeded RNG and appends it to
    ``<spill>/tgt<j>/src<i>.txt`` — one file per (source, target) pair, so
    there are no concurrent writers per file;
  phase B (gather): output partition j concatenates its spill files in
    sorted source order and shuffles locally with a seeded RNG.

Both phases are pure functions of (seed, partition index), so any rank or
worker can recompute any partition — the shuffle is deterministic and
restartable.
"""

import functools
import os

from ..core import random as lrandom
from .partition import read_lines


def _scatter_state(seed, src_index):
  return lrandom.get_state(f'{seed}:scatter:{src_index}')


def _gather_state(seed, tgt_index):
  return lrandom.get_state(f'{seed}:gather:{tgt_index}')


def scatter_partition(lines, src_index, num_targets, spill_dir, seed,
                      delimiter='\n'):
  """Phase A for one input partition. Returns per-target line counts.

  ``delimiter`` is the record delimiter used in the spill files — must be
  one the records cannot contain (CRLF for code records with embedded
  newlines).
  """
  state = _scatter_state(seed, src_index)
  buckets = [[] for _ in range(num_targets)]
  lines = list(lines)
  targets, state = lrandom.randrange_batch(num_targets, len(lines),
                                           rng_state=state)
  for line, j in zip(lines, targets):
    buckets[j].append(line)
  counts = []
  for j, bucket in enumerate(buckets):
    counts.append(len(bucket))
    if not bucket:
      continue
    tgt_dir = os.path.join(spill_dir, f'tgt{j}')
    os.makedirs(tgt_dir, exist_ok=True)
    # pid-unique tmp: an elastic re-execution of this scatter task may
    # briefly overlap the revoked owner; distinct tmps keep both renames
    # well-formed (identical bytes either way — scatter is seeded).
    tmp = os.path.join(tgt_dir, f'.src{src_index}.{os.getpid()}.tmp')
    with open(tmp, 'w', encoding='utf-8', newline='') as f:
      f.write(delimiter.join(bucket))
      f.write(delimiter)
    os.rename(tmp, os.path.join(tgt_dir, f'src{src_index}.txt'))
  return counts


def gather_partition(tgt_index, spill_dir, seed, delimiter='\n'):
  """Phase B for one output partition: concat spills + local shuffle."""
  tgt_dir = os.path.join(spill_dir, f'tgt{tgt_index}')
  lines = []
  if os.path.isdir(tgt_dir):
    names = sorted(
        (f for f in os.listdir(tgt_dir) if f.endswith('.txt')),
        key=lambda n: int(n[len('src'):-len('.txt')]))
    for name in names:
      with open(os.path.join(tgt_dir, name), encoding='utf-8',
                newline='') as f:
        lines.extend(r for r in f.read().split(delimiter) if r.strip())
  lrandom.shuffle(lines, rng_state=_gather_state(seed, tgt_index))
  return lines


def _scatter_corpus_task(part_slices, idx, num_targets, spill_dir, seed,
                         sample_ratio, sample_seed, delimiter):
  from ..preprocess.readers import read_partition_lines
  lines = read_partition_lines(part_slices, idx, sample_ratio, sample_seed,
                               delimiter)
  return scatter_partition(lines, idx, num_targets, spill_dir, seed,
                           delimiter=delimiter)


def _slices_cost(part_slices, idx):
  """LPT cost key for scatter: bytes of text the partition will read.
  Deterministic (pure function of the partition plan), so every rank and
  worker count derives the same enqueue order."""
  try:
    total = sum(int(s.end) - int(s.start) for s in part_slices)
  except (AttributeError, TypeError):
    return idx
  return total if total > 0 else idx


def shuffle_corpus(executor, corpus, spill_dir, seed, num_targets=None):
  """Shuffle a :class:`~lddl_tpu.preprocess.readers.Corpus` (honoring its
  per-partition subsampling) into ``num_targets`` on-disk partitions.

  Each task carries only its own partition's slices (plus scalar sampling
  parameters), so scatter payloads stay O(1) in the number of partitions.
  """
  if num_targets is None:
    num_targets = corpus.num_partitions
  task = functools.partial(
      _scatter_corpus_task,
      num_targets=num_targets,
      spill_dir=spill_dir,
      seed=seed,
      sample_ratio=corpus.sample_ratio,
      sample_seed=corpus.sample_seed,
      delimiter=corpus.delimiter)
  executor.map(task, list(corpus.partitions), gather=False,
               label='scatter', cost_key=_slices_cost)
  return num_targets


def _scatter_slices_task(part_slices, idx, num_targets, spill_dir, seed):
  lines = (line for s in part_slices for line in read_lines(s))
  return scatter_partition(lines, idx, num_targets, spill_dir, seed)


def shuffle_lines(executor, partitions, spill_dir, seed, num_targets=None):
  """Shuffle all lines of ``partitions`` into ``num_targets`` shuffled
  output partitions on disk. Returns the number of output partitions.

  ``partitions`` is a list of :class:`TextSlice` lists/iterables as produced
  by :func:`plan_text_partitions` (each element = one partition's slices).
  """
  partitions = list(partitions)
  if num_targets is None:
    num_targets = len(partitions)
  task = functools.partial(
      _scatter_slices_task,
      num_targets=num_targets,
      spill_dir=spill_dir,
      seed=seed)
  # map(gather=False) ends with a barrier, so all spills are visible to all
  # ranks when this returns.
  executor.map(task, partitions, gather=False, label='scatter',
               cost_key=_slices_cost)
  return num_targets

"""Task execution across local worker processes and comm ranks.

Replaces the reference's Dask-on-MPI substrate (``dask_mpi.initialize`` +
dask.distributed scheduler, reference ``lddl/dask/bert/pretrain.py:573-581``)
with a deliberately simple model that matches how the reference actually
uses Dask: embarrassingly-parallel ``map`` over partitions, one global
shuffle, and metadata gathers.

Topology: the global task list is strided across comm ranks
(``tasks[rank::world]``); each rank fans its share out to a local
**persistent** worker pool (``pool.WorkerPool``): created lazily on the
first pooled ``map()``, reused across every later phase of the run (warm
tokenizer/native-encoder state via registered warmup hooks), torn down by
``close()`` / context-manager exit. Within a rank, dispatch is
work-stealing off one shared queue with tasks enqueued largest-first
(LPT by a deterministic cost key); across ranks the plan stays the pure
stride above — no extra collectives. On TPU-VM pods, one rank per host
with ``JaxProcessBackend`` gives multi-host scaling without MPI; results
(small metadata only — bulk data goes through the shared filesystem) are
re-gathered with the backend's collectives.
"""

import functools
import json
import multiprocessing as _mp
import os
import pickle
import sys
import tempfile
import threading
import time
import weakref

from ..comm import (HeartbeatPump, LeaseStaleness, NullBackend,
                    comm_heartbeat_interval)
from ..core import faults
from ..telemetry import get_telemetry
from ..telemetry.server import maybe_start_monitor
from ..telemetry.trace import get_tracer
from .parquet_io import (MANIFEST_MISSING, manifest_key,
                         publish_result_manifest, read_result_manifest)
from .pool import (AsyncShardWriter, PoolBroken, TaskFailed, WorkerPool,
                   WriteBackError, _default_mp_context, install_writer,
                   write_back_enabled)

#: Idle wait between claim passes while peers hold every pending lease.
_ELASTIC_POLL = 0.05


def elastic_enabled(comm):
  """Whether ``map()`` runs the lease-claimed elastic path over ``comm``.

  Env ``LDDL_ELASTIC``: ``0/false/off`` forces the static stride
  (escape hatch); ``1/on`` uses leases wherever the backend offers a
  store (including the best-effort jax coordination-service KV store);
  unset/auto enables it only where the claim substrate is first-class
  (``elastic_default``, today the FileBackend — which also covers
  world-size-1 runs, where the lease manifests are what makes a killed
  preprocess resumable)."""
  v = os.environ.get('LDDL_ELASTIC', '').strip().lower()
  if v in ('0', 'false', 'off', 'no'):
    return False
  if v in ('1', 'true', 'on', 'yes'):
    return True
  return getattr(comm, 'elastic_default', False)


def lease_timeout():
  """Seconds of heartbeat silence before survivors revoke a lease (env
  ``LDDL_LEASE_TIMEOUT``). The pid-beacon death probe usually fires far
  earlier on same-host worlds; this is the cross-host backstop."""
  try:
    return max(0.2, float(os.environ.get('LDDL_LEASE_TIMEOUT', '60')))
  except ValueError:
    return 60.0


def _elastic_run(fn, publisher, rank, task, global_index):
  """Elastic task wrapper (module-level, picklable for pool dispatch):
  run the task, then publish its completion manifest through the
  write-back-ordered path — so the manifest can only land after the
  task's shard writes are durable. The fault site is what the
  robustness tests drive kills/delays/IO-errors through."""
  faults.inject('elastic.task', gi=global_index, rank=rank)
  result = fn(task, global_index)
  if publisher is not None:
    publisher(global_index, result)
  return result


class _ElasticTaskError:
  """Pickled into a completion manifest when a task fails: the phase
  still *completes* on every rank (no partition is left permanently
  pending, which would deadlock the manifest wait), and every rank
  raises the same error at gather time."""

  def __init__(self, err):
    self.err = err


def _publish_error_manifest(store, gi, err):
  """Best-effort: record a task failure as the partition's manifest.
  ``err`` is an exception or a worker traceback string. Failure to
  publish is survivable — the local raise stops this rank's heartbeat,
  so peers still recover via the staleness path."""
  text = err if isinstance(err, str) else f'{type(err).__name__}: {err}'
  try:
    store.publish(manifest_key(gi), pickle.dumps(_ElasticTaskError(text)))
  except OSError:
    return


# The heartbeat pump moved to comm/backend.py (PR 13: the train fleet's
# lease-based membership shares it); the old private name stays bound for
# this module's call site and any external references.
_HeartbeatPump = HeartbeatPump


class _LeaseClaimer:
  """Rank-local view of one elastic phase's lease namespace.

  Which rank executes which partition is racy by design — claims go to
  whoever wins the CAS first, so a fast rank absorbs a slow or dead
  rank's share. What each partition *produces* is ``f(task,
  global_index)`` with atomic-rename writes, so the shard bytes are
  identical to the fault-free static-stride run no matter how claims
  land, how often a lease is revoked, or how many times a partition is
  re-executed.

  Revocation: a pending foreign claim is revoked when its owner is
  positively dead (pid beacon) or its heartbeat counter has not moved
  for the lease timeout. The decision inputs are shared state every
  survivor reads identically, so all survivors reach the same verdict;
  the ``revoke`` CAS then picks exactly one winner fleet-wide, and the
  generation bump makes ``claim.<gi>.g<gen+1>`` claimable again.
  """

  def __init__(self, store, order, timeout=None, telemetry=None):
    self._store = store
    self._order = list(order)
    self._staleness = LeaseStaleness(
        store, lease_timeout() if timeout is None else timeout)
    self._done = set()
    self._mine = set()  # claims this rank won (executed this incarnation)
    self._gen = {}  # gi -> live claim generation
    self._foreign = {}  # (gi, gen) -> owning rank (immutable once read)
    tele = telemetry if telemetry is not None else get_telemetry()
    self._claims = tele.counter('pipeline.elastic.claims')
    self._reexecutions = tele.counter('pipeline.elastic.reexecutions')
    self._revokes = tele.counter('pipeline.elastic.revokes')

  @property
  def done_count(self):
    return len(self._done)

  def all_done(self):
    return len(self._done) == len(self._order)

  def refresh(self):
    """Sync the completion set from published manifests. Returns how
    many newly completed partitions were observed."""
    before = len(self._done)
    for key in self._store.list('done.'):
      suffix = key[len('done.'):]
      if suffix.isdigit():
        self._done.add(int(suffix))
    return len(self._done) - before

  def next_claim(self):
    """Win and return the next partition this rank should execute (in
    LPT preference order), or None when every pending partition is
    done, ours, or held by a peer."""
    for gi in self._order:
      if gi in self._done or gi in self._mine:
        continue
      gen = self._gen.get(gi, 0)
      if (gi, gen) in self._foreign:
        continue
      owner = self._store.try_claim(f'claim.{gi}.g{gen}')
      if owner is None or owner == self._store.rank:
        # None: the CAS was won just now. Our own rank: the claim is
        # left over from a previous incarnation of this run (restart
        # before the manifest landed) — the lease is still ours and
        # re-execution is idempotent, so run it rather than waiting for
        # peers to age it out.
        self._mine.add(gi)
        self._claims.add(1)
        if gen > 0:
          self._reexecutions.add(1)
        return gi
      if owner >= 0:
        # Cache: the owner of (gi, gen) can never change, so one CAS
        # attempt per generation per rank is all the traffic claims
        # cost. (-1 = owner momentarily unreadable: retry next pass.)
        self._foreign[(gi, gen)] = owner
    return None

  def pending_unclaimed(self):
    """Whether a claim pass could currently win anything."""
    return any(
        gi not in self._done and gi not in self._mine and
        (gi, self._gen.get(gi, 0)) not in self._foreign
        for gi in self._order)

  def observe(self):
    """Death/staleness sweep over foreign-held pending partitions.

    Revokes stale leases (CAS: one winner fleet-wide counts the revoke)
    and bumps the local generation so the next claim pass re-executes.
    Returns True when any lease was newly revoked (work opened up)."""
    progressed = False
    for gi in self._order:
      if gi in self._done or gi in self._mine:
        continue
      gen = self._gen.get(gi, 0)
      owner = self._foreign.get((gi, gen))
      if owner is None or not self._owner_stale(owner):
        continue
      if self._store.try_claim(f'revoke.{gi}.g{gen}') is None:
        self._revokes.add(1)
      self._gen[gi] = gen + 1
      progressed = True
    return progressed

  def _owner_stale(self, owner):
    # Shared verdict (positive pid death OR heartbeat counter silent
    # past the lease timeout on our own clock): see LeaseStaleness.
    return self._staleness.stale(owner)


def _run_task(fn, global_index, task):
  # Timed inside the (possibly pooled) worker so the duration is true
  # task latency, not submit-to-completion time inflated by queueing.
  # The start timestamp and worker pid ride back with the result:
  # CLOCK_MONOTONIC is machine-wide, so the parent can place the span on
  # the merged timeline (one trace lane per pool worker) without the
  # worker owning a trace buffer of its own.
  t0 = time.monotonic()
  result = fn(task, global_index)
  return global_index, result, t0, time.monotonic() - t0, os.getpid()


class ProgressReporter:
  """Live per-rank progress for long runs — the operational capability
  the reference gets for free from the Dask distributed dashboard
  (pinned bokeh, reference ``setup.py:52``): per-worker progress and
  straggler visibility DURING a multi-hour preprocess, not post-hoc.

  Controlled by env ``LDDL_PROGRESS``:
    - ``1`` / ``stderr``: one line per phase every >=2 s on stderr
      (`[lddl <phase>] rank R: done/total (rate/s, eta Ns)`);
    - a directory path: per-rank JSON heartbeats
      ``lddl_status.rank<R>.json`` (atomic rename), refreshed every
      >=2 s — tail/watch them from another terminal, or compare ranks'
      ``done``/``updated_unix`` to spot stragglers and dead ranks.

  When a phase finishes, :meth:`finish` replaces the heartbeat with a
  final ``{"phase": ..., "complete": true, "workers": N}`` record — so a
  status file left on disk after the run never claims an in-flight phase.
  """

  def __init__(self, spec, rank):
    self._stderr = spec in ('1', 'true', 'stderr')
    self._dir = None if self._stderr else spec
    if self._dir:
      os.makedirs(self._dir, exist_ok=True)
    self._rank = rank
    self._label = None
    self._t0 = 0.0
    self._done0 = 0
    self._last = 0.0

  def update(self, label, done, total, force=False, extra=None):
    now = time.monotonic()
    if label != self._label:
      # Rate baseline starts at the first completion we observe for the
      # phase — computing it from `done / ~0s` would print absurd rates.
      self._label, self._t0, self._done0 = label, now, done
    # lddl: noqa[LDA003] progress-print rate limit: reporting is
    # rank-local observability; skipping a heartbeat changes no plan.
    if not force and now - self._last < 2.0:
      return
    self._last = now
    elapsed = max(now - self._t0, 1e-9)
    rate = (done - self._done0) / elapsed if done > self._done0 else None
    eta = (total - done) / rate if rate else None
    if self._stderr:
      rate_s = f'{rate:.1f}/s' if rate else '--/s'
      eta_s = f'eta {eta:.0f}s' if eta is not None else 'eta --'
      tail = ' done' if extra and extra.get('complete') else ''
      print(f'[lddl {label}] rank {self._rank}: {done}/{total} '
            f'({rate_s}, {eta_s}){tail}', file=sys.stderr, flush=True)
      return
    record = {
        'rank': self._rank, 'pid': os.getpid(), 'phase': label,
        'done': done, 'total': total,
        'tasks_per_sec': round(rate, 3) if rate else None,
        'eta_sec': round(eta, 1) if eta is not None else None,
        'updated_unix': time.time(),
        # Monotonic phase clock so live rate windows over successive
        # heartbeats never depend on wall time (eta_sec is unchanged).
        'monotonic_elapsed_sec': round(now - self._t0, 3),
    }
    if extra:
      record.update(extra)
    payload = json.dumps(record)
    fd, tmp = tempfile.mkstemp(dir=self._dir)
    with os.fdopen(fd, 'w') as f:
      f.write(payload)
    os.replace(tmp, os.path.join(self._dir,
                                 f'lddl_status.rank{self._rank}.json'))

  def finish(self, label, total, workers):
    """Write the phase's terminal record (``complete: true``) so stale
    heartbeats never masquerade as an in-flight phase."""
    self.update(label, total, total, force=True,
                extra={'complete': True, 'workers': workers})


class Executor:
  """Rank-local scheduler over a persistent worker pool.

  Use as a context manager (or call :meth:`close`) so the pool is torn
  down deterministically; a leaked Executor still reaps its workers via
  a GC finalizer, but only close() guarantees *when*.
  """

  def __init__(self, comm=None, num_local_workers=None, mp_start_method=None):
    self._comm = comm if comm is not None else NullBackend()
    if num_local_workers is None:
      num_local_workers = max(1, (os.cpu_count() or 1))
    self._num_local_workers = num_local_workers
    # An explicit start method sticks; otherwise the context is resolved at
    # pool-creation time so a jax import *after* construction still
    # switches the pool off fork.
    self._mp_context = (_mp.get_context(mp_start_method)
                        if mp_start_method else None)
    self._pool = None
    self._finalizer = None
    self._warmups = {}  # key -> zero-arg picklable callable
    self._label_counts = {}  # map label -> phases run (elastic namespaces)
    spec = os.environ.get('LDDL_PROGRESS', '')
    # '0'/'false'/'off' must disable, not become a directory named '0'.
    self._progress = (ProgressReporter(spec, self._comm.rank)
                      if spec not in ('', '0', 'false', 'off') else None)
    # Live metrics endpoint (LDDL_MONITOR): no-op singleton when unset.
    maybe_start_monitor(rank=self._comm.rank)

  @property
  def comm(self):
    return self._comm

  @property
  def num_local_workers(self):
    return self._num_local_workers

  # -- persistent pool lifecycle --------------------------------------------

  def set_warmup(self, fn, key=None):
    """Register a zero-arg picklable warmup hook (tokenizer / native
    encoder pre-load). Runs once per worker per pool lifetime: at worker
    startup for hooks registered before the pool exists, via an immediate
    broadcast for hooks registered after. Duplicate keys are ignored, so
    phases can re-register their warmup idempotently."""
    key = key if key is not None else fn
    if key in self._warmups:
      return
    self._warmups[key] = fn
    if self._pool is not None:
      self._pool.broadcast(fn)

  def _get_pool(self):
    if self._pool is None:
      pool = WorkerPool(
          self._num_local_workers,
          mp_context=self._mp_context or _default_mp_context(),
          warmups=tuple(self._warmups.values()))
      self._pool = pool
      # Reap workers even if the owner forgets close(); holds only the
      # pool (not self), so the Executor stays collectable.
      self._finalizer = weakref.finalize(self, pool.shutdown)
    return self._pool

  def _drop_pool(self, force=False):
    if self._finalizer is not None:
      self._finalizer.detach()
      self._finalizer = None
    if self._pool is not None:
      pool, self._pool = self._pool, None
      pool.shutdown(force=force)

  def close(self):
    """Tear down the persistent pool (idempotent)."""
    self._drop_pool()

  def __enter__(self):
    return self

  def __exit__(self, exc_type, exc, tb):
    self.close()
    return False

  def scheduler_info(self):
    """Scheduler configuration for bench/telemetry stamping."""
    if self._pool is not None:
      start_method = self._pool.start_method
    else:
      ctx = self._mp_context or _default_mp_context()
      start_method = (getattr(ctx, '_name', None) if ctx else None) \
          or _mp.get_start_method(allow_none=True) or 'fork'
    return {
        'workers': self._num_local_workers,
        'start_method': start_method,
        'persistent_pool': self._num_local_workers > 1,
        'stealing': self._num_local_workers > 1,
        'lpt': self._num_local_workers > 1,
        'write_back': write_back_enabled(),
        'elastic': elastic_enabled(self._comm),
    }

  # -- elastic phase namespaces ---------------------------------------------

  def _elastic_store(self, label, peek=False):
    """Lease store for the next map phase labeled ``label``, or None for
    the static-stride path. Namespaces are ``<label>.<n>`` with a
    per-label counter: ranks call ``map`` in lockstep and a restarted
    run replays the same call sequence, so namespaces line up across
    ranks and across restarts — which is exactly what makes completion
    manifests resumable."""
    if not elastic_enabled(self._comm):
      return None
    n = self._label_counts.get(label, 0)
    if not peek:
      self._label_counts[label] = n + 1
    return self._comm.lease_store(f'{label}.{n}')

  def resume_pending(self, label):
    """Whether the comm substrate already holds completion manifests for
    the next map phase labeled ``label`` — i.e. this run is a restart
    that will skip published work. Callers use it to preserve partial
    outputs a resume still needs (e.g. ``run_shuffled``'s spill
    pre-clean)."""
    store = self._elastic_store(label, peek=True)
    return bool(store is not None and store.list('done.'))

  # -- map ------------------------------------------------------------------

  def map(self, fn, tasks, gather=True, label='map', cost_key=None):
    """Run ``fn(task, global_index)`` for every task.

    Tasks are strided over comm ranks, then fed to the rank's persistent
    worker pool through one shared queue in size-descending (LPT) order
    of ``cost_key(task, global_index)`` (any deterministic numeric — e.g.
    input shard bytes; defaults to the index). Scheduling never changes
    results: task output is a function of ``(task, global_index)`` only,
    and the return value is task-ordered. With ``gather=True`` every rank
    returns the full result list (results must be picklable metadata, not
    bulk data); with ``gather=False`` each rank returns only
    ``[(global_index, result), ...]`` for its own tasks (ordered by
    global index), followed by a barrier. ``label`` names the phase in
    live progress reporting (env ``LDDL_PROGRESS``).
    """
    tasks = list(tasks)
    rank = self._comm.rank
    world = self._comm.world_size
    my_indices = list(range(rank, len(tasks), world))
    total = len(my_indices)
    tele = get_telemetry()
    tracer = get_tracer()
    if tracer.enabled:
      tracer.set_identity(rank=rank)
    task_name = f'pipeline.{label}.task'
    task_hist = tele.histogram(f'pipeline.{label}.task_seconds')
    tasks_done = tele.counter(f'pipeline.{label}.tasks')
    local_results = []
    map_span = tele.span(f'pipeline.{label}.map_seconds')
    t_map = time.monotonic()
    map_span.__enter__()
    store = self._elastic_store(label) if tasks else None
    pooled = self._num_local_workers > 1 and (
        len(tasks) > 1 if store is not None else len(my_indices) > 1)
    if store is not None:
      # Elastic path: task ownership is negotiated through CAS'd leases
      # instead of the stride, so live ranks absorb dead/slow ranks'
      # shares and restarts skip manifested partitions. No collectives —
      # a dead rank can never hang the phase.
      ordered = self._map_elastic(fn, tasks, store, pooled, label,
                                  task_name, cost_key, task_hist,
                                  tasks_done, tracer, tele, local_results)
      total = len(tasks)
    elif not pooled:
      self._map_serial(fn, tasks, my_indices, label, task_name,
                       task_hist, tasks_done, tracer, tele, local_results)
    else:
      self._map_pooled(fn, tasks, my_indices, label, task_name, cost_key,
                       task_hist, tasks_done, tracer, tele, local_results)
    if self._progress:
      self._progress.finish(label, total,
                            self._num_local_workers if pooled else 1)
    map_span.__exit__(None, None, None)
    if tracer.enabled:
      tracer.complete(f'pipeline.{label}.map', t_map,
                      time.monotonic() - t_map,
                      args={'tasks': total})
    if store is not None:
      local_results.sort(key=lambda r: r[0])
      return ordered if gather else local_results
    if not gather:
      self._comm.barrier()
      return local_results
    gathered = self._comm.allgather_object(local_results)
    ordered = [None] * len(tasks)
    seen = [False] * len(tasks)
    for rank_results in gathered:
      for i, res in rank_results:
        ordered[i] = res
        seen[i] = True
    missing = [i for i, ok in enumerate(seen) if not ok]
    if missing:
      # A silent None here used to flow downstream and fail far from the
      # cause; name the holes at the boundary instead.
      shown = ', '.join(map(str, missing[:32]))
      more = f' (+{len(missing) - 32} more)' if len(missing) > 32 else ''
      raise RuntimeError(
          f'map({label!r}) gather returned no result for {len(missing)} '
          f'of {len(tasks)} tasks — missing global indices: {shown}{more}. '
          'A rank likely dropped tasks or returned a truncated result '
          'list.')
    return ordered

  def _map_serial(self, fn, tasks, my_indices, label, task_name,
                  task_hist, tasks_done, tracer, tele, local_results):
    total = len(my_indices)
    # Even single-worker ranks get overlapped write-back: tasks hand
    # their Parquet writes to the ambient writer thread (Arrow releases
    # the GIL), so encode of shard N+1 overlaps the write of shard N.
    writer = AsyncShardWriter() if write_back_enabled() else None
    previous = install_writer(writer)
    progress_gauge = tele.gauge(f'pipeline.{label}.progress_frac')
    try:
      for i in my_indices:
        gi, res, t0, dt, pid = _run_task(fn, i, tasks[i])
        task_hist.observe(dt)
        tasks_done.add(1)
        tracer.complete(task_name, t0, dt, tid=pid)
        local_results.append((gi, res))
        progress_gauge.set(len(local_results) / total)
        if self._progress:
          self._progress.update(label, len(local_results), total)
      if writer is not None:
        writer.flush()
    except BaseException:
      # The task error is the story; drain the writer quietly.
      if writer is not None:
        writer.close(raise_errors=False)
        writer = None
      raise
    finally:
      install_writer(previous)
      if writer is not None:
        backlog = writer.take_backlog_hwm()
        writer.close()
        tele.gauge('pipeline.pool.writer_backlog').set(backlog)

  def _map_pooled(self, fn, tasks, my_indices, label, task_name, cost_key,
                  task_hist, tasks_done, tracer, tele, local_results):
    total = len(my_indices)
    pool = self._get_pool()
    items = []
    for i in my_indices:
      cost = cost_key(tasks[i], i) if cost_key is not None else i
      items.append((i, tasks[i], cost))
    steals = tele.counter(f'pipeline.{label}.steals')
    idle_hist = tele.histogram(f'pipeline.{label}.worker_idle_seconds')
    depth_gauge = tele.gauge('pipeline.pool.queue_depth')
    progress_gauge = tele.gauge(f'pipeline.{label}.progress_frac')
    done = 0

    def on_result(msg):
      nonlocal done
      _, gi, res, terr, t0, dt, pid, wid, pos, wait = msg
      done += 1
      pending = total - done
      depth_gauge.set(pending)
      progress_gauge.set(done / total)
      if terr is None:
        task_hist.observe(dt)
        tasks_done.add(1)
        idle_hist.observe(wait)
        # Under static stride, queue position `pos` would have belonged
        # to worker `pos % N`; a different worker pulling it is a steal —
        # the load-balance events the static scheduler couldn't make.
        if pos % pool.num_workers != wid:
          steals.add(1)
        tracer.complete(task_name, t0, dt, tid=pid)
        if wait > 0:
          tracer.complete(f'pipeline.{label}.worker_idle', t0 - wait, wait,
                          tid=pid)
        tracer.counter('pipeline.pool.queue_depth', pending)
        local_results.append((gi, res))
      if self._progress:
        self._progress.update(label, done, total)

    try:
      _, hwms = pool.run_phase(fn, items, on_result=on_result)
    except PoolBroken:
      # A dead worker poisons the queues; rebuild lazily on next map().
      self._drop_pool(force=True)
      raise
    tele.gauge('pipeline.pool.writer_backlog').set(max(hwms) if hwms else 0)
    # The shared queue hands results back in completion order; the
    # contract is task order.
    local_results.sort(key=lambda r: r[0])

  # -- elastic map (lease-claimed partitions) -------------------------------

  def _map_elastic(self, fn, tasks, store, pooled, label, task_name,
                   cost_key, task_hist, tasks_done, tracer, tele,
                   local_results):
    """Lease-claimed variant of map: the full task list is the shared
    work pool; ranks claim partitions through CAS'd leases in LPT order,
    publish completion manifests next to the shards, and revoke+re-run
    leases whose owner dies or goes silent. Phase completion is "every
    partition has a manifest" — no collectives, so a dead rank cannot
    hang survivors. Returns the manifest-ordered result list."""
    total = len(tasks)

    def cost(i):
      return cost_key(tasks[i], i) if cost_key is not None else i

    order = sorted(range(total), key=lambda i: (-cost(i), i))
    claimer = _LeaseClaimer(store, order, telemetry=tele)
    skipped = claimer.refresh()
    if skipped:
      # Restart-resume: these partitions were published by a previous
      # incarnation of this run; their shards are already on disk.
      tele.counter('pipeline.elastic.resume_skipped').add(skipped)
    # FileLeaseStore manifests live on the shared filesystem: workers
    # publish them through their own write-back queue (ordered after the
    # task's shard writes). KV stores have no worker-reachable substrate,
    # so the parent publishes after each pass instead.
    publisher = (functools.partial(publish_result_manifest,
                                   store.manifest_root)
                 if store.manifest_root else None)
    wrapped = functools.partial(_elastic_run, fn, publisher,
                                self._comm.rank)
    progress_gauge = tele.gauge(f'pipeline.{label}.progress_frac')
    pump = _HeartbeatPump(store, comm_heartbeat_interval())
    try:
      while not claimer.all_done():
        if pooled:
          executed = self._elastic_pass_pooled(
              wrapped, tasks, claimer, store, publisher is None, label,
              task_name, task_hist, tasks_done, tracer, tele,
              local_results)
        else:
          executed = self._elastic_pass_serial(
              wrapped, tasks, claimer, store, publisher is None,
              task_name, task_hist, tasks_done, tracer, tele,
              local_results)
        claimer.refresh()
        progress_gauge.set(claimer.done_count / total)
        if self._progress:
          self._progress.update(label, claimer.done_count, total)
        if claimer.all_done():
          break
        revoked = claimer.observe()
        if not executed and not revoked and not claimer.pending_unclaimed():
          # Peers hold every pending lease and none is stale: wait for
          # their manifests (or for a lease to age into revocation).
          time.sleep(_ELASTIC_POLL)
    finally:
      pump.stop()
    return self._collect_manifests(store, total, label)

  def _elastic_pass_serial(self, wrapped, tasks, claimer, store,
                           parent_publish, task_name, task_hist,
                           tasks_done, tracer, tele, local_results):
    """One serial claim-execute pass; returns tasks executed."""
    executed = []
    writer = AsyncShardWriter() if write_back_enabled() else None
    previous = install_writer(writer)
    try:
      while True:
        gi = claimer.next_claim()
        if gi is None:
          break
        t0 = time.monotonic()
        try:
          res = wrapped(tasks[gi], gi)
        except Exception as e:
          # Publish the failure as the partition's manifest so peers
          # complete the phase and raise the same error instead of
          # waiting forever on a partition nobody can finish.
          _publish_error_manifest(store, gi, e)
          raise
        dt = time.monotonic() - t0
        task_hist.observe(dt)
        tasks_done.add(1)
        tracer.complete(task_name, t0, dt, tid=os.getpid())
        local_results.append((gi, res))
        executed.append((gi, res))
      if writer is not None:
        writer.flush()
    except BaseException:
      if writer is not None:
        writer.close(raise_errors=False)
        writer = None
      raise
    finally:
      install_writer(previous)
      if writer is not None:
        backlog = writer.take_backlog_hwm()
        writer.close()
        tele.gauge('pipeline.pool.writer_backlog').set(backlog)
    if parent_publish:
      for gi, res in executed:
        store.publish(manifest_key(gi), pickle.dumps(res))
    return len(executed)

  def _elastic_pass_pooled(self, wrapped, tasks, claimer, store,
                           parent_publish, label, task_name, task_hist,
                           tasks_done, tracer, tele, local_results):
    """One pooled claim-execute pass over :meth:`WorkerPool.run_stream`;
    claims are won lazily as workers free up, so claim order adapts to
    this rank's actual throughput. Returns tasks executed."""
    pool = self._get_pool()
    executed = []
    idle_hist = tele.histogram(f'pipeline.{label}.worker_idle_seconds')

    def puller():
      gi = claimer.next_claim()
      if gi is None:
        return None
      return (gi, tasks[gi], 0)

    def on_result(msg):
      _, gi, res, terr, t0, dt, pid, wid, pos, wait = msg
      if terr is None:
        task_hist.observe(dt)
        tasks_done.add(1)
        idle_hist.observe(wait)
        tracer.complete(task_name, t0, dt, tid=pid)
        local_results.append((gi, res))
        executed.append((gi, res))

    try:
      records = pool.run_stream(wrapped, puller, on_result=on_result)
      hwms, flush_errs = pool.flush_round()
    except PoolBroken:
      self._drop_pool(force=True)
      raise
    tele.gauge('pipeline.pool.writer_backlog').set(max(hwms) if hwms else 0)
    failed = sorted((m for m in records if m[3] is not None),
                    key=lambda m: m[1])
    if failed:
      gi, err = failed[0][1], failed[0][3]
      _publish_error_manifest(store, gi, err)
      raise TaskFailed(
          f'task (global index {gi}) failed in pool worker:\n{err}')
    if flush_errs:
      # A lost deferred write means this rank's manifests for those
      # shards were withheld (the writer refuses to vouch for them);
      # failing here stops our heartbeat, so survivors revoke the
      # affected leases and re-execute.
      raise WriteBackError(
          'deferred shard write(s) failed:\n' + '\n'.join(flush_errs))
    if parent_publish:
      for gi, res in executed:
        store.publish(manifest_key(gi), pickle.dumps(res))
    return len(executed)

  def _collect_manifests(self, store, total, label):
    ordered = []
    for gi in range(total):
      res = read_result_manifest(store, gi)
      if res is MANIFEST_MISSING:
        raise RuntimeError(
            f'map({label!r}) completion manifest for task {gi} vanished '
            'after the phase completed — the lease substrate was '
            'modified externally')
      if isinstance(res, _ElasticTaskError):
        raise TaskFailed(
            f'task (global index {gi}) failed on another rank (reported '
            f'via its completion manifest):\n{res.err}')
      ordered.append(res)
    return ordered

"""Task execution across local worker processes and comm ranks.

Replaces the reference's Dask-on-MPI substrate (``dask_mpi.initialize`` +
dask.distributed scheduler, reference ``lddl/dask/bert/pretrain.py:573-581``)
with a deliberately simple model that matches how the reference actually
uses Dask: embarrassingly-parallel ``map`` over partitions, one global
shuffle, and metadata gathers.

Topology: the global task list is strided across comm ranks
(``tasks[rank::world]``); each rank fans its share out to a local process
pool. On TPU-VM pods, one rank per host with ``JaxProcessBackend`` gives
multi-host scaling without MPI; results (small metadata only — bulk data
goes through the shared filesystem) are re-gathered with the backend's
collectives.
"""

import concurrent.futures as _cf
import multiprocessing as _mp
import os
import sys

from ..comm import NullBackend


def _run_task(fn, global_index, task):
  return global_index, fn(task, global_index)


def _default_mp_context():
  """fork is fastest, but forking a process that has initialized JAX (its
  runtime holds locks in background threads) can deadlock the child — so
  once ``jax`` is imported anywhere in the process, pool workers come from
  a clean forkserver instead."""
  if 'jax' in sys.modules and 'forkserver' in _mp.get_all_start_methods():
    return _mp.get_context('forkserver')
  if 'jax' in sys.modules:
    return _mp.get_context('spawn')
  return None  # platform default (fork on Linux)


class Executor:

  def __init__(self, comm=None, num_local_workers=None, mp_start_method=None):
    self._comm = comm if comm is not None else NullBackend()
    if num_local_workers is None:
      num_local_workers = max(1, (os.cpu_count() or 1))
    self._num_local_workers = num_local_workers
    # An explicit start method sticks; otherwise the context is resolved at
    # map() time so a jax import *after* construction still switches the
    # pool off fork.
    self._mp_context = (_mp.get_context(mp_start_method)
                        if mp_start_method else None)

  @property
  def comm(self):
    return self._comm

  @property
  def num_local_workers(self):
    return self._num_local_workers

  def map(self, fn, tasks, gather=True):
    """Run ``fn(task, global_index)`` for every task.

    Tasks are strided over comm ranks, then over the local process pool.
    With ``gather=True`` every rank returns the full, task-ordered result
    list (results must be picklable metadata, not bulk data); with
    ``gather=False`` each rank returns only ``[(global_index, result), ...]``
    for its own tasks, followed by a barrier.
    """
    tasks = list(tasks)
    rank = self._comm.rank
    world = self._comm.world_size
    my_indices = list(range(rank, len(tasks), world))
    local_results = []
    if self._num_local_workers <= 1 or len(my_indices) <= 1:
      for i in my_indices:
        local_results.append(_run_task(fn, i, tasks[i]))
    else:
      with _cf.ProcessPoolExecutor(
          max_workers=min(self._num_local_workers, len(my_indices)),
          mp_context=self._mp_context or _default_mp_context()) as pool:
        futures = [pool.submit(_run_task, fn, i, tasks[i]) for i in my_indices]
        for fut in futures:
          local_results.append(fut.result())
    if not gather:
      self._comm.barrier()
      return local_results
    gathered = self._comm.allgather_object(local_results)
    ordered = [None] * len(tasks)
    for rank_results in gathered:
      for i, res in rank_results:
        ordered[i] = res
    return ordered

"""Task execution across local worker processes and comm ranks.

Replaces the reference's Dask-on-MPI substrate (``dask_mpi.initialize`` +
dask.distributed scheduler, reference ``lddl/dask/bert/pretrain.py:573-581``)
with a deliberately simple model that matches how the reference actually
uses Dask: embarrassingly-parallel ``map`` over partitions, one global
shuffle, and metadata gathers.

Topology: the global task list is strided across comm ranks
(``tasks[rank::world]``); each rank fans its share out to a local process
pool. On TPU-VM pods, one rank per host with ``JaxProcessBackend`` gives
multi-host scaling without MPI; results (small metadata only — bulk data
goes through the shared filesystem) are re-gathered with the backend's
collectives.
"""

import concurrent.futures as _cf
import json
import multiprocessing as _mp
import os
import sys
import tempfile
import time

from ..comm import NullBackend
from ..telemetry import get_telemetry
from ..telemetry.trace import get_tracer


def _run_task(fn, global_index, task):
  # Timed inside the (possibly pooled) worker so the duration is true
  # task latency, not submit-to-completion time inflated by queueing.
  # The start timestamp and worker pid ride back with the result:
  # CLOCK_MONOTONIC is machine-wide, so the parent can place the span on
  # the merged timeline (one trace lane per pool worker) without the
  # worker owning a trace buffer of its own.
  t0 = time.monotonic()
  result = fn(task, global_index)
  return global_index, result, t0, time.monotonic() - t0, os.getpid()


class ProgressReporter:
  """Live per-rank progress for long runs — the operational capability
  the reference gets for free from the Dask distributed dashboard
  (pinned bokeh, reference ``setup.py:52``): per-worker progress and
  straggler visibility DURING a multi-hour preprocess, not post-hoc.

  Controlled by env ``LDDL_PROGRESS``:
    - ``1`` / ``stderr``: one line per phase every >=2 s on stderr
      (`[lddl <phase>] rank R: done/total (rate/s, eta Ns)`);
    - a directory path: per-rank JSON heartbeats
      ``lddl_status.rank<R>.json`` (atomic rename), refreshed every
      >=2 s — tail/watch them from another terminal, or compare ranks'
      ``done``/``updated_unix`` to spot stragglers and dead ranks.
  """

  def __init__(self, spec, rank):
    self._stderr = spec in ('1', 'true', 'stderr')
    self._dir = None if self._stderr else spec
    if self._dir:
      os.makedirs(self._dir, exist_ok=True)
    self._rank = rank
    self._label = None
    self._t0 = 0.0
    self._done0 = 0
    self._last = 0.0

  def update(self, label, done, total, force=False):
    now = time.monotonic()
    if label != self._label:
      # Rate baseline starts at the first completion we observe for the
      # phase — computing it from `done / ~0s` would print absurd rates.
      self._label, self._t0, self._done0 = label, now, done
    # lddl: noqa[LDA003] progress-print rate limit: reporting is
    # rank-local observability; skipping a heartbeat changes no plan.
    if not force and now - self._last < 2.0:
      return
    self._last = now
    elapsed = max(now - self._t0, 1e-9)
    rate = (done - self._done0) / elapsed if done > self._done0 else None
    eta = (total - done) / rate if rate else None
    if self._stderr:
      rate_s = f'{rate:.1f}/s' if rate else '--/s'
      eta_s = f'eta {eta:.0f}s' if eta is not None else 'eta --'
      print(f'[lddl {label}] rank {self._rank}: {done}/{total} '
            f'({rate_s}, {eta_s})', file=sys.stderr, flush=True)
      return
    payload = json.dumps({
        'rank': self._rank, 'pid': os.getpid(), 'phase': label,
        'done': done, 'total': total,
        'tasks_per_sec': round(rate, 3) if rate else None,
        'eta_sec': round(eta, 1) if eta is not None else None,
        'updated_unix': time.time(),
    })
    fd, tmp = tempfile.mkstemp(dir=self._dir)
    with os.fdopen(fd, 'w') as f:
      f.write(payload)
    os.replace(tmp, os.path.join(self._dir,
                                 f'lddl_status.rank{self._rank}.json'))


def _default_mp_context():
  """fork is fastest, but forking a process that has initialized JAX (its
  runtime holds locks in background threads) can deadlock the child — so
  once ``jax`` is imported anywhere in the process, pool workers come from
  a clean forkserver instead."""
  if 'jax' in sys.modules and 'forkserver' in _mp.get_all_start_methods():
    return _mp.get_context('forkserver')
  if 'jax' in sys.modules:
    return _mp.get_context('spawn')
  return None  # platform default (fork on Linux)


class Executor:

  def __init__(self, comm=None, num_local_workers=None, mp_start_method=None):
    self._comm = comm if comm is not None else NullBackend()
    if num_local_workers is None:
      num_local_workers = max(1, (os.cpu_count() or 1))
    self._num_local_workers = num_local_workers
    # An explicit start method sticks; otherwise the context is resolved at
    # map() time so a jax import *after* construction still switches the
    # pool off fork.
    self._mp_context = (_mp.get_context(mp_start_method)
                        if mp_start_method else None)
    spec = os.environ.get('LDDL_PROGRESS', '')
    # '0'/'false'/'off' must disable, not become a directory named '0'.
    self._progress = (ProgressReporter(spec, self._comm.rank)
                      if spec not in ('', '0', 'false', 'off') else None)

  @property
  def comm(self):
    return self._comm

  @property
  def num_local_workers(self):
    return self._num_local_workers

  def map(self, fn, tasks, gather=True, label='map'):
    """Run ``fn(task, global_index)`` for every task.

    Tasks are strided over comm ranks, then over the local process pool.
    With ``gather=True`` every rank returns the full, task-ordered result
    list (results must be picklable metadata, not bulk data); with
    ``gather=False`` each rank returns only ``[(global_index, result), ...]``
    for its own tasks, followed by a barrier. ``label`` names the phase
    in live progress reporting (env ``LDDL_PROGRESS``).
    """
    tasks = list(tasks)
    rank = self._comm.rank
    world = self._comm.world_size
    my_indices = list(range(rank, len(tasks), world))
    total = len(my_indices)
    tele = get_telemetry()
    tracer = get_tracer()
    if tracer.enabled:
      tracer.set_identity(rank=rank)
    task_name = f'pipeline.{label}.task'
    task_hist = tele.histogram(f'pipeline.{label}.task_seconds')
    tasks_done = tele.counter(f'pipeline.{label}.tasks')
    local_results = []
    map_span = tele.span(f'pipeline.{label}.map_seconds')
    t_map = time.monotonic()
    map_span.__enter__()
    if self._num_local_workers <= 1 or len(my_indices) <= 1:
      for i in my_indices:
        gi, res, t0, dt, pid = _run_task(fn, i, tasks[i])
        task_hist.observe(dt)
        tasks_done.add(1)
        tracer.complete(task_name, t0, dt, tid=pid)
        local_results.append((gi, res))
        if self._progress:
          self._progress.update(label, len(local_results), total,
                                force=len(local_results) == total)
    else:
      with _cf.ProcessPoolExecutor(
          max_workers=min(self._num_local_workers, len(my_indices)),
          mp_context=self._mp_context or _default_mp_context()) as pool:
        futures = [pool.submit(_run_task, fn, i, tasks[i]) for i in my_indices]
        if self._progress:
          # Completion-ordered accounting for the live view; results are
          # still read back in task order below.
          done = 0
          for _ in _cf.as_completed(futures):
            done += 1
            self._progress.update(label, done, total, force=done == total)
        for fut in futures:
          gi, res, t0, dt, pid = fut.result()
          task_hist.observe(dt)
          tasks_done.add(1)
          tracer.complete(task_name, t0, dt, tid=pid)
          local_results.append((gi, res))
    map_span.__exit__(None, None, None)
    if tracer.enabled:
      tracer.complete(f'pipeline.{label}.map', t_map,
                      time.monotonic() - t_map,
                      args={'tasks': len(my_indices)})
    if not gather:
      self._comm.barrier()
      return local_results
    gathered = self._comm.allgather_object(local_results)
    ordered = [None] * len(tasks)
    for rank_results in gathered:
      for i, res in rank_results:
        ordered[i] = res
    return ordered

"""Task execution across local worker processes and comm ranks.

Replaces the reference's Dask-on-MPI substrate (``dask_mpi.initialize`` +
dask.distributed scheduler, reference ``lddl/dask/bert/pretrain.py:573-581``)
with a deliberately simple model that matches how the reference actually
uses Dask: embarrassingly-parallel ``map`` over partitions, one global
shuffle, and metadata gathers.

Topology: the global task list is strided across comm ranks
(``tasks[rank::world]``); each rank fans its share out to a local
**persistent** worker pool (``pool.WorkerPool``): created lazily on the
first pooled ``map()``, reused across every later phase of the run (warm
tokenizer/native-encoder state via registered warmup hooks), torn down by
``close()`` / context-manager exit. Within a rank, dispatch is
work-stealing off one shared queue with tasks enqueued largest-first
(LPT by a deterministic cost key); across ranks the plan stays the pure
stride above — no extra collectives. On TPU-VM pods, one rank per host
with ``JaxProcessBackend`` gives multi-host scaling without MPI; results
(small metadata only — bulk data goes through the shared filesystem) are
re-gathered with the backend's collectives.
"""

import json
import multiprocessing as _mp
import os
import sys
import tempfile
import time
import weakref

from ..comm import NullBackend
from ..telemetry import get_telemetry
from ..telemetry.server import maybe_start_monitor
from ..telemetry.trace import get_tracer
from .pool import (AsyncShardWriter, PoolBroken, WorkerPool,
                   _default_mp_context, install_writer, write_back_enabled)


def _run_task(fn, global_index, task):
  # Timed inside the (possibly pooled) worker so the duration is true
  # task latency, not submit-to-completion time inflated by queueing.
  # The start timestamp and worker pid ride back with the result:
  # CLOCK_MONOTONIC is machine-wide, so the parent can place the span on
  # the merged timeline (one trace lane per pool worker) without the
  # worker owning a trace buffer of its own.
  t0 = time.monotonic()
  result = fn(task, global_index)
  return global_index, result, t0, time.monotonic() - t0, os.getpid()


class ProgressReporter:
  """Live per-rank progress for long runs — the operational capability
  the reference gets for free from the Dask distributed dashboard
  (pinned bokeh, reference ``setup.py:52``): per-worker progress and
  straggler visibility DURING a multi-hour preprocess, not post-hoc.

  Controlled by env ``LDDL_PROGRESS``:
    - ``1`` / ``stderr``: one line per phase every >=2 s on stderr
      (`[lddl <phase>] rank R: done/total (rate/s, eta Ns)`);
    - a directory path: per-rank JSON heartbeats
      ``lddl_status.rank<R>.json`` (atomic rename), refreshed every
      >=2 s — tail/watch them from another terminal, or compare ranks'
      ``done``/``updated_unix`` to spot stragglers and dead ranks.

  When a phase finishes, :meth:`finish` replaces the heartbeat with a
  final ``{"phase": ..., "complete": true, "workers": N}`` record — so a
  status file left on disk after the run never claims an in-flight phase.
  """

  def __init__(self, spec, rank):
    self._stderr = spec in ('1', 'true', 'stderr')
    self._dir = None if self._stderr else spec
    if self._dir:
      os.makedirs(self._dir, exist_ok=True)
    self._rank = rank
    self._label = None
    self._t0 = 0.0
    self._done0 = 0
    self._last = 0.0

  def update(self, label, done, total, force=False, extra=None):
    now = time.monotonic()
    if label != self._label:
      # Rate baseline starts at the first completion we observe for the
      # phase — computing it from `done / ~0s` would print absurd rates.
      self._label, self._t0, self._done0 = label, now, done
    # lddl: noqa[LDA003] progress-print rate limit: reporting is
    # rank-local observability; skipping a heartbeat changes no plan.
    if not force and now - self._last < 2.0:
      return
    self._last = now
    elapsed = max(now - self._t0, 1e-9)
    rate = (done - self._done0) / elapsed if done > self._done0 else None
    eta = (total - done) / rate if rate else None
    if self._stderr:
      rate_s = f'{rate:.1f}/s' if rate else '--/s'
      eta_s = f'eta {eta:.0f}s' if eta is not None else 'eta --'
      tail = ' done' if extra and extra.get('complete') else ''
      print(f'[lddl {label}] rank {self._rank}: {done}/{total} '
            f'({rate_s}, {eta_s}){tail}', file=sys.stderr, flush=True)
      return
    record = {
        'rank': self._rank, 'pid': os.getpid(), 'phase': label,
        'done': done, 'total': total,
        'tasks_per_sec': round(rate, 3) if rate else None,
        'eta_sec': round(eta, 1) if eta is not None else None,
        'updated_unix': time.time(),
        # Monotonic phase clock so live rate windows over successive
        # heartbeats never depend on wall time (eta_sec is unchanged).
        'monotonic_elapsed_sec': round(now - self._t0, 3),
    }
    if extra:
      record.update(extra)
    payload = json.dumps(record)
    fd, tmp = tempfile.mkstemp(dir=self._dir)
    with os.fdopen(fd, 'w') as f:
      f.write(payload)
    os.replace(tmp, os.path.join(self._dir,
                                 f'lddl_status.rank{self._rank}.json'))

  def finish(self, label, total, workers):
    """Write the phase's terminal record (``complete: true``) so stale
    heartbeats never masquerade as an in-flight phase."""
    self.update(label, total, total, force=True,
                extra={'complete': True, 'workers': workers})


class Executor:
  """Rank-local scheduler over a persistent worker pool.

  Use as a context manager (or call :meth:`close`) so the pool is torn
  down deterministically; a leaked Executor still reaps its workers via
  a GC finalizer, but only close() guarantees *when*.
  """

  def __init__(self, comm=None, num_local_workers=None, mp_start_method=None):
    self._comm = comm if comm is not None else NullBackend()
    if num_local_workers is None:
      num_local_workers = max(1, (os.cpu_count() or 1))
    self._num_local_workers = num_local_workers
    # An explicit start method sticks; otherwise the context is resolved at
    # pool-creation time so a jax import *after* construction still
    # switches the pool off fork.
    self._mp_context = (_mp.get_context(mp_start_method)
                        if mp_start_method else None)
    self._pool = None
    self._finalizer = None
    self._warmups = {}  # key -> zero-arg picklable callable
    spec = os.environ.get('LDDL_PROGRESS', '')
    # '0'/'false'/'off' must disable, not become a directory named '0'.
    self._progress = (ProgressReporter(spec, self._comm.rank)
                      if spec not in ('', '0', 'false', 'off') else None)
    # Live metrics endpoint (LDDL_MONITOR): no-op singleton when unset.
    maybe_start_monitor(rank=self._comm.rank)

  @property
  def comm(self):
    return self._comm

  @property
  def num_local_workers(self):
    return self._num_local_workers

  # -- persistent pool lifecycle --------------------------------------------

  def set_warmup(self, fn, key=None):
    """Register a zero-arg picklable warmup hook (tokenizer / native
    encoder pre-load). Runs once per worker per pool lifetime: at worker
    startup for hooks registered before the pool exists, via an immediate
    broadcast for hooks registered after. Duplicate keys are ignored, so
    phases can re-register their warmup idempotently."""
    key = key if key is not None else fn
    if key in self._warmups:
      return
    self._warmups[key] = fn
    if self._pool is not None:
      self._pool.broadcast(fn)

  def _get_pool(self):
    if self._pool is None:
      pool = WorkerPool(
          self._num_local_workers,
          mp_context=self._mp_context or _default_mp_context(),
          warmups=tuple(self._warmups.values()))
      self._pool = pool
      # Reap workers even if the owner forgets close(); holds only the
      # pool (not self), so the Executor stays collectable.
      self._finalizer = weakref.finalize(self, pool.shutdown)
    return self._pool

  def _drop_pool(self, force=False):
    if self._finalizer is not None:
      self._finalizer.detach()
      self._finalizer = None
    if self._pool is not None:
      pool, self._pool = self._pool, None
      pool.shutdown(force=force)

  def close(self):
    """Tear down the persistent pool (idempotent)."""
    self._drop_pool()

  def __enter__(self):
    return self

  def __exit__(self, exc_type, exc, tb):
    self.close()
    return False

  def scheduler_info(self):
    """Scheduler configuration for bench/telemetry stamping."""
    if self._pool is not None:
      start_method = self._pool.start_method
    else:
      ctx = self._mp_context or _default_mp_context()
      start_method = (getattr(ctx, '_name', None) if ctx else None) \
          or _mp.get_start_method(allow_none=True) or 'fork'
    return {
        'workers': self._num_local_workers,
        'start_method': start_method,
        'persistent_pool': self._num_local_workers > 1,
        'stealing': self._num_local_workers > 1,
        'lpt': self._num_local_workers > 1,
        'write_back': write_back_enabled(),
    }

  # -- map ------------------------------------------------------------------

  def map(self, fn, tasks, gather=True, label='map', cost_key=None):
    """Run ``fn(task, global_index)`` for every task.

    Tasks are strided over comm ranks, then fed to the rank's persistent
    worker pool through one shared queue in size-descending (LPT) order
    of ``cost_key(task, global_index)`` (any deterministic numeric — e.g.
    input shard bytes; defaults to the index). Scheduling never changes
    results: task output is a function of ``(task, global_index)`` only,
    and the return value is task-ordered. With ``gather=True`` every rank
    returns the full result list (results must be picklable metadata, not
    bulk data); with ``gather=False`` each rank returns only
    ``[(global_index, result), ...]`` for its own tasks (ordered by
    global index), followed by a barrier. ``label`` names the phase in
    live progress reporting (env ``LDDL_PROGRESS``).
    """
    tasks = list(tasks)
    rank = self._comm.rank
    world = self._comm.world_size
    my_indices = list(range(rank, len(tasks), world))
    total = len(my_indices)
    tele = get_telemetry()
    tracer = get_tracer()
    if tracer.enabled:
      tracer.set_identity(rank=rank)
    task_name = f'pipeline.{label}.task'
    task_hist = tele.histogram(f'pipeline.{label}.task_seconds')
    tasks_done = tele.counter(f'pipeline.{label}.tasks')
    local_results = []
    map_span = tele.span(f'pipeline.{label}.map_seconds')
    t_map = time.monotonic()
    map_span.__enter__()
    pooled = self._num_local_workers > 1 and len(my_indices) > 1
    if not pooled:
      self._map_serial(fn, tasks, my_indices, label, task_name,
                       task_hist, tasks_done, tracer, tele, local_results)
    else:
      self._map_pooled(fn, tasks, my_indices, label, task_name, cost_key,
                       task_hist, tasks_done, tracer, tele, local_results)
    if self._progress:
      self._progress.finish(label, total,
                            self._num_local_workers if pooled else 1)
    map_span.__exit__(None, None, None)
    if tracer.enabled:
      tracer.complete(f'pipeline.{label}.map', t_map,
                      time.monotonic() - t_map,
                      args={'tasks': len(my_indices)})
    if not gather:
      self._comm.barrier()
      return local_results
    gathered = self._comm.allgather_object(local_results)
    ordered = [None] * len(tasks)
    seen = [False] * len(tasks)
    for rank_results in gathered:
      for i, res in rank_results:
        ordered[i] = res
        seen[i] = True
    missing = [i for i, ok in enumerate(seen) if not ok]
    if missing:
      # A silent None here used to flow downstream and fail far from the
      # cause; name the holes at the boundary instead.
      shown = ', '.join(map(str, missing[:32]))
      more = f' (+{len(missing) - 32} more)' if len(missing) > 32 else ''
      raise RuntimeError(
          f'map({label!r}) gather returned no result for {len(missing)} '
          f'of {len(tasks)} tasks — missing global indices: {shown}{more}. '
          'A rank likely dropped tasks or returned a truncated result '
          'list.')
    return ordered

  def _map_serial(self, fn, tasks, my_indices, label, task_name,
                  task_hist, tasks_done, tracer, tele, local_results):
    total = len(my_indices)
    # Even single-worker ranks get overlapped write-back: tasks hand
    # their Parquet writes to the ambient writer thread (Arrow releases
    # the GIL), so encode of shard N+1 overlaps the write of shard N.
    writer = AsyncShardWriter() if write_back_enabled() else None
    previous = install_writer(writer)
    progress_gauge = tele.gauge(f'pipeline.{label}.progress_frac')
    try:
      for i in my_indices:
        gi, res, t0, dt, pid = _run_task(fn, i, tasks[i])
        task_hist.observe(dt)
        tasks_done.add(1)
        tracer.complete(task_name, t0, dt, tid=pid)
        local_results.append((gi, res))
        progress_gauge.set(len(local_results) / total)
        if self._progress:
          self._progress.update(label, len(local_results), total)
      if writer is not None:
        writer.flush()
    except BaseException:
      # The task error is the story; drain the writer quietly.
      if writer is not None:
        writer.close(raise_errors=False)
        writer = None
      raise
    finally:
      install_writer(previous)
      if writer is not None:
        backlog = writer.take_backlog_hwm()
        writer.close()
        tele.gauge('pipeline.pool.writer_backlog').set(backlog)

  def _map_pooled(self, fn, tasks, my_indices, label, task_name, cost_key,
                  task_hist, tasks_done, tracer, tele, local_results):
    total = len(my_indices)
    pool = self._get_pool()
    items = []
    for i in my_indices:
      cost = cost_key(tasks[i], i) if cost_key is not None else i
      items.append((i, tasks[i], cost))
    steals = tele.counter(f'pipeline.{label}.steals')
    idle_hist = tele.histogram(f'pipeline.{label}.worker_idle_seconds')
    depth_gauge = tele.gauge('pipeline.pool.queue_depth')
    progress_gauge = tele.gauge(f'pipeline.{label}.progress_frac')
    done = 0

    def on_result(msg):
      nonlocal done
      _, gi, res, terr, t0, dt, pid, wid, pos, wait = msg
      done += 1
      pending = total - done
      depth_gauge.set(pending)
      progress_gauge.set(done / total)
      if terr is None:
        task_hist.observe(dt)
        tasks_done.add(1)
        idle_hist.observe(wait)
        # Under static stride, queue position `pos` would have belonged
        # to worker `pos % N`; a different worker pulling it is a steal —
        # the load-balance events the static scheduler couldn't make.
        if pos % pool.num_workers != wid:
          steals.add(1)
        tracer.complete(task_name, t0, dt, tid=pid)
        if wait > 0:
          tracer.complete(f'pipeline.{label}.worker_idle', t0 - wait, wait,
                          tid=pid)
        tracer.counter('pipeline.pool.queue_depth', pending)
        local_results.append((gi, res))
      if self._progress:
        self._progress.update(label, done, total)

    try:
      _, hwms = pool.run_phase(fn, items, on_result=on_result)
    except PoolBroken:
      # A dead worker poisons the queues; rebuild lazily on next map().
      self._drop_pool(force=True)
      raise
    tele.gauge('pipeline.pool.writer_backlog').set(max(hwms) if hwms else 0)
    # The shared queue hands results back in completion order; the
    # contract is task order.
    local_results.sort(key=lambda r: r[0])

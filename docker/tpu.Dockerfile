# lddl_tpu image for TPU-VM hosts.
#
# TPU-native analogue of the reference's NGC images
# (docker/ngc_pyt.Dockerfile, ngc_paddle.Dockerfile): instead of an NGC
# CUDA base, start from a slim Python base and install the TPU-enabled
# jax wheels. On a TPU-VM the container must run with --privileged (or
# the TPU device flags) and host networking so libtpu can reach the
# chips; see docker/interactive.sh.
#
# Build:  docker build -f docker/tpu.Dockerfile -t lddl_tpu .

FROM python:3.12-slim-bookworm

ENV LANG=C.UTF-8 \
    LC_ALL=C.UTF-8 \
    PIP_NO_CACHE_DIR=1

RUN apt-get update -qq && \
    apt-get install -y --no-install-recommends \
        git vim tmux g++ make libjemalloc-dev wget && \
    rm -rf /var/lib/apt/lists/*

# TPU-enabled jax + the framework's Python dependencies.
RUN pip install -U pip && \
    pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html && \
    pip install flax optax orbax-checkpoint chex einops \
        numpy pyarrow transformers requests tqdm pytest

# The preprocessor is malloc-heavy on the host side; jemalloc is the same
# allocator swap the reference documents (README.md:22-28).
ENV LD_PRELOAD=/usr/lib/x86_64-linux-gnu/libjemalloc.so.2

WORKDIR /workspace/lddl_tpu
COPY . .
RUN pip install ./

# Pre-build the native WordPiece/pairing library into the *installed*
# copy (cd / so the import resolves to site-packages, not the source tree
# that docker/interactive.sh bind-mounts over). Runs using the mounted
# source tree still rebuild lazily on first use — g++ is in the image.
RUN cd / && python -c "from lddl_tpu.native.build import build_library; build_library(verbose=True)"

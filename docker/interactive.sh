#!/usr/bin/env bash
# Interactive/one-shot runner for the lddl_tpu image on a TPU-VM host
# (reference analogue: docker/interactive.sh, which wires --gpus; TPU
# containers need the TPU character devices + host networking instead).
#
# Usage: bash docker/interactive.sh [extra-mounts] [cmd] [image]

MOUNTS=${1:-""}
CMD=${2:-"bash"}
IMAGE=${3:-"lddl_tpu"}

docker run \
  --privileged \
  --init \
  -it \
  --rm \
  --network=host \
  --ipc=host \
  -e TPU_NAME -e TPU_WORKER_ID -e TPU_WORKER_HOSTNAMES \
  -v "$PWD":/workspace/lddl_tpu \
  ${MOUNTS} \
  "${IMAGE}" \
  ${CMD}

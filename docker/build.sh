#!/usr/bin/env bash
# Build the lddl_tpu TPU-VM image (reference analogue: docker/build.sh).
set -euo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")/.."
docker build -f docker/tpu.Dockerfile -t lddl_tpu .
